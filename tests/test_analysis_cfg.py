"""CFG lowering + dataflow engine: unit shapes and the corpus sweep.

Two layers:

1. unit tests pin the lowering of each control construct (branch join,
   loop back edge, early return, ``with`` enter/exit pseudo-statements,
   try/finally routing, break/continue) and the fixpoint semantics the
   lockset rules depend on (must-join = intersection, released-then-
   write, explicit acquire/release, seeded entry facts);
2. the property sweep builds a CFG for EVERY function in the package
   and checks the graph invariants and fixpoint termination — the
   analyzer's own input corpus is the property-test generator, so any
   construct the engine ever meets in anger is covered by
   construction.
"""

import ast
import textwrap
from pathlib import Path

import pytest

from siddhi_tpu.analysis import index_package
from siddhi_tpu.analysis.cfg import CFG, WithEnter, WithExit, build_cfg
from siddhi_tpu.analysis.dataflow import TOP, Analysis, solve, stmt_facts
from siddhi_tpu.analysis.locksets import LocksetAnalysis

REPO = Path(__file__).resolve().parent.parent


def cfg_of(src: str) -> CFG:
    fn = ast.parse(textwrap.dedent(src)).body[0]
    return build_cfg(fn)


def check_consistency(cfg: CFG):
    blocks = {b.bid: b for b in cfg.blocks}
    assert cfg.entry.bid in blocks and cfg.exit.bid in blocks
    for b in cfg.blocks:
        for s in b.succs:
            assert s.bid in blocks, (b, s)
            assert b in s.preds, f"succ {s.bid} of {b.bid} lacks pred link"
        for p in b.preds:
            assert p.bid in blocks, (b, p)
            assert b in p.succs, f"pred {p.bid} of {b.bid} lacks succ link"


def locksets_at(src: str, seed=frozenset(), aliases=None):
    """{lineno: frozenset(token names)} for every real statement."""
    cfg = cfg_of(src)
    analysis = LocksetAnalysis(seed, aliases or {})
    res = solve(cfg, analysis)
    assert res.converged
    out = {}
    for _b, stmt, fact in stmt_facts(cfg, analysis, res):
        if isinstance(stmt, (WithEnter, WithExit)):
            continue
        if fact is not TOP and hasattr(stmt, "lineno"):
            out[stmt.lineno] = frozenset(n for _k, n in fact)
    return out


# -- lowering shapes ---------------------------------------------------------

def test_branch_join():
    cfg = cfg_of("""
        def f(x):
            if x:
                a = 1
            else:
                a = 2
            return a
    """)
    check_consistency(cfg)
    # then/else both reach the join block holding `return a`
    ret = [b for b in cfg.blocks
           if any(isinstance(s, ast.Return) for s in b.stmts)]
    assert len(ret) == 1 and len(ret[0].preds) == 2


def test_loop_back_edge_and_exit():
    cfg = cfg_of("""
        def f(n):
            i = 0
            while i < n:
                i += 1
            return i
    """)
    check_consistency(cfg)
    header = next(b for b in cfg.blocks
                  if any(isinstance(s, ast.While) for s in b.stmts))
    # loop body edges back to the header; header exits to the return
    assert any(header in s.succs for s in cfg.blocks if s is not header)
    assert len(header.succs) == 2


def test_early_return_makes_tail_unreachable():
    cfg = cfg_of("""
        def f():
            return 1
            x = 2
    """)
    check_consistency(cfg)
    live = cfg.reachable()
    dead = [b for b in cfg.blocks
            if any(isinstance(s, ast.Assign) for s in b.stmts)]
    assert dead and all(b.bid not in live for b in dead)


def test_with_emits_enter_and_exit_pseudo_statements():
    cfg = cfg_of("""
        def f(self):
            with self._lock:
                x = 1
            y = 2
    """)
    check_consistency(cfg)
    kinds = [type(s).__name__ for b in cfg.blocks for s in b.stmts]
    assert kinds.count("WithEnter") == 1
    assert kinds.count("WithExit") == 1


def test_break_and_continue_edges():
    cfg = cfg_of("""
        def f(xs):
            for x in xs:
                if x < 0:
                    continue
                if x > 10:
                    break
                use(x)
            return None
    """)
    check_consistency(cfg)


def test_try_finally_runs_on_both_paths():
    cfg = cfg_of("""
        def f(self):
            try:
                risky()
            finally:
                cleanup()
            after()
    """)
    check_consistency(cfg)
    fin = next(b for b in cfg.blocks if any(
        isinstance(s, ast.Expr) and isinstance(s.value, ast.Call)
        and getattr(s.value.func, "id", "") == "cleanup"
        for s in b.stmts))
    # reached from the try body AND routes on toward after()/exit
    assert fin.preds and fin.succs


def test_except_handler_reachable_from_try_body():
    cfg = cfg_of("""
        def f(self):
            try:
                risky()
            except ValueError:
                handle()
            return 1
    """)
    check_consistency(cfg)
    live = cfg.reachable()
    handler = next(b for b in cfg.blocks if any(
        isinstance(s, ast.Expr) and isinstance(s.value, ast.Call)
        and getattr(s.value.func, "id", "") == "handle"
        for s in b.stmts))
    assert handler.bid in live


def test_lambda_builds():
    fn = ast.parse("f = lambda x: x + 1").body[0].value
    cfg = build_cfg(fn)
    check_consistency(cfg)


def test_build_cfg_rejects_non_functions():
    with pytest.raises(TypeError):
        build_cfg(ast.parse("x = 1").body[0])


# -- lockset fixpoint semantics ----------------------------------------------

def test_with_lockset_held_inside_released_after():
    ls = locksets_at("""
        def f(self):
            a = 1
            with self._lock:
                b = 2
            c = 3
    """)
    assert ls[3] == frozenset()
    assert ls[5] == {"_lock"}
    assert ls[6] == frozenset()


def test_explicit_release_mid_with_clears_the_lockset():
    """The flow fact the lexical under_lock check cannot express."""
    ls = locksets_at("""
        def f(self):
            with self._lock:
                a = 1
                self._lock.release()
                b = 2
    """)
    assert ls[4] == {"_lock"}
    assert ls[6] == frozenset()   # released-then-write


def test_acquire_release_pair():
    ls = locksets_at("""
        def f(self):
            self._lock.acquire()
            a = 1
            self._lock.release()
            b = 2
    """)
    assert ls[4] == {"_lock"}
    assert ls[6] == frozenset()


def test_must_join_is_intersection_across_branches():
    ls = locksets_at("""
        def f(self, x):
            if x:
                self._lock.acquire()
            a = 1
    """)
    assert ls[5] == frozenset()   # held on only ONE path -> not held


def test_seeded_entry_fact():
    ls = locksets_at("""
        def f(self):
            a = 1
    """, seed=frozenset({("attr", "_lock")}))
    assert ls[3] == {"_lock"}


def test_alias_expansion_unifies_chain_tokens():
    ls = locksets_at("""
        def f(self):
            ctx = self.runtime.app_context
            with ctx.process_lock:
                a = 1
    """, aliases={"ctx": "self.runtime.app_context"})
    assert ls[5] == {"app_context.process_lock"}


def test_backward_direction_smoke():
    class ReachesExit(Analysis):
        direction = "backward"

        def initial(self, cfg):
            return frozenset({"exit"})

        def join(self, a, b):
            return a | b

        def transfer(self, stmt, fact):
            return fact

    cfg = cfg_of("""
        def f(x):
            if x:
                return 1
            return 2
    """)
    res = solve(cfg, ReachesExit())
    assert res.converged
    assert res.block_out[cfg.entry.bid] == frozenset({"exit"})


# -- the corpus sweep --------------------------------------------------------

def test_every_package_function_builds_and_converges():
    """Property sweep over the real corpus: every function in
    ``siddhi_tpu/`` lowers to a mutually-consistent CFG whose lockset
    fixpoint terminates inside the iteration bound."""
    indexes = index_package(REPO / "siddhi_tpu", REPO)
    assert len(indexes) > 50
    n = 0
    for idx in indexes:
        for qual, fn in idx.functions.items():
            cfg = build_cfg(fn)
            check_consistency(cfg)
            assert cfg.entry.bid in cfg.reachable()
            res = solve(cfg, LocksetAnalysis(frozenset(), {}))
            assert res.converged, f"{idx.rel}:{qual} did not converge"
            n += 1
    assert n > 500, f"corpus suspiciously small: {n} functions"
