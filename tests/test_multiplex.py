"""Multi-tenant engine multiplexing differential suite.

``@app:multiplex(slots='N')`` packs compatible queries from MANY apps on
one SiddhiManager into shared device engines (siddhi_tpu/multiplex/):
tumbling-window device queries tile their accumulator state by seat,
dense-NFA patterns take one partition row each, and one jitted step per
batch cycle serves every seated tenant.

The contract under test is bit-identical outputs versus the same apps
running dedicated engines — including under transient injected faults,
poison quarantine of one tenant, and crash + journal replay of one
tenant while the others keep flowing.  Incompatible shapes must fall
back to dedicated engines with a counted, readable reason.
"""

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.event import Event
from siddhi_tpu.core.exceptions import (
    SiddhiAppCreationError,
    SimulatedCrashError,
)
from siddhi_tpu.util.persistence import InMemoryPersistenceStore


def _collector(res):
    return lambda events: res.extend(tuple(e.data) for e in events)


def _series(n, seed, off):
    rng = np.random.default_rng(seed)
    ks = rng.integers(0, 3, size=n)
    vs = rng.integers(1, 100, size=n).astype(float) + off
    return [([int(k), float(v)], 1000 + j * 250)
            for j, (k, v) in enumerate(zip(ks, vs))]


class TestMultiplexDifferential:
    """N multiplexed tenants == N dedicated runtimes, bit for bit."""

    TWO_SHAPE_APP = """
@app:name('t{i}') @app:execution('tpu') @app:playback {mux}
define stream S (k long, v double);
define stream A (v double);
define stream B (w double);
@info(name='qw') from S#window.lengthBatch(4)
select k, sum(v) as s, count() as c group by k insert into OutW;
@info(name='qp') from every e1=A[v > 2] -> e2=B[w > e1.v]
select e1.v as v1, e2.w as w2 insert into OutP;
"""

    def _run_two_shapes(self, multiplex, n=8, nev=24):
        mgr = SiddhiManager()
        try:
            outs = {i: {"w": [], "p": []} for i in range(n)}
            rts = []
            for i in range(n):
                rt = mgr.create_siddhi_app_runtime(self.TWO_SHAPE_APP.format(
                    i=i, mux="@app:multiplex(slots='8')" if multiplex else ""))
                rts.append(rt)
                rt.add_callback("OutW", _collector(outs[i]["w"]))
                rt.add_callback("OutP", _collector(outs[i]["p"]))
                rt.start()
            hs = [rt.get_input_handler("S") for rt in rts]
            ha = [rt.get_input_handler("A") for rt in rts]
            hb = [rt.get_input_handler("B") for rt in rts]
            sends = {i: _series(nev, 11 + i, 10 * i) for i in range(n)}
            for j in range(nev):
                for i in range(n):
                    row, ts = sends[i][j]
                    hs[i].send(list(row), timestamp=ts)
                    if j % 2 == 0:
                        ha[i].send([float(j % 7 + i)], timestamp=ts)
                    else:
                        hb[i].send([float(j % 5 + i)], timestamp=ts)
            low = {name: eng for rt in rts
                   for name, eng in rt.lowering().items()}
            for rt in rts:
                rt.shutdown()
            return outs, low
        finally:
            mgr.shutdown()

    def test_eight_tenants_two_shapes_bit_identical(self):
        mux, lowm = self._run_two_shapes(True)
        ded, lowd = self._run_two_shapes(False)
        assert lowm == {"qw": "multiplex", "qp": "multiplex"}
        assert lowd == {"qw": "device", "qp": "dense"}
        assert any(mux[i]["w"] for i in mux) and any(mux[i]["p"] for i in mux)
        assert mux == ded

    def test_timebatch_groupby_staggered_timestamps(self):
        APP = """
@app:name('m{i}') @app:execution('tpu') @app:playback {mux}
define stream S (g double, price double);
@info(name='q') from S#window.timeBatch(10)
select g, sum(price) as total, max(price) as mx
group by g insert into Out;
"""

        def run(multiplex, n=4, nev=12):
            mgr = SiddhiManager()
            try:
                outs = {i: [] for i in range(n)}
                rts = []
                for i in range(n):
                    rt = mgr.create_siddhi_app_runtime(APP.format(
                        i=i,
                        mux="@app:multiplex(slots='8')" if multiplex else ""))
                    rts.append(rt)
                    rt.add_callback("Out", _collector(outs[i]))
                    rt.start()
                hs = [rt.get_input_handler("S") for rt in rts]
                for k in range(nev):
                    for i, h in enumerate(hs):
                        # tenants live at staggered wall-clock offsets, so
                        # their pane boundaries interleave inside the group
                        h.send([float(k % 2), float(k + 100 * i)],
                               timestamp=1000 + 3 * k + i)
                for rt in rts:
                    rt.shutdown()
                return outs
            finally:
                mgr.shutdown()

        mux = run(True)
        ded = run(False)
        assert any(mux[i] for i in mux)
        assert mux == ded

    def test_one_shared_step_per_batch_cycle(self):
        """8 tenants' sub-batches combine into ~1 jitted step per cycle,
        not 8 — the whole point of seat-packing."""
        APP = """
@app:name('m{i}') @app:execution('tpu') @app:multiplex(slots='8')
define stream S (g double, price double);
@info(name='q') from S#window.lengthBatch(16)
select g, sum(price) as total group by g insert into Out;
"""
        mgr = SiddhiManager()
        try:
            rts = [mgr.create_siddhi_app_runtime(APP.format(i=i))
                   for i in range(8)]
            for rt in rts:
                rt.add_callback("Out", lambda ev: None)
                rt.start()
            hs = [rt.get_input_handler("S") for rt in rts]
            cycles = 20
            for k in range(cycles):
                for i, h in enumerate(hs):
                    h.send([float(k % 3), float(k + i)], timestamp=1000 + k)
            reg = mgr.siddhi_context.multiplex_registry
            groups = reg.open_groups()
            assert len(groups) == 1 and reg.seats_placed == 8
            g = groups[0]
            assert g.occupied_count() == 8
            # slow (per-tenant fallback) steps only on first-contact JIT
            # warmup; steady state is one combined step per send cycle
            assert g.combined_steps <= cycles + 2
            assert g.combined_steps + g.slow_steps < 8 * cycles / 2
            for rt in rts:
                rt.shutdown()
        finally:
            mgr.shutdown()


class TestMultiplexFaults:
    pytestmark = pytest.mark.faults

    APP = ("@app:name('m{i}') @app:playback @app:execution('tpu') "
           "@app:multiplex(slots='4') {faults}"
           "define stream S (k long, v double); "
           "@info(name='q') from S#window.lengthBatch(4) "
           "select k, sum(v) as s group by k insert into Out;")

    N = 3
    NEV = 24

    def _run(self, tenant1_faults=""):
        sends = {i: _series(self.NEV, 11 + i, 1000 * i) for i in range(self.N)}
        mgr = SiddhiManager()
        try:
            outs = {i: [] for i in range(self.N)}
            rts = []
            for i in range(self.N):
                rt = mgr.create_siddhi_app_runtime(self.APP.format(
                    i=i, faults=tenant1_faults if i == 1 else ""))
                rts.append(rt)
                rt.add_callback("Out", _collector(outs[i]))
                rt.start()
            hs = [rt.get_input_handler("S") for rt in rts]
            for j in range(self.NEV):
                for i in range(self.N):
                    row, ts = sends[i][j]
                    hs[i].send(list(row), timestamp=ts)
            fi = rts[1].app_context.fault_injector
            stats = fi.stats.as_dict() if fi else {}
            for rt in rts:
                rt.shutdown()
            return outs, stats
        finally:
            mgr.shutdown()

    def test_transient_faults_on_one_tenant_bit_identical(self):
        ref, _ = self._run()
        got, st = self._run(
            "@app:faults(transfer.retry.scale='0.001', "
            "ingest.put='transient:count=3', "
            "emit.drain='transient:count=2') ")
        assert st["faults_injected"] >= 5
        assert st["transfer_retries"] >= 3 and st["drains_recovered"] >= 2
        assert got == ref

    def test_poison_quarantine_isolates_tenant(self):
        """Tenant 1's state poisons mid-run; it quarantines without
        stalling the group — tenants 0/2 stay bit-identical."""
        ref, _ = self._run()
        got, st = self._run("@app:faults(state.poison='poison:count=1:after=5') ")
        assert st["poison_quarantines"] >= 1
        assert got[0] == ref[0] and got[2] == ref[2]

    def test_crash_and_journal_replay_one_tenant(self):
        """Tenant 1 checkpoints, crashes mid-run, restores + replays its
        journal on a fresh runtime — all three tenants end bit-identical
        to a run that never crashed (same shared group throughout)."""
        sends = {i: _series(30, 11 + i, 1000 * i) for i in range(self.N)}

        def reference():
            mgr = SiddhiManager()
            try:
                outs = {i: [] for i in range(self.N)}
                rts = []
                for i in range(self.N):
                    rt = mgr.create_siddhi_app_runtime(
                        self.APP.format(i=i, faults=""))
                    rts.append(rt)
                    rt.add_callback("Out", _collector(outs[i]))
                    rt.start()
                hs = [rt.get_input_handler("S") for rt in rts]
                for j in range(30):
                    for i in range(self.N):
                        row, ts = sends[i][j]
                        hs[i].send(list(row), timestamp=ts)
                for rt in rts:
                    rt.shutdown()
                return outs
            finally:
                mgr.shutdown()

        def crashed():
            mgr = SiddhiManager()
            mgr.set_persistence_store(InMemoryPersistenceStore())
            try:
                outs = {i: [] for i in range(self.N)}
                rts = {}
                for i in range(self.N):
                    rt = mgr.create_siddhi_app_runtime(self.APP.format(
                        i=i, faults="@app:faults(journal='256') "))
                    rts[i] = rt
                    rt.add_callback("Out", _collector(outs[i]))
                    rt.start()
                hs = {i: rts[i].get_input_handler("S")
                      for i in range(self.N)}
                for j in range(30):
                    if j == 10:
                        rts[1].persist()
                    if j == 20:
                        rts[1].app_context.fault_injector.configure(
                            "ingest", "crash", count=1)
                        row, ts = sends[1][j]
                        with pytest.raises(SimulatedCrashError):
                            hs[1].send(list(row), timestamp=ts)
                        rts[1].shutdown()  # seat freed, group lives on
                        rt2 = mgr.create_siddhi_app_runtime(self.APP.format(
                            i=1, faults="@app:faults(journal='256') "))
                        rt2.add_callback("Out", _collector(outs[1]))
                        rt2.start()
                        # the crashed send WAS journaled: replay covers it
                        assert rt2.restore_last_revision() is not None
                        rts[1] = rt2
                        hs[1] = rt2.get_input_handler("S")
                        for i in (0, 2):
                            row, ts = sends[i][j]
                            hs[i].send(list(row), timestamp=ts)
                        continue
                    for i in range(self.N):
                        row, ts = sends[i][j]
                        hs[i].send(list(row), timestamp=ts)
                for i in range(self.N):
                    rts[i].shutdown()
                return outs
            finally:
                mgr.shutdown()

        ref = reference()
        got = crashed()
        assert got == ref


class TestMultiplexFallback:
    def test_sliding_window_falls_back_with_counted_reason(self):
        APP = """
@app:name('fb') @app:execution('tpu') @app:multiplex(slots='4')
@app:statistics('basic')
define stream S (k long, v double);
@info(name='q1') from S#window.length(4)
select k, sum(v) as s group by k insert into Out;
@info(name='q2') from S#window.lengthBatch(4)
select k, sum(v) as s group by k insert into Out2;
"""
        mgr = SiddhiManager()
        try:
            rt = mgr.create_siddhi_app_runtime(APP)
            rt.add_callback("Out", lambda e: None)
            rt.add_callback("Out2", lambda e: None)
            rt.start()
            h = rt.get_input_handler("S")
            for k in range(8):
                h.send([k % 2, float(k)], timestamp=1000 + k)
            assert rt.lowering() == {"q1": "device", "q2": "multiplex"}
            st = rt.statistics()
            pre = "io.siddhi.SiddhiApps.fb.Siddhi.Queries."
            assert st[pre + "q1.multiplexFallbacks"] == 1
            assert "tumbling" in st[pre + "q1.multiplexFallbackReason"]
            assert st[pre + "q2.multiplexGroup"]
            rt.shutdown()
        finally:
            mgr.shutdown()

    def test_multiplex_requires_tpu_mode(self):
        with pytest.raises(SiddhiAppCreationError, match="tpu"):
            SiddhiManager().create_siddhi_app_runtime(
                "@app:multiplex define stream S (v double); "
                "@info(name='q') from S select v insert into Out;")

    def test_slots_out_of_range_rejected(self):
        with pytest.raises(SiddhiAppCreationError, match="slots"):
            SiddhiManager().create_siddhi_app_runtime(
                "@app:execution('tpu') @app:multiplex(slots='1') "
                "define stream S (v double); "
                "@info(name='q') from S select v insert into Out;")


class TestFlushSkipRegressions:
    """Hot-pane flush batching must never skip a pane that holds data."""

    APP = """
@app:name('m{i}') @app:execution('tpu') @app:playback {mux}
define stream S (k long, v double);
@info(name='q') from S[v > 1.0]#window.{win}
select k, sum(v) as s group by k insert into Out;
"""

    def _run(self, multiplex, win, sends_fn, n=3):
        mgr = SiddhiManager()
        try:
            outs = {i: [] for i in range(n)}
            rts = []
            for i in range(n):
                rt = mgr.create_siddhi_app_runtime(self.APP.format(
                    i=i, win=win,
                    mux="@app:multiplex(slots='4')" if multiplex else ""))
                rts.append(rt)
                rt.add_callback("Out", _collector(outs[i]))
                rt.start()
            sends_fn([rt.get_input_handler("S") for rt in rts])
            reg = mgr.siddhi_context.multiplex_registry
            skips = (sum(g.flush_skips for g in reg.open_groups())
                     if reg else 0)
            for rt in rts:
                rt.shutdown()
            return outs, skips
        finally:
            mgr.shutdown()

    def test_lengthbatch_pane_filled_by_one_batch(self):
        """A lengthBatch pane closed by a single oversized batch is FULL
        at flush time even though the engine's fill counter still reads
        0 (it increments after the closing flush) — the empty-pane skip
        must not fire for lengthBatch."""

        def big_batches(hs):
            for j in range(3):
                for i, h in enumerate(hs):
                    h.send([Event(1000 + 10 * j + t,
                                  [int(t % 2), float(2 + t + 10 * i)])
                            for t in range(6)])

        mux, _ = self._run(True, "lengthBatch(4)", big_batches)
        ded, _ = self._run(False, "lengthBatch(4)", big_batches)
        assert any(mux[i] for i in mux)
        assert mux == ded

    def test_timebatch_gaps_skip_empty_panes_bit_identical(self):
        """Timestamp gaps close empty timeBatch panes; those flushes are
        coalesced away (counted) without changing any output."""

        def gap_sends(hs):
            for j, t in enumerate([1000, 1002, 1050, 1052, 1200, 1201, 1500]):
                for i, h in enumerate(hs):
                    # one event per tenant fails the filter: its pane is
                    # empty despite receiving traffic
                    v = 0.5 if j == 2 else float(5 + j + 10 * i)
                    h.send([int(j % 2), v], timestamp=t)

        mux, skips = self._run(True, "timeBatch(10)", gap_sends)
        ded, _ = self._run(False, "timeBatch(10)", gap_sends)
        assert mux == ded
        assert skips > 0

    def test_sharded_timebatch_gap_skips(self):
        """The same empty-pane skip on the mesh-sharded engine path
        (parallel/device_shard.py): identical rows, counted skips."""

        def run(devices):
            APP = ("@app:name('sh') @app:execution('tpu', partitions='16'%s) "
                   "@app:playback "
                   "define stream S (k long, v double); "
                   "@info(name='q') from S[v > 1.0]#window.timeBatch(10) "
                   "select k, sum(v) as s group by k insert into Out;"
                   ) % (", devices='8'" if devices else "")
            mgr = SiddhiManager()
            try:
                rt = mgr.create_siddhi_app_runtime(APP)
                got = []
                rt.add_callback("Out", _collector(got))
                rt.start()
                h = rt.get_input_handler("S")
                for j, t in enumerate([1000, 1002, 1050, 1052,
                                       1200, 1201, 1500]):
                    h.send([int(j % 2), float(5 + j)], timestamp=t)
                qr = rt.query_runtimes["q"]
                eng = qr.device_runtime.engine
                skips = getattr(eng, "flush_skips", None)
                rt.shutdown()
                return got, skips
            finally:
                mgr.shutdown()

        sharded, skips = run(True)
        single, _ = run(False)
        assert sharded == single and len(sharded) > 0
        assert skips and skips > 0


class TestMultiplexPersistence:
    def test_persist_restore_forgets_post_persist_event(self):
        """restore() rewinds exactly one tenant's seat state mid-pane;
        the other tenants' accumulators are untouched."""
        APP = """
@app:name('m{i}') @app:execution('tpu') @app:playback {mux}
define stream S (g double, price double);
@info(name='q') from S#window.lengthBatch(6)
select g, sum(price) as total group by g insert into Out;
"""

        def run(multiplex):
            mgr = SiddhiManager()
            mgr.set_persistence_store(InMemoryPersistenceStore())
            try:
                outs = {i: [] for i in range(3)}
                rts = []
                for i in range(3):
                    rt = mgr.create_siddhi_app_runtime(APP.format(
                        i=i,
                        mux="@app:multiplex(slots='4')" if multiplex else ""))
                    rts.append(rt)
                    rt.add_callback("Out", _collector(outs[i]))
                    rt.start()
                hs = [rt.get_input_handler("S") for rt in rts]
                for k in range(4):
                    for i, h in enumerate(hs):
                        h.send([float(k % 2), float(k + 10 * i)],
                               timestamp=1000 + k)
                # persist tenant 1 mid-pane, send one stray event, then
                # restore: the stray must be forgotten
                rts[1].persist()
                hs[1].send([0.0, 999.0], timestamp=1005)
                rts[1].restore_last_revision()
                for k in range(4, 6):
                    for i, h in enumerate(hs):
                        h.send([float(k % 2), float(k + 10 * i)],
                               timestamp=1000 + k)
                for rt in rts:
                    rt.shutdown()
                return outs
            finally:
                mgr.shutdown()

        mux = run(True)
        ded = run(False)
        assert any(mux[i] for i in mux)
        assert mux == ded
        # no pane ever saw the rolled-back 999 event
        assert all(total < 900 for rows in mux.values()
                   for (_g, total) in rows)
