"""Dense (jitted) absent-state patterns: `not X for t` on the TPU path.

Differential host-vs-dense corpus for absent semantics under
`@app:execution('tpu')`: trailing absent (timer emission), mid-chain
absent, logical and-not (with and without `for`), every-arms with
independent deadlines, within interplay, partitioned deadlines, and the
eligibility fallbacks.  Reference analog: the scheduler-armed absent
processors (AbsentStreamPreStateProcessor.java:35,
LogicalAbsentPreStateProcessor) exercised by
query/pattern/absent/AbsentPatternTestCase.java — here the deadline
lives in a per-(partition, node, instance) int32 register advanced by a
jitted timer step (ops/dense_nfa.py make_time_step).
"""

import numpy as np
import pytest

F56 = np.float32(55.6).item()  # 'price float' is float32 on both engines

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.dense_pattern import DensePatternRuntime

STREAMS = (
    "define stream Stream1 (symbol string, price float, volume int); "
    "define stream Stream2 (symbol string, price float, volume int); "
    "define stream Stream3 (symbol string, price float, volume int); "
    "define stream Tick (x int); "
)
# the Tick consumer keeps the junction alive so ticks always advance the
# playback watermark (and with it, absent deadlines)
TICK_SINK = "from Tick select x insert into IgnoredTicks; "
TPU = "@app:execution('tpu') "


def run(app, sends, out="OutputStream", with_ts=False):
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime("@app:playback " + app)
        got = []
        if with_ts:
            cb = lambda evs: got.extend(
                (list(e.data), e.timestamp) for e in evs)
        else:
            cb = lambda evs: got.extend(list(e.data) for e in evs)
        rt.add_callback(out, cb)
        rt.start()
        for stream, row, ts in sends:
            rt.get_input_handler(stream).send(row, timestamp=ts)
        qr = rt.query_runtimes.get("q")
        proc = getattr(qr, "pattern_processor", None) if qr else None
        rt.shutdown()
        return got, proc
    finally:
        m.shutdown()


def differential(query, sends, out="OutputStream", dense_expected=True):
    """Run host and dense forms of the same app; assert identical output
    (values AND timestamps) and that the dense form really lowered."""
    app = STREAMS + TICK_SINK + query
    host, hproc = run(app, sends, out, with_ts=True)
    dense, dproc = run(TPU + app, sends, out, with_ts=True)
    if dense_expected:
        assert isinstance(dproc, DensePatternRuntime), (
            "query did not lower to the dense path")
        assert not isinstance(hproc, DensePatternRuntime)
    assert dense == host, f"dense {dense} != host {host}"
    return host, dproc


class TestTrailingAbsentDense:
    Q = ("@info(name='q') from e1=Stream1[price>20] -> "
         "not Stream2[price>e1.price] for 1 sec "
         "select e1.price as p1 insert into OutputStream;")

    def test_fires_at_deadline(self):
        got, proc = differential(self.Q, [
            ("Stream1", ["WSO2", 55.6, 100], 1000),
            ("Tick", [1], 2500),
        ])
        # emission timestamp is the deadline, not the tick
        assert got == [([F56], 2000)]
        assert proc.time_fires == 1
        assert proc.step_invocations > 0

    def test_suppressed_by_matching_event(self):
        got, _ = differential(self.Q, [
            ("Stream1", ["WSO2", 55.6, 100], 1000),
            ("Stream2", ["IBM", 58.7, 100], 1500),
            ("Tick", [1], 2500),
        ])
        assert got == []

    def test_non_matching_absent_event_keeps_pending(self):
        # Stream2 event FAILING the filter (price <= e1.price) must not
        # cancel the pending deadline
        got, _ = differential(self.Q, [
            ("Stream1", ["WSO2", 55.6, 100], 1000),
            ("Stream2", ["IBM", 10.0, 100], 1500),
            ("Tick", [1], 2500),
        ])
        assert got == [([F56], 2000)]

    def test_event_after_deadline_does_not_cancel(self):
        got, _ = differential(self.Q, [
            ("Stream1", ["WSO2", 55.6, 100], 1000),
            ("Stream2", ["IBM", 58.7, 100], 2100),  # too late
        ])
        assert got == [([F56], 2000)]

    def test_every_arms_fire_independent_deadlines(self):
        q = self.Q.replace("from e1=", "from every e1=")
        got, proc = differential(q, [
            ("Stream1", ["A", 30.0, 1], 1000),   # deadline 2000
            ("Stream1", ["B", 40.0, 1], 1400),   # deadline 2400
            ("Tick", [1], 2200),                  # fires only A
            ("Tick", [2], 3000),                  # fires B
        ])
        assert got == [([30.0], 2000), ([40.0], 2400)]
        assert proc.time_fires == 2

    def test_every_kill_hits_all_matching_arms(self):
        q = self.Q.replace("from e1=", "from every e1=")
        got, _ = differential(q, [
            ("Stream1", ["A", 30.0, 1], 1000),
            ("Stream1", ["B", 40.0, 1], 1400),
            # price 35 > A's 30 kills A's arm; B's arm (40) survives
            ("Stream2", ["K", 35.0, 1], 1600),
            ("Tick", [1], 3000),
        ])
        assert got == [([40.0], 2400)]

    def test_within_expires_before_deadline(self):
        q = ("@info(name='q') from e1=Stream1[price>20] -> "
             "not Stream2[price>e1.price] for 2 sec "
             "within 1 sec "
             "select e1.price as p1 insert into OutputStream;")
        got, _ = differential(q, [
            ("Stream1", ["WSO2", 55.6, 100], 1000),
            ("Tick", [1], 4000),
        ])
        assert got == []


class TestMidChainAbsentDense:
    Q = ("@info(name='q') from e1=Stream1[price>20] -> "
         "not Stream2[price == e1.price] for 1 sec -> "
         "e3=Stream3[price > e1.price] "
         "select e1.price as p1, e3.price as p insert into OutputStream;")

    def test_third_state_matches_only_after_deadline(self):
        got, proc = differential(self.Q, [
            ("Stream1", ["W", 30.0, 1], 1000),    # deadline 2000
            ("Stream3", ["W", 50.0, 1], 1500),    # too early: still waiting
            ("Tick", [1], 2100),                   # deadline passes
            ("Stream3", ["W", 60.0, 1], 2500),    # now matches
        ])
        assert got == [([30.0, 60.0], 2500)]
        assert proc.step_invocations > 0

    def test_absent_event_kills_chain(self):
        got, _ = differential(self.Q, [
            ("Stream1", ["W", 30.0, 1], 1000),
            ("Stream2", ["W", 30.0, 1], 1500),    # same price: kill
            ("Tick", [1], 2100),
            ("Stream3", ["W", 60.0, 1], 2500),
        ])
        assert got == []

    def test_absent_filter_mismatch_keeps_chain(self):
        got, _ = differential(self.Q, [
            ("Stream1", ["W", 30.0, 1], 1000),
            ("Stream2", ["X", 1.0, 1], 1500),     # different price
            ("Tick", [1], 2100),
            ("Stream3", ["W", 60.0, 1], 2500),
        ])
        assert got == [([30.0, 60.0], 2500)]


class TestLogicalAbsentDense:
    def test_and_not_without_for_fires_on_present(self):
        q = ("@info(name='q') from e1=Stream1[price>20] -> "
             "(e2=Stream3[price>30] and not Stream2[price>40]) "
             "select e1.price as p1, e2.price as p insert into OutputStream;")
        got, proc = differential(q, [
            ("Stream1", ["W", 25.0, 1], 1000),
            ("Stream3", ["W", 35.0, 1], 1500),    # completes immediately
        ])
        assert got == [([25.0, 35.0], 1500)]

    def test_and_not_without_for_killed_by_absent(self):
        q = ("@info(name='q') from e1=Stream1[price>20] -> "
             "(e2=Stream3[price>30] and not Stream2[price>40]) "
             "select e1.price as p1, e2.price as p insert into OutputStream;")
        got, _ = differential(q, [
            ("Stream1", ["W", 25.0, 1], 1000),
            ("Stream2", ["K", 45.0, 1], 1200),    # violates before e2
            ("Stream3", ["W", 35.0, 1], 1500),
        ])
        assert got == []

    def test_and_not_for_waits_out_the_window(self):
        q = ("@info(name='q') from e1=Stream1[price>20] -> "
             "(e2=Stream3[price>30] and not Stream2[price>40] for 1 sec) "
             "select e1.price as p1, e2.price as p insert into OutputStream;")
        # e2 arrives INSIDE the window: completion deferred to deadline
        got, proc = differential(q, [
            ("Stream1", ["W", 25.0, 1], 1000),    # window ends 2000
            ("Stream3", ["W", 35.0, 1], 1500),
            ("Tick", [1], 2500),
        ])
        assert got == [([25.0, 35.0], 2000)]
        assert proc.time_fires == 1

    def test_and_not_for_present_after_window_completes_immediately(self):
        q = ("@info(name='q') from e1=Stream1[price>20] -> "
             "(e2=Stream3[price>30] and not Stream2[price>40] for 1 sec) "
             "select e1.price as p1, e2.price as p insert into OutputStream;")
        got, _ = differential(q, [
            ("Stream1", ["W", 25.0, 1], 1000),
            ("Tick", [1], 2200),                   # window passes, no e2 yet
            ("Stream3", ["W", 35.0, 1], 2500),    # completes at its own ts
        ])
        assert got == [([25.0, 35.0], 2500)]

    def test_and_not_for_violated_inside_window(self):
        q = ("@info(name='q') from e1=Stream1[price>20] -> "
             "(e2=Stream3[price>30] and not Stream2[price>40] for 1 sec) "
             "select e1.price as p1, e2.price as p insert into OutputStream;")
        got, _ = differential(q, [
            ("Stream1", ["W", 25.0, 1], 1000),
            ("Stream3", ["W", 35.0, 1], 1300),
            ("Stream2", ["K", 45.0, 1], 1600),    # violates pre-deadline
            ("Tick", [1], 2500),
        ])
        assert got == []


class TestPartitionedAbsentDense:
    APP = (
        "@app:execution('tpu', partitions='64') "
        + STREAMS + TICK_SINK +
        "partition with (symbol of Stream1, symbol of Stream2) begin "
        "@info(name='q') from e1=Stream1[price>20] -> "
        "not Stream2[price>e1.price] for 1 sec "
        "select e1.price as p insert into OutputStream; "
        "end;"
    )
    HOST_APP = APP.replace("@app:execution('tpu', partitions='64') ", "")

    def _run(self, app, sends):
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime("@app:playback " + app)
            got = []
            rt.add_callback(
                "OutputStream",
                lambda evs: got.extend((list(e.data), e.timestamp)
                                       for e in evs))
            rt.start()
            for stream, row, ts in sends:
                rt.get_input_handler(stream).send(row, timestamp=ts)
            rt.shutdown()
            return got
        finally:
            m.shutdown()

    def test_per_key_deadlines(self):
        sends = [
            ("Stream1", ["A", 30.0, 1], 1000),    # A deadline 2000
            ("Stream1", ["B", 50.0, 1], 1200),    # B deadline 2200
            ("Stream2", ["B", 60.0, 1], 1500),    # kills B's key only
            ("Tick", [1], 3000),
        ]
        host = self._run(self.HOST_APP, sends)
        dense = self._run(self.APP, sends)
        assert dense == host
        assert sorted(dense) == [([30.0], 2000)]


class TestAbsentEligibilityFallbacks:
    def _proc(self, query, app_prefix=TPU):
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(
                "@app:playback " + app_prefix + STREAMS + TICK_SINK + query)
            qr = rt.query_runtimes.get("q")
            proc = getattr(qr, "pattern_processor", None)
            rt.shutdown()
            return proc
        finally:
            m.shutdown()

    def test_leading_absent_falls_back(self):
        proc = self._proc(
            "@info(name='q') from not Stream1[price>20] for 1 sec -> "
            "e2=Stream2[price>20] "
            "select e2.price as p insert into OutputStream;")
        assert not isinstance(proc, DensePatternRuntime)

    def test_sequence_absent_falls_back(self):
        proc = self._proc(
            "@info(name='q') from e1=Stream1[price>20], "
            "not Stream2[price>e1.price] for 1 sec "
            "select e1.price as p insert into OutputStream;")
        assert not isinstance(proc, DensePatternRuntime)

    def test_every_start_logical_and_not_falls_back(self):
        # the host virgin instance dies permanently on an absent-side
        # violation; the dense standing virgin would re-arm forever —
        # the shape must stay on the host engine (review finding r4)
        proc = self._proc(
            "@info(name='q') from every (e1=Stream1[price>20] "
            "and not Stream2[price>40]) "
            "select e1.price as p insert into OutputStream;")
        assert not isinstance(proc, DensePatternRuntime)
        got_h, _ = run(
            STREAMS + TICK_SINK +
            "@info(name='q') from every (e1=Stream1[price>20] "
            "and not Stream2[price>40]) "
            "select e1.price as p insert into OutputStream;", [
                ("Stream2", ["K", 45.0, 1], 1000),
                ("Stream1", ["W", 25.0, 1], 1500),
                ("Stream1", ["W", 26.0, 1], 1600),
            ])
        got_d, _ = run(
            TPU + STREAMS + TICK_SINK +
            "@info(name='q') from every (e1=Stream1[price>20] "
            "and not Stream2[price>40]) "
            "select e1.price as p insert into OutputStream;", [
                ("Stream2", ["K", 45.0, 1], 1000),
                ("Stream1", ["W", 25.0, 1], 1500),
                ("Stream1", ["W", 26.0, 1], 1600),
            ])
        assert got_d == got_h == []

    def test_all_absent_logical_node_matches_host(self):
        # (not B and not C for t): no present side — completion can only
        # come from the timer, never from a non-killing event of a
        # constituent stream (review finding r4)
        q = ("@info(name='q') from e1=Stream1[price>20] -> "
             "(not Stream2[price>40] and not Stream3[price>40] for 1 sec) "
             "select e1.price as p insert into OutputStream;")
        got, _ = differential(q, [
            ("Stream1", ["W", 25.0, 1], 1000),     # window ends 2000
            ("Stream2", ["X", 10.0, 1], 1500),     # filter fails: no kill
            ("Tick", [1], 2500),                    # timer completes
        ])
        assert got == [([25.0], 2000)]
        got2, _ = differential(q, [
            ("Stream1", ["W", 25.0, 1], 1000),
            ("Stream3", ["K", 45.0, 1], 1500),     # violation: killed
            ("Tick", [1], 2500),
        ])
        assert got2 == []

    def test_same_stream_and_not_falls_back(self):
        proc = self._proc(
            "@info(name='q') from e1=Stream1[price>20] -> "
            "(e2=Stream1[price>30] and not Stream1[price>100]) "
            "select e1.price as p insert into OutputStream;")
        assert not isinstance(proc, DensePatternRuntime)

    def test_eligible_absent_lowers_dense(self):
        proc = self._proc(
            "@info(name='q') from e1=Stream1[price>20] -> "
            "not Stream2[price>e1.price] for 1 sec "
            "select e1.price as p insert into OutputStream;")
        assert isinstance(proc, DensePatternRuntime)
        assert proc.engine.has_deadlines


class TestAbsentSnapshotDense:
    def test_pending_deadline_survives_restore(self):
        app = ("@app:playback " + TPU + STREAMS + TICK_SINK +
               "@info(name='q') from e1=Stream1[price>20] -> "
               "not Stream2[price>e1.price] for 1 sec "
               "select e1.price as p insert into OutputStream;")
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(app)
            got = []
            rt.add_callback(
                "OutputStream",
                lambda evs: got.extend((list(e.data), e.timestamp)
                                       for e in evs))
            rt.start()
            rt.get_input_handler("Stream1").send(
                ["WSO2", 55.6, 100], timestamp=1000)
            snap = rt.snapshot()
            # kill the pending instance, then restore: it must come back
            rt.get_input_handler("Stream2").send(
                ["K", 60.0, 1], timestamp=1200)
            rt.restore(snap)
            rt.get_input_handler("Tick").send([1], timestamp=2500)
            rt.shutdown()
            assert got == [([F56], 2000)]
        finally:
            m.shutdown()


class TestPartitionedAggregatingAbsent:
    """Absent + aggregating selector + partitioned, all dense: timer
    matches map engine rows back to their partition keys so the shared
    partition-axis selector aggregates per key."""

    APP = (
        STREAMS + TICK_SINK +
        "partition with (symbol of Stream1, symbol of Stream2) begin "
        "@info(name='q') from every e1=Stream1[price>20] -> "
        "not Stream2[price>e1.price] for 1 sec "
        "select count() as n insert into OutputStream; "
        "end;"
    )

    def _drive(self, header, sends):
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(
                "@app:playback " + header + self.APP)
            got = []
            rt.add_callback(
                "OutputStream",
                lambda evs: got.extend(list(e.data) for e in evs))
            rt.start()
            for stream, row, ts in sends:
                rt.get_input_handler(stream).send(row, timestamp=ts)
            pr = rt.partitions.get("partition_0")
            runtime = (next(iter(pr.dense_query_runtimes.values()))
                       .pattern_processor
                       if pr is not None and getattr(pr, "is_dense", False)
                       else None)
            rt.shutdown()
            return got, runtime
        finally:
            m.shutdown()

    def test_per_key_counts_from_timer_matches(self):
        sends = [
            ("Stream1", ["a", 30.0, 1], 1000),   # a deadline 2000
            ("Stream1", ["b", 40.0, 1], 1200),   # b deadline 2200
            ("Stream2", ["b", 50.0, 1], 1500),   # kills b's arm
            ("Tick", [1], 3000),                  # fires a
            ("Stream1", ["a", 35.0, 1], 3500),   # a deadline 4500
            ("Tick", [2], 5000),                  # fires a again
        ]
        host, hproc = self._drive("", sends)
        dense, dproc = self._drive(
            "@app:execution('tpu', partitions='16') ", sends)
        assert hproc is None
        assert isinstance(dproc, DensePatternRuntime)
        assert dproc.engine.has_deadlines
        assert dproc.time_fires >= 2
        # per-key count: a fires twice (n=1, n=2); b never fires
        assert dense == host == [[1], [2]]
