"""Config plane tests (reference: util/config/ + config test cases)."""

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.util.config import (
    ConfigReader,
    InMemoryConfigManager,
    YAMLConfigManager,
)


@pytest.fixture
def manager():
    m = SiddhiManager()
    yield m
    m.shutdown()


YAML_DOC = """
properties:
  deployment.mode: test
extensions:
  - extension:
      namespace: source
      name: inMemory
      properties:
        default.prefix: pfx
refs:
  - ref:
      name: bus1
      type: inMemory
      properties:
        topic: cfg-topic
"""


class TestConfigManagers:
    def test_in_memory_reader(self):
        cm = InMemoryConfigManager(
            {"source.http.port": "8280", "global.prop": "x"},
            {"ref1": {"type": "inMemory", "topic": "t"}},
        )
        r = cm.generate_config_reader("source", "http")
        assert r.read_config("port") == "8280"
        assert r.read_config("missing", "dflt") == "dflt"
        assert cm.extract_system_configs("ref1")["topic"] == "t"
        assert cm.extract_property("global.prop") == "x"

    def test_yaml_manager(self):
        cm = YAMLConfigManager(YAML_DOC)
        assert cm.extract_property("deployment.mode") == "test"
        r = cm.generate_config_reader("source", "inMemory")
        assert r.read_config("default.prefix") == "pfx"
        refs = cm.extract_system_configs("bus1")
        assert refs == {"type": "inMemory", "topic": "cfg-topic"}
        assert cm.generate_config_reader("sink", "nope").get_all_configs() == {}

    def test_source_by_ref(self, manager):
        import time

        from siddhi_tpu.transport.broker import InMemoryBroker

        manager.set_config_manager(YAMLConfigManager(YAML_DOC))
        rt = manager.create_siddhi_app_runtime(
            "@source(ref='bus1', @map(type='passThrough')) "
            "define stream S (v long); "
            "from S[v > 1] select v insert into Out;"
        )
        got = []
        rt.add_callback("Out", lambda evs: got.extend(evs))
        rt.start()
        InMemoryBroker.publish("cfg-topic", [5])
        time.sleep(0.1)
        rt.shutdown()
        assert [e.data[0] for e in got] == [5]

    def test_undefined_ref_raises(self, manager):
        from siddhi_tpu.core.exceptions import SiddhiAppCreationError

        with pytest.raises(SiddhiAppCreationError):
            manager.create_siddhi_app_runtime(
                "@source(ref='nope', @map(type='passThrough')) "
                "define stream S (v long); from S select v insert into O;"
            )

    def test_store_config_reader_passed(self, manager):
        from siddhi_tpu.table import InMemoryRecordStore

        seen = {}

        class CfgStore(InMemoryRecordStore):
            def init(self, definition, options, config_reader=None):
                super().init(definition, options, config_reader)
                seen["reader"] = config_reader

        manager.set_extension("cfgstore", CfgStore, kind="store")
        manager.set_config_manager(InMemoryConfigManager(
            {"store.cfgstore.flush.interval": "9"}
        ))
        rt = manager.create_siddhi_app_runtime(
            "@store(type='cfgstore') define table T (v long); "
            "define stream S (v long); from S select v insert into T;"
        )
        rt.start()
        rt.shutdown()
        assert seen["reader"].read_config("flush.interval") == "9"
