"""Table-mutation conformance, part 2: update / update-or-insert /
delete / set-clause matrices ported from the reference corpus
(modules/siddhi-core/src/test/java/io/siddhi/core/query/table/
UpdateFromTableTestCase.java, UpdateOrInsertTableTestCase.java,
DeleteFromTableTestCase.java, set/SetUpdateInMemoryTableTestCase.java).
Final table contents are asserted with on-demand pull queries (the
`in`-membership check streams mirror the reference's OutStream
assertions where the scenario relies on them).
"""

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager

F = lambda x: np.float32(x).item()  # table floats are exact float32

DEFS = (
    "define stream StockStream (symbol string, price float, volume long); "
    "define stream UpdateStockStream (symbol string, price float, volume long); "
    "define stream DeleteStockStream (symbol string, price float, volume long); "
    "define table StockTable (symbol string, price float, volume long); "
)
INSERT = "@info(name='q1') from StockStream insert into StockTable; "

STOCKS = [["WSO2", 55.6, 100], ["IBM", 75.6, 100], ["WSO2", 57.6, 100]]


def run_app(app, sends):
    """sends: (stream_id, row); returns the runtime factory result
    (runtime kept open for on-demand queries until shutdown)."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    rt.start()
    for stream, row in sends:
        rt.get_input_handler(stream).send(row)
    return m, rt


def table_rows(rt, select="symbol, price, volume"):
    events = rt.query(f"from StockTable select {select};")
    return sorted(tuple(e.data) for e in events)


class TestUpdateFromTable:
    def test_update_on_constant_no_match_keeps_table(self):
        # UpdateFromTableTestCase.updateFromTableTest1: GOOG update row
        # matches on symbol=='IBM' -> IBM row takes GOOG's values
        app = DEFS + INSERT + (
            "@info(name='q2') from UpdateStockStream update StockTable "
            "on StockTable.symbol=='IBM';")
        m, rt = run_app(app, [("StockStream", s) for s in STOCKS]
                        + [("UpdateStockStream", ["GOOG", 10.6, 100])])
        try:
            assert table_rows(rt) == sorted([
                ("WSO2", F(55.6), 100), ("GOOG", F(10.6), 100),
                ("WSO2", F(57.6), 100)])
        finally:
            m.shutdown()

    def test_update_on_stream_attr(self):
        # updateFromTableTest2: both WSO2 rows replaced
        app = DEFS + INSERT + (
            "@info(name='q2') from UpdateStockStream update StockTable "
            "on StockTable.symbol==symbol;")
        m, rt = run_app(app, [("StockStream", s) for s in STOCKS]
                        + [("UpdateStockStream", ["WSO2", 10.0, 100])])
        try:
            assert table_rows(rt) == sorted([
                ("WSO2", F(10.0), 100), ("IBM", F(75.6), 100),
                ("WSO2", F(10.0), 100)])
        finally:
            m.shutdown()

    def test_update_then_in_membership(self):
        # updateFromTableTest3: `in` checks see pre- and post-update rows
        app = DEFS + INSERT + (
            "define stream CheckStockStream (symbol string, volume long); "
            "@info(name='q2') from UpdateStockStream update StockTable "
            "on StockTable.symbol==symbol; "
            "@info(name='q3') from CheckStockStream["
            "(symbol==StockTable.symbol and volume==StockTable.volume) "
            "in StockTable] insert into OutStream;")
        m = SiddhiManager()
        rt = m.create_siddhi_app_runtime(app)
        got = []
        rt.add_callback("OutStream", lambda evs: got.extend(list(e.data) for e in evs))
        rt.start()
        try:
            rt.get_input_handler("StockStream").send(["WSO2", 55.6, 100])
            rt.get_input_handler("StockStream").send(["IBM", 55.6, 100])
            chk = rt.get_input_handler("CheckStockStream")
            chk.send(["IBM", 100])
            chk.send(["WSO2", 100])
            rt.get_input_handler("UpdateStockStream").send(["IBM", 77.6, 200])
            chk.send(["IBM", 100])   # volume now 200: no membership
            chk.send(["WSO2", 100])
            assert got == [["IBM", 100], ["WSO2", 100], ["WSO2", 100]]
        finally:
            m.shutdown()

    def test_update_with_projected_subset(self):
        # updateFromTableTest4/5: update query projects (symbol, volume)
        # only — untouched columns keep their values
        app = DEFS.replace(
            "define stream UpdateStockStream (symbol string, price float, "
            "volume long); ",
            "define stream UpdateStockStream (comp string, vol long); "
        ) + INSERT + (
            "@info(name='q2') from UpdateStockStream "
            "select comp as symbol, vol as volume "
            "update StockTable on StockTable.symbol==symbol;")
        m, rt = run_app(app, [
            ("StockStream", ["WSO2", 55.6, 100]),
            ("StockStream", ["IBM", 155.6, 100]),
            ("UpdateStockStream", ["IBM", 200]),
        ])
        try:
            # price survives the partial update
            assert table_rows(rt) == sorted([
                ("WSO2", F(55.6), 100), ("IBM", F(155.6), 200)])
        finally:
            m.shutdown()

    def test_update_via_table_join_values(self):
        # updateFromTableTest6: join supplies the update row
        app = DEFS.replace(
            "define stream UpdateStockStream (symbol string, price float, "
            "volume long); ",
            "define stream UpdateStockStream (comp string, vol long); "
        ) + INSERT + (
            "@info(name='q2') from UpdateStockStream join StockTable "
            "on UpdateStockStream.comp == StockTable.symbol "
            "select symbol, vol as volume "
            "update StockTable on StockTable.symbol==symbol;")
        m, rt = run_app(app, [
            ("StockStream", ["WSO2", 55.6, 100]),
            ("StockStream", ["IBM", 155.6, 100]),
            ("UpdateStockStream", ["IBM", 200]),
        ])
        try:
            assert table_rows(rt) == sorted([
                ("WSO2", F(55.6), 100), ("IBM", F(155.6), 200)])
        finally:
            m.shutdown()


class TestSetClauseUpdate:
    def _final(self, q2, update_row=("IBM", 100.0, 100)):
        app = DEFS + INSERT + q2
        m, rt = run_app(app, [("StockStream", s) for s in STOCKS]
                        + [("UpdateStockStream", list(update_row))])
        try:
            return table_rows(rt)
        finally:
            m.shutdown()

    def test_set_all_columns(self):
        # SetUpdateInMemoryTableTestCase.updateFromTableTest1
        rows = self._final(
            "@info(name='q2') from UpdateStockStream update StockTable "
            "set StockTable.price = price, StockTable.symbol = symbol, "
            "StockTable.volume = volume on StockTable.symbol == symbol;")
        assert rows == sorted([
            ("WSO2", F(55.6), 100), ("IBM", F(100.0), 100),
            ("WSO2", F(57.6), 100)])

    def test_set_subset_of_columns(self):
        # updateFromTableTest2: volume untouched
        rows = self._final(
            "@info(name='q2') from UpdateStockStream update StockTable "
            "set StockTable.price = price, StockTable.symbol = symbol "
            "on StockTable.symbol == symbol;")
        assert rows == sorted([
            ("WSO2", F(55.6), 100), ("IBM", F(100.0), 100),
            ("WSO2", F(57.6), 100)])

    def test_set_constant(self):
        # updateFromTableTest3
        rows = self._final(
            "@info(name='q2') from UpdateStockStream update StockTable "
            "set StockTable.price = 10 on StockTable.symbol == symbol;")
        assert rows == sorted([
            ("WSO2", F(55.6), 100), ("IBM", F(10.0), 100),
            ("WSO2", F(57.6), 100)])

    def test_set_from_projected_arithmetic(self):
        # updateFromTableTest4: select price+100 as newPrice -> set
        rows = self._final(
            "@info(name='q2') from UpdateStockStream "
            "select price + 100 as newPrice, symbol "
            "update StockTable set StockTable.price = newPrice "
            "on StockTable.symbol == symbol;")
        assert rows == sorted([
            ("WSO2", F(55.6), 100), ("IBM", F(200.0), 100),
            ("WSO2", F(57.6), 100)])

    def test_set_expression_over_projection(self):
        # updateFromTableTest5: set price = newPrice + 100
        rows = self._final(
            "@info(name='q2') from UpdateStockStream "
            "select price + 100 as newPrice, symbol "
            "update StockTable set StockTable.price = newPrice + 100 "
            "on StockTable.symbol == symbol;")
        assert rows == sorted([
            ("WSO2", F(55.6), 100), ("IBM", F(300.0), 100),
            ("WSO2", F(57.6), 100)])

    def test_set_unqualified_lhs(self):
        # updateFromTableTest6: bare `set price = 100`
        rows = self._final(
            "@info(name='q2') from UpdateStockStream update StockTable "
            "set price = 100 on StockTable.symbol == symbol;")
        assert rows == sorted([
            ("WSO2", F(55.6), 100), ("IBM", F(100.0), 100),
            ("WSO2", F(57.6), 100)])


class TestUpdateOrInsert:
    def test_no_match_inserts(self):
        # UpdateOrInsertTableTestCase.updateOrInsertTableTest1: GOOG
        # update on symbol=='IBM' REPLACES the IBM row (condition hit)
        app = DEFS + INSERT + (
            "@info(name='q2') from UpdateStockStream "
            "update or insert into StockTable "
            "on StockTable.symbol=='IBM';")
        m, rt = run_app(app, [("StockStream", s) for s in STOCKS]
                        + [("UpdateStockStream", ["GOOG", 10.6, 100])])
        try:
            assert table_rows(rt) == sorted([
                ("WSO2", F(55.6), 100), ("GOOG", F(10.6), 100),
                ("WSO2", F(57.6), 100)])
        finally:
            m.shutdown()

    def test_upsert_as_only_writer(self):
        # updateOrInsertTableTest2: stream upserts directly; the second
        # WSO2 row updates BOTH earlier WSO2 rows
        app = DEFS + (
            "@info(name='q2') from StockStream "
            "update or insert into StockTable "
            "on StockTable.symbol==symbol;")
        m, rt = run_app(app, [
            ("StockStream", ["WSO2", 55.6, 100]),
            ("StockStream", ["IBM", 75.6, 100]),
            ("StockStream", ["WSO2", 57.6, 100]),
            ("StockStream", ["WSO2", 10.0, 100]),
        ])
        try:
            assert table_rows(rt) == sorted([
                ("WSO2", F(10.0), 100), ("IBM", F(75.6), 100)])
        finally:
            m.shutdown()

    def test_upsert_inserts_fresh_key(self):
        # updateOrInsertTableTest5: FB row not present -> inserted
        app = DEFS.replace(
            "define stream UpdateStockStream (symbol string, price float, "
            "volume long); ",
            "define stream UpdateStockStream (comp string, vol long); "
        ) + INSERT + (
            "@info(name='q2') from UpdateStockStream "
            "select comp as symbol, vol as volume "
            "update or insert into StockTable "
            "on StockTable.symbol==symbol;")
        m, rt = run_app(app, [
            ("StockStream", ["WSO2", 55.6, 100]),
            ("StockStream", ["IBM", 55.6, 100]),
            ("UpdateStockStream", ["FB", 300]),
        ])
        try:
            rows = table_rows(rt, select="symbol, volume")
            assert rows == sorted([("WSO2", 100), ("IBM", 100),
                                   ("FB", 300)])
        finally:
            m.shutdown()

    def test_upsert_partial_projection_inserts_defaults(self):
        # updateOrInsertTableTest7: projected 0f price lands on both the
        # update and the membership check
        app = DEFS.replace(
            "define stream UpdateStockStream (symbol string, price float, "
            "volume long); ",
            "define stream UpdateStockStream (comp string, vol long); "
        ) + INSERT + (
            "@info(name='q2') from UpdateStockStream "
            "select comp as symbol, 0f as price, vol as volume "
            "update or insert into StockTable "
            "on StockTable.symbol==symbol;")
        m, rt = run_app(app, [
            ("StockStream", ["WSO2", 55.6, 100]),
            ("StockStream", ["IBM", 155.6, 100]),
            ("UpdateStockStream", ["IBM", 200]),
        ])
        try:
            assert table_rows(rt) == sorted([
                ("WSO2", F(55.6), 100), ("IBM", F(0.0), 200)])
        finally:
            m.shutdown()


class TestDeleteFromTable:
    def _final(self, q2, deletes):
        app = DEFS + INSERT + q2
        m, rt = run_app(app, [("StockStream", s) for s in STOCKS]
                        + [("DeleteStockStream", d) for d in deletes])
        try:
            return table_rows(rt)
        finally:
            m.shutdown()

    def test_no_delete_event_keeps_rows(self):
        # DeleteFromTableTestCase.deleteFromTableTest0
        rows = self._final(
            "@info(name='q2') from DeleteStockStream delete StockTable "
            "on symbol=='IBM';", [])
        assert len(rows) == 3

    def test_delete_condition_on_event_only(self):
        # bare attrs in an on-condition bind to the matching EVENT
        # (shadowing same-named table columns — _merge_table_scope):
        # an event-only condition deletes ALL rows when it holds and
        # nothing otherwise (the reference's deleteFromTableTest1/3
        # only smoke-test this shape; qualified forms are pinned below)
        rows = self._final(
            "@info(name='q2') from DeleteStockStream delete StockTable "
            "on symbol=='IBM';", [["IBM", 57.6, 100]])
        assert rows == []
        rows = self._final(
            "@info(name='q2') from DeleteStockStream delete StockTable "
            "on symbol=='IBM';", [["WSO2", 57.6, 100]])
        assert len(rows) == 3

    def test_delete_on_qualified_constant(self):
        # deleteFromTableTest2
        rows = self._final(
            "@info(name='q2') from DeleteStockStream delete StockTable "
            "on StockTable.symbol=='IBM';", [["WSO2", 57.6, 100]])
        assert rows == sorted([
            ("WSO2", F(55.6), 100), ("WSO2", F(57.6), 100)])

    def test_delete_with_stream_filter(self):
        # deleteFromTableTest5: [vol>=100] gates the delete
        app = (
            "define stream StockStream (symbol string, price float, vol long); "
            "define stream DeleteStockStream (symbol string, price float, vol long); "
            "define table StockTable (symbol string, price float, volume long); "
            "@info(name='q1') from StockStream "
            "select symbol, price, vol as volume insert into StockTable; "
            "@info(name='q2') from DeleteStockStream[vol>=100] "
            "delete StockTable on StockTable.symbol==symbol;")
        m, rt = run_app(app, [
            ("StockStream", ["WSO2", 55.6, 100]),
            ("StockStream", ["IBM", 75.6, 100]),
            ("StockStream", ["WSO2", 57.6, 100]),
            ("DeleteStockStream", ["IBM", 57.6, 100]),
        ])
        try:
            assert table_rows(rt) == sorted([
                ("WSO2", F(55.6), 100), ("WSO2", F(57.6), 100)])
        finally:
            m.shutdown()

    def test_delete_then_membership(self):
        # deleteFromTableTest4
        app = DEFS + INSERT + (
            "define stream CheckStockStream (symbol string); "
            "@info(name='q2') from DeleteStockStream delete StockTable "
            "on StockTable.symbol=='IBM'; "
            "@info(name='q3') from CheckStockStream["
            "symbol==StockTable.symbol in StockTable] "
            "insert into OutStream;")
        m = SiddhiManager()
        rt = m.create_siddhi_app_runtime(app)
        got = []
        rt.add_callback("OutStream", lambda evs: got.extend(list(e.data) for e in evs))
        rt.start()
        try:
            rt.get_input_handler("StockStream").send(["WSO2", 55.6, 100])
            rt.get_input_handler("StockStream").send(["IBM", 55.6, 100])
            chk = rt.get_input_handler("CheckStockStream")
            chk.send(["IBM"])
            chk.send(["WSO2"])
            rt.get_input_handler("DeleteStockStream").send(["IBM", 57.6, 100])
            chk.send(["IBM"])
            chk.send(["WSO2"])
            assert got == [["IBM"], ["WSO2"], ["WSO2"]]
        finally:
            m.shutdown()


class TestPrimaryKeyIndexMatrix:
    """Probe-vs-scan correctness over primary-key and indexed columns
    (the behavioral surface of PrimaryKeyTableTestCase /
    IndexTableTestCase: every compiled-condition plan must return the
    same rows a full scan would)."""

    APP = (
        "define stream Ins (symbol string, price float, volume long); "
        "define stream Probe (symbol string, price float, volume long); "
        "@primaryKey('symbol') @index('volume') "
        "define table T (symbol string, price float, volume long); "
        "from Ins insert into T; "
    )

    ROWS = [
        ["A", 10.0, 100], ["B", 20.0, 200], ["C", 30.0, 200],
        ["D", 40.0, 300], ["E", 50.0, 400],
    ]

    CONDS = [
        # (on-condition, expected symbols)
        ("T.symbol == 'C'", {"C"}),
        ("T.symbol == 'Z'", set()),
        ("T.volume == 200", {"B", "C"}),
        ("T.volume != 200", {"A", "D", "E"}),
        ("T.volume > 200", {"D", "E"}),
        ("T.volume >= 200", {"B", "C", "D", "E"}),
        ("T.volume < 200", {"A"}),
        ("T.volume <= 200", {"A", "B", "C"}),
        ("T.symbol == 'C' and T.volume == 200", {"C"}),
        ("T.symbol == 'C' and T.volume == 300", set()),
        ("T.symbol == 'B' or T.symbol == 'D'", {"B", "D"}),
        ("T.volume == 200 and T.price > 25.0", {"C"}),
        ("T.price > 25.0", {"C", "D", "E"}),  # non-indexed scan
        ("not (T.volume == 200)", {"A", "D", "E"}),
    ]

    def test_condition_matrix_on_demand(self):
        m = SiddhiManager()
        rt = m.create_siddhi_app_runtime(self.APP)
        rt.start()
        try:
            for r in self.ROWS:
                rt.get_input_handler("Ins").send(r)
            for cond, want in self.CONDS:
                events = rt.query(f"from T on {cond} select symbol;")
                got = {e.data[0] for e in events}
                assert got == want, f"cond {cond}: {got} != {want}"
        finally:
            m.shutdown()

    def test_pk_upsert_replaces_row(self):
        app = self.APP + (
            "define stream Up (symbol string, price float, volume long); "
            "from Up update or insert into T on T.symbol == symbol; ")
        m = SiddhiManager()
        rt = m.create_siddhi_app_runtime(app)
        rt.start()
        try:
            for r in self.ROWS:
                rt.get_input_handler("Ins").send(r)
            rt.get_input_handler("Up").send(["C", 99.0, 999])
            events = rt.query("from T on T.symbol == 'C' "
                              "select symbol, price, volume;")
            assert [tuple(e.data) for e in events] == [("C", F(99.0), 999)]
            # the index must track the moved volume
            events = rt.query("from T on T.volume == 999 select symbol;")
            assert [e.data[0] for e in events] == ["C"]
            events = rt.query("from T on T.volume == 200 select symbol;")
            assert {e.data[0] for e in events} == {"B"}
        finally:
            m.shutdown()

    def test_index_tracks_deletes(self):
        app = self.APP + (
            "define stream Del (symbol string); "
            "from Del delete T on T.symbol == symbol; ")
        m = SiddhiManager()
        rt = m.create_siddhi_app_runtime(app)
        rt.start()
        try:
            for r in self.ROWS:
                rt.get_input_handler("Ins").send(r)
            rt.get_input_handler("Del").send(["B"])
            events = rt.query("from T on T.volume == 200 select symbol;")
            assert {e.data[0] for e in events} == {"C"}
        finally:
            m.shutdown()
