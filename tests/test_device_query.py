"""Device (jitted) general-query pipeline vs the host engine.

Every test runs the same SiddhiQL app through BOTH paths on the same
event series — the host engine via the public SiddhiManager API
(playback mode so event time drives windows deterministically) and the
device engine via ops.device_query.compile_query — and asserts the
emitted rows agree.  Reference behavior being pinned:
QuerySelector.java:76-99 (+ aggregator executors), FilterProcessor,
Length/Time/LengthBatch/TimeBatchWindowProcessor.
"""

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.ops.device_query import compile_query


def host_rows(app, sends, out="OutputStream"):
    """Run via the public API in playback mode -> list of row dicts."""
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime("@app:playback " + app)
        got = []
        rt.add_callback(out, lambda evs: got.extend(evs))
        rt.start()
        h = rt.get_input_handler("S")
        for row, ts in sends:
            h.send(row, timestamp=ts)
        rt.shutdown()
        names = rt.junctions[out].definition.attribute_names
        return [dict(zip(names, e.data)) for e in got]
    finally:
        m.shutdown()


def device_rows(app, sends, attrs, **kw):
    eng = compile_query(app, **kw)
    state = eng.init_state()
    cols = {a: np.asarray([r[i] for r, _t in sends], dtype=np.float64)
            for i, a in enumerate(attrs)}
    ts = np.asarray([t for _r, t in sends], dtype=np.int64)
    state, rows = eng.process(state, cols, ts)
    return rows


def assert_rows_close(host, dev, ordered=True):
    assert len(host) == len(dev), f"{len(host)} host vs {len(dev)} device rows"

    def norm(row):
        return tuple(
            round(float(v), 3) if isinstance(v, (int, float, np.number))
            else v
            for v in row.values()
        )

    h = [norm(r) for r in host]
    d = [norm(r) for r in dev]
    if not ordered:
        h, d = sorted(h), sorted(d)
    for i, (a, b) in enumerate(zip(h, d)):
        assert a == pytest.approx(b, rel=1e-4, abs=1e-3), (
            f"row {i}: host {a} != device {b}")


def series(n, seed, n_keys=4, t0=1000, dt_max=400):
    rng = np.random.default_rng(seed)
    ts = t0 + np.cumsum(rng.integers(1, dt_max, size=n))
    keys = rng.integers(0, n_keys, size=n)
    vals = rng.integers(1, 100, size=n).astype(float)
    return [([int(k), float(v)], int(t)) for k, v, t in zip(keys, vals, ts)]


APP_ATTRS = ["k", "v"]
DEFINE = "define stream S (k long, v double); "


class TestFilterQuery:
    APP = DEFINE + "from S[v > 50.0] select k, v, v * 2.0 as dbl insert into OutputStream;"

    def test_equivalence(self):
        sends = series(200, seed=1)
        assert_rows_close(
            host_rows(self.APP, sends),
            device_rows(self.APP, sends, APP_ATTRS),
        )

    def test_no_window_no_state(self):
        eng = compile_query(self.APP)
        assert eng.kind == "filter"
        assert eng.init_state() == {}


class TestRunningAggregates:
    def test_ungrouped_running_sum_count(self):
        app = DEFINE + (
            "from S[v > 20.0] select sum(v) as s, count() as c, avg(v) as a "
            "insert into OutputStream;")
        sends = series(150, seed=2)
        assert_rows_close(host_rows(app, sends),
                          device_rows(app, sends, APP_ATTRS))

    def test_grouped_running_min_max(self):
        app = DEFINE + (
            "from S select k, min(v) as lo, max(v) as hi, sum(v) as s "
            "group by k insert into OutputStream;")
        sends = series(200, seed=3, n_keys=7)
        assert_rows_close(host_rows(app, sends),
                          device_rows(app, sends, APP_ATTRS))

    def test_multiple_batches_carry_state(self):
        app = DEFINE + (
            "from S select k, sum(v) as s group by k "
            "insert into OutputStream;")
        sends = series(120, seed=4)
        eng = compile_query(app)
        state = eng.init_state()
        dev = []
        for lo in range(0, 120, 37):  # uneven batch splits
            chunk = sends[lo:lo + 37]
            cols = {a: np.asarray([r[i] for r, _t in chunk], dtype=float)
                    for i, a in enumerate(APP_ATTRS)}
            ts = np.asarray([t for _r, t in chunk], dtype=np.int64)
            state, rows = eng.process(state, cols, ts)
            dev.extend(rows)
        assert_rows_close(host_rows(app, sends), dev)


class TestSlidingLengthWindow:
    def test_ungrouped(self):
        app = DEFINE + (
            "from S#window.length(5) select sum(v) as s, count() as c "
            "insert into OutputStream;")
        sends = series(100, seed=5)
        assert_rows_close(host_rows(app, sends),
                          device_rows(app, sends, APP_ATTRS))

    def test_grouped_with_filter(self):
        app = DEFINE + (
            "from S[v > 30.0]#window.length(8) "
            "select k, sum(v) as s, min(v) as lo, max(v) as hi, avg(v) as a "
            "group by k insert into OutputStream;")
        sends = series(250, seed=6, n_keys=5)
        assert_rows_close(host_rows(app, sends),
                          device_rows(app, sends, APP_ATTRS))

    def test_cross_batch_window_carry(self):
        app = DEFINE + (
            "from S#window.length(6) select k, sum(v) as s group by k "
            "insert into OutputStream;")
        sends = series(90, seed=7)
        eng = compile_query(app)
        state = eng.init_state()
        dev = []
        for lo in range(0, 90, 23):
            chunk = sends[lo:lo + 23]
            cols = {a: np.asarray([r[i] for r, _t in chunk], dtype=float)
                    for i, a in enumerate(APP_ATTRS)}
            ts = np.asarray([t for _r, t in chunk], dtype=np.int64)
            state, rows = eng.process(state, cols, ts)
            dev.extend(rows)
        assert_rows_close(host_rows(app, sends), dev)


class TestSlidingTimeWindow:
    def test_ungrouped(self):
        app = DEFINE + (
            "from S#window.time(1 sec) select sum(v) as s, count() as c "
            "insert into OutputStream;")
        sends = series(120, seed=8)
        assert_rows_close(host_rows(app, sends),
                          device_rows(app, sends, APP_ATTRS))

    def test_grouped(self):
        app = DEFINE + (
            "from S#window.time(2 sec) select k, sum(v) as s, avg(v) as a "
            "group by k insert into OutputStream;")
        sends = series(200, seed=9, n_keys=6)
        assert_rows_close(host_rows(app, sends),
                          device_rows(app, sends, APP_ATTRS))


class TestTumblingTimeBatch:
    def test_grouped_flushes(self):
        app = DEFINE + (
            "from S#window.timeBatch(1 sec) select k, sum(v) as s "
            "group by k insert into OutputStream;")
        sends = series(150, seed=10, n_keys=4)
        assert_rows_close(
            host_rows(app, sends),
            device_rows(app, sends, APP_ATTRS),
            ordered=False,  # flush rows: group order is incidental
        )

    def test_sparse_panes_idle_reanchor(self):
        app = DEFINE + (
            "from S#window.timeBatch(1 sec) select sum(v) as s "
            "insert into OutputStream;")
        # long silences force the idle/re-anchor path
        sends = [([0, 10.0], 1000), ([0, 20.0], 1400),
                 ([0, 30.0], 9000), ([0, 40.0], 9500),
                 ([0, 50.0], 30000)]
        assert_rows_close(host_rows(app, sends),
                          device_rows(app, sends, APP_ATTRS))

    def test_ungrouped_avg(self):
        app = DEFINE + (
            "from S[v > 25.0]#window.timeBatch(2 sec) "
            "select avg(v) as a, count() as c insert into OutputStream;")
        sends = series(180, seed=11)
        assert_rows_close(host_rows(app, sends),
                          device_rows(app, sends, APP_ATTRS))


class TestTumblingLengthBatch:
    def test_grouped(self):
        app = DEFINE + (
            "from S#window.lengthBatch(10) select k, sum(v) as s, count() as c "
            "group by k insert into OutputStream;")
        sends = series(95, seed=12, n_keys=3)
        assert_rows_close(
            host_rows(app, sends),
            device_rows(app, sends, APP_ATTRS),
            ordered=False,
        )

    def test_filtered_flush_boundaries(self):
        # boundaries are placed on PASSING events only
        app = DEFINE + (
            "from S[v > 50.0]#window.lengthBatch(7) select sum(v) as s "
            "insert into OutputStream;")
        sends = series(160, seed=13)
        assert_rows_close(host_rows(app, sends),
                          device_rows(app, sends, APP_ATTRS))


class TestEligibility:
    def test_string_filter_rejected(self):
        from siddhi_tpu.core.exceptions import SiddhiAppCreationError

        app = ("define stream S (sym string, v double); "
               "from S[sym == 'IBM'] select v insert into OutputStream;")
        with pytest.raises(SiddhiAppCreationError):
            compile_query(app)

    def test_unsupported_window_rejected(self):
        from siddhi_tpu.core.exceptions import SiddhiAppCreationError

        app = DEFINE + ("from S#window.sort(5, v) select v "
                        "insert into OutputStream;")
        with pytest.raises(SiddhiAppCreationError):
            compile_query(app)

    def test_having_supported(self):
        app = DEFINE + (
            "from S select k, sum(v) as s group by k having s > 100.0 "
            "insert into OutputStream;")
        sends = series(80, seed=14)
        assert_rows_close(host_rows(app, sends),
                          device_rows(app, sends, APP_ATTRS))


class TestAdvisorRegressions:
    def test_having_select_alias(self):
        """`sum(v) as s ... having s > X` resolves the alias on the
        device path (round-2 advisor high finding)."""
        app = (
            "define stream S (k int, v double); "
            "@info(name='q') from S select k as k, sum(v) as s "
            "group by k having s > 100.0 insert into OutputStream;"
        )
        sends = [([1, 60.0], 10), ([1, 50.0], 20), ([2, 10.0], 30)]
        host = host_rows(app, sends)
        dev = device_rows(app, sends, ["k", "v"])
        assert_rows_close(host, dev)

    def test_tumbling_group_key_register_with_filtered_duplicate(self):
        """A batch holding both a filtered and a passing row of the SAME
        first-seen group must record the true key (round-2 advisor
        medium: duplicate-index scatter could clobber grp_keys with the
        stale 0 via the filtered lane)."""
        app = (
            "define stream S (k int, v double); "
            "@info(name='q') from S[v > 0.0]#window.lengthBatch(2) "
            "select k + 0.5 as kk, sum(v) as s "
            "group by k insert into OutputStream;"
        )
        # filtered row of group 3 arrives FIRST in the same batch
        sends = [([3, -1.0], 10), ([3, 1.0], 20), ([3, 2.0], 30)]
        host = host_rows(app, sends)
        dev = device_rows(app, sends, ["k", "v"])
        assert_rows_close(host, dev)
        assert dev and dev[0]["kk"] == 3.5

    def test_rel_ts_re_anchor_past_int32(self):
        """Streams running past ~24.8 days of relative time re-anchor
        instead of silently wrapping int32 (round-2 advisor low)."""
        app = (
            "define stream S (k int, v double); "
            "@info(name='q') from S#window.time(10 sec) "
            "select sum(v) as s insert into OutputStream;"
        )
        eng = compile_query(app)
        state = eng.init_state()
        state, rows1 = eng.process(
            state, {"k": np.asarray([1]), "v": np.asarray([1.0])},
            np.asarray([1_000]))
        base0 = eng.base_ts
        far = 1_000 + 3_000_000_000  # ~34 days later, past int32 ms range
        state, rows2 = eng.process(
            state, {"k": np.asarray([1]), "v": np.asarray([2.0])},
            np.asarray([far]))
        assert eng.base_ts > base0  # re-anchored
        assert [r["s"] for r in rows1] == [1.0]
        assert [r["s"] for r in rows2] == [2.0]  # old event left the window


def test_direct_api_rejects_order_by():
    """compile_query has no host-side selector downstream, so order
    by/limit must RAISE there (silently dropping them would corrupt
    results); the SiddhiManager path applies them host-side instead
    (tests/test_device_wide_aggs.py TestOrderByLimitOnDevicePath)."""
    import pytest

    from siddhi_tpu.core.exceptions import SiddhiAppCreationError
    from siddhi_tpu.ops.device_query import compile_query

    with pytest.raises(SiddhiAppCreationError):
        compile_query(
            "define stream S (k int, v double); "
            "@info(name='q') from S select k, sum(v) as s group by k "
            "order by s desc limit 1 insert into O;", "q")
