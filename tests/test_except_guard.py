"""Tier-1 guard: no fault may vanish without a log line or counter.

The fault-injection work replaced every silent ``except Exception:
pass`` swallow on the processing path (emit-queue concat fallback,
transport start rollback, join lane-pruning probe) with handlers that
log, count, or route through the @OnError machinery.  This test
AST-scans ``siddhi_tpu/core/`` and ``siddhi_tpu/transport/`` (the
layers events and faults actually traverse) and fails when a handler
catching ``Exception`` (or a bare ``except:``) whose body is only
``pass``/``...`` reappears — the signature of a fault disappearing
without trace.

Narrow handlers (``except queue.Empty: pass``) are fine: swallowing a
SPECIFIC expected condition is control flow, not fault masking.  If a
new broad swallow is genuinely sanctioned, list it in ALLOWED with a
justification — the guard keeps the decision visible in review.
"""

import ast
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
SCANNED_DIRS = ("siddhi_tpu/core", "siddhi_tpu/transport")

# "<relpath>:<qualified function>" -> justification.  Empty today: every
# broad swallow on the processing path now logs, counts, or re-routes.
ALLOWED: dict = {}

BROAD = {"Exception", "BaseException"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:  # bare `except:`
        return True
    if isinstance(t, ast.Name):
        return t.id in BROAD
    if isinstance(t, ast.Tuple):
        return any(isinstance(e, ast.Name) and e.id in BROAD for e in t.elts)
    return False


def _is_silent(handler: ast.ExceptHandler) -> bool:
    body = handler.body
    return all(
        isinstance(s, ast.Pass)
        or (isinstance(s, ast.Expr) and isinstance(s.value, ast.Constant))
        for s in body)


def silent_broad_handlers(source):
    """Yield (lineno, qualified enclosing scope) of silent broad excepts."""
    stack = []
    hits = []

    class V(ast.NodeVisitor):
        def _scoped(self, node):
            stack.append(node.name)
            self.generic_visit(node)
            stack.pop()

        visit_FunctionDef = _scoped
        visit_AsyncFunctionDef = _scoped
        visit_ClassDef = _scoped

        def visit_ExceptHandler(self, node):
            if _is_broad(node) and _is_silent(node):
                hits.append((node.lineno, ".".join(stack) or "<module>"))
            self.generic_visit(node)

    V().visit(ast.parse(source))
    return hits


def _scanned_files():
    for d in SCANNED_DIRS:
        root = REPO / d
        assert root.is_dir(), f"guard is stale: {d} moved"
        yield from sorted(root.rglob("*.py"))


def test_no_silent_broad_excepts_in_core_and_transport():
    offenders = []
    for path in _scanned_files():
        rel = path.relative_to(REPO).as_posix()
        for lineno, qual in silent_broad_handlers(path.read_text()):
            key = f"{rel}:{qual}"
            if key not in ALLOWED:
                offenders.append(f"{rel}:{lineno} in {qual}()")
    assert not offenders, (
        "silent `except Exception: pass` on the processing path — faults "
        "must leave a log line, a counter, or an @OnError route (or be "
        "added to ALLOWED with a justification):\n  "
        + "\n  ".join(offenders))


def test_allowlist_not_stale():
    live = set()
    for path in _scanned_files():
        rel = path.relative_to(REPO).as_posix()
        for _lineno, qual in silent_broad_handlers(path.read_text()):
            live.add(f"{rel}:{qual}")
    gone = set(ALLOWED) - live
    assert not gone, (
        f"ALLOWED entries no longer match a silent handler; prune them: "
        f"{sorted(gone)}")


@pytest.mark.parametrize("snippet,expect", [
    ("try:\n    x()\nexcept Exception:\n    pass\n", 1),
    ("try:\n    x()\nexcept:\n    pass\n", 1),
    ("try:\n    x()\nexcept (ValueError, Exception):\n    pass\n", 1),
    ("try:\n    x()\nexcept Exception:\n    '''docstring only'''\n", 1),
    ("try:\n    x()\nexcept Exception as e:\n    log.debug('%s', e)\n", 0),
    ("try:\n    x()\nexcept queue.Empty:\n    pass\n", 0),
])
def test_detector_self_check(snippet, expect):
    assert len(silent_broad_handlers(snippet)) == expect
