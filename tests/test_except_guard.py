"""Tier-1 guard: no fault may vanish without a log line or counter.

Thin shim over the ``broad-except-swallow`` rule in
``siddhi_tpu.analysis`` (which absorbed this file's AST detector and
allowlist).  The test names are stable tier-1 anchors; the contract —
no silent ``except Exception: pass`` in ``siddhi_tpu/core/`` or
``siddhi_tpu/transport/`` — now lives in
``siddhi_tpu/analysis/rules/broad_except.py``.
"""

from pathlib import Path

import pytest

from siddhi_tpu.analysis import ModuleIndex, get_rule, index_package, run_rules

REPO = Path(__file__).resolve().parent.parent

RULE = "broad-except-swallow"


def _run():
    indexes = index_package(REPO / "siddhi_tpu", REPO)
    return run_rules(indexes, [get_rule(RULE)])


def test_no_silent_broad_excepts_in_core_and_transport():
    hits = [f for f in _run()["findings"] if f.rule == RULE]
    assert not hits, (
        "silent `except Exception: pass` on the processing path — faults "
        "must leave a log line, a counter, or an @OnError route (or be "
        "allowlisted in siddhi_tpu/analysis/allowlists.py with a "
        "justification):\n  " + "\n  ".join(f.render() for f in hits))


def test_allowlist_not_stale():
    """Allowlist entries expire: one that no longer matches a finding
    surfaces as a ``stale-allowlist`` finding — the list only shrinks."""
    stale = [f for f in _run()["findings"] if f.rule == "stale-allowlist"]
    assert not stale, "\n  ".join(f.render() for f in stale)


@pytest.mark.parametrize("snippet,expect", [
    ("try:\n    x()\nexcept Exception:\n    pass\n", 1),
    ("try:\n    x()\nexcept:\n    pass\n", 1),
    ("try:\n    x()\nexcept (ValueError, Exception):\n    pass\n", 1),
    ("try:\n    x()\nexcept Exception:\n    '''docstring only'''\n", 1),
    ("try:\n    x()\nexcept Exception as e:\n    log.debug('%s', e)\n", 0),
    ("try:\n    x()\nexcept queue.Empty:\n    pass\n", 0),
])
def test_detector_self_check(snippet, expect):
    rule = get_rule(RULE)
    rule.begin()
    # rel inside a scanned dir so the rule actually looks at the fixture
    idx = ModuleIndex(Path("fixture.py"), "siddhi_tpu/core/_fixture.py",
                      source=snippet)
    assert len(list(rule.check(idx))) == expect
