"""Source/Sink transport conformance tests.

Modeled on the reference transport corpus
(modules/siddhi-core/src/test/java/io/siddhi/core/transport/
InMemoryTransportTestCase / MultiClientDistributedSinkTestCase /
TestFailingInMemorySink): the in-memory broker is the transport double;
@source/@sink annotated streams exchange events through topics.
"""

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.event import Event
from siddhi_tpu.core.exceptions import ConnectionUnavailableError
from siddhi_tpu.transport import InMemoryBroker
from siddhi_tpu.transport.broker import FunctionSubscriber


@pytest.fixture
def manager():
    InMemoryBroker.clear()
    m = SiddhiManager()
    yield m
    m.shutdown()
    InMemoryBroker.clear()


def test_inmemory_source_to_query(manager):
    app = (
        "@source(type='inMemory', topic='stocks') "
        "define stream S (symbol string, price float); "
        "@info(name='q') from S[price > 50.0] select symbol insert into Out;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    got = []
    rt.add_callback("q", lambda ts, ins, rem: got.extend(e.data for e in (ins or [])))
    rt.start()
    InMemoryBroker.publish("stocks", ["IBM", 75.0])
    InMemoryBroker.publish("stocks", ["WSO2", 45.0])
    InMemoryBroker.publish("stocks", Event(data=["GOOG", 60.0]))
    assert got == [["IBM"], ["GOOG"]]


def test_inmemory_sink_publishes(manager):
    app = (
        "define stream S (symbol string, price float); "
        "@sink(type='inMemory', topic='out') "
        "define stream Out (symbol string); "
        "from S select symbol insert into Out;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    received = []
    InMemoryBroker.subscribe(FunctionSubscriber("out", received.append))
    rt.get_input_handler("S").send(["IBM", 10.0])
    assert len(received) == 1 and received[0].data == ["IBM"]


def test_json_mappers_roundtrip(manager):
    app = (
        "@source(type='inMemory', topic='in', @map(type='json')) "
        "define stream S (symbol string, volume long); "
        "@sink(type='inMemory', topic='out', @map(type='json')) "
        "define stream Out (symbol string, volume long); "
        "from S select symbol, volume insert into Out;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    received = []
    InMemoryBroker.subscribe(FunctionSubscriber("out", received.append))
    InMemoryBroker.publish("in", '{"symbol": "IBM", "volume": 100}')
    InMemoryBroker.publish("in", '[{"symbol": "A", "volume": 1}, {"symbol": "B", "volume": 2}]')
    import json

    assert [json.loads(r) for r in received] == [
        {"symbol": "IBM", "volume": 100},
        {"symbol": "A", "volume": 1},
        {"symbol": "B", "volume": 2},
    ]


def test_source_pause_resume_on_persist(manager):
    from siddhi_tpu.util.persistence import InMemoryPersistenceStore

    manager.set_persistence_store(InMemoryPersistenceStore())
    app = (
        "@app:name('p') "
        "@source(type='inMemory', topic='t') "
        "define stream S (v long); "
        "define table T (v long); from S insert into T;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    InMemoryBroker.publish("t", [1])
    rt.persist()
    InMemoryBroker.publish("t", [2])
    assert sorted(e.data[0] for e in rt.query("from T select v;")) == [1, 2]


def test_roundrobin_distributed_sink(manager):
    app = (
        "define stream S (v long); "
        "@sink(type='inMemory', @distribution(strategy='roundRobin', "
        "@destination(topic='d1'), @destination(topic='d2'))) "
        "define stream Out (v long); "
        "from S select v insert into Out;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    d1, d2 = [], []
    InMemoryBroker.subscribe(FunctionSubscriber("d1", d1.append))
    InMemoryBroker.subscribe(FunctionSubscriber("d2", d2.append))
    h = rt.get_input_handler("S")
    for i in range(4):
        h.send([i])
    assert [e.data[0] for e in d1] == [0, 2]
    assert [e.data[0] for e in d2] == [1, 3]


def test_partitioned_distributed_sink(manager):
    app = (
        "define stream S (sym string, v long); "
        "@sink(type='inMemory', @distribution(strategy='partitioned', "
        "partitionKey='sym', @destination(topic='p1'), @destination(topic='p2'))) "
        "define stream Out (sym string, v long); "
        "from S select sym, v insert into Out;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    p1, p2 = [], []
    InMemoryBroker.subscribe(FunctionSubscriber("p1", p1.append))
    InMemoryBroker.subscribe(FunctionSubscriber("p2", p2.append))
    h = rt.get_input_handler("S")
    for sym, v in [("A", 1), ("B", 2), ("A", 3), ("B", 4)]:
        h.send([sym, v])
    # every event delivered exactly once, each key pinned to one destination
    assert len(p1) + len(p2) == 4
    seen = {}
    for topic, events in (("p1", p1), ("p2", p2)):
        for e in events:
            seen.setdefault(e.data[0], set()).add(topic)
    assert all(len(topics) == 1 for topics in seen.values())


def test_broadcast_distributed_sink(manager):
    app = (
        "define stream S (v long); "
        "@sink(type='inMemory', @distribution(strategy='broadcast', "
        "@destination(topic='b1'), @destination(topic='b2'))) "
        "define stream Out (v long); "
        "from S select v insert into Out;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    b1, b2 = [], []
    InMemoryBroker.subscribe(FunctionSubscriber("b1", b1.append))
    InMemoryBroker.subscribe(FunctionSubscriber("b2", b2.append))
    rt.get_input_handler("S").send([7])
    assert len(b1) == 1 and len(b2) == 1


def test_failing_sink_drops_and_logs(manager):
    """Publish failure must not break the processing chain
    (reference: TestFailingInMemorySink + Sink.onError)."""
    from siddhi_tpu.transport.sink import Sink

    published, failed = [], []

    class FailingSink(Sink):
        def publish(self, payload):
            if len(failed) < 1:
                failed.append(payload)
                raise ConnectionUnavailableError("transport down")
            published.append(payload)

    manager.set_extension("failing", FailingSink, kind="sink")
    app = (
        "define stream S (v long); "
        "@sink(type='failing', topic='x', retry.scale='0.0001') "
        "define stream Out (v long); "
        "from S select v insert into Out;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    h = rt.get_input_handler("S")
    h.send([1])  # fails, dropped
    h.send([2])  # succeeds
    assert len(failed) == 1 and len(published) == 1
    assert published[0].data == [2]


def test_source_connect_retry(manager):
    """A source whose connect fails keeps retrying with backoff
    (reference: Source.connectWithRetry)."""
    import time

    from siddhi_tpu.transport.source import Source

    attempts = []

    class FlakySource(Source):
        def connect(self):
            attempts.append(1)
            if len(attempts) < 2:
                raise ConnectionUnavailableError("not yet")

    manager.set_extension("flaky", FlakySource, kind="source")
    app = (
        "@source(type='flaky', retry.scale='0.0001') "
        "define stream S (v long); "
        "from S select v insert into Out;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    deadline = time.time() + 2
    while len(attempts) < 2 and time.time() < deadline:
        time.sleep(0.01)
    assert len(attempts) >= 2
    assert rt.sources[0].connected


class TestHandlerManagers:
    def test_source_and_sink_handlers(self, manager):
        import time

        from siddhi_tpu.transport.broker import InMemoryBroker, Subscriber
        from siddhi_tpu.transport.handler import (
            SinkHandler,
            SinkHandlerManager,
            SourceHandler,
            SourceHandlerManager,
        )

        seen = {"in": [], "out": []}

        class CountingSourceHandler(SourceHandler):
            def on_events(self, events):
                seen["in"].extend(e.data for e in events)
                return events

        class TaggingSinkHandler(SinkHandler):
            def on_events(self, events):
                seen["out"].extend(e.data for e in events)
                return events

        class SrcMgr(SourceHandlerManager):
            def generate_source_handler(self):
                return CountingSourceHandler()

        class SnkMgr(SinkHandlerManager):
            def generate_sink_handler(self):
                return TaggingSinkHandler()

        manager.set_source_handler_manager(SrcMgr())
        manager.set_sink_handler_manager(SnkMgr())
        rt = manager.create_siddhi_app_runtime(
            "@source(type='inMemory', topic='h-in', @map(type='passThrough')) "
            "define stream S (v long); "
            "@sink(type='inMemory', topic='h-out', @map(type='passThrough')) "
            "define stream Out (v long); "
            "from S[v > 1] select v insert into Out;"
        )
        got = []

        class Sub(Subscriber):
            def on_message(self, m):
                got.append(m)

            def get_topic(self):
                return "h-out"

        sub = Sub()
        InMemoryBroker.subscribe(sub)
        rt.start()
        InMemoryBroker.publish("h-in", [5])
        InMemoryBroker.publish("h-in", [0])
        time.sleep(0.15)
        rt.shutdown()
        InMemoryBroker.unsubscribe(sub)
        assert seen["in"] == [[5], [0]]     # source handler saw everything
        assert seen["out"] == [[5]]         # sink handler saw filtered output
        assert [e.data for e in got] == [[5]]

    def test_record_table_handler_manager(self, manager):
        from siddhi_tpu.table.record import RecordTableHandler
        from siddhi_tpu.transport.handler import RecordTableHandlerManager

        adds = []

        class SpyHandler(RecordTableHandler):
            def on_add(self, records, call):
                adds.extend(records)
                return call(records)

        class Mgr(RecordTableHandlerManager):
            def generate_record_table_handler(self):
                return SpyHandler()

        manager.set_record_table_handler_manager(Mgr())
        rt = manager.create_siddhi_app_runtime(
            "define stream S (v long); "
            "@store(type='memory') define table T (v long); "
            "from S select v insert into T;"
        )
        rt.start()
        rt.get_input_handler("S").send([42])
        rt.shutdown()
        assert adds == [[42]]
