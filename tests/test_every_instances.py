"""Overlapping-`every` instance-axis semantics: dense vs host, bit-exact.

The round-3 verdict's missing item 3: the dense engine kept at most one
pending instance per (partition, node), silently collapsing overlapping
`every` arms.  The instance axis lifts that; this corpus — modeled on
the reference's EveryPatternTestCase / pattern suites
(modules/siddhi-core/src/test/java/io/siddhi/core/query/pattern/
EveryPatternTestCase.java), which depend on simultaneous partial
matches — pins host==dense equality on concrete event values AND
emission order through the public SiddhiManager API.
"""

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.dense_pattern import DensePatternRuntime


def run_app(app, sends, out="Alerts", mode=None, stream="S"):
    header = "@app:playback "
    if mode:
        header += f"@app:execution('{mode}') "
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime(header + app)
        got = []
        rt.add_callback(out, lambda evs: got.extend(e.data for e in evs))
        rt.start()
        for stream_id, row, ts in sends:
            rt.get_input_handler(stream_id).send(row, timestamp=ts)
        qr = next(iter(rt.query_runtimes.values()))
        runtime = getattr(qr, "pattern_processor", None)
        rt.shutdown()
        return got, runtime
    finally:
        m.shutdown()


def differential(app, sends, require_dense=True):
    host, _ = run_app(app, sends)
    dense, runtime = run_app(app, sends, mode="tpu")
    if require_dense:
        assert isinstance(runtime, DensePatternRuntime), (
            "query did not lower densely")
        assert runtime.step_invocations > 0
    assert dense == host, f"dense {dense} != host {host}"
    return host


DEFINE = "define stream S (k double, v double); "


class TestOverlappingEvery:
    def test_two_arms_complete_on_one_event(self):
        # reference EveryPatternTestCase shape: every a -> b where two
        # a's arm before any b; the b completes BOTH, oldest arm first
        app = DEFINE + (
            "@info(name='q') from every a=S[v > 100.0] -> b=S[v > a.v] "
            "within 10 min select a.v as av, b.v as bv insert into Alerts;")
        host = differential(app, [
            ("S", [0.0, 500.0], 1000),
            ("S", [0.0, 400.0], 1100),   # not b for 500; arms its own
            ("S", [0.0, 600.0], 1200),   # completes both arms
        ])
        assert host == [[500.0, 600.0], [400.0, 600.0]]

    def test_three_deep_overlap(self):
        app = DEFINE + (
            "@info(name='q') from every a=S[v > 0.0] -> b=S[v > a.v] "
            "-> c=S[v > b.v] within 10 min "
            "select a.v as av, b.v as bv, c.v as cv insert into Alerts;")
        differential(app, [
            ("S", [0.0, 10.0], 1000),
            ("S", [0.0, 20.0], 1100),
            ("S", [0.0, 30.0], 1200),
            ("S", [0.0, 40.0], 1300),
            ("S", [0.0, 5.0], 1400),
            ("S", [0.0, 50.0], 1500),
        ])

    def test_within_expires_only_old_arms(self):
        app = DEFINE + (
            "@info(name='q') from every a=S[v > 100.0] -> b=S[v > a.v] "
            "within 2 sec select a.v as av, b.v as bv insert into Alerts;")
        host = differential(app, [
            ("S", [0.0, 500.0], 1000),
            ("S", [0.0, 400.0], 2500),
            ("S", [0.0, 600.0], 3500),  # 500-arm expired; 400-arm alive
        ])
        assert host == [[400.0, 600.0]]

    def test_every_exact_count_pairs(self):
        # every a{2} -> b: non-overlapping consecutive pairs (the host
        # rearms only at satisfaction)
        app = DEFINE + (
            "@info(name='q') from every a=S[v > 0.0]<2> -> b=S[v < 0.0] "
            "within 10 min select a[0].v as a0, a[last].v as a1, b.v as bv "
            "insert into Alerts;")
        host = differential(app, [
            ("S", [0.0, 1.0], 1000),
            ("S", [0.0, 2.0], 1100),
            ("S", [0.0, 3.0], 1200),
            ("S", [0.0, 4.0], 1300),
            ("S", [0.0, -1.0], 1400),
        ])
        # arms (1,2) then (3,4); both pend at b and complete on -1
        assert host == [[1.0, 2.0, -1.0], [3.0, 4.0, -1.0]]

    def test_open_count_clones_per_success(self):
        # fail+ -> success (BASELINE config 3 shape): the dually-pending
        # count clones per success event — two successes emit twice
        app = ("define stream Login (user double, ok double); "
               "@info(name='q') from every f=Login[ok < 1.0]<1:> "
               "-> s=Login[ok > 0.0] within 10 min "
               "select f[0].ok as fo, s.ok as so insert into Alerts;")
        differential(app, [
            ("Login", [1.0, 0.0], 1000),
            ("Login", [1.0, 0.5], 1100),
            ("Login", [1.0, 2.0], 1200),
            ("Login", [1.0, 3.0], 1300),
            ("Login", [1.0, 0.0], 1400),
            ("Login", [1.0, 4.0], 1500),
        ])

    def test_open_count_bounded_moves_at_max(self):
        # a<2:3> -> b: advancing clones at successor events plus the
        # instance's own move when the count fills
        app = DEFINE + (
            "@info(name='q') from a=S[v > 0.0]<2:3> -> b=S[v < 0.0] "
            "within 10 min select a[0].v as a0, b.v as bv "
            "insert into Alerts;")
        differential(app, [
            ("S", [0.0, 1.0], 1000),
            ("S", [0.0, 2.0], 1100),
            ("S", [0.0, 3.0], 1200),
            ("S", [0.0, -1.0], 1300),
        ])

    def test_open_count_last_ref_same_stream_clone(self):
        """[last] through a via-clone sees the captures BEFORE the
        cloning event (reference: dual-pending successors are tried
        before capture, _process_event step 1) — pinned host==dense."""
        app = DEFINE + (
            "@info(name='q') from every a=S[v > 0.0]<1:> -> b=S[v > 10.0] "
            "within 10 min select a[0].v as a0, a[last].v as al, b.v as bv "
            "insert into Alerts;")
        # 15.0 passes BOTH filters: it clones (a-last = 2.0) AND extends
        # the count; 20.0 then clones with a-last = 15.0
        differential(app, [
            ("S", [0.0, 1.0], 1000),
            ("S", [0.0, 2.0], 1100),
            ("S", [0.0, 15.0], 1200),
            ("S", [0.0, 20.0], 1300),
        ])

    def test_logical_repeat_side_ignored(self):
        """A second event on an already-matched AND side neither
        refreshes the capture nor the within anchor (the reference skips
        matched sides) — pinned host==dense."""
        app = (
            "define stream A (x double); define stream B (y double); "
            "@info(name='q') from every (a=A[x > 0.0] and b=B[y > 0.0]) "
            "within 1 sec select a.x as ax, b.y as by insert into Alerts;")
        # second A at 800 must NOT refresh the anchor or the capture;
        # B at 1500 finds the arm expired (anchor stays at t=0)
        host = differential(app, [
            ("A", [1.0], 100),
            ("A", [2.0], 800),
            ("B", [3.0], 1500),
        ])
        assert host == []
        # within the window, the FIRST A's capture is kept
        host2 = differential(app, [
            ("A", [1.0], 100),
            ("A", [2.0], 800),
            ("B", [3.0], 900),
        ])
        assert host2 == [[1.0, 3.0]]

    def test_logical_and_every_overlap(self):
        app = (
            "define stream A (x double); define stream B (y double); "
            "define stream C (z double); "
            "@info(name='q') from every (a=A[x > 0.0] and b=B[y > 0.0]) "
            "-> c=C[z > 0.0] within 10 min "
            "select a.x as ax, b.y as by, c.z as cz insert into Alerts;")
        differential(app, [
            ("A", [1.0], 1000),
            ("B", [2.0], 1100),   # completes first and-pair; rearms
            ("A", [3.0], 1200),
            ("B", [4.0], 1300),   # completes second and-pair
            ("C", [5.0], 1400),   # completes both pending chains
        ])

    def test_sequence_keeps_single_instance(self):
        app = DEFINE + (
            "@info(name='q') from every a=S[v > 100.0], b=S[v > a.v] "
            "select a.v as av, b.v as bv insert into Alerts;")
        differential(app, [
            ("S", [0.0, 500.0], 1000),
            ("S", [0.0, 600.0], 1100),
            ("S", [0.0, 700.0], 1200),
        ])


class TestPatternStateIntrospection:
    def test_runtime_pattern_state_dense_and_host(self):
        app = (
            "define stream S (v double); "
            "@info(name='qd') from every a=S[v > 100.0] -> b=S[v > a.v] "
            "within 10 min select a.v as av, b.v as bv insert into Alerts; "
            "define stream T (card string, v double); "
            "@info(name='qh') from every a=T[v > 100.0] -> "
            "b=T[card == a.card] "
            "select a.v as av insert into Alerts2;")
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(
                "@app:playback @app:execution('tpu') " + app)
            rt.start()
            h = rt.get_input_handler("S")
            h.send([500.0], timestamp=1000)
            h.send([400.0], timestamp=1100)
            ht = rt.get_input_handler("T")
            ht.send(["c1", 500.0], timestamp=1200)
            st = rt.pattern_state()
            assert st["qd"]["engine"] == "dense"
            assert st["qd"]["active_instances"] == 2
            assert st["qd"]["dropped_instances"] == 0
            assert st["qd"]["instance_lanes"] == 4
            assert st["qh"]["engine"] == "host"  # string capture -> host
            assert st["qh"]["active_instances"] >= 1
            rt.shutdown()
        finally:
            m.shutdown()

    def test_rest_pattern_state_endpoint(self):
        import json
        from urllib.request import urlopen

        from siddhi_tpu.service import SiddhiService

        svc = SiddhiService()
        svc.start()
        try:
            code, payload = svc.deploy(
                "@app:name('psapp') @app:playback @app:execution('tpu') "
                "define stream S (v double); "
                "@info(name='q') from every a=S[v > 100.0] -> b=S[v > a.v] "
                "within 10 min select a.v as av, b.v as bv "
                "insert into Alerts;")
            assert code == 200, payload
            svc.get_runtime("psapp").get_input_handler("S").send(
                [500.0], timestamp=1000)
            with urlopen(
                    f"http://127.0.0.1:{svc.port}/siddhi-pattern-state/psapp"
            ) as r:
                body = json.loads(r.read())
            assert body["status"] == "OK"
            assert body["queries"]["q"]["engine"] == "dense"
            assert body["queries"]["q"]["active_instances"] == 1
        finally:
            svc.stop()
            svc.manager.shutdown()


class TestInstanceCapacity:
    APP = DEFINE + (
        "@info(name='q') from every a=S[v > 100.0] -> b=S[v > a.v] "
        "within 10 min select a.v as av, b.v as bv insert into Alerts;")

    def overflow_run(self, instances, sends):
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(
                "@app:playback "
                f"@app:execution('tpu', instances='{instances}') " + self.APP)
            got = []
            rt.add_callback("Alerts", lambda evs: got.extend(e.data for e in evs))
            rt.start()
            h = rt.get_input_handler("S")
            for row, ts in sends:
                h.send(row, timestamp=ts)
            qr = next(iter(rt.query_runtimes.values()))
            runtime = qr.pattern_processor
            overflow = int(np.asarray(runtime.state["overflow"]).sum())
            rt.shutdown()
            return got, overflow
        finally:
            m.shutdown()

    def test_overflow_drops_newest_and_counts(self):
        sends = [([0.0, 500.0], 1000), ([0.0, 400.0], 1100),
                 ([0.0, 300.0], 1200), ([0.0, 600.0], 1300)]
        got, overflow = self.overflow_run(2, sends)
        # two lanes: 500- and 400-arms kept; the 300-arm dropped
        assert got == [[500.0, 600.0], [400.0, 600.0]]
        assert overflow == 1

    def test_overflow_warns_at_shutdown(self, caplog):
        """Short-lived apps (fewer batches than the poll interval) still
        surface the dropped-instance warning via the shutdown check."""
        import logging

        sends = [([0.0, 500.0], 1000), ([0.0, 400.0], 1100),
                 ([0.0, 300.0], 1200), ([0.0, 600.0], 1300)]
        with caplog.at_level(logging.WARNING, logger="siddhi_tpu"):
            self.overflow_run(2, sends)
        assert any("dropped" in r.message for r in caplog.records)

    def test_enough_lanes_no_overflow(self):
        sends = [([0.0, 500.0], 1000), ([0.0, 400.0], 1100),
                 ([0.0, 300.0], 1200), ([0.0, 600.0], 1300)]
        got, overflow = self.overflow_run(4, sends)
        assert got == [[500.0, 600.0], [400.0, 600.0], [300.0, 600.0]]
        assert overflow == 0
