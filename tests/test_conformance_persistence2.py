"""Persistence conformance, part 2: the kitchen-sink snapshot contract.

Every stateful element type snapshots and restores together in one app
— windows (sliding + batch mid-period), group-by aggregator states,
pattern pending instances (host and dense), partitions, tables,
incremental aggregations, and rate-limiter held state — modeled on the
reference managment suite's PersistenceTestCase /
IncrementalPersistenceTestCase cold-restart scenarios
(modules/siddhi-core/src/test/java/io/siddhi/core/managment/).
"""

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.util.persistence import InMemoryPersistenceStore

SINK_APP = (
    "@app:name('kitchen') @app:playback "
    "define stream S (k string, v long); "
    "define stream P (k string, v long); "
    "define table T (k string, v long); "
    "@info(name='qwin') from S#window.length(3) select k, sum(v) as total "
    "insert into WinOut; "
    "@info(name='qgrp') from S select k, sum(v) as total group by k "
    "insert into GrpOut; "
    "@info(name='qtab') from S insert into T; "
    "@info(name='qpat') from every a=P[v > 10] -> b=P[v > a.v] "
    "select a.v as av, b.v as bv insert into PatOut; "
)


def fresh_manager():
    m = SiddhiManager()
    m.set_persistence_store(InMemoryPersistenceStore())
    return m


def attach(rt, names):
    outs = {n: [] for n in names}
    for n in names:
        rt.add_callback(
            n, (lambda lst: lambda evs: lst.extend(
                list(e.data) for e in evs))(outs[n]))
    return outs


class TestKitchenSinkPersistence:
    def test_all_element_types_roll_back_together(self):
        m = fresh_manager()
        try:
            rt = m.create_siddhi_app_runtime(SINK_APP)
            outs = attach(rt, ["WinOut", "GrpOut", "PatOut"])
            rt.start()
            s = rt.get_input_handler("S")
            p = rt.get_input_handler("P")
            s.send(["a", 1], timestamp=1000)
            s.send(["a", 2], timestamp=1100)
            p.send(["x", 20], timestamp=1200)   # pattern arm pending
            rev = rt.persist()
            # post-snapshot mutations
            s.send(["a", 4], timestamp=1300)
            p.send(["x", 30], timestamp=1400)   # completes: (20, 30)
            assert outs["WinOut"][-1] == ["a", 7]
            assert outs["GrpOut"][-1] == ["a", 7]
            assert outs["PatOut"] == [[20, 30]]
            # roll back: window sum 3, group sum 3, arm(20) pending again
            rt.restore_revision(rev)
            s.send(["a", 10], timestamp=2000)
            assert outs["WinOut"][-1] == ["a", 13]
            assert outs["GrpOut"][-1] == ["a", 13]
            p.send(["x", 25], timestamp=2100)   # restored arm completes
            assert outs["PatOut"][-1] == [20, 25]
            # table rolled back too: only the pre-snapshot rows + new one
            rows = sorted(tuple(e.data) for e in rt.query(
                "from T select k, v;"))
            assert rows == [("a", 1), ("a", 2), ("a", 10)]
            rt.shutdown()
        finally:
            m.shutdown()

    def test_cold_restart_restore_last(self):
        # persist, SHUT DOWN the runtime, rebuild from the app string in
        # a fresh runtime sharing the store, restore last revision
        store = InMemoryPersistenceStore()
        m1 = SiddhiManager()
        m1.set_persistence_store(store)
        try:
            rt1 = m1.create_siddhi_app_runtime(SINK_APP)
            rt1.start()
            s = rt1.get_input_handler("S")
            s.send(["a", 5], timestamp=1000)
            s.send(["b", 7], timestamp=1100)
            rt1.persist()
            rt1.shutdown()
        finally:
            m1.shutdown()

        m2 = SiddhiManager()
        m2.set_persistence_store(store)
        try:
            rt2 = m2.create_siddhi_app_runtime(SINK_APP)
            outs = attach(rt2, ["GrpOut"])
            rt2.start()
            rt2.restore_last_revision()
            rt2.get_input_handler("S").send(["a", 1], timestamp=2000)
            assert outs["GrpOut"][-1] == ["a", 6]  # 5 + 1 survives restart
            # restored rows (a,5)/(b,7) plus the post-restore (a,1)
            rows = sorted(tuple(e.data) for e in rt2.query(
                "from T select k, v;"))
            assert rows == [("a", 1), ("a", 5), ("b", 7)]
            rt2.shutdown()
        finally:
            m2.shutdown()

    def test_dense_pattern_state_cold_restart(self):
        app = (
            "@app:name('densePersist') @app:playback "
            "@app:execution('tpu', partitions='16') "
            "define stream Txn (card string, amount double); "
            "partition with (card of Txn) begin "
            "@info(name='q') from every a=Txn[amount > 100.0] -> "
            "b=Txn[amount > a.amount] "
            "select a.amount as base, b.amount as bv insert into Alerts; "
            "end;"
        )
        store = InMemoryPersistenceStore()
        m1 = SiddhiManager()
        m1.set_persistence_store(store)
        try:
            rt1 = m1.create_siddhi_app_runtime(app)
            rt1.start()
            h = rt1.get_input_handler("Txn")
            h.send(["c1", 150.0], timestamp=1000)   # arm pending
            h.send(["c2", 200.0], timestamp=1100)   # arm pending
            rt1.persist()
            rt1.shutdown()
        finally:
            m1.shutdown()

        m2 = SiddhiManager()
        m2.set_persistence_store(store)
        try:
            rt2 = m2.create_siddhi_app_runtime(app)
            got = []
            rt2.add_callback(
                "Alerts", lambda evs: got.extend(list(e.data) for e in evs))
            rt2.start()
            rt2.restore_last_revision()
            h = rt2.get_input_handler("Txn")
            h.send(["c1", 160.0], timestamp=2000)   # restored arm fires
            h.send(["c2", 210.0], timestamp=2100)
            assert sorted(map(tuple, got)) == [
                (150.0, 160.0), (200.0, 210.0)]
            rt2.shutdown()
        finally:
            m2.shutdown()

    def test_incremental_snapshots_accumulate(self):
        # incremental persistence: later revisions only carry deltas but
        # restore still reproduces full state
        m = fresh_manager()
        try:
            rt = m.create_siddhi_app_runtime(SINK_APP)
            outs = attach(rt, ["GrpOut"])
            rt.start()
            s = rt.get_input_handler("S")
            s.send(["a", 1], timestamp=1000)
            rt.persist()
            s.send(["a", 2], timestamp=1100)
            rev2 = rt.persist()
            s.send(["a", 4], timestamp=1200)
            rt.restore_revision(rev2)
            s.send(["a", 10], timestamp=2000)
            assert outs["GrpOut"][-1] == ["a", 13]  # 1+2+10
            rt.shutdown()
        finally:
            m.shutdown()

    def test_ratelimiter_held_state_persists(self):
        app = (
            "@app:name('rl') @app:playback "
            "define stream S (k string, v long); "
            "@info(name='q') from S select k output every 3 events "
            "insert into Out; "
        )
        m = fresh_manager()
        try:
            rt = m.create_siddhi_app_runtime(app)
            outs = attach(rt, ["Out"])
            rt.start()
            h = rt.get_input_handler("S")
            h.send(["a", 1], timestamp=1000)
            h.send(["b", 2], timestamp=1100)
            rev = rt.persist()          # two events held, none emitted
            h.send(["c", 3], timestamp=1200)
            assert [g[0] for g in outs["Out"]] == ["a", "b", "c"]
            rt.restore_revision(rev)    # back to two held
            h.send(["d", 4], timestamp=2000)
            assert [g[0] for g in outs["Out"]][-3:] == ["a", "b", "d"]
            rt.shutdown()
        finally:
            m.shutdown()
