"""@async junction conformance: the Disruptor-ring-buffer analog
(reference StreamJunction.java:276-313 + StreamHandler.java:57) — a
queue/worker batcher decoupling producers from the processing chain,
coalescing events into device micro-batches.
"""

import time

import pytest

from siddhi_tpu import SiddhiManager


def wait_for(pred, timeout=5.0):
    end = time.time() + timeout
    while time.time() < end:
        if pred():
            return True
        time.sleep(0.01)
    return pred()


class TestAsyncJunction:
    def test_async_stream_processes_all_events_in_order(self):
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(
                "@async(buffer.size='64', batch.size.max='16') "
                "define stream S (v long); "
                "@info(name='q') from S[v % 2 == 0] select v "
                "insert into O;")
            got = []
            rt.add_callback("O", lambda evs: got.extend(e.data[0] for e in evs))
            rt.start()
            h = rt.get_input_handler("S")
            for i in range(200):
                h.send([i])
            assert wait_for(lambda: len(got) == 100)
            assert got == list(range(0, 200, 2))  # order preserved
            rt.shutdown()
        finally:
            m.shutdown()

    def test_async_coalesces_into_micro_batches(self):
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(
                "@async(buffer.size='256', batch.size.max='64') "
                "define stream S (v long); "
                "@info(name='q') from S select v insert into O;")
            chunks = []
            rt.add_callback("O", lambda evs: chunks.append(len(evs)))
            rt.start()
            h = rt.get_input_handler("S")
            for i in range(256):
                h.send([i])
            assert wait_for(lambda: sum(chunks) == 256)
            # the worker coalesced at least SOME events (fewer chunks
            # than events proves batching; exact sizes are timing-bound)
            assert len(chunks) < 256
            assert max(chunks) <= 64
            rt.shutdown()
        finally:
            m.shutdown()

    def test_async_stateful_query_consistent(self):
        # per-group sums must be exact despite the worker thread
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(
                "@async(buffer.size='128') "
                "define stream S (k string, v long); "
                "@info(name='q') from S select k, sum(v) as total "
                "group by k insert into O;")
            got = []
            rt.add_callback("O", lambda evs: got.extend(list(e.data) for e in evs))
            rt.start()
            h = rt.get_input_handler("S")
            for i in range(60):
                h.send(["a" if i % 2 else "b", 1])
            assert wait_for(lambda: len(got) == 60)
            finals = {}
            for k, total in got:
                finals[k] = total
            assert finals == {"a": 30, "b": 30}
            rt.shutdown()
        finally:
            m.shutdown()

    def test_shutdown_drains_pending(self):
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(
                "@async(buffer.size='512') define stream S (v long); "
                "@info(name='q') from S select v insert into O;")
            got = []
            rt.add_callback("O", lambda evs: got.extend(e.data[0] for e in evs))
            rt.start()
            h = rt.get_input_handler("S")
            for i in range(300):
                h.send([i])
            rt.shutdown()  # must not lose queued events
            assert len(got) == 300
        finally:
            m.shutdown()


def test_stop_with_full_queue_does_not_deadlock():
    """Shutdown while the async ring is FULL must not block: the worker
    exits via the running flag after its current dispatch, so stop()
    must never wait for queue space (regression: a producer-saturated
    @async junction deadlocked shutdown)."""
    import threading
    import time as _time

    import numpy as np

    from siddhi_tpu import SiddhiManager
    from siddhi_tpu.core.event import EventBatch

    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime(
            "@async(buffer.size='4', batch.size.max='8') "
            "define stream S (v double); "
            "from S select v insert into Out;")
        rt.add_callback("Out", lambda evs: _time.sleep(0.01))
        rt.start()
        h = rt.get_input_handler("S")
        b = EventBatch("S", ["v"], {"v": np.ones(64)},
                       np.zeros(64, dtype=np.int64))
        # saturate the 4-slot ring faster than the 10ms/dispatch consumer
        for _ in range(32):
            h.send_batch(b)
        done = threading.Event()

        def shut():
            rt.shutdown()
            done.set()

        t = threading.Thread(target=shut, daemon=True)
        t.start()
        assert done.wait(timeout=10), "shutdown deadlocked on a full ring"
    finally:
        m.shutdown()
