"""@app:execution('tpu') device lowering of GENERAL single-stream
queries through the product API.

The round-3 verdict's top gap: ops/device_query.py existed but the
planner never called it.  These tests prove the wiring — every scenario
runs the same SiddhiQL app through SiddhiManager twice (host mode vs
@app:execution('tpu')), asserts the emitted rows agree, and asserts the
jitted device step actually ran (step_invocations > 0).  Reference
behavior being pinned: query/input/ProcessStreamReceiver.java:99-179 +
query/selector/QuerySelector.java:76-99 driven through SiddhiManager
(the black-box style of the reference test corpus).
"""

import contextlib

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.device_single import DeviceQueryRuntime


def hot_loop_transfer_guard(enabled):
    """``jax.transfer_guard('disallow')`` around the batch loop: every
    device↔host crossing must be explicit (staged_put / device_get on
    the drain).  An implicit transfer — ``int(device_scalar)``,
    ``np.asarray(device_array)`` — raises instead of silently stalling.
    The static twin is the ``host-sync-hazard`` analysis rule; this pins
    the same contract dynamically.  On the CPU backend the guard is a
    no-op (jax treats host<->cpu-device crossings as free), so it only
    bites on real accelerator runs — wiring it here keeps tier-1 green
    while making TPU CI enforce the contract."""
    if not enabled:
        return contextlib.nullcontext()
    import jax

    return jax.transfer_guard("disallow")


def run_app(app, sends, out="OutputStream", mode=None, batches=None,
            want_runtime=False, transfer_guard=False):
    """Run via the public API in playback mode -> list of row dicts.

    ``batches``: optional list of (start, end) slices — events are sent
    in those groups via send_batch to exercise batched junction input.
    """
    header = "@app:playback "
    if mode:
        header += f"@app:execution('{mode}') "
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime(header + app)
        got = []
        rt.add_callback(out, lambda evs: got.extend(evs))
        rt.start()
        h = rt.get_input_handler("S")
        with hot_loop_transfer_guard(transfer_guard):
            if batches is None:
                for row, ts in sends:
                    h.send(row, timestamp=ts)
            else:
                from siddhi_tpu.core.event import Event

                for lo, hi in batches:
                    chunk = sends[lo:hi]
                    h.send([Event(t, list(r)) for r, t in chunk])
        qr = next(iter(rt.query_runtimes.values()))
        runtime = getattr(qr, "device_runtime", None)
        rt.shutdown()
        names = rt.junctions[out].definition.attribute_names
        rows = [dict(zip(names, e.data)) for e in got]
        if want_runtime:
            return rows, runtime
        return rows
    finally:
        m.shutdown()


def assert_rows_close(host, dev, ordered=True):
    assert len(host) == len(dev), f"{len(host)} host vs {len(dev)} device rows"

    def norm(row):
        return tuple(
            round(float(v), 3) if isinstance(v, (int, float, np.number))
            and not isinstance(v, bool) else v
            for v in row.values()
        )

    h = [norm(r) for r in host]
    d = [norm(r) for r in dev]
    if not ordered:
        h, d = sorted(h), sorted(d)
    for i, (a, b) in enumerate(zip(h, d)):
        for x, y in zip(a, b):
            if isinstance(x, float):
                assert x == pytest.approx(y, rel=1e-4, abs=1e-3), (
                    f"row {i}: host {a} != device {b}")
            else:
                assert x == y, f"row {i}: host {a} != device {b}"


def differential(app, sends, ordered=True, out="OutputStream", batches=None,
                 transfer_guard=False):
    """Host vs tpu through the product API; asserts the device path ran."""
    host = run_app(app, sends, out=out, batches=batches)
    dev, runtime = run_app(app, sends, out=out, mode="tpu", batches=batches,
                           want_runtime=True, transfer_guard=transfer_guard)
    assert isinstance(runtime, DeviceQueryRuntime), (
        "query did not lower to the device path")
    assert runtime.step_invocations > 0, "jitted device step never ran"
    assert_rows_close(host, dev, ordered=ordered)
    return dev


def series(n, seed, n_keys=4, t0=1000, dt_max=400):
    rng = np.random.default_rng(seed)
    ts = t0 + np.cumsum(rng.integers(1, dt_max, size=n))
    keys = rng.integers(0, n_keys, size=n)
    vals = rng.integers(1, 100, size=n).astype(float)
    return [([int(k), float(v)], int(t)) for k, v, t in zip(keys, vals, ts)]


DEFINE = "define stream S (k long, v double); "


class TestFilterLowering:
    APP = DEFINE + ("from S[v > 50.0] select k, v, v * 2.0 as dbl "
                    "insert into OutputStream;")

    def test_filter_projection(self):
        # transfer_guard: the device-mode batch loop may only cross the
        # device boundary explicitly (see hot_loop_transfer_guard)
        dev = differential(self.APP, series(200, seed=1),
                           transfer_guard=True)
        # LONG passthrough stays exact at native width
        assert all(isinstance(r["k"], (int, np.integer)) for r in dev)

    def test_long_passthrough_exact_above_2p24(self):
        # card-number-sized LONG select items survive the device path
        # bit-exactly (they never touch a float32 lane)
        big = 16_777_217_123  # > 2^24 and > 2^32
        app = self.APP
        sends = [([big, 60.0], 1000), ([big + 1, 70.0], 2000)]
        dev = differential(app, sends)
        assert [int(r["k"]) for r in dev] == [big, big + 1]


class TestRunningLowering:
    def test_ungrouped_running(self):
        app = DEFINE + (
            "from S[v > 20.0] select sum(v) as s, count() as c, avg(v) as a "
            "insert into OutputStream;")
        differential(app, series(150, seed=2))

    def test_grouped_min_max(self):
        app = DEFINE + (
            "from S select k, min(v) as lo, max(v) as hi, sum(v) as s "
            "group by k insert into OutputStream;")
        differential(app, series(200, seed=3, n_keys=7))

    def test_batched_input(self):
        app = DEFINE + (
            "from S select k, sum(v) as s group by k "
            "insert into OutputStream;")
        sends = series(120, seed=4)
        differential(app, sends,
                     batches=[(i, i + 37) for i in range(0, 120, 37)])


class TestWindowLowering:
    def test_sliding_length(self):
        app = DEFINE + (
            "from S[v > 30.0]#window.length(8) "
            "select k, sum(v) as s, min(v) as lo, max(v) as hi, avg(v) as a "
            "group by k insert into OutputStream;")
        differential(app, series(250, seed=6, n_keys=5))

    def test_sliding_time(self):
        app = DEFINE + (
            "from S#window.time(2 sec) select k, sum(v) as s, avg(v) as a "
            "group by k insert into OutputStream;")
        differential(app, series(200, seed=9, n_keys=6))

    def test_tumbling_time_batch(self):
        app = DEFINE + (
            "from S#window.timeBatch(1 sec) select k, sum(v) as s "
            "group by k insert into OutputStream;")
        differential(app, series(150, seed=10, n_keys=4), ordered=False)

    def test_tumbling_length_batch(self):
        app = DEFINE + (
            "from S#window.lengthBatch(10) select k, sum(v) as s, count() as c "
            "group by k insert into OutputStream;")
        differential(app, series(95, seed=12, n_keys=3), ordered=False)

    def test_having(self):
        app = DEFINE + (
            "from S select k, sum(v) as s group by k having s > 100.0 "
            "insert into OutputStream;")
        differential(app, series(80, seed=14))


class TestChaining:
    def test_insert_into_feeds_downstream_query(self):
        # device-lowered query feeding a second (host) query
        app = DEFINE + (
            "from S[v > 10.0] select k, v insert into Mid; "
            "from Mid select k, v * 3.0 as t insert into OutputStream;")
        host = run_app(app, series(60, seed=15))
        dev = run_app(app, series(60, seed=15), mode="tpu")
        assert_rows_close(host, dev)

    def test_string_group_key(self):
        # STRING group keys intern host-side; the query still lowers
        app = ("define stream S (sym string, v double); "
               "from S select sym, sum(v) as s group by sym "
               "insert into OutputStream;")
        sends = [(["IBM", 10.0], 1000), (["MSFT", 20.0], 1100),
                 (["IBM", 5.0], 1200), (["MSFT", 1.0], 1300)]
        host = run_app(app, sends)
        dev, runtime = run_app(app, sends, mode="tpu", want_runtime=True)
        assert isinstance(runtime, DeviceQueryRuntime)
        assert runtime.step_invocations > 0
        assert_rows_close(host, dev)
        assert [r["sym"] for r in dev] == ["IBM", "MSFT", "IBM", "MSFT"]


class TestFallbacks:
    def fallback(self, app, sends=None):
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(
                "@app:playback @app:execution('tpu') " + app)
            qr = next(iter(rt.query_runtimes.values()))
            assert getattr(qr, "device_runtime", None) is None, (
                "expected host fallback")
            return rt, m
        finally:
            m.shutdown()

    def test_string_filter_falls_back(self):
        self.fallback("define stream S (sym string, v double); "
                      "from S[sym == 'IBM'] select v insert into OutputStream;")

    def test_unsupported_window_falls_back(self):
        self.fallback(DEFINE + "from S#window.sort(5, v) select v "
                               "insert into OutputStream;")

    def test_long_arithmetic_falls_back(self):
        # round 5: plain LONG comparisons ride hi/lo pair lanes (see
        # tests/test_device_wide_aggs.py); LONG ARITHMETIC still has no
        # 64-bit device lane -> host engine
        self.fallback(DEFINE + "from S[k + 1 == 123456789012] select v "
                               "insert into OutputStream;")

    def test_expired_output_falls_back(self):
        self.fallback(DEFINE + "from S#window.length(3) select k, v "
                               "insert expired events into OutputStream;")

    def test_expired_events_output_falls_back(self):
        # round 5: order by/limit and per-group/snapshot rates now ride
        # the device path; non-CURRENT output (window expiry consumers)
        # is the representative remaining host-only surface
        self.fallback(DEFINE + "from S#window.length(2) select k, v "
                               "insert expired events into OutputStream;")

    def test_fallback_still_correct(self):
        app = ("define stream S (sym string, v double); "
               "from S[sym == 'IBM'] select sym, v insert into OutputStream;")
        sends = [(["IBM", 1.0], 1000), (["MSFT", 2.0], 1100),
                 (["IBM", 3.0], 1200)]
        host = run_app(app, sends)
        dev = run_app(app, sends, mode="tpu")
        assert_rows_close(host, dev)
        assert [r["v"] for r in dev] == [1.0, 3.0]


class TestReviewRegressions:
    def test_long_constant_falls_back_not_wraps(self):
        """An out-of-int32 literal must NOT lower onto int32 lanes
        (it would wrap modulo 2^32 and match the wrong rows)."""
        app = ("define stream S (i int, v double); "
               "from S[i == 2200000000] select v insert into OutputStream;")
        sends = [([-2094967296, 1.0], 1000)]  # == 2200000000 mod 2^32
        host = run_app(app, sends)
        dev, runtime = run_app(app, sends, mode="tpu", want_runtime=True)
        assert runtime is None  # fell back
        assert host == dev == []

    def test_int_expression_exact_above_2p24(self):
        """INT computed select items stay int32 end-to-end — no float32
        rounding through the output matrix."""
        app = ("define stream S (i int, v double); "
               "from S select i + 1 as x insert into OutputStream;")
        sends = [([100_000_001, 0.0], 1000)]
        dev, runtime = run_app(app, sends, mode="tpu", want_runtime=True)
        assert isinstance(runtime, DeviceQueryRuntime)
        assert dev == [{"x": 100_000_002}]

    def test_mixed_dtype_partition_keys_fall_back_to_dict_intern(self):
        """Int keys then string keys on one dense runtime: the sorted
        index cannot order both, so the runtime must degrade to the
        exact dict intern instead of resetting int-key rows."""
        from siddhi_tpu.compiler import SiddhiCompiler
        from siddhi_tpu.core.dense_pattern import (
            DensePatternRuntime, build_dense_engine)

        app = SiddhiCompiler.parse(
            "define stream S (k long, v double); "
            "from every e1=S[v > 5.0] -> e2=S[v > e1.v] within 10 sec "
            "select e1.v as a, e2.v as b insert into Out;")
        q = app.queries[0]
        defs = app.stream_definitions
        eng = build_dense_engine(
            q, q.input_stream, lambda s: defs[s.stream_id], 64)
        rt = DensePatternRuntime(eng, "#m", emit=lambda b: None)
        r_int = rt.intern_keys(np.asarray([7, 8, 7]))
        assert list(r_int) == [0, 1, 0]
        r_str = rt.intern_keys(np.asarray(["seven", "eight"]))
        assert not rt._vector_intern
        assert list(r_str) == [2, 3]
        # int keys keep their original rows after the degradation
        assert list(rt.intern_keys(np.asarray([8, 7]))) == [1, 0]


class TestTimerPaneFlush:
    def test_timebatch_flushes_on_watermark_without_new_pane_events(self):
        """A later event on ANOTHER stream advances the watermark and
        must close the open pane (host TimeBatchWindow scheduler
        parity), even though no further S event arrives."""
        app = (DEFINE + "define stream Tick (x double); "
               "from S#window.timeBatch(1 sec) select sum(v) as s "
               "insert into OutputStream; "
               "from Tick select x insert into Ignored;")
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(
                "@app:playback @app:execution('tpu') " + app)
            got = []
            rt.add_callback("OutputStream", lambda evs: got.extend(evs))
            rt.start()
            qr = rt.query_runtimes[rt.query_names()[0]]
            assert isinstance(qr.device_runtime, DeviceQueryRuntime)
            h = rt.get_input_handler("S")
            h.send([0, 10.0], timestamp=1000)
            h.send([0, 20.0], timestamp=1400)
            assert got == []  # pane still open
            # watermark moves past the boundary via the other stream
            rt.get_input_handler("Tick").send([1.0], timestamp=2500)
            assert len(got) == 1 and got[0].data[0] == pytest.approx(30.0)
            rt.shutdown()
        finally:
            m.shutdown()


class TestSnapshotRestore:
    def test_persist_restore_roundtrip(self):
        app = DEFINE + (
            "from S#window.length(4) select k, sum(v) as s group by k "
            "insert into OutputStream;")
        sends = series(40, seed=16)
        # uninterrupted run
        full = run_app(app, sends, mode="tpu")
        # interrupted: snapshot at the midpoint, restore into a new app
        m = SiddhiManager()
        try:
            hdr = "@app:playback @app:execution('tpu') "
            rt = m.create_siddhi_app_runtime(hdr + app)
            got = []
            rt.add_callback("OutputStream", lambda evs: got.extend(evs))
            rt.start()
            h = rt.get_input_handler("S")
            for row, ts in sends[:20]:
                h.send(row, timestamp=ts)
            blob = rt.snapshot()
            rt.shutdown()

            rt2 = m.create_siddhi_app_runtime(hdr + app)
            got2 = []
            rt2.add_callback("OutputStream", lambda evs: got2.extend(evs))
            rt2.start()
            rt2.restore(blob)
            h2 = rt2.get_input_handler("S")
            for row, ts in sends[20:]:
                h2.send(row, timestamp=ts)
            rt2.shutdown()
            names = rt2.junctions["OutputStream"].definition.attribute_names
            resumed = [dict(zip(names, e.data)) for e in got + got2]
            assert_rows_close(full, resumed)
        finally:
            m.shutdown()
