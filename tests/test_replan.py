"""Live re-planning differential suite.

``SiddhiAppRuntime.replan()`` re-lowers a RUNNING app under a new plan:
pause ingest, build a complete replacement engine set from a fresh
parse (per-query pins override the cost model), adopt it onto the same
runtime object, then rebuild all engine state by replaying the input
journal's full history with the output ledger suppressing everything
already delivered.

The contract under test: the observable output sequence of a run that
switches plans MID-STREAM is identical to an uninterrupted run on
either plan — across baseline→fused, dense→hotkey and single→sharded
switches, under transient ingest/emit faults, and across a simulated
crash between replacement build and re-seat (which must leave the old
engines fully operational).  Refusals (no journal) are counted, forced
switches land over REST, and the PlanMonitor's observed-cost switch
rides the same bit-exact protocol.
"""

import json
import types
import urllib.error
import urllib.request

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.exceptions import (
    SiddhiAppRuntimeError,
    SimulatedCrashError,
)


def _collector(res):
    return lambda events: res.extend(
        (e.timestamp, tuple(e.data)) for e in events)


def _norm(rows):
    """DOUBLE attrs ride float32 device lanes (documented precision
    subset): one-decimal inputs are exact at 4dp."""
    return [(ts, tuple(round(v, 4) if isinstance(v, float) else v
                       for v in r)) for ts, r in rows]


CHAIN = """
@app:name('rp{tag}') @app:playback @app:execution('tpu') {faults}
define stream SIn (sym int, price float, vol int);
@info(name='q1') from SIn[price > 10.0]
select sym, price, vol insert into Mid;
@info(name='q2') from Mid[vol > 50] select sym, price insert into Out;
"""

JOURNAL = "@app:faults(journal='8192')"


def _chain_sends(n, seed):
    rng = np.random.default_rng(seed)
    out, ts = [], 1000
    for _ in range(n):
        out.append(([int(rng.integers(0, 5)),
                     float(np.float32(rng.uniform(0, 30))),
                     int(rng.integers(1, 100))], ts))
        ts += 3
    return out


def _run_chain(tag, faults, sends, switch_at=None, pins=None,
               sink="Out"):
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime(
            CHAIN.format(tag=tag, faults=faults))
        got = []
        rt.add_callback(sink, _collector(got))
        rt.start()
        h = rt.get_input_handler("SIn")
        lows = []
        for i, (row, ts) in enumerate(sends):
            if switch_at is not None and i == switch_at:
                lows.append(dict(rt.lowering()))
                rt.replan(pins, reason="test switch")
                lows.append(dict(rt.lowering()))
                h = rt.get_input_handler("SIn")
            h.send(list(row), timestamp=ts)
        st = rt.statistics()
        rt.shutdown()
        return got, lows, st
    finally:
        m.shutdown()


class TestMidStreamSwitches:
    def test_baseline_to_fused_bit_identical(self):
        sends = _chain_sends(400, 11)
        ref, _, _ = _run_chain("b0", JOURNAL, sends)
        fused_ref, _, _ = _run_chain("b1", JOURNAL + " @app:fuse", sends)
        got, lows, st = _run_chain(
            "b2", JOURNAL, sends, switch_at=200,
            pins={"q1": "fuse", "q2": "fuse"})
        assert lows == [{"q1": "device", "q2": "device"},
                        {"q1": "fused", "q2": "fused"}]
        assert len(ref) > 0
        # identical to the uninterrupted run on EITHER plan
        assert got == ref
        assert got == fused_ref
        # the switch is in the replan history, per changed query
        key = "io.siddhi.SiddhiApps.rpb2.Siddhi.Queries"
        assert st[f"{key}.q1.plannerReplans"] >= 1
        assert st[f"{key}.q2.plannerReplans"] >= 1

    def test_fused_to_baseline_bit_identical(self):
        sends = _chain_sends(300, 29)
        ref, _, _ = _run_chain("u0", JOURNAL + " @app:fuse", sends)
        got, lows, _ = _run_chain(
            "u1", JOURNAL + " @app:fuse", sends, switch_at=150,
            pins={"q1": "device", "q2": "device"})
        assert lows == [{"q1": "fused", "q2": "fused"},
                        {"q1": "device", "q2": "device"}]
        assert got == ref

    def test_single_to_sharded_bit_identical(self):
        from siddhi_tpu.ops.device_query import DeviceQueryEngine
        from siddhi_tpu.parallel.device_shard import ShardedDeviceQueryEngine

        app = """
@app:name('rs{tag}') @app:playback @app:faults(journal='8192')
@app:execution('tpu', devices='8')
define stream SIn (sym int, price float, vol int);
@info(name='q1') from SIn#window.lengthBatch(32)
select sum(price) as s, count() as c insert into Out;
"""

        def run(tag, switch_at=None, pins0=None, pins1=None):
            m = SiddhiManager()
            try:
                rt = m.create_siddhi_app_runtime(app.format(tag=tag))
                got = []
                rt.add_callback("Out", _collector(got))
                rt.start()
                if pins0:
                    rt.replan(pins0, reason="pin single-device start")
                h = rt.get_input_handler("SIn")
                kinds = []
                for i, (row, ts) in enumerate(sends):
                    if switch_at is not None and i == switch_at:
                        qr = rt.query_runtimes["q1"]
                        kinds.append(type(qr.device_runtime.engine))
                        rt.replan(pins1, reason="shard it")
                        h = rt.get_input_handler("SIn")
                        qr = rt.query_runtimes["q1"]
                        kinds.append(type(qr.device_runtime.engine))
                    h.send(list(row), timestamp=ts)
                rt.shutdown()
                return got, kinds
            finally:
                m.shutdown()

        sends = _chain_sends(400, 17)
        ref, _ = run("r")  # legacy: mesh declared -> sharded throughout
        got, kinds = run("s", switch_at=200, pins0={"q1": "device"},
                         pins1={"q1": "device+shard"})
        # the lowering string stays 'device'; the switch is visible in
        # the engine type
        assert kinds == [DeviceQueryEngine, ShardedDeviceQueryEngine]
        assert len(ref) > 0
        assert got == ref

    def test_dense_to_hotkey_identical_on_either_plan(self):
        app = """
@app:name('rh{tag}') @app:playback @app:faults(journal='16384')
@app:execution('tpu', instances='16') {ann}
define stream S (k long, u double, v double);
partition with (k of S) begin
@info(name='q') from every a=S[v > 8.0] -> b=S[v > 12.0]
select b.v as bv insert into Alerts;
end;
"""

        def run(tag, ann, switch_at=None, pins=None):
            m = SiddhiManager()
            try:
                rt = m.create_siddhi_app_runtime(
                    app.format(tag=tag, ann=ann))
                got = []
                rt.add_callback("Alerts", _collector(got))
                rt.start()
                h = rt.get_input_handler("S")
                lows = []
                for i, (row, ts) in enumerate(sends):
                    if switch_at is not None and i == switch_at:
                        lows.append(dict(rt.lowering()))
                        rt.replan(pins, reason="route the hot key")
                        lows.append(dict(rt.lowering()))
                        h = rt.get_input_handler("S")
                    h.send(list(row), timestamp=ts)
                st = rt.statistics()
                rt.shutdown()
                return got, lows, st
            finally:
                m.shutdown()

        rng = np.random.default_rng(5)
        sends, t = [], 1000
        for _ in range(600):
            t += int(rng.integers(1, 40))
            k = 3 if rng.random() < 0.6 else int(rng.integers(0, 30))
            sends.append(([k, round(float(rng.uniform(0, 20)), 1),
                           round(float(rng.uniform(0, 20)), 1)], t))

        dense_ref, _, _ = run("d", "")
        hk_ref, _, _ = run(
            "k", "@app:hotkeys(k='4', promote='0.3', demote='0.1')")
        got, lows, st = run("s", "", switch_at=300,
                            pins={"q": "dense+hotkey"})
        assert lows == [{"q": "dense"}, {"q": "hotkey"}]
        # promotion actually happened post-switch (no hollow pass)
        key = "io.siddhi.SiddhiApps.rhs.Siddhi.Queries.q"
        assert st[f"{key}.hotkeyPromotions"] >= 1
        assert len(dense_ref) > 0
        # identical to the uninterrupted run on EITHER plan, in the
        # suite's documented float32-lane precision subset
        assert _norm(got) == _norm(dense_ref)
        assert _norm(got) == _norm(hk_ref)


class TestReplanFaults:
    pytestmark = pytest.mark.faults

    def test_switch_under_transient_ingest_emit_faults(self):
        sends = _chain_sends(200, 13)
        ref, _, _ = _run_chain("t0", JOURNAL, sends)
        faults = ("@app:faults(journal='8192', "
                  "transfer.retry.scale='0.001', "
                  "ingest.put='transient:count=3', "
                  "emit.drain='transient:count=2')")
        got, lows, st = _run_chain(
            "t1", faults, sends, switch_at=100,
            pins={"q1": "fuse", "q2": "fuse"})
        assert lows[1] == {"q1": "fused", "q2": "fused"}
        assert got == ref

    def test_crash_between_capture_and_reseat_leaves_old_plan_live(self):
        sends = _chain_sends(200, 23)
        ref, _, _ = _run_chain("c0", JOURNAL, sends)
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(
                CHAIN.format(tag="c1", faults=JOURNAL))
            got = []
            rt.add_callback("Out", _collector(got))
            rt.start()
            h = rt.get_input_handler("SIn")
            for i, (row, ts) in enumerate(sends):
                if i == 100:
                    rt.app_context.fault_injector.configure(
                        "replan.reseat", "crash", count=1)
                    with pytest.raises(SimulatedCrashError):
                        rt.replan({"q1": "fuse", "q2": "fuse"},
                                  reason="doomed")
                    # the old engines survived the abandoned switch
                    assert rt.lowering() == {"q1": "device",
                                             "q2": "device"}
                    # and the retry lands
                    rt.replan({"q1": "fuse", "q2": "fuse"},
                              reason="retry")
                    assert rt.lowering() == {"q1": "fused",
                                             "q2": "fused"}
                    h = rt.get_input_handler("SIn")
                h.send(list(row), timestamp=ts)
            rt.shutdown()
        finally:
            m.shutdown()
        assert got == ref

    def test_replan_without_journal_refused_and_counted(self):
        sends = _chain_sends(40, 31)
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(
                CHAIN.format(tag="n0", faults=""))
            rt.start()
            h = rt.get_input_handler("SIn")
            for row, ts in sends:
                h.send(list(row), timestamp=ts)
            with pytest.raises(SiddhiAppRuntimeError, match="journal"):
                rt.replan({"q1": "fuse", "q2": "fuse"}, reason="no")
            # still running on the old plan, refusal counted
            assert rt.lowering() == {"q1": "device", "q2": "device"}
            st = rt.statistics()
            key = "io.siddhi.SiddhiApps.rpn0.Siddhi.Queries.rpn0"
            assert st[f"{key}.plannerFallbacks"] >= 1
            assert "replan refused" in st[f"{key}.plannerFallbackReason"]
            rt.shutdown()
        finally:
            m.shutdown()


class TestMonitorAndRest:
    def test_monitor_switch_is_bit_exact_and_pinned(self):
        """The observed-cost switch rides the same replay protocol:
        device → host on tiny observed batches, outputs unchanged, and
        the switched query comes back pinned (no flip-flop)."""
        from siddhi_tpu.planner.monitor import PlanMonitor

        app = """
@app:name('rm{tag}') @app:playback @app:execution('tpu')
@app:plan(auto='true') @app:faults(journal='8192')
define stream S (sym int, price float);
@info(name='q1') from S[price > 10.0] select sym insert into Out;
"""

        def run(tag, switch_at=None):
            m = SiddhiManager()
            try:
                rt = m.create_siddhi_app_runtime(app.format(tag=tag))
                got = []
                rt.add_callback("Out", _collector(got))
                rt.start()
                h = rt.get_input_handler("S")
                switched = None
                for i, (row, ts) in enumerate(sends):
                    if switch_at is not None and i == switch_at:
                        mon = PlanMonitor(rt)
                        sm = rt.app_context.statistics_manager
                        sm.latency["q1"] = types.SimpleNamespace(
                            name="q1", events=4 * 10, batches=10)
                        assert mon.decide() == {"q1": "host"}
                        assert mon.maybe_replan() is True
                        switched = dict(rt.lowering())
                        # back pinned: the monitor never flip-flops it
                        sm2 = rt.app_context.statistics_manager
                        assert sm2.plans["q1"].mode == "pinned"
                        assert PlanMonitor(rt).decide() == {}
                        h = rt.get_input_handler("S")
                    h.send(list(row), timestamp=ts)
                st = rt.statistics()
                rt.shutdown()
                return got, switched, st
            finally:
                m.shutdown()

        rng = np.random.default_rng(3)
        sends = [([int(rng.integers(0, 9)),
                   float(np.float32(rng.uniform(0, 30)))], 1000 + 3 * i)
                 for i in range(200)]
        ref, _, _ = run("r")
        got, switched, st = run("s", switch_at=100)
        assert switched == {"q1": "host"}
        assert got == ref
        # the un-forced switch is in the app-wide history
        key = "io.siddhi.SiddhiApps.rms.Siddhi.Queries.q1"
        assert st[f"{key}.plannerReplans"] >= 1

    def test_rest_plan_dump_and_forced_replan(self):
        from siddhi_tpu.service import SiddhiService

        svc = SiddhiService()
        svc.start()
        base = f"http://127.0.0.1:{svc.port}"
        try:
            app = CHAIN.format(tag="w0", faults=JOURNAL).replace(
                "@app:name('rpw0')", "@app:name('restPlan')")
            req = urllib.request.Request(
                f"{base}/siddhi-artifact-deploy", data=app.encode(),
                method="POST")
            with urllib.request.urlopen(req) as r:
                assert r.status == 200

            with urllib.request.urlopen(
                    f"{base}/siddhi-plan/restPlan") as r:
                payload = json.loads(r.read())
            assert payload["lowering"] == {"q1": "device", "q2": "device"}
            assert set(payload["plans"]) == {"q1", "q2"}
            rec = payload["plans"]["q1"]
            assert rec["actual"] == "device"
            assert {c["path"] for c in rec["candidates"]} >= \
                {"host", "device"}
            assert all("cost" in c for c in rec["candidates"])

            # force a composed plan over REST, then confirm the dump
            # shows the switch in the re-plan history
            with urllib.request.urlopen(
                    f"{base}/siddhi-replan/restPlan?q1=fuse&q2=fuse") as r:
                assert json.loads(r.read())["queries"] == \
                    {"q1": "fused", "q2": "fused"}
            with urllib.request.urlopen(
                    f"{base}/siddhi-plan/restPlan") as r:
                payload = json.loads(r.read())
            assert payload["lowering"] == {"q1": "fused", "q2": "fused"}
            assert any(e["to"] == "fused" or e["to"] == "fuse"
                       for e in payload["replans"]) or payload["replans"]

            # unknown app -> 404; a refused replan -> 409
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(f"{base}/siddhi-plan/ghost")
            assert e.value.code == 404
        finally:
            svc.stop()
