"""Mesh-sharded device query engine: a running group-by query's
per-group state lives on N devices (shard-major rows under shard_map)
and results match the host engine — the device-query analog of the
dense NFA's sharded partition axis (tests/test_sharded_product.py).
"""

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.device_single import DeviceQueryRuntime
from siddhi_tpu.ops.device_query import compile_query
from siddhi_tpu.parallel import ShardedDeviceQueryEngine, make_mesh

APP = "define stream S (sym string, v double, k int); "


def n_state_devices(state):
    return len({d for arr in state.values() for d in arr.devices()})


class TestShardedEngine:
    def test_differential_vs_unsharded(self):
        q = (APP + "@info(name='q') from S select k, sum(v) as s, "
             "count() as c, min(v) as mn, max(v) as mx group by k "
             "insert into Out;")
        plain = compile_query(q, "q", n_groups=64)
        sharded = ShardedDeviceQueryEngine(
            compile_query(q, "q", n_groups=64), make_mesh(8))
        ps, ss = plain.init_state(), sharded.init_state()
        assert n_state_devices(ss) == 8
        rng = np.random.default_rng(1)
        for step in range(4):
            n = int(rng.integers(5, 60))
            cols = {
                "sym": np.array(["x"] * n),
                "v": rng.uniform(0, 50, n),
                "k": rng.integers(0, 30, n).astype(np.int32),
            }
            ts = np.arange(n, dtype=np.int64) + 1000 + step * 1000
            ps, prow = plain.process(ps, cols, ts)
            ss, srow = sharded.process(ss, cols, ts)
            assert len(prow) == len(srow)
            for i, (a, b) in enumerate(zip(prow, srow)):
                # int lanes bit-exact, float32 sums within tolerance
                assert int(a["k"]) == int(b["k"])
                assert int(a["c"]) == int(b["c"])
                assert float(b["s"]) == pytest.approx(
                    float(a["s"]), rel=1e-5)
                assert float(b["mn"]) == float(a["mn"])
                assert float(b["mx"]) == float(a["mx"])

    def test_stateless_filter_kind_rejected(self):
        # windowed kinds shard now (tests/test_sharded_windows.py); the
        # stateless filter kind is the one remaining single-device case
        from siddhi_tpu.core.exceptions import SiddhiAppCreationError

        q = (APP + "@info(name='q') from S[v > 10] select sym, v "
             "insert into Out;")
        with pytest.raises(SiddhiAppCreationError, match="stateless"):
            ShardedDeviceQueryEngine(compile_query(q, "q"), make_mesh(8))

    def test_keyed_forever_agg_rejected(self):
        from siddhi_tpu.core.exceptions import SiddhiAppCreationError

        q = (APP + "@info(name='q') from S#window.length(3) select k, "
             "maxForever(v) as mf insert into Out;")
        with pytest.raises(SiddhiAppCreationError, match="co-locate"):
            ShardedDeviceQueryEngine(
                compile_query(q, "q", partition_mode=True, n_wgroups=64),
                make_mesh(8))


class TestShardedProductPath:
    def _app(self, devices):
        return (
            "@app:playback "
            f"@app:execution('tpu', partitions='64', devices='{devices}') "
            + APP +
            "@info(name='gq') from S select k, sum(v) as s group by k "
            "insert into Out;"
        )

    def test_group_state_on_8_devices_matches_host(self):
        events = []
        rng = np.random.default_rng(2)
        for i in range(80):
            events.append(([str(i % 3), float(rng.integers(0, 50)),
                            int(rng.integers(0, 20))], 1000 + i))

        def run(app):
            m = SiddhiManager()
            try:
                rt = m.create_siddhi_app_runtime(app)
                got = []
                rt.add_callback("Out", lambda evs: got.extend(
                    tuple(e.data) for e in evs))
                rt.start()
                h = rt.get_input_handler("S")
                for row, ts in events:
                    h.send(row, timestamp=ts)
                runtimes = [getattr(qr, "device_runtime", None)
                            for qr in rt.query_runtimes.values()]
                rt.shutdown()
                return got, runtimes
            finally:
                m.shutdown()

        host, _ = run("@app:playback " + APP +
                      "@info(name='gq') from S select k, sum(v) as s "
                      "group by k insert into Out;")
        dev, runtimes = run(self._app(8))
        dr = [r for r in runtimes if isinstance(r, DeviceQueryRuntime)]
        assert dr, "query did not lower"
        assert isinstance(dr[0].engine, ShardedDeviceQueryEngine)
        assert n_state_devices(dr[0].state) == 8
        assert len(host) == len(dev)
        for a, b in zip(host, dev):
            assert a[0] == b[0]
            assert b[1] == pytest.approx(a[1], rel=1e-5)

    def test_sharded_snapshot_restore(self):
        from siddhi_tpu.util.persistence import InMemoryPersistenceStore

        app = "@app:name('shsnap') " + self._app(8)
        m = SiddhiManager()
        m.set_persistence_store(InMemoryPersistenceStore())
        try:
            rt = m.create_siddhi_app_runtime(app)
            rt.start()
            h = rt.get_input_handler("S")
            h.send(["a", 10.0, 1], timestamp=1000)
            h.send(["a", 20.0, 2], timestamp=1001)
            rev = rt.persist()
            rt.shutdown()

            rt2 = m.create_siddhi_app_runtime(app)
            got = []
            rt2.add_callback("Out", lambda evs: got.extend(
                tuple(e.data) for e in evs))
            rt2.start()
            rt2.restore_revision(rev)
            dr = [getattr(qr, "device_runtime", None)
                  for qr in rt2.query_runtimes.values()]
            assert n_state_devices(dr[0].state) == 8  # placement restored
            h2 = rt2.get_input_handler("S")
            h2.send(["a", 5.0, 1], timestamp=1002)  # k=1: 10 + 5
            rt2.shutdown()
            assert got == [(1, 15.0)], got
        finally:
            m.shutdown()


class TestShardedPurge:
    def test_partitioned_purge_reclaims_sharded_rows(self):
        # composed-group form (inner group-by): wgroups must still
        # intern so the idle purge sees last-use times
        app = (
            "@app:playback "
            "@app:execution('tpu', partitions='16', devices='8') "
            + APP +
            "@purge(enable='true', interval='1 sec', idle.period='2 sec') "
            "partition with (sym of S) begin "
            "@info(name='pq') from S select sym, k, sum(v) as s "
            "group by k insert into Out; end;"
        )
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(app)
            pr = rt.partitions["partition_0"]
            assert pr.is_dense
            got = []
            rt.add_callback("Out", lambda evs: got.extend(
                tuple(e.data) for e in evs))
            rt.start()
            h = rt.get_input_handler("S")
            for i, u in enumerate(["a", "b", "c"]):
                h.send([u, 1.0, 0], timestamp=1000 + i)
            qr = next(iter(pr.dense_query_runtimes.values()))
            eng = qr.device_runtime.engine
            assert isinstance(eng, ShardedDeviceQueryEngine)
            assert int(eng._wgrp_in_use.sum()) == 3  # wgroups interned
            # watermark jump purges all three idle keys...
            h.send(["a", 5.0, 0], timestamp=60_000)
            assert len(eng._wgrp_ids) == 1  # ...then 'a' re-interned
            rt.shutdown()
            # 'a' restarted from scratch: purged row was zeroed
            assert got[-1] == ("a", 0, 5.0), got
        finally:
            m.shutdown()


class TestShardedPartitionedProduct:
    def test_partitioned_running_sharded(self):
        # partition key composes into the sharded group axis
        app = (
            "@app:playback "
            "@app:execution('tpu', partitions='64', devices='8') "
            + APP +
            "partition with (sym of S) begin "
            "@info(name='pq') from S select sym, sum(v) as s "
            "insert into Out; end;"
        )
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(app)
            pr = rt.partitions["partition_0"]
            assert pr.is_dense
            got = []
            rt.add_callback("Out", lambda evs: got.extend(
                tuple(e.data) for e in evs))
            rt.start()
            h = rt.get_input_handler("S")
            for i in range(16):
                h.send([f"u{i % 5}", 1.0, 0], timestamp=1000 + i)
            qr = next(iter(pr.dense_query_runtimes.values()))
            assert isinstance(qr.device_runtime.engine,
                              ShardedDeviceQueryEngine)
            assert n_state_devices(qr.device_runtime.state) == 8
            rt.shutdown()
            # per-key running sums: u0 hits 1,2,3,4 over its 4 events...
            per_key = {}
            expect = []
            for i in range(16):
                k = f"u{i % 5}"
                per_key[k] = per_key.get(k, 0.0) + 1.0
                expect.append((k, per_key[k]))
            assert got == expect, (got, expect)
        finally:
            m.shutdown()


class TestShardedGroupKeySideChannel:
    def test_big_batch_chunking_keeps_group_keys(self):
        """>MAX_DEVICE_BATCH sharded batches must accumulate the
        group-key side channel across chunks (regression: only the last
        chunk's keys survived, collapsing per-group rate limiting)."""
        import numpy as np

        from siddhi_tpu.core.event import EventBatch

        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(
                "@app:playback "
                "@app:execution('tpu', partitions='16', devices='8') "
                + APP +
                "@info(name='gq') from S select k, sum(v) as s group by k "
                "output first every 5000 events insert into Out;")
            got = []
            rt.add_callback("Out", lambda evs: got.extend(
                tuple(e.data) for e in evs))
            rt.start()
            n = 3000
            rng = np.random.default_rng(0)
            ks = rng.integers(0, 4, n).astype(np.int32)
            rt.get_input_handler("S").send_batch(EventBatch(
                "S", ["sym", "v", "k"],
                {"sym": np.asarray(["x"] * n, dtype=object),
                 "v": np.ones(n), "k": ks},
                1000 + np.arange(n, dtype=np.int64)))
            rt.shutdown()
            # per-group FIRST within the 5000-event period: exactly one
            # row per distinct k (a global-group collapse emits just 1)
            assert len(got) == 4, got
            assert sorted(g[0] for g in got) == [0, 1, 2, 3]
        finally:
            m.shutdown()
