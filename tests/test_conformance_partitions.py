"""Partition conformance matrix: value/range keys, inner streams, purge.

Ported behavior families from the reference's partition suite
(modules/siddhi-core/src/test/java/io/siddhi/core/query/partition/
PartitionTestCase1/2.java): per-key isolated query state, range labels,
inner (#) streams scoped per key, idle-key purge.
"""

import pytest

from siddhi_tpu import SiddhiManager

DEFINE = "define stream S (user string, region string, v double); "


def run(app, sends, out="OutputStream"):
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime("@app:playback " + DEFINE + app)
        got = []
        if out in rt.junctions:
            rt.add_callback(out, lambda evs: got.extend(e.data for e in evs))
        rt.start()
        t = 1000
        for row in sends:
            if isinstance(row, tuple):
                row, t = row
            rt.get_input_handler("S").send(row, timestamp=t)
            t += 100
        rt.shutdown()
        return got
    finally:
        m.shutdown()


class TestValuePartition:
    def test_per_key_running_sum_isolated(self):
        app = ("partition with (user of S) begin "
               "from S select user, sum(v) as total insert into OutputStream; "
               "end;")
        got = run(app, [["a", "r1", 10.0], ["b", "r1", 5.0],
                        ["a", "r1", 1.0], ["b", "r1", 2.0]])
        assert got == [["a", 10.0], ["b", 5.0], ["a", 11.0], ["b", 7.0]]

    def test_per_key_length_window(self):
        app = ("partition with (user of S) begin "
               "from S#window.length(2) select user, sum(v) as total "
               "insert into OutputStream; end;")
        got = run(app, [["a", "r", 1.0], ["a", "r", 2.0], ["a", "r", 3.0],
                        ["b", "r", 10.0]])
        # a's window slides independently of b's
        assert got == [["a", 1.0], ["a", 3.0], ["a", 5.0], ["b", 10.0]]

    def test_per_key_pattern_state(self):
        app = ("partition with (user of S) begin "
               "from every e1=S[v > 100.0] -> e2=S[v > e1.v] "
               "select e1.user as user, e1.v as a, e2.v as b "
               "insert into OutputStream; end;")
        got = run(app, [["x", "r", 150.0], ["y", "r", 500.0],
                        ["x", "r", 200.0],   # completes x only
                        ["y", "r", 600.0]])  # completes y only
        assert got == [["x", 150.0, 200.0], ["y", 500.0, 600.0]]

    def test_multi_attribute_keys_independent(self):
        app = ("partition with (region of S) begin "
               "from S select region, count() as c insert into OutputStream; "
               "end;")
        got = run(app, [["u1", "east", 1.0], ["u2", "west", 1.0],
                        ["u3", "east", 1.0]])
        assert got == [["east", 1], ["west", 1], ["east", 2]]


class TestRangePartition:
    APP = ("partition with (v < 100.0 as 'small' or v >= 100.0 as 'large' "
           "of S) begin from S select user, count() as c "
           "insert into OutputStream; end;")

    def test_ranges_isolate_counts(self):
        got = run(self.APP, [["a", "r", 50.0], ["b", "r", 500.0],
                             ["c", "r", 60.0]])
        # 'small' partition counts a,c; 'large' counts b
        assert got == [["a", 1], ["b", 1], ["c", 2]]

    def test_unmatched_rows_dropped(self):
        app = ("partition with (v < 100.0 as 'small' of S) begin "
               "from S select user, count() as c insert into OutputStream; "
               "end;")
        got = run(app, [["a", "r", 50.0], ["b", "r", 500.0],
                        ["c", "r", 60.0]])
        assert got == [["a", 1], ["c", 2]]  # b matches no range


class TestInnerStreams:
    def test_inner_stream_scoped_per_key(self):
        # '#P' inner streams connect queries within ONE key's instance
        app = ("partition with (user of S) begin "
               "from S select user, v * 2.0 as d insert into #Mid; "
               "from #Mid select user, sum(d) as total "
               "insert into OutputStream; end;")
        got = run(app, [["a", "r", 1.0], ["b", "r", 10.0],
                        ["a", "r", 2.0]])
        assert got == [["a", 2.0], ["b", 20.0], ["a", 6.0]]


class TestPartitionPurge:
    def test_idle_instances_purged_and_state_reset(self):
        app = ("@purge(enable='true', interval='1 sec', "
               "idle.period='2 sec') "
               "partition with (user of S) begin "
               "from S select user, count() as c insert into OutputStream; "
               "end;")
        got = run(app, [
            (["a", "r", 1.0], 1000),
            (["a", "r", 1.0], 1500),   # c=2
            (["b", "r", 1.0], 9000),   # watermark jump: a idle > 2 sec
            (["a", "r", 1.0], 9500),   # a's instance was purged: c restarts
        ])
        assert got == [["a", 1], ["a", 2], ["b", 1], ["a", 1]]


class TestPartitionWithExpressionKey:
    def test_expression_partition_key(self):
        # any expression may key the partition (reference
        # ValuePartitionExecutor evaluates a compiled expression)
        app = ("partition with (v % 2.0 of S) begin "
               "from S select user, count() as c insert into OutputStream; "
               "end;")
        got = run(app, [["a", "x", 1.0], ["b", "y", 2.0], ["c", "x", 3.0]])
        # keys 1.0, 0.0, 1.0 — first and third share an instance
        assert got == [["a", 1], ["b", 1], ["c", 2]]
