"""Join condition on the device: the O(B*W) cross-product probe runs as
a jitted [B, W] kernel under @app:execution('tpu') while buffering /
expiry / outer-fill keep the host JoinRuntime's exact semantics
(reference: query/input/stream/join/JoinProcessor.java:45; SURVEY §7
step 7).  Differential: device-probed runs must equal numpy-probed runs
row for row.
"""

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager

DEFS = ("define stream A (sym string, x double, n int) ; "
        "define stream B (sym2 string, y double, m int) ; ")


def run(app, events, out="O"):
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime("@app:playback " + app)
        got = []
        rt.add_callback(out, lambda evs: got.extend(
            tuple(e.data) for e in evs))
        rt.start()
        for sid, row, ts in events:
            rt.get_input_handler(sid).send(row, timestamp=ts)
        jrs = [getattr(qr, "join_runtime", None)
               for qr in rt.query_runtimes.values()]
        lowering = rt.lowering()
        rt.shutdown()
        return got, [j for j in jrs if j is not None], lowering
    finally:
        m.shutdown()


def mk_events(n=40, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        sid = "A" if rng.integers(2) else "B"
        row = ([f"s{int(rng.integers(4))}", float(rng.integers(0, 10)),
                int(rng.integers(0, 5))])
        out.append((sid, row, 1000 + i * int(rng.integers(1, 60))))
    return out


def differential(app, events, expect_probe=True):
    host, _, _ = run(app, events)
    dev, jrs, lowering = run("@app:execution('tpu') " + app, events)
    if expect_probe:
        assert jrs and jrs[0].device_probe is not None, lowering
        assert jrs[0].probe_invocations > 0
        assert "device_probe" in lowering.values()
    else:
        assert all(j.device_probe is None for j in jrs)
    assert host == dev, (len(host), len(dev), host[:4], dev[:4])
    return dev


class TestDeviceJoinProbe:
    def test_length_length(self):
        app = (DEFS + "@info(name='j') from A#window.length(3) join "
               "B#window.length(3) on A.x < B.y "
               "select A.sym as s1, B.sym2 as s2, A.x as x, B.y as y "
               "insert into O;")
        out = differential(app, mk_events(50))
        assert out  # pairs actually produced

    def test_time_time(self):
        app = (DEFS + "@info(name='j') from A#window.time(500 ms) join "
               "B#window.time(500 ms) on A.x >= B.y "
               "select A.x as x, B.y as y insert into O;")
        differential(app, mk_events(50, seed=1))

    def test_compound_condition_with_filters(self):
        app = (DEFS + "@info(name='j') from A[x > 1.0]#window.length(4) "
               "join B[y < 9.0]#window.length(4) "
               "on A.x < B.y and A.n != B.m "
               "select A.x as x, B.y as y, A.n as n, B.m as m "
               "insert into O;")
        differential(app, mk_events(60, seed=2))

    def test_left_outer_join(self):
        # outer fill stays host-side; the probe only computes the mask
        app = (DEFS + "@info(name='j') from A#window.length(2) "
               "left outer join B#window.length(2) on A.x < B.y "
               "select A.x as x, B.y as y insert into O;")
        differential(app, mk_events(40, seed=3))

    def test_unidirectional(self):
        app = (DEFS + "@info(name='j') from A#window.length(3) "
               "unidirectional join B#window.length(3) on A.x < B.y "
               "select A.x as x, B.y as y insert into O;")
        differential(app, mk_events(40, seed=4))

    def test_select_strings_while_condition_numeric(self):
        # STRING attrs may flow through select; only CONDITION attrs
        # need device lanes
        app = (DEFS + "@info(name='j') from A#window.length(3) join "
               "B#window.length(3) on A.n == B.m "
               "select A.sym as s1, B.sym2 as s2 insert into O;")
        differential(app, mk_events(40, seed=5))

    def test_expired_pairs_match(self):
        # window-expired rows post-join as EXPIRED through the same mask
        app = (DEFS + "@info(name='j') from A#window.length(1) join "
               "B#window.length(2) on A.x <= B.y "
               "select A.x as x, B.y as y insert into O;")
        differential(app, mk_events(40, seed=6))


class TestDeviceJoinFallbacks:
    def test_string_condition_keeps_numpy_probe(self):
        app = (DEFS + "@info(name='j') from A#window.length(3) join "
               "B#window.length(3) on A.sym == B.sym2 "
               "select A.x as x, B.y as y insert into O;")
        differential(app, mk_events(40, seed=7), expect_probe=False)

    def test_no_condition_keeps_numpy_path(self):
        app = (DEFS + "@info(name='j') from A#window.length(2) join "
               "B#window.length(2) "
               "select A.x as x, B.y as y insert into O;")
        differential(app, mk_events(30, seed=8), expect_probe=False)

    def test_timestamp_condition_keeps_numpy_probe(self):
        # epoch-ms magnitudes exceed the device int32 lane; the kernel
        # env has no timestamp key so the trace check declines
        app = (DEFS + "@info(name='j') from A#window.length(3) join "
               "B#window.length(3) "
               "on A.x < B.y and eventTimestamp() > 0 "
               "select A.x as x, B.y as y insert into O;")
        differential(app, mk_events(30, seed=9), expect_probe=False)

    def test_nulls_in_numeric_column_fall_back_per_batch(self):
        # upstream can deliver object-dtype numeric columns carrying
        # None (e.g. an outer join's unmatched fill); the probe must
        # yield to the null-safe numpy evaluation for that batch
        from siddhi_tpu.core.event import EventBatch

        app = (DEFS + "@info(name='j') from A#window.length(3) join "
               "B#window.length(3) on A.x < B.y "
               "select A.x as x, B.y as y insert into O;")

        def run_nullable(mode):
            m = SiddhiManager()
            try:
                rt = m.create_siddhi_app_runtime("@app:playback " + mode + app)
                got = []
                rt.add_callback("O", lambda evs: got.extend(
                    tuple(e.data) for e in evs))
                rt.start()
                xs = np.empty(3, dtype=object)
                xs[:] = [1.0, None, 3.0]
                rt.get_input_handler("B").send([ "b", 5.0, 0], timestamp=1)
                rt.get_input_handler("A").send_batch(EventBatch(
                    "A", ["sym", "x", "n"],
                    {"sym": np.array(["a1", "a2", "a3"], dtype=object),
                     "x": xs, "n": np.zeros(3, dtype=np.int32)},
                    np.array([2, 3, 4], dtype=np.int64)))
                rt.shutdown()
                return got
            finally:
                m.shutdown()

        host = run_nullable("")
        dev = run_nullable("@app:execution('tpu') ")
        assert host == dev and len(host) == 2, (host, dev)

    def test_nullable_unrelated_column_keeps_probe(self):
        # only condition-REFERENCED attributes ride lanes: nulls in a
        # column the condition never reads must not force a fallback
        from siddhi_tpu.core.event import EventBatch

        app = (DEFS + "@info(name='j') from A#window.length(3) join "
               "B#window.length(3) on A.n == B.m "
               "select A.n as n, B.m as m insert into O;")
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(
                "@app:playback @app:execution('tpu') " + app)
            got = []
            rt.add_callback("O", lambda evs: got.extend(
                tuple(e.data) for e in evs))
            rt.start()
            rt.get_input_handler("B").send(["b", 1.0, 3], timestamp=1)
            xs = np.empty(2, dtype=object)
            xs[:] = [None, 2.0]  # nulls in x, which the condition ignores
            rt.get_input_handler("A").send_batch(EventBatch(
                "A", ["sym", "x", "n"],
                {"sym": np.array(["a1", "a2"], dtype=object),
                 "x": xs, "n": np.array([3, 9], dtype=np.int32)},
                np.array([2, 3], dtype=np.int64)))
            jr = next(iter(rt.query_runtimes.values())).join_runtime
            assert jr.probe_invocations > 0  # probe ran despite nulls
            rt.shutdown()
            assert got == [(3, 3)], got
        finally:
            m.shutdown()


class TestDeviceJoinFuzz:
    @pytest.mark.parametrize("seed", range(3))
    def test_fuzz(self, seed):
        rng = np.random.default_rng(300 + seed)
        conds = ["A.x < B.y", "A.x >= B.y", "A.n == B.m",
                 "A.x + B.y > 8.0", "A.n < B.m or A.x > 7.0"]
        wins = ["#window.length({n})", "#window.time({t} ms)"]
        for _ in range(3):
            wa = wins[rng.integers(2)].format(
                n=int(rng.integers(1, 5)), t=int(rng.integers(100, 800)))
            wb = wins[rng.integers(2)].format(
                n=int(rng.integers(1, 5)), t=int(rng.integers(100, 800)))
            cond = conds[rng.integers(len(conds))]
            app = (DEFS + f"@info(name='j') from A{wa} join B{wb} "
                   f"on {cond} select A.x as x, B.y as y, A.n as n "
                   "insert into O;")
            differential(app, mk_events(int(rng.integers(20, 60)),
                                        seed=1000 + seed))
