"""Window conformance matrix: every concrete window's emission contract.

Ported behavior families from the reference's window processors
(modules/siddhi-core/src/main/java/io/siddhi/core/query/processor/
stream/window/*WindowProcessor.java and the window/ test package):
CURRENT + EXPIRED emission asserted via QueryCallback's in/remove
events, on event-time playback.
"""

import pytest

from siddhi_tpu import SiddhiManager

DEFINE = "define stream S (symbol string, v double); "
TICK = "define stream Tick (x int); from Tick select x insert into _T; "


def run(query, sends, want_removed=False):
    """Returns (in_events, removed_events) data lists from a
    QueryCallback (reference test style: ts, inEvents, removeEvents)."""
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime(
            "@app:playback " + DEFINE + TICK + "@info(name='q') " + query)
        ins, outs = [], []

        def cb(ts, in_events, out_events):
            if in_events:
                ins.extend(e.data for e in in_events)
            if out_events:
                outs.extend(e.data for e in out_events)

        rt.add_callback("q", cb)
        rt.start()
        for stream, row, ts in sends:
            rt.get_input_handler(stream).send(row, timestamp=ts)
        rt.shutdown()
        return ins, outs
    finally:
        m.shutdown()


def srows(rows, t0=1000, dt=100):
    return [("S", r, t0 + i * dt) for i, r in enumerate(rows)]


ROWS = [["A", 1.0], ["B", 2.0], ["C", 3.0], ["D", 4.0]]


class TestLengthWindow:
    def test_current_and_expired(self):
        ins, outs = run("from S#window.length(2) select symbol, v "
                        "insert all events into OutputStream;", srows(ROWS))
        assert ins == [["A", 1.0], ["B", 2.0], ["C", 3.0], ["D", 4.0]]
        # third arrival evicts A, fourth evicts B
        assert outs == [["A", 1.0], ["B", 2.0]]

    def test_aggregate_over_length(self):
        ins, _ = run("from S#window.length(2) select sum(v) as s "
                     "insert into OutputStream;", srows(ROWS))
        assert [r[0] for r in ins] == [1.0, 3.0, 5.0, 7.0]


class TestLengthBatchWindow:
    def test_flush_every_n(self):
        ins, _ = run("from S#window.lengthBatch(2) select symbol, v "
                     "insert into OutputStream;", srows(ROWS))
        assert ins == [["A", 1.0], ["B", 2.0], ["C", 3.0], ["D", 4.0]]

    def test_batch_sum_emits_per_flush(self):
        ins, _ = run("from S#window.lengthBatch(2) select sum(v) as s "
                     "insert into OutputStream;", srows(ROWS))
        assert [r[0] for r in ins] == [3.0, 7.0]


class TestTimeWindow:
    def test_expiry_after_horizon(self):
        sends = [("S", ["A", 1.0], 1000), ("S", ["B", 2.0], 1400),
                 ("Tick", [1], 2600)]  # A (2000) and B (2400) expire
        ins, outs = run("from S#window.time(1 sec) select symbol, v "
                        "insert all events into OutputStream;", sends)
        assert ins == [["A", 1.0], ["B", 2.0]]
        assert outs == [["A", 1.0], ["B", 2.0]]

    def test_sliding_sum_decreases_on_expiry(self):
        q = ("from S#window.time(1 sec) select sum(v) as s "
             "insert all events into OutputStream;")
        sends = [("S", ["A", 1.0], 1000), ("S", ["B", 2.0], 1400),
                 ("S", ["C", 4.0], 2100)]  # A expired at 2000
        ins, _ = run(q, sends)
        assert [r[0] for r in ins] == [1.0, 3.0, 6.0]


class TestTimeBatchWindow:
    def test_pane_flush(self):
        sends = [("S", ["A", 1.0], 1000), ("S", ["B", 2.0], 1400),
                 ("S", ["C", 3.0], 2100),  # crosses the 2000 boundary
                 ("Tick", [1], 3100)]
        ins, _ = run("from S#window.timeBatch(1 sec) select sum(v) as s "
                     "insert into OutputStream;", sends)
        assert [r[0] for r in ins] == [3.0, 3.0]


class TestExternalTimeWindow:
    def test_event_driven_expiry(self):
        # externalTime expires against the EVENT's own time attribute
        q = ("from S#window.externalTime(eventTimestamp(), 1 sec) "
             "select symbol, v insert all events into OutputStream;")
        sends = [("S", ["A", 1.0], 1000), ("S", ["B", 2.0], 1500),
                 ("S", ["C", 3.0], 2100)]  # pushes A out (>= 1000+1000)
        ins, outs = run(q, sends)
        assert ins == [["A", 1.0], ["B", 2.0], ["C", 3.0]]
        assert outs == [["A", 1.0]]


class TestSessionWindow:
    def test_gap_closes_session(self):
        q = ("from S#window.session(1 sec) select sum(v) as s "
             "insert into OutputStream;")
        sends = [("S", ["A", 1.0], 1000), ("S", ["B", 2.0], 1500),
                 ("Tick", [1], 2600),   # gap > 1 sec: session 1 closes
                 ("S", ["C", 3.0], 5000),
                 ("Tick", [1], 6100)]
        ins, _ = run(q, sends)
        # running sum on arrivals; session-1 expiry retracts (A, B) in
        # the same advance that admits C: 1, 1+2, 3-3+3
        assert [r[0] for r in ins] == [1.0, 3.0, 3.0]


class TestDelayWindow:
    def test_events_delayed(self):
        q = "from S#window.delay(1 sec) select symbol insert into OutputStream;"
        sends = [("S", ["A", 1.0], 1000),
                 ("Tick", [1], 1500),   # not yet
                 ("Tick", [1], 2100)]   # released
        ins, _ = run(q, sends)
        assert ins == [["A"]]

    def test_nothing_before_delay(self):
        q = "from S#window.delay(1 sec) select symbol insert into OutputStream;"
        sends = [("S", ["A", 1.0], 1000), ("Tick", [1], 1500)]
        ins, _ = run(q, sends)
        assert ins == []


class TestSortWindow:
    def test_keeps_top_k_sorted(self):
        # sort window keeps the N LOWEST by the sort attr (asc), evicting
        # the greatest when full
        q = ("from S#window.sort(2, v) select symbol, v "
             "insert all events into OutputStream;")
        ins, outs = run(q, srows([["A", 5.0], ["B", 1.0], ["C", 3.0]]))
        assert ins == [["A", 5.0], ["B", 1.0], ["C", 3.0]]
        assert outs == [["A", 5.0]]  # greatest evicted when C arrives


class TestFrequentWindows:
    def test_frequent_keeps_heavy_hitters(self):
        q = ("from S#window.frequent(1, symbol) select symbol "
             "insert into OutputStream;")
        ins, _ = run(q, srows([["A", 1.0], ["A", 1.0], ["B", 1.0],
                               ["A", 1.0]]))
        # B never enters the top-1 heavy-hitter set and is suppressed
        assert [r[0] for r in ins] == ["A", "A", "A"]

    def test_lossy_frequent_runs(self):
        q = ("from S#window.lossyFrequent(0.5, 0.1, symbol) select symbol "
             "insert into OutputStream;")
        ins, _ = run(q, srows([["A", 1.0], ["A", 1.0], ["B", 1.0]]))
        assert [r[0] for r in ins][:2] == ["A", "A"]


class TestTimeLengthWindow:
    def test_bounded_by_both(self):
        q = ("from S#window.timeLength(1 sec, 2) select symbol "
             "insert all events into OutputStream;")
        # length bound evicts first when 3 arrive quickly
        ins, outs = run(q, srows(ROWS[:3], dt=50))
        assert [r[0] for r in ins] == ["A", "B", "C"]
        assert [r[0] for r in outs] == ["A"]


class TestHoppingWindow:
    def test_hop_flushes(self):
        q = ("from S#window.hopping(1 sec, 500 millisec) "
             "select sum(v) as s insert into OutputStream;")
        sends = [("S", ["A", 1.0], 1000), ("S", ["B", 2.0], 1400),
                 ("Tick", [1], 2600)]
        ins, _ = run(q, sends)
        assert len(ins) >= 1  # overlapping panes emit sums
        assert ins[0][0] == pytest.approx(3.0)


class TestCronAndExpressionWindows:
    def test_cron_window_flush(self):
        q = ("from S#window.cron('*/2 * * * * ?') select sum(v) as s "
             "insert into OutputStream;")
        sends = [("S", ["A", 1.0], 1000), ("S", ["B", 2.0], 1500),
                 ("Tick", [1], 3000)]  # a */2-second boundary passes
        ins, _ = run(q, sends)
        assert [r[0] for r in ins] == [3.0]

    def test_expression_window(self):
        # keep events while the expression holds (count-bounded here)
        q = ("from S#window.expression('count() <= 2') "
             "select symbol insert all events into OutputStream;")
        ins, outs = run(q, srows(ROWS[:3]))
        assert [r[0] for r in ins] == ["A", "B", "C"]
        assert [r[0] for r in outs] == ["A"]


class TestNamedWindowSharing:
    def test_two_queries_share_window(self):
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(
                "@app:playback " + DEFINE +
                "define window W (symbol string, v double) length(2); "
                "from S insert into W; "
                "@info(name='q1') from W select sum(v) as s "
                "insert into Out1; "
                "@info(name='q2') from W select count() as c "
                "insert into Out2;")
            got1, got2 = [], []
            rt.add_callback("Out1", lambda evs: got1.extend(e.data for e in evs))
            rt.add_callback("Out2", lambda evs: got2.extend(e.data for e in evs))
            rt.start()
            h = rt.get_input_handler("S")
            for i, r in enumerate(ROWS[:3]):
                h.send(r, timestamp=1000 + i * 100)
            rt.shutdown()
            # window default output is ALL events: the expired A retracts
            assert [g[0] for g in got1] == [1.0, 3.0, 5.0 - 1.0 + 1.0]
            assert [g[0] for g in got2] == [1, 2, 2]
        finally:
            m.shutdown()


class TestExternalTimeBatchReference:
    def test_batches_split_at_external_boundaries(self):
        # ExternalTimeBatchWindowTestCase.test1: batches [10s,15s),
        # [15s,20s), [20s,25s) flush when an event crosses the boundary
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(
                "@app:playback "
                "define stream I (currentTime long, value int); "
                "@info(name='q') from "
                "I#window.externalTimeBatch(currentTime, 5 sec) "
                "select value insert into O;")
            chunks = []
            rt.add_callback(
                "O", lambda evs: chunks.append([e.data[0] for e in evs]))
            rt.start()
            h = rt.get_input_handler("I")
            for t, v in [(10000, 1), (11000, 2), (12000, 3), (13000, 4),
                         (14000, 5), (15000, 6), (16500, 7), (17000, 8),
                         (18000, 9), (19000, 10), (20000, 11), (20500, 12),
                         (22000, 13), (25000, 14)]:
                h.send([t, v], timestamp=t)
            rt.shutdown()
            assert chunks == [[1, 2, 3, 4, 5], [6, 7, 8, 9, 10],
                              [11, 12, 13]]
        finally:
            m.shutdown()


class TestWindowEdgeMatrix:
    """Edge semantics of the trickier windows: session gaps, sort
    eviction, frequent/lossyFrequent approximate eviction, delay, and
    timeLength interplay (reference: query/processor/stream/window/*)."""

    def _run(self, query, sends, defs=None, out="O"):
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(
                "@app:playback "
                + (defs or "define stream S (k string, v long); ")
                + "define stream Tick (x int); "
                  "from Tick select x insert into _T; "
                + query)
            got = []
            rt.add_callback(out, lambda evs: got.extend(
                (list(e.data), int(e.timestamp)) for e in evs))
            rt.start()
            for stream, row, ts in sends:
                rt.get_input_handler(stream).send(row, timestamp=ts)
            rt.shutdown()
            return got
        finally:
            m.shutdown()

    def test_session_window_gap_closes_session(self):
        got = self._run(
            "@info(name='q') from S#window.session(1 sec, k) "
            "select k, sum(v) as total insert into O;",
            [("S", ["a", 1], 1000),
             ("S", ["a", 2], 1400),
             ("Tick", [1], 3000),     # gap > 1s: a's session closes
             ("S", ["a", 5], 3200)])  # new session
        # running sums while the session accumulates, reset after close
        vals = [row for row, _ in got]
        assert vals[0] == ["a", 1] and vals[1] == ["a", 3]
        assert vals[-1] == ["a", 5]

    def test_session_key_scopes_expiry_not_aggregation(self):
        # the session KEY groups events into sessions for gap expiry;
        # a selector without group-by still sums ALL live events
        got = self._run(
            "@info(name='q') from S#window.session(1 sec, k) "
            "select k, sum(v) as total insert into O;",
            [("S", ["a", 1], 1000),
             ("S", ["b", 10], 1100),
             ("S", ["a", 2], 1500)])
        vals = [row for row, _ in got]
        assert vals == [["a", 1], ["b", 11], ["a", 13]]

    def test_sort_window_evicts_extreme(self):
        # sort(2, v, 'asc') keeps the 2 SMALLEST v values; the CURRENT
        # event's row shows the pre-eviction sum (the EXPIRED eviction
        # follows it in the same chunk, reference chunk ordering)
        got = self._run(
            "@info(name='q') from S#window.sort(2, v, 'asc') "
            "select k, sum(v) as total insert into O;",
            [("S", ["a", 5], 1000),
             ("S", ["b", 1], 1100),
             ("S", ["c", 9], 1200),   # evicted in the same chunk
             ("S", ["d", 2], 1300)])  # evicts 5 -> buffer {1, 2}
        vals = [row for row, _ in got]
        assert vals == [["a", 5], ["b", 6], ["c", 15], ["d", 8]]

    def test_frequent_window_keeps_top_keys(self):
        got = self._run(
            "@info(name='q') from S#window.frequent(2, k) "
            "select k, count() as n insert into O;",
            [("S", ["a", 1], 1000),
             ("S", ["a", 1], 1100),
             ("S", ["b", 1], 1200),
             ("S", ["a", 1], 1300)])
        # two distinct frequent slots; 'a' stays counted throughout
        vals = [row for row, _ in got]
        assert vals[-1][0] == "a"

    def test_delay_window_emits_after_interval(self):
        got = self._run(
            "@info(name='q') from S#window.delay(1 sec) "
            "select k, v insert into O;",
            [("S", ["a", 1], 1000),
             ("Tick", [1], 2500)])
        # the delayed event surfaces once the watermark passes 2000,
        # keeping its ORIGINAL timestamp
        assert got == [(["a", 1], 1000)]

    def test_time_length_caps_both_axes(self):
        got = self._run(
            "@info(name='q') from S#window.timeLength(1 sec, 2) "
            "select sum(v) as total insert into O;",
            [("S", ["a", 1], 1000),
             ("S", ["b", 2], 1100),
             ("S", ["c", 4], 1200)])  # length cap 2: 'a' evicted
        vals = [row for row, _ in got]
        assert vals == [[1], [3], [6]]
