"""Named window + trigger conformance tests.

Modeled on the reference window/ (15 named-window test classes, e.g.
WindowTestCase, JoinWindowTestCase) and query/trigger/TriggerTestCase.
"""

import time

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.trigger import CronSchedule


@pytest.fixture
def manager():
    m = SiddhiManager()
    yield m
    m.shutdown()


def collect_stream(rt, stream):
    got = []
    rt.add_callback(stream, lambda events: got.extend(e.data for e in events))
    return got


def test_named_window_shared_by_queries(manager):
    app = (
        "define stream S (sym string, v int); "
        "define window W (sym string, v int) length(2) output all events; "
        "from S insert into W; "
        "@info(name='sum') from W select sum(v) as total insert into T;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    got = collect_stream(rt, "T")
    h = rt.get_input_handler("S")
    h.send(["a", 10])
    h.send(["b", 20])
    h.send(["c", 30])  # evicts a -> expired(a) reduces sum; window = {b, c}
    assert got[-1] == [50]


def test_named_window_join(manager):
    app = (
        "define stream S (sym string); "
        "define stream Q (sym string); "
        "define window W (sym string) length(5); "
        "from S insert into W; "
        "from Q join W as w on Q.sym == w.sym "
        "select Q.sym as sym insert into Out;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    got = collect_stream(rt, "Out")
    rt.get_input_handler("S").send(["X"])
    rt.get_input_handler("Q").send(["X"])
    rt.get_input_handler("Q").send(["Y"])
    assert got == [["X"]]


def test_window_cannot_get_input_handler(manager):
    app = (
        "define stream S (v int); "
        "define window W (v int) length(2); "
        "from S insert into W;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    with pytest.raises(Exception):
        rt.get_input_handler("W")


def test_start_trigger(manager):
    app = (
        "define trigger T at 'start'; "
        "from T select triggered_time insert into Out;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    got = collect_stream(rt, "Out")
    rt.start()
    assert len(got) == 1 and got[0][0] > 0


def test_periodic_trigger(manager):
    app = (
        "define trigger T at every 100 milliseconds; "
        "from T select triggered_time insert into Out;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    got = collect_stream(rt, "Out")
    rt.start()
    time.sleep(0.45)
    rt.shutdown()
    assert 2 <= len(got) <= 6
    times = [g[0] for g in got]
    assert times == sorted(times)


def test_trigger_feeds_queries_like_a_stream(manager):
    app = (
        "define stream S (v int); "
        "define trigger T at every 100 milliseconds; "
        "from T#window.length(1) join S#window.length(10) "
        "select S.v as v insert into Out;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    got = collect_stream(rt, "Out")
    rt.start()
    rt.get_input_handler("S").send([42])
    time.sleep(0.3)
    rt.shutdown()
    assert [42] in got


# -- cron schedule unit coverage (CronTrigger analog) -----------------------


def test_cron_every_five_seconds():
    c = CronSchedule("*/5 * * * * ?")
    t0 = 1_700_000_000_000  # some epoch ms
    f1 = c.next_fire(t0)
    assert f1 is not None and (f1 // 1000) % 5 == 0 and f1 > t0
    f2 = c.next_fire(f1)
    assert f2 - f1 == 5000


def test_cron_unix_five_field_daily():
    c = CronSchedule("30 2 * * *")  # 02:30:00 daily
    t0 = 1_700_000_000_000
    f1 = c.next_fire(t0)
    import datetime

    dt = datetime.datetime.fromtimestamp(f1 / 1000, datetime.timezone.utc)
    assert (dt.hour, dt.minute, dt.second) == (2, 30, 0)
    f2 = c.next_fire(f1)
    assert f2 - f1 == 86_400_000


def test_cron_day_of_week():
    c = CronSchedule("0 0 12 ? * MON")
    f1 = c.next_fire(1_700_000_000_000)
    import datetime

    dt = datetime.datetime.fromtimestamp(f1 / 1000, datetime.timezone.utc)
    assert dt.weekday() == 0 and dt.hour == 12
