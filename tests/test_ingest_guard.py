"""Tier-1 guard: every ingest-path H2D transfer goes through staging.

Thin shim over the ``ingest-put-bypass`` rule in ``siddhi_tpu.analysis``
(which absorbed this file's AST scanner, allowlist, and staleness
check).  The test names are stable tier-1 anchors; the contract and the
curated allowlist (staging/mesh/state buckets) now live in
``siddhi_tpu/analysis/rules/ingest_put.py`` and
``siddhi_tpu/analysis/allowlists.py``.
"""

from pathlib import Path

from siddhi_tpu.analysis import ModuleIndex, get_rule, index_package, run_rules

REPO = Path(__file__).resolve().parent.parent

RULE = "ingest-put-bypass"


def _run():
    indexes = index_package(REPO / "siddhi_tpu", REPO)
    return run_rules(indexes, [get_rule(RULE)])


def test_detector_sees_through_receiver_chains():
    src = ("import jax\n"
           "class E:\n"
           "    def a(self):\n"
           "        jax.device_put(1)\n"
           "    def b(self):\n"
           "        self.jax.device_put(1)\n")
    rule = get_rule(RULE)
    rule.begin()
    idx = ModuleIndex(Path("fixture.py"), "fixture.py", source=src)
    hits = [(f.line, f.scope) for f in rule.check(idx)]
    assert hits == [(4, "E.a"), (6, "E.b")]


def test_no_device_put_bypasses_ingest_staging():
    hits = [f for f in _run()["findings"] if f.rule == RULE]
    assert not hits, (
        "direct device_put outside the sanctioned staging/mesh/state "
        "sites — route batch ingest through core/ingest_stage.staged_put "
        "(fault site + counters), or allowlist it in "
        "siddhi_tpu/analysis/allowlists.py WITH a bucket justification:\n  "
        + "\n  ".join(f.render() for f in hits))


def test_allowlist_not_stale():
    """Allowlist entries expire: one that no longer matches a finding
    surfaces as a ``stale-allowlist`` finding — the list only shrinks."""
    stale = [f for f in _run()["findings"] if f.rule == "stale-allowlist"]
    assert not stale, "\n  ".join(f.render() for f in stale)
