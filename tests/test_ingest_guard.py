"""Tier-1 guard: every ingest-path H2D transfer goes through staging.

The ingest pipeline's contract is that host→device puts of BATCH data
happen ONLY through ``core/ingest_stage.py`` ``staged_put`` — the one
wrapper that arms the ``ingest.put`` fault-injection site (bounded
retry-with-backoff, crash-journal semantics) and counts
``IngestStats.device_puts``.  A future edit that calls
``jax.device_put`` directly on a batch path silently bypasses both the
fault harness and the staging counters: chaos runs stop covering that
transfer and the overlap evidence under-reports.

This test AST-scans the whole package and fails when a ``device_put``
call appears outside the curated allowlist.  Buckets:
  staging — the sanctioned wrapper itself
  mesh    — sharding helpers placing STATE rows on the mesh (one-time /
            barrier placement, not per-batch event data; faults on the
            sharded batch path still flow through staged_put in
            parallel/device_shard.py ``_put``)
  state   — engine state initialization / re-anchor barriers (same
            reasoning: not an ingest path, and arming ``ingest.put``
            there would skew the injector's per-batch fault cadence)
"""

import ast
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
PKG = REPO / "siddhi_tpu"

ALLOWED = {
    "siddhi_tpu/core/ingest_stage.py": {
        "staged_put",                                     # staging
    },
    "siddhi_tpu/parallel/mesh.py": {
        "ShardedPatternEngine._put",                      # mesh
    },
    "siddhi_tpu/ops/dense_nfa.py": {
        "DensePatternEngine.init_state",                  # state
        "DensePatternEngine.maybe_re_anchor",             # state
    },
}


def device_put_calls(source):
    """Yield (lineno, qualified enclosing function) for every
    ``*.device_put(...)`` call, regardless of the receiver chain
    (``jax.device_put``, ``self.jax.device_put``, ...)."""
    stack = []
    hits = []

    class V(ast.NodeVisitor):
        def _scoped(self, node):
            stack.append(node.name)
            self.generic_visit(node)
            stack.pop()

        visit_FunctionDef = _scoped
        visit_AsyncFunctionDef = _scoped
        visit_ClassDef = _scoped

        def visit_Call(self, node):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "device_put":
                hits.append((node.lineno, ".".join(stack) or "<module>"))
            self.generic_visit(node)

    V().visit(ast.parse(source))
    return hits


def test_detector_sees_through_receiver_chains():
    src = ("import jax\n"
           "class E:\n"
           "    def a(self):\n"
           "        jax.device_put(1)\n"
           "    def b(self):\n"
           "        self.jax.device_put(1)\n")
    assert device_put_calls(src) == [(4, "E.a"), (6, "E.b")]


def test_no_device_put_bypasses_ingest_staging():
    offenders = []
    for path in sorted(PKG.rglob("*.py")):
        rel = path.relative_to(REPO).as_posix()
        allowed = ALLOWED.get(rel, set())
        for lineno, qual in device_put_calls(path.read_text()):
            if qual not in allowed:
                offenders.append(f"{rel}:{lineno} device_put in {qual}()")
    assert not offenders, (
        "direct device_put outside the sanctioned staging/mesh/state "
        "sites — route batch ingest through core/ingest_stage.staged_put "
        "(fault site + counters), or add it to the allowlist WITH a "
        "bucket justification:\n  " + "\n  ".join(offenders))


def test_allowlist_not_stale():
    """Every allowlisted function still exists and still calls
    device_put — keeps the guard honest as the ingest paths evolve."""
    for rel, allowed in ALLOWED.items():
        path = REPO / rel
        assert path.exists(), f"guard list is stale: {rel} moved"
        live = {q for _ln, q in device_put_calls(path.read_text())}
        gone = allowed - live
        assert not gone, (f"{rel}: allowlisted entries no longer call "
                          f"device_put; prune them: {sorted(gone)}")
