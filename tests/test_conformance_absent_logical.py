"""Logical-absent pattern conformance, ported from the reference's
LogicalAbsentPatternTestCase.java (modules/siddhi-core/src/test/java/
io/siddhi/core/query/pattern/absent/): `and not` / `or not` with and
without `for` windows — including the or-race where a violation only
disables the absent branch and an unviolated window completes with
null present captures.
"""

import pytest

from siddhi_tpu import SiddhiManager

STREAMS = (
    "define stream Stream1 (symbol string, price float, volume int); "
    "define stream Stream2 (symbol string, price float, volume int); "
    "define stream Stream3 (symbol string, price float, volume int); "
    "define stream Tick (x int); "
)
TICK_SINK = "from Tick select x insert into IgnoredTicks; "


def run(query, sends, out="OutputStream"):
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime(
            "@app:playback " + STREAMS + TICK_SINK + query)
        got = []
        rt.add_callback(out, lambda evs: got.extend(list(e.data) for e in evs))
        rt.start()
        for stream, row, ts in sends:
            rt.get_input_handler(stream).send(row, timestamp=ts)
        rt.shutdown()
        return got
    finally:
        m.shutdown()


class TestAndNotUntimed:
    Q = ("@info(name='q') from e1=Stream1[price>10] -> "
         "not Stream2[price>20] and e3=Stream3[price>30] "
         "select e1.symbol as symbol1, e3.symbol as symbol3 "
         "insert into OutputStream;")

    def test_completes_on_present_side(self):
        # testQueryAbsent1
        got = run(self.Q, [
            ("Stream1", ["WSO2", 15.0, 100], 1000),
            ("Stream3", ["GOOGLE", 35.0, 100], 1100),
        ])
        assert got == [["WSO2", "GOOGLE"]]

    def test_absent_event_blocks(self):
        # testQueryAbsent2
        got = run(self.Q, [
            ("Stream1", ["WSO2", 15.0, 100], 1000),
            ("Stream2", ["IBM", 25.0, 100], 1100),
            ("Stream3", ["GOOGLE", 35.0, 100], 1200),
        ])
        assert got == []

    def test_leading_and_not(self):
        # testQueryAbsent3/4
        q = ("@info(name='q') from not Stream1[price>10] and "
             "e2=Stream2[price>20] -> e3=Stream3[price>30] "
             "select e2.symbol as symbol2, e3.symbol as symbol3 "
             "insert into OutputStream;")
        got = run(q, [
            ("Stream2", ["IBM", 25.0, 100], 1000),
            ("Stream3", ["GOOGLE", 35.0, 100], 1100),
        ])
        assert got == [["IBM", "GOOGLE"]]
        got = run(q, [
            ("Stream1", ["WSO2", 15.0, 100], 1000),
            ("Stream2", ["IBM", 25.0, 100], 1100),
            ("Stream3", ["GOOGLE", 35.0, 100], 1200),
        ])
        assert got == []


class TestAndNotTimed:
    Q = ("@info(name='q') from e1=Stream1[price>10] -> "
         "not Stream2[price>20] for 1 sec and e3=Stream3[price>30] "
         "select e1.symbol as symbol1, e3.symbol as symbol3 "
         "insert into OutputStream;")

    def test_present_after_window_completes(self):
        # testQueryAbsent5
        got = run(self.Q, [
            ("Stream1", ["WSO2", 15.0, 100], 1000),
            ("Stream3", ["GOOGLE", 35.0, 100], 2200),
        ])
        assert got == [["WSO2", "GOOGLE"]]

    def test_present_inside_window_defers_to_deadline(self):
        # testQueryAbsent5_1
        got = run(self.Q, [
            ("Stream1", ["WSO2", 15.0, 100], 1000),
            ("Stream3", ["GOOGLE", 35.0, 100], 1500),
            ("Tick", [1], 2700),
        ])
        assert got == [["WSO2", "GOOGLE"]]

    def test_violation_blocks_and(self):
        # testQueryAbsent7
        got = run(self.Q, [
            ("Stream1", ["WSO2", 15.0, 100], 1000),
            ("Stream2", ["IBM", 25.0, 100], 1100),
            ("Stream3", ["GOOGLE", 35.0, 100], 1200),
            ("Tick", [1], 3500),
        ])
        assert got == []


class TestOrNotTimed:
    Q = ("@info(name='q') from e1=Stream1[price>10] -> "
         "not Stream2[price>20] for 1 sec or e3=Stream3[price>30] "
         "select e1.symbol as symbol1, e3.symbol as symbol3 "
         "insert into OutputStream;")

    def test_present_side_wins_inside_window(self):
        # testQueryAbsent11
        got = run(self.Q, [
            ("Stream1", ["WSO2", 15.0, 100], 1000),
            ("Stream3", ["GOOGLE", 35.0, 100], 1100),
        ])
        assert got == [["WSO2", "GOOGLE"]]

    def test_absent_branch_wins_on_silence(self):
        # testQueryAbsent13: deadline passes with no e3 — null capture
        got = run(self.Q, [
            ("Stream1", ["WSO2", 15.0, 100], 1000),
            ("Tick", [1], 2500),
        ])
        assert got == [["WSO2", None]]

    def test_no_fire_before_deadline(self):
        # testQueryAbsent14
        got = run(self.Q, [
            ("Stream1", ["WSO2", 15.0, 100], 1000),
            ("Tick", [1], 1100),
        ])
        assert got == []

    def test_violation_leaves_present_branch_alive(self):
        # testQueryAbsent15: B disables the absent branch; C still wins
        got = run(self.Q, [
            ("Stream1", ["WSO2", 15.0, 100], 1000),
            ("Stream2", ["IBM", 25.0, 100], 1100),
            ("Stream3", ["GOOGLE", 35.0, 100], 1200),
            ("Tick", [1], 3500),
        ])
        assert got == [["WSO2", "GOOGLE"]]

    def test_violation_then_silence_never_fires(self):
        # testQueryAbsent16
        got = run(self.Q, [
            ("Stream1", ["WSO2", 15.0, 100], 1000),
            ("Stream2", ["IBM", 25.0, 100], 1100),
            ("Tick", [1], 2500),
        ])
        assert got == []

    def test_dense_mode_falls_back_and_matches(self):
        # or-absent stays on the host engine under execution('tpu');
        # output must be identical
        from siddhi_tpu.core.dense_pattern import DensePatternRuntime

        sends = [
            ("Stream1", ["WSO2", 15.0, 100], 1000),
            ("Tick", [1], 2500),
        ]
        host = run(self.Q, sends)
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(
                "@app:playback @app:execution('tpu') "
                + STREAMS + TICK_SINK + self.Q)
            got = []
            rt.add_callback(
                "OutputStream",
                lambda evs: got.extend(list(e.data) for e in evs))
            rt.start()
            for stream, row, ts in sends:
                rt.get_input_handler(stream).send(row, timestamp=ts)
            proc = rt.query_runtimes["q"].pattern_processor
            assert not isinstance(proc, DensePatternRuntime)
            rt.shutdown()
            assert got == host == [["WSO2", None]]
        finally:
            m.shutdown()
