"""Logical-absent pattern conformance, ported from the reference's
LogicalAbsentPatternTestCase.java (modules/siddhi-core/src/test/java/
io/siddhi/core/query/pattern/absent/): `and not` / `or not` with and
without `for` windows — including the or-race where a violation only
disables the absent branch and an unviolated window completes with
null present captures.
"""

import pytest

from siddhi_tpu import SiddhiManager

STREAMS = (
    "define stream Stream1 (symbol string, price float, volume int); "
    "define stream Stream2 (symbol string, price float, volume int); "
    "define stream Stream3 (symbol string, price float, volume int); "
    "define stream Tick (x int); "
)
TICK_SINK = "from Tick select x insert into IgnoredTicks; "


def run(query, sends, out="OutputStream"):
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime(
            "@app:playback " + STREAMS + TICK_SINK + query)
        got = []
        rt.add_callback(out, lambda evs: got.extend(list(e.data) for e in evs))
        rt.start()
        for stream, row, ts in sends:
            rt.get_input_handler(stream).send(row, timestamp=ts)
        rt.shutdown()
        return got
    finally:
        m.shutdown()


class TestAndNotUntimed:
    Q = ("@info(name='q') from e1=Stream1[price>10] -> "
         "not Stream2[price>20] and e3=Stream3[price>30] "
         "select e1.symbol as symbol1, e3.symbol as symbol3 "
         "insert into OutputStream;")

    def test_completes_on_present_side(self):
        # testQueryAbsent1
        got = run(self.Q, [
            ("Stream1", ["WSO2", 15.0, 100], 1000),
            ("Stream3", ["GOOGLE", 35.0, 100], 1100),
        ])
        assert got == [["WSO2", "GOOGLE"]]

    def test_absent_event_blocks(self):
        # testQueryAbsent2
        got = run(self.Q, [
            ("Stream1", ["WSO2", 15.0, 100], 1000),
            ("Stream2", ["IBM", 25.0, 100], 1100),
            ("Stream3", ["GOOGLE", 35.0, 100], 1200),
        ])
        assert got == []

    def test_leading_and_not(self):
        # testQueryAbsent3/4
        q = ("@info(name='q') from not Stream1[price>10] and "
             "e2=Stream2[price>20] -> e3=Stream3[price>30] "
             "select e2.symbol as symbol2, e3.symbol as symbol3 "
             "insert into OutputStream;")
        got = run(q, [
            ("Stream2", ["IBM", 25.0, 100], 1000),
            ("Stream3", ["GOOGLE", 35.0, 100], 1100),
        ])
        assert got == [["IBM", "GOOGLE"]]
        got = run(q, [
            ("Stream1", ["WSO2", 15.0, 100], 1000),
            ("Stream2", ["IBM", 25.0, 100], 1100),
            ("Stream3", ["GOOGLE", 35.0, 100], 1200),
        ])
        assert got == []


class TestAndNotTimed:
    Q = ("@info(name='q') from e1=Stream1[price>10] -> "
         "not Stream2[price>20] for 1 sec and e3=Stream3[price>30] "
         "select e1.symbol as symbol1, e3.symbol as symbol3 "
         "insert into OutputStream;")

    def test_present_after_window_completes(self):
        # testQueryAbsent5
        got = run(self.Q, [
            ("Stream1", ["WSO2", 15.0, 100], 1000),
            ("Stream3", ["GOOGLE", 35.0, 100], 2200),
        ])
        assert got == [["WSO2", "GOOGLE"]]

    def test_present_inside_window_defers_to_deadline(self):
        # testQueryAbsent5_1
        got = run(self.Q, [
            ("Stream1", ["WSO2", 15.0, 100], 1000),
            ("Stream3", ["GOOGLE", 35.0, 100], 1500),
            ("Tick", [1], 2700),
        ])
        assert got == [["WSO2", "GOOGLE"]]

    def test_violation_blocks_and(self):
        # testQueryAbsent7
        got = run(self.Q, [
            ("Stream1", ["WSO2", 15.0, 100], 1000),
            ("Stream2", ["IBM", 25.0, 100], 1100),
            ("Stream3", ["GOOGLE", 35.0, 100], 1200),
            ("Tick", [1], 3500),
        ])
        assert got == []


class TestOrNotTimed:
    Q = ("@info(name='q') from e1=Stream1[price>10] -> "
         "not Stream2[price>20] for 1 sec or e3=Stream3[price>30] "
         "select e1.symbol as symbol1, e3.symbol as symbol3 "
         "insert into OutputStream;")

    def test_present_side_wins_inside_window(self):
        # testQueryAbsent11
        got = run(self.Q, [
            ("Stream1", ["WSO2", 15.0, 100], 1000),
            ("Stream3", ["GOOGLE", 35.0, 100], 1100),
        ])
        assert got == [["WSO2", "GOOGLE"]]

    def test_absent_branch_wins_on_silence(self):
        # testQueryAbsent13: deadline passes with no e3 — null capture
        got = run(self.Q, [
            ("Stream1", ["WSO2", 15.0, 100], 1000),
            ("Tick", [1], 2500),
        ])
        assert got == [["WSO2", None]]

    def test_no_fire_before_deadline(self):
        # testQueryAbsent14
        got = run(self.Q, [
            ("Stream1", ["WSO2", 15.0, 100], 1000),
            ("Tick", [1], 1100),
        ])
        assert got == []

    def test_violation_leaves_present_branch_alive(self):
        # testQueryAbsent15: B disables the absent branch; C still wins
        got = run(self.Q, [
            ("Stream1", ["WSO2", 15.0, 100], 1000),
            ("Stream2", ["IBM", 25.0, 100], 1100),
            ("Stream3", ["GOOGLE", 35.0, 100], 1200),
            ("Tick", [1], 3500),
        ])
        assert got == [["WSO2", "GOOGLE"]]

    def test_violation_then_silence_never_fires(self):
        # testQueryAbsent16
        got = run(self.Q, [
            ("Stream1", ["WSO2", 15.0, 100], 1000),
            ("Stream2", ["IBM", 25.0, 100], 1100),
            ("Tick", [1], 2500),
        ])
        assert got == []

    def test_dense_mode_falls_back_and_matches(self):
        # or-absent stays on the host engine under execution('tpu');
        # output must be identical
        from siddhi_tpu.core.dense_pattern import DensePatternRuntime

        sends = [
            ("Stream1", ["WSO2", 15.0, 100], 1000),
            ("Tick", [1], 2500),
        ]
        host = run(self.Q, sends)
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(
                "@app:playback @app:execution('tpu') "
                + STREAMS + TICK_SINK + self.Q)
            got = []
            rt.add_callback(
                "OutputStream",
                lambda evs: got.extend(list(e.data) for e in evs))
            rt.start()
            for stream, row, ts in sends:
                rt.get_input_handler(stream).send(row, timestamp=ts)
            proc = rt.query_runtimes["q"].pattern_processor
            assert not isinstance(proc, DensePatternRuntime)
            rt.shutdown()
            assert got == host == [["WSO2", None]]
        finally:
            m.shutdown()


class TestEveryAbsent:
    """EveryAbsentPatternTestCase: `every not X for t` re-arms after
    each fire — one match per silent window, including catch-up when
    the watermark jumps several windows at once."""

    Q = ("@info(name='q') from e1=Stream1[price>20] -> "
         "every not Stream2[price>e1.price] for 1 sec "
         "select e1.symbol as symbol1 insert into OutputStream;")

    def test_fires_once_per_silent_window(self):
        # testQueryAbsent1: silence from 1000 to 4100 -> fires at
        # 2000, 3000, 4000
        got = run(self.Q, [
            ("Stream1", ["WSO2", 55.6, 100], 1000),
            ("Tick", [1], 4100),
        ])
        assert got == [["WSO2"], ["WSO2"], ["WSO2"]]

    def test_violation_kills_current_window_only(self):
        # testQueryAbsent4: fires at 2000/3000; B at 3100 kills the
        # pending window; nothing after
        got = run(self.Q, [
            ("Stream1", ["WSO2", 55.6, 100], 1000),
            ("Tick", [1], 3050),
            ("Stream2", ["IBM", 58.7, 100], 3100),
            ("Tick", [2], 4500),
        ])
        assert got == [["WSO2"], ["WSO2"]]

    def test_immediate_violation_blocks_all(self):
        # testQueryAbsent6
        got = run(self.Q, [
            ("Stream1", ["WSO2", 55.6, 100], 1000),
            ("Stream2", ["IBM", 58.7, 100], 1100),
            ("Tick", [1], 2500),
        ])
        assert got == []

    def test_non_matching_event_does_not_interrupt(self):
        # testQueryAbsent7: a Stream2 event FAILING the filter
        got = run(self.Q, [
            ("Stream1", ["WSO2", 55.6, 100], 1000),
            ("Stream2", ["IBM", 50.7, 100], 1100),
            ("Tick", [1], 3100),
        ])
        assert got == [["WSO2"], ["WSO2"]]

    def test_leading_every_absent(self):
        # testQueryAbsent5/8: every not S1 for 1s -> e2; two silent
        # windows elapse before each e2
        q = ("@info(name='q') from every not Stream1[price>20] for 1 sec "
             "-> e2=Stream2[price>30] "
             "select e2.symbol as symbol insert into OutputStream;")
        got = run(q, [
            ("Tick", [1], 3100),                     # windows at 1000, 2000, 3000
            ("Stream2", ["IBM", 58.7, 100], 3200),  # one e2: how many arms?
        ])
        # every re-arm: each elapsed window armed a waiting arm; the
        # single e2 completes ALL pending arms
        assert len(got) >= 1 and all(g == ["IBM"] for g in got)


class TestOrAbsentValidation:
    def test_double_absent_or_rejected(self):
        # two racing absences share one deadline/violation slot — the
        # engine rejects the shape instead of mishandling it
        m = SiddhiManager()
        try:
            with pytest.raises(Exception, match="two absent states"):
                m.create_siddhi_app_runtime(
                    STREAMS +
                    "@info(name='q') from e1=Stream3[price>10] -> "
                    "not Stream1[price>10] for 1 sec or "
                    "not Stream2[price>10] for 2 sec "
                    "select e1.price as p insert into OutputStream;")
        finally:
            m.shutdown()


class TestGroupEveryAbsentFallback:
    def test_group_every_with_absent_stays_on_host(self):
        # host: a violation kills the single group arm PERMANENTLY;
        # the dense arm-when-empty virgin would resurrect it
        from siddhi_tpu.core.dense_pattern import DensePatternRuntime

        q = ("@info(name='q') from every (e1=Stream1[price>10] -> "
             "not Stream2[price>20] for 1 sec) "
             "select e1.price as p insert into OutputStream;")
        sends = [
            ("Stream1", ["A", 15.0, 1], 1000),
            ("Stream2", ["K", 25.0, 1], 1500),   # violation kills arm
            ("Stream1", ["B", 16.0, 1], 3000),
            ("Tick", [1], 5000),
        ]
        host = run(q, sends)
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(
                "@app:playback @app:execution('tpu') "
                + STREAMS + TICK_SINK + q)
            got = []
            rt.add_callback(
                "OutputStream",
                lambda evs: got.extend(list(e.data) for e in evs))
            rt.start()
            for stream, row, ts in sends:
                rt.get_input_handler(stream).send(row, timestamp=ts)
            proc = rt.query_runtimes["q"].pattern_processor
            assert not isinstance(proc, DensePatternRuntime)
            rt.shutdown()
            assert got == host
        finally:
            m.shutdown()
