"""Widened device-query operator surface, differential vs host:

- stdDev / minForever / maxForever / and / or aggregators (reference:
  query/selector/attribute/aggregator/*.java) on running, sliding,
  and tumbling forms;
- LONG attributes in plain comparisons via bit-exact hi/lo int32 pair
  lanes (any magnitude) + the documented arithmetic fallback;
- BOOL attribute lanes;
- adversarial float32 drift fuzz pinning the device path's precision
  contract (ops/device_query.py module docstring: float32 accumulation
  is a documented subset of the host's float64).
"""

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.device_single import DeviceQueryRuntime

DEFS = ("define stream S (k long, v double, n long, ok bool); ")


def drive(app, sends, out="O"):
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime("@app:playback " + app)
        got = []
        rt.add_callback(out, lambda evs: got.extend(list(e.data) for e in evs))
        rt.start()
        h = rt.get_input_handler("S")
        for row, ts in sends:
            h.send(row, timestamp=ts)
        runtimes = [getattr(qr, "device_runtime", None)
                    for qr in rt.query_runtimes.values()]
        rt.shutdown()
        return got, runtimes
    finally:
        m.shutdown()


def differential(query, sends, expect_device=True, rel=1e-4):
    host, _ = drive(query, sends)
    dev, runtimes = drive("@app:execution('tpu') " + query, sends)
    dr = [r for r in runtimes if isinstance(r, DeviceQueryRuntime)]
    if expect_device:
        assert dr, f"did not lower: {query}"
    else:
        assert not dr, f"unexpectedly lowered: {query}"
    assert len(dev) == len(host), (host, dev)
    for i, (a, b) in enumerate(zip(host, dev)):
        for x, y in zip(a, b):
            if isinstance(x, float):
                assert y == pytest.approx(x, rel=rel, abs=1e-6), \
                    f"row {i}: {a} != {b}"
            else:
                assert x == y, f"row {i}: {a} != {b}"
    return dev


def mk_sends(n=40, seed=5):
    rng = np.random.default_rng(seed)
    return [([int(rng.integers(0, 4)), float(rng.integers(0, 50)),
              int(rng.integers(0, 10**12)), bool(rng.integers(0, 2))],
             1000 + i * 61)
            for i in range(n)]


class TestNewAggregators:
    @pytest.mark.parametrize("agg,alias", [
        ("stdDev(v)", "sd"), ("minForever(v)", "mf"),
        ("maxForever(v)", "xf"), ("and(ok)", "a"), ("or(ok)", "o"),
    ])
    def test_running(self, agg, alias):
        differential(
            DEFS + f"@info(name='q') from S select k, {agg} as {alias} "
            "group by k insert into O;", mk_sends())

    @pytest.mark.parametrize("agg,alias", [
        ("stdDev(v)", "sd"), ("minForever(v)", "mf"),
        ("maxForever(v)", "xf"), ("and(ok)", "a"), ("or(ok)", "o"),
    ])
    def test_length_window(self, agg, alias):
        differential(
            DEFS + f"@info(name='q') from S#window.length(3) select k, "
            f"{agg} as {alias} group by k insert into O;", mk_sends())

    @pytest.mark.parametrize("agg,alias", [
        ("stdDev(v)", "sd"), ("minForever(v)", "mf"), ("or(ok)", "o"),
    ])
    def test_time_window(self, agg, alias):
        differential(
            DEFS + f"@info(name='q') from S#window.time(300 ms) select k, "
            f"{agg} as {alias} group by k insert into O;", mk_sends())

    def test_tumbling_std_and_forever(self):
        # lengthBatch flush emits per-group rows (host and device order
        # groups differently within one flush: multiset compare);
        # forever values survive pane resets
        # (MinForeverAttributeAggregatorExecutor semantics)
        q = (DEFS + "@info(name='q') from S#window.lengthBatch(5) select "
             "k, stdDev(v) as sd, maxForever(v) as xf group by k "
             "insert into O;")
        sends = mk_sends(30)
        host, _ = drive(q, sends)
        dev, runtimes = drive("@app:execution('tpu') " + q, sends)
        assert any(isinstance(r, DeviceQueryRuntime) for r in runtimes)
        norm = lambda rows: sorted(
            tuple(round(x, 4) if isinstance(x, float) else x for x in r)
            for r in rows)
        assert norm(host) == norm(dev)
        assert host, "tumbling query emitted nothing"

    def test_distinct_count_falls_back(self):
        differential(
            DEFS + "@info(name='q') from S select k, distinctCount(v) "
            "as dc group by k insert into O;", mk_sends(),
            expect_device=False)

    def test_mixed_all_aggs_one_select(self):
        differential(
            DEFS + "@info(name='q') from S#window.length(4) select k, "
            "sum(v) as s, count() as c, avg(v) as av, min(v) as mn, "
            "max(v) as mx, stdDev(v) as sd, minForever(v) as mf, "
            "maxForever(v) as xf, and(ok) as b1, or(ok) as b2 "
            "group by k insert into O;", mk_sends(60))


class TestLongLanes:
    def test_long_filter_large_magnitudes(self):
        # > 2^32 constants: bit-exact hi/lo pair compares
        differential(
            DEFS + "@info(name='q') from S[n > 500000000000] "
            "select k, n insert into O;", mk_sends(60))

    def test_long_vs_long_attr_compare(self):
        differential(
            DEFS + "@info(name='q') from S[n != k] select k, n, v "
            "insert into O;", mk_sends())

    @pytest.mark.parametrize("op", ["==", "!=", "<", "<=", ">", ">="])
    def test_all_operators_boundary(self, op):
        # values straddling the int32 boundary and the exact constant
        c = 2**31 + 7
        sends = [([0, 0.0, x, True], 1000 + i) for i, x in enumerate([
            c - 1, c, c + 1, -c, 0, 2**40, -(2**40)])]
        differential(
            DEFS + f"@info(name='q') from S[n {op} {c}] select n "
            "insert into O;", sends)

    def test_long_arithmetic_falls_back(self):
        differential(
            DEFS + "@info(name='q') from S[n + 1 > 5] select k "
            "insert into O;", mk_sends(10), expect_device=False)

    def test_long_sum_falls_back(self):
        differential(
            DEFS + "@info(name='q') from S select k, sum(n) as s "
            "group by k insert into O;", mk_sends(10),
            expect_device=False)

    def test_bool_attr_filter(self):
        differential(
            DEFS + "@info(name='q') from S[ok] select k, v "
            "insert into O;", mk_sends())


class TestFloat32DriftContract:
    """Pin the float32 precision contract on adversarial inputs: the
    device path accumulates sums in float32 (MXU-native), so the
    guaranteed bound is |device - host| <= C * eps32 * sum(|x|) with
    C covering accumulation-order effects — NOT exact equality.
    min/max/count stay exact because inputs are float32-representable
    and comparisons do not accumulate."""

    EPS32 = 1.2e-7
    C = 64  # accumulation-order head-room

    def _run(self, sends, query):
        host, _ = drive(DEFS + query, sends)
        dev, runtimes = drive("@app:execution('tpu') " + DEFS + query, sends)
        assert any(isinstance(r, DeviceQueryRuntime) for r in runtimes)
        assert len(host) == len(dev)
        return host, dev

    def test_large_magnitude_sum_bounded_drift(self):
        rng = np.random.default_rng(3)
        # float32-representable magnitudes around 1e8
        vals = (rng.uniform(0.5e8, 1e8, 64).astype(np.float32)
                .astype(np.float64))
        sends = [([0, float(v), 0, True], 1000 + i)
                 for i, v in enumerate(vals)]
        host, dev = self._run(
            sends, "@info(name='q') from S select sum(v) as s insert into O;")
        budget = np.cumsum(np.abs(vals)) * self.EPS32 * self.C
        for i, (h, d) in enumerate(zip(host, dev)):
            assert abs(h[0] - d[0]) <= budget[i], (
                f"row {i}: drift {abs(h[0] - d[0])} over budget {budget[i]}")

    def test_cancellation_heavy_sum_bounded_drift(self):
        rng = np.random.default_rng(4)
        base = rng.uniform(0.5e8, 1e8, 32).astype(np.float32).astype(np.float64)
        vals = np.empty(64)
        vals[0::2] = base
        vals[1::2] = -base  # pairwise cancellation; true sum ~ 0
        sends = [([0, float(v), 0, True], 1000 + i)
                 for i, v in enumerate(vals)]
        host, dev = self._run(
            sends, "@info(name='q') from S select sum(v) as s insert into O;")
        budget = np.cumsum(np.abs(vals)) * self.EPS32 * self.C
        for i, (h, d) in enumerate(zip(host, dev)):
            assert abs(h[0] - d[0]) <= budget[i]

    def test_min_max_count_exact_on_adversarial_magnitudes(self):
        rng = np.random.default_rng(5)
        vals = (rng.uniform(-1e8, 1e8, 64).astype(np.float32)
                .astype(np.float64))
        sends = [([int(i % 3), float(v), 0, True], 1000 + i)
                 for i, v in enumerate(vals)]
        host, dev = self._run(
            sends,
            "@info(name='q') from S#window.length(5) select k, min(v) as "
            "mn, max(v) as mx, count() as c group by k insert into O;")
        for i, (h, d) in enumerate(zip(host, dev)):
            assert h == d, f"row {i}: {h} != {d}"

    def test_stddev_relative_error_on_spread_data(self):
        # stdDev uses the sum/sumsq decomposition: on data whose spread
        # is comparable to its magnitude the relative error stays small
        rng = np.random.default_rng(6)
        vals = rng.uniform(1e6, 3e6, 80)
        sends = [([0, float(v), 0, True], 1000 + i)
                 for i, v in enumerate(vals)]
        host, dev = self._run(
            sends,
            "@info(name='q') from S select stdDev(v) as sd insert into O;")
        for i, (h, d) in enumerate(zip(host, dev)):
            if i < 2:
                continue  # n<2: stddev ~ 0, relative error meaningless
            assert d[0] == pytest.approx(h[0], rel=2e-3), f"row {i}"


class TestOrderByLimitOnDevicePath:
    """Round 5: order by / limit / offset ride the host passthrough
    selector over device-emitted chunks — per-chunk semantics identical
    to the host engine's _order_limit position."""

    def test_order_by_lowers_and_matches(self):
        differential(
            DEFS + "@info(name='q') from S#window.lengthBatch(4) select "
            "k, sum(v) as s group by k order by s desc "
            "insert into O;", mk_sends(32))

    def test_limit_offset(self):
        differential(
            DEFS + "@info(name='q') from S#window.lengthBatch(6) select "
            "k, count() as c group by k order by c desc, k asc limit 2 "
            "insert into O;", mk_sends(36))


class TestRateLimitersOnDevicePath:
    """Round 5: per-group first/last and snapshot output rates lower —
    the device runtime attaches the host selector's group-key side
    channel (batch.aux['group_keys']) to emitted chunks."""

    def test_per_group_first_every_n(self):
        differential(
            DEFS + "@info(name='q') from S select k, sum(v) as s "
            "group by k output first every 3 events insert into O;",
            mk_sends(40))

    def test_per_group_last_every_time(self):
        differential(
            DEFS + "@info(name='q') from S select k, sum(v) as s "
            "group by k output last every 500 ms insert into O;",
            mk_sends(40))

    def test_snapshot_rate(self):
        differential(
            DEFS + "@info(name='q') from S select k, sum(v) as s "
            "group by k output snapshot every 400 ms insert into O;",
            mk_sends(40))

    def test_group_keys_aux_reaches_rate_limiter(self):
        """The side channel must be visible at the rate-limiter position
        (the same place the host selector's aux is consumed)."""
        from siddhi_tpu import SiddhiManager

        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(
                "@app:playback @app:execution('tpu') " + DEFS +
                "@info(name='q') from S select k, sum(v) as s group by k "
                "insert into O;")
            qr = rt.query_runtimes["q"]
            seen = []
            orig = qr.rate_limiter.process

            def spy(batch, now):
                seen.append(list(batch.aux.get("group_keys") or []))
                return orig(batch, now)

            qr.rate_limiter.process = spy
            rt.start()
            h = rt.get_input_handler("S")
            h.send([7, 1.0, 0, True], timestamp=1000)
            h.send([9, 2.0, 0, True], timestamp=1001)
            rt.shutdown()
            assert seen == [[7], [9]], seen
        finally:
            m.shutdown()
