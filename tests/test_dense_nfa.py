"""Dense (TPU-path) NFA validation against the host engine.

Same event sequences through `compile_pattern` (jitted dense step, CPU
backend under tests) and through the full host engine — match counts and
captured values must agree on the dense-eligible subset.
"""

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.ops.dense_nfa import compile_pattern

FRAUD_APP = (
    "define stream Txn (card long, amount double); "
    "@info(name='fraud') "
    "from every a=Txn[amount > 100.0] -> b=Txn[amount > a.amount]<3:5> within 10 min "
    "select a.amount as base, b[0].amount as b0, b[last].amount as blast "
    "insert into Alerts;"
)


def host_matches(app, sends):
    """sends: (key:int, amount, ts) — run per-key via separate partitions
    emulated by filtering; here single-partition runs per key."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    got = []
    rt.add_callback("Alerts", lambda evs: got.extend(evs))
    rt.start()
    h = rt.get_input_handler("Txn")
    for key, amount, ts in sends:
        h.send([key, amount], timestamp=ts)
    rt.shutdown()
    m.shutdown()
    return got


class TestDenseFraud:
    def test_matches_host_single_partition(self):
        eng = compile_pattern(FRAUD_APP, "fraud", n_partitions=8)
        state = eng.init_state()
        sends = [
            (0, 150.0, 1000),
            (0, 200.0, 2000),
            (0, 50.0, 2500),   # fails b filter (not > 200? it's > a=150... careful)
            (0, 250.0, 3000),
            (0, 300.0, 4000),
        ]
        # NOTE: b filter is amount > a.amount (a=150): 200,250,300 match; 50 doesn't
        part = np.asarray([s[0] for s in sends])
        cols = {"amount": np.asarray([s[1] for s in sends], dtype=np.float64),
                "card": np.asarray([float(s[0]) for s in sends])}
        ts = np.asarray([s[2] for s in sends], dtype=np.int64)
        state, emit, out = eng.process(state, "Txn", part, cols, ts)
        host = host_matches(FRAUD_APP, sends)
        assert len(emit) == len(host) == 1
        out_row = out[0]
        names = eng.output_names
        host_row = host[0].data
        # base, b0, blast
        assert out_row[0] == pytest.approx(host_row[0])
        assert out_row[1] == pytest.approx(host_row[1])
        assert out_row[2] == pytest.approx(host_row[2])

    def test_within_expiry_matches_host(self):
        eng = compile_pattern(FRAUD_APP, "fraud", n_partitions=8)
        state = eng.init_state()
        sends = [
            (0, 150.0, 1000),
            (0, 200.0, 2000),
            # gap beyond 10 min: expires partial
            (0, 250.0, 700_000),
            (0, 260.0, 701_000),
            (0, 270.0, 702_000),
            (0, 280.0, 703_000),
        ]
        part = np.asarray([s[0] for s in sends])
        cols = {"amount": np.asarray([s[1] for s in sends]),
                "card": np.asarray([float(s[0]) for s in sends])}
        ts = np.asarray([s[2] for s in sends], dtype=np.int64)
        state, emit, out = eng.process(state, "Txn", part, cols, ts)
        host = host_matches(FRAUD_APP, sends)
        assert len(emit) == len(host)

    def test_multi_partition_isolation(self):
        eng = compile_pattern(FRAUD_APP, "fraud", n_partitions=16)
        state = eng.init_state()
        # interleave two cards; only card 3 escalates
        sends = [
            (3, 150.0, 1000), (7, 500.0, 1100),
            (3, 200.0, 1200), (7, 100.0, 1300),
            (3, 250.0, 1400), (7, 90.0, 1500),
            (3, 300.0, 1600), (7, 80.0, 1700),
        ]
        part = np.asarray([s[0] for s in sends])
        cols = {"amount": np.asarray([s[1] for s in sends]),
                "card": np.asarray([float(s[0]) for s in sends])}
        ts = np.asarray([s[2] for s in sends], dtype=np.int64)
        state, emit, out = eng.process(state, "Txn", part, cols, ts)
        assert len(emit) == 1
        assert out[0][0] == pytest.approx(150.0)

    def test_brute_force_kleene(self):
        app = (
            "define stream Login (user long, ok int); "
            "@info(name='bf') "
            "from every f=Login[ok == 0]<3:100> -> s=Login[ok == 1] within 1 min "
            "select f[0].ok as f0, s.ok as sk insert into Alerts;"
        )
        eng = compile_pattern(app, "bf", n_partitions=32)
        state = eng.init_state()
        # user 5: 3 fails then success -> 1 match; user 9: 2 fails + success -> 0
        sends = [(5, 0), (9, 0), (5, 0), (9, 0), (5, 0), (5, 1), (9, 1)]
        part = np.asarray([s[0] for s in sends])
        cols = {"ok": np.asarray([float(s[1]) for s in sends]),
                "user": np.asarray([float(s[0]) for s in sends])}
        ts = np.arange(1000, 1000 + len(sends), dtype=np.int64) * 10
        state, emit, out = eng.process(state, "Login", part, cols, ts)
        assert len(emit) == 1

    def test_logical_and_two_streams(self):
        app = (
            "define stream Tick (sym long, price double); "
            "define stream News (sym long, score double); "
            "@info(name='an') "
            "from t=Tick[price > 10.0] and n=News[score > 0.5] within 5 sec "
            "select t.price as p, n.score as sc insert into Alerts;"
        )
        eng = compile_pattern(app, "an", n_partitions=8, every_start=True)
        state = eng.init_state()
        # partition 2: tick then news within window -> match
        state, e1, _ = eng.process(
            state, "Tick", np.asarray([2]), {"price": np.asarray([20.0])},
            np.asarray([1000], dtype=np.int64))
        assert len(e1) == 0
        state, e2, out = eng.process(
            state, "News", np.asarray([2]), {"score": np.asarray([0.9])},
            np.asarray([2000], dtype=np.int64))
        assert len(e2) == 1
        # partition 4: news too late
        state, _, _ = eng.process(
            state, "Tick", np.asarray([4]), {"price": np.asarray([20.0])},
            np.asarray([10_000], dtype=np.int64))
        state, e3, _ = eng.process(
            state, "News", np.asarray([4]), {"score": np.asarray([0.9])},
            np.asarray([20_000], dtype=np.int64))
        assert len(e3) == 0

    def test_batch_collision_rounds(self):
        # duplicate partitions in one batch must process in order
        eng = compile_pattern(FRAUD_APP, "fraud", n_partitions=4)
        state = eng.init_state()
        sends = [(1, 150.0), (1, 200.0), (1, 250.0), (1, 300.0), (1, 350.0)]
        part = np.asarray([s[0] for s in sends])
        cols = {"amount": np.asarray([s[1] for s in sends]),
                "card": np.ones(len(sends))}
        ts = np.arange(1000, 1000 + len(sends), dtype=np.int64)
        state, emit, out = eng.process(state, "Txn", part, cols, ts)
        assert len(emit) == 1


SEQ_APP = (
    "define stream Ticks (key long, price double); "
    "@info(name='seq3') "
    "from every e1=Ticks[price > 10.0], e2=Ticks[price > e1.price], "
    "e3=Ticks[price > e2.price] within 1 sec "
    "select e1.price as p1, e2.price as p2, e3.price as p3 "
    "insert into Alerts;"
)


class TestDenseSequence:
    """Strict-continuity sequences on the dense path (BASELINE config #1:
    3-state `e1, e2, e3 within 1 sec`), validated against the host
    engine."""

    def _dense(self, sends, app=SEQ_APP, name="seq3"):
        eng = compile_pattern(app, name, n_partitions=8)
        state = eng.init_state()
        part = np.asarray([s[0] for s in sends])
        cols = {"price": np.asarray([s[1] for s in sends], dtype=np.float64),
                "key": np.asarray([float(s[0]) for s in sends])}
        ts = np.asarray([s[2] for s in sends], dtype=np.int64)
        state, emit, out = eng.process(state, "Ticks", part, cols, ts)
        return emit, out

    def _host(self, sends, app=SEQ_APP):
        m = SiddhiManager()
        rt = m.create_siddhi_app_runtime(app)
        got = []
        rt.add_callback("Alerts", lambda evs: got.extend(evs))
        rt.start()
        h = rt.get_input_handler("Ticks")
        for key, price, ts in sends:
            h.send([key, price], timestamp=ts)
        rt.shutdown()
        m.shutdown()
        return got

    def test_rising_triple_matches_host(self):
        sends = [(0, 11.0, 100), (0, 12.0, 200), (0, 13.0, 300)]
        emit, out = self._dense(sends)
        host = self._host(sends)
        assert len(emit) == len(host) == 1
        assert out[0].tolist() == pytest.approx(host[0].data)

    def test_interruption_kills_and_restarts(self):
        # 11,12 then a drop (5) breaks continuity; 20,21,22 completes
        sends = [(0, 11.0, 100), (0, 12.0, 200), (0, 5.0, 300),
                 (0, 20.0, 400), (0, 21.0, 500), (0, 22.0, 600)]
        emit, out = self._dense(sends)
        host = self._host(sends)
        assert len(emit) == len(host) == 1
        assert out[0].tolist() == pytest.approx(host[0].data)  # 20,21,22

    def test_within_expires_sequence(self):
        sends = [(0, 11.0, 100), (0, 12.0, 200), (0, 13.0, 5000)]
        emit, out = self._dense(sends)
        host = self._host(sends)
        assert len(emit) == len(host) == 0

    def test_per_partition_isolation(self):
        sends = [(0, 11.0, 100), (1, 50.0, 150), (0, 12.0, 200),
                 (1, 51.0, 250), (0, 13.0, 300), (1, 52.0, 350)]
        emit, out = self._dense(sends)
        # each key independently completes its own rising triple
        assert len(emit) == 2

    def test_randomized_agreement_with_host(self):
        rng = np.random.default_rng(11)
        sends = [(0, float(p), 100 * (i + 1))
                 for i, p in enumerate(rng.uniform(5.0, 30.0, 40).round(1))]
        emit, out = self._dense(sends)
        host = self._host(sends)
        assert len(emit) == len(host)
        dense_rows = [r.tolist() for r in out]
        host_rows = [e.data for e in host]
        for d, h in zip(dense_rows, host_rows):
            assert d == pytest.approx(h)


class TestDenseNonEverySequence:
    def test_non_every_dies_after_interruption(self):
        # reference semantics (SequenceTestCase.testQuery31): a non-every
        # sequence arms ONCE; 11 advances, 5 kills the pending instance,
        # and nothing re-arms — 20,21,22 must NOT match
        app = (
            "define stream Ticks (key long, price double); "
            "@info(name='ne') "
            "from e1=Ticks[price > 10.0], e2=Ticks[price > e1.price], "
            "e3=Ticks[price > e2.price] within 1 sec "
            "select e1.price as p1, e3.price as p3 insert into Alerts;"
        )
        sends = [(0, 11.0, 100), (0, 5.0, 200), (0, 20.0, 300),
                 (0, 21.0, 400), (0, 22.0, 500), (0, 23.0, 600)]
        eng = compile_pattern(app, "ne", n_partitions=4)
        state = eng.init_state()
        part = np.asarray([s[0] for s in sends])
        cols = {"price": np.asarray([s[1] for s in sends]),
                "key": np.zeros(len(sends))}
        ts = np.asarray([s[2] for s in sends], dtype=np.int64)
        state, emit, out = eng.process(state, "Ticks", part, cols, ts)

        m = SiddhiManager()
        rt = m.create_siddhi_app_runtime(app)
        host = []
        rt.add_callback("Alerts", lambda evs: host.extend(evs))
        rt.start()
        h = rt.get_input_handler("Ticks")
        for k, p, t in sends:
            h.send([k, p], timestamp=t)
        rt.shutdown()
        m.shutdown()
        assert len(emit) == len(host) == 0


class TestReAnchor:
    def test_rel_ts_re_anchor_past_int32(self):
        """Streams past ~24.8 days of relative time re-anchor the base
        instead of wrapping int32; expired armed instances are cleared."""
        app = (
            "define stream Txn (card long, amount double); "
            "@info(name='q') "
            "from every a=Txn[amount > 100.0] -> b=Txn[amount > a.amount] "
            "within 10 min "
            "select a.amount as base, b.amount as bv insert into Alerts;"
        )
        eng = compile_pattern(app, "q", n_partitions=4)
        state = eng.init_state()

        def send(state, amount, ts):
            return eng.process(
                state, "Txn", np.asarray([0]),
                {"amount": np.asarray([amount]),
                 "card": np.asarray([0.0])},
                np.asarray([ts], dtype=np.int64))

        state, emit, _ = send(state, 150.0, 1_000)      # arms a=150
        assert len(emit) == 0
        base0 = eng.base_ts
        far = 1_000 + 3_000_000_000                      # ~34 days later
        state, emit, _ = send(state, 200.0, far)         # old arm expired
        assert eng.base_ts > base0
        assert len(emit) == 0                            # 200 only re-arms a
        state, emit, out = send(state, 250.0, far + 50)  # completes a->b
        assert len(emit) == 1
        row = dict(zip(eng.output_names, out[0]))
        assert row["base"] == 200.0 and row["bv"] == 250.0
