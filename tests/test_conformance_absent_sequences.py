"""Sequence-absent conformance, ported from the reference's
AbsentSequenceTestCase.java (modules/siddhi-core/src/test/java/io/
siddhi/core/query/sequence/absent/): `not X for t` inside strict
sequences — trailing, leading and mid-chain absence, interaction with
logical nodes and Kleene counts.  Thread.sleep gaps become playback
timestamp gaps; expectations are the reference's event counts/rows.
"""

import pytest

from siddhi_tpu import SiddhiManager

STREAMS = (
    "define stream Stream1 (symbol string, price float, volume int); "
    "define stream Stream2 (symbol string, price float, volume int); "
    "define stream Stream3 (symbol string, price float, volume int); "
    "define stream Stream4 (symbol string, price float, volume int); "
    "define stream Tick (x int); "
)
TICK_SINK = "from Tick select x insert into IgnoredTicks; "


def run(query, sends, out="OutputStream"):
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime(
            "@app:playback " + STREAMS + TICK_SINK + query)
        got = []
        rt.add_callback(out, lambda evs: got.extend(list(e.data) for e in evs))
        rt.start()
        for stream, row, ts in sends:
            rt.get_input_handler(stream).send(row, timestamp=ts)
        rt.shutdown()
        return got
    finally:
        m.shutdown()


class TestTrailingAbsentSequence:
    Q = ("@info(name='q') from e1=Stream1[price>20], "
         "not Stream2[price>e1.price] for 1 sec "
         "select e1.symbol as symbol1 insert into OutputStream;")

    def test_fires_when_nothing_arrives(self):
        # testQueryAbsent1
        got = run(self.Q, [
            ("Stream1", ["WSO2", 55.6, 100], 1000),
            ("Tick", [1], 2500),
        ])
        assert got == [["WSO2"]]

    def test_late_event_does_not_cancel(self):
        # testQueryAbsent2: Stream2 after the window
        got = run(self.Q, [
            ("Stream1", ["WSO2", 55.6, 100], 1000),
            ("Stream2", ["IBM", 58.7, 100], 2100),
        ])
        assert got == [["WSO2"]]

    def test_matching_event_within_window_cancels(self):
        # testQueryAbsent3
        got = run(self.Q, [
            ("Stream1", ["WSO2", 55.6, 100], 1000),
            ("Stream2", ["IBM", 58.7, 100], 1100),
            ("Tick", [1], 2500),
        ])
        assert got == []

    def test_non_matching_event_keeps_waiting(self):
        # testQueryAbsent4: filter fails (50.7 < 55.6) — still fires
        got = run(self.Q, [
            ("Stream1", ["WSO2", 55.6, 100], 1000),
            ("Stream2", ["IBM", 50.7, 100], 1100),
            ("Tick", [1], 2500),
        ])
        assert got == [["WSO2"]]

    def test_kleene_plus_then_absent(self):
        # testQueryAbsent36: e1+ keeps collecting, then absence fires
        q = ("@info(name='q') from e1=Stream1[price>10]+, "
             "not Stream2[price>20] for 1 sec "
             "select e1[0].symbol as s0, e1[1].symbol as s1, "
             "e1[2].symbol as s2, e1[3].symbol as s3 "
             "insert into OutputStream;")
        got = run(q, [
            ("Stream1", ["ORACLE", 25.0, 100], 1000),
            ("Stream1", ["WSO2", 35.0, 100], 1100),
            ("Stream1", ["IBM", 45.0, 100], 1200),
            ("Tick", [1], 2500),
        ])
        assert len(got) == 1


class TestLeadingAbsentSequence:
    Q = ("@info(name='q') from not Stream1[price>20] for 1 sec, "
         "e2=Stream2[price>30] "
         "select e2.symbol as symbol insert into OutputStream;")

    def test_fires_after_silent_window(self):
        # testQueryAbsent5: nothing on Stream1 for 1s, then e2
        got = run(self.Q, [
            ("Tick", [1], 2200),
            ("Stream2", ["IBM", 58.7, 100], 2300),
        ])
        assert got == [["IBM"]]

    def test_event_during_window_blocks(self):
        # testQueryAbsent8-style: matching Stream1 inside the window
        got = run(self.Q, [
            ("Stream1", ["WSO2", 55.6, 100], 1100),
            ("Stream2", ["IBM", 58.7, 100], 1200),
        ])
        assert got == []

    def test_e2_before_window_elapses_blocks(self):
        # testQueryAbsent27: e2 arrives before the 1s silence completes
        got = run(self.Q, [
            ("Stream2", ["IBM", 58.7, 100], 500),
        ])
        assert got == []

    def test_non_matching_stream1_event_ok(self):
        # testQueryAbsent17: a Stream1 event FAILING the filter arrives
        # DURING the silence window (deadline = start + 1s = 1000) and
        # doesn't violate the absence
        got = run(self.Q.replace("price>20", "price>10"), [
            ("Stream1", ["WSO2", 5.6, 100], 500),
            ("Stream2", ["IBM", 58.7, 100], 1100),
        ])
        assert got == [["IBM"]]

    def test_sequence_not_restarted_once_blocked(self):
        # testQueryAbsent6: violation during the first window kills the
        # non-every sequence permanently
        got = run(self.Q.replace("price>20", "price>10"), [
            ("Stream1", ["WSO2", 59.6, 100], 1100),
            ("Stream2", ["IBM", 58.7, 100], 3200),
        ])
        assert got == []


class TestMidChainAbsentSequence:
    Q = ("@info(name='q') from e1=Stream1[price>10], "
         "not Stream2[price>20] for 1 sec, e3=Stream3[price>30] "
         "select e1.symbol as symbol1, e3.symbol as symbol3 "
         "insert into OutputStream;")

    def test_waits_out_window_then_third(self):
        # testQueryAbsent12
        got = run(self.Q, [
            ("Stream1", ["WSO2", 15.6, 100], 1000),
            ("Tick", [1], 2100),
            ("Stream3", ["GOOGLE", 55.7, 100], 2200),
        ])
        assert got == [["WSO2", "GOOGLE"]]

    def test_non_matching_absent_event_keeps_chain(self):
        # testQueryAbsent13
        got = run(self.Q, [
            ("Stream1", ["WSO2", 15.6, 100], 1000),
            ("Stream2", ["IBM", 8.7, 100], 1100),
            ("Tick", [1], 2200),
            ("Stream3", ["GOOGLE", 55.7, 100], 2300),
        ])
        assert got == [["WSO2", "GOOGLE"]]

    def test_violation_kills_chain(self):
        # testQueryAbsent14/38
        got = run(self.Q, [
            ("Stream1", ["WSO2", 15.6, 100], 1000),
            ("Stream2", ["IBM", 28.7, 100], 1100),
            ("Tick", [1], 2300),
            ("Stream3", ["GOOGLE", 55.7, 100], 2400),
        ])
        assert got == []

    def test_absent_then_logical_and(self):
        # testQueryAbsent28
        q = ("@info(name='q') from e1=Stream1[price>10], "
             "not Stream2[price>20] for 1 sec, "
             "e2=Stream3[price>30] and e3=Stream4[price>40] "
             "select e1.symbol as symbol1, e2.symbol as symbol2, "
             "e3.symbol as symbol3 insert into OutputStream;")
        got = run(q, [
            ("Stream1", ["IBM", 18.7, 100], 1000),
            ("Tick", [1], 2200),
            ("Stream3", ["WSO2", 35.0, 100], 2300),
            ("Stream4", ["GOOGLE", 56.86, 100], 2400),
        ])
        assert got == [["IBM", "WSO2", "GOOGLE"]]

    def test_absent_then_logical_or_either_side(self):
        # testQueryAbsent30/31
        q = ("@info(name='q') from e1=Stream1[price>10], "
             "not Stream2[price>20] for 1 sec, "
             "e2=Stream3[price>30] or e3=Stream4[price>40] "
             "select e1.symbol as symbol1, e2.symbol as symbol2, "
             "e3.symbol as symbol3 insert into OutputStream;")
        got = run(q, [
            ("Stream1", ["IBM", 18.7, 100], 1000),
            ("Tick", [1], 2200),
            ("Stream3", ["WSO2", 35.0, 100], 2300),
        ])
        assert got == [["IBM", "WSO2", None]]
        got = run(q, [
            ("Stream1", ["IBM", 18.7, 100], 1000),
            ("Tick", [1], 2200),
            ("Stream4", ["GOOGLE", 56.86, 100], 2300),
        ])
        assert got == [["IBM", None, "GOOGLE"]]

    def test_trailing_absent_after_three_states(self):
        # testQueryAbsent19/20
        q = ("@info(name='q') from e1=Stream1[price>10], "
             "e2=Stream2[price>20], e3=Stream3[price>30], "
             "not Stream4[price>40] for 1 sec "
             "select e1.symbol as symbol1, e2.symbol as symbol2, "
             "e3.symbol as symbol3 insert into OutputStream;")
        base = [
            ("Stream1", ["WSO2", 15.6, 100], 1000),
            ("Stream2", ["IBM", 28.7, 100], 1100),
            ("Stream3", ["GOOGLE", 35.7, 100], 1200),
        ]
        got = run(q, base + [("Tick", [1], 2500)])
        assert got == [["WSO2", "IBM", "GOOGLE"]]
        got = run(q, base + [
            ("Stream4", ["ORACLE", 44.7, 100], 1300),
            ("Tick", [1], 2500),
        ])
        assert got == []


class TestEveryAbsentSequence:
    """EveryAbsentSequenceTestCase: `every not X for t` leading a strict
    sequence — re-arming silence windows feeding the next state."""

    Q = ("@info(name='q') from every not Stream1[price>20] for 1 sec, "
         "e2=Stream2[price>30] "
         "select e2.symbol as symbol insert into OutputStream;")

    def test_two_matches_across_rearm(self):
        # testQueryAbsent2: silence windows complete before each e2
        got = run(self.Q, [
            ("Tick", [1], 2200),
            ("Stream2", ["IBM", 58.7, 100], 2300),
            ("Tick", [2], 3500),
            ("Stream2", ["WSO2", 68.7, 100], 3600),
        ])
        assert got == [["IBM"], ["WSO2"]]

    def test_violation_then_silent_window_recovers(self):
        # testQueryAbsent3: the every re-arms after the violated window
        got = run(self.Q, [
            ("Stream1", ["WSO2", 59.6, 100], 1000),
            ("Tick", [1], 3100),
            ("Stream2", ["IBM", 58.7, 100], 3200),
        ])
        assert got == [["IBM"]]

    def test_continuous_violations_block(self):
        # testQueryAbsent4: a matching Stream1 event every 500ms keeps
        # every window violated
        got = run(self.Q.replace("price>20", "price>10"), [
            ("Stream1", ["WSO2", 25.6, 100], 1000),
            ("Stream1", ["WSO2", 25.6, 100], 1500),
            ("Stream1", ["WSO2", 25.6, 100], 2000),
            ("Stream2", ["IBM", 58.7, 100], 2500),
        ])
        assert got == []

    def test_e2_before_any_window_completes_blocks(self):
        # testQueryAbsent5-style
        got = run(self.Q, [
            ("Stream1", ["WSO2", 55.6, 100], 1000),
            ("Stream2", ["IBM", 58.7, 100], 1100),
        ])
        assert got == []

    def test_three_state_after_silence(self):
        # testQueryAbsent8
        q = ("@info(name='q') from every not Stream1[price>10] for 1 sec, "
             "e2=Stream2[price>20], e3=Stream3[price>30] "
             "select e2.symbol as symbol2, e3.symbol as symbol3 "
             "insert into OutputStream;")
        got = run(q, [
            ("Tick", [1], 2100),
            ("Stream2", ["IBM", 28.7, 100], 2200),
            ("Stream3", ["GOOGLE", 55.7, 100], 2300),
        ])
        assert got == [["IBM", "GOOGLE"]]

    def test_violation_mid_chain_blocks(self):
        # testQueryAbsent7: Stream1 violates during the leading window
        q = ("@info(name='q') from every not Stream1[price>10] for 1 sec, "
             "e2=Stream2[price>20], e3=Stream3[price>30] "
             "select e2.symbol as symbol2, e3.symbol as symbol3 "
             "insert into OutputStream;")
        got = run(q, [
            ("Stream1", ["WSO2", 15.6, 100], 1000),
            ("Stream2", ["IBM", 28.7, 100], 1100),
            ("Stream3", ["GOOGLE", 55.7, 100], 1200),
        ])
        assert got == []


class TestLogicalAbsentSequence:
    """LogicalAbsentSequenceTestCase: and-not / or-not nodes inside
    strict sequences (untimed and timed)."""

    def test_and_not_untimed(self):
        # testQueryAbsent1/2
        q = ("@info(name='q') from e1=Stream1[price>10], "
             "not Stream2[price>20] and e3=Stream3[price>30] "
             "select e1.symbol as symbol1, e3.symbol as symbol3 "
             "insert into OutputStream;")
        got = run(q, [
            ("Stream1", ["WSO2", 15.0, 100], 1000),
            ("Stream3", ["GOOGLE", 35.0, 100], 1100),
        ])
        assert got == [["WSO2", "GOOGLE"]]
        got = run(q, [
            ("Stream1", ["WSO2", 15.0, 100], 1000),
            ("Stream2", ["IBM", 25.0, 100], 1100),
            ("Stream3", ["GOOGLE", 35.0, 100], 1200),
        ])
        assert got == []

    def test_leading_and_not_untimed(self):
        # testQueryAbsent3/4
        q = ("@info(name='q') from not Stream1[price>10] and "
             "e2=Stream2[price>20], e3=Stream3[price>30] "
             "select e2.symbol as symbol2, e3.symbol as symbol3 "
             "insert into OutputStream;")
        got = run(q, [
            ("Stream2", ["IBM", 25.0, 100], 1000),
            ("Stream3", ["GOOGLE", 35.0, 100], 1100),
        ])
        assert got == [["IBM", "GOOGLE"]]
        got = run(q, [
            ("Stream1", ["WSO2", 15.0, 100], 1000),
            ("Stream2", ["IBM", 25.0, 100], 1100),
            ("Stream3", ["GOOGLE", 35.0, 100], 1200),
        ])
        assert got == []

    def test_and_not_timed_waits_window(self):
        # testQueryAbsent5/6
        q = ("@info(name='q') from e1=Stream1[price>10], "
             "not Stream2[price>20] for 1 sec and e3=Stream3[price>30] "
             "select e1.symbol as symbol1, e3.symbol as symbol3 "
             "insert into OutputStream;")
        got = run(q, [
            ("Stream1", ["WSO2", 15.0, 100], 1000),
            ("Stream3", ["GOOGLE", 35.0, 100], 2200),
        ])
        assert got == [["WSO2", "GOOGLE"]]

    def test_leading_and_not_timed(self):
        # testQueryAbsent8/9: silence must elapse BEFORE e2
        q = ("@info(name='q') from not Stream1[price>10] for 1 sec and "
             "e2=Stream2[price>20], e3=Stream3[price>30] "
             "select e2.symbol as symbol2, e3.symbol as symbol3 "
             "insert into OutputStream;")
        got = run(q, [
            ("Tick", [1], 2100),
            ("Stream2", ["IBM", 25.0, 100], 2200),
            ("Stream3", ["GOOGLE", 35.0, 100], 2300),
        ])
        assert got == [["IBM", "GOOGLE"]]
        # e2 inside the window: e3 kills the incomplete arm
        got = run(q, [
            ("Stream2", ["IBM", 25.0, 100], 500),
            ("Stream3", ["GOOGLE", 35.0, 100], 600),
        ])
        assert got == []

    def test_or_not_timed_present_wins(self):
        # testQueryAbsent11/12
        q = ("@info(name='q') from e1=Stream1[price>10], "
             "not Stream2[price>20] for 1 sec or e3=Stream3[price>30] "
             "select e1.symbol as symbol1, e3.symbol as symbol3 "
             "insert into OutputStream;")
        got = run(q, [
            ("Stream1", ["WSO2", 15.0, 100], 1000),
            ("Stream3", ["GOOGLE", 35.0, 100], 1100),
        ])
        assert got == [["WSO2", "GOOGLE"]]


class TestAbsentWithEverySequence:
    """AbsentWithEverySequenceTestCase: `every e1, not X for t` — the
    sequence's single-pending-per-state rule drops later arms while one
    waits at the absent node."""

    def test_single_pending_fires_once(self):
        # testQuery1: GOOG's arm is dropped (WSO2's already waiting);
        # one fire at WSO2's deadline
        q = ("@info(name='q') from every e1=Stream1[price>20], "
             "not Stream2[price>e1.price] for 1 sec "
             "select e1.symbol as symbol insert into OutputStream;")
        got = run(q, [
            ("Stream1", ["WSO2", 55.6, 100], 1000),
            ("Stream1", ["GOOG", 55.6, 100], 1100),
            ("Tick", [1], 2500),
        ])
        assert got == [["WSO2"]]

    def test_violation_kills_single_pending(self):
        # testQuery2
        q = ("@info(name='q') from every e1=Stream1[price>20], "
             "not Stream2[price>e1.price] for 1 sec "
             "select e1.symbol as symbol insert into OutputStream;")
        got = run(q, [
            ("Stream1", ["WSO2", 55.6, 100], 1000),
            ("Stream1", ["GOOG", 55.6, 100], 1100),
            ("Stream2", ["IBM", 55.7, 100], 1200),
            ("Tick", [1], 2500),
        ])
        assert got == []

    def test_waits_out_then_third_state(self):
        # testQuery3
        q = ("@info(name='q') from every e1=Stream1[price>20], "
             "not Stream2[price>e1.price] for 1 sec, "
             "e3=Stream3[price>e1.price] "
             "select e1.symbol as symbol1, e3.symbol as symbol3 "
             "insert into OutputStream;")
        got = run(q, [
            ("Stream1", ["WSO2", 55.6, 100], 1000),
            ("Stream1", ["GOOG", 55.6, 100], 1100),
            ("Tick", [1], 2300),
            ("Stream3", ["IBM", 55.7, 100], 2400),
        ])
        assert got == [["WSO2", "IBM"]]
