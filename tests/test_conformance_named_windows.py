"""Named-window conformance, ported from the reference `window/`
suites (CustomJoinWindowTestCase.java, SessionWindowTestCase.java,
ExternalTimeBatchWindowTestCase.java, DelayWindowTestCase.java,
LengthBatchWindowTestCase.java): shared `define window` instances
joined with tables/streams, session/externalTimeBatch/delay named
forms, and multi-reader fan-in.
"""

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager


@pytest.fixture
def manager():
    m = SiddhiManager()
    yield m
    m.shutdown()


def run(manager, app, sends, out="OutputStream"):
    rt = manager.create_siddhi_app_runtime("@app:playback " + app)
    got = []
    rt.add_callback(out, lambda evs: got.extend(list(e.data) for e in evs))
    rt.start()
    for sid, row, ts in sends:
        rt.get_input_handler(sid).send(row, timestamp=ts)
    rt.shutdown()
    return got


class TestJoinWindowWithTable:
    def test_named_window_joins_table(self, manager):
        """reference: CustomJoinWindowTestCase.testJoinWindowWithTable:55"""
        app = (
            "define stream StockStream (symbol string, price float, "
            "volume long); "
            "define stream CheckStockStream (symbol string); "
            "define window CheckStockWindow(symbol string) length(1) "
            "output all events; "
            "define table StockTable (symbol string, price float, "
            "volume long); "
            "from StockStream insert into StockTable; "
            "from CheckStockStream insert into CheckStockWindow; "
            "@info(name='q2') from CheckStockWindow join StockTable "
            "on CheckStockWindow.symbol == StockTable.symbol "
            "select CheckStockWindow.symbol as checkSymbol, "
            "StockTable.symbol as symbol, StockTable.volume as volume "
            "insert into OutputStream;")
        got = run(manager, app, [
            ("StockStream", ["WSO2", 55.6, 100], 1000),
            ("StockStream", ["IBM", 75.6, 10], 1001),
            ("CheckStockStream", ["WSO2"], 1002),
        ])
        assert got == [["WSO2", "WSO2", 100]]

    def test_two_queries_share_one_window(self, manager):
        """reference: CustomJoinWindowTestCase — multiple readers of
        one shared window instance see the SAME buffer."""
        app = (
            "define stream S (symbol string, v double); "
            "define window W (symbol string, v double) length(2); "
            "from S insert into W; "
            "@info(name='qa') from W select symbol, sum(v) as t "
            "insert into OutA; "
            "@info(name='qb') from W select symbol, count() as c "
            "insert into OutB;")
        rt = manager.create_siddhi_app_runtime("@app:playback " + app)
        a, b = [], []
        rt.add_callback("OutA", lambda evs: a.extend(list(e.data) for e in evs))
        rt.add_callback("OutB", lambda evs: b.extend(list(e.data) for e in evs))
        rt.start()
        h = rt.get_input_handler("S")
        h.send(["x", 1.0], timestamp=1000)
        h.send(["x", 2.0], timestamp=1001)
        h.send(["x", 3.0], timestamp=1002)  # expires the 1.0 row
        rt.shutdown()
        assert [r[1] for r in a] == [1.0, 3.0, 5.0]
        assert [r[1] for r in b] == [1, 2, 2]


class TestSessionNamedWindow:
    def test_session_gap_closes(self, manager):
        """reference: SessionWindowTestCase — events within the session
        gap aggregate; a gap closes the session (emitting expired)."""
        app = (
            "define stream S (user string, v double); "
            "@info(name='q') from S#window.session(100 ms, user) "
            "select user, sum(v) as total insert all events into Out;")
        rt = manager.create_siddhi_app_runtime("@app:playback " + app)
        cur, exp = [], []

        def cb(ts, ins, outs):
            cur.extend(list(e.data) for e in (ins or []))
            exp.extend(list(e.data) for e in (outs or []))

        rt.add_callback("q", cb)
        rt.start()
        h = rt.get_input_handler("S")
        h.send(["u", 1.0], timestamp=1000)
        h.send(["u", 2.0], timestamp=1050)   # same session
        h.send(["u", 5.0], timestamp=1500)   # gap: prior session closed
        rt.shutdown()
        assert [r[1] for r in cur] == [1.0, 3.0, 5.0]
        assert exp, "closed session must emit expired rows"


class TestExternalTimeBatchNamed:
    def test_external_time_batch_flushes_on_event_time_column(self, manager):
        """reference: ExternalTimeBatchWindowTestCase — panes keyed off
        an ATTRIBUTE timestamp, not arrival time."""
        app = (
            "define stream S (ts long, v double); "
            "@info(name='q') from S#window.externalTimeBatch(ts, 1 sec) "
            "select sum(v) as total insert into Out;")
        got = run(manager, app, [
            ("S", [1_000, 1.0], 50_000),   # arrival time irrelevant
            ("S", [1_500, 2.0], 50_001),
            ("S", [2_100, 4.0], 50_002),   # crosses the 1s pane -> flush
        ], out="Out")
        assert got == [[3.0]]


class TestDelayNamed:
    def test_delay_window_holds_events(self, manager):
        """reference: DelayWindowTestCase — events surface only after
        the delay elapses (event time under playback)."""
        app = (
            "define stream S (v double); "
            "@info(name='q') from S#window.delay(1 sec) "
            "select v insert into Out;")
        got = run(manager, app, [
            ("S", [1.0], 1000),
            ("S", [2.0], 1100),
            ("S", [0.0], 2200),  # watermark passes 1000+1s and 1100+1s
        ], out="Out")
        assert [g[0] for g in got][:2] == [1.0, 2.0]


class TestNamedWindowOutputToTable:
    def test_window_feeds_table(self, manager):
        """Window-expired rows can drive table mutations downstream."""
        app = (
            "define stream S (symbol string, v long); "
            "define window W (symbol string, v long) lengthBatch(2); "
            "define table T (symbol string, v long); "
            "from S insert into W; "
            "from W insert into T;")
        rt = manager.create_siddhi_app_runtime("@app:playback " + app)
        rt.start()
        h = rt.get_input_handler("S")
        h.send(["a", 1], timestamp=1000)
        h.send(["b", 2], timestamp=1001)  # pane flush -> T
        h.send(["c", 3], timestamp=1002)
        batch = rt.tables["T"].rows_batch()
        rt.shutdown()
        syms = sorted(np.asarray(batch.columns["symbol"]).tolist())
        assert syms == ["a", "b"]


class TestJunctionTopologies:
    """reference: stream/JunctionTestCase.java — fan-in/fan-out and
    multi-hop chains through stream junctions, plus concurrent
    producers."""

    def test_fan_out_fan_in(self, manager):
        app = (
            "define stream S (v long); "
            "@info(name='q1') from S[v > 0] select v insert into Mid1; "
            "@info(name='q2') from S[v > 0] select v insert into Mid2; "
            "@info(name='q3') from Mid1 select v insert into Sink; "
            "@info(name='q4') from Mid2 select v insert into Sink;")
        got = run(manager, app, [("S", [1], 1000), ("S", [2], 1001)],
                  out="Sink")
        assert sorted(g[0] for g in got) == [1, 1, 2, 2]

    def test_three_hop_chain(self, manager):
        app = (
            "define stream S (v long); "
            "from S select v + 1 as v insert into A; "
            "from A select v * 10 as v insert into B; "
            "from B select v - 5 as v insert into C;")
        got = run(manager, app, [("S", [1], 1000)], out="C")
        assert got == [[15]]  # ((1+1)*10)-5

    def test_multithreaded_producers(self, manager):
        """reference: multiThreadedTest1 — concurrent senders through
        one junction; every event is delivered exactly once."""
        import threading

        rt = manager.create_siddhi_app_runtime(
            "define stream S (v long); "
            "from S select v insert into Out;")
        got = []
        lock = threading.Lock()

        def cb(evs):
            with lock:
                got.extend(e.data[0] for e in evs)

        rt.add_callback("Out", cb)
        rt.start()
        h = rt.get_input_handler("S")

        def pump(base):
            for i in range(200):
                h.send([base + i])

        threads = [threading.Thread(target=pump, args=(k * 1000,))
                   for k in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        rt.shutdown()
        assert sorted(got) == sorted(
            k * 1000 + i for k in range(4) for i in range(200))


class TestCallbackContracts:
    """reference: stream/CallbackTestCase.java — stream vs query
    callbacks and their error surfaces."""

    def test_stream_and_query_callbacks_both_fire(self, manager):
        rt = manager.create_siddhi_app_runtime(
            "@app:playback define stream S (v long); "
            "@info(name='q') from S[v > 1] select v insert into Out;")
        stream_got, query_got = [], []
        rt.add_callback("Out", lambda evs: stream_got.extend(
            e.data for e in evs))
        rt.add_callback("q", lambda ts, ins, outs: query_got.extend(
            e.data for e in (ins or [])))
        rt.start()
        h = rt.get_input_handler("S")
        h.send([1], timestamp=1000)
        h.send([2], timestamp=1001)
        rt.shutdown()
        assert stream_got == [[2]] and query_got == [[2]]

    def test_unknown_callback_target_rejected(self, manager):
        from siddhi_tpu.core.exceptions import SiddhiAppRuntimeError

        rt = manager.create_siddhi_app_runtime(
            "define stream S (v long); from S select v insert into Out;")
        with pytest.raises(SiddhiAppRuntimeError):
            rt.add_callback("nope", lambda evs: None)
