"""Tier-1 guard: no stray synchronous device→host transfers.

The async emit pipeline's contract is that jit outputs leave the device
ONLY through the sanctioned drain path (``core/emit_queue.py``
``fetch_coalesced`` / ``EmitQueue.drain``) or an explicit barrier
(snapshot/restore, timer steps).  A future edit that sneaks a
``np.asarray(...)`` / ``jax.device_get(...)`` onto the hot batch path
re-introduces the per-batch transfer stall this PR removed — and does so
silently, because results stay correct.

This test AST-scans the device runtime modules and fails when a
materializing call appears in a function outside the curated allowlist
below.  Host-side ingest conversions (interning, routing, padding) also
use ``np.asarray`` on genuine numpy inputs; those functions are listed
explicitly so NEW call sites still trip the guard.
"""

import ast
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

# Module path -> functions (class-qualified) where materializing calls
# are sanctioned.  Everything falls into four buckets:
#   ingest    — converting HOST inputs (cols/ts/keys) before device_put
#   drain     — the coalesced fetch + deferred-emit materializers
#   barrier   — snapshot/restore/timer paths, already behind drain()
#   stats     — slow-polled gauges (overflow poll, pattern_state)
ALLOWED = {
    "siddhi_tpu/core/emit_queue.py": {
        "fetch_coalesced",                                    # drain
    },
    "siddhi_tpu/core/device_single.py": {
        "DeviceQueryRuntime.process_stream_batch",            # ingest
        "DeviceQueryRuntime.snapshot",                        # barrier
        "DeviceQueryRuntime.restore",                         # barrier
    },
    "siddhi_tpu/core/dense_pattern.py": {
        "DensePatternRuntime.intern_keys",                    # ingest
        "DensePatternRuntime._intern_keys_dict",              # ingest
        "DensePatternRuntime._rebuild_key_index",             # ingest
        "DensePatternRuntime.process_stream_batch",           # ingest
        "DensePatternRuntime.purge_idle",                     # barrier
        "DensePatternRuntime.on_time",                        # barrier
        "DensePatternRuntime.snapshot",                       # barrier
        "DensePatternRuntime.restore",                        # barrier
        "DensePatternRuntime.stats",                          # stats
    },
    "siddhi_tpu/ops/device_query.py": {
        "_split_i64",                                         # ingest
        "DeviceQueryEngine._host_env",                        # ingest
        "DeviceQueryEngine._intern_groups",                   # ingest
        "DeviceQueryEngine._intern_wgroups",                  # ingest
        "DeviceQueryEngine.host_lane_cols",                   # ingest
        "DeviceQueryEngine._pad",                             # ingest
        "DeviceQueryEngine._host_filter_mask",                # ingest
        "DeviceQueryEngine.process_batch_deferred",           # ingest
        "DeviceQueryEngine._deferred_chunk",                  # ingest
        "DeviceQueryEngine._acc_segment",                     # ingest
        "DeviceQueryEngine._out_columns",                     # drain
        "DeviceQueryEngine._flush_cols",                      # barrier
        "DeviceQueryEngine.purge_idle_keys",                  # barrier
        "DeviceQueryEngine.host_restore",                     # barrier
        "DeferredDeviceEmit.materialize",                     # drain
        "DeferredDeviceEmit._concat_parts",                   # drain
        "DeferredDeviceEmit.resolve",                         # drain
    },
    "siddhi_tpu/ops/dense_nfa.py": {
        "DensePatternEngine.prepare_cols",                    # ingest
        "DensePatternEngine.process_deferred",                # ingest
        "DensePatternEngine.on_time_state",                   # barrier
        "DensePatternEngine.maybe_re_anchor",                 # barrier
        "DeferredDenseEmit.materialize",                      # drain
        "DeferredDenseEmit.resolve",                          # drain
    },
    "siddhi_tpu/parallel/device_shard.py": {
        "ShardedDeviceQueryEngine.init_state",                # ingest
        "ShardedDeviceQueryEngine.put_state",                 # barrier
        "ShardedDeviceQueryEngine.process_batch_deferred",    # ingest
        "ShardedDeviceQueryEngine._deferred_chunk",           # ingest
        "ShardedDeviceQueryEngine._sliding_chunk",            # ingest
        "ShardedDeviceQueryEngine._acc_segment",              # ingest
    },
    "siddhi_tpu/parallel/mesh.py": {
        "make_mesh",                                          # ingest
        "route_to_shards",                                    # ingest
        "ShardedPatternEngine.route",                         # ingest
        "ShardedPatternEngine.process_deferred",              # ingest
    },
}

MATERIALIZERS = {"np.asarray", "numpy.asarray", "np.array", "numpy.array",
                 "jax.device_get"}


def materializing_calls(source):
    """Yield (lineno, call, qualified enclosing function)."""
    stack = []
    hits = []

    class V(ast.NodeVisitor):
        def _scoped(self, node):
            stack.append(node.name)
            self.generic_visit(node)
            stack.pop()

        visit_FunctionDef = _scoped
        visit_AsyncFunctionDef = _scoped
        visit_ClassDef = _scoped

        def visit_Call(self, node):
            f = node.func
            if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
                name = f"{f.value.id}.{f.attr}"
                if name in MATERIALIZERS:
                    hits.append((node.lineno, name,
                                 ".".join(stack) or "<module>"))
            self.generic_visit(node)

    V().visit(ast.parse(source))
    return hits


def test_no_stray_sync_transfers_in_device_runtimes():
    offenders = []
    for rel, allowed in ALLOWED.items():
        path = REPO / rel
        assert path.exists(), f"guard list is stale: {rel} moved"
        for lineno, call, qual in materializing_calls(path.read_text()):
            if qual not in allowed:
                offenders.append(f"{rel}:{lineno} {call} in {qual}()")
    assert not offenders, (
        "synchronous device->host materialization outside the sanctioned "
        "async-emit drain path (route it through the runtime's EmitQueue, "
        "or add it to the allowlist WITH a bucket justification):\n  "
        + "\n  ".join(offenders))


def test_allowlist_not_stale():
    """Every allowlisted function still exists and still materializes —
    keeps the guard list honest as the runtimes evolve."""
    for rel, allowed in ALLOWED.items():
        live = {q for _ln, _c, q in
                materializing_calls((REPO / rel).read_text())}
        gone = allowed - live
        assert not gone, (f"{rel}: allowlisted entries no longer "
                          f"materialize; prune them: {sorted(gone)}")
