"""Tier-1 guard: no stray synchronous device→host transfers.

Thin shim over the ``host-sync-hazard`` rule in ``siddhi_tpu.analysis``
(which absorbed this file's AST scanner, allowlist, and staleness
check).  The test names are stable tier-1 anchors; the contract, the
scanned-module list, and the curated allowlist (with bucket
justifications) now live in ``siddhi_tpu/analysis/rules/host_sync.py``
and ``siddhi_tpu/analysis/allowlists.py``.
"""

from pathlib import Path

from siddhi_tpu.analysis import get_rule, index_package, run_rules

REPO = Path(__file__).resolve().parent.parent

RULE = "host-sync-hazard"


def _run():
    indexes = index_package(REPO / "siddhi_tpu", REPO)
    return run_rules(indexes, [get_rule(RULE)])


def test_no_stray_sync_transfers_in_device_runtimes():
    hits = [f for f in _run()["findings"] if f.rule == RULE]
    assert not hits, (
        "synchronous device->host materialization outside the sanctioned "
        "async-emit drain path (route it through the runtime's EmitQueue, "
        "or allowlist it in siddhi_tpu/analysis/allowlists.py WITH a "
        "bucket justification):\n  "
        + "\n  ".join(f.render() for f in hits))


def test_allowlist_not_stale():
    """Allowlist entries expire: one that no longer matches a finding
    surfaces as a ``stale-allowlist`` finding — the list only shrinks."""
    stale = [f for f in _run()["findings"] if f.rule == "stale-allowlist"]
    assert not stale, "\n  ".join(f.render() for f in stale)
