"""Randomized host-vs-dense differential testing of the pattern engines.

For a grid of pattern shapes (every-chains, counts, logical nodes,
sequences, within windows, integer id-joins) and seeded random event
streams, the SAME app runs through SiddhiManager twice — host mode and
@app:execution('tpu') — and the emitted rows must be IDENTICAL (values
and order).  This is the breadth play the hand-written corpora cannot
match: each (shape, seed) pair pins thousands of engine transitions.

The dense path must actually engage (asserted via the runtime type), so
a silent fallback cannot hollow the test out.
"""

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.dense_pattern import DensePatternRuntime

DEFINE = "define stream S (k long, u double, v double); "


def run(app, sends, mode_tpu, instances=16):
    m = SiddhiManager()
    try:
        header = "@app:playback "
        if mode_tpu:
            header += f"@app:execution('tpu', instances='{instances}') "
        rt = m.create_siddhi_app_runtime(header + DEFINE + app)
        got = []
        rt.add_callback("Alerts", lambda evs: got.extend(e.data for e in evs))
        rt.start()
        h = rt.get_input_handler("S")
        for row, ts in sends:
            h.send(row, timestamp=ts)
        qr = next(iter(rt.query_runtimes.values()), None)
        runtime = getattr(qr, "pattern_processor", None)
        overflow = (runtime.overflow_total()
                    if isinstance(runtime, DensePatternRuntime) else 0)
        rt.shutdown()
        return got, runtime, overflow
    finally:
        m.shutdown()


def gen_stream(seed, n=60, v_lo=0.0, v_hi=20.0, dt_max=400):
    rng = np.random.default_rng(seed)
    ts = 1000 + np.cumsum(rng.integers(1, dt_max, size=n))
    ks = rng.integers(0, 3, size=n)
    us = rng.uniform(v_lo, v_hi, size=n).round(1)
    vs = rng.uniform(v_lo, v_hi, size=n).round(1)
    return [([int(k), float(u), float(v)], int(t))
            for k, u, v, t in zip(ks, us, vs, ts)]


def norm(rows):
    """Round float values: DOUBLE attrs ride float32 dense lanes (the
    documented precision subset) — one-decimal inputs are exact at 4dp."""
    return [
        [round(v, 4) if isinstance(v, float) else v for v in r] for r in rows
    ]


def differential(app, seed, n=60, approx=False, **stream_kw):
    sends = gen_stream(seed, n=n, **stream_kw)
    host, _, _ = run(app, sends, mode_tpu=False)
    dense, runtime, overflow = run(app, sends, mode_tpu=True)
    assert isinstance(runtime, DensePatternRuntime), "did not lower densely"
    if overflow:
        # capacity-dropped instances legitimately diverge; with 16 lanes
        # over these streams this should stay rare — surface it
        pytest.skip(f"instance overflow ({overflow}) — not comparable")
    if approx:
        # aggregated outputs (sum over float32-quantized captures) carry
        # accumulated lane error — 4dp rounding could flip at a boundary,
        # so compare with a relative tolerance instead
        assert len(dense) == len(host), (
            f"seed {seed}: {len(dense)} dense vs {len(host)} host rows")
        for dr, hr in zip(dense, host):
            assert dr == pytest.approx(hr, rel=1e-4, abs=1e-3), (dr, hr)
        return host
    assert norm(dense) == norm(host), (
        f"seed {seed}: dense {len(dense)} rows != host {len(host)} rows\n"
        f"dense: {dense[:6]}...\nhost:  {host[:6]}...")
    return host


SHAPES = {
    "every_pair": (
        "@info(name='q') from every a=S[v > 10.0] -> b=S[v > a.v] "
        "within 3 sec select a.v as av, b.v as bv insert into Alerts;"),
    "every_triple": (
        "@info(name='q') from every a=S[v > 5.0] -> b=S[v > a.v] "
        "-> c=S[v > b.v] within 5 sec "
        "select a.v as av, b.v as bv, c.v as cv insert into Alerts;"),
    "every_two_filters": (
        "@info(name='q') from every a=S[u > 10.0 and v > 10.0] "
        "-> b=S[v < a.v and u > a.u] within 4 sec "
        "select a.u as au, a.v as av, b.u as bu, b.v as bv "
        "insert into Alerts;"),
    "exact_count": (
        "@info(name='q') from every a=S[v > 8.0]<2> -> b=S[v < 4.0] "
        "within 5 sec select a[0].v as a0, a[last].v as a1, b.v as bv "
        "insert into Alerts;"),
    "open_count": (
        "@info(name='q') from every a=S[v > 12.0]<1:> -> b=S[v < 4.0] "
        "within 5 sec select a[0].v as a0, b.v as bv insert into Alerts;"),
    "bounded_count": (
        "@info(name='q') from a=S[v > 8.0]<2:4> -> b=S[v < 4.0] "
        "within 5 sec select a[0].v as a0, b.v as bv insert into Alerts;"),
    "sequence_pair": (
        "@info(name='q') from every a=S[v > 10.0], b=S[v > a.v] "
        "select a.v as av, b.v as bv insert into Alerts;"),
    "non_every": (
        "@info(name='q') from a=S[v > 10.0] -> b=S[v > a.v] "
        "select a.v as av, b.v as bv insert into Alerts;"),
    "int_id_join": (
        "@info(name='q') from every a=S[v > 10.0] -> b=S[k == a.k] "
        "within 3 sec select a.v as av, b.v as bv insert into Alerts;"),
    "no_within": (
        "@info(name='q') from every a=S[v > 15.0] -> b=S[v > a.v] "
        "select a.v as av, b.v as bv insert into Alerts;"),
    "aggregating_selector": (
        "@info(name='q') from every a=S[v > 10.0] -> b=S[v > a.v] "
        "within 3 sec select a.v as av, sum(b.v) as t, count() as c "
        "group by a.v insert into Alerts;"),
    "having_over_aggregate": (
        "@info(name='q') from every a=S[v > 10.0] -> b=S[v > a.v] "
        "within 3 sec select a.v as av, sum(b.v) as t "
        "group by a.v having t > 20.0 insert into Alerts;"),
    # absent deadlines fire from the jitted timer step; the randomized
    # stream's watermark advances drive both engines' schedulers
    "trailing_absent": (
        "@info(name='q') from every a=S[v > 12.0] -> "
        "not S[v > a.v] for 500 millisec "
        "select a.v as av insert into Alerts;"),
    "group_every": (
        # whole-chain group-every: ONE arm at a time (virgin forms only
        # while the partition is empty), re-armed at completion/expiry
        "@info(name='q') from every (a=S[v > 8.0] -> b=S[v > a.v]) "
        "within 2 sec select a.v as av, b.v as bv insert into Alerts;"),
    "mid_chain_absent": (
        "@info(name='q') from every a=S[v > 14.0] -> "
        "not S[v > a.v] for 400 millisec -> c=S[v < 5.0] "
        "select a.v as av, c.v as cv insert into Alerts;"),
}


APPROX_SHAPES = {"aggregating_selector", "having_over_aggregate"}


@pytest.mark.parametrize("shape", sorted(SHAPES))
@pytest.mark.parametrize("seed", [1, 2, 3, 4, 5])
def test_shape_matches_host(shape, seed):
    differential(SHAPES[shape], seed, approx=shape in APPROX_SHAPES)


@pytest.mark.parametrize("seed", [11, 12, 13])
def test_dense_stream_high_match_rate(seed):
    # low thresholds -> many overlapping arms and frequent completions
    app = ("@info(name='q') from every a=S[v > 2.0] -> b=S[v > a.v] "
           "within 2 sec select a.v as av, b.v as bv insert into Alerts;")
    differential(app, seed, n=40)


@pytest.mark.parametrize("seed", [21, 22])
def test_long_stream_within_churn(seed):
    # long stream with tight within: constant arm expiry churn
    app = ("@info(name='q') from every a=S[v > 6.0] -> b=S[v > a.v] "
           "within 1 sec select a.v as av, b.v as bv insert into Alerts;")
    differential(app, seed, n=120, dt_max=700)


def test_partitioned_fuzz_matches_host():
    app = ("partition with (k of S) begin "
           "@info(name='q') from every a=S[v > 8.0] -> b=S[v > a.v] "
           "within 3 sec select a.v as av, b.v as bv insert into Alerts; "
           "end;")
    sends = gen_stream(seed=31, n=80)
    host, _, _ = run(app, sends, mode_tpu=False)
    dense, _, _ = run(app, sends, mode_tpu=True)
    assert norm(dense) == norm(host)


def gen_skewed_stream(seed, n=360, hot_key=7, dt_max=60):
    """Three skew phases: the hot key takes ~85% of traffic, then the
    stream goes uniform (the router must demote and hand pending state
    back), then the same key heats up again (re-promotion)."""
    rng = np.random.default_rng(seed)
    out, t = [], 1000
    for i in range(n):
        t += int(rng.integers(1, dt_max))
        phase = (3 * i) // n
        hot = phase != 1 and rng.random() < 0.85
        k = hot_key if hot else int(rng.integers(0, 30))
        out.append(([int(k), float(round(rng.uniform(0, 20), 1)),
                     float(round(rng.uniform(0, 20), 1))], int(t)))
    return out


@pytest.mark.parametrize("seed", [51, 52, 53])
def test_hotkey_skewed_fuzz_matches_host(seed):
    """Skewed keys crossing the promote/demote thresholds mid-run under
    @app:hotkeys: routing (dense rows <-> scan slots, exact state
    handoff both ways) must never alter detections."""
    from siddhi_tpu.core.hotkey_router import HotKeyRouterRuntime

    app = ("partition with (k of S) begin "
           "@info(name='q') from every a=S[v > 8.0] -> b=S[v > 12.0] "
           "select b.v as bv insert into Alerts; "
           "end;")
    sends = gen_skewed_stream(seed)
    host, _, _ = run(app, sends, mode_tpu=False)
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime(
            "@app:playback @app:execution('tpu', instances='16') "
            "@app:hotkeys(k='4', promote='0.3', demote='0.1') "
            + DEFINE + app)
        got = []
        rt.add_callback("Alerts", lambda evs: got.extend(e.data for e in evs))
        rt.start()
        h = rt.get_input_handler("S")
        for row, ts in sends:
            h.send(row, timestamp=ts)
        router = None
        for pr in rt.partitions.values():
            for qr in pr.dense_query_runtimes.values():
                router = qr.pattern_processor
        assert isinstance(router, HotKeyRouterRuntime), "did not wrap"
        hot = router.hot_metrics()
        rt.shutdown()
    finally:
        m.shutdown()
    # the phased skew must actually exercise both decision edges
    assert hot["hotkeyPromotions"] >= 1, hot
    assert hot["hotkeyDemotions"] >= 1, hot
    assert norm(got) == norm(host)


KERNEL_APP = (
    "@info(name='q') from every a=S[v > 8.0] -> b=S[v > 12.0] "
    "within 3 sec select b.v as bv insert into Alerts;")


@pytest.mark.parametrize("packed", [False, True])
@pytest.mark.parametrize("seed", [
    61,
    pytest.param(62, marks=pytest.mark.slow),
    pytest.param(63, marks=pytest.mark.slow),
])
def test_kernel_step_matches_xla_fuzz(seed, packed):
    """@app:kernels swaps the dense step for the packed-plane Pallas
    kernel (interpret mode on CPU) — emitted rows must be BIT-identical
    to the plain XLA dense path, no norm().  The packed variant also
    round-trips the live engine state through the bit-plane converters
    mid-assertion, pinning pack/unpack against real state."""
    sends = gen_stream(seed, n=80)
    xla, _, _ = run(KERNEL_APP, sends, mode_tpu=True)
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime(
            "@app:playback @app:execution('tpu', instances='16') "
            "@app:kernels " + DEFINE + KERNEL_APP)
        got = []
        rt.add_callback("Alerts", lambda evs: got.extend(e.data for e in evs))
        rt.start()
        h = rt.get_input_handler("S")
        for row, ts in sends:
            h.send(row, timestamp=ts)
        qr = next(iter(rt.query_runtimes.values()))
        assert qr.lowered_to == "kernel", qr.lowered_to
        if packed:
            from siddhi_tpu.kernels import plane_pack

            state = {k: np.asarray(v)
                     for k, v in qr.pattern_processor.state.items()}
            back = plane_pack.unpack_state(plane_pack.pack_state(state))
            assert set(back) == set(state)
            for k in state:
                assert np.array_equal(back[k], state[k]), k
        rt.shutdown()
    finally:
        m.shutdown()
    assert got == xla  # bit-identical: same lanes, same dtypes


def test_sharded_fuzz_matches_host():
    app = ("partition with (k of S) begin "
           "@info(name='q') from every a=S[v > 8.0] -> b=S[v > a.v] "
           "within 3 sec select a.v as av, b.v as bv insert into Alerts; "
           "end;")
    sends = gen_stream(seed=41, n=80)
    host, _, _ = run(app, sends, mode_tpu=False)
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime(
            "@app:playback @app:execution('tpu', partitions='64', "
            "devices='8', instances='8') " + DEFINE + app)
        got = []
        rt.add_callback("Alerts", lambda evs: got.extend(e.data for e in evs))
        rt.start()
        h = rt.get_input_handler("S")
        for row, ts in sends:
            h.send(row, timestamp=ts)
        rt.shutdown()
    finally:
        m.shutdown()
    assert norm(got) == norm(host)
