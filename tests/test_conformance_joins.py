"""Join conformance matrix: join types x window buffering x tables.

Ported behavior families from the reference's join suite
(modules/siddhi-core/src/test/java/io/siddhi/core/query/join/
JoinTestCase.java, OuterJoinTestCase.java, table/JoinTableTestCase.java):
window-buffered stream joins, outer-join null fills, unidirectional
triggering, table probes.
"""

import pytest

from siddhi_tpu import SiddhiManager

STREAMS = (
    "define stream Ticks (symbol string, price double); "
    "define stream News (symbol string, headline string); "
)


def run(app, sends, out="OutputStream"):
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime("@app:playback " + app)
        got = []
        rt.add_callback(out, lambda evs: got.extend(e.data for e in evs))
        rt.start()
        t = 1000
        for stream, row in sends:
            rt.get_input_handler(stream).send(row, timestamp=t)
            t += 100
        rt.shutdown()
        return got
    finally:
        m.shutdown()


class TestInnerJoin:
    Q = (STREAMS +
         "from Ticks#window.length(5) as t join News#window.length(5) as n "
         "on t.symbol == n.symbol "
         "select t.symbol as symbol, t.price as price, n.headline as h "
         "insert into OutputStream;")

    def test_match_after_both_sides_buffered(self):
        got = run(self.Q, [
            ("Ticks", ["IBM", 100.0]),
            ("News", ["IBM", "up"]),
        ])
        assert got == [["IBM", 100.0, "up"]]

    def test_no_match_different_symbols(self):
        got = run(self.Q, [
            ("Ticks", ["IBM", 100.0]),
            ("News", ["WSO2", "up"]),
        ])
        assert got == []

    def test_each_arrival_probes_opposite_window(self):
        got = run(self.Q, [
            ("Ticks", ["IBM", 100.0]),
            ("News", ["IBM", "a"]),     # match 1
            ("Ticks", ["IBM", 101.0]),  # matches buffered news -> match 2
        ])
        assert got == [["IBM", 100.0, "a"], ["IBM", 101.0, "a"]]

    def test_window_eviction_limits_matches(self):
        q = (STREAMS +
             "from Ticks#window.length(1) as t join News#window.length(5) "
             "as n on t.symbol == n.symbol "
             "select t.symbol as symbol, t.price as price "
             "insert into OutputStream;")
        got = run(q, [
            ("Ticks", ["IBM", 100.0]),
            ("Ticks", ["WSO2", 50.0]),   # evicts IBM from length(1)
            ("News", ["IBM", "x"]),      # IBM gone: no match
            ("News", ["WSO2", "y"]),     # WSO2 present: match
        ])
        assert got == [["WSO2", 50.0]]

    def test_join_condition_on_values(self):
        q = ("define stream A (k string, v double); "
             "define stream B (k string, v double); "
             "from A#window.length(5) as a join B#window.length(5) as b "
             "on a.v < b.v select a.v as av, b.v as bv "
             "insert into OutputStream;")
        got = run(q, [("A", ["x", 1.0]), ("A", ["y", 5.0]),
                      ("B", ["z", 3.0])])
        assert got == [[1.0, 3.0]]


class TestOuterJoins:
    def test_left_outer_null_fill(self):
        q = (STREAMS +
             "from Ticks#window.length(5) as t left outer join "
             "News#window.length(5) as n on t.symbol == n.symbol "
             "select t.symbol as symbol, n.headline as h "
             "insert into OutputStream;")
        got = run(q, [
            ("Ticks", ["IBM", 100.0]),   # no news yet -> null fill
            ("News", ["IBM", "up"]),     # now matches
        ])
        assert got == [["IBM", None], ["IBM", "up"]]

    def test_right_outer_null_fill(self):
        q = (STREAMS +
             "from Ticks#window.length(5) as t right outer join "
             "News#window.length(5) as n on t.symbol == n.symbol "
             "select n.symbol as symbol, t.price as price "
             "insert into OutputStream;")
        got = run(q, [
            ("News", ["IBM", "up"]),     # no tick yet -> null fill
            ("Ticks", ["IBM", 100.0]),
        ])
        assert got == [["IBM", None], ["IBM", 100.0]]

    def test_full_outer_both_sides(self):
        q = (STREAMS +
             "from Ticks#window.length(5) as t full outer join "
             "News#window.length(5) as n on t.symbol == n.symbol "
             "select t.symbol as ts, n.symbol as ns "
             "insert into OutputStream;")
        got = run(q, [
            ("Ticks", ["IBM", 100.0]),
            ("News", ["WSO2", "up"]),
        ])
        assert got == [["IBM", None], [None, "WSO2"]]


class TestUnidirectional:
    def test_only_left_triggers(self):
        q = (STREAMS +
             "from Ticks#window.length(5) unidirectional join "
             "News#window.length(5) "
             "on Ticks.symbol == News.symbol "
             "select Ticks.symbol as symbol, News.headline as h "
             "insert into OutputStream;")
        got = run(q, [
            ("News", ["IBM", "up"]),     # buffered, no trigger
            ("Ticks", ["IBM", 100.0]),   # triggers against buffer
            ("News", ["IBM", "again"]),  # must NOT trigger
        ])
        assert got == [["IBM", "up"]]


class TestTableJoin:
    APP = ("define stream S (symbol string, qty int); "
           "define table Prices (symbol string, price double); "
           "define stream P (symbol string, price double); "
           "from P insert into Prices; "
           "from S join Prices as pr on S.symbol == pr.symbol "
           "select S.symbol as symbol, S.qty as qty, pr.price as price "
           "insert into OutputStream;")

    def test_stream_probes_table(self):
        got = run(self.APP, [
            ("P", ["IBM", 700.0]),
            ("P", ["WSO2", 60.0]),
            ("S", ["IBM", 3]),
            ("S", ["GOOG", 1]),   # not in table: no row
            ("S", ["WSO2", 2]),
        ])
        assert got == [["IBM", 3, 700.0], ["WSO2", 2, 60.0]]

    def test_table_update_visible_to_next_probe(self):
        app = self.APP + (" define stream U (symbol string, price double); "
                          "from U update Prices set Prices.price = U.price "
                          "on Prices.symbol == U.symbol; ")
        got = run(app, [
            ("P", ["IBM", 700.0]),
            ("S", ["IBM", 1]),
            ("U", ["IBM", 710.0]),
            ("S", ["IBM", 2]),
        ])
        assert got == [["IBM", 1, 700.0], ["IBM", 2, 710.0]]


class TestJoinWithAggregation:
    def test_join_groupby_over_window(self):
        q = (STREAMS +
             "from Ticks#window.lengthBatch(4) as t join "
             "News#window.length(10) as n on t.symbol == n.symbol "
             "select t.symbol as symbol, sum(t.price) as total "
             "group by t.symbol insert into OutputStream;")
        got = run(q, [
            ("News", ["IBM", "x"]),
            ("Ticks", ["IBM", 10.0]),
            ("Ticks", ["IBM", 20.0]),
            ("Ticks", ["WSO2", 5.0]),
            ("Ticks", ["IBM", 30.0]),  # batch flushes here
        ])
        assert got[-1] == ["IBM", 60.0]
