"""Crash-consistent async durability: matrix, checksums, spill, stats.

The durability/ package adds a non-blocking persist pipeline: capture
under the barrier (device-array references + cheap host copies), then a
checkpoint writer thread does the D2H fetch, per-element pickle +
SHA-256, and an atomic-manifest store commit.  The contracts pinned
here:

* **Crash matrix** — a simulated crash (``SimulatedCrashError``, a
  BaseException that tears through every hardening layer like SIGKILL)
  at EVERY durability step (post-blob, pre-manifest, mid-manifest,
  post-manifest-before-journal-mark, mid-spill) leaves either the
  previous or the new revision fully restorable, and restore + journal
  replay is bit-identical to an uninterrupted run — across the
  device-single, sharded, fused, multiplexed, and hotkey engines.
* **Checksummed manifests** — a flipped byte anywhere in a revision
  (blob or manifest) fails validation and the restore walk falls back
  to the previous revision with a warning.
* **Journal spill** — a full journal spills cold segments to the
  persistence store; replay stitches spilled + in-memory segments.
* **Async == sync** — both modes route through the same capture, so
  the persisted state trees are byte-identical.
* **No silent degradation** — unfreezable elements (host NFA instance
  lists), forced-sync fallbacks, coalesced persists, retries, and
  failures are all counted and surfaced through the statistics feed.
"""

import os
import pickle
import threading
import time

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.exceptions import SimulatedCrashError
from siddhi_tpu.durability import (
    AsyncCheckpointWriter,
    DurableFileSystemPersistenceStore,
)
from siddhi_tpu.util.persistence import (
    InMemoryPersistenceStore,
    IncrementalFileSystemPersistenceStore,
)

pytestmark = pytest.mark.faults


# -- engine matrix ----------------------------------------------------------

AGG_BODY = ("define stream S (k long, v double); "
            "@info(name='q') from S#window.length(4) "
            "select k, sum(v) as s group by k insert into Out;")

FUSED_BODY = """
define stream SIn (sym int, price float, vol int);
define stream Mid (sym int, price float, vol int);
define stream Win (sym int, total double);
@info(name='q1') from SIn[price > 10.0]
select sym, price, vol insert into Mid;
@info(name='q2') from Mid#window.length(8)
select sym, sum(price) as total insert into Win;
@info(name='q3') from Win[total > 50.0]
select sym, total insert into Out;
"""

MUX_BODY = ("define stream S (k long, v double); "
            "@info(name='qw') from S#window.lengthBatch(4) "
            "select k, sum(v) as s, count() as c group by k "
            "insert into Out;")

HOTKEY_BODY = (
    "define stream S (k long, u double, v double); "
    "partition with (k of S) begin "
    "@info(name='q') from every a=S[v > 8.0] -> b=S[v > 12.0] "
    "select b.v as bv insert into Out; end;")


def kv_series(n, seed=11, n_keys=3):
    rng = np.random.default_rng(seed)
    keys = rng.integers(0, n_keys, size=n)
    vals = rng.integers(1, 100, size=n).astype(float)
    ts = 1000 + np.arange(n) * 250
    return [([int(k), float(v)], int(t)) for k, v, t in zip(keys, vals, ts)]


def fused_series(n, seed=7):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        out.append(([int(rng.integers(0, 4)),
                     round(float(rng.uniform(5.0, 20.0)), 1),
                     int(rng.integers(0, 100))], 1000 + i * 100))
    return out


def hk_series(n, seed=5):
    rng = np.random.default_rng(seed)
    out, t = [], 1000
    for _ in range(n):
        t += int(rng.integers(1, 40))
        k = 7 if rng.random() < 0.5 else int(rng.integers(0, 20))
        out.append(([k, round(float(rng.uniform(0, 20)), 1),
                     round(float(rng.uniform(0, 20)), 1)], t))
    return out


ENGINES = {
    "device_single": ("@app:execution('tpu') ", AGG_BODY, "S",
                      kv_series(30)),
    "sharded": ("@app:execution('tpu', partitions='16', devices='8') ",
                AGG_BODY, "S", kv_series(30)),
    "fused": ("@app:execution('tpu') @app:fuse ", FUSED_BODY, "SIn",
              fused_series(30)),
    "multiplex": ("@app:execution('tpu') @app:multiplex(slots='8') ",
                  MUX_BODY, "S", kv_series(30)),
    "hotkey": ("@app:execution('tpu', instances='16') "
               "@app:hotkeys(k='4', promote='0.3', demote='0.1') ",
               HOTKEY_BODY, "S", hk_series(60)),
}

#: crash site -> which revision must survive ('prev' = the torn write is
#: invisible, 'new' = the write landed, only the journal mark is behind)
CRASH_SITES = {
    "persist.post_blob": "prev",
    "persist.pre_manifest": "prev",
    "persist.mid_manifest": "prev",
    "persist.post_manifest": "new",
}

_REFERENCE_CACHE = {}


def _app(engine, journal=256):
    exec_opts, body, _stream, _sends = ENGINES[engine]
    return ("@app:name('dur') @app:playback "
            f"@app:faults(journal='{journal}') " + exec_opts + body)


def _reference(engine):
    """Uninterrupted-run output of the engine's send series (cached —
    the matrix replays it once per crash site)."""
    if engine in _REFERENCE_CACHE:
        return _REFERENCE_CACHE[engine]
    exec_opts, body, stream, sends = ENGINES[engine]
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime(
            "@app:name('dur') @app:playback " + exec_opts + body)
        got = []
        rt.add_callback("Out", lambda evs: got.extend(tuple(e.data)
                                                      for e in evs))
        rt.start()
        h = rt.get_input_handler(stream)
        for row, ts in sends:
            h.send(list(row), timestamp=ts)
        rt.shutdown()
    finally:
        m.shutdown()
    assert len(got) > 2, f"{engine}: series too tame; matrix is vacuous"
    _REFERENCE_CACHE[engine] = got
    return got


class TestCrashMatrix:
    """Kill the durability pipeline between every step, on every
    engine; recovery must be bit-exact."""

    @pytest.mark.parametrize("engine", sorted(ENGINES))
    @pytest.mark.parametrize("site", sorted(CRASH_SITES))
    def test_async_crash_site_recovers_bit_exact(self, engine, site,
                                                 tmp_path):
        ref = _reference(engine)
        _exec, _body, stream, sends = ENGINES[engine]
        persist_at, crash_at = 10, 20
        m = SiddhiManager()
        try:
            m.set_persistence_store(
                DurableFileSystemPersistenceStore(str(tmp_path)))
            rt = m.create_siddhi_app_runtime(_app(engine))
            got = []
            rt.add_callback("Out", lambda evs: got.extend(tuple(e.data)
                                                          for e in evs))
            rt.start()
            h = rt.get_input_handler(stream)
            for row, ts in sends[:persist_at]:
                h.send(list(row), timestamp=ts)
            rev1 = rt.persist(mode="async")
            assert rt.wait_for_persist(rev1, timeout=30) == "committed"
            for row, ts in sends[persist_at:crash_at]:
                h.send(list(row), timestamp=ts)
            rt.app_context.fault_injector.configure(site, "crash", count=1)
            rev2 = rt.persist(mode="async")
            assert rt.wait_for_persist(rev2, timeout=30) == "crashed"
            rt.shutdown()  # the crashed runtime is gone

            rt2 = m.create_siddhi_app_runtime(_app(engine))
            rt2.add_callback("Out", lambda evs: got.extend(tuple(e.data)
                                                           for e in evs))
            rt2.start()
            restored = rt2.restore_last_revision()
            expected = rev2 if CRASH_SITES[site] == "new" else rev1
            assert restored == expected, (
                f"{engine}/{site}: restored '{restored}', "
                f"expected '{expected}'")
            h2 = rt2.get_input_handler(stream)
            for row, ts in sends[crash_at:]:
                h2.send(list(row), timestamp=ts)
            rt2.shutdown()
            assert got == ref, (
                f"{engine}/{site}: crash+recover diverged from the "
                "uninterrupted run")
        finally:
            m.shutdown()

    @pytest.mark.parametrize("site", sorted(CRASH_SITES))
    def test_sync_crash_site_recovers_bit_exact(self, site, tmp_path):
        # the same matrix through the blocking path: the crash surfaces
        # in the persist() call itself
        engine = "device_single"
        ref = _reference(engine)
        _exec, _body, stream, sends = ENGINES[engine]
        m = SiddhiManager()
        try:
            m.set_persistence_store(
                DurableFileSystemPersistenceStore(str(tmp_path)))
            rt = m.create_siddhi_app_runtime(_app(engine))
            got = []
            rt.add_callback("Out", lambda evs: got.extend(tuple(e.data)
                                                          for e in evs))
            rt.start()
            h = rt.get_input_handler(stream)
            for row, ts in sends[:10]:
                h.send(list(row), timestamp=ts)
            rev1 = rt.persist(mode="sync")
            for row, ts in sends[10:20]:
                h.send(list(row), timestamp=ts)
            rt.app_context.fault_injector.configure(site, "crash", count=1)
            with pytest.raises(SimulatedCrashError):
                rt.persist(mode="sync")
            store = m.siddhi_context.persistence_store
            revs = store.revisions("dur")
            rt.shutdown()

            rt2 = m.create_siddhi_app_runtime(_app(engine))
            rt2.add_callback("Out", lambda evs: got.extend(tuple(e.data)
                                                           for e in evs))
            rt2.start()
            restored = rt2.restore_last_revision()
            if CRASH_SITES[site] == "prev":
                assert restored == rev1
            else:
                assert restored == revs[-1] != rev1
            h2 = rt2.get_input_handler(stream)
            for row, ts in sends[20:]:
                h2.send(list(row), timestamp=ts)
            rt2.shutdown()
            assert got == ref
        finally:
            m.shutdown()

    def test_mid_spill_crash_recovers_bit_exact(self, tmp_path):
        # kill the process in the middle of a journal-segment spill: the
        # written segment is durable, the in-memory journal is gone, and
        # recovery stitches segments + journal into a gapless replay
        engine = "device_single"
        ref = _reference(engine)
        _exec, _body, stream, sends = ENGINES[engine]
        m = SiddhiManager()
        try:
            m.set_persistence_store(
                DurableFileSystemPersistenceStore(str(tmp_path)))
            rt = m.create_siddhi_app_runtime(_app(engine, journal=4))
            got = []
            rt.add_callback("Out", lambda evs: got.extend(tuple(e.data)
                                                          for e in evs))
            rt.start()
            h = rt.get_input_handler(stream)
            for row, ts in sends[:6]:
                h.send(list(row), timestamp=ts)
            rt.persist()
            crash_at = 16  # > depth-4 journal: spills before the crash
            for row, ts in sends[6:crash_at]:
                h.send(list(row), timestamp=ts)
            rt.app_context.fault_injector.configure(
                "journal.spill.mid", "crash", count=1)
            with pytest.raises(SimulatedCrashError):
                h.send(list(sends[crash_at][0]),
                       timestamp=sends[crash_at][1])
            rt.shutdown()

            rt2 = m.create_siddhi_app_runtime(_app(engine, journal=4))
            rt2.add_callback("Out", lambda evs: got.extend(tuple(e.data)
                                                           for e in evs))
            rt2.start()
            assert rt2.restore_last_revision() is not None
            jr2 = rt2.app_context.input_journal
            assert jr2.stats.replayed_spilled_batches > 0
            h2 = rt2.get_input_handler(stream)
            # the crashed send was journaled before the spill crash, so
            # replay already delivered it — continue after it
            for row, ts in sends[crash_at + 1:]:
                h2.send(list(row), timestamp=ts)
            rt2.shutdown()
            assert got == ref, "mid-spill crash diverged"
        finally:
            m.shutdown()


class TestChecksummedManifests:
    def _persist_twice(self, m, tmp_path):
        _exec, _body, stream, sends = ENGINES["device_single"]
        m.set_persistence_store(
            DurableFileSystemPersistenceStore(str(tmp_path)))
        rt = m.create_siddhi_app_runtime(_app("device_single"))
        rt.start()
        h = rt.get_input_handler(stream)
        for row, ts in sends[:8]:
            h.send(list(row), timestamp=ts)
        rev1 = rt.persist(mode="sync")
        for row, ts in sends[8:16]:
            h.send(list(row), timestamp=ts)
        rev2 = rt.persist(mode="sync")
        rt.shutdown()
        return rev1, rev2

    @pytest.mark.parametrize("victim", ["blob", "manifest"])
    def test_flipped_byte_walks_back_to_previous_revision(
            self, victim, tmp_path, caplog):
        import logging

        m = SiddhiManager()
        try:
            rev1, rev2 = self._persist_twice(m, tmp_path)
            rev_dir = tmp_path / "dur" / f"{rev2}.ckpt"
            if victim == "blob":
                target = sorted(p for p in rev_dir.iterdir()
                                if p.name.endswith(".blob"))[0]
            else:
                target = rev_dir / "MANIFEST.json"
            raw = bytearray(target.read_bytes())
            raw[len(raw) // 2] ^= 0xFF
            target.write_bytes(bytes(raw))

            rt2 = m.create_siddhi_app_runtime(_app("device_single"))
            rt2.start()
            with caplog.at_level(logging.WARNING, logger="siddhi_tpu"):
                assert rt2.restore_last_revision() == rev1
            assert any(rev2 in r.message for r in caplog.records), (
                "the skipped corrupt revision must be surfaced")
            rt2.shutdown()
        finally:
            m.shutdown()

    def test_torn_revision_without_manifest_is_invisible(self, tmp_path):
        m = SiddhiManager()
        try:
            rev1, rev2 = self._persist_twice(m, tmp_path)
            store = m.siddhi_context.persistence_store
            # simulate a crash that wrote blobs but no manifest
            torn = tmp_path / "dur" / "9999999999999_dur.ckpt"
            torn.mkdir()
            (torn / "0000.blob").write_bytes(b"half a checkpoint")
            assert store.revisions("dur") == [rev1, rev2]
            assert store.get_last_revision("dur") == rev2
        finally:
            m.shutdown()

    def test_eviction_keeps_newest_committed(self, tmp_path):
        store = DurableFileSystemPersistenceStore(
            str(tmp_path), revisions_to_keep=2)
        for i in range(5):
            store.save("a", f"{1000 + i}_a", pickle.dumps({"i": i}))
        assert store.revisions("a") == ["1003_a", "1004_a"]
        assert pickle.loads(store.load("a", "1004_a")) == {"i": 4}


class TestAsyncSyncEquivalence:
    def test_async_and_sync_state_trees_are_byte_identical(self, tmp_path):
        _exec, _body, stream, sends = ENGINES["device_single"]
        trees = {}
        for mode in ("sync", "async"):
            m = SiddhiManager()
            try:
                store = DurableFileSystemPersistenceStore(
                    str(tmp_path / mode))
                m.set_persistence_store(store)
                rt = m.create_siddhi_app_runtime(_app("device_single"))
                rt.start()
                h = rt.get_input_handler(stream)
                for row, ts in sends[:12]:
                    h.send(list(row), timestamp=ts)
                rev = rt.persist(mode=mode)
                assert rt.wait_for_persist(rev, timeout=30) in (
                    "committed", "idle")
                trees[mode] = store.load("dur", rev)
                rt.shutdown()
            finally:
                m.shutdown()
        assert trees["sync"] is not None
        assert trees["sync"] == trees["async"], (
            "async capture must persist the exact state the blocking "
            "path persists")


class TestDegradationCounters:
    def test_unfreezable_host_state_falls_back_counted(self, tmp_path):
        # host NFA instance lists cannot freeze-by-reference: they are
        # pickled in-barrier, the persist still commits, and the
        # degradation is counted — never silent
        body = ("define stream S (k long, v double); "
                "@info(name='q') from every e1=S[v > 50.0] "
                "-> e2=S[v > e1.v] within 10 sec "
                "select e1.v as a, e2.v as b insert into Out;")
        app = ("@app:name('hostpat') @app:playback "
               "@app:faults(journal='64') " + body)
        ref_m = SiddhiManager()
        try:
            rt = ref_m.create_siddhi_app_runtime(
                "@app:name('hostpat') @app:playback " + body)
            ref = []
            rt.add_callback("Out", lambda evs: ref.extend(tuple(e.data)
                                                          for e in evs))
            rt.start()
            h = rt.get_input_handler("S")
            for row, ts in kv_series(24, seed=3):
                h.send(list(row), timestamp=ts)
            rt.shutdown()
        finally:
            ref_m.shutdown()
        m = SiddhiManager()
        try:
            m.set_persistence_store(
                DurableFileSystemPersistenceStore(str(tmp_path)))
            rt = m.create_siddhi_app_runtime(app)
            got = []
            rt.add_callback("Out", lambda evs: got.extend(tuple(e.data)
                                                          for e in evs))
            rt.start()
            h = rt.get_input_handler("S")
            sends = kv_series(24, seed=3)
            for row, ts in sends[:12]:
                h.send(list(row), timestamp=ts)
            rev = rt.persist(mode="async")
            assert rt.wait_for_persist(rev, timeout=30) == "committed"
            assert rt._durability_stats().capture_fallback_elements > 0
            sm = rt.app_context.statistics_manager
            assert any(r.startswith("unfreezable")
                       for r in sm.persist_fallback_reasons.values())
            rt.shutdown()

            rt2 = m.create_siddhi_app_runtime(app)
            rt2.add_callback("Out", lambda evs: got.extend(tuple(e.data)
                                                           for e in evs))
            rt2.start()
            assert rt2.restore_last_revision() == rev
            h2 = rt2.get_input_handler("S")
            for row, ts in sends[12:]:
                h2.send(list(row), timestamp=ts)
            rt2.shutdown()
            assert got == ref, "prepickled-fallback restore diverged"
        finally:
            m.shutdown()

    def test_incremental_store_forces_counted_sync(self, tmp_path):
        _exec, _body, stream, sends = ENGINES["device_single"]
        m = SiddhiManager()
        try:
            m.set_persistence_store(
                IncrementalFileSystemPersistenceStore(str(tmp_path)))
            rt = m.create_siddhi_app_runtime(_app("device_single"))
            rt.start()
            h = rt.get_input_handler(stream)
            for row, ts in sends[:8]:
                h.send(list(row), timestamp=ts)
            rt.persist(mode="async")  # degrades to sync, counted
            sm = rt.app_context.statistics_manager
            assert sm.persist_fallback_reasons.get("dur") == (
                "incremental-store-sync-only")
            assert rt._durability_stats().persists_sync == 1
            assert rt._durability_stats().persists_async == 0
            rt.shutdown()
        finally:
            m.shutdown()

    def test_statistics_feed_reports_durability_metrics(self, tmp_path):
        _exec, _body, stream, sends = ENGINES["device_single"]
        m = SiddhiManager()
        try:
            m.set_persistence_store(
                DurableFileSystemPersistenceStore(str(tmp_path)))
            rt = m.create_siddhi_app_runtime(_app("device_single"))
            rt.start()
            h = rt.get_input_handler(stream)
            for row, ts in sends[:8]:
                h.send(list(row), timestamp=ts)
            rev = rt.persist(mode="async")
            assert rt.wait_for_persist(rev, timeout=30) == "committed"
            stats = rt.statistics()
            key = [k for k in stats if "Durability" in k
                   and k.endswith("persist_commits")]
            assert key and stats[key[0]] == 1
            assert stats[key[0].replace(
                "persist_commits", "persists_async")] == 1
            rt.shutdown()
        finally:
            m.shutdown()


class TestIncrementalChainHygiene:
    def test_restore_resets_digest_chain_to_base(self, tmp_path):
        # regression: an increment diffed against PRE-restore digests
        # poisons the chain — after any restore the next incremental
        # snapshot must be a full base
        _exec, _body, stream, sends = ENGINES["device_single"]
        m = SiddhiManager()
        try:
            m.set_persistence_store(
                IncrementalFileSystemPersistenceStore(str(tmp_path)))
            rt = m.create_siddhi_app_runtime(_app("device_single"))
            rt.start()
            h = rt.get_input_handler(stream)
            for row, ts in sends[:6]:
                h.send(list(row), timestamp=ts)
            rt.persist()  # base
            for row, ts in sends[6:12]:
                h.send(list(row), timestamp=ts)
            rt.persist()  # inc
            rt.restore_last_revision()
            svc = rt._snapshot_service()
            assert svc._digests == {} and svc._incs_since_base == 0
            kind, _data = svc.incremental_snapshot()
            assert kind == "base"
            rt.shutdown()
        finally:
            m.shutdown()


class TestBoundedInMemoryStore:
    def test_eviction_keeps_newest(self):
        store = InMemoryPersistenceStore(revisions_to_keep=5)
        for i in range(8):
            store.save("a", f"rev{i:02d}", b"x%d" % i)
        assert store.revisions("a") == [f"rev{i:02d}" for i in range(3, 8)]
        assert store.load("a", "rev02") is None
        assert store.load("a", "rev07") == b"x7"


class TestWriterUnit:
    def test_coalescing_supersedes_queued_not_inflight(self):
        w = AsyncCheckpointWriter("t")
        gate = threading.Event()
        abandoned = []
        w.submit("r1", lambda: gate.wait(10))
        deadline = time.monotonic() + 5
        while w.status("r1") != "inflight":
            assert time.monotonic() < deadline
            time.sleep(0.005)
        w.submit("r2", lambda: None, on_abandon=abandoned.append)
        w.submit("r3", lambda: None, on_abandon=abandoned.append)
        assert w.status("r2") == "superseded"
        assert abandoned == ["r2"]
        gate.set()
        assert w.wait("r1", timeout=10) == "committed"
        assert w.wait("r3", timeout=10) == "committed"
        assert w.stats.persists_coalesced == 1
        assert w.stats.persist_commits == 2
        w.shutdown()

    def test_retryable_fault_retries_then_commits(self):
        w = AsyncCheckpointWriter("t")
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("disk hiccup")

        w.submit("r", flaky)
        assert w.wait("r", timeout=10) == "committed"
        assert len(calls) == 3
        assert w.stats.persist_retries == 2
        w.shutdown()

    def test_non_retryable_failure_abandons_mark(self):
        w = AsyncCheckpointWriter("t")
        abandoned = []

        def broken():
            raise ValueError("cannot serialize")

        w.submit("r", broken, on_abandon=abandoned.append)
        assert w.wait("r", timeout=10) == "failed"
        assert abandoned == ["r"]
        assert w.stats.persist_failures == 1
        w.shutdown()

    def test_crashed_writer_rejects_new_submits(self):
        w = AsyncCheckpointWriter("t")

        def die():
            raise SimulatedCrashError("persist.write")

        w.submit("r", die)
        assert w.wait("r", timeout=10) == "crashed"
        with pytest.raises(SimulatedCrashError):
            w.submit("r2", lambda: None)


class TestPersistAnnotationAndService:
    def test_persist_interval_daemon_checkpoints(self, tmp_path):
        app = ("@app:name('periodic') @app:playback "
               "@app:persist(interval='50 millisec', mode='async') "
               + AGG_BODY)
        m = SiddhiManager()
        try:
            store = DurableFileSystemPersistenceStore(str(tmp_path))
            m.set_persistence_store(store)
            rt = m.create_siddhi_app_runtime(app)
            assert rt.app_context.persist_mode == "async"
            assert rt.app_context.persist_interval_ms == 50
            rt.start()
            h = rt.get_input_handler("S")
            for row, ts in kv_series(8):
                h.send(list(row), timestamp=ts)
            deadline = time.monotonic() + 10
            while not store.revisions("periodic"):
                assert time.monotonic() < deadline, "daemon never persisted"
                time.sleep(0.02)
            rt.shutdown()
            assert not getattr(rt, "_persist_stop", None)
        finally:
            m.shutdown()

    def test_bad_persist_annotation_rejected(self):
        from siddhi_tpu.core.exceptions import SiddhiAppCreationError

        m = SiddhiManager()
        try:
            with pytest.raises(SiddhiAppCreationError):
                m.create_siddhi_app_runtime(
                    "@app:name('bad') @app:persist(mode='turbo') "
                    + AGG_BODY)
        finally:
            m.shutdown()

    def test_service_persist_and_restore_endpoints(self, tmp_path):
        from siddhi_tpu.service import SiddhiService

        m = SiddhiManager()
        m.set_persistence_store(
            DurableFileSystemPersistenceStore(str(tmp_path)))
        svc = SiddhiService(manager=m)
        try:
            code, payload = svc.deploy(
                "@app:name('rest') @app:playback " + AGG_BODY)
            assert code == 200
            rt = svc.get_runtime("rest")
            h = rt.get_input_handler("S")
            for row, ts in kv_series(8):
                h.send(list(row), timestamp=ts)
            code, payload = svc.persist("rest")
            assert code == 200 and payload["revision"]
            code, payload = svc.restore_last("rest")
            assert code == 200 and payload["revision"]
            code, _ = svc.persist("nope")
            assert code == 404
        finally:
            svc.stop()
            m.shutdown()


class TestFileStoreJournalSegments:
    def test_segments_roundtrip_and_prune(self, tmp_path):
        from siddhi_tpu.util.persistence import FileSystemPersistenceStore

        store = FileSystemPersistenceStore(str(tmp_path))
        store.save_journal_segment("a", 1, 4, b"cold")
        store.save_journal_segment("a", 5, 8, b"warm")
        assert store.load_journal_segments("a") == [
            (1, 4, b"cold"), (5, 8, b"warm")]
        # the journal dir must not masquerade as a revision
        store.save("a", "100_a", b"snap")
        assert store.revisions("a") == ["100_a"]
        store.prune_journal_segments("a", 4)
        assert store.load_journal_segments("a") == [(5, 8, b"warm")]
        store.clear_journal("a")
        assert store.load_journal_segments("a") == []
