"""Statistics + playback-mode conformance tests.

Modeled on the reference managment suite
(modules/siddhi-core/src/test/java/io/siddhi/core/managment/
StatisticsTestCase / PlayBackTestCase): @app:statistics installs
throughput/latency trackers; @app:playback drives windows on event time,
with the idle heartbeat draining them when input stops.
"""

import time

import pytest

from siddhi_tpu import SiddhiManager


@pytest.fixture
def manager():
    m = SiddhiManager()
    yield m
    m.shutdown()


def test_statistics_trackers(manager):
    app = (
        "@app:name('statApp') @app:statistics('true') "
        "define stream S (v long); "
        "@info(name='q') from S select v insert into Out;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    h = rt.get_input_handler("S")
    for i in range(5):
        h.send([i])
    stats = rt.statistics()
    assert stats["io.siddhi.SiddhiApps.statApp.Siddhi.Streams.S.totalEvents"] == 5
    assert stats["io.siddhi.SiddhiApps.statApp.Siddhi.Queries.q.events"] == 5
    assert stats["io.siddhi.SiddhiApps.statApp.Siddhi.Queries.q.latencyAvgMs"] >= 0


def test_statistics_level_switchable(manager):
    app = (
        "@app:name('switchApp') "
        "define stream S (v long); "
        "@info(name='q') from S select v insert into Out;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    h = rt.get_input_handler("S")
    h.send([1])
    assert rt.statistics() == {}  # off by default
    rt.set_statistics_level("basic")
    h.send([2])
    stats = rt.statistics()
    assert stats["io.siddhi.SiddhiApps.switchApp.Siddhi.Streams.S.totalEvents"] == 1
    rt.set_statistics_level("off")
    h.send([3])
    assert rt.statistics() == {}  # downgrade drops the trackers


def test_playback_time_window_event_time(manager):
    """Windows run on event timestamps in playback mode
    (reference: PlayBackTestCase.playBackTest1)."""
    app = (
        "@app:playback "
        "define stream S (symbol string, price float); "
        "@info(name='q') from S#window.time(1 sec) "
        "select symbol, count() as n insert into Out;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    got = []
    rt.add_callback("q", lambda ts, ins, rem: got.extend(e.data for e in (ins or [])))
    h = rt.get_input_handler("S")
    t0 = 1_500_000_000_000
    h.send(["A", 1.0], timestamp=t0)
    h.send(["B", 2.0], timestamp=t0 + 100)
    assert got[-1][1] == 2
    # jump event time 2s forward: first two must have expired from the window
    h.send(["C", 3.0], timestamp=t0 + 2100)
    assert got[-1][1] == 1


def test_playback_idle_heartbeat_drains_window(manager):
    """With idle.time/increment, event time advances without events
    (reference: PlayBackTestCase heartbeat test)."""
    app = (
        "@app:playback(idle.time='50 millisecond', increment='1 sec') "
        "define stream S (symbol string); "
        "@info(name='q') from S#window.timeBatch(1 sec) "
        "select count() as n insert into Out;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    got = []
    rt.add_callback("q", lambda ts, ins, rem: got.extend(e.data for e in (ins or [])))
    h = rt.get_input_handler("S")
    h.send(["A"], timestamp=1_500_000_000_000)
    # no further events: the heartbeat must advance event time and flush
    deadline = time.time() + 3
    while not got and time.time() < deadline:
        time.sleep(0.02)
    assert got and got[-1][0] == 1
