"""Transport conformance, ported from the reference suites
(`transport/InMemoryTransportTestCase.java`,
`MultiClientDistributedSinkTestCase.java`, with the
`TestFailingInMemorySink`/`TestFailingInMemorySource` doubles):
dynamic sink options, failing-sink retry/backoff/drop accounting,
failing-source connect retries, multi-sink streams, and distributed
endpoint failover.
"""

import time

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.exceptions import ConnectionUnavailableError
from siddhi_tpu.transport.broker import InMemoryBroker, Subscriber
from siddhi_tpu.transport.sink import Sink
from siddhi_tpu.transport.source import Source


@pytest.fixture
def manager():
    m = SiddhiManager()
    yield m
    m.shutdown()


class _Topic(Subscriber):
    def __init__(self, topic):
        self.topic = topic
        self.messages = []

    def get_topic(self):
        return self.topic

    def on_message(self, msg):
        self.messages.append(msg)


class TestDynamicSinkOptions:
    def test_per_event_topic_routing(self, manager):
        """@sink(topic='{{symbol}}') routes each event by its own
        attribute value (reference:
        inMemorySinkAndEventMappingWithSiddhiQLDynamicParams:57)."""
        wso2, ibm = _Topic("WSO2"), _Topic("IBM")
        InMemoryBroker.subscribe(wso2)
        InMemoryBroker.subscribe(ibm)
        try:
            rt = manager.create_siddhi_app_runtime(
                "define stream FooStream (symbol string, price float, "
                "volume long); "
                "@sink(type='inMemory', topic='{{symbol}}', "
                "@map(type='passThrough')) "
                "define stream BarStream (symbol string, price float, "
                "volume long); "
                "from FooStream select * insert into BarStream;")
            rt.start()
            h = rt.get_input_handler("FooStream")
            h.send(["WSO2", 55.6, 100])
            h.send(["IBM", 75.6, 100])
            h.send(["WSO2", 57.6, 100])
            rt.shutdown()
            assert len(wso2.messages) == 2
            assert len(ibm.messages) == 1
            assert ibm.messages[0].data[1] == pytest.approx(75.6)
        finally:
            InMemoryBroker.unsubscribe(wso2)
            InMemoryBroker.unsubscribe(ibm)

    def test_static_topic_unchanged(self, manager):
        t = _Topic("fixed")
        InMemoryBroker.subscribe(t)
        try:
            rt = manager.create_siddhi_app_runtime(
                "define stream S (v long); "
                "@sink(type='inMemory', topic='fixed', "
                "@map(type='passThrough')) "
                "define stream Out (v long); "
                "from S select v insert into Out;")
            rt.start()
            rt.get_input_handler("S").send([1])
            rt.shutdown()
            assert len(t.messages) == 1
        finally:
            InMemoryBroker.unsubscribe(t)

    def test_unknown_template_attribute_errors(self, manager):
        rt = manager.create_siddhi_app_runtime(
            "define stream S (v long); "
            "@sink(type='inMemory', topic='{{nope}}', "
            "@map(type='passThrough')) "
            "define stream Out (v long); "
            "from S select v insert into Out;")
        errors = []
        rt.add_exception_listener(errors.append)
        rt.start()
        rt.get_input_handler("S").send([1])
        rt.shutdown()
        assert errors, "unresolvable template must surface an error"


class TestFailingSink:
    """The TestFailingInMemorySink contract: while the transport is
    down, publishes drop (counted), a single backoff reconnect chain
    runs, and delivery resumes after reconnection (reference:
    inMemoryWithFailingSink:511, inMemoryWithFailingSink1:579)."""

    def _failing_sink_cls(self, state):
        class FailingInMemorySink(Sink):
            def connect(self):
                if state["fail"]:
                    state["errors"] += 1
                    raise ConnectionUnavailableError("connect failed")

            def publish(self, payload):
                if state["fail"]:
                    state["errors"] += 1
                    raise ConnectionUnavailableError("transport down")
                InMemoryBroker.publish(self.resolve_option("topic"), payload)

        return FailingInMemorySink

    def test_temporary_failure_drops_then_recovers(self, manager):
        state = {"fail": False, "errors": 0}
        manager.set_extension("testFailingInMemory",
                              self._failing_sink_cls(state), kind="sink")
        wso2, ibm = _Topic("WSO2"), _Topic("IBM")
        InMemoryBroker.subscribe(wso2)
        InMemoryBroker.subscribe(ibm)
        try:
            rt = manager.create_siddhi_app_runtime(
                "define stream FooStream (symbol string, price float, "
                "volume long); "
                "@sink(type='testFailingInMemory', topic='{{symbol}}', "
                "retry.scale='0.0001', @map(type='passThrough')) "
                "define stream BarStream (symbol string, price float, "
                "volume long); "
                "from FooStream select * insert into BarStream;")
            rt.start()
            h = rt.get_input_handler("FooStream")
            h.send(["WSO2", 55.6, 100])
            h.send(["IBM", 75.6, 100])
            state["fail"] = True
            h.send(["WSO2", 57.6, 100])  # publish fails, dropped
            h.send(["WSO2", 57.6, 100])  # not connected, dropped
            state["fail"] = False
            deadline = time.time() + 2
            while not rt.sinks[0].connected and time.time() < deadline:
                time.sleep(0.005)
            h.send(["IBM", 75.6, 100])
            rt.shutdown()
            # reference assertions: 1 WSO2 delivery, 2 IBM deliveries,
            # both down-window WSO2 events dropped
            assert len(wso2.messages) == 1
            assert len(ibm.messages) == 2
            assert state["errors"] >= 1
        finally:
            InMemoryBroker.unsubscribe(wso2)
            InMemoryBroker.unsubscribe(ibm)

    def test_always_failing_delivers_nothing(self, manager):
        state = {"fail": True, "errors": 0}
        manager.set_extension("testFailingInMemory",
                              self._failing_sink_cls(state), kind="sink")
        t = _Topic("T")
        InMemoryBroker.subscribe(t)
        try:
            rt = manager.create_siddhi_app_runtime(
                "define stream S (v long); "
                "@sink(type='testFailingInMemory', topic='T', "
                "retry.scale='0.0001', @map(type='passThrough')) "
                "define stream Out (v long); "
                "from S select v insert into Out;")
            rt.start()
            h = rt.get_input_handler("S")
            for i in range(4):
                h.send([i])
            time.sleep(0.05)
            rt.shutdown()
            assert t.messages == []
            assert state["errors"] >= 4  # every attempt errored
        finally:
            InMemoryBroker.unsubscribe(t)


class TestFailingSource:
    def test_source_connects_after_failures_then_flows(self, manager):
        """reference: inMemoryWithFailingSource:650 — events sent while
        the source cannot connect are lost; flow resumes after the
        retry chain connects."""
        state = {"failures_left": 2, "attempts": 0}

        class FailingInMemorySource(Source):
            def connect(self):
                state["attempts"] += 1
                if state["failures_left"] > 0:
                    state["failures_left"] -= 1
                    raise ConnectionUnavailableError("broker down")
                self._sub = type("S", (Subscriber,), {
                    "get_topic": lambda s: self.options.get("topic"),
                    "on_message": lambda s, msg: self.deliver(msg),
                })()
                InMemoryBroker.subscribe(self._sub)

            def disconnect(self):
                sub = getattr(self, "_sub", None)
                if sub is not None:
                    InMemoryBroker.unsubscribe(sub)

        manager.set_extension("testFailingInMemorySource",
                              FailingInMemorySource, kind="source")
        rt = manager.create_siddhi_app_runtime(
            "@source(type='testFailingInMemorySource', topic='IN', "
            "retry.scale='0.0001', @map(type='passThrough')) "
            "define stream S (v long); "
            "from S select v insert into Out;")
        got = []
        rt.add_callback("Out", lambda evs: got.extend(e.data for e in evs))
        rt.start()
        deadline = time.time() + 2
        while state["attempts"] < 3 and time.time() < deadline:
            time.sleep(0.005)
        assert rt.sources[0].connected
        from siddhi_tpu.core.event import Event

        InMemoryBroker.publish("IN", Event(data=[42]))
        rt.shutdown()
        assert state["attempts"] == 3  # 2 failures + 1 success
        assert got == [[42]]


class TestMultiSinkStream:
    def test_two_sinks_same_stream(self, manager):
        """reference: inMemoryTestCase3:367 — two @sink annotations on
        one stream publish every event to both topics."""
        t1, t2 = _Topic("topic1"), _Topic("topic2")
        InMemoryBroker.subscribe(t1)
        InMemoryBroker.subscribe(t2)
        try:
            rt = manager.create_siddhi_app_runtime(
                "define stream S (v long); "
                "@sink(type='inMemory', topic='topic1', "
                "@map(type='passThrough')) "
                "@sink(type='inMemory', topic='topic2', "
                "@map(type='passThrough')) "
                "define stream Out (v long); "
                "from S select v insert into Out;")
            rt.start()
            h = rt.get_input_handler("S")
            h.send([1])
            h.send([2])
            rt.shutdown()
            assert len(t1.messages) == 2
            assert len(t2.messages) == 2
        finally:
            InMemoryBroker.unsubscribe(t1)
            InMemoryBroker.unsubscribe(t2)


class TestDistributedSinkFailover:
    def test_roundrobin_skips_failed_endpoint(self, manager):
        """reference: MultiClientDistributedSinkTestCase — when one
        endpoint fails, round-robin continues over the remaining
        endpoints; the endpoint rejoins after its reconnect."""
        state = {"fail_topic": None, "errors": 0}

        class FlakyInMemorySink(Sink):
            def connect(self):
                if self.resolve_option("topic") == state["fail_topic"]:
                    raise ConnectionUnavailableError("endpoint down")

            def publish(self, payload):
                topic = self.resolve_option("topic")
                if topic == state["fail_topic"]:
                    state["errors"] += 1
                    raise ConnectionUnavailableError("endpoint down")
                InMemoryBroker.publish(topic, payload)

        manager.set_extension("flakyInMemory", FlakyInMemorySink,
                              kind="sink")
        t1, t2 = _Topic("d1"), _Topic("d2")
        InMemoryBroker.subscribe(t1)
        InMemoryBroker.subscribe(t2)
        try:
            rt = manager.create_siddhi_app_runtime(
                "define stream S (v long); "
                "@sink(type='flakyInMemory', retry.scale='0.0001', "
                "@map(type='passThrough'), "
                "@distribution(strategy='roundRobin', "
                "@destination(topic='d1'), @destination(topic='d2'))) "
                "define stream Out (v long); "
                "from S select v insert into Out;")
            rt.start()
            h = rt.get_input_handler("S")
            h.send([1])  # -> d1
            h.send([2])  # -> d2
            state["fail_topic"] = "d2"
            h.send([3])  # -> d1 (rotation counter)
            h.send([4])  # -> d2 fails (dropped); d2 leaves rotation
            h.send([5])  # -> d1 (only active endpoint)
            state["fail_topic"] = None
            deadline = time.time() + 2
            sink = rt.sinks[0]
            while (not all(c.connected for c in sink.children)
                   and time.time() < deadline):
                time.sleep(0.005)
            h.send([6])  # d2 re-admitted: round robin over both again
            h.send([7])
            rt.shutdown()
            d1_vals = [m.data[0] for m in t1.messages]
            d2_vals = [m.data[0] for m in t2.messages]
            assert d1_vals[:3] == [1, 3, 5], d1_vals
            assert 4 not in d1_vals + d2_vals  # dropped while down
            assert d2_vals[0] == 2 and len(d2_vals) == 2, d2_vals
            # post-recovery, 6 and 7 alternate over both endpoints
            assert sorted(d1_vals[3:] + d2_vals[1:]) == [6, 7]
            assert state["errors"] == 1
        finally:
            InMemoryBroker.unsubscribe(t1)
            InMemoryBroker.unsubscribe(t2)

    def test_broadcast_excludes_failed_endpoint(self, manager):
        state = {"fail_topic": None}

        class FlakySink(Sink):
            def publish(self, payload):
                topic = self.resolve_option("topic")
                if topic == state["fail_topic"]:
                    raise ConnectionUnavailableError("down")
                InMemoryBroker.publish(topic, payload)

        manager.set_extension("flaky2", FlakySink, kind="sink")
        t1, t2 = _Topic("b1"), _Topic("b2")
        InMemoryBroker.subscribe(t1)
        InMemoryBroker.subscribe(t2)
        try:
            rt = manager.create_siddhi_app_runtime(
                "define stream S (v long); "
                "@sink(type='flaky2', retry.scale='100000', "
                "@map(type='passThrough'), "
                "@distribution(strategy='broadcast', "
                "@destination(topic='b1'), @destination(topic='b2'))) "
                "define stream Out (v long); "
                "from S select v insert into Out;")
            rt.start()
            h = rt.get_input_handler("S")
            h.send([1])  # both
            state["fail_topic"] = "b2"
            h.send([2])  # b2 fails and leaves the broadcast set
            h.send([3])  # b1 only
            rt.shutdown()
            assert [m.data[0] for m in t1.messages] == [1, 2, 3]
            assert [m.data[0] for m in t2.messages] == [1]
        finally:
            InMemoryBroker.unsubscribe(t1)
            InMemoryBroker.unsubscribe(t2)
