"""Doc generator tests (reference: siddhi-doc-gen renders @Extension
metadata to markdown)."""

from siddhi_tpu.docgen import generate_markdown


def test_generates_all_kinds():
    md = generate_markdown()
    for heading in ("Windows", "Sources", "Sinks", "Stores", "Script languages"):
        assert heading in md
    # a few concrete extensions with their docstrings
    assert "### `cron`" in md
    assert "CronWindowProcessor" in md
    assert "### `inMemory`" in md


def test_cli_writes_file(tmp_path):
    from siddhi_tpu.docgen import main

    out = tmp_path / "ext.md"
    assert main([str(out)]) == 0
    assert out.read_text().startswith("# siddhi_tpu extensions")
