"""Extension SPI tests (reference: query/extension/*TestCase.java —
custom functions/windows registered via siddhiManager.setExtension, and
script-defined functions)."""

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.extension.function import FunctionExecutor
from siddhi_tpu.query_api import AttrType


@pytest.fixture
def manager():
    m = SiddhiManager()
    yield m
    m.shutdown()


def run(manager, app, rows, out="O", stream="S"):
    rt = manager.create_siddhi_app_runtime(app)
    got = []
    rt.add_callback(out, lambda evs: got.extend(evs))
    rt.start()
    h = rt.get_input_handler(stream)
    for r in rows:
        h.send(r)
    rt.shutdown()
    return got


class TestScriptFunctions:
    def test_python_expression_body(self, manager):
        got = run(manager,
                  "define function double[python] return long { data[0] * 2 }; "
                  "define stream S (v long); "
                  "from S select double(v) as d insert into O;",
                  [[21]])
        assert [e.data[0] for e in got] == [42]

    def test_python_statement_body_result(self, manager):
        got = run(manager,
                  "define function tag[python] return string "
                  "{ result = 'v=' + str(data[0]) }; "
                  "define stream S (v long); "
                  "from S select tag(v) as t insert into O;",
                  [[7]])
        assert [e.data[0] for e in got] == ["v=7"]

    def test_two_arg_script(self, manager):
        got = run(manager,
                  "define function addem[python] return long { data[0] + data[1] }; "
                  "define stream S (a long, b long); "
                  "from S select addem(a, b) as s insert into O;",
                  [[3, 4]])
        assert [e.data[0] for e in got] == [7]

    def test_script_in_filter(self, manager):
        got = run(manager,
                  "define function isBig[python] return bool { data[0] > 10 }; "
                  "define stream S (v long); "
                  "from S[isBig(v)] select v insert into O;",
                  [[5], [50]])
        assert [e.data[0] for e in got] == [50]

    def test_unknown_language_raises(self, manager):
        from siddhi_tpu.core.exceptions import SiddhiAppCreationError

        with pytest.raises(SiddhiAppCreationError):
            manager.create_siddhi_app_runtime(
                "define function f[cobol] return long { 42 }; "
                "define stream S (v long); from S select f() as x insert into O;"
            )

    def test_javascript_needs_engine(self, manager):
        from siddhi_tpu.core.exceptions import SiddhiAppCreationError

        with pytest.raises(SiddhiAppCreationError, match="JavaScript"):
            manager.create_siddhi_app_runtime(
                "define function f[javascript] return long { return 42; }; "
                "define stream S (v long); from S select f() as x insert into O;"
            )


class TestCustomFunctionExtension:
    def test_function_executor(self, manager):
        class PlusOne(FunctionExecutor):
            return_type = AttrType.LONG

            def execute(self, v):
                return v + 1

        manager.set_extension("custom:plusOne", PlusOne, kind="function")
        got = run(manager,
                  "define stream S (v long); "
                  "from S select custom:plusOne(v) as d insert into O;",
                  [[41]])
        assert [e.data[0] for e in got] == [42]

    def test_plain_callable(self, manager):
        manager.set_extension("sq", lambda v: v * v, kind="function")
        got = run(manager,
                  "define stream S (v long); from S select sq(v) as d insert into O;",
                  [[9]])
        assert [e.data[0] for e in got] == [81]

    def test_remove_extension(self, manager):
        manager.set_extension("gone", lambda v: v, kind="function")
        manager.remove_extension("gone", kind="function")
        with pytest.raises(Exception):
            manager.create_siddhi_app_runtime(
                "define stream S (v long); from S select gone(v) as d insert into O;"
            )


class TestCustomWindowExtension:
    def test_custom_window(self, manager):
        from siddhi_tpu.ops.windows import LengthWindow

        class KeepOne(LengthWindow):
            def __init__(self, args, attribute_names):
                # fixed capacity 1 regardless of args
                from siddhi_tpu.planner.expr import CompiledExpression
                from siddhi_tpu.query_api import AttrType as T

                one = CompiledExpression(lambda env: 1, T.INT)
                super().__init__([one], attribute_names)

        manager.set_extension("custom:keepOne", KeepOne, kind="window")
        got = run(manager,
                  "define stream S (v long); "
                  "from S#window.custom:keepOne() select sum(v) as t "
                  "insert into O;",
                  [[1], [2], [3]])
        assert [e.data[0] for e in got] == [1, 2, 3]


class TestParameterValidation:
    """Plan-time extension argument validation (reference:
    util/extension/validator/InputParameterValidator.java)."""

    def test_bad_arity_fails_at_creation(self, manager):
        from siddhi_tpu.core.exceptions import SiddhiAppValidationError

        with pytest.raises(SiddhiAppValidationError):
            manager.create_siddhi_app_runtime(
                "define stream S (v long); "
                "from S#window.length(2, 3) select v insert into OutputStream;"
            )

    def test_bad_type_fails_at_creation(self, manager):
        from siddhi_tpu.core.exceptions import SiddhiAppValidationError

        with pytest.raises(SiddhiAppValidationError):
            manager.create_siddhi_app_runtime(
                "define stream S (v long); "
                "from S#window.length('two') select v insert into OutputStream;"
            )

    def test_named_window_validated(self, manager):
        from siddhi_tpu.core.exceptions import SiddhiAppValidationError

        with pytest.raises(SiddhiAppValidationError):
            manager.create_siddhi_app_runtime(
                "define stream S (v long); "
                "define window W (v long) time(1 sec, 2 sec) output all events; "
                "from S insert into W;"
            )

    def test_repetitive_overload_accepts_tail(self, manager):
        # sort(length, attr, 'asc') exercises the REPEAT marker
        rt = manager.create_siddhi_app_runtime(
            "define stream S (v long); "
            "from S#window.sort(2, v, 'asc') select v insert into OutputStream;"
        )
        rt.shutdown()

    def test_custom_extension_without_declaration_unchecked(self, manager):
        from siddhi_tpu.ops.windows import WindowProcessor

        class AnyArgsWindow(WindowProcessor):
            def process(self, batch, now):
                return batch

        manager.set_extension("anyArgs", AnyArgsWindow, kind="window")
        rt = manager.create_siddhi_app_runtime(
            "define stream S (v long); "
            "from S#window.anyArgs(1, 'x', v) select v insert into OutputStream;"
        )
        rt.shutdown()


class TestCustomAggregators:
    def test_custom_aggregator_extension(self, manager):
        # reference: custom AttributeAggregatorExecutor extensions
        # (util/extension/holder/AttributeAggregatorExtensionHolder);
        # the factory receives the argument type and implements the
        # AggExecutor run protocol
        import numpy as np

        from siddhi_tpu.ops.aggregators import AggExecutor

        class GeoMean(AggExecutor):
            return_type = AttrType.DOUBLE

            def __init__(self, arg_type=None):
                pass

            def new_state(self):
                return {"logsum": 0.0, "n": 0}

            def add_run(self, state, values):
                logs = np.log(values.astype(np.float64))
                cum = state["logsum"] + np.cumsum(logs)
                ns = state["n"] + np.arange(1, len(values) + 1)
                state["logsum"] = cum[-1] if len(cum) else state["logsum"]
                state["n"] += len(values)
                return np.exp(cum / ns)

            def remove_run(self, state, values):
                logs = np.log(values.astype(np.float64))
                cum = state["logsum"] - np.cumsum(logs)
                ns = state["n"] - np.arange(1, len(values) + 1)
                state["logsum"] = cum[-1] if len(cum) else state["logsum"]
                state["n"] -= len(values)
                return np.exp(cum / np.maximum(ns, 1))

        manager.set_extension("custom:geoMean", GeoMean, kind="aggregator")
        got = run(manager,
                  "define stream S (v double); "
                  "from S select custom:geoMean(v) as g insert into O;",
                  [[2.0], [8.0]])
        vals = [e.data[0] for e in got]
        assert vals[0] == pytest.approx(2.0)
        assert vals[1] == pytest.approx(4.0)  # sqrt(2*8)

    def test_custom_aggregator_with_group_by(self, manager):
        import numpy as np

        from siddhi_tpu.ops.aggregators import AggExecutor

        class Last(AggExecutor):
            return_type = AttrType.DOUBLE

            def __init__(self, arg_type=None):
                pass

            def new_state(self):
                return {"last": None}

            def add_run(self, state, values):
                state["last"] = float(values[-1])
                return values.astype(np.float64)

            def remove_run(self, state, values):
                return np.full(len(values), state["last"] or 0.0)

        manager.set_extension("lastVal", Last, kind="aggregator")
        got = run(manager,
                  "define stream S (k string, v double); "
                  "from S select k, lastVal(v) as l group by k "
                  "insert into O;",
                  [["a", 1.0], ["b", 5.0], ["a", 3.0]])
        assert [list(e.data) for e in got] == [
            ["a", 1.0], ["b", 5.0], ["a", 3.0]]


class TestBuiltinStreamFunctions:
    def test_pol2cart_appends_xy(self, manager):
        # reference Pol2CartStreamFunctionProcessor.java:149
        import math

        got = run(manager,
                  "define stream P (theta double, rho double); "
                  "from P#pol2Cart(theta, rho) select x, y insert into O;",
                  [[60.0, 2.0]], stream="P")
        x, y = got[0].data
        assert x == pytest.approx(2 * math.cos(math.radians(60.0)))
        assert y == pytest.approx(2 * math.sin(math.radians(60.0)))

    def test_pol2cart_z_passthrough_and_downstream_filter(self, manager):
        got = run(manager,
                  "define stream P (theta double, rho double, e double); "
                  "from P#pol2Cart(theta, rho, e)[x > 0.5] "
                  "select x, z insert into O;",
                  [[60.0, 2.0, 5.0],     # x = 1.0: kept
                   [120.0, 0.4, 6.0]],   # x = -0.2: filtered
                  stream="P")
        assert len(got) == 1
        assert got[0].data[1] == pytest.approx(5.0)

    def test_log_function_passthrough(self, manager):
        got = run(manager,
                  "define stream S (v double); "
                  "from S#log('checkpoint') select v insert into O;",
                  [[7.0], [8.0]])
        assert [e.data[0] for e in got] == [7.0, 8.0]

    def test_pol2cart_select_star_and_sibling_isolation(self, manager):
        # select * includes the appended columns, and a sibling query on
        # the SAME stream must not see them (no shared-batch mutation)
        rt = manager.create_siddhi_app_runtime(
            "define stream P (theta double, rho double); "
            "@info(name='q1') from P#pol2Cart(theta, rho) "
            "select * insert into O; "
            "@info(name='q2') from P select * insert into O2;")
        star, sib = [], []
        rt.add_callback("O", lambda evs: star.extend(list(e.data) for e in evs))
        rt.add_callback("O2", lambda evs: sib.extend(list(e.data) for e in evs))
        rt.start()
        rt.get_input_handler("P").send([0.0, 2.0])
        rt.shutdown()
        assert len(star) == 1 and len(star[0]) == 4   # theta, rho, x, y
        assert star[0][2] == pytest.approx(2.0)       # x = rho*cos(0)
        assert sib == [[0.0, 2.0]]                    # untouched schema
