"""Absent-pattern conformance: `not X for t` timing edges.

Ported behavior families from the reference's absent suites
(modules/siddhi-core/src/test/java/io/siddhi/core/query/pattern/absent/
AbsentPatternTestCase.java, EveryAbsentPatternTestCase.java,
LogicalAbsentPatternTestCase.java).  Event-time playback replaces the
reference's Thread.sleep: a Tick stream advances the watermark so absent
deadlines fire deterministically.
"""

import pytest

from siddhi_tpu import SiddhiManager

STREAMS = (
    "define stream Stream1 (symbol string, price float, volume int); "
    "define stream Stream2 (symbol string, price float, volume int); "
    "define stream Tick (x int); "
)
# the Tick consumer keeps the junction alive so ticks always advance the
# watermark even when no other query reads Tick
TICK_SINK = "from Tick select x insert into IgnoredTicks; "


def run(query, sends, out="OutputStream"):
    """sends: (stream, row, ts) — rows sent in playback event time."""
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime(
            "@app:playback " + STREAMS + TICK_SINK + query)
        got = []
        rt.add_callback(out, lambda evs: got.extend(e.data for e in evs))
        rt.start()
        for stream, row, ts in sends:
            rt.get_input_handler(stream).send(row, timestamp=ts)
        rt.shutdown()
        return got
    finally:
        m.shutdown()


class TestTrailingAbsent:
    """e1 -> not e2 for T (reference AbsentPatternTestCase 1-8)."""

    Q = ("@info(name='q') from e1=Stream1[price>20] -> "
         "not Stream2[price>e1.price] for 1 sec "
         "select e1.symbol as symbol1 insert into OutputStream;")

    def test_emits_when_nothing_arrives(self):
        got = run(self.Q, [
            ("Stream1", ["WSO2", 55.6, 100], 1000),
            ("Tick", [1], 2500),  # watermark passes the 2000 deadline
        ])
        assert got == [["WSO2"]]

    def test_e2_after_deadline_still_emits(self):
        got = run(self.Q, [
            ("Stream1", ["WSO2", 55.6, 100], 1000),
            ("Stream2", ["IBM", 58.7, 100], 2100),  # too late to cancel
        ])
        assert got == [["WSO2"]]

    def test_e2_within_window_cancels(self):
        got = run(self.Q, [
            ("Stream1", ["WSO2", 55.6, 100], 1000),
            ("Stream2", ["IBM", 58.7, 100], 1500),  # cancels the absence
            ("Tick", [1], 3000),
        ])
        assert got == []

    def test_non_matching_e2_does_not_cancel(self):
        # e2 filter is price > e1.price: a lower price is not "presence"
        got = run(self.Q, [
            ("Stream1", ["WSO2", 55.6, 100], 1000),
            ("Stream2", ["IBM", 10.0, 100], 1500),
            ("Tick", [1], 2500),
        ])
        assert got == [["WSO2"]]

    def test_deadline_boundary_exact(self):
        # watermark exactly AT the deadline fires it (>=)
        got = run(self.Q, [
            ("Stream1", ["WSO2", 55.6, 100], 1000),
            ("Tick", [1], 2000),
        ])
        assert got == [["WSO2"]]

    def test_without_matching_e1_nothing_fires(self):
        got = run(self.Q, [
            ("Stream1", ["WSO2", 5.0, 100], 1000),  # fails price>20
            ("Tick", [1], 5000),
        ])
        assert got == []


class TestEveryTrailingAbsent:
    """every e1 -> not e2 for T (reference EveryAbsentPatternTestCase)."""

    Q = ("@info(name='q') from every e1=Stream1[price>20] -> "
         "not Stream2[price>e1.price] for 1 sec "
         "select e1.symbol as symbol1 insert into OutputStream;")

    def test_every_arm_fires_independently(self):
        got = run(self.Q, [
            ("Stream1", ["WSO2", 55.6, 100], 1000),
            ("Stream1", ["GOOG", 40.0, 100], 1400),
            ("Tick", [1], 3000),  # both deadlines (2000, 2400) pass
        ])
        assert sorted(g[0] for g in got) == ["GOOG", "WSO2"]

    def test_cancel_one_arm_keep_other(self):
        got = run(self.Q, [
            ("Stream1", ["WSO2", 55.6, 100], 1000),
            ("Stream1", ["GOOG", 40.0, 100], 1400),
            # cancels BOTH arms? price 60 > 55.6 and > 40.0 — yes both
            ("Stream2", ["X", 60.0, 1], 1500),
            ("Tick", [1], 3000),
        ])
        assert got == []

    def test_cancel_only_lower_arm(self):
        got = run(self.Q, [
            ("Stream1", ["WSO2", 55.6, 100], 1000),
            ("Stream1", ["GOOG", 40.0, 100], 1400),
            # 45.0 > 40.0 only: cancels the GOOG arm, WSO2 fires
            ("Stream2", ["X", 45.0, 1], 1500),
            ("Tick", [1], 3000),
        ])
        assert [g[0] for g in got] == ["WSO2"]

    def test_rearms_after_firing(self):
        got = run(self.Q, [
            ("Stream1", ["WSO2", 55.6, 100], 1000),
            ("Tick", [1], 2500),   # first absence fires
            ("Stream1", ["IBM", 30.0, 100], 3000),
            ("Tick", [1], 4500),   # second absence fires
        ])
        assert [g[0] for g in got] == ["WSO2", "IBM"]


class TestLogicalAbsent:
    """(e1 and not e2 for T) shapes
    (reference LogicalAbsentPatternTestCase)."""

    def test_and_not_waits_full_window_from_start(self):
        # the leading absent side's clock runs from QUERY START
        # (reference: AbsentStreamPreStateProcessor arms its scheduler
        # when the start state activates); e1 within the window waits
        # for the deadline before completing
        q = ("@info(name='q') from e1=Stream1[price>20] and "
             "not Stream2[price>50] for 1 sec "
             "select e1.symbol as symbol1 insert into OutputStream;")
        got = run(q, [
            ("Stream1", ["WSO2", 55.6, 100], 300),
            ("Tick", [1], 2500),  # deadline (start + 1 sec) passes
        ])
        assert got == [["WSO2"]]

    def test_and_not_canceled_by_presence(self):
        q = ("@info(name='q') from e1=Stream1[price>20] and "
             "not Stream2[price>50] for 1 sec "
             "select e1.symbol as symbol1 insert into OutputStream;")
        got = run(q, [
            ("Stream1", ["WSO2", 55.6, 100], 300),
            ("Stream2", ["IBM", 70.0, 100], 600),  # inside the window
            ("Tick", [1], 3000),
        ])
        assert got == []

    def test_chained_after_absent_completion(self):
        q = ("@info(name='q') from e1=Stream1[price>20] -> "
             "not Stream2[price>e1.price] for 1 sec -> "
             "e3=Stream1[price>e1.price] "
             "select e1.symbol as s1, e3.symbol as s3 "
             "insert into OutputStream;")
        got = run(q, [
            ("Stream1", ["WSO2", 55.6, 100], 1000),
            ("Tick", [1], 2500),                       # absence holds
            ("Stream1", ["IBM", 75.0, 100], 3000),     # completes chain
        ])
        assert got == [["WSO2", "IBM"]]

    def test_chain_blocked_when_absence_violated(self):
        q = ("@info(name='q') from e1=Stream1[price>20] -> "
             "not Stream2[price>e1.price] for 1 sec -> "
             "e3=Stream1[price>e1.price] "
             "select e1.symbol as s1, e3.symbol as s3 "
             "insert into OutputStream;")
        got = run(q, [
            ("Stream1", ["WSO2", 55.6, 100], 1000),
            ("Stream2", ["X", 60.0, 1], 1500),         # violates absence
            ("Stream1", ["IBM", 75.0, 100], 3000),
        ])
        assert got == []
