"""Observability layer: cycle tracing, flight recorder, histograms,
Prometheus exposition, and the statistics-manager hardening that rides
along.

The differential acceptance test kills a device app mid-stream with the
fault injector and asserts the flight-recorder dump holds complete,
correctly ordered ingest -> step -> emit spans for the final cycles —
the black-box post-mortem the recorder exists for.
"""

import json
import re
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.event import EventBatch
from siddhi_tpu.core.exceptions import (
    SiddhiAppCreationError,
    SimulatedCrashError,
)
from siddhi_tpu.observability import (
    FlightRecorder,
    LatencyHistogram,
    Tracer,
    render_prometheus,
)
from siddhi_tpu.observability.prometheus import CONTENT_TYPE
from siddhi_tpu.service import SiddhiService
from siddhi_tpu.util.statistics import (
    LatencyTracker,
    StatisticsManager,
    ThroughputTracker,
)

PATTERN_BODY = (
    "define stream S (k long, v double); "
    "@info(name='q') from every a=S[v > 8.0] -> b=S[v > 12.0] "
    "select b.v as bv insert into Out;")


def device_app(name, trace="", faults=""):
    return (f"@app:name('{name}') @app:playback @app:execution('tpu') "
            f"{trace}{faults}" + PATTERN_BODY)


def make_batch(i, n=32, seed=3):
    rng = np.random.default_rng(seed + i)
    return EventBatch(
        "S", ["k", "v"],
        {"k": np.arange(n, dtype=np.int64) % 4,
         "v": rng.uniform(0.0, 20.0, n)},
        np.full(n, 1_000 + i * 10, dtype=np.int64))


# -- histograms ---------------------------------------------------------------


def test_histogram_quantiles():
    h = LatencyHistogram()
    for _ in range(100):
        h.record_ms(0.75)  # lands in the (0.5, 1.0] bucket
    assert h.count == 100
    assert h.sum_ms == pytest.approx(75.0)
    assert h.max_ms == pytest.approx(0.75)
    # every quantile interpolates inside the landing bucket
    assert 0.5 < h.p50_ms() <= 1.0
    assert 0.5 < h.p99_ms() <= 1.0
    h.reset()
    assert h.count == 0 and h.sum_ms == 0.0 and h.p50_ms() == 0.0


def test_histogram_spread_and_overflow():
    h = LatencyHistogram()
    for v in (0.06, 0.06, 0.06, 200.0, 200.0, 9_999.0):
        h.record_ms(v)
    # p50 lands among the 0.06ms samples, p99 in the tail
    assert h.p50_ms() <= 0.25
    assert h.p95_ms() > 100.0
    # overflow bucket (beyond the last bound) reports the observed max
    assert h.quantile_ms(0.999) == pytest.approx(9_999.0)
    bounds, counts, sum_ms, count = h.snapshot()
    assert count == 6 and sum(counts) == 6
    assert len(bounds) == len(LatencyHistogram.BOUNDS_MS)


def test_histogram_record_s_converts():
    h = LatencyHistogram()
    h.record_s(0.002)
    assert h.max_ms == pytest.approx(2.0)


# -- throughput tracker: windowed rate fix ------------------------------------


def test_throughput_windowed_rate_tracks_recent_traffic():
    now = [0.0]
    t = ThroughputTracker("S", clock=lambda: now[0])
    # 1000 ev/s for the first window
    for _ in range(5):
        t.add(1000)
        now[0] += 1.0
    first = t.events_per_second()
    assert first == pytest.approx(1000.0, rel=0.05)
    # then 45s of silence: the windowed rate decays toward zero while
    # the lifetime rate only divides by the longer elapsed time
    now[0] += 45.0
    assert t.events_per_second() < t.lifetime_events_per_second()
    assert t.events_per_second() < first * 0.1
    assert t.lifetime_events_per_second() == pytest.approx(
        5000.0 / 50.0, rel=0.01)
    assert t.count == 5000


def test_throughput_young_tracker_matches_lifetime():
    now = [0.0]
    t = ThroughputTracker("S", clock=lambda: now[0])
    t.add(100)
    now[0] += 1.0  # window not yet closed
    assert t.events_per_second() == pytest.approx(
        t.lifetime_events_per_second())


def test_throughput_reset():
    now = [0.0]
    t = ThroughputTracker("S", clock=lambda: now[0])
    t.add(100)
    now[0] += 10.0
    t.events_per_second()
    t.reset()
    assert t.count == 0
    assert t.events_per_second() == 0.0
    assert t.lifetime_events_per_second() == 0.0


# -- latency tracker percentiles ----------------------------------------------


def test_latency_tracker_percentiles_ride_along():
    lt = LatencyTracker("q")
    for _ in range(10):
        lt.mark_in(4)
        lt.mark_out(4)
    # existing keys keep their semantics
    assert lt.batches == 10 and lt.events == 40
    assert lt.avg_ms() >= 0.0 and lt.max_ms() >= lt.avg_ms()
    # new percentile read-outs come from the histogram
    assert lt.hist.count == 10
    assert lt.p50_ms() >= 0.0
    assert lt.p99_ms() >= lt.p50_ms()
    lt.reset()
    assert lt.hist.count == 0 and lt.p50_ms() == 0.0


def test_statistics_feed_has_percentile_keys():
    sm = StatisticsManager("app")
    lt = sm.latency_tracker("q")
    lt.mark_in(2)
    lt.mark_out(2)
    st = sm.stats()
    base = "io.siddhi.SiddhiApps.app.Siddhi.Queries.q."
    for metric in ("latencyAvgMs", "latencyMaxMs", "latencyP50Ms",
                   "latencyP95Ms", "latencyP99Ms", "events"):
        assert base + metric in st


# -- tracer sampling ----------------------------------------------------------


def test_tracer_sampling_strides():
    t = Tracer("app", sample=4)
    toks = [t.begin_cycle("device", 1) for _ in range(8)]
    sampled = [tok for tok in toks if tok is not None]
    # ids 1..8: only 4 and 8 hit the 1-in-4 stride
    assert [tok.cycle for tok in sampled] == [4, 8]
    assert Tracer("app", sample=0).begin_cycle("device", 1) is None
    every = Tracer("app", sample=1)
    assert all(every.begin_cycle("device", 1) is not None
               for _ in range(5))


def test_tracer_stage_stats_only_reports_recorded_stages():
    t = Tracer("app", sample=1)
    assert t.stage_stats() == {}
    tok = t.begin_cycle("device", 8)
    tok.dispatched()
    assert sorted(t.stage_stats()) == ["ingest"]
    assert t.stage_stats()["ingest"]["spans"] == 1


def test_trace_annotation_parse_errors():
    m = SiddhiManager()
    try:
        for ann in ("@app:trace(sample='2/3') ",
                    "@app:trace(sample='bogus') ",
                    "@app:trace(sample='0') ",
                    "@app:trace(cycles='0') ",
                    "@app:trace(cycles='99999') "):
            with pytest.raises(SiddhiAppCreationError):
                m.create_siddhi_app_runtime(
                    device_app("badtrace", trace=ann), register=False)
    finally:
        m.shutdown()


def test_trace_annotation_configures_tracer(tmp_path):
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime(device_app(
            "anntrace",
            trace=f"@app:trace(sample='1/8', cycles='16', "
                  f"dir='{tmp_path}') "), register=False)
        tr = rt.app_context.tracer
        assert tr.sample == 8
        assert tr.recorder.cycles == 16
        assert tr.recorder.dump_dir == str(tmp_path)
        assert rt.app_context.statistics_manager.tracer is tr
        rt.shutdown()
        # default-on: no annotation still builds a sampled tracer
        rt2 = m.create_siddhi_app_runtime(
            device_app("anntrace2"), register=False)
        assert rt2.app_context.tracer.sample == Tracer.DEFAULT_SAMPLE
        rt2.shutdown()
    finally:
        m.shutdown()


# -- flight recorder ----------------------------------------------------------


def test_recorder_ring_evicts_to_newest_cycles():
    r = FlightRecorder("app", cycles=2)  # ring depth 2*4 spans
    for c in range(1, 6):
        for stage in ("ingest", "step", "emit"):
            r.record((c, stage, "device", 0.0, 1.0, 1))
    groups = r.cycle_groups()
    # oldest cycles evicted, newest complete
    assert list(groups)[-1] == 5
    assert [s[1] for s in groups[5]] == ["ingest", "step", "emit"]
    assert len(r.spans()) == r.ring.maxlen


def test_recorder_dump_writes_json(tmp_path):
    r = FlightRecorder("app", cycles=4, dump_dir=str(tmp_path))
    r.record((1, "ingest", "device", 0.0, 1.0, 8))
    payload = r.dump("unit-test")
    assert r.last_dump is payload
    assert payload["reason"] == "unit-test"
    files = list(tmp_path.glob("app-*-unit-test.json"))
    assert len(files) == 1
    on_disk = json.loads(files[0].read_text())
    assert on_disk["spans"][0]["stage"] == "ingest"
    assert on_disk["spans"][0]["n_events"] == 8


def test_recorder_dump_file_cap(tmp_path):
    r = FlightRecorder("app", cycles=4, dump_dir=str(tmp_path))
    for i in range(FlightRecorder.MAX_DUMP_FILES + 5):
        r.dump(f"r{i}")
    assert len(list(tmp_path.glob("*.json"))) == FlightRecorder.MAX_DUMP_FILES
    # in-memory dump keeps updating past the file cap
    assert r.last_dump["reason"] == f"r{FlightRecorder.MAX_DUMP_FILES + 4}"


def test_chrome_trace_export():
    t = Tracer("app", sample=1)
    tok = t.begin_cycle("device", 8)
    tok.dispatched()
    tok.step_done(3)
    tok.emitted(t.clock())
    ch = t.recorder.chrome_trace()
    events = ch["traceEvents"]
    assert [e["ph"] for e in events] == ["X", "X", "X"]
    assert all(e["dur"] >= 0.0 and e["ts"] > 0.0 for e in events)
    # stages map to distinct tids (stacked tracks)
    assert len({e["tid"] for e in events}) == 3
    assert events[0]["args"]["cycle"] == 1
    assert ch["otherData"]["app"] == "app"


# -- differential: fault-injector kill dumps ordered cycles -------------------


def test_crash_dump_has_complete_ordered_final_cycles(tmp_path):
    """Kill the app mid-stream; the flight recorder must hold complete
    ingest -> step -> emit span triples for the final cycles, correctly
    ordered within and across cycles."""
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime(device_app(
            "crashbox",
            trace=f"@app:trace(sample='1', cycles='8', dir='{tmp_path}') ",
            faults="@app:faults(step.dense='crash:after=6') "))
        rt.start()
        h = rt.get_input_handler("S")
        with pytest.raises(SimulatedCrashError):
            for i in range(20):
                h.send_batch(make_batch(i))
        dump = rt.app_context.tracer.recorder.last_dump
        assert dump is not None
        assert dump["reason"].startswith("fault-injector-crash:")
        spans = dump["spans"]
        assert spans, "crash dump must carry the span ring"
        by_cycle = {}
        for s in spans:
            by_cycle.setdefault(s["cycle"], []).append(s)
        cycles = list(by_cycle)
        assert cycles == sorted(cycles), "cycles must appear in order"
        # every cycle except the one the crash interrupted is a
        # complete, ordered ingest -> step -> emit triple
        for cid in cycles[:-1]:
            group = by_cycle[cid]
            assert [s["stage"] for s in group] == ["ingest", "step",
                                                   "emit"], cid
            starts = [s["t_start"] for s in group]
            assert starts == sorted(starts), cid
            assert all(s["t_end"] >= s["t_start"] for s in group)
            assert group[0]["n_events"] == 32
        # the dump also survived to disk
        files = list(tmp_path.glob("crashbox-*.json"))
        assert files and json.loads(files[0].read_text())["spans"]
    finally:
        m.shutdown()


# -- prometheus exposition ----------------------------------------------------

_SAMPLE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"            # metric name
    r"(?:\{((?:[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\",?)*)\})?"
    r" (-?(?:[0-9.]+(?:[eE][+-]?[0-9]+)?|\+Inf|NaN))$")


def assert_valid_exposition(body):
    """Minimal text-format 0.0.4 validator: every line is a well-formed
    comment or sample, each family's # TYPE appears exactly once before
    its samples, histogram series are cumulative and consistent."""
    typed = {}
    seen_families = set()
    hist_buckets = {}
    hist_counts = {}
    for line in body.splitlines():
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split()
            assert parts[1] == "TYPE", line
            family, kind = parts[2], parts[3]
            assert family not in typed, f"duplicate TYPE for {family}"
            assert kind in ("gauge", "counter", "histogram"), line
            typed[family] = kind
            continue
        mm = _SAMPLE.match(line)
        assert mm, f"malformed sample line: {line!r}"
        name, labels, value = mm.group(1), mm.group(2) or "", mm.group(3)
        family = re.sub(r"_(bucket|sum|count)$", "", name)
        base = name if name in typed else family
        assert base in typed, f"sample {name} precedes its # TYPE"
        seen_families.add(base)
        if typed[base] == "histogram":
            if name.endswith("_bucket"):
                series = re.sub(r',?le="[^"]*"', "", labels)
                le = re.search(r'le="([^"]*)"', labels).group(1)
                hist_buckets.setdefault((base, series), []).append(
                    (le, float(value)))
            elif name.endswith("_count"):
                series = labels
                hist_counts[(base, series)] = float(value)
    for key, buckets in hist_buckets.items():
        counts = [c for _le, c in buckets]
        assert counts == sorted(counts), f"non-cumulative buckets: {key}"
        assert buckets[-1][0] == "+Inf", f"missing +Inf bucket: {key}"
        assert hist_counts.get(key) == buckets[-1][1], key
    return seen_families


def test_render_prometheus_shapes():
    h = LatencyHistogram()
    h.record_ms(0.7)
    stats = {
        "io.siddhi.SiddhiApps.a.Siddhi.Streams.S.throughput": 12.5,
        "io.siddhi.SiddhiApps.a.Siddhi.Queries.q.loweredTo": "dense",
        "weird.key": 1,
    }
    body = render_prometheus(
        [("a", stats, [("siddhi_query_latency_ms", {"app": "a",
                                                    "name": "q"}, h)])])
    fams = assert_valid_exposition(body)
    assert "siddhi_streams_throughput" in fams
    assert "siddhi_queries_lowered_to_info" in fams  # string -> _info gauge
    assert "siddhi_metric" in fams                   # catch-all
    assert "siddhi_query_latency_ms" in fams
    assert 'value="dense"' in body


def test_render_prometheus_empty():
    assert render_prometheus([]) == "\n"


def test_service_metrics_and_trace_endpoints():
    svc = SiddhiService()
    svc.start()
    try:
        base = f"http://127.0.0.1:{svc.port}"
        # no apps yet: /metrics still serves a valid (empty) page
        resp = urllib.request.urlopen(f"{base}/metrics")
        assert resp.headers["Content-Type"] == CONTENT_TYPE
        req = urllib.request.Request(
            f"{base}/siddhi-artifact-deploy",
            data=device_app("svcapp",
                            trace="@app:trace(sample='1') ").encode(),
            method="POST")
        assert json.load(urllib.request.urlopen(req))["status"] == "OK"
        rt = svc.get_runtime("svcapp")
        h = rt.get_input_handler("S")
        for i in range(4):
            h.send_batch(make_batch(i))
        rt.drain_device_emits()

        body = urllib.request.urlopen(f"{base}/metrics").read().decode()
        fams = assert_valid_exposition(body)
        assert "siddhi_stage_duration_ms" in fams
        assert 'app="svcapp"' in body

        tr = json.load(urllib.request.urlopen(
            f"{base}/siddhi-trace/svcapp"))
        assert tr["status"] == "OK" and tr["sample"] == 1
        stages = [s["stage"] for s in tr["trace"]["spans"]]
        assert {"ingest", "step", "emit"} <= set(stages)

        ch = json.load(urllib.request.urlopen(
            f"{base}/siddhi-trace/svcapp?format=chrome"))
        assert ch["traceEvents"] and ch["traceEvents"][0]["ph"] == "X"
    finally:
        svc.stop()


def test_service_404_paths():
    svc = SiddhiService()
    svc.start()
    try:
        base = f"http://127.0.0.1:{svc.port}"
        for path in ("/siddhi-trace/nope", "/siddhi-statistics/nope",
                     "/siddhi-pattern-state/nope", "/nonsense"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(base + path)
            assert ei.value.code == 404, path
    finally:
        svc.stop()


# -- statistics manager reporting loop ----------------------------------------


def _stats_threads():
    return [t for t in threading.enumerate()
            if t.name.startswith("stats-")]


def test_reporting_loop_start_stop_idempotent():
    sm = StatisticsManager("looper", interval_s=0.05)
    before = len(_stats_threads())
    sm.start_reporting()
    sm.start_reporting()  # second start is a no-op
    assert len(_stats_threads()) == before + 1
    reporter = sm._reporter
    sm.stop_reporting()
    sm.stop_reporting()  # second stop is a no-op
    reporter.join(timeout=2.0)
    assert not reporter.is_alive(), "reporter thread must exit on stop"
    # restart spins up a fresh generation, old thread stays dead
    sm.start_reporting()
    assert sm._reporter is not reporter
    sm.stop_reporting()
    sm._reporter.join(timeout=2.0)
    assert len(_stats_threads()) == before


def test_reporting_loop_survives_stats_error():
    sm = StatisticsManager("angry", interval_s=0.01)
    sm.throughput["boom"] = None  # stats() raises AttributeError
    sm.start_reporting()
    try:
        time.sleep(0.1)
        assert sm._reporter.is_alive(), "reporter must survive bad stats"
    finally:
        sm.stop_reporting()
        sm._reporter.join(timeout=2.0)


def test_statistics_manager_reset_clears_trackers():
    sm = StatisticsManager("resetme")
    tt = sm.throughput_tracker("S")
    lt = sm.latency_tracker("q")
    tt.add(100)
    lt.mark_in(4)
    lt.mark_out(4)
    sm.reset()
    assert tt.count == 0
    assert lt.batches == 0 and lt.hist.count == 0
    # reset is idempotent and leaves the feed serviceable
    sm.reset()
    assert isinstance(sm.stats(), dict)
