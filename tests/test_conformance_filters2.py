"""Filter conformance, part 2: the per-type x per-operator comparison
matrix.  The reference implements one generated executor class per
(type, type, operator) combination (core/executor/condition/compare/ —
e.g. GreaterThanCompareConditionExpressionExecutorFloatDouble); this
matrix pins the same per-type exactness through the generic compiled
expressions: every numeric type pair, string and bool comparisons,
cross-type promotion, and boundary values (float32 precision edge,
int64 magnitudes).
"""

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager

DEFS = ("define stream S (i int, l long, f float, d double, "
        "s string, b bool); ")

ROW = {"i": 5, "l": 5_000_000_000, "f": 2.5, "d": 2.5,
       "s": "mm", "b": True}


def matches(cond, row=None):
    """Returns True when the single sent row passes [cond]."""
    r = dict(ROW, **(row or {}))
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime(
            DEFS + f"@info(name='q') from S[{cond}] select i insert into O;")
        got = []
        rt.add_callback("O", lambda evs: got.extend(evs))
        rt.start()
        rt.get_input_handler("S").send(
            [r["i"], r["l"], r["f"], r["d"], r["s"], r["b"]])
        rt.shutdown()
        return len(got) == 1
    finally:
        m.shutdown()


OPS = {
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
}

# (attr, live value, [probe constants])
NUMERIC_CASES = [
    ("i", 5, ["4", "5", "6"]),
    ("l", 5_000_000_000, ["4999999999", "5000000000", "5000000001"]),
    ("f", 2.5, ["2.0", "2.5", "3.0"]),
    ("d", 2.5, ["2.0", "2.5", "3.0"]),
]


class TestCompareMatrix:
    @pytest.mark.parametrize("attr,val,probes",
                             NUMERIC_CASES,
                             ids=[c[0] for c in NUMERIC_CASES])
    def test_numeric_attr_vs_constant(self, attr, val, probes):
        for op, fn in OPS.items():
            for p in probes:
                want = fn(val, float(p) if "." in p else int(p))
                got = matches(f"{attr} {op} {p}")
                assert got == want, f"{attr} {op} {p}: {got} != {want}"

    def test_cross_type_attr_pairs(self):
        # i(5) vs f(2.5), l vs d, i vs l — promotion must be numeric
        assert matches("i > f")
        assert not matches("i < f")
        assert matches("l > d")
        assert matches("l > i")
        assert matches("i == l", {"l": 5})
        assert matches("f == d")

    def test_string_compare_full_operator_set(self):
        for op, fn in OPS.items():
            for probe in ("ll", "mm", "nn"):
                want = fn("mm", probe)
                got = matches(f"s {op} '{probe}'")
                assert got == want, f"s {op} '{probe}'"

    def test_bool_compare(self):
        assert matches("b == true")
        assert not matches("b == false")
        assert matches("b != false")
        assert not matches("b", {"b": False})

    def test_long_precision_above_float32(self):
        # 2^24 + 1 vs 2^24: float32 would collapse these
        assert matches("l == 16777217", {"l": 16777217})
        assert not matches("l == 16777216", {"l": 16777217})
        assert matches("l > 16777216", {"l": 16777217})

    def test_negative_and_zero_boundaries(self):
        assert matches("i < 0", {"i": -1})
        assert not matches("i < 0", {"i": 0})
        assert matches("i <= 0", {"i": 0})
        assert matches("d < 0.0", {"d": -0.5})
        assert matches("d == 0.0", {"d": 0.0})

    def test_logical_combinations(self):
        assert matches("i == 5 and d == 2.5")
        assert not matches("i == 5 and d == 9.9")
        assert matches("i == 9 or d == 2.5")
        assert matches("not (i == 9)")
        assert matches("(i > 4 and i < 6) or b == false")

    def test_arithmetic_in_condition(self):
        assert matches("i + 1 == 6")
        assert matches("i * 2 > 9")
        assert matches("d / 2.0 == 1.25")
        assert matches("i - 10 < 0")
        assert matches("l % 7 == " + str(5_000_000_000 % 7))
