"""Device-query differential sweep: a scenario matrix of general
single-stream queries run under @app:execution('tpu') AND on the host
engine, asserting identical outputs and that the jitted device step
actually ran.  Complements test_device_single_integration with broader
shapes (arithmetic filters, batch windows + having, min/max over
expiry, multi-query apps, null handling).
"""

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.device_single import DeviceQueryRuntime

DEFS = "define stream S (k long, v double, w long); "


def drive(app, sends, out="O"):
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime("@app:playback " + app)
        got = []
        rt.add_callback(out, lambda evs: got.extend(list(e.data) for e in evs))
        rt.start()
        h = rt.get_input_handler("S")
        for row, ts in sends:
            h.send(row, timestamp=ts)
        runtimes = [getattr(qr, "device_runtime", None)
                    for qr in rt.query_runtimes.values()]
        rt.shutdown()
        return got, runtimes
    finally:
        m.shutdown()


def differential(query, sends, expect_device=True, out="O"):
    host, _ = drive(query, sends, out)
    dev, runtimes = drive("@app:execution('tpu') " + query, sends, out)
    if expect_device:
        dr = [r for r in runtimes if isinstance(r, DeviceQueryRuntime)]
        assert dr, "no query lowered to the device path"
        assert all(r.step_invocations > 0 for r in dr)
    assert len(dev) == len(host)
    for i, (a, b) in enumerate(zip(host, dev)):
        for x, y in zip(a, b):
            if isinstance(x, float):
                assert y == pytest.approx(x, rel=1e-5), f"row {i}: {a} != {b}"
            else:
                assert x == y, f"row {i}: {a} != {b}"
    return host


def mk_sends(n=40, seed=9):
    rng = np.random.default_rng(seed)
    return [([int(rng.integers(0, 5)), float(rng.integers(0, 100)),
              int(rng.integers(0, 1000))], 1000 + i * 37)
            for i in range(n)]


class TestDeviceDifferentialSweep:
    def test_arithmetic_filter_projection(self):
        q = (DEFS + "@info(name='q') from S[v * 2.0 + 1.0 > 50.0] "
             "select k, v * 10.0 as sv, v - 1.0 as d insert into O;")
        got = differential(q, mk_sends())
        assert len(got) > 0

    def test_length_window_running_aggregates(self):
        q = (DEFS + "@info(name='q') from S#window.length(5) "
             "select sum(v) as s, count() as c, avg(v) as a, "
             "min(v) as mn, max(v) as mx insert into O;")
        differential(q, mk_sends())

    def test_time_window_group_by(self):
        q = (DEFS + "@info(name='q') from S#window.time(1 sec) "
             "select k, sum(v) as total, count() as n group by k "
             "insert into O;")
        differential(q, mk_sends())

    def test_length_batch_having(self):
        # batch flushes emit one row per group; host orders groups by
        # arrival, the device engine by group slot — compare as sets
        q = (DEFS + "@info(name='q') from S#window.lengthBatch(8) "
             "select k, sum(v) as total group by k having total > 50.0 "
             "insert into O;")
        host, _ = drive(q, mk_sends())
        dev, runtimes = drive("@app:execution('tpu') " + q, mk_sends())
        assert any(isinstance(r, DeviceQueryRuntime) for r in runtimes)
        assert sorted((k, round(t, 4)) for k, t in host) == \
            sorted((k, round(t, 4)) for k, t in dev)
        assert len(host) > 0

    def test_time_batch_min_max(self):
        q = (DEFS + "@info(name='q') from S#window.timeBatch(1 sec) "
             "select min(v) as mn, max(v) as mx, count() as n "
             "insert into O;")
        differential(q, mk_sends())

    def test_filterless_passthrough_projection(self):
        q = (DEFS + "@info(name='q') from S select k, v insert into O;")
        differential(q, mk_sends(12))

    def test_multi_query_app_mixed_paths(self):
        # two device-eligible queries plus one host-only (string attr)
        q = (DEFS +
             "define stream T (name string, x long); "
             "@info(name='q1') from S[v > 50.0] select k, v insert into O; "
             "@info(name='q2') from S#window.length(3) "
             "select sum(v) as sv insert into O2; "
             "@info(name='q3') from T[name == 'a'] select x insert into O3;")
        host, _ = drive(q, mk_sends(20))
        dev, runtimes = drive("@app:execution('tpu') " + q, mk_sends(20))
        assert len(host) == len(dev)
        for i, (a, b) in enumerate(zip(host, dev)):
            assert a == [pytest.approx(x) for x in b], f"row {i}: {a} != {b}"
        assert sum(isinstance(r, DeviceQueryRuntime) for r in runtimes) >= 2

    def test_chained_inserts_cross_engines(self):
        # a device query feeding a second query through a mid stream
        q = (DEFS +
             "@info(name='q1') from S[v > 20.0] select k, v insert into Mid; "
             "@info(name='q2') from Mid#window.length(4) "
             "select k, sum(v) as total group by k insert into O;")
        differential(q, mk_sends())


class TestDeviceQueryFuzz:
    """Seeded random (filter, window, selector) combinations — each
    (shape, seed) pair pins the device engine against the host across
    thousands of window transitions."""

    WINDOWS = ["", "#window.length({n})", "#window.lengthBatch({n})",
               "#window.time({t} sec)", "#window.timeBatch({t} sec)"]
    SELECTS = [
        "k, v",
        "sum(v) as s, count() as c",
        "k, sum(v) as s group by k",
        "k, avg(v) as a, min(v) as mn, max(v) as mx group by k",
    ]

    @pytest.mark.parametrize("seed", range(8))
    def test_random_combination(self, seed):
        rng = np.random.default_rng(100 + seed)
        win = self.WINDOWS[rng.integers(0, len(self.WINDOWS))].format(
            n=int(rng.integers(2, 7)), t=int(rng.integers(1, 3)))
        sel = self.SELECTS[rng.integers(0, len(self.SELECTS))]
        if "Batch" in win and "(" not in sel:
            # tumbling device queries reduce per flush: select items may
            # reference only group keys and aggregates (documented
            # eligibility) — pair batch windows with aggregating selects
            sel = self.SELECTS[1 + rng.integers(0, len(self.SELECTS) - 1)]
        thr = float(rng.integers(10, 80))
        filt = f"[v > {thr}]" if rng.integers(0, 2) else ""
        q = (DEFS + f"@info(name='q') from S{filt}{win} "
             f"select {sel} insert into O;")
        sends = mk_sends(60, seed=200 + seed)
        host, _ = drive(q, sends)
        dev, runtimes = drive("@app:execution('tpu') " + q, sends)
        assert any(isinstance(r, DeviceQueryRuntime) for r in runtimes), (
            f"seed {seed}: {q} did not lower")
        batchy = "Batch" in win and "group by" in sel
        if batchy:
            # batch flushes order groups differently (see
            # test_length_batch_having); compare per-row multisets
            ha = sorted(tuple(round(x, 4) if isinstance(x, float) else x
                              for x in r) for r in host)
            da = sorted(tuple(round(x, 4) if isinstance(x, float) else x
                              for x in r) for r in dev)
            assert ha == da, f"seed {seed}: {q}"
        else:
            assert len(host) == len(dev), (
                f"seed {seed}: {q}: {len(host)} vs {len(dev)}")
            for i, (a, b) in enumerate(zip(host, dev)):
                assert a == [pytest.approx(x, rel=1e-4, abs=1e-6)
                             for x in b], f"seed {seed} row {i}: {a} != {b}"
