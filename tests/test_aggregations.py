"""Incremental aggregation conformance tests.

Modeled on the reference aggregation test corpus
(modules/siddhi-core/src/test/java/io/siddhi/core/aggregation/
AggregationTestCase): define aggregation every sec...year, events in with
explicit timestamps, per-duration buckets asserted via joins / find.
"""

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.event import events_from_batch


@pytest.fixture
def manager():
    m = SiddhiManager()
    yield m
    m.shutdown()


BASE = 1_496_289_720_000  # 2017-06-01 04:02:00 UTC


def test_aggregation_sum_avg_per_seconds(manager):
    app = (
        "define stream Stock (symbol string, price double, volume long, ts long); "
        "define aggregation StockAgg "
        "from Stock select symbol, sum(price) as total, avg(price) as avgPrice, "
        "count() as n group by symbol "
        "aggregate by ts every sec, min, hour;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    h = rt.get_input_handler("Stock")
    # two events in second 0, one in second 1 — same symbol
    h.send(["WSO2", 50.0, 10, BASE])
    h.send(["WSO2", 70.0, 20, BASE + 500])
    h.send(["WSO2", 60.0, 30, BASE + 1000])

    agg = rt.aggregations["StockAgg"]
    b = agg.find("seconds")
    rows = {
        int(b.columns["AGG_TIMESTAMP"][i]): (
            b.columns["symbol"][i],
            float(b.columns["total"][i]),
            float(b.columns["avgPrice"][i]),
            int(b.columns["n"][i]),
        )
        for i in range(len(b))
    }
    assert rows[BASE] == ("WSO2", 120.0, 60.0, 2)
    assert rows[BASE + 1000] == ("WSO2", 60.0, 60.0, 1)


def test_aggregation_rollup_minutes(manager):
    app = (
        "define stream Stock (symbol string, price double, ts long); "
        "define aggregation A "
        "from Stock select symbol, sum(price) as total, min(price) as lo, "
        "max(price) as hi group by symbol "
        "aggregate by ts every sec, min;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    h = rt.get_input_handler("Stock")
    # spread across two minutes
    h.send(["IBM", 10.0, BASE])
    h.send(["IBM", 30.0, BASE + 30_000])
    h.send(["IBM", 20.0, BASE + 60_000])

    agg = rt.aggregations["A"]
    b = agg.find("minutes")
    rows = {
        int(b.columns["AGG_TIMESTAMP"][i]): (
            float(b.columns["total"][i]),
            float(b.columns["lo"][i]),
            float(b.columns["hi"][i]),
        )
        for i in range(len(b))
    }
    minute0 = BASE - BASE % 60_000
    assert rows[minute0] == (40.0, 10.0, 30.0)
    assert rows[minute0 + 60_000] == (20.0, 20.0, 20.0)


def test_aggregation_group_isolation(manager):
    app = (
        "define stream S (symbol string, price double, ts long); "
        "define aggregation A from S "
        "select symbol, sum(price) as total group by symbol "
        "aggregate by ts every sec;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    h = rt.get_input_handler("S")
    h.send(["A", 1.0, BASE])
    h.send(["B", 2.0, BASE])
    h.send(["A", 3.0, BASE])
    b = rt.aggregations["A"].find("seconds")
    got = sorted(
        (b.columns["symbol"][i], float(b.columns["total"][i])) for i in range(len(b))
    )
    assert got == [("A", 4.0), ("B", 2.0)]


def test_aggregation_join_within_per(manager):
    app = (
        "define stream Stock (symbol string, price double, ts long); "
        "define stream Probe (symbol string, startT long, endT long); "
        "define aggregation A from Stock "
        "select symbol, sum(price) as total group by symbol "
        "aggregate by ts every sec, min; "
        "@info(name='q') "
        "from Probe as p join A as a "
        "on p.symbol == a.symbol "
        "within p.startT, p.endT "
        "per 'seconds' "
        "select a.AGG_TIMESTAMP as bucket, a.symbol as symbol, a.total as total "
        "insert into Out;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    outs = []
    rt.add_callback("q", lambda ts, ins, rem: outs.extend(ins or []))
    sh = rt.get_input_handler("Stock")
    sh.send(["WSO2", 50.0, BASE])
    sh.send(["WSO2", 70.0, BASE + 500])
    sh.send(["IBM", 10.0, BASE])
    sh.send(["WSO2", 60.0, BASE + 1000])
    rt.get_input_handler("Probe").send(["WSO2", BASE, BASE + 1000])
    assert len(outs) == 1
    assert outs[0].data == [BASE, "WSO2", 120.0]


def test_aggregation_out_of_order_event(manager):
    app = (
        "define stream S (v double, ts long); "
        "define aggregation A from S select sum(v) as total "
        "aggregate by ts every sec, min;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    h = rt.get_input_handler("S")
    h.send([1.0, BASE])
    h.send([2.0, BASE + 5000])  # watermark passes BASE's second bucket
    h.send([4.0, BASE + 100])  # late: belongs to the BASE bucket
    b = rt.aggregations["A"].find("seconds")
    rows = {int(b.columns["AGG_TIMESTAMP"][i]): float(b.columns["total"][i]) for i in range(len(b))}
    assert rows[BASE] == 5.0
    assert rows[BASE + 5000] == 2.0


def test_aggregation_months_buckets(manager):
    app = (
        "define stream S (v double, ts long); "
        "define aggregation A from S select sum(v) as total "
        "aggregate by ts every day, month;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    h = rt.get_input_handler("S")
    jun1 = 1_496_275_200_000  # 2017-06-01 00:00:00 UTC
    jul1 = 1_498_867_200_000  # 2017-07-01 00:00:00 UTC
    h.send([1.0, jun1 + 1000])
    h.send([2.0, jun1 + 86_400_000])
    h.send([10.0, jul1 + 5])
    b = rt.aggregations["A"].find("months")
    rows = {int(b.columns["AGG_TIMESTAMP"][i]): float(b.columns["total"][i]) for i in range(len(b))}
    assert rows[jun1] == 3.0
    assert rows[jul1] == 10.0


class TestAggregationPurge:
    APP = (
        "@app:playback "
        "define stream S (sym string, v long); "
        "@purge(enable='true', interval='1 sec', "
        "@retentionPeriod(sec='120 sec', min='1 day')) "
        "define aggregation Agg from S select sym, sum(v) as total "
        "group by sym aggregate every sec...min;"
    )

    def test_purges_old_finished_buckets(self, manager):
        rt = manager.create_siddhi_app_runtime(self.APP)
        rt.start()
        h = rt.get_input_handler("S")
        h.send(["A", 1], timestamp=1_000)
        # jump 10 minutes: second-buckets older than 120s purge on the
        # next batch; minute retention (1 day) keeps the rollup
        h.send(["A", 2], timestamp=600_000)
        agg = rt.aggregations["Agg"]
        sec_finished = agg.stores["seconds"].finished
        # the early second-bucket was purged; later state remains
        assert all(k[0] >= 600_000 - 120_000 for k in sec_finished), sec_finished
        assert len(agg.stores["minutes"].finished) >= 1
        # the minute rollup still answers historical queries incl. the
        # purged range's value
        events = rt.query(
            "from Agg within 0L, 999999999L per 'minutes' select sym, total")
        assert any(e.data[0] == "A" and e.data[1] == 1 for e in events), [
            e.data for e in events]
        rt.shutdown()

    def test_invalid_retention_below_minimum(self, manager):
        from siddhi_tpu.core.exceptions import SiddhiAppCreationError

        import pytest as _pytest
        with _pytest.raises(SiddhiAppCreationError, match="retention"):
            manager.create_siddhi_app_runtime(
                "define stream S (v long); "
                "@purge(enable='true', @retentionPeriod(sec='10 sec')) "
                "define aggregation A from S select sum(v) as t "
                "aggregate every sec...min;"
            )

    def test_purge_disabled_retains(self, manager):
        rt = manager.create_siddhi_app_runtime(
            "@app:playback define stream S (v long); "
            "@purge(enable='false') "
            "define aggregation A from S select sum(v) as t aggregate every sec...min;"
        )
        rt.start()
        h = rt.get_input_handler("S")
        h.send([1], timestamp=1_000)
        h.send([2], timestamp=100_000_000)
        agg = rt.aggregations["A"]
        assert len(agg.stores["seconds"].finished) >= 1
        rt.shutdown()


class TestStdDevDeviceBank:
    """stdDev decomposes to sum + sumsq + count; in tpu mode ALL three
    base fields must ride the device bucket bank (the sumsq row is a
    DOUBLE "sum"-op field, and the shared count denominator banks for
    stdDev exactly as it does for avg), so stdDev-bearing ingest skips
    the host reduction entirely."""

    APP = (
        "{mode}@app:playback "
        "define stream S (sym string, price double, ts long); "
        "define aggregation A from S select sym, stdDev(price) as sd "
        "group by sym aggregate by ts every sec...min;"
    )

    def _run(self, manager, mode, probe=False):
        import numpy as np

        rt = manager.create_siddhi_app_runtime(self.APP.format(mode=mode))
        rt.start()
        agg = rt.aggregations["A"]
        if probe:
            assert agg._bank is not None
            assert set(agg._bank.names) == {f.name for f in agg.base_fields}
        rng = np.random.default_rng(7)
        n = 400
        ts = np.sort(BASE + rng.integers(0, 5_000, n)).astype(np.int64)
        for i in range(0, n, 50):
            for j in range(i, min(i + 50, n)):
                h = rt.get_input_handler("S")
                h.send([f"s{int(rng.integers(0, 8))}",
                        float(rng.uniform(1, 100)), int(ts[j])])
        out = rt.query(
            f"from A within {BASE - 1000}, {BASE + 100_000} per 'seconds' "
            "select sym, sd;")
        rt.shutdown()
        return sorted([list(e.data) for e in out], key=lambda r: r[0])

    def test_stddev_banks_count_and_matches_host(self, manager):
        host = self._run(manager, "")
        m2 = SiddhiManager()
        try:
            dev = self._run(m2, "@app:execution('tpu') ", probe=True)
        finally:
            m2.shutdown()
        assert len(host) == len(dev) > 0
        for a, b in zip(host, dev):
            assert a[0] == b[0]
            # float32 device lanes + sum/sumsq decomposition tolerance
            assert b[1] == pytest.approx(a[1], abs=5e-3, rel=1e-3), (a, b)


class TestIntMinMaxDeviceBank:
    """min/max over an INT argument ride the device bucket bank as
    single int32 rows at native width (INT is exactly int32, identities
    the int32 extrema) — exact, no pair split; a count in the same
    select banks as a float32 add row, so this ingest shape performs no
    host reduction at all."""

    APP = (
        "{mode}@app:playback "
        "define stream S (sym string, v int, ts long); "
        "define aggregation A from S select sym, min(v) as lo, "
        "max(v) as hi, count() as n group by sym "
        "aggregate by ts every sec...min;"
    )

    def _run(self, manager, mode, vals, probe=False):
        import numpy as np

        rt = manager.create_siddhi_app_runtime(self.APP.format(mode=mode))
        rt.start()
        agg = rt.aggregations["A"]
        if probe:
            assert agg._bank is not None
            # every base field banks: INT extrema + the bare count
            assert set(agg._bank.names) == {f.name for f in agg.base_fields}
            assert any(kind == "i32" for _op, kind in agg._bank._lanes)
        rng = np.random.default_rng(17)
        n = len(vals)
        ts = np.sort(BASE + rng.integers(0, 5_000, n)).astype(np.int64)
        h = rt.get_input_handler("S")
        for j in range(n):
            h.send([f"s{int(rng.integers(0, 6))}", int(vals[j]), int(ts[j])])
        if probe:
            # the bank must actually absorb the batches on device
            assert agg._bank.scatters > 0
        out = rt.query(
            f"from A within {BASE - 1000}, {BASE + 100_000} per 'seconds' "
            "select sym, lo, hi, n;")
        rt.shutdown()
        return sorted([list(e.data) for e in out], key=lambda r: r[0])

    def _diff(self, manager, vals):
        host = self._run(manager, "", vals)
        m2 = SiddhiManager()
        try:
            dev = self._run(m2, "@app:execution('tpu') ", vals, probe=True)
        finally:
            m2.shutdown()
        assert len(host) == len(dev) > 0
        # int32 rows and the count barrier are exact — no tolerance
        assert host == dev, (host[:4], dev[:4])

    def test_int_min_max_exact_on_bank_path(self, manager):
        import numpy as np

        rng = np.random.default_rng(19)
        self._diff(manager, rng.integers(-100_000, 100_000, 500))

    def test_int_extrema_at_type_bounds_exact(self, manager):
        import numpy as np

        # values spanning the full int32 range hit the identity edges
        rng = np.random.default_rng(23)
        vals = rng.integers(-(2**31), 2**31 - 1, 300)
        vals[0], vals[1] = -(2**31), 2**31 - 1
        self._diff(manager, vals)


class TestCountOnlyDeviceBank:
    """A count-only select (no avg/stdDev rewrite) banks its bare count
    as a float32 add row under the 2**24 overflow barrier — previously
    it forced the host reduction every batch."""

    APP = (
        "{mode}@app:playback "
        "define stream S (sym string, v int, ts long); "
        "define aggregation A from S select sym, count() as n "
        "group by sym aggregate by ts every sec...min;"
    )

    def _run(self, manager, mode, probe=False):
        import numpy as np

        rt = manager.create_siddhi_app_runtime(self.APP.format(mode=mode))
        rt.start()
        agg = rt.aggregations["A"]
        if probe:
            assert agg._bank is not None
            assert [f.op for f in agg._bank.fields] == ["count"]
        rng = np.random.default_rng(29)
        n = 400
        ts = np.sort(BASE + rng.integers(0, 5_000, n)).astype(np.int64)
        h = rt.get_input_handler("S")
        for j in range(n):
            h.send([f"s{int(rng.integers(0, 8))}",
                    int(rng.integers(-100, 100)), int(ts[j])])
        if probe:
            assert agg._bank.scatters > 0
        out = rt.query(
            f"from A within {BASE - 1000}, {BASE + 100_000} per 'seconds' "
            "select sym, n;")
        rt.shutdown()
        return sorted([list(e.data) for e in out], key=lambda r: r[0])

    def test_count_only_banks_and_matches_host(self, manager):
        host = self._run(manager, "")
        m2 = SiddhiManager()
        try:
            dev = self._run(m2, "@app:execution('tpu') ", probe=True)
        finally:
            m2.shutdown()
        assert len(host) == len(dev) > 0
        assert host == dev, (host[:4], dev[:4])


class TestLongSumDeviceBank:
    """sum(intcol) widens INT→LONG; in tpu mode LONG sums ride the
    device bucket bank as hi/lo int32 pair rows (hi += v >> 16,
    lo += v & 0xFFFF, flush merge hi * 65536 + lo) — EXACTLY, unlike
    the float32 lanes.  An avg over the same int argument shares the
    banked _SUM numerator and banks its count denominator too."""

    APP = (
        "{mode}@app:playback "
        "define stream S (sym string, v int, ts long); "
        "define aggregation A from S select sym, sum(v) as total, "
        "avg(v) as mean group by sym aggregate by ts every sec...min;"
    )

    def _run(self, manager, mode, vals, probe=False):
        import numpy as np

        rt = manager.create_siddhi_app_runtime(self.APP.format(mode=mode))
        rt.start()
        agg = rt.aggregations["A"]
        if probe:
            assert agg._bank is not None
            # the LONG _SUM field owns a pair lane; count banks with it
            assert agg._bank.long_names, agg._bank.names
            assert set(agg._bank.names) == {f.name for f in agg.base_fields}
        rng = np.random.default_rng(11)
        n = len(vals)
        ts = np.sort(BASE + rng.integers(0, 5_000, n)).astype(np.int64)
        h = rt.get_input_handler("S")
        for j in range(n):
            h.send([f"s{int(rng.integers(0, 6))}", int(vals[j]), int(ts[j])])
        out = rt.query(
            f"from A within {BASE - 1000}, {BASE + 100_000} per 'seconds' "
            "select sym, total, mean;")
        rt.shutdown()
        return sorted([list(e.data) for e in out], key=lambda r: r[0])

    def _diff(self, manager, vals):
        host = self._run(manager, "", vals)
        m2 = SiddhiManager()
        try:
            dev = self._run(m2, "@app:execution('tpu') ", vals, probe=True)
        finally:
            m2.shutdown()
        assert len(host) == len(dev) > 0
        for a, b in zip(host, dev):
            assert a[0] == b[0], (a, b)
            # hi/lo int32 pair rows are exact — no tolerance
            assert a[1] == b[1], ("LONG sum must be exact", a, b)
            assert b[2] == pytest.approx(a[2], rel=1e-6), (a, b)

    def test_long_sum_exact_on_bank_path(self, manager):
        import numpy as np

        rng = np.random.default_rng(3)
        self._diff(manager, rng.integers(-100_000, 100_000, 500))

    def test_long_sum_negative_heavy_exact(self, manager):
        import numpy as np

        # all-negative sums exercise the signed two's-complement split
        # (hi goes negative while lo stays in [0, 65535])
        rng = np.random.default_rng(5)
        self._diff(manager, rng.integers(-(2**31), -1, 300))

    def test_overflow_risk_forces_flush_or_host_path(self):
        from siddhi_tpu.aggregation.device_bank import DeviceBucketBank
        from siddhi_tpu.aggregation.runtime import BaseField
        from siddhi_tpu.query_api import AttrType
        import numpy as np

        f = BaseField("_SUM0", "sum", None, AttrType.LONG)
        bank = DeviceBucketBank([f], cap=8)
        v = np.asarray([2**40, -(2**40)], dtype=np.int64)
        assert not bank.long_overflow_risk({"_SUM0": v}, 2)
        # a batch whose per-event hi magnitude alone nears int32 must
        # report risk even on an empty bank (host-path fallback)
        hot = np.asarray([2**50], dtype=np.int64)
        assert bank.long_overflow_risk({"_SUM0": hot}, 1)
        # accumulated moderate batches eventually trip the barrier too
        bank.rows[(0, ())] = 0
        bank.scatter(np.zeros(2, dtype=np.int32), {"_SUM0": v})
        assert bank._long_hi_used["_SUM0"] > 0
        bank._long_hi_used["_SUM0"] = (1 << 31) - 10
        assert bank.long_overflow_risk({"_SUM0": v}, 2)
        bank.clear()
        assert not bank.long_overflow_risk({"_SUM0": v}, 2)
