"""Persistence / snapshot conformance tests.

Modeled on the reference managment suite
(modules/siddhi-core/src/test/java/io/siddhi/core/managment/
PersistenceTestCase / SnapshotableEventQueueTestCase): persist a running
app, keep sending events, restore, and assert the state rolled back to
the revision point.
"""

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.util.persistence import (
    FileSystemPersistenceStore,
    InMemoryPersistenceStore,
)


@pytest.fixture
def manager():
    m = SiddhiManager()
    m.set_persistence_store(InMemoryPersistenceStore())
    yield m
    m.shutdown()


def test_persist_restore_count_window(manager):
    app = (
        "@app:name('persistApp') "
        "define stream S (symbol string, price float); "
        "@info(name='q') from S#window.length(10) "
        "select symbol, count() as n insert into Out;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    got = []
    rt.add_callback("q", lambda ts, ins, rem: got.extend(e.data for e in (ins or [])))
    h = rt.get_input_handler("S")
    h.send(["A", 1.0])
    h.send(["A", 2.0])
    assert got[-1][1] == 2
    revision = rt.persist()
    h.send(["A", 3.0])
    assert got[-1][1] == 3
    rt.restore_revision(revision)
    h.send(["A", 9.0])
    # count resumes from the persisted 2, not from 3
    assert got[-1][1] == 3


def test_restore_last_revision_table(manager):
    app = (
        "@app:name('tableApp') "
        "define stream S (symbol string, volume long); "
        "define table T (symbol string, volume long); "
        "from S insert into T;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    h = rt.get_input_handler("S")
    h.send(["A", 1])
    rt.persist()
    h.send(["B", 2])
    assert len(rt.query("from T select symbol;")) == 2
    rt.restore_last_revision()
    assert [e.data for e in rt.query("from T select symbol;")] == [["A"]]


def test_restore_last_revision_picks_newest(manager):
    app = (
        "@app:name('revApp') "
        "define stream S (v long); "
        "define table T (v long); "
        "from S insert into T;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    h = rt.get_input_handler("S")
    h.send([1])
    import time

    rt.persist()
    time.sleep(0.002)  # distinct revision timestamps
    h.send([2])
    rt.persist()
    h.send([3])
    rt.restore_last_revision()
    assert sorted(e.data[0] for e in rt.query("from T select v;")) == [1, 2]


def test_pattern_state_survives_restore(manager):
    app = (
        "@app:name('patternApp') "
        "define stream S (sym string, v double); "
        "@info(name='q') from every a=S[v > 10.0] -> b=S[v > a.v] "
        "select a.v as av, b.v as bv insert into Out;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    got = []
    rt.add_callback("q", lambda ts, ins, rem: got.extend(e.data for e in (ins or [])))
    h = rt.get_input_handler("S")
    h.send(["A", 20.0])  # arms a=20
    rev = rt.persist()
    rt.restore_revision(rev)
    h.send(["A", 30.0])  # must still complete the armed partial match
    assert [20.0, 30.0] in got


def test_aggregation_state_survives_restore(manager):
    BASE = 1_496_289_720_000
    app = (
        "@app:name('aggApp') "
        "define stream S (v double, ts long); "
        "define aggregation A from S select sum(v) as total "
        "aggregate by ts every sec, min;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    h = rt.get_input_handler("S")
    h.send([1.0, BASE])
    rev = rt.persist()
    h.send([2.0, BASE + 100])
    rt.restore_revision(rev)
    h.send([4.0, BASE + 200])
    b = rt.aggregations["A"].find("seconds")
    assert float(b.columns["total"][0]) == 5.0  # 1 + 4, the 2 rolled back


def test_filesystem_store_keeps_limited_revisions(tmp_path):
    m = SiddhiManager()
    store = FileSystemPersistenceStore(str(tmp_path), revisions_to_keep=2)
    m.set_persistence_store(store)
    app = (
        "@app:name('fsApp') "
        "define stream S (v long); define table T (v long); "
        "from S insert into T;"
    )
    rt = m.create_siddhi_app_runtime(app)
    rt.start()
    h = rt.get_input_handler("S")
    import time

    revs = []
    for i in range(4):
        h.send([i])
        revs.append(rt.persist())
        time.sleep(0.002)
    assert store.load("fsApp", revs[0]) is None  # evicted
    assert store.get_last_revision("fsApp") == revs[-1]
    rt.restore_last_revision()
    assert sorted(e.data[0] for e in rt.query("from T select v;")) == [0, 1, 2, 3]
    store.clear_all_revisions("fsApp")
    assert store.get_last_revision("fsApp") is None
    m.shutdown()


def test_persist_without_store_raises():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("define stream S (v long); from S select v insert into O;")
    rt.start()
    from siddhi_tpu.core.exceptions import SiddhiAppRuntimeError

    with pytest.raises(SiddhiAppRuntimeError):
        rt.persist()
    m.shutdown()


class TestIncrementalPersistence:
    def test_incremental_persist_restore(self, manager, tmp_path):
        from siddhi_tpu.util.persistence import IncrementalFileSystemPersistenceStore

        store = IncrementalFileSystemPersistenceStore(str(tmp_path))
        manager.set_persistence_store(store)
        app = (
            "@app:name('incApp') "
            "define stream S (sym string, v long); "
            "define table T (sym string, total long); "
            "from S select sym, v as total insert into T;"
        )
        rt = manager.create_siddhi_app_runtime(app)
        rt.start()
        h = rt.get_input_handler("S")
        h.send(["A", 1])
        rev1 = rt.persist()          # first persist -> base
        h.send(["B", 2])
        rev2 = rt.persist()          # -> increment with only table delta
        h.send(["C", 3])             # not persisted

        import os
        files = sorted(os.listdir(tmp_path / rt.name))
        assert any(f.endswith(".base") for f in files), files
        assert any(f.endswith(".inc") for f in files), files

        rt.shutdown()
        rt2 = manager.create_siddhi_app_runtime(app)
        rt2.start()
        restored = rt2.restore_last_revision()
        assert restored == rev2
        events = rt2.query("from T select sym")
        assert sorted(e.data[0] for e in events) == ["A", "B"]
        rt2.shutdown()

    def test_increment_smaller_than_base(self, manager, tmp_path):
        from siddhi_tpu.util.persistence import IncrementalFileSystemPersistenceStore

        store = IncrementalFileSystemPersistenceStore(str(tmp_path))
        manager.set_persistence_store(store)
        app = (
            "define stream S (sym string, v long); "
            "define table T (sym string, total long); "
            "define table U (sym string, total long); "
            "from S[v < 100] select sym, v as total insert into T; "
            "from S[v >= 100] select sym, v as total insert into U;"
        )
        rt = manager.create_siddhi_app_runtime(app)
        rt.start()
        h = rt.get_input_handler("S")
        for i in range(50):
            h.send([f"row{i}", i])
        rt.persist()                 # base holds 50 rows in T
        h.send(["only-u", 500])      # only table U changes
        rt.persist()
        import os
        d = tmp_path / rt.name
        base = next(f for f in os.listdir(d) if f.endswith(".base"))
        inc = next(f for f in os.listdir(d) if f.endswith(".inc"))
        assert os.path.getsize(d / inc) < os.path.getsize(d / base)
        rt.shutdown()
