"""Skew-aware hot-key routing differential suite.

``@app:hotkeys`` (planner/hotkeys.py) wraps eligible partitioned dense
pattern queries in a ``HotKeyRouterRuntime`` (core/hotkey_router.py): a
space-saving sketch watches the junction's key histogram per batch
cycle, keys whose decayed traffic share crosses the promote threshold
move onto the batched associative-scan engine (ops/hotkey_scan.py),
and cool back to the dense path below the demote threshold — with
EXACT pending-state handoff at each boundary.

The contract under test is bit-identical detections versus the host
engine across chain shapes, with the router's decision counters
evidencing that promotion actually happened (a silent dense fallback
cannot hollow the suite out) — including promotion/demotion
mid-stream, under transient ingest/emit faults, crash + journal
replay, and persist/restore — plus a counted, readable fallback
reason for every ineligible shape.
"""

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.exceptions import SimulatedCrashError
from siddhi_tpu.core.hotkey_router import (
    HotKeyRouterRuntime,
    SpaceSavingSketch,
)
from siddhi_tpu.util.persistence import InMemoryPersistenceStore

DEFINE = "define stream S (k long, u double, v double); "
TPU = "@app:execution('tpu', instances='16') "
HOTKEYS = "@app:hotkeys(k='4', promote='0.3', demote='0.1') "


def wrap(q):
    return f"partition with (k of S) begin {q} end;"


# eligible class: every-headed linear chains, capture-free boolean
# filters, selects over final-node attributes only, no within
SHAPES = {
    "pair": (
        "@info(name='q') from every a=S[v > 8.0] -> b=S[v > 12.0] "
        "select b.v as bv insert into Alerts;"),
    "triple": (
        "@info(name='q') from every a=S[v > 4.0] -> b=S[u > 6.0] "
        "-> c=S[v > 10.0] "
        "select c.u as cu, c.v as cv insert into Alerts;"),
    "quad_two_filters": (
        "@info(name='q') from every a=S[u > 3.0 and v > 3.0] "
        "-> b=S[v > 6.0] -> c=S[u > 9.0] -> d=S[v > 12.0] "
        "select d.u as du, d.v as dv insert into Alerts;"),
}


def gen(seed, phases, dt_max=40):
    """Event stream in phases of (n, hot_key, p_hot): each phase sends
    ``n`` events, each going to ``hot_key`` with probability ``p_hot``
    and to a uniform key in 0..29 otherwise."""
    rng = np.random.default_rng(seed)
    out, t = [], 1000
    for n, hot_key, p_hot in phases:
        for _ in range(n):
            t += int(rng.integers(1, dt_max))
            k = (int(hot_key) if hot_key is not None
                 and rng.random() < p_hot else int(rng.integers(0, 30)))
            out.append(([k, round(float(rng.uniform(0, 20)), 1),
                         round(float(rng.uniform(0, 20)), 1)], t))
    return out


def norm(rows):
    """DOUBLE attrs ride float32 device lanes (documented precision
    subset): one-decimal inputs are exact at 4dp."""
    return [[round(v, 4) if isinstance(v, float) else v for v in r]
            for r in rows]


def run(app, sends, header, mgr=None):
    own = mgr is None
    if own:
        mgr = SiddhiManager()
    try:
        rt = mgr.create_siddhi_app_runtime(header + DEFINE + app)
        got = []
        rt.add_callback("Alerts",
                        lambda evs: got.extend(list(e.data) for e in evs))
        rt.start()
        h = rt.get_input_handler("S")
        for row, ts in sends:
            h.send(list(row), timestamp=ts)
        router = None
        for pr in rt.partitions.values():
            for qr in getattr(pr, "dense_query_runtimes", {}).values():
                router = getattr(qr, "pattern_processor", None)
        low = rt.lowering()
        hot = (router.hot_metrics()
               if isinstance(router, HotKeyRouterRuntime) else {})
        fi = rt.app_context.fault_injector
        fstats = fi.stats.as_dict() if fi else {}
        rt.shutdown()
        return got, router, low, hot, fstats
    finally:
        if own:
            mgr.shutdown()


SKEWED = [(400, 7, 0.8)]  # one hot key at 80% of traffic


class TestHotKeyDifferential:
    """Routed detections == host detections, per chain shape, with the
    promotion counters proving the scan path actually engaged."""

    @pytest.mark.parametrize("shape", sorted(SHAPES))
    @pytest.mark.parametrize("seed", [1, 2, 3])
    def test_shape_matches_host(self, shape, seed):
        sends = gen(seed, SKEWED)
        host, _, _, _, _ = run(wrap(SHAPES[shape]), sends, "@app:playback ")
        got, router, low, hot, _ = run(
            wrap(SHAPES[shape]), sends, "@app:playback " + TPU + HOTKEYS)
        assert isinstance(router, HotKeyRouterRuntime), "did not wrap"
        assert low["q"] == "hotkey"
        assert hot["hotkeyPromotions"] >= 1, hot
        assert hot["hotkeyRoutedEvents"] > 0, hot
        assert norm(got) == norm(host), (
            f"{shape}/{seed}: {len(got)} routed vs {len(host)} host rows")

    @pytest.mark.parametrize("seed", [11, 12])
    def test_promote_demote_midstream(self, seed):
        """The hot key cools mid-run: its pending chains hand back to
        the dense row exactly (detections still identical), and both
        decision counters advance."""
        phases = [(350, 7, 0.85), (350, None, 0.0)]
        sends = gen(seed, phases)
        app = wrap(SHAPES["pair"])
        host, _, _, _, _ = run(app, sends, "@app:playback ")
        got, router, _, hot, _ = run(
            app, sends, "@app:playback " + TPU + HOTKEYS)
        assert hot["hotkeyPromotions"] >= 1, hot
        assert hot["hotkeyDemotions"] >= 1, hot
        assert norm(got) == norm(host)

    def test_rehot_after_demotion(self):
        """hot -> cold -> hot again: the same key re-promotes."""
        phases = [(300, 7, 0.85), (250, None, 0.0), (300, 7, 0.85)]
        sends = gen(21, phases)
        app = wrap(SHAPES["pair"])
        host, _, _, _, _ = run(app, sends, "@app:playback ")
        got, _, _, hot, _ = run(app, sends, "@app:playback " + TPU + HOTKEYS)
        assert hot["hotkeyPromotions"] >= 2, hot
        assert hot["hotkeyDemotions"] >= 1, hot
        assert norm(got) == norm(host)

    def test_multiple_hot_keys(self):
        """Two heavy keys share the slot axis of one batched scan."""
        rng = np.random.default_rng(31)
        sends, t = [], 1000
        for _ in range(500):
            t += int(rng.integers(1, 40))
            r = rng.random()
            k = 7 if r < 0.4 else (13 if r < 0.8 else int(rng.integers(0, 30)))
            sends.append(([k, round(float(rng.uniform(0, 20)), 1),
                           round(float(rng.uniform(0, 20)), 1)], t))
        app = wrap(SHAPES["triple"])
        host, _, _, _, _ = run(app, sends, "@app:playback ")
        got, router, _, hot, _ = run(
            app, sends, "@app:playback " + TPU + HOTKEYS)
        assert hot["hotkeyPromotions"] >= 2, hot
        assert hot["hotkeyActiveKeys"] >= 2, hot
        assert norm(got) == norm(host)


class TestHotKeyFaults:
    pytestmark = pytest.mark.faults

    def test_transient_faults_bit_identical(self):
        sends = gen(41, SKEWED)
        app = wrap(SHAPES["pair"])
        ref, _, _, _, _ = run(app, sends, "@app:playback " + TPU + HOTKEYS)
        faults = ("@app:faults(transfer.retry.scale='0.001', "
                  "ingest.put='transient:count=3', "
                  "emit.drain='transient:count=2') ")
        got, _, low, hot, fstats = run(
            app, sends, "@app:playback " + TPU + HOTKEYS + faults)
        assert low["q"] == "hotkey"
        assert hot["hotkeyPromotions"] >= 1
        assert fstats["faults_injected"] >= 5
        assert fstats["transfer_retries"] >= 3
        assert norm(got) == norm(ref)

    def test_crash_and_journal_replay(self):
        """Checkpoint, crash mid-run (after the hot key promoted),
        restore + journal replay on a fresh runtime — identical to a
        run that never crashed.  The snapshot demotes every hot key, so
        the persisted tree is a plain dense snapshot; the rebuilt
        router re-promotes deterministically from the replayed skew."""
        sends = gen(51, SKEWED)
        app = wrap(SHAPES["pair"])
        ref, _, _, _, _ = run(app, sends, "@app:playback " + TPU + HOTKEYS)

        mgr = SiddhiManager()
        mgr.set_persistence_store(InMemoryPersistenceStore())
        try:
            header = ("@app:name('hkc') @app:playback " + TPU + HOTKEYS
                      + "@app:faults(journal='512') ")
            rt = mgr.create_siddhi_app_runtime(header + DEFINE + app)
            got = []
            rt.add_callback(
                "Alerts", lambda evs: got.extend(list(e.data) for e in evs))
            rt.start()
            h = rt.get_input_handler("S")
            for j, (row, ts) in enumerate(sends):
                if j == 150:
                    rt.persist()
                if j == 250:
                    rt.app_context.fault_injector.configure(
                        "ingest", "crash", count=1)
                    with pytest.raises(SimulatedCrashError):
                        h.send(list(row), timestamp=ts)
                    rt.shutdown()
                    rt = mgr.create_siddhi_app_runtime(header + DEFINE + app)
                    rt.add_callback(
                        "Alerts",
                        lambda evs: got.extend(list(e.data) for e in evs))
                    rt.start()
                    assert rt.restore_last_revision() is not None
                    h = rt.get_input_handler("S")
                    continue
                h.send(list(row), timestamp=ts)
            assert rt.lowering()["q"] == "hotkey"
            rt.shutdown()
        finally:
            mgr.shutdown()
        assert norm(got) == norm(ref)


class TestHotKeyPersistence:
    def test_persist_restore_forgets_post_persist_event(self):
        """restore() rewinds a PROMOTED key's pending chains: the
        checkpoint demotes them into the dense snapshot, a stray
        post-persist event is rolled back, and the continued run
        matches the plain dense runtime under the same sequence."""

        def go(header):
            mgr = SiddhiManager()
            mgr.set_persistence_store(InMemoryPersistenceStore())
            try:
                rt = mgr.create_siddhi_app_runtime(
                    header + DEFINE + wrap(SHAPES["pair"]))
                got = []
                rt.add_callback(
                    "Alerts",
                    lambda evs: got.extend(list(e.data) for e in evs))
                rt.start()
                h = rt.get_input_handler("S")
                sends = gen(61, SKEWED)
                for row, ts in sends[:250]:
                    h.send(list(row), timestamp=ts)
                rt.persist()
                # stray event arms new chains on the hot key, then is
                # rolled back whole
                h.send([7, 15.0, 15.0], timestamp=sends[249][1] + 5)
                rt.restore_last_revision()
                for row, ts in sends[250:]:
                    h.send(list(row), timestamp=ts)
                rt.shutdown()
                return got
            finally:
                mgr.shutdown()

        hot = go("@app:playback " + TPU + HOTKEYS)
        dense = go("@app:playback " + TPU)
        assert len(hot) > 0 and norm(hot) == norm(dense)


INELIGIBLE = {
    "within": (
        "@info(name='q') from every a=S[v > 8.0] -> b=S[v > 12.0] "
        "within 3 sec select b.v as bv insert into Alerts;"),
    "sequence": (
        "@info(name='q') from every a=S[v > 8.0], b=S[v > 12.0] "
        "select b.v as bv insert into Alerts;"),
    "capture_filter": (
        "@info(name='q') from every a=S[v > 8.0] -> b=S[v > a.v] "
        "select b.v as bv insert into Alerts;"),
    "non_final_select": (
        "@info(name='q') from every a=S[v > 8.0] -> b=S[v > 12.0] "
        "select a.v as av, b.v as bv insert into Alerts;"),
    "count_node": (
        "@info(name='q') from every a=S[v > 8.0]<2> -> b=S[v > 12.0] "
        "select b.v as bv insert into Alerts;"),
    "absent_deadline": (
        "@info(name='q') from every a=S[v > 12.0] -> "
        "not S[v > 15.0] for 500 millisec "
        "select a.v as av insert into Alerts;"),
}


class TestHotKeyFallback:
    """Every ineligible shape stays dense with a counted, readable
    reason on the statistics feed — never silently."""

    @pytest.mark.parametrize("shape", sorted(INELIGIBLE))
    def test_ineligible_falls_back_counted(self, shape):
        mgr = SiddhiManager()
        try:
            rt = mgr.create_siddhi_app_runtime(
                "@app:playback " + TPU + HOTKEYS + DEFINE
                + wrap(INELIGIBLE[shape]))
            rt.start()
            assert rt.lowering()["q"] == "dense"
            st = rt.statistics()
            fb = {k: v for k, v in st.items() if "hotkeyFallback" in k}
            counts = [v for k, v in fb.items() if k.endswith("Fallbacks")]
            reasons = [v for k, v in fb.items()
                       if k.endswith("FallbackReason")]
            assert counts == [1], st
            assert reasons and reasons[0], st
            rt.shutdown()
        finally:
            mgr.shutdown()

    def test_hotkeys_annotation_needs_tpu(self):
        from siddhi_tpu.core.exceptions import SiddhiAppCreationError

        mgr = SiddhiManager()
        try:
            with pytest.raises(SiddhiAppCreationError,
                               match="hotkeys needs"):
                mgr.create_siddhi_app_runtime(
                    "@app:hotkeys(k='4') " + DEFINE
                    + wrap(SHAPES["pair"]))
        finally:
            mgr.shutdown()

    def test_hysteresis_band_validated(self):
        from siddhi_tpu.core.exceptions import SiddhiAppCreationError

        mgr = SiddhiManager()
        try:
            with pytest.raises(SiddhiAppCreationError, match="demote"):
                mgr.create_siddhi_app_runtime(
                    TPU + "@app:hotkeys(promote='0.2', demote='0.4') "
                    + DEFINE + wrap(SHAPES["pair"]))
        finally:
            mgr.shutdown()


class TestSpaceSavingSketch:
    def test_capacity_bound_and_heavy_hitters(self):
        sk = SpaceSavingSketch(cap=8, decay=1.0)
        rng = np.random.default_rng(5)
        for _ in range(50):
            ks = np.where(rng.random(64) < 0.6, 7,
                          rng.integers(100, 1000, size=64))
            u, c = np.unique(ks, return_counts=True)
            sk.update(u, c)
        assert len(sk.counts) <= 8
        # the true heavy hitter dominates despite constant eviction
        assert sk.heavy(0.3) and sk.heavy(0.3)[0] == 7
        assert sk.share(7) > 0.5

    def test_decay_forgets_old_traffic(self):
        sk = SpaceSavingSketch(cap=8, decay=0.5)
        sk.update(np.asarray([7]), np.asarray([1000]))
        assert sk.share(7) > 0.9
        for _ in range(30):
            sk.update(np.asarray([1, 2]), np.asarray([50, 50]))
        assert sk.share(7) < 0.05

    def test_deterministic_tie_break(self):
        a, b = SpaceSavingSketch(16), SpaceSavingSketch(16)
        for sk in (a, b):
            sk.update(np.asarray([3, 1, 2]), np.asarray([10, 10, 10]))
        assert a.heavy(0.1) == b.heavy(0.1)
