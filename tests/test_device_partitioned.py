"""Partitioned general queries on the device engine, differentially
against the host per-key-instance form (reference semantics:
partition/PartitionRuntimeImpl.java:75, PartitionStreamReceiver.java:
82-118 — each key behaves as its own cloned query).

Per-event sends must match the host ORDER exactly; batched sends match
as multisets (the host routes key-grouped sub-batches, the device
engine emits in input-row order — both are interleavings of identical
per-key subsequences).
"""

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.event import EventBatch

DEFS = "define stream S (user string, v double, k int); "


def run_app(app_body, events, tpu, batched=False, partitions=64,
            expect_dense=True):
    """events: list of (user, v, k, ts)."""
    mode = (f"@app:execution('tpu', partitions='{partitions}') "
            if tpu else "")
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime(
            "@app:playback " + mode + DEFS + app_body)
        if tpu and expect_dense:
            pr = rt.partitions["partition_0"]
            assert pr.is_dense, "expected the partition to lower densely"
        got = []
        rt.add_callback("Out", lambda evs: got.extend(
            tuple(e.data) for e in evs))
        rt.start()
        h = rt.get_input_handler("S")
        if batched:
            users = np.asarray([e[0] for e in events])
            vs = np.asarray([e[1] for e in events], dtype=np.float64)
            ks = np.asarray([e[2] for e in events], dtype=np.int32)
            ts = np.asarray([e[3] for e in events], dtype=np.int64)
            h.send_batch(EventBatch(
                "S", ["user", "v", "k"],
                {"user": users, "v": vs, "k": ks}, ts))
        else:
            for u, v, k, t in events:
                h.send([u, float(v), int(k)], timestamp=t)
        rt.shutdown()
        return got
    finally:
        m.shutdown()


def _rows_match(a, b, abs_tol=1e-6):
    """Row equality with rel tolerance on floats (device state
    accumulates in float32, a documented precision subset of the host's
    float64 — ops/device_query.py module docstring).  ``abs_tol`` is
    raised for stdDev queries: the float32 sum/sumsq decomposition has
    an absolute error floor of ~sqrt(eps32)*|x| near zero variance."""
    if len(a) != len(b):
        return False
    for x, y in zip(a, b):
        if isinstance(x, float) or isinstance(y, float):
            if y != pytest.approx(x, rel=1e-4, abs=abs_tol):
                return False
        elif x != y:
            return False
    return True


def assert_differential(app_body, events, batched=False, abs_tol=1e-6,
                        **kw):
    """Device vs host.  Per-event sends compare in exact order.  For
    batched sends the reference side is the host run PER EVENT — the
    reference's event-at-a-time semantics — compared as multisets: the
    device batch path preserves per-event semantics regardless of
    batching (per-row time-window expiry), while the host batch path
    approximates time windows at the batch watermark."""
    host = run_app(app_body, events, tpu=False, batched=False, **kw)
    dev = run_app(app_body, events, tpu=True, batched=batched, **kw)
    assert len(host) == len(dev), (host, dev)
    if batched:
        skey = lambda rows: sorted(
            rows, key=lambda r: tuple(
                round(x, 3) if isinstance(x, float) else repr(x)
                for x in r))
        host, dev = skey(host), skey(dev)
    for i, (a, b) in enumerate(zip(host, dev)):
        assert _rows_match(a, b, abs_tol), f"row {i}: {a} != {b}"
    return dev


def events_seq(n=40, seed=0, users=("a", "b", "c"), t_step=100):
    rng = np.random.default_rng(seed)
    out = []
    t = 1_000
    for _ in range(n):
        out.append((
            users[int(rng.integers(len(users)))],
            round(float(rng.uniform(0, 10)), 3),
            int(rng.integers(0, 3)),
            t,
        ))
        t += int(rng.integers(1, t_step))
    return out


PARTITION = "partition with (user of S) begin {q} end;"


class TestPartitionedFilter:
    def test_filter_projection(self):
        q = ("@info(name='q') from S[v > 5.0] select user, v "
             "insert into Out;")
        assert_differential(PARTITION.format(q=q), events_seq())

    def test_filter_batched(self):
        q = ("@info(name='q') from S[v > 5.0 and k != 1] select user, v, k "
             "insert into Out;")
        assert_differential(PARTITION.format(q=q), events_seq(64),
                            batched=True)

    def test_two_filter_queries(self):
        # the reference's SimplePartitionedDoubleFilterQueryPerformance
        # shape: two filter queries in one partition body
        q = ("@info(name='q1') from S[v > 5.0] select user, v "
             "insert into Out; "
             "@info(name='q2') from S[v <= 5.0] select user, v "
             "insert into Out;")
        assert_differential(PARTITION.format(q=q), events_seq())


class TestPartitionedRunningAggregates:
    @pytest.mark.parametrize("agg", ["sum(v)", "count()", "avg(v)",
                                     "min(v)", "max(v)", "stdDev(v)",
                                     "minForever(v)", "maxForever(v)"])
    def test_running(self, agg):
        q = (f"@info(name='q') from S select user, {agg} as a "
             "insert into Out;")
        assert_differential(PARTITION.format(q=q), events_seq(),
                            abs_tol=5e-3 if "stdDev" in agg else 1e-6)

    def test_running_with_filter(self):
        q = ("@info(name='q') from S[v > 2.0] select user, sum(v) as a, "
             "count() as c insert into Out;")
        assert_differential(PARTITION.format(q=q), events_seq())

    def test_inner_group_by(self):
        # per-(key, group) state: composed group axis
        q = ("@info(name='q') from S select user, k, sum(v) as a "
             "group by k insert into Out;")
        assert_differential(PARTITION.format(q=q), events_seq())

    def test_inner_group_by_having(self):
        q = ("@info(name='q') from S select user, k, sum(v) as a "
             "group by k having a > 10.0 insert into Out;")
        assert_differential(PARTITION.format(q=q), events_seq(60))

    def test_batched_running(self):
        q = ("@info(name='q') from S select user, sum(v) as a "
             "insert into Out;")
        assert_differential(PARTITION.format(q=q), events_seq(64),
                            batched=True)


class TestPartitionedSlidingWindows:
    @pytest.mark.parametrize("agg", ["sum(v)", "count()", "avg(v)",
                                     "min(v)", "max(v)", "stdDev(v)",
                                     "minForever(v)", "maxForever(v)"])
    def test_length_window(self, agg):
        q = (f"@info(name='q') from S#window.length(3) select user, "
             f"{agg} as a insert into Out;")
        assert_differential(PARTITION.format(q=q), events_seq(),
                            abs_tol=5e-3 if "stdDev" in agg else 1e-6)

    def test_length_window_with_filter(self):
        q = ("@info(name='q') from S[v > 2.0]#window.length(2) "
             "select user, sum(v) as a insert into Out;")
        assert_differential(PARTITION.format(q=q), events_seq())

    @pytest.mark.parametrize("agg", ["sum(v)", "count()", "min(v)"])
    def test_time_window(self, agg):
        q = (f"@info(name='q') from S#window.time(250 ms) select user, "
             f"{agg} as a insert into Out;")
        assert_differential(PARTITION.format(q=q), events_seq())

    def test_time_window_group_by(self):
        q = ("@info(name='q') from S#window.time(300 ms) select user, k, "
             "count() as c group by k insert into Out;")
        assert_differential(PARTITION.format(q=q), events_seq())

    def test_length_window_batched(self):
        q = ("@info(name='q') from S#window.length(4) select user, "
             "sum(v) as a insert into Out;")
        assert_differential(PARTITION.format(q=q), events_seq(64),
                            batched=True)

    def test_window_displacement_within_one_batch(self):
        # one key floods > W events in a single batch: displaced rows
        # must never land in the ring buffer
        events = [("a", float(i), 0, 1000 + i) for i in range(16)]
        q = ("@info(name='q') from S#window.length(3) select user, "
             "sum(v) as a insert into Out;")
        assert_differential(PARTITION.format(q=q), events, batched=True)


class TestRangePartitionsOnDevice:
    def test_range_partition_running(self):
        body = ("partition with (v < 5.0 as 'low' or v >= 5.0 as 'high' "
                "of S) begin @info(name='q') from S select k, count() as c "
                "insert into Out; end;")
        assert_differential(body, events_seq())


class TestMixedPartitionBody:
    def test_pattern_and_filter_in_one_partition(self):
        # pattern lowers to the dense NFA, the filter to the device
        # query engine — both under one partition
        body = ("partition with (user of S) begin "
                "@info(name='pat') from every e1=S[v > 8.0] -> "
                "e2=S[v > 8.0] within 10 sec "
                "select e1.v as v1, e2.v as v2 insert into Out; "
                "@info(name='flt') from S[v > 9.0] select user, v "
                "insert into Out; end;")
        assert_differential(body, events_seq(60, seed=3))


class TestFallbacks:
    """Ineligible partition bodies fall back WHOLESALE to per-key
    instances (and still produce host-exact results trivially)."""

    def _is_dense(self, body):
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(
                "@app:playback @app:execution('tpu', partitions='16') "
                + DEFS + body)
            return rt.partitions["partition_0"].is_dense
        finally:
            m.shutdown()

    def test_tumbling_falls_back(self):
        q = ("@info(name='q') from S#window.lengthBatch(3) select user, "
             "sum(v) as a insert into Out;")
        assert not self._is_dense(PARTITION.format(q=q))
        assert_differential(PARTITION.format(q=q), events_seq(),
                            partitions=16, expect_dense=False)

    def test_rate_limit_falls_back(self):
        q = ("@info(name='q') from S select user, sum(v) as a "
             "output last every 3 events insert into Out;")
        assert not self._is_dense(PARTITION.format(q=q))

    def test_order_by_falls_back(self):
        q = ("@info(name='q') from S select user, v order by v "
             "insert into Out;")
        assert not self._is_dense(PARTITION.format(q=q))

    def test_mixed_with_ineligible_falls_back_wholesale(self):
        q = ("@info(name='q1') from S select user, sum(v) as a "
             "insert into Out; "
             "@info(name='q2') from S#window.lengthBatch(2) select user, "
             "sum(v) as a insert into Out;")
        assert not self._is_dense(PARTITION.format(q=q))


class TestPartitionedDevicePersistence:
    def test_snapshot_restore_roundtrip(self):
        from siddhi_tpu.util.persistence import InMemoryPersistenceStore

        app = ("@app:name('pdp') @app:playback "
               "@app:execution('tpu', partitions='16') " + DEFS +
               PARTITION.format(q=(
                   "@info(name='q') from S#window.length(2) select user, "
                   "sum(v) as a insert into Out;")))
        m = SiddhiManager()
        m.set_persistence_store(InMemoryPersistenceStore())
        try:
            rt = m.create_siddhi_app_runtime(app)
            assert rt.partitions["partition_0"].is_dense
            rt.start()
            h = rt.get_input_handler("S")
            h.send(["a", 1.0, 0], timestamp=1000)
            h.send(["a", 3.0, 0], timestamp=1001)
            h.send(["b", 7.0, 0], timestamp=1002)
            rev = rt.persist()
            rt.shutdown()

            rt2 = m.create_siddhi_app_runtime(app)
            got = []
            rt2.add_callback("Out", lambda evs: got.extend(
                tuple(e.data) for e in evs))
            rt2.start()
            rt2.restore_revision(rev)
            h2 = rt2.get_input_handler("S")
            h2.send(["a", 10.0, 0], timestamp=1003)  # window [3, 10]
            h2.send(["b", 1.0, 0], timestamp=1004)   # window [7, 1]
            rt2.shutdown()
            assert got == [("a", 13.0), ("b", 8.0)], got
        finally:
            m.shutdown()


class TestPartitionedFuzz:
    """Seeded sweep over query shape x event stream combinations."""

    QUERIES = [
        "from S[v > 4.0] select user, v insert into Out;",
        "from S select user, sum(v) as a, max(v) as m insert into Out;",
        "from S[k != 0] select user, count() as c insert into Out;",
        "from S select user, k, avg(v) as a group by k insert into Out;",
        "from S#window.length(2) select user, sum(v) as a insert into Out;",
        "from S#window.length(5) select user, min(v) as a, count() as c "
        "insert into Out;",
        "from S[v > 1.0]#window.time(200 ms) select user, sum(v) as a "
        "insert into Out;",
        "from S#window.time(150 ms) select user, k, count() as c "
        "group by k insert into Out;",
    ]

    @pytest.mark.parametrize("seed", range(4))
    def test_fuzz(self, seed):
        rng = np.random.default_rng(100 + seed)
        for qi, q in enumerate(self.QUERIES):
            events = events_seq(
                n=int(rng.integers(20, 60)), seed=seed * 31 + qi,
                users=tuple("uvwxyz"[: int(rng.integers(2, 6))]),
                t_step=int(rng.integers(20, 200)))
            assert_differential(
                PARTITION.format(q=f"@info(name='q') {q}"), events,
                batched=bool(rng.integers(2)))


class TestPartitionedDevicePurge:
    def test_purge_frees_rows_and_matches_host_reset(self):
        app_body = (
            "@purge(enable='true', interval='1 sec', idle.period='2 sec') "
            + PARTITION.format(q=(
                "@info(name='q') from S select user, count() as c "
                "insert into Out;")))
        # host and device must agree INCLUDING the purge-induced reset
        events = [("a", 1.0, 0, 1000), ("b", 1.0, 0, 1001),
                  ("a", 1.0, 0, 1500),
                  # watermark jump: both engines purge idle keys
                  ("a", 1.0, 0, 60_000), ("b", 1.0, 0, 60_001)]
        host = run_app(app_body, events, tpu=False)
        dev = run_app(app_body, events, tpu=True, partitions=2)
        assert host == dev, (host, dev)
        # b restarted at 1 (purged), proving a 2-row engine survived 2
        # distinct live keys + 1 reused row
        assert dev[-1] == ("b", 1)
