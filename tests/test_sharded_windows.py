"""Differential suite: sharded windowed state vs the single-device path.

The shard-major layout (parallel/device_shard.py) now covers every
stateful device-query kind — tumbling panes (lengthBatch/timeBatch),
the global sliding ring (length/time), and the keyed per-partition
sliding window.  The contract is BIT-IDENTITY: an app compiled with
``devices='8'`` must emit exactly the rows, in exactly the order, of
the same app on one device — including when batches straddle pane
boundaries, when transient ingest/emit faults fire mid-stream, across
a crash + journal replay, and across persist()/restore.

conftest.py forces an 8-device virtual CPU mesh (>= the 4-device floor
this suite requires); anything less fails loudly there.
"""

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.device_single import DeviceQueryRuntime
from siddhi_tpu.core.event import EventBatch
from siddhi_tpu.core.exceptions import SimulatedCrashError
from siddhi_tpu.parallel import ShardedDeviceQueryEngine
from siddhi_tpu.util.persistence import InMemoryPersistenceStore

DEFINE = "define stream S (sym string, v double, k int); "

SINGLE = "@app:playback @app:execution('tpu') "
SHARDED = "@app:playback @app:execution('tpu', partitions='64', devices='8') "

WINDOWS = {
    "lengthBatch": "#window.lengthBatch(5)",
    "timeBatch": "#window.timeBatch(100 ms)",
    "sliding_length": "#window.length(6)",
    "sliding_time": "#window.time(200 ms)",
}

# sizes chosen against the 5-event pane: runs straddle, under-fill,
# exactly fill, and multi-fill a pane within single batches
BATCH_SIZES = (3, 7, 2, 11, 5, 1, 9, 16, 4)


def query(win):
    return (DEFINE + f"@info(name='q') from S{WINDOWS[win]} "
            "select k, sum(v) as s, count() as c, min(v) as mn, "
            "max(v) as mx group by k insert into OutputStream;")


def batches(seed=9, sizes=BATCH_SIZES, n_keys=5, n_syms=1):
    """Multi-event EventBatches with integer-valued floats (exact in
    float32, so reduction order cannot blur the bit-identity check)."""
    rng = np.random.default_rng(seed)
    syms = np.asarray([f"s{i}" for i in range(n_syms)], dtype=object)
    out, t = [], 1000
    for n in sizes:
        cols = {
            "sym": syms[rng.integers(0, n_syms, n)],
            "v": rng.integers(0, 50, n).astype(np.float64),
            "k": rng.integers(0, n_keys, n).astype(np.int32),
        }
        ts = t + np.arange(n, dtype=np.int64) * 17
        t = int(ts[-1]) + 29
        out.append((cols, ts))
    return out


def run(app, sends, store=None, transfer_guard=False):
    import contextlib

    m = SiddhiManager()
    try:
        if store is not None:
            m.set_persistence_store(store)
        rt = m.create_siddhi_app_runtime(app)
        got = []
        rt.add_callback("OutputStream", lambda evs: got.extend(
            tuple(e.data) for e in evs))
        rt.start()
        h = rt.get_input_handler("S")
        # transfer_guard: the sharded batch loop may only cross the
        # device boundary explicitly (staged_put onto the mesh, explicit
        # device_get at the count gate / drain) — the dynamic twin of
        # the host-sync-hazard analysis rule.  No-op on the CPU backend;
        # bites on real accelerator runs.
        guard = contextlib.nullcontext()
        if transfer_guard:
            import jax

            guard = jax.transfer_guard("disallow")
        with guard:
            for cols, ts in sends:
                h.send_batch(EventBatch(
                    "S", ["sym", "v", "k"],
                    {k: v.copy() for k, v in cols.items()}, ts.copy()))
        runtimes = [getattr(qr, "device_runtime", None)
                    for qr in rt.query_runtimes.values()]
        for pr in getattr(rt, "partitions", {}).values():
            runtimes += [qr.device_runtime for qr in
                         getattr(pr, "dense_query_runtimes", {}).values()]
        rt.shutdown()
        return got, runtimes, rt
    finally:
        m.shutdown()


def sharded_runtime(runtimes):
    dr = [r for r in runtimes if isinstance(r, DeviceQueryRuntime)]
    assert dr, "query did not lower to a device runtime"
    assert isinstance(dr[0].engine, ShardedDeviceQueryEngine), (
        "sharded path fell back to single-device")
    return dr[0]


def n_state_devices(state):
    return len({d for arr in state.values() for d in arr.devices()})


class TestBitIdentity:
    @pytest.mark.parametrize("win", sorted(WINDOWS))
    def test_pane_straddling_batches(self, win):
        q = query(win)
        single, _, _ = run(SINGLE + q, batches())
        sharded, runtimes, _ = run(SHARDED + q, batches(),
                                   transfer_guard=True)
        dr = sharded_runtime(runtimes)
        assert n_state_devices(dr.state) == 8
        assert len(single) >= 5, "series too tame; differential is vacuous"
        assert sharded == single

    def test_keyed_sliding_partitioned(self):
        # partition-mode sliding: per-key ring rows shard on the
        # partition-key (wgroup) axis
        body = (DEFINE + "partition with (sym of S) begin "
                "@info(name='pq') from S#window.length(4) select sym, k, "
                "sum(v) as s group by k insert into OutputStream; end;")
        sends = batches(seed=4, n_keys=3, n_syms=4)
        single, _, _ = run(
            "@app:playback @app:execution('tpu', partitions='16') " + body,
            sends)
        sharded, runtimes, _ = run(
            "@app:playback @app:execution('tpu', partitions='16', "
            "devices='8') " + body, sends)
        sharded_runtime(runtimes)
        assert len(single) >= 5
        assert sharded == single

    def test_timer_flush_path(self):
        # a timeBatch pane closed by the playback clock advancing (no
        # carrier event in the closing batch) must emit identically
        q = query("timeBatch")
        sends = batches(sizes=(4, 3))
        # a late straggler far past the pane end drives flush_due
        sends.append(({"sym": np.asarray(["s0"], dtype=object),
                       "v": np.asarray([1.0]),
                       "k": np.asarray([0], dtype=np.int32)},
                      np.asarray([60_000], dtype=np.int64)))
        single, _, _ = run(SINGLE + q, sends)
        sharded, runtimes, _ = run(SHARDED + q, sends)
        sharded_runtime(runtimes)
        assert len(single) >= 2
        assert sharded == single


class TestTransientFaults:
    @pytest.mark.parametrize("spec", [
        "ingest.put='transient:count=2'",
        "emit.drain='transient:count=2'",
    ])
    def test_transient_fault_bit_exact(self, spec):
        q = query("lengthBatch")
        clean, _, _ = run(SHARDED + q, batches())
        chaotic, runtimes, rt = run(
            "@app:playback @app:faults(seed='3', "
            f"transfer.retry.scale='0.0001', {spec}) "
            "@app:execution('tpu', partitions='64', devices='8') " + q,
            batches())
        sharded_runtime(runtimes)
        assert chaotic == clean, (
            "retried transfers must not lose, dup, or reorder rows")
        fi = rt.app_context.fault_injector
        assert fi.stats.faults_injected == 2
        assert fi.stats.transfer_retries == 2
        assert fi.stats.drains_failed == 0


class TestCrashRecovery:
    @pytest.mark.parametrize("win", ["lengthBatch", "sliding_time"])
    def test_crash_and_journal_replay_bit_identical(self, win):
        q = query(win)
        header = ("@app:name('shwincrash') @app:playback "
                  "@app:faults(journal='256') "
                  "@app:execution('tpu', partitions='64', devices='8') ")
        # per-event sends: the journal replays per recorded batch, and
        # a 30-event series crosses several pane/ring boundaries
        rng = np.random.default_rng(13)
        sends = [(["s0", float(rng.integers(0, 50)),
                   int(rng.integers(0, 4))], 1000 + i * 40)
                 for i in range(30)]

        def reference():
            got, _, _ = run(SHARDED + q, [
                ({"sym": np.asarray([r[0]], dtype=object),
                  "v": np.asarray([r[1]]),
                  "k": np.asarray([r[2]], dtype=np.int32)},
                 np.asarray([ts], dtype=np.int64)) for r, ts in sends])
            return got

        ref = reference()
        assert len(ref) >= 4

        m = SiddhiManager()
        try:
            m.set_persistence_store(InMemoryPersistenceStore())
            rt = m.create_siddhi_app_runtime(header + q)
            got = []
            rt.add_callback("OutputStream", lambda evs: got.extend(
                tuple(e.data) for e in evs))
            rt.start()
            h = rt.get_input_handler("S")
            for row, ts in sends[:10]:
                h.send(list(row), timestamp=ts)
            rt.persist()  # mid-pane checkpoint
            for row, ts in sends[10:20]:
                h.send(list(row), timestamp=ts)
            rt.app_context.fault_injector.configure(
                "ingest", "crash", count=1)
            with pytest.raises(SimulatedCrashError):
                h.send(list(sends[20][0]), timestamp=sends[20][1])
            rt.shutdown()

            rt2 = m.create_siddhi_app_runtime(header + q)
            rt2.add_callback("OutputStream", lambda evs: got.extend(
                tuple(e.data) for e in evs))
            rt2.start()
            assert rt2.restore_last_revision() is not None
            h2 = rt2.get_input_handler("S")
            # the crashed send WAS journaled; replay delivered it
            for row, ts in sends[21:]:
                h2.send(list(row), timestamp=ts)
            rt2.shutdown()
            assert got == ref, (
                f"{win}: crash+replay diverged from the uninterrupted run")
            jr = rt2.app_context.input_journal
            assert jr.stats.replayed_batches == 11
        finally:
            m.shutdown()


class TestSnapshotRestore:
    @pytest.mark.parametrize("win", sorted(WINDOWS))
    def test_persist_restore_mid_pane(self, win):
        # split after 3 batches (12 events): a lengthBatch(5) pane is
        # 2/5 full and the sliding rings hold live rows at the cut
        q = query(win)
        app = "@app:name('shwinsnap') " + SHARDED + q
        sends = batches()
        ref, _, _ = run(app, sends, store=InMemoryPersistenceStore())
        assert len(ref) >= 5

        store = InMemoryPersistenceStore()
        m = SiddhiManager()
        try:
            m.set_persistence_store(store)
            rt = m.create_siddhi_app_runtime(app)
            got = []
            rt.add_callback("OutputStream", lambda evs: got.extend(
                tuple(e.data) for e in evs))
            rt.start()
            h = rt.get_input_handler("S")
            for cols, ts in sends[:3]:
                h.send_batch(EventBatch(
                    "S", ["sym", "v", "k"],
                    {k: v.copy() for k, v in cols.items()}, ts.copy()))
            rev = rt.persist()
            rt.shutdown()

            rt2 = m.create_siddhi_app_runtime(app)
            rt2.add_callback("OutputStream", lambda evs: got.extend(
                tuple(e.data) for e in evs))
            rt2.start()
            rt2.restore_revision(rev)
            dr = sharded_runtime(
                [getattr(qr, "device_runtime", None)
                 for qr in rt2.query_runtimes.values()])
            assert n_state_devices(dr.state) == 8  # placement restored
            h2 = rt2.get_input_handler("S")
            for cols, ts in sends[3:]:
                h2.send_batch(EventBatch(
                    "S", ["sym", "v", "k"],
                    {k: v.copy() for k, v in cols.items()}, ts.copy()))
            rt2.shutdown()
            assert got == ref, (
                f"{win}: persist/restore diverged from the "
                "uninterrupted run")
        finally:
            m.shutdown()
