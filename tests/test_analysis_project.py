"""ProjectIndex: cross-module resolution the lexical pass cannot do.

Covers the whole-program layer added over ``ModuleIndex``: import maps
(absolute, relative, aliased, ``__init__`` re-exports), C3 MRO over
project-local bases (mixins and diamonds), MRO-merged method tables,
and the conservative call graph — plus the two DIFFERENTIAL fixtures
the upgrade exists for: a mixin lock conflict and a cross-module jitted
helper that the pre-project lexical pass provably misses (asserted:
old resolver finds zero, project mode finds it).
"""

import textwrap
from pathlib import Path

from siddhi_tpu.analysis import (Allowlist, ModuleIndex, get_rule,
                                 run_rules)
from siddhi_tpu.analysis.project import ProjectIndex, module_name_of


def _mod(rel, src):
    return ModuleIndex(Path(rel), rel, source=textwrap.dedent(src))


def make_project(files):
    indexes = [_mod(rel, src) for rel, src in files.items()]
    return ProjectIndex(indexes), {i.rel: i for i in indexes}


# -- module naming / imports ------------------------------------------------

def test_module_name_of():
    assert module_name_of("siddhi_tpu/core/stream.py") == \
        "siddhi_tpu.core.stream"
    assert module_name_of("siddhi_tpu/core/__init__.py") == \
        "siddhi_tpu.core"


def test_import_map_absolute_relative_aliased():
    proj, _ = make_project({
        "pkg/__init__.py": "",
        "pkg/a.py": "def f():\n    return 1\n",
        "pkg/sub/__init__.py": "",
        "pkg/sub/b.py": """
            import pkg.a
            from pkg.a import f
            from pkg.a import f as g
            from .. import a as amod
            from ..a import f as h
        """,
    })
    imp = proj.imports["pkg.sub.b"]
    assert imp["f"] == "pkg.a.f"
    assert imp["g"] == "pkg.a.f"
    assert imp["amod"] == "pkg.a"
    assert imp["h"] == "pkg.a.f"
    assert imp["pkg"] == "pkg"
    # all forms resolve to the same def
    for name in ("f", "g", "h"):
        assert proj.resolve_symbol("pkg.sub.b", name) == \
            ("function", "pkg.a.f")
    assert proj.resolve_symbol("pkg.sub.b", "amod.f") == \
        ("function", "pkg.a.f")
    assert proj.resolve_symbol("pkg.sub.b", "pkg.a.f") == \
        ("function", "pkg.a.f")


def test_reexport_chasing_through_package_init():
    proj, _ = make_project({
        "pkg/__init__.py": "from .impl import f\n",
        "pkg/impl.py": "def f():\n    return 1\n",
        "pkg/user.py": "from pkg import f\n",
    })
    assert proj.resolve_symbol("pkg.user", "f") == \
        ("function", "pkg.impl.f")


def test_function_local_imports_resolve():
    proj, idxs = make_project({
        "pkg/__init__.py": "",
        "pkg/a.py": "def helper():\n    return 1\n",
        "pkg/b.py": """
            def outer():
                from pkg.a import helper
                return helper()
        """,
    })
    idx = idxs["pkg/b.py"]
    call = next(c for c in idx.calls())
    hit = proj.resolve_call(idx, call)
    assert hit is not None and hit[2] == "pkg.a.helper"


# -- class hierarchy --------------------------------------------------------

DIAMOND = {
    "pkg/__init__.py": "",
    "pkg/base.py": """
        class Base:
            def hello(self):
                return "base"
            def shared(self):
                return "base"
    """,
    "pkg/mix.py": """
        from pkg.base import Base
        class Left(Base):
            def shared(self):
                return "left"
        class Right(Base):
            def hello(self):
                return "right"
    """,
    "pkg/leaf.py": """
        from pkg.mix import Left, Right
        class Leaf(Left, Right):
            pass
    """,
}


def test_c3_mro_over_diamond():
    proj, _ = make_project(DIAMOND)
    assert proj.mro("pkg.leaf.Leaf") == [
        "pkg.leaf.Leaf", "pkg.mix.Left", "pkg.mix.Right", "pkg.base.Base"]


def test_method_resolution_most_derived_wins():
    proj, _ = make_project(DIAMOND)
    methods = proj.class_methods("pkg.leaf.Leaf")
    assert methods["shared"][2] == "pkg.mix.Left"    # Left overrides Base
    assert methods["hello"][2] == "pkg.mix.Right"    # Right overrides Base
    # and the defining index is the defining module's
    assert methods["shared"][0].rel == "pkg/mix.py"


# -- call graph -------------------------------------------------------------

def test_self_dispatch_resolves_through_mro():
    proj, idxs = make_project({
        "pkg/__init__.py": "",
        "pkg/base.py": """
            class Base:
                def run(self):
                    return self.work()
        """,
        "pkg/leaf.py": """
            from pkg.base import Base
            class Leaf(Base):
                def work(self):
                    return 1
        """,
    })
    idx = idxs["pkg/base.py"]
    call = next(c for c in idx.calls())
    # from Base itself, work() is not defined anywhere on Base's MRO
    assert proj.resolve_call(idx, call) is None
    # ...but the merged table of Leaf sees Base.run AND Leaf.work
    methods = proj.class_methods("pkg.leaf.Leaf")
    assert set(methods) == {"run", "work"}


def test_partial_and_wrapper_first_arg_resolve():
    proj, idxs = make_project({
        "pkg/__init__.py": "",
        "pkg/a.py": "def f(x):\n    return x\n",
        "pkg/b.py": """
            import functools
            from pkg.a import f
            def build():
                return functools.partial(f, 1)
        """,
    })
    idx = idxs["pkg/b.py"]
    call = next(c for c in idx.calls()
                if idx.dotted(c.func) == "functools.partial")
    hit = proj.resolve_call(idx, call)
    assert hit is not None and hit[2] == "pkg.a.f"


# -- differential fixtures: project mode catches what lexical misses --------

MIXIN_LOCK_FILES = {
    "pkg/__init__.py": "",
    "pkg/retrymix.py": """
        import threading
        class RetryMixin:
            def arm(self):
                t = threading.Timer(1.0, self._fire)
                t.daemon = True
                t.start()
            def _fire(self):
                self.connected = True    # thread side, unlocked
    """,
    "pkg/client.py": """
        from pkg.retrymix import RetryMixin
        class Client(RetryMixin):
            def shutdown(self):
                self.connected = False   # main side, unlocked
    """,
}


def test_lock_discipline_differential_mixin_conflict():
    """The Timer target lives in the mixin, the main-path write in the
    subclass: invisible lexically, a conflict through the MRO."""
    rule = get_rule("lock-discipline")
    indexes = [_mod(rel, src) for rel, src in MIXIN_LOCK_FILES.items()]
    # OLD resolver (single-module lexical): zero findings on BOTH files
    for idx in indexes:
        rule.begin()
        assert list(rule.check(idx)) == [], idx.rel
    # NEW resolver (whole-program): exactly the mixin conflict
    res = run_rules(indexes, [rule], {"lock-discipline":
                                      Allowlist("lock-discipline", {})})
    assert [(f.rel, f.scope) for f in res["findings"]] == \
        [("pkg/client.py", "Client.connected")]


CROSS_JIT_FILES = {
    "pkg/__init__.py": "",
    "pkg/steps.py": """
        import time
        def scan_step(state, cols):
            t0 = time.time()    # host clock inside a jitted callable
            return state + cols
    """,
    "pkg/engine.py": """
        import jax
        from pkg.steps import scan_step
        class Engine:
            def build(self):
                self._step = jax.jit(scan_step)
    """,
}


def test_jit_purity_differential_cross_module_callable():
    """The jitted callable is imported from another module: the lexical
    resolver cannot find its def; the project resolver follows the
    import and attributes the finding to the helper's file."""
    rule = get_rule("jit-purity")
    indexes = [_mod(rel, src) for rel, src in CROSS_JIT_FILES.items()]
    # OLD resolver: zero findings on BOTH files
    for idx in indexes:
        rule.begin()
        assert list(rule.check(idx)) == [], idx.rel
    # NEW resolver: the helper's host clock is found, in the helper
    res = run_rules(indexes, [rule],
                    {"jit-purity": Allowlist("jit-purity", {})})
    assert [(f.rel, f.scope) for f in res["findings"]] == \
        [("pkg/steps.py", "scan_step")]
    assert "host clock" in res["findings"][0].message


def test_jit_purity_follows_transitive_helpers():
    """Effects two hops from the jitted root are still trace-time."""
    rule = get_rule("jit-purity")
    indexes = [_mod(rel, src) for rel, src in {
        "pkg/__init__.py": "",
        "pkg/low.py": """
            def leaf(x):
                print("tracing")   # effect two hops down
                return x
        """,
        "pkg/mid.py": """
            from pkg.low import leaf
            def helper(x):
                return leaf(x)
        """,
        "pkg/top.py": """
            import jax
            from pkg.mid import helper
            def build():
                return jax.jit(helper)
        """,
    }.items()]
    res = run_rules(indexes, [rule],
                    {"jit-purity": Allowlist("jit-purity", {})})
    assert [(f.rel, f.scope) for f in res["findings"]] == \
        [("pkg/low.py", "leaf")]


def test_retrace_cross_module_builder_call():
    """A hot function calling a non-hot builder in another module that
    returns a fresh jit wrapper churns the compile cache; memoizing the
    result at the call site is quiet."""
    rule = get_rule("retrace-hazard")
    churn = {
        "pkg/__init__.py": "",
        "pkg/build.py": """
            import jax
            def make_fn(c):
                return jax.jit(lambda x: x * c)
        """,
        "pkg/hot.py": """
            from pkg.build import make_fn
            class E:
                def process_batch(self, cols):
                    f = make_fn(2)      # fresh wrapper per batch
                    return f(cols)
        """,
    }
    indexes = [_mod(rel, src) for rel, src in churn.items()]
    # lexically invisible: the wrap is in another module
    for idx in indexes:
        rule.begin()
        assert list(rule.check(idx)) == [], idx.rel
    res = run_rules(indexes, [rule],
                    {"retrace-hazard": Allowlist("retrace-hazard", {})})
    assert [(f.rel, f.scope) for f in res["findings"]] == \
        [("pkg/hot.py", "E.process_batch")]

    memo = dict(churn)
    memo["pkg/hot.py"] = """
        from pkg.build import make_fn
        class E:
            def process_batch(self, cols):
                if self._f is None:
                    self._f = make_fn(2)
                return self._f(cols)
    """
    indexes = [_mod(rel, src) for rel, src in memo.items()]
    res = run_rules(indexes, [rule],
                    {"retrace-hazard": Allowlist("retrace-hazard", {})})
    assert res["findings"] == []


def test_lock_discipline_mixin_conflict_dedups_to_base_most_class():
    """The same mixin-internal conflict seen through N subclasses is
    one finding, on the mixin."""
    files = {
        "pkg/__init__.py": "",
        "pkg/mix.py": """
            import threading
            class Mix:
                def arm(self):
                    t = threading.Timer(1.0, self._fire)
                    t.daemon = True
                    t.start()
                def _fire(self):
                    self.state = 1    # thread side
                def reset(self):
                    self.state = 0    # main side
        """,
        "pkg/subs.py": """
            from pkg.mix import Mix
            class A(Mix):
                pass
            class B(Mix):
                pass
        """,
    }
    indexes = [_mod(rel, src) for rel, src in files.items()]
    rule = get_rule("lock-discipline")
    res = run_rules(indexes, [rule], {"lock-discipline":
                                      Allowlist("lock-discipline", {})})
    assert [(f.rel, f.scope) for f in res["findings"]] == \
        [("pkg/mix.py", "Mix.state")]
