"""Multi-process (DCN-analog) bring-up: `distributed_initialize` with a
REAL 2-process CPU cluster — each subprocess is one "host" owning one
device of a global mesh, and a shard_map psum runs across the process
boundary (the multi-host form of the single-process sharding the rest
of the suite exercises; SURVEY §2.3 distribution row).
"""

import subprocess
import sys
import textwrap

import pytest

WORKER = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("JAX_NUM_CPU_DEVICES", None)
    os.environ.pop("XLA_FLAGS", None)
    pid, n, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    sys.path.insert(0, %(repo)r)
    from siddhi_tpu.parallel import distributed_initialize

    distributed_initialize(
        coordinator_address="127.0.0.1:" + port,
        num_processes=n, process_id=pid)

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    assert jax.process_count() == n, jax.process_count()
    assert jax.device_count() == n  # one CPU device per process
    mesh = Mesh(np.asarray(jax.devices()), axis_names=("p",))

    # one shard per process; psum crosses the process boundary (DCN)
    local = jnp.full((1, 4), float(pid + 1))
    garr = jax.make_array_from_single_device_arrays(
        (n, 4), NamedSharding(mesh, P("p", None)),
        [jax.device_put(local, jax.local_devices()[0])])

    def f(x):
        return jax.lax.psum(jnp.sum(x), axis_name="p")

    from siddhi_tpu.parallel.mesh import get_shard_map

    total = jax.jit(get_shard_map()(
        f, mesh=mesh, in_specs=P("p", None), out_specs=P()))(garr)
    expect = 4.0 * sum(range(1, n + 1))
    assert float(total) == expect, (float(total), expect)
    print(f"proc {pid} OK psum={float(total)}")
""")


def test_two_process_mesh_psum(tmp_path):
    import socket

    with socket.socket() as s:  # free port for the coordinator
        s.bind(("127.0.0.1", 0))
        port = str(s.getsockname()[1])
    script = tmp_path / "worker.py"
    script.write_text(WORKER % {"repo": str(__import__("pathlib").Path(
        __file__).resolve().parent.parent)})
    env = {"PATH": "/usr/bin:/bin:/usr/local/bin", "HOME": "/tmp"}
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(pid), "2", port],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env)
        for pid in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=150)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("distributed worker timed out")
        outs.append(out.decode())
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out}"
        assert f"proc {pid} OK psum=12.0" in out, out
