"""On-demand (pull) query conformance tests.

Modeled on the reference store-query corpus
(modules/siddhi-core/src/test/java/io/siddhi/core/query/StoreQueryTableTestCase
/ StoreQueryTestCase): populate a table/window/aggregation via push queries,
then pull with runtime.query(...) and assert rows.
"""

import pytest

from siddhi_tpu import SiddhiManager


@pytest.fixture
def manager():
    m = SiddhiManager()
    yield m
    m.shutdown()


TABLE_APP = (
    "define stream StockStream (symbol string, price float, volume long); "
    "define table StockTable (symbol string, price float, volume long); "
    "from StockStream insert into StockTable;"
)


def _populate(rt):
    h = rt.get_input_handler("StockStream")
    h.send(["WSO2", 55.6, 100])
    h.send(["IBM", 75.6, 10])
    h.send(["WSO2", 57.6, 50])


def test_find_all(manager):
    rt = manager.create_siddhi_app_runtime(TABLE_APP)
    rt.start()
    _populate(rt)
    events = rt.query("from StockTable select symbol, price, volume;")
    got = sorted(tuple(e.data) for e in events)
    assert [(s, pytest.approx(p), v) for s, p, v in [
        ("IBM", 75.6, 10), ("WSO2", 55.6, 100), ("WSO2", 57.6, 50),
    ]] == got


def test_find_with_condition(manager):
    rt = manager.create_siddhi_app_runtime(TABLE_APP)
    rt.start()
    _populate(rt)
    events = rt.query("from StockTable on volume > 40 select symbol, volume;")
    assert sorted(tuple(e.data) for e in events) == [("WSO2", 50), ("WSO2", 100)]


def test_find_select_star(manager):
    rt = manager.create_siddhi_app_runtime(TABLE_APP)
    rt.start()
    _populate(rt)
    events = rt.query("from StockTable on symbol == 'IBM';")
    assert [tuple(e.data) for e in events] == [("IBM", pytest.approx(75.6), 10)]


def test_find_group_by_aggregation(manager):
    rt = manager.create_siddhi_app_runtime(TABLE_APP)
    rt.start()
    _populate(rt)
    events = rt.query(
        "from StockTable select symbol, sum(volume) as totalVolume "
        "group by symbol order by symbol;"
    )
    assert [tuple(e.data) for e in events] == [("IBM", 10), ("WSO2", 150)]


def test_find_having_limit(manager):
    rt = manager.create_siddhi_app_runtime(TABLE_APP)
    rt.start()
    _populate(rt)
    events = rt.query(
        "from StockTable select symbol, volume having volume >= 10 "
        "order by volume desc limit 2;"
    )
    assert [tuple(e.data) for e in events] == [("WSO2", 100), ("WSO2", 50)]


def test_on_demand_insert(manager):
    rt = manager.create_siddhi_app_runtime(TABLE_APP)
    rt.start()
    rt.query(
        "select 'GOOG' as symbol, 100.0 as price, 7 as volume "
        "insert into StockTable;"
    )
    events = rt.query("from StockTable select symbol, volume;")
    assert [tuple(e.data) for e in events] == [("GOOG", 7)]


def test_on_demand_delete(manager):
    rt = manager.create_siddhi_app_runtime(TABLE_APP)
    rt.start()
    _populate(rt)
    rt.query("select 'WSO2' as sym delete StockTable on StockTable.symbol == sym;")
    events = rt.query("from StockTable select symbol;")
    assert [tuple(e.data) for e in events] == [("IBM",)]


def test_on_demand_update(manager):
    rt = manager.create_siddhi_app_runtime(TABLE_APP)
    rt.start()
    _populate(rt)
    rt.query(
        "select 1000 as newVolume update StockTable "
        "set StockTable.volume = newVolume on StockTable.symbol == 'IBM';"
    )
    events = rt.query("from StockTable on symbol == 'IBM' select volume;")
    assert [tuple(e.data) for e in events] == [(1000,)]


def test_on_demand_update_or_insert(manager):
    rt = manager.create_siddhi_app_runtime(TABLE_APP)
    rt.start()
    rt.query(
        "select 'MSFT' as symbol, 10.0 as price, 5 as volume "
        "update or insert into StockTable "
        "set StockTable.volume = volume on StockTable.symbol == symbol;"
    )
    assert [tuple(e.data) for e in rt.query("from StockTable select symbol, volume;")] == [
        ("MSFT", 5)
    ]
    rt.query(
        "select 'MSFT' as symbol, 10.0 as price, 50 as volume "
        "update or insert into StockTable "
        "set StockTable.volume = volume on StockTable.symbol == symbol;"
    )
    assert [tuple(e.data) for e in rt.query("from StockTable select symbol, volume;")] == [
        ("MSFT", 50)
    ]


def test_on_demand_window_find(manager):
    app = (
        "define stream S (symbol string, price float); "
        "define window W (symbol string, price float) length(3) output all events; "
        "from S insert into W;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    h = rt.get_input_handler("S")
    for i in range(5):
        h.send([f"S{i}", float(i)])
    events = rt.query("from W select symbol;")
    assert sorted(e.data[0] for e in events) == ["S2", "S3", "S4"]


def test_on_demand_aggregation_find(manager):
    BASE = 1_496_289_720_000
    app = (
        "define stream S (symbol string, price double, ts long); "
        "define aggregation A from S "
        "select symbol, sum(price) as total group by symbol "
        "aggregate by ts every sec, min;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    h = rt.get_input_handler("S")
    h.send(["WSO2", 50.0, BASE])
    h.send(["WSO2", 70.0, BASE + 500])
    h.send(["IBM", 10.0, BASE + 1000])
    events = rt.query(
        f"from A on symbol == 'WSO2' within {BASE}L, {BASE + 60000}L per 'seconds' "
        "select AGG_TIMESTAMP, symbol, total;"
    )
    assert [tuple(e.data) for e in events] == [(BASE, "WSO2", 120.0)]
