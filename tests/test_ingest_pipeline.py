"""Ingest-side pipelining: double-buffered H2D staging differentials.

Every device engine now routes its host→device transfers through
``core/ingest_stage.py``: batch conversion, ``staged_put`` and the
jitted step dispatch happen at receive time, but the blocking count-gate
fetch (and the emit enqueue it gates) defers behind a bounded staging
window (``@app:execution('tpu', ingest.depth='N')``).  With depth 2 the
count fetch for batch N runs only after batch N+1's H2D transfer and
step dispatch are already queued — transfer and compute overlap.

These tests pin the exactness contract differentially: the same app and
series at synchronous ingest (depth 1, the default) vs a staged window
must produce identical callbacks on the device-single, dense, and
sharded paths — including under ``transient`` faults on the
``ingest.put`` site and across a simulated crash + journal replay — and
assert the IngestStats evidence that staging actually happened
(``staged_batches``, ``max_staging_depth``, overlap/stall counters,
barrier ``flush_syncs``).  ``emit.depth='auto'`` rides along: the
controller's effective depth must track rtt/cadence and never exceed
its bound, with output still bit-identical to host.
"""

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.dense_pattern import DensePatternRuntime
from siddhi_tpu.core.device_single import DeviceQueryRuntime
from siddhi_tpu.core.emit_queue import EmitDepthController
from siddhi_tpu.core.exceptions import (
    SiddhiAppCreationError,
    SimulatedCrashError,
)
from siddhi_tpu.util.persistence import InMemoryPersistenceStore

pytestmark = pytest.mark.faults

DEFINE = "define stream S (k long, v double); "
FILTER_APP = DEFINE + ("from S[v > 20.0] select k, v, v * 2.0 as dbl "
                       "insert into OutputStream;")
AGG_APP = DEFINE + ("@info(name='q') from S#window.length(4) "
                    "select k, sum(v) as s group by k "
                    "insert into OutputStream;")
PATTERN_APP = DEFINE + (
    "@info(name='q') from every e1=S[v > 50.0] -> e2=S[v > e1.v] "
    "within 10 sec select e1.v as a, e2.v as b insert into OutputStream;")

# engine -> (@app:execution tail WITHOUT ingest.depth, body)
ENGINES = {
    "device_single": ("", AGG_APP),
    "dense_nfa": (", instances='32'", PATTERN_APP),
    "sharded": (", partitions='16', devices='8'", AGG_APP),
}


def series(n, seed, n_keys=4, t0=1000, dt_max=400):
    rng = np.random.default_rng(seed)
    ts = t0 + np.cumsum(rng.integers(1, dt_max, size=n))
    keys = rng.integers(0, n_keys, size=n)
    vals = rng.integers(1, 100, size=n).astype(float)
    return [([int(k), float(v)], int(t)) for k, v, t in zip(keys, vals, ts)]


def run_app(app, sends, out="OutputStream", exec_opts=None,
            faults=None, want_runtime=False):
    """Playback run -> list of data tuples.  ``exec_opts`` is the option
    tail of @app:execution('tpu'...), e.g. ", ingest.depth='2'"; None
    runs the host engine.  ``faults`` is an @app:faults option string.
    want_runtime additionally returns (device_runtime, app_runtime)."""
    header = "@app:playback "
    if faults is not None:
        header += f"@app:faults({faults}) "
    if exec_opts is not None:
        header += f"@app:execution('tpu'{exec_opts}) "
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime(header + app)
        got = []
        rt.add_callback(out, lambda evs: got.extend(tuple(e.data)
                                                    for e in evs))
        rt.start()
        h = rt.get_input_handler("S")
        for row, ts in sends:
            h.send(row, timestamp=ts)
        qr = next(iter(rt.query_runtimes.values()))
        runtime = (getattr(qr, "device_runtime", None)
                   or getattr(qr, "pattern_processor", None))
        rt.shutdown()
        if want_runtime:
            return got, runtime, rt
        return got
    finally:
        m.shutdown()


def staged_differential(app, sends, out="OutputStream", extra="", depth=2,
                        ordered=True):
    """host == sync ingest == staged ingest; returns the staged runtime."""
    host = run_app(app, sends, out=out)
    sync, rt1, _ = run_app(app, sends, out=out, exec_opts=extra,
                           want_runtime=True)
    staged, rtS, _ = run_app(app, sends, out=out,
                             exec_opts=f"{extra}, ingest.depth='{depth}'",
                             want_runtime=True)
    assert rt1 is not None, "query did not lower to a device engine"
    assert rt1.ingest_stage.depth == 1
    assert rtS.ingest_stage.depth == depth
    assert len(rtS.ingest_stage) == 0, "shutdown left staged batches behind"
    if not ordered:
        host, sync, staged = sorted(host), sorted(sync), sorted(staged)
    assert sync == host, "synchronous-ingest device path diverged from host"
    assert staged == host, "staged ingest changed callback content/order"
    return rtS


class TestStagedIngestDifferential:
    def test_device_single_filter(self):
        rt = staged_differential(FILTER_APP, series(120, seed=21))
        assert isinstance(rt, DeviceQueryRuntime)
        st = rt.ingest_stats
        assert st.staged_batches > 0
        assert st.max_staging_depth == 2
        assert st.device_puts > 0
        # overlap evidence: every non-barrier finish happened with the
        # NEXT batch already dispatched — each one is either an overlap
        # (count scalar already resident) or a stall (host blocked)
        assert st.overlapped_batches + st.ingest_stalls > 0
        # shutdown drains through the stage: the last in-flight batch
        # finishes under a flush barrier
        assert st.flush_syncs > 0

    def test_device_single_grouped_window(self):
        rt = staged_differential(AGG_APP, series(150, seed=22, n_keys=5))
        assert isinstance(rt, DeviceQueryRuntime)
        assert rt.ingest_stats.staged_batches > 0

    def test_staging_composes_with_deep_emit(self):
        sends = series(160, seed=23)
        host = run_app(FILTER_APP, sends)
        got, rt, _ = run_app(
            FILTER_APP, sends,
            exec_opts=", ingest.depth='3', emit.depth='4'",
            want_runtime=True)
        assert got == host
        assert rt.ingest_stats.max_staging_depth == 3
        assert rt.emit_stats.deferred_batches > 0

    def test_dense_pattern_staged(self):
        rt = staged_differential(PATTERN_APP, series(120, seed=24),
                                 extra=", instances='32'")
        assert isinstance(rt, DensePatternRuntime)
        st = rt.ingest_stats
        assert st.staged_batches > 0
        assert st.device_puts > 0
        assert st.overlapped_batches + st.ingest_stalls > 0

    def test_sharded_staged(self):
        # windowless running aggregation: the one kind the planner
        # shards over the device mesh
        app = DEFINE + ("from S select k, sum(v) as s group by k "
                        "insert into OutputStream;")
        rt = staged_differential(app, series(200, seed=25, n_keys=8),
                                 extra=", partitions='16', devices='8'")
        assert isinstance(rt, DeviceQueryRuntime)
        assert rt.engine.n_shards == 8
        st = rt.ingest_stats
        assert st.staged_batches > 0
        # every dispatched batch went through the shared staged_put
        # (one coalesced pytree put per dispatch)
        assert st.device_puts >= st.staged_batches

    def test_timer_fire_barrier_staged(self):
        # timeBatch panes close on timer fires — the fire() path must
        # flush the ingest stage before the emit drain or pane contents
        # shift by up to depth-1 batches
        app = DEFINE + ("from S#window.timeBatch(1 sec) select k, "
                        "sum(v) as s group by k insert into OutputStream;")
        staged_differential(app, series(150, seed=26), depth=3,
                            ordered=False)


class TestIngestFlushBarriers:
    def test_snapshot_midstream_is_a_barrier(self):
        m = SiddhiManager()
        try:
            m.set_persistence_store(InMemoryPersistenceStore())
            rt = m.create_siddhi_app_runtime(
                "@app:playback @app:execution('tpu', ingest.depth='4') "
                + FILTER_APP)
            got = []
            rt.add_callback("OutputStream",
                            lambda evs: got.extend(tuple(e.data)
                                                   for e in evs))
            rt.start()
            h = rt.get_input_handler("S")
            for i in range(3):
                h.send([i, 50.0], timestamp=1000 + i)
            drt = next(iter(rt.query_runtimes.values())).device_runtime
            # window depth 4: all three batches still staged, no emits
            assert len(drt.ingest_stage) == 3
            assert got == []
            rt.persist()  # snapshot barrier: flush stage, drain emits
            assert len(drt.ingest_stage) == 0
            assert drt.ingest_stats.flush_syncs >= 3
            assert got == [(i, 50.0, 100.0) for i in range(3)]
            rt.shutdown()
        finally:
            m.shutdown()


class TestIngestFaultDifferential:
    @pytest.mark.parametrize("engine", sorted(ENGINES))
    def test_transient_ingest_put_recovered_staged(self, engine):
        extra, body = ENGINES[engine]
        sends = series(40, seed=31, n_keys=4)
        clean, _, _ = run_app(body, sends,
                              exec_opts=f"{extra}, ingest.depth='2'",
                              want_runtime=True)
        chaotic, _, rt = run_app(
            body, sends, exec_opts=f"{extra}, ingest.depth='2'",
            faults=("transfer.retry.scale='0.0001', "
                    "ingest.put='transient:count=2'"),
            want_runtime=True)
        assert chaotic == clean, (
            f"{engine}: retried ingest puts must not lose or dup rows")
        fi = rt.app_context.fault_injector
        assert fi.stats.faults_injected == 2
        assert fi.stats.transfer_retries == 2
        assert fi.stats.drains_recovered >= 1
        assert fi.stats.drains_failed == 0

    @pytest.mark.parametrize("engine", sorted(ENGINES))
    def test_crash_recovery_staged_bit_identical(self, engine):
        """Crash mid-stream with batches in the staging window: the
        journal replay on a fresh runtime must reproduce the exact
        uninterrupted sequence (staged ingest defers only EMISSION —
        journal + checkpoint semantics are untouched)."""
        extra, body = ENGINES[engine]
        exec_opts = f"{extra}, ingest.depth='2'"
        sends = series(30, seed=32, n_keys=3)
        ref = run_app(body, sends, exec_opts=exec_opts)
        assert len(ref) > 4, "series too tame; differential is vacuous"

        header = ("@app:name('ingestcrash') @app:playback "
                  "@app:faults(journal='256') "
                  f"@app:execution('tpu'{exec_opts}) ")
        m = SiddhiManager()
        try:
            m.set_persistence_store(InMemoryPersistenceStore())
            rt = m.create_siddhi_app_runtime(header + body)
            got = []
            rt.add_callback("OutputStream",
                            lambda evs: got.extend(tuple(e.data)
                                                   for e in evs))
            rt.start()
            h = rt.get_input_handler("S")
            for row, ts in sends[:10]:
                h.send(list(row), timestamp=ts)
            rt.persist()
            for row, ts in sends[10:20]:
                h.send(list(row), timestamp=ts)
            rt.app_context.fault_injector.configure("ingest", "crash",
                                                    count=1)
            with pytest.raises(SimulatedCrashError):
                h.send(list(sends[20][0]), timestamp=sends[20][1])
            rt.shutdown()  # the crashed runtime is gone

            rt2 = m.create_siddhi_app_runtime(header + body)
            rt2.add_callback("OutputStream",
                             lambda evs: got.extend(tuple(e.data)
                                                    for e in evs))
            rt2.start()
            assert rt2.restore_last_revision() is not None
            h2 = rt2.get_input_handler("S")
            # the crashed send WAS journaled (crash fires after the
            # record), so replay already delivered it — continue after
            for row, ts in sends[21:]:
                h2.send(list(row), timestamp=ts)
            rt2.shutdown()
            assert got == ref, (
                f"{engine}: crash+recover with staged ingest diverged "
                "from the uninterrupted run")
        finally:
            m.shutdown()


class TestAutoEmitDepth:
    def test_controller_converges_to_rtt_over_cadence(self):
        # deterministic: injected timestamps, constant cadence and rtt
        c = EmitDepthController()
        t = 0.0
        for _ in range(50):
            c.note_push(t)
            t += 0.001
            c.note_drain(0.0042)
        assert c.effective_depth == 5  # ceil(4.2ms rtt / 1ms gap)

    def test_controller_never_exceeds_bound(self):
        c = EmitDepthController()
        c.note_push(0.0)
        c.note_push(0.001)
        c.note_drain(60.0)  # pathological rtt: clamp, don't grow
        assert c.effective_depth == EmitDepthController.AUTO_DEPTH_MAX

    def test_controller_floors_at_sync(self):
        c = EmitDepthController()
        c.note_push(0.0)
        c.note_push(10.0)  # slow cadence, instant fetch -> depth 1
        c.note_drain(0.0001)
        assert c.effective_depth == 1

    def test_auto_depth_runtime_differential(self):
        sends = series(150, seed=41)
        host = run_app(FILTER_APP, sends)
        auto, rt, _ = run_app(FILTER_APP, sends,
                              exec_opts=", emit.depth='auto'",
                              want_runtime=True)
        assert auto == host, "auto emit depth changed callback content"
        assert rt.emit_queue.controller is not None
        assert 1 <= rt.emit_queue.depth <= EmitDepthController.AUTO_DEPTH_MAX
        assert rt.emit_stats.auto_depth >= 1  # controller engaged
        # the bounded-queue contract: auto can never grow the pending
        # window past its own ceiling
        assert (rt.emit_stats.max_pending_depth
                <= EmitDepthController.AUTO_DEPTH_MAX)

    def test_auto_depth_with_staged_ingest(self):
        sends = series(150, seed=42, n_keys=5)
        host = run_app(AGG_APP, sends)
        got, rt, _ = run_app(
            AGG_APP, sends,
            exec_opts=", ingest.depth='2', emit.depth='auto'",
            want_runtime=True)
        assert got == host
        assert rt.ingest_stats.staged_batches > 0
        assert rt.emit_queue.controller is not None
        assert (rt.emit_stats.max_pending_depth
                <= EmitDepthController.AUTO_DEPTH_MAX)


class TestAnnotationValidation:
    @pytest.mark.parametrize("opt", ["ingest.depth='0'",
                                     "ingest.depth='-2'",
                                     "ingest.depth='fast'",
                                     "agg.device.min.batch='0'",
                                     "agg.device.min.batch='many'",
                                     "emit.depth='turbo'"])
    def test_bad_values_rejected_at_build(self, opt):
        m = SiddhiManager()
        try:
            with pytest.raises(SiddhiAppCreationError,
                               match="must be a positive integer"):
                m.create_siddhi_app_runtime(
                    f"@app:execution('tpu', {opt}) " + FILTER_APP)
        finally:
            m.shutdown()

    def test_statistics_expose_ingest_counters(self):
        app = ("@app:name('ingestApp') @app:statistics('true') "
               "@app:playback @app:execution('tpu', ingest.depth='2') "
               + DEFINE +
               "@info(name='q') from S[v > 50.0] select k, v "
               "insert into OutputStream;")
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(app)
            rt.start()
            h = rt.get_input_handler("S")
            for i, v in enumerate([60.0, 70.0, 10.0, 80.0]):
                h.send([i, v], timestamp=1000 + i)
            stats = rt.statistics()
            pre = "io.siddhi.SiddhiApps.ingestApp.Siddhi.Queries.q."
            assert stats[pre + "stagedBatches"] == 4
            assert stats[pre + "devicePuts"] >= 1
            assert stats[pre + "maxStagingDepth"] == 2
            assert (stats[pre + "overlappedBatches"]
                    + stats[pre + "ingestStalls"]) >= 1
            rt.shutdown()
        finally:
            m.shutdown()
