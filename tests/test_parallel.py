"""Mesh sharding tests on the 8-virtual-device CPU mesh (conftest.py).

Validates the scale-out surface (SURVEY.md §2.3 mapping): partition-axis
sharding via shard_map, shard-local state with per-shard scratch rows,
host-side event routing with collision-round splitting, and the psum'd
global match count.
"""

import numpy as np
import pytest

APP = (
    "define stream Txn (key long, v double); "
    "@info(name='f') from every a=Txn[v > 100.0] -> b=Txn[v > a.v]<3:5> "
    "within 10 min "
    "select a.v as base, b[0].v as b0 insert into Alerts;"
)


@pytest.fixture(scope="module")
def sharded():
    from siddhi_tpu.ops.dense_nfa import compile_pattern
    from siddhi_tpu.parallel import ShardedPatternEngine, make_mesh

    mesh = make_mesh(8)
    eng = compile_pattern(APP, "f", n_partitions=8 * 64)
    return ShardedPatternEngine(eng, mesh)


class TestRouting:
    def test_route_to_shards_layout(self):
        from siddhi_tpu.parallel import route_to_shards

        part = np.asarray([0, 64, 65, 130, 3])
        cols = {"v": np.asarray([1.0, 2.0, 3.0, 4.0, 5.0], dtype=np.float32)}
        ts = np.asarray([10, 20, 30, 40, 50])
        lp, rc, rts, valid, pos = route_to_shards(4, 64, part, cols, ts)
        B = len(lp) // 4
        assert B >= 16  # pow-2 padded with a floor, bounding recompiles
        # shard 0 got partitions 0 and 3 (local ids 0, 3)
        assert sorted(lp[:B][valid[:B]].tolist()) == [0, 3]
        # shard 1 got 64, 65 -> local 0, 1
        assert sorted(lp[B:2 * B][valid[B:2 * B]].tolist()) == [0, 1]
        # shard 2 got 130 -> local 2
        assert lp[2 * B:3 * B][valid[2 * B:3 * B]].tolist() == [2]
        # values follow their events; pos maps inputs to slots
        assert rc["v"][2 * B:3 * B][valid[2 * B:3 * B]].tolist() == [4.0]
        assert valid.sum() == 5
        for i in range(5):
            assert rc["v"][pos[i]] == cols["v"][i]
        # padded lanes target the per-shard scratch row, never partition 0
        assert (lp[~valid] == 64).all()

    def test_out_of_range_partition_rejected(self):
        from siddhi_tpu.core.exceptions import SiddhiAppCreationError
        from siddhi_tpu.parallel import route_to_shards

        with pytest.raises(SiddhiAppCreationError):
            route_to_shards(2, 8, np.asarray([99]), {}, np.asarray([1]))


class TestShardedEngine:
    def _drive(self, sharded, part, values):
        state = sharded.init_state()
        result = None
        for i, v in enumerate(values):
            n = len(part)
            state, emit, out, total = sharded.process(
                state, np.asarray(part),
                {"v": np.full(n, v, dtype=np.float32),
                 "key": np.zeros(n, dtype=np.float32)},
                np.full(n, 1_000_000 + i * 100, dtype=np.int64),
            )
            result = (state, emit, out, total)
        return result

    def test_match_count_psummed_across_shards(self, sharded):
        state = sharded.init_state()
        part = np.asarray([i * 64 + 1 for i in range(8)])  # one key per shard
        totals = []
        for i, v in enumerate([150.0, 160.0, 170.0, 180.0]):
            state, emit, out, total = sharded.process(
                state, part,
                {"v": np.full(8, v, dtype=np.float32),
                 "key": np.zeros(8, dtype=np.float32)},
                np.full(8, 1_000_000 + i * 100, dtype=np.int64),
            )
            totals.append(total)
        # the 3rd b completes the <3:5> count on every shard at once
        assert totals == [0, 0, 0, 8]
        assert emit.tolist() == list(range(8))
        # per-event outputs mapped back to input order: [a.v, b[0].v]
        assert out[0].tolist() == [150.0, 160.0]

    def test_collision_rounds_same_partition(self, sharded):
        # the whole escalation for ONE key arrives in a single batch;
        # process() must split rounds so state transitions don't race
        state = sharded.init_state()
        part = np.asarray([5, 5, 5, 5])
        state, emit, out, total = sharded.process(
            state, part,
            {"v": np.asarray([150.0, 160.0, 170.0, 180.0], dtype=np.float32),
             "key": np.zeros(4, dtype=np.float32)},
            np.asarray([1_000_000, 1_000_100, 1_000_200, 1_000_300], dtype=np.int64),
        )
        assert total == 1
        assert emit.tolist() == [3]

    def test_epoch_millis_timestamps(self, sharded):
        # absolute epoch-ms int64 timestamps must survive the relative-
        # timestamp normalization (raw int32 truncation would corrupt)
        state = sharded.init_state()
        base = 1_753_000_000_000
        part = np.asarray([9])
        totals = []
        for i, v in enumerate([150.0, 160.0, 170.0, 180.0]):
            state, emit, out, total = sharded.process(
                state, part,
                {"v": np.asarray([v], dtype=np.float32),
                 "key": np.zeros(1, dtype=np.float32)},
                np.asarray([base + i * 100], dtype=np.int64),
            )
            totals.append(total)
        assert totals == [0, 0, 0, 1]

    def test_shard_isolation_and_reset(self, sharded):
        state, emit, out, total = self._drive(
            sharded, [3 * 64 + 7],
            [150.0, 160.0, 170.0, 180.0])
        assert total == 1
        active = np.asarray(state["active"])
        # scratch rows and every partition row are clear after emission
        assert not active.any()

    def test_state_sharding_placement(self, sharded):
        state = sharded.init_state()
        assert len(state["active"].sharding.device_set) == 8
        assert state["active"].shape[0] == 8 * 65  # 64 partitions + scratch
