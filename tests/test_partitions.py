"""Partition conformance tests.

Modeled on the reference partition test corpus
(modules/siddhi-core/src/test/java/io/siddhi/core/query/partition/
PartitionTestCase1/2): per-key isolated state, value + range partitioning,
inner streams, output to global streams.
"""

import pytest

from siddhi_tpu import SiddhiManager


@pytest.fixture
def manager():
    m = SiddhiManager()
    yield m
    m.shutdown()


def collect_stream(rt, stream):
    got = []
    rt.add_callback(stream, lambda events: got.extend(e.data for e in events))
    return got


def test_value_partition_isolates_aggregation_state(manager):
    app = (
        "define stream S (sym string, v int); "
        "partition with (sym of S) begin "
        "from S select sym, sum(v) as total insert into Out; "
        "end;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    got = collect_stream(rt, "Out")
    h = rt.get_input_handler("S")
    h.send(["a", 10])
    h.send(["b", 5])
    h.send(["a", 20])   # a's sum independent of b
    h.send(["b", 7])
    assert got == [["a", 10], ["b", 5], ["a", 30], ["b", 12]]


def test_partition_windows_are_per_key(manager):
    app = (
        "define stream S (sym string, v int); "
        "partition with (sym of S) begin "
        "from S#window.length(2) select sym, sum(v) as total insert into Out; "
        "end;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    got = collect_stream(rt, "Out")
    h = rt.get_input_handler("S")
    h.send(["a", 1])
    h.send(["a", 2])
    h.send(["b", 100])
    h.send(["a", 4])  # a's window [2,4] -> 6; b untouched
    assert got == [["a", 1], ["a", 3], ["b", 100], ["a", 6]]


def test_range_partition(manager):
    app = (
        "define stream S (v int); "
        "partition with (v < 10 as 'small' or v >= 10 as 'large' of S) begin "
        "from S select v, count() as n insert into Out; "
        "end;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    got = collect_stream(rt, "Out")
    h = rt.get_input_handler("S")
    h.send([5])
    h.send([50])
    h.send([7])
    assert got == [[5, 1], [50, 1], [7, 2]]


def test_inner_stream_is_key_local(manager):
    app = (
        "define stream S (sym string, v int); "
        "partition with (sym of S) begin "
        "from S select sym, v * 2 as d insert into #Doubled; "
        "from #Doubled select sym, sum(d) as total insert into Out; "
        "end;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    got = collect_stream(rt, "Out")
    h = rt.get_input_handler("S")
    h.send(["a", 1])
    h.send(["b", 10])
    h.send(["a", 2])
    assert got == [["a", 2], ["b", 20], ["a", 6]]


def test_partition_output_reaches_global_queries(manager):
    app = (
        "define stream S (sym string, v int); "
        "partition with (sym of S) begin "
        "from S select sym, sum(v) as total insert into Mid; "
        "end; "
        "from Mid[total > 10] select sym insert into Big;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    got = collect_stream(rt, "Big")
    h = rt.get_input_handler("S")
    h.send(["a", 6])
    h.send(["a", 6])   # total 12 -> Big
    h.send(["b", 5])
    assert got == [["a"]]


def test_partition_pattern_per_key(manager):
    """Patterns inside partitions keep per-key NFA state."""
    app = (
        "define stream S (sym string, v int); "
        "partition with (sym of S) begin "
        "from e1=S[v > 10] -> e2=S[v > e1.v] "
        "select e1.sym as sym, e1.v as first, e2.v as second "
        "insert into Out; "
        "end;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    got = collect_stream(rt, "Out")
    h = rt.get_input_handler("S")
    h.send(["a", 20])
    h.send(["b", 30])
    h.send(["b", 25])   # not > 30; arms nothing for b's e2
    h.send(["a", 21])   # a matches (20, 21)
    assert got == [["a", 20, 21]]


def test_partition_purge_removes_idle_instances(manager):
    app = (
        "@app:playback "
        "define stream S (sym string, v int); "
        "@purge(enable='true', interval='1 sec', idle.period='2 sec') "
        "partition with (sym of S) begin "
        "from S select sym, sum(v) as total insert into Out; "
        "end;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    got = collect_stream(rt, "Out")
    h = rt.get_input_handler("S")
    h.send(["a", 1], timestamp=1_000)
    h.send(["b", 1], timestamp=1_100)
    pr = list(rt.partitions.values())[0]
    assert set(pr.instances) == {"a", "b"}
    # advance event time far beyond idle.period; only 'b' stays fresh
    h.send(["b", 1], timestamp=10_000)
    h.send(["b", 1], timestamp=20_000)
    assert "a" not in pr.instances and "b" in pr.instances
    # 'a' returning starts fresh state (sum resets)
    h.send(["a", 5], timestamp=20_100)
    assert got[-1] == ["a", 5]
