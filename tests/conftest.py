"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on a virtual CPU mesh exactly as the driver's dryrun does.

This environment pre-imports jax at interpreter startup (sitecustomize
on PYTHONPATH) with JAX_PLATFORMS preset to a TPU plugin, so setting
environment variables here is too late — they are read at jax import
time.  Backends initialize lazily, however, so jax.config.update still
takes effect; anything less than 8 devices is a loud failure (not a
silent skip) — see _assert_virtual_mesh.
"""

import os

# Belt-and-braces for subprocesses that re-exec with this environ.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["JAX_NUM_CPU_DEVICES"] = "8"

import jax  # noqa: E402

# Pallas registers its TPU lowering rules at import time, which needs
# "tpu" to still be a KNOWN platform — import it before the factory
# scrub below forgets tpu.  This registers rules only; no backend
# initializes here, so the hang-defense the scrub provides is intact.
try:
    import jax.experimental.pallas  # noqa: E402,F401
except Exception:
    pass  # no pallas in this jax build: kernel tests fall back gracefully

# Plugin backends (the tunneled device) can initialize during backends()
# even under JAX_PLATFORMS=cpu via get_backend hooks; a downed remote
# endpoint makes that init hang forever.  Tests are CPU-only by
# contract, so drop every non-CPU backend factory before anything
# touches a backend (same defense as __graft_entry__.dryrun_multichip).
try:
    from jax._src import xla_bridge as _xb

    for _name in list(getattr(_xb, "_backend_factories", {})):
        if _name != "cpu":
            _xb._backend_factories.pop(_name, None)
except Exception as _e:  # pragma: no cover - jax-version drift
    import warnings

    # the scrub touches a private attr; if a jax upgrade renames it the
    # hang-defense silently vanishes — make that visible
    warnings.warn(
        f"CPU-only backend scrub ineffective ({_e}); a downed remote "
        "device plugin may hang backend init", RuntimeWarning)

from siddhi_tpu.parallel import ensure_virtual_devices  # noqa: E402

ensure_virtual_devices(8)
try:
    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass  # backends already initialized; the fixture below will complain

import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _assert_virtual_mesh():
    """Fail (don't skip) if the 8-device virtual CPU mesh never
    materialized — otherwise every sharding test silently skips and the
    scale-out module merges unexercised."""
    n = len(jax.devices())
    platform = jax.devices()[0].platform
    assert platform == "cpu" and n >= 8, (
        f"virtual CPU mesh failed to materialize: {n} {platform} devices"
    )
