"""Device-resident stream-graph fusion differential suite.

``@app:fuse`` (planner/fusion.py) lowers `insert into` chains whose
intermediate streams have exactly one device producer and one device
consumer into ONE jitted multi-stage program (ops/fused_graph.py +
core/fused_graph.py): intermediate event columns stay in HBM, no
EventBatch is built and no junction dispatch happens between stages.

The contract under test is bit-identical callbacks versus the same app
running per-query engines with junction hops — across chain shapes
(filter→filter, filter→window→filter, filter→window→dense-pattern),
under transient ingest/emit faults, crash + journal replay, and
persist/restore mid-chain — plus dispatch accounting (one jitted step
per batch cycle, zero intermediate dispatches, zero intermediate
EventBatches) and counted, readable fallback reasons for unfusable
chains.
"""

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.event import EventBatch
from siddhi_tpu.core.exceptions import (
    SiddhiAppCreationError,
    SimulatedCrashError,
)
from siddhi_tpu.util.persistence import InMemoryPersistenceStore


def _collector(res):
    return lambda events: res.extend(
        (e.timestamp, tuple(e.data)) for e in events)


def _sends(n, seed):
    rng = np.random.default_rng(seed)
    out = []
    ts = 1000
    for _ in range(n):
        out.append(([int(rng.integers(0, 5)),
                     float(np.float32(rng.uniform(0, 30))),
                     int(rng.integers(1, 100))], ts))
        ts += 3
    return out


TWO_STAGE = """
@app:name('f2{tag}') @app:playback @app:execution('tpu') {fuse}
define stream SIn (sym int, price float, vol int);

@info(name='q1') from SIn[price > 10.0]
select sym, price, vol insert into Mid;
@info(name='q2') from Mid[vol > 50]
select sym, price insert into Out;
"""

THREE_STAGE = """
@app:name('f3{tag}') @app:playback @app:execution('tpu') {fuse}{faults}
define stream SIn (sym int, price float, vol int);
define stream Mid (sym int, price float, vol int);
define stream Win (sym int, total double);

@info(name='q1') from SIn[price > 10.0]
select sym, price, vol insert into Mid;
@info(name='q2') from Mid#window.length(8)
select sym, sum(price) as total insert into Win;
@info(name='q3') from Win[total > 50.0]
select sym, total insert into Out;
"""

DENSE_TAIL = """
@app:name('fd{tag}') @app:playback @app:execution('tpu') {fuse}
define stream SIn (sym int, price float, vol int);
define stream Mid (sym int, price float, vol int);
define stream Win (sym int, total double);

@info(name='q1') from SIn[price > 5.0]
select sym, price, vol insert into Mid;
@info(name='q2') from Mid#window.length(4)
select sym, sum(price) as total insert into Win;
@info(name='q3') from every e1=Win[total > 30.0] -> e2=Win[total > e1.total]
select e1.sym as s1, e1.total as t1, e2.total as t2 insert into Out;
"""


def _run_app(app_text, fuse, sends, tag_extra="", faults="", mgr=None):
    own = mgr is None
    if own:
        mgr = SiddhiManager()
    try:
        rt = mgr.create_siddhi_app_runtime(app_text.format(
            tag=("F" if fuse else "J") + tag_extra,
            fuse="@app:fuse" if fuse else "", faults=faults))
        got = []
        rt.add_callback("Out", _collector(got))
        rt.start()
        h = rt.get_input_handler("SIn")
        for row, ts in sends:
            h.send(list(row), timestamp=ts)
        low = rt.lowering()
        junc = {k: j.dispatches for k, j in rt.junctions.items()}
        fi = rt.app_context.fault_injector
        fstats = fi.stats.as_dict() if fi else {}
        rt.shutdown()
        return got, low, junc, fstats
    finally:
        if own:
            mgr.shutdown()


class TestFusedDifferential:
    """Fused chains == junction hops, bit for bit, per chain shape."""

    def test_two_stage_filter_filter_undeclared_intermediate(self):
        # Mid is never declared: the planner synthesizes its schema from
        # the producer's output spec
        sends = _sends(60, 3)
        gf, lf, jf, _ = _run_app(TWO_STAGE, True, sends)
        gj, lj, _, _ = _run_app(TWO_STAGE, False, sends)
        assert lf == {"q1": "fused", "q2": "fused"}
        assert "fused" not in lj.values()
        assert len(gf) > 0 and gf == gj
        assert jf.get("Mid", 0) == 0

    def test_three_stage_filter_window_filter(self):
        sends = _sends(90, 0)
        gf, lf, jf, _ = _run_app(THREE_STAGE, True, sends)
        gj, lj, jjn, _ = _run_app(THREE_STAGE, False, sends)
        assert lf == {"q1": "fused", "q2": "fused", "q3": "fused"}
        assert len(gf) > 0 and gf == gj
        # intermediate junctions never dispatch on the fused path; the
        # junction path hops through both
        assert jf.get("Mid", 0) == 0 and jf.get("Win", 0) == 0
        assert jjn["Mid"] > 0 and jjn["Win"] > 0

    def test_three_stage_dense_pattern_tail(self):
        sends = _sends(75, 7)
        gf, lf, jf, _ = _run_app(DENSE_TAIL, True, sends)
        gj, lj, _, _ = _run_app(DENSE_TAIL, False, sends)
        assert lf == {"q1": "fused", "q2": "fused", "q3": "fused"}
        assert lj["q3"] == "dense"
        assert len(gf) > 0 and gf == gj
        assert jf.get("Mid", 0) == 0 and jf.get("Win", 0) == 0

    def test_large_batches_chunked_bit_identical(self):
        # many-row junction batches exercise the chunked ingest path
        rng = np.random.default_rng(21)
        sends = []
        for b in range(6):
            rows = [[int(rng.integers(0, 5)),
                     float(np.float32(rng.uniform(0, 30))),
                     int(rng.integers(1, 100))] for _ in range(64)]
            sends.append((rows, 1000 + 50 * b))

        def run(fuse):
            mgr = SiddhiManager()
            try:
                rt = mgr.create_siddhi_app_runtime(THREE_STAGE.format(
                    tag="BF" if fuse else "BJ",
                    fuse="@app:fuse" if fuse else "", faults=""))
                got = []
                rt.add_callback("Out", _collector(got))
                rt.start()
                h = rt.get_input_handler("SIn")
                from siddhi_tpu.core.event import Event
                for rows, ts in sends:
                    h.send([Event(ts + i, list(r))
                            for i, r in enumerate(rows)])
                rt.shutdown()
                return got
            finally:
                mgr.shutdown()

        gf, gj = run(True), run(False)
        assert len(gf) > 0 and gf == gj


class TestFusedDispatchAccounting:
    """One jitted program per batch cycle; intermediates stay in HBM."""

    def test_one_jit_per_cycle_and_hop_counters(self):
        n = 40
        sends = _sends(n, 5)
        mgr = SiddhiManager()
        try:
            rt = mgr.create_siddhi_app_runtime(THREE_STAGE.format(
                tag="A", fuse="@app:fuse", faults=""))
            rt.add_callback("Out", lambda e: None)
            rt.start()
            h = rt.get_input_handler("SIn")
            for row, ts in sends:
                h.send(list(row), timestamp=ts)
            dr = rt.query_runtimes["q3"].device_runtime
            st = dr.stats()
            # the WHOLE 3-stage chain advances with ONE fused dispatch
            # per batch cycle — not one per stage
            assert st["engine"] == "fused" and st["stages"] == 3
            assert st["step_invocations"] == n
            assert st["fused_hops"] == 2 * n  # (stages - 1) per dispatch
            assert rt.junctions["SIn"].dispatches == n
            assert rt.junctions["Mid"].dispatches == 0
            assert rt.junctions["Win"].dispatches == 0
            rt.shutdown()
        finally:
            mgr.shutdown()

    def test_no_intermediate_eventbatches(self, monkeypatch):
        """The fused path must never materialize an EventBatch on an
        intermediate stream — its columns live in HBM between stages."""
        built = []
        orig = EventBatch.__init__

        def counting(self, stream_id, *a, **k):
            built.append(stream_id)
            orig(self, stream_id, *a, **k)

        sends = _sends(50, 9)
        monkeypatch.setattr(EventBatch, "__init__", counting)
        _run_app(THREE_STAGE, True, sends, tag_extra="NB")
        fused_built = list(built)
        built.clear()
        _run_app(THREE_STAGE, False, sends, tag_extra="NB")
        junction_built = list(built)
        assert "Mid" not in fused_built and "Win" not in fused_built
        assert "Mid" in junction_built and "Win" in junction_built
        assert len(fused_built) < len(junction_built)


class TestFusedFaults:
    pytestmark = pytest.mark.faults

    def test_transient_ingest_emit_faults_bit_identical(self):
        sends = _sends(80, 13)
        ref, _, _, _ = _run_app(THREE_STAGE, True, sends, tag_extra="T0")
        got, low, junc, st = _run_app(
            THREE_STAGE, True, sends, tag_extra="T1",
            faults="@app:faults(transfer.retry.scale='0.001', "
                   "ingest.put='transient:count=3', "
                   "emit.drain='transient:count=2') ")
        assert low == {"q1": "fused", "q2": "fused", "q3": "fused"}
        assert st["faults_injected"] >= 5
        assert st["transfer_retries"] >= 3 and st["drains_recovered"] >= 2
        assert junc.get("Mid", 0) == 0 and junc.get("Win", 0) == 0
        assert got == ref

    def test_crash_and_journal_replay(self):
        """Checkpoint, crash mid-run, restore + journal replay on a
        fresh runtime — bit-identical to a run that never crashed."""
        sends = _sends(30, 17)
        ref, _, _, _ = _run_app(THREE_STAGE, True, sends, tag_extra="C0")

        mgr = SiddhiManager()
        mgr.set_persistence_store(InMemoryPersistenceStore())
        try:
            faults = "@app:faults(journal='256') "
            app = THREE_STAGE.format(tag="FC1", fuse="@app:fuse",
                                     faults=faults)
            rt = mgr.create_siddhi_app_runtime(app)
            got = []
            rt.add_callback("Out", _collector(got))
            rt.start()
            h = rt.get_input_handler("SIn")
            for j, (row, ts) in enumerate(sends):
                if j == 10:
                    rt.persist()
                if j == 20:
                    rt.app_context.fault_injector.configure(
                        "ingest", "crash", count=1)
                    with pytest.raises(SimulatedCrashError):
                        h.send(list(row), timestamp=ts)
                    rt.shutdown()
                    rt = mgr.create_siddhi_app_runtime(app)
                    rt.add_callback("Out", _collector(got))
                    rt.start()
                    # the crashed send WAS journaled: replay covers it
                    assert rt.restore_last_revision() is not None
                    h = rt.get_input_handler("SIn")
                    continue
                h.send(list(row), timestamp=ts)
            assert rt.lowering() == {
                "q1": "fused", "q2": "fused", "q3": "fused"}
            rt.shutdown()
        finally:
            mgr.shutdown()
        assert got == ref


class TestFusedPersistence:
    def test_persist_restore_forgets_post_persist_event(self):
        """restore() rewinds the WHOLE chain's device state mid-window
        (q2's accumulator is partially filled at the checkpoint)."""

        def run(fuse):
            mgr = SiddhiManager()
            mgr.set_persistence_store(InMemoryPersistenceStore())
            try:
                rt = mgr.create_siddhi_app_runtime(THREE_STAGE.format(
                    tag="PF" if fuse else "PJ",
                    fuse="@app:fuse" if fuse else "", faults=""))
                got = []
                rt.add_callback("Out", _collector(got))
                rt.start()
                h = rt.get_input_handler("SIn")
                sends = _sends(40, 19)
                for row, ts in sends[:20]:
                    h.send(list(row), timestamp=ts)
                rt.persist()
                # stray event lands in q2's window, then is rolled back
                h.send([0, 29.0, 99], timestamp=5000)
                rt.restore_last_revision()
                for row, ts in sends[20:]:
                    h.send(list(row), timestamp=ts)
                rt.shutdown()
                return got
            finally:
                mgr.shutdown()

        gf, gj = run(True), run(False)
        assert len(gf) > 0 and gf == gj


class TestFusedFallback:
    """Unfusable chains drop to junction dispatch with a counted,
    readable reason — never silently."""

    def _stats(self, app_text, out_streams=("Out",), sends=None):
        mgr = SiddhiManager()
        try:
            rt = mgr.create_siddhi_app_runtime(app_text)
            for s in out_streams:
                rt.add_callback(s, lambda e: None)
            rt.start()
            if sends:
                h = rt.get_input_handler("SIn")
                for row, ts in sends:
                    h.send(list(row), timestamp=ts)
            low = rt.lowering()
            st = rt.statistics()
            rt.shutdown()
            return low, st
        finally:
            mgr.shutdown()

    def test_async_intermediate_falls_back(self):
        APP = """
@app:name('fba') @app:execution('tpu') @app:fuse @app:statistics('basic')
define stream SIn (sym int, price float);
@async(buffer.size='16')
define stream Mid (sym int, price float);
@info(name='q1') from SIn[price > 1.0] select sym, price insert into Mid;
@info(name='q2') from Mid select sym, price insert into Out;
"""
        low, st = self._stats(APP)
        assert "fused" not in low.values()
        pre = "io.siddhi.SiddhiApps.fba.Siddhi.Queries."
        assert st[pre + "q1.fusedFallbacks"] == 1
        assert "@async" in st[pre + "q1.fusedFallbackReason"]

    def test_table_hop_falls_back(self):
        APP = """
@app:name('fbt') @app:execution('tpu') @app:fuse @app:statistics('basic')
define stream SIn (sym int, price float);
define table T (sym int, price float);
@info(name='q1') from SIn[price > 1.0] select sym, price insert into T;
"""
        low, st = self._stats(APP, out_streams=())
        assert "fused" not in low.values()
        pre = "io.siddhi.SiddhiApps.fbt.Siddhi.Queries."
        assert st[pre + "q1.fusedFallbacks"] == 1
        assert "table" in st[pre + "q1.fusedFallbackReason"]

    def test_multi_consumer_intermediate_falls_back(self):
        APP = """
@app:name('fbm') @app:execution('tpu') @app:fuse @app:statistics('basic')
define stream SIn (sym int, price float);
define stream Mid (sym int, price float);
@info(name='q1') from SIn[price > 1.0] select sym, price insert into Mid;
@info(name='q2') from Mid select sym, price insert into Out;
@info(name='q3') from Mid[price > 2.0] select sym, price insert into Out2;
"""
        low, st = self._stats(APP, out_streams=("Out", "Out2"))
        assert "fused" not in low.values()
        pre = "io.siddhi.SiddhiApps.fbm.Siddhi.Queries."
        assert st[pre + "q1.fusedFallbacks"] == 1
        assert "one consumer" in st[pre + "q1.fusedFallbackReason"]

    def test_host_only_interior_stage_falls_back(self):
        # a STRING intermediate attribute has no device-resident lane
        APP = """
@app:name('fbs') @app:execution('tpu') @app:fuse @app:statistics('basic')
define stream SIn (sym string, price float);
@info(name='q1') from SIn[price > 1.0] select sym, price insert into Mid;
@info(name='q2') from Mid[price > 2.0] select sym, price insert into Out;
"""
        low, st = self._stats(APP)
        assert "fused" not in low.values()
        pre = "io.siddhi.SiddhiApps.fbs.Siddhi.Queries."
        assert st[pre + "q1.fusedFallbacks"] >= 1
        assert "lane" in st[pre + "q1.fusedFallbackReason"]

    def test_unfusable_tail_truncates_chain_prefix_still_fuses(self):
        """A group-by tail cannot fuse, but the q1→q2 prefix must still
        lower — per-chain truncation, not all-or-nothing."""
        APP = """
@app:name('fbg') @app:playback @app:execution('tpu') @app:fuse
@app:statistics('basic')
define stream SIn (sym int, price float, vol int);
define stream Mid (sym int, price float, vol int);
define stream Win (sym int, total double);
@info(name='q1') from SIn[price > 5.0]
select sym, price, vol insert into Mid;
@info(name='q2') from Mid#window.length(4)
select sym, sum(price) as total insert into Win;
@info(name='q3') from Win select sym, sum(total) as s
group by sym insert into Out;
"""
        low, st = self._stats(APP, sends=_sends(30, 23))
        assert low["q1"] == "fused" and low["q2"] == "fused"
        assert low["q3"] != "fused"
        pre = "io.siddhi.SiddhiApps.fbg.Siddhi.Queries."
        assert st[pre + "q3.fusedFallbacks"] >= 1
        assert "group-by" in st[pre + "q3.fusedFallbackReason"]

    def test_truncated_prefix_bit_identical(self):
        APP = """
@app:name('ftr{tag}') @app:playback @app:execution('tpu') {fuse}{faults}
define stream SIn (sym int, price float, vol int);
define stream Mid (sym int, price float, vol int);
define stream Win (sym int, total double);
@info(name='q1') from SIn[price > 5.0]
select sym, price, vol insert into Mid;
@info(name='q2') from Mid#window.length(4)
select sym, sum(price) as total insert into Win;
@info(name='q3') from Win select sym, sum(total) as s
group by sym insert into Out;
"""
        sends = _sends(45, 29)
        gf, lf, _, _ = _run_app(APP, True, sends)
        gj, _, _, _ = _run_app(APP, False, sends)
        assert lf["q1"] == "fused" and lf["q2"] == "fused"
        assert len(gf) > 0 and gf == gj

    def test_fuse_requires_tpu_mode(self):
        with pytest.raises(SiddhiAppCreationError, match="tpu"):
            SiddhiManager().create_siddhi_app_runtime(
                "@app:fuse define stream S (v double); "
                "@info(name='q') from S select v insert into Out;")
