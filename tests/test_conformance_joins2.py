"""Join conformance, part 2: join-type x window matrix, unidirectional
joins, self-joins, table joins with computed conditions and aggregation
joins — the behavioral families of the reference's JoinTestCase.java /
OuterJoinTestCase.java (modules/siddhi-core/src/test/java/io/siddhi/
core/query/join/) and JoinTableTestCase.java.  Window-buffered joins
probe the OPPOSITE side's current window contents on each arrival.
"""

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager

DEFS = (
    "define stream L (sym string, lv long); "
    "define stream R (sym string, rv long); "
)


def run(app, sends, out="OutputStream"):
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime("@app:playback " + app)
        got = []
        rt.add_callback(out, lambda evs: got.extend(list(e.data) for e in evs))
        rt.start()
        for stream, row, ts in sends:
            rt.get_input_handler(stream).send(row, timestamp=ts)
        rt.shutdown()
        return got
    finally:
        m.shutdown()


def seq(rows, t0=1000, dt=100):
    return [(s, r, t0 + i * dt) for i, (s, r) in enumerate(rows)]


class TestInnerJoinMatrix:
    def test_length_window_join_probes_opposite(self):
        app = (DEFS +
               "@info(name='q') from L#window.length(2) join R#window.length(2) "
               "on L.sym == R.sym "
               "select L.sym as sym, L.lv as lv, R.rv as rv "
               "insert into OutputStream;")
        got = run(app, seq([
            ("L", ["a", 1]),          # R empty: nothing
            ("R", ["a", 10]),         # joins L(a,1)
            ("L", ["a", 2]),          # joins R(a,10)
            ("L", ["b", 3]),          # no R(b)
            ("R", ["b", 20]),         # joins L(b,3) — L(a,1) evicted
        ]))
        assert got == [["a", 1, 10], ["a", 2, 10], ["b", 3, 20]]

    def test_eviction_shrinks_join_candidates(self):
        app = (DEFS +
               "@info(name='q') from L#window.length(1) join R#window.length(2) "
               "on L.sym == R.sym "
               "select L.lv as lv, R.rv as rv insert into OutputStream;")
        got = run(app, seq([
            ("L", ["a", 1]),
            ("L", ["a", 2]),          # evicts L(a,1)
            ("R", ["a", 10]),         # joins ONLY L(a,2)
        ]))
        assert got == [[2, 10]]

    def test_self_join_with_aliases(self):
        app = (DEFS +
               "@info(name='q') from L#window.length(3) as x "
               "join L#window.length(3) as y "
               "on x.lv < y.lv "
               "select x.lv as a, y.lv as b insert into OutputStream;")
        got = run(app, seq([
            ("L", ["a", 1]),
            ("L", ["a", 2]),
        ]))
        # second event: x(2) joins y(1)? no (2<1 false); x(1) joins y(2)
        # both directions fire on each arrival
        assert sorted(map(tuple, got)) == [(1, 2)]

    def test_unidirectional_left_only_triggers(self):
        app = (DEFS +
               "@info(name='q') from L#window.length(2) unidirectional "
               "join R#window.length(2) on L.sym == R.sym "
               "select L.lv as lv, R.rv as rv insert into OutputStream;")
        got = run(app, seq([
            ("L", ["a", 1]),
            ("R", ["a", 10]),         # right arrival must NOT emit
            ("L", ["a", 2]),          # left arrival joins R(a,10)
        ]))
        assert got == [[2, 10]]

    def test_cross_join_without_condition(self):
        app = (DEFS +
               "@info(name='q') from L#window.length(2) join R#window.length(2) "
               "select L.lv as lv, R.rv as rv insert into OutputStream;")
        got = run(app, seq([
            ("L", ["a", 1]),
            ("R", ["b", 10]),
            ("R", ["c", 20]),
        ]))
        assert got == [[1, 10], [1, 20]]


class TestOuterJoinMatrix:
    def test_left_outer_emits_nulls_for_missing_right(self):
        app = (DEFS +
               "@info(name='q') from L#window.length(2) left outer join "
               "R#window.length(2) on L.sym == R.sym "
               "select L.lv as lv, R.rv as rv insert into OutputStream;")
        got = run(app, seq([
            ("L", ["a", 1]),          # no right: (1, null)
            ("R", ["a", 10]),         # right arrival joins L(a,1)
            ("L", ["b", 2]),          # no right b: (2, null)
        ]))
        assert got == [[1, None], [1, 10], [2, None]]

    def test_right_outer_emits_nulls_for_missing_left(self):
        app = (DEFS +
               "@info(name='q') from L#window.length(2) right outer join "
               "R#window.length(2) on L.sym == R.sym "
               "select L.lv as lv, R.rv as rv insert into OutputStream;")
        got = run(app, seq([
            ("R", ["a", 10]),         # no left: (null, 10)
            ("L", ["a", 1]),          # joins
        ]))
        assert got == [[None, 10], [1, 10]]

    def test_full_outer_both_directions(self):
        app = (DEFS +
               "@info(name='q') from L#window.length(2) full outer join "
               "R#window.length(2) on L.sym == R.sym "
               "select L.lv as lv, R.rv as rv insert into OutputStream;")
        got = run(app, seq([
            ("L", ["a", 1]),
            ("R", ["b", 10]),
            ("R", ["a", 20]),
        ]))
        assert got == [[1, None], [None, 10], [1, 20]]


class TestTableJoins2:
    def test_table_join_with_arithmetic_condition(self):
        app = (
            "define stream S (sym string, qty long); "
            "define stream Boot (sym string, price long); "
            "define table P (sym string, price long); "
            "from Boot insert into P; "
            "@info(name='q') from S join P "
            "on S.sym == P.sym and S.qty * P.price > 100 "
            "select S.sym as sym, S.qty * P.price as total "
            "insert into OutputStream;")
        got = run(app, [
            ("Boot", ["a", 10], 500),
            ("Boot", ["b", 3], 600),
            ("S", ["a", 20], 1000),   # 200 > 100: out
            ("S", ["b", 20], 1100),   # 60: no
            ("S", ["b", 50], 1200),   # 150: out
        ])
        assert got == [["a", 200], ["b", 150]]

    def test_table_join_aggregating_select(self):
        # arriving events PRE-probe the table before entering the batch
        # window (reference: preJoinProcessor sits left of the window),
        # so the running sum emits per arrival, not per flush
        app = (
            "define stream S (sym string, qty long); "
            "define stream Boot (sym string, price long); "
            "define table P (sym string, price long); "
            "from Boot insert into P; "
            "@info(name='q') from S#window.lengthBatch(2) join P "
            "on S.sym == P.sym "
            "select S.sym as sym, sum(S.qty) as total group by S.sym "
            "insert into OutputStream;")
        got = run(app, [
            ("Boot", ["a", 10], 500),
            ("S", ["a", 1], 1000),
            ("S", ["a", 2], 1100),
        ])
        assert got == [["a", 1], ["a", 3]]


class TestJoinWithin:
    def test_aggregation_join_per_within(self):
        # join against an incremental aggregation with within/per
        app = (
            "define stream S (sym string, v double); "
            "define stream Q (sym string); "
            "define aggregation Agg from S select sym, sum(v) as total "
            "group by sym aggregate every sec...min; "
            "@info(name='q') from Q join Agg "
            "on Q.sym == Agg.sym "
            "within '1970-01-01 00:00:00' per 'seconds' "
            "select Agg.sym as sym, Agg.total as total "
            "insert into OutputStream;")
        got = run(app, [
            ("S", ["a", 5.0], 1000),
            ("S", ["a", 7.0], 1400),
            ("Q", ["a"], 5000),
        ])
        assert got == [["a", 12.0]]


class TestJoinNullChecks:
    def test_is_null_over_outer_join_nulls(self):
        # IsNullTestCase family: LONG columns carry real nulls after a
        # left outer join and `is null` must see them downstream
        app = (DEFS +
               "@info(name='q') from L#window.length(2) left outer join "
               "R#window.length(2) on L.sym == R.sym "
               "select L.lv as lv, R.rv as rv insert into Mid; "
               "@info(name='q2') from Mid[rv is null] select lv "
               "insert into O2; "
               "@info(name='q3') from Mid[not (rv is null)] select lv, rv "
               "insert into O3;")
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime("@app:playback " + app)
            nulls, joined = [], []
            rt.add_callback("O2", lambda evs: nulls.extend(
                list(e.data) for e in evs))
            rt.add_callback("O3", lambda evs: joined.extend(
                list(e.data) for e in evs))
            rt.start()
            rt.get_input_handler("L").send(["a", 1], timestamp=1000)
            rt.get_input_handler("R").send(["a", 10], timestamp=1100)
            rt.get_input_handler("L").send(["b", 2], timestamp=1200)
            rt.shutdown()
            assert nulls == [[1], [2]]
            assert joined == [[1, 10]]
        finally:
            m.shutdown()

    def test_aggregates_skip_null_inputs(self):
        # reference aggregators IGNORE null data: sum(rv) holds its
        # value over null rows instead of crashing or resetting
        app = (DEFS +
               "@info(name='q') from L#window.length(2) left outer join "
               "R#window.length(2) on L.sym == R.sym "
               "select L.lv as lv, R.rv as rv insert into Mid; "
               "@info(name='q2') from Mid select sum(rv) as s, "
               "count() as c insert into O2;")
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime("@app:playback " + app)
            got = []
            rt.add_callback("O2", lambda evs: got.extend(
                list(e.data) for e in evs))
            rt.start()
            rt.get_input_handler("L").send(["a", 1], timestamp=1000)
            rt.get_input_handler("R").send(["a", 10], timestamp=1100)
            rt.get_input_handler("L").send(["b", 2], timestamp=1200)
            rt.shutdown()
            assert got == [[None, 1], [10, 2], [10, 3]]
        finally:
            m.shutdown()

    def test_order_by_with_nulls_sorts_last(self):
        # reference OrderByEventComparator: nulls lose to any non-null
        # in BOTH directions
        app = (DEFS +
               "@info(name='q') from L#window.length(3) left outer join "
               "R#window.length(3) on L.sym == R.sym "
               "select L.lv as lv, R.rv as rv insert into Mid; "
               "@info(name='q2') from Mid#window.lengthBatch(3) "
               "select lv, rv order by rv insert into O2; "
               "@info(name='q3') from Mid#window.lengthBatch(3) "
               "select lv, rv order by rv desc insert into O3;")
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime("@app:playback " + app)
            asc, desc = [], []
            rt.add_callback("O2", lambda evs: asc.extend(
                list(e.data) for e in evs))
            rt.add_callback("O3", lambda evs: desc.extend(
                list(e.data) for e in evs))
            rt.start()
            rt.get_input_handler("L").send(["a", 1], timestamp=1000)
            rt.get_input_handler("R").send(["a", 10], timestamp=1100)
            rt.get_input_handler("L").send(["b", 2], timestamp=1200)
            rt.shutdown()
            # rows: (1, null), (1, 10), (2, null)
            assert asc == [[1, 10], [1, None], [2, None]]
            assert desc == [[1, 10], [1, None], [2, None]]
        finally:
            m.shutdown()

    def test_convert_and_cast_null_safe(self):
        app = (DEFS +
               "@info(name='q') from L#window.length(3) left outer join "
               "R#window.length(3) on L.sym == R.sym "
               "select L.lv as lv, R.rv as rv insert into Mid; "
               "@info(name='q2') from Mid select cast(rv, 'string') as c, "
               "convert(rv, 'double') as d insert into O2;")
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime("@app:playback " + app)
            got = []
            rt.add_callback("O2", lambda evs: got.extend(
                list(e.data) for e in evs))
            rt.start()
            rt.get_input_handler("L").send(["a", 1], timestamp=1000)
            rt.get_input_handler("R").send(["a", 10], timestamp=1100)
            rt.shutdown()
            assert got == [[None, None], ["10", 10.0]]
        finally:
            m.shutdown()
