"""Incremental-aggregation conformance: the sec->year cascade.

Ported behavior families from the reference's aggregation suites
(modules/siddhi-core/src/test/java/io/siddhi/core/aggregation/
AggregationTestCase.java): multi-duration rollups, out-of-order events,
on-demand `within ... per ...` stitching, and joins against
aggregations.
"""

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager

BASE_TS = 1_600_002_000_000  # hour-aligned (divisible by 3_600_000) so buckets nest

DEFINE = (
    "define stream Trades (symbol string, price double, volume long, "
    "ts long); "
)
AGG = (
    "define aggregation TradeAgg from Trades "
    "select symbol, sum(price) as total, avg(price) as avgPrice, "
    "count() as n "
    "group by symbol aggregate by ts every sec ... hour;"
)


def setup(extra=""):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("@app:playback " + DEFINE + AGG + extra)
    rt.start()
    return m, rt


def send_trades(rt, rows):
    h = rt.get_input_handler("Trades")
    for symbol, price, volume, off in rows:
        ts = BASE_TS + off
        h.send([symbol, price, volume, ts], timestamp=ts)


class TestOnDemandStitching:
    def test_within_per_seconds(self):
        m, rt = setup()
        try:
            send_trades(rt, [
                ("IBM", 10.0, 1, 0),
                ("IBM", 20.0, 1, 500),     # same second
                ("IBM", 30.0, 1, 1500),    # next second
            ])
            rows = rt.query(
                "from TradeAgg within "
                f"{BASE_TS} , {BASE_TS + 10_000} per 'seconds' "
                "select symbol, total, n")
            data = sorted(e.data for e in rows)
            assert data == [["IBM", 30.0, 2], ["IBM", 30.0, 1]] or data == [
                ["IBM", 30.0, 1], ["IBM", 30.0, 2]]
        finally:
            rt.shutdown()
            m.shutdown()

    def test_per_minutes_rolls_up(self):
        m, rt = setup()
        try:
            send_trades(rt, [
                ("IBM", 10.0, 1, 0),
                ("IBM", 20.0, 1, 30_000),    # same minute
                ("IBM", 40.0, 1, 90_000),    # next minute
            ])
            rows = rt.query(
                "from TradeAgg within "
                f"{BASE_TS}, {BASE_TS + 600_000} per 'minutes' "
                "select symbol, total, n")
            got = sorted(e.data for e in rows)
            assert got == [["IBM", 30.0, 2], ["IBM", 40.0, 1]]
        finally:
            rt.shutdown()
            m.shutdown()

    def test_group_isolation_across_symbols(self):
        m, rt = setup()
        try:
            send_trades(rt, [
                ("IBM", 10.0, 1, 0),
                ("WSO2", 5.0, 1, 100),
                ("IBM", 20.0, 1, 200),
            ])
            rows = rt.query(
                "from TradeAgg within "
                f"{BASE_TS}, {BASE_TS + 10_000} per 'seconds' "
                "select symbol, total")
            got = sorted(e.data for e in rows)
            assert got == [["IBM", 30.0], ["WSO2", 5.0]]
        finally:
            rt.shutdown()
            m.shutdown()

    def test_avg_stitched(self):
        m, rt = setup()
        try:
            send_trades(rt, [
                ("IBM", 10.0, 1, 0),
                ("IBM", 30.0, 1, 100),
            ])
            rows = rt.query(
                "from TradeAgg within "
                f"{BASE_TS}, {BASE_TS + 10_000} per 'seconds' "
                "select symbol, avgPrice")
            assert [e.data for e in rows] == [["IBM", 20.0]]
        finally:
            rt.shutdown()
            m.shutdown()


class TestOutOfOrder:
    def test_late_event_merges_into_closed_bucket(self):
        m, rt = setup()
        try:
            send_trades(rt, [
                ("IBM", 10.0, 1, 0),
                ("IBM", 20.0, 1, 2_000),   # closes the first second
            ])
            # late event for the FIRST second arrives after it closed
            h = rt.get_input_handler("Trades")
            h.send(["IBM", 5.0, 1, BASE_TS + 500], timestamp=BASE_TS + 2_500)
            rows = rt.query(
                "from TradeAgg within "
                f"{BASE_TS}, {BASE_TS + 10_000} per 'seconds' "
                "select symbol, total order by total")
            totals = sorted(e.data[1] for e in rows)
            assert totals == [15.0, 20.0]
        finally:
            rt.shutdown()
            m.shutdown()


class TestAggregationJoin:
    def test_stream_joins_aggregation_with_per(self):
        extra = (
            "define stream Q (symbol string); "
            "@info(name='j') from Q join TradeAgg "
            "on Q.symbol == TradeAgg.symbol "
            f"within {BASE_TS}, {BASE_TS + 600_000} per 'seconds' "
            "select TradeAgg.symbol as symbol, TradeAgg.total as total "
            "insert into OutputStream;")
        m, rt = setup(extra)
        try:
            got = []
            rt.add_callback("OutputStream",
                            lambda evs: got.extend(e.data for e in evs))
            send_trades(rt, [
                ("IBM", 10.0, 1, 0),
                ("IBM", 20.0, 1, 400),
            ])
            rt.get_input_handler("Q").send(["IBM"],
                                           timestamp=BASE_TS + 5_000)
            assert got == [["IBM", 30.0]]
        finally:
            rt.shutdown()
            m.shutdown()


class TestPurgeAnnotation:
    def test_purge_drops_old_buckets(self):
        agg = AGG.replace(
            "define aggregation TradeAgg",
            "@purge(enable='true', interval='1 sec', "
            "@retentionPeriod(sec='2 min', min='all')) "
            "define aggregation TradeAgg")
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(
                "@app:playback " + DEFINE + agg)
            rt.start()
            send_trades(rt, [
                ("IBM", 10.0, 1, 0),
                ("IBM", 20.0, 1, 5_000),
                ("IBM", 30.0, 1, 6_000),
            ])
            rows = rt.query(
                "from TradeAgg within "
                f"{BASE_TS}, {BASE_TS + 60_000} per 'minutes' "
                "select symbol, total")
            # minute rollup keeps everything even after seconds purge
            assert [e.data for e in rows] == [["IBM", 60.0]]
        finally:
            rt.shutdown()
            m.shutdown()


class TestAggregatorBreadthAcrossDurations:
    """min/max across rollups + explicit within-range strings
    (reference AggregationTestCase min/max/start-end variants)."""

    AGG_MM = (
        "define aggregation MM from Trades "
        "select symbol, min(price) as lo, max(price) as hi, "
        "sum(volume) as vol "
        "group by symbol aggregate by ts every sec ... min;"
    )

    def test_min_max_rollup_to_minutes(self):
        m = SiddhiManager()
        rt = m.create_siddhi_app_runtime(
            "@app:playback " + DEFINE + self.AGG_MM)
        rt.start()
        try:
            send_trades(rt, [
                ("A", 9.0, 10, 0),
                ("A", 3.0, 20, 15_000),   # same minute, other second
                ("A", 7.0, 30, 61_000),   # next minute
            ])
            # advance the cascade past the open buckets
            send_trades(rt, [("Z", 1.0, 1, 200_000)])
            got = rt.query(
                "from MM within {s}L, {e}L per 'minutes' "
                "select symbol, lo, hi, vol;".format(
                    s=BASE_TS, e=BASE_TS + 180_000))
            rows = sorted(tuple(e.data) for e in got
                          if e.data[0] == "A")
            assert rows == [("A", 3.0, 9.0, 30), ("A", 7.0, 7.0, 30)]
        finally:
            rt.shutdown()
            m.shutdown()

    def test_per_seconds_granularity_counts(self):
        m = SiddhiManager()
        rt = m.create_siddhi_app_runtime(
            "@app:playback " + DEFINE + self.AGG_MM)
        rt.start()
        try:
            send_trades(rt, [
                ("A", 1.0, 1, 0),
                ("A", 2.0, 1, 100),       # same second
                ("A", 4.0, 1, 1_100),     # next second
            ])
            send_trades(rt, [("Z", 1.0, 1, 60_000)])
            got = rt.query(
                "from MM within {s}L, {e}L per 'seconds' "
                "select symbol, lo, hi;".format(
                    s=BASE_TS, e=BASE_TS + 10_000))
            rows = sorted(tuple(e.data) for e in got if e.data[0] == "A")
            assert rows == [("A", 1.0, 2.0), ("A", 4.0, 4.0)]
        finally:
            rt.shutdown()
            m.shutdown()


class TestLatestAndFilteredAggregations:
    """reference: LatestAggregationTestCase.java (non-aggregate select
    items carry the LATEST value per bucket/group) and
    AggregationFilterTestCase.java (filters on the aggregation input)."""

    def _run(self, app, sends, query):
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime("@app:playback " + app)
            rt.start()
            h = rt.get_input_handler("stockStream")
            for row, ts in sends:
                h.send(row, timestamp=ts)
            out = rt.query(query)
            rt.shutdown()
            return [list(e.data) for e in out]
        finally:
            m.shutdown()

    BASE_TS = 1_496_289_950_000  # the reference suites' epoch anchor

    def test_latest_value_per_bucket(self):
        """reference: LatestAggregationTestCase:65 — `(price*quantity)
        as latestPrice` keeps the LAST value seen in each bucket."""
        app = ("define stream stockStream (symbol string, price double, "
               "quantity int, timestamp long); "
               "define aggregation A from stockStream "
               "select symbol, avg(price) as ap, "
               "(price * quantity) as latest "
               "group by symbol aggregate by timestamp every sec...min;")
        t = self.BASE_TS
        rows = self._run(app, [
            (["IBM", 10.0, 2, t], t),
            (["IBM", 20.0, 3, t + 100], t + 100),   # same second
            (["IBM", 30.0, 4, t + 2000], t + 2000),  # next bucket
        ], "from A within %d, %d per 'seconds' select symbol, ap, latest;"
           % (t - 1000, t + 10_000))
        by_latest = sorted(r[2] for r in rows)
        # bucket 1 latest = 20*3 = 60; bucket 2 latest = 30*4 = 120
        assert by_latest == [60.0, 120.0], rows

    def test_filtered_aggregation_input(self):
        """reference: AggregationFilterTestCase:43 — only rows passing
        the input filter aggregate."""
        app = ("define stream stockStream (symbol string, price double, "
               "quantity int, timestamp long); "
               "define aggregation A from stockStream[price > 15.0] "
               "select symbol, sum(price) as t "
               "group by symbol aggregate by timestamp every sec...min;")
        t = self.BASE_TS
        rows = self._run(app, [
            (["IBM", 10.0, 1, t], t),          # filtered out
            (["IBM", 20.0, 1, t + 100], t + 100),
            (["IBM", 30.0, 1, t + 200], t + 200),
        ], "from A within %d, %d per 'seconds' select symbol, t;"
           % (t - 1000, t + 10_000))
        assert rows == [["IBM", 50.0]], rows

    def test_distinct_count_aggregation(self):
        """reference: DistinctCountAggregationTestCase."""
        app = ("define stream stockStream (symbol string, price double, "
               "quantity int, timestamp long); "
               "define aggregation A from stockStream "
               "select symbol, distinctCount(price) as d "
               "group by symbol aggregate by timestamp every sec...min;")
        t = self.BASE_TS
        rows = self._run(app, [
            (["IBM", 10.0, 1, t], t),
            (["IBM", 10.0, 1, t + 50], t + 50),
            (["IBM", 20.0, 1, t + 100], t + 100),
        ], "from A within %d, %d per 'seconds' select symbol, d;"
           % (t - 1000, t + 10_000))
        assert rows == [["IBM", 2]], rows


class TestVectorizedIngest:
    """The segmented ingest reductions (np scatter ufuncs + the tpu-mode
    device scatter) must match the per-segment reference semantics on
    large mixed batches."""

    def _run(self, mode, n=2048, seed=7):
        from siddhi_tpu.core.event import EventBatch

        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(
                "@app:playback " + mode +
                "define stream S (sym string, price double, vol long, "
                "timestamp long); "
                "define aggregation A from S select sym, sum(price) as sp, "
                "min(price) as mn, max(price) as mx, count() as c, "
                "sum(vol) as sv group by sym "
                "aggregate by timestamp every sec...min;")
            rt.start()
            rng = np.random.default_rng(seed)
            t0 = 1_496_289_950_000
            ts = t0 + rng.integers(0, 5_000, n)
            order = np.argsort(ts, kind="stable")  # in-order arrival
            ts = ts[order].astype(np.int64)
            syms = np.asarray(
                [f"s{int(i)}" for i in rng.integers(0, 40, n)],
                dtype=object)[order]
            price = rng.uniform(1, 100, n)[order]
            vol = rng.integers(1, 10**10, n)[order].astype(np.int64)
            rt.get_input_handler("S").send_batch(EventBatch(
                "S", ["sym", "price", "vol", "timestamp"],
                {"sym": syms, "price": price, "vol": vol,
                 "timestamp": ts.copy()}, ts))
            out = rt.query(
                f"from A within {t0 - 1000}, {t0 + 100_000} per 'seconds' "
                "select sym, sp, mn, mx, c, sv;")
            rt.shutdown()
            return sorted([list(e.data) for e in out],
                          key=lambda r: r[0])
        finally:
            m.shutdown()

    def test_host_vectorized_matches_semantics(self):
        rows = self._run("")
        assert rows and all(r[2] <= r[3] for r in rows)  # min <= max
        # int sums exact at > 2^32 magnitudes (native-width scatter)
        assert all(isinstance(r[5], int) and r[5] > 2**32 for r in rows)

    def test_fast_path_equals_exact_fallback(self, monkeypatch):
        """The combined-code segmentation must agree VALUE-FOR-VALUE
        with the exact per-row fallback (the semantic reference)."""
        import siddhi_tpu.aggregation.runtime as agg_rt

        fast = self._run("")
        real_unique = np.unique

        def poisoned(*a, **kw):
            raise TypeError("force the exact per-row segmentation")

        # poison only the segmentation uniques inside on_event; the
        # fallback path itself uses no np.unique
        monkeypatch.setattr(agg_rt.np, "unique", poisoned)
        try:
            exact = self._run("")
        finally:
            monkeypatch.setattr(agg_rt.np, "unique", real_unique)
        assert len(fast) == len(exact)
        for a, b in zip(fast, exact):
            assert a[0] == b[0] and a[4] == b[4] and a[5] == b[5], (a, b)
            for i in (1, 2, 3):
                assert b[i] == pytest.approx(a[i], rel=1e-12), (a, b)

    def test_tpu_device_scatter_matches_host(self):
        host = self._run("")
        dev = self._run("@app:execution('tpu') ")
        assert len(host) == len(dev)
        for a, b in zip(host, dev):
            assert a[0] == b[0] and a[4] == b[4] and a[5] == b[5]
            for i in (1, 2, 3):  # float32 device lanes: rel tolerance
                assert b[i] == pytest.approx(a[i], rel=1e-4), (a, b)


class TestIngestFallbacks:
    def test_null_group_key_falls_back_exactly(self):
        """Nulls in an object group-by column are unorderable for
        np.unique; ingest must fall back to the exact per-row probe."""
        from siddhi_tpu.core.event import EventBatch

        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(
                "@app:playback "
                "define stream S (sym string, price double, "
                "timestamp long); "
                "define aggregation A from S select sym, sum(price) as sp "
                "group by sym aggregate by timestamp every sec...min;")
            rt.start()
            t0 = 1_496_289_950_000
            syms = np.empty(4, dtype=object)
            syms[:] = ["a", None, "a", None]
            ts = np.full(4, t0, dtype=np.int64)
            rt.get_input_handler("S").send_batch(EventBatch(
                "S", ["sym", "price", "timestamp"],
                {"sym": syms, "price": np.array([1.0, 2.0, 3.0, 4.0]),
                 "timestamp": ts.copy()}, ts))
            out = rt.query(
                f"from A within {t0 - 1000}, {t0 + 10_000} per 'seconds' "
                "select sym, sp;")
            rt.shutdown()
            rows = sorted([list(e.data) for e in out],
                          key=lambda r: repr(r[0]))
            assert rows == [["a", 4.0], [None, 6.0]], rows
        finally:
            m.shutdown()

    def test_int_sum_does_not_wrap(self):
        """int32 attribute sums exceed 2^31 within one bucket: the
        scatter accumulator must widen like np.sum does."""
        from siddhi_tpu.core.event import EventBatch

        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(
                "@app:playback "
                "define stream S (k string, v int, timestamp long); "
                "define aggregation A from S select k, sum(v) as sv "
                "group by k aggregate by timestamp every sec...min;")
            rt.start()
            t0 = 1_496_289_950_000
            n = 3
            ts = np.full(n, t0, dtype=np.int64)
            rt.get_input_handler("S").send_batch(EventBatch(
                "S", ["k", "v", "timestamp"],
                {"k": np.asarray(["x"] * n, dtype=object),
                 "v": np.full(n, 2**30, dtype=np.int32),
                 "timestamp": ts.copy()}, ts))
            out = rt.query(
                f"from A within {t0 - 1000}, {t0 + 10_000} per 'seconds' "
                "select k, sv;")
            rt.shutdown()
            assert [list(e.data) for e in out] == [["x", 3 * 2**30]]
        finally:
            m.shutdown()
