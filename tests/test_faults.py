"""Deterministic fault injection + hardening of the async device pipeline.

``@app:faults(...)`` arms a seeded :class:`FaultInjector` on the app
context; choke points across the transfer/runtime/transport layers call
into it so chaos runs are reproducible.  These tests pin the hardening
contracts:

- transient transfer faults on the emit-drain path are retried with
  backoff and fully recovered (output bit-identical to a fault-free run);
- sticky device loss fails the affected drains but never kills the
  runtime (per-query isolation);
- injected callback/sink exceptions route through the @OnError fault
  stream machinery instead of unwinding the processing chain;
- ``retry.max.attempts`` bounds the reconnect ladder and marks the sink
  failed through the OnError path on exhaustion;
- clock stalls drop a scheduler advance without corrupting timer state;
- NaN/Inf state poison is detected, quarantined, and the state
  re-materialized from the last known-good copy;
- every counter is visible through ``runtime.statistics()`` and the REST
  feed even at statistics level 'off'.
"""

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.exceptions import (
    ConnectionUnavailableError,
    DeviceLostError,
    InjectedFaultError,
    SimulatedCrashError,
    TransferFaultError,
)
from siddhi_tpu.util.faults import FaultInjector, InputJournal

pytestmark = pytest.mark.faults

DEFINE = "define stream S (k long, v double); "
FILTER_APP = DEFINE + "from S[v > 0.0] select k, v insert into OutputStream;"


def _run(app, sends, out="OutputStream"):
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime(app)
        got = []
        rt.add_callback(out, lambda evs: got.extend(tuple(e.data)
                                                    for e in evs))
        rt.start()
        h = rt.get_input_handler("S")
        for i, row in enumerate(sends):
            h.send(list(row), timestamp=1000 + i)
        rt.shutdown()
        return got, rt
    finally:
        m.shutdown()


class TestInjectorCore:
    def test_seeded_probability_is_deterministic(self):
        def trips(seed):
            fi = FaultInjector(seed=seed)
            fi.configure("x", "error", p=0.5, count=10 ** 9)
            out = []
            for _ in range(64):
                try:
                    fi.check("x")
                    out.append(0)
                except InjectedFaultError:
                    out.append(1)
            return out

        a, b, c = trips(7), trips(7), trips(8)
        assert a == b, "same seed must trip the same sequence"
        assert a != c, "different seeds should diverge"
        assert 0 < sum(a) < 64

    def test_count_and_after_budgets(self):
        fi = FaultInjector()
        fi.configure("x", "error", count=2, after=1)
        fi.check("x")  # skipped by after=1
        for _ in range(2):
            with pytest.raises(InjectedFaultError):
                fi.check("x")
        fi.check("x")  # budget exhausted -> clean
        assert fi.stats.faults_injected == 2

    def test_sticky_never_exhausts(self):
        fi = FaultInjector()
        fi.configure("x", "sticky")
        for _ in range(5):
            with pytest.raises(DeviceLostError):
                fi.check("x")

    def test_kind_exception_mapping(self):
        cases = {"transient": TransferFaultError, "sticky": DeviceLostError,
                 "error": InjectedFaultError,
                 "conn": ConnectionUnavailableError,
                 "crash": SimulatedCrashError}
        for kind, exc in cases.items():
            fi = FaultInjector()
            fi.configure("x", kind)
            with pytest.raises(exc):
                fi.check("x")

    def test_crash_is_not_an_Exception(self):
        # a simulated crash must tear through `except Exception`
        # hardening, exactly like a SIGKILL would
        assert not issubclass(SimulatedCrashError, Exception)

    def test_options_parsing(self):
        fi = FaultInjector()
        depth = fi.configure_from_options({
            "seed": "42", "transfer.retry.attempts": "5",
            "transfer.retry.scale": "0.5", "journal": "77",
            "emit.drain": "transient:count=2:p=0.25:after=3",
        })
        assert depth == 77
        assert fi.seed == 42
        assert fi.transfer_retry_attempts == 5
        assert fi.transfer_retry_scale == 0.5
        spec = fi._specs["emit.drain"][0]
        assert (spec.kind, spec.remaining, spec.p, spec.after) == (
            "transient", 2, 0.25, 3)

    @pytest.mark.parametrize("bad", ["", "transient:count", "transient:x=1",
                                     "nosuchkind"])
    def test_bad_specs_rejected(self, bad):
        fi = FaultInjector()
        with pytest.raises(ValueError):
            fi.configure_from_options({"emit.drain": bad})


class TestTransientDrainRecovery:
    def test_emit_drain_transient_is_retried_and_bit_exact(self):
        sends = [[i, float(i + 1)] for i in range(8)]
        clean, _ = _run("@app:playback @app:execution('tpu') " + FILTER_APP,
                        sends)
        chaotic, rt = _run(
            "@app:playback "
            "@app:faults(seed='3', transfer.retry.scale='0.0001', "
            "emit.drain='transient:count=3') "
            "@app:execution('tpu') " + FILTER_APP, sends)
        assert chaotic == clean, "retried drains must not lose or dup rows"
        fi = rt.app_context.fault_injector
        # count=3 trips on three consecutive attempts of the FIRST
        # drain, which then succeeds on attempt 4: one recovered drain
        assert fi.stats.faults_injected == 3
        assert fi.stats.transfer_retries == 3
        assert fi.stats.drains_recovered == 1
        assert fi.stats.drains_failed == 0

    def test_retry_budget_exhaustion_drops_batch_not_runtime(self):
        # more consecutive transient faults than transfer.retry.attempts:
        # that drain fails (batch dropped + counted) but later batches
        # flow normally
        sends = [[i, 1.0] for i in range(6)]
        got, rt = _run(
            "@app:playback "
            "@app:faults(transfer.retry.attempts='1', "
            "transfer.retry.scale='0.0001', "
            "emit.drain='transient:count=2') "
            "@app:execution('tpu') " + FILTER_APP, sends)
        fi = rt.app_context.fault_injector
        assert fi.stats.drains_failed == 1
        assert len(got) == 5  # one batch of one row lost, rest intact

    def test_sharded_ingest_put_transient_recovered(self):
        sends = [[i % 4, float(i + 1)] for i in range(24)]
        app = DEFINE + ("from S select k, sum(v) as s group by k "
                        "insert into OutputStream;")
        clean, _ = _run(
            "@app:playback @app:execution('tpu', partitions='16', "
            "devices='8') " + app, sends)
        chaotic, rt = _run(
            "@app:playback "
            "@app:faults(transfer.retry.scale='0.0001', "
            "ingest.put='transient:count=2') "
            "@app:execution('tpu', partitions='16', devices='8') " + app,
            sends)
        assert chaotic == clean
        fi = rt.app_context.fault_injector
        assert fi.stats.faults_injected == 2
        assert fi.stats.transfer_retries == 2


class TestStickyDeviceLoss:
    def test_runtime_survives_device_loss(self):
        sends = [[i, 1.0] for i in range(5)]
        got, rt = _run(
            "@app:playback @app:faults(emit.drain='sticky') "
            "@app:execution('tpu') " + FILTER_APP, sends)
        fi = rt.app_context.fault_injector
        assert got == []  # every drain lost to the dead device
        assert fi.stats.drains_failed > 0
        assert fi.stats.drains_recovered == 0

    def test_isolation_routes_to_exception_listeners(self):
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(
                "@app:playback @app:faults(emit.drain='sticky:count=1') "
                "@app:execution('tpu') " + FILTER_APP)
            seen = []
            rt.add_exception_listener(seen.append)
            rt.add_callback("OutputStream", lambda evs: None)
            rt.start()
            rt.get_input_handler("S").send([1, 1.0], timestamp=1000)
            rt.shutdown()
            assert any(isinstance(e, DeviceLostError) for e in seen)
        finally:
            m.shutdown()


class TestCallbackIsolation:
    def test_injected_callback_error_does_not_stop_the_stream(self):
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(
                "@app:playback @app:faults(callback='error:count=1') "
                + FILTER_APP)
            got, errs = [], []
            rt.add_exception_listener(errs.append)
            rt.add_callback("OutputStream",
                            lambda evs: got.extend(tuple(e.data)
                                                   for e in evs))
            rt.start()
            h = rt.get_input_handler("S")
            h.send([1, 1.0], timestamp=1000)  # eaten by the injection
            h.send([2, 2.0], timestamp=1001)
            rt.shutdown()
            assert got == [(2, 2.0)]
            assert any(isinstance(e, InjectedFaultError) for e in errs)
        finally:
            m.shutdown()


class TestSinkFaults:
    def setup_method(self):
        from siddhi_tpu.transport.broker import InMemoryBroker

        InMemoryBroker.clear()

    def test_injected_publish_error_routes_to_fault_stream(self):
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(
                "@app:faults(sink.publish='error:count=1') "
                "@OnError(action='stream') "
                "@sink(type='inMemory', topic='t1') "
                "define stream S (k long, v double);")
            faulted = []
            rt.add_callback("!S", lambda evs: faulted.extend(
                tuple(e.data) for e in evs))
            from siddhi_tpu.transport.broker import (
                FunctionSubscriber,
                InMemoryBroker,
            )
            published = []
            sub = FunctionSubscriber("t1", published.append)
            InMemoryBroker.subscribe(sub)
            rt.start()
            h = rt.get_input_handler("S")
            h.send([1, 1.0], timestamp=1000)
            h.send([2, 2.0], timestamp=1001)
            rt.shutdown()
            InMemoryBroker.unsubscribe(sub)
            assert len(published) == 1  # second event went through
            assert len(faulted) == 1
            assert faulted[0][:2] == (1, 1.0)
            assert isinstance(faulted[0][2], InjectedFaultError)
        finally:
            m.shutdown()

    def test_retry_max_attempts_marks_sink_failed(self):
        import time

        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(
                "@app:faults(sink.connect='conn:count=99') "
                "@sink(type='inMemory', topic='t2', "
                "retry.max.attempts='2', retry.scale='0.00002') "
                "define stream S (k long, v double);")
            rt.start()
            sink = rt.sinks[0]
            deadline = time.time() + 5.0
            while not sink.failed and time.time() < deadline:
                time.sleep(0.01)
            assert sink.failed, "sink never gave up its reconnect ladder"
            assert not sink.connected
            fi = rt.app_context.fault_injector
            assert fi.stats.connect_retries_exhausted == 1
            rt.shutdown()
        finally:
            m.shutdown()

    def test_connect_recovers_within_budget(self):
        import time

        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(
                "@app:faults(sink.connect='conn:count=1') "
                "@sink(type='inMemory', topic='t3', "
                "retry.max.attempts='5', retry.scale='0.00002') "
                "define stream S (k long, v double);")
            rt.start()
            sink = rt.sinks[0]
            deadline = time.time() + 5.0
            while not sink.connected and time.time() < deadline:
                time.sleep(0.01)
            assert sink.connected
            assert not sink.failed
            rt.shutdown()
        finally:
            m.shutdown()


class TestTimerStall:
    def test_stall_drops_one_advance_then_recovers(self):
        # timeBatch pane close rides scheduler.advance; a stalled clock
        # must skip the fire (counted) and the NEXT advance must still
        # close the pane — no timer-state corruption
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(
                "@app:playback @app:faults(timer='stall:count=1') "
                + DEFINE +
                "from S#window.timeBatch(1 sec) select sum(v) as s "
                "insert into OutputStream;")
            got = []
            rt.add_callback("OutputStream",
                            lambda evs: got.extend(tuple(e.data)
                                                   for e in evs))
            rt.start()
            h = rt.get_input_handler("S")
            h.send([1, 10.0], timestamp=1000)
            h.send([1, 5.0], timestamp=2500)   # advance stalled here
            h.send([1, 2.0], timestamp=2600)   # next advance fires panes
            rt.shutdown()
            fi = rt.app_context.fault_injector
            assert fi.stats.timer_stalls == 1
            assert (10.0,) in got  # the pane still closed eventually
        finally:
            m.shutdown()


class TestPoisonQuarantine:
    APP = DEFINE + ("from S#window.length(4) select k, sum(v) as s "
                    "insert into OutputStream;")

    def test_poisoned_state_rematerialized_from_last_good(self):
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(
                "@app:playback "
                "@app:faults(state.poison='poison:count=1:after=2') "
                "@app:execution('tpu') " + self.APP)
            got = []
            rt.add_callback("OutputStream",
                            lambda evs: got.extend(tuple(e.data)
                                                   for e in evs))
            rt.start()
            h = rt.get_input_handler("S")
            for i, v in enumerate([1.0, 2.0, 4.0, 8.0]):
                h.send([0, v], timestamp=1000 + i)
            rt.shutdown()
            fi = rt.app_context.fault_injector
            assert fi.stats.poison_quarantines == 1
            # batch 3 (v=4.0) was poisoned: its output is quarantined and
            # the state rolled back to after batch 2 — batch 4 then sums
            # over {1,2,8} instead of carrying NaN forward
            assert got == [(0, 1.0), (0, 3.0), (0, 11.0)]
            assert all(np.isfinite(s) for _k, s in got)
        finally:
            m.shutdown()

    def test_poison_guard_idle_when_unarmed(self):
        got, rt = _run("@app:playback @app:faults(seed='1') "
                       "@app:execution('tpu') " + self.APP,
                       [[0, 1.0], [0, 2.0]])
        fi = rt.app_context.fault_injector
        assert fi.stats.poison_quarantines == 0
        assert got == [(0, 1.0), (0, 3.0)]


class TestCountersVisible:
    def test_statistics_and_rest_feed_expose_fault_counters(self):
        import json
        from urllib.request import urlopen

        from siddhi_tpu.service import SiddhiService

        svc = SiddhiService()
        svc.start()
        try:
            code, _ = svc.deploy(
                "@app:name('chaosApp') @app:playback "
                "@app:faults(seed='3', transfer.retry.scale='0.0001', "
                "emit.drain='transient:count=1') "
                "@app:execution('tpu') " + FILTER_APP)
            assert code in (200, 201)
            rt = svc.get_runtime("chaosApp")
            rt.get_input_handler("S").send([1, 1.0], timestamp=1000)
            rt.drain_device_emits()
            pre = "io.siddhi.SiddhiApps.chaosApp.Siddhi.Faults.injector."
            # direct runtime feed — note @app:statistics is OFF: fault
            # counters are registered ungated
            stats = rt.statistics()
            assert stats[pre + "faults_injected"] == 1
            assert stats[pre + "transfer_retries"] == 1
            assert stats[pre + "drains_recovered"] == 1
            # REST feed — over real HTTP
            with urlopen(f"http://127.0.0.1:{svc.port}"
                         "/siddhi-statistics/chaosApp") as r:
                body = json.loads(r.read())
            assert body["status"] == "OK"
            assert body["metrics"][pre + "faults_injected"] == 1
            code, _ = svc.statistics("nosuchapp")
            assert code == 404
        finally:
            svc.stop()
            svc.manager.shutdown()


class TestPersistenceRobustness:
    def test_missing_directory_is_not_an_error(self, tmp_path):
        from siddhi_tpu.util.persistence import FileSystemPersistenceStore

        store = FileSystemPersistenceStore(str(tmp_path / "never_created"))
        assert store.get_last_revision("app") is None
        assert store.revisions("app") == []
        store.clear_all_revisions("app")  # no raise

    def test_foreign_and_truncated_files_skipped(self, tmp_path):
        from siddhi_tpu.util.persistence import FileSystemPersistenceStore

        store = FileSystemPersistenceStore(str(tmp_path))
        store.save("app", "100_app", b"good")
        d = tmp_path / "app"
        (d / "junk.txt").write_bytes(b"not a revision")
        (d / "200_app").write_bytes(b"")  # truncated save
        assert store.load("app", "200_app") is None
        assert store.load("app", "100_app") == b"good"
        assert store.load("app", "999_app") is None  # missing file
        assert store.revisions("app") == ["100_app", "200_app"]

    def test_restore_falls_back_past_corrupt_newest_revision(self):
        from siddhi_tpu.util.persistence import FileSystemPersistenceStore

        import tempfile

        with tempfile.TemporaryDirectory() as td:
            m = SiddhiManager()
            try:
                m.set_persistence_store(FileSystemPersistenceStore(td))
                rt = m.create_siddhi_app_runtime(
                    "@app:name('fb') " + DEFINE +
                    "from S#window.length(3) select sum(v) as s "
                    "insert into OutputStream;")
                rt.start()
                h = rt.get_input_handler("S")
                h.send([1, 5.0], timestamp=1000)
                rev1 = rt.persist()
                h.send([1, 7.0], timestamp=2000)
                rev2 = rt.persist()
                assert rev1 != rev2
                # corrupt the NEWEST revision on disk (truncate)
                import os

                open(os.path.join(td, "fb", rev2), "wb").close()
                got = []
                rt.add_callback("OutputStream",
                                lambda evs: got.extend(tuple(e.data)
                                                       for e in evs))
                used = rt.restore_last_revision()
                assert used == rev1, "should fall back to the good revision"
                h.send([1, 1.0], timestamp=3000)
                rt.shutdown()
                assert got == [(6.0,)]  # window holds {5.0} + 1.0
            finally:
                m.shutdown()

    def test_all_revisions_corrupt_raises(self):
        from siddhi_tpu.core.exceptions import CannotRestoreSiddhiAppStateError
        from siddhi_tpu.util.persistence import InMemoryPersistenceStore

        class BrokenStore(InMemoryPersistenceStore):
            def load(self, app_name, revision):
                return b"\x00garbage"

        m = SiddhiManager()
        try:
            m.set_persistence_store(BrokenStore())
            rt = m.create_siddhi_app_runtime("@app:name('br') " + FILTER_APP)
            rt.start()
            rt.get_input_handler("S").send([1, 1.0], timestamp=1000)
            rt.persist()
            with pytest.raises(CannotRestoreSiddhiAppStateError):
                rt.restore_last_revision()
            rt.shutdown()
        finally:
            m.shutdown()


class TestJournalUnit:
    def _batch(self, n, base=0):
        from siddhi_tpu.core.event import EventBatch

        return EventBatch(
            "S", ["k"], {"k": np.arange(base, base + n, dtype=np.int64)},
            1000 + np.arange(n, dtype=np.int64))

    def test_overflow_poisons_replay(self):
        jr = InputJournal(depth=2)
        jr.mark_revision("r1")
        for i in range(4):
            jr.record("S", self._batch(1, base=i))
        assert jr.entries_after("r1") is None  # gapped
        assert jr.stats.journal_dropped == 2

    def test_unknown_revision_returns_none(self):
        jr = InputJournal(depth=8)
        jr.record("S", self._batch(1))
        assert jr.entries_after("never_marked") is None

    def test_partial_suppression_splits_batch(self):
        jr = InputJournal(depth=8)
        jr.mark_revision("r1")  # checkpoint taken: nothing delivered yet
        key = ("stream", "S")
        # 3 events delivered AFTER the checkpoint, before the crash
        out = jr.deliver(key, self._batch(3))
        assert len(out) == 3
        jr.begin_replay()
        try:
            # replay re-emits 5 rows; first 3 suppressed, tail delivered
            replayed = jr.deliver(key, self._batch(5))
        finally:
            jr.end_replay()
        assert len(replayed) == 2
        assert list(replayed.columns["k"]) == [3, 4]
        assert jr.stats.suppressed_events == 3
