"""Filter conformance matrix: per-type comparisons, arithmetic,
boolean logic, null handling, builtin functions.

Ported behavior families from the reference's filter corpus
(modules/siddhi-core/src/test/java/io/siddhi/core/query/
FilterTestCase1.java, FilterTestCase2.java, BooleanCompareTestCase.java,
StringCompareTestCase.java, IsNullTestCase.java) — black-box SiddhiQL
string in -> events in -> concrete event values out, the reference's own
test style (SURVEY.md section 4).
"""

import pytest

from siddhi_tpu import SiddhiManager


def run(app, sends, out="OutputStream", stream="S"):
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime("@app:playback " + app)
        got = []
        rt.add_callback(out, lambda evs: got.extend(e.data for e in evs))
        rt.start()
        h = rt.get_input_handler(stream)
        t = 1000
        for row in sends:
            h.send(row, timestamp=t)
            t += 100
        rt.shutdown()
        return got
    finally:
        m.shutdown()


STOCK = "define stream S (symbol string, price float, volume long); "
TYPED = ("define stream S (i int, l long, f float, d double, "
         "s string, b bool); ")

ROWS = [
    ["IBM", 700.0, 100],
    ["WSO2", 60.5, 200],
    ["GOOG", 50.0, 30],
    ["IBM", 76.6, 400],
    ["WSO2", 45.6, 50],
]


class TestNumericCompares:
    """Reference: FilterTestCase1 — every operator against every numeric
    type, concrete surviving rows asserted."""

    CASES = [
        ("volume < 100", ["GOOG", "WSO2"]),
        ("volume <= 100", ["IBM", "GOOG", "WSO2"]),
        ("volume > 100", ["WSO2", "IBM"]),
        ("volume >= 200", ["WSO2", "IBM"]),
        ("volume == 200", ["WSO2"]),
        ("volume != 200", ["IBM", "GOOG", "IBM", "WSO2"]),
        ("price < 60.0", ["GOOG", "WSO2"]),
        ("price <= 50.0", ["GOOG", "WSO2"]),
        ("price > 70.0", ["IBM", "IBM"]),
        # float32(76.6) == 76.5999985... < double 76.6 — java float->double
        # promotion semantics: the 76.6f row does NOT pass
        ("price >= 76.6", ["IBM"]),
        # float attr vs int literal (cross-type promotion)
        ("price > 50", ["IBM", "WSO2", "IBM"]),
        # long attr vs float literal
        ("volume > 99.5", ["IBM", "WSO2", "IBM"]),
    ]

    @pytest.mark.parametrize("cond,expect", CASES)
    def test_compare(self, cond, expect):
        got = run(STOCK + f"from S[{cond}] select symbol "
                          "insert into OutputStream;", ROWS)
        assert [g[0] for g in got] == expect

    def test_compound_and_or_not(self):
        got = run(STOCK + "from S[(price > 50.0 and volume < 300) or "
                          "symbol == 'GOOG'] select symbol, price "
                          "insert into OutputStream;", ROWS)
        assert got == [["IBM", 700.0], ["WSO2", 60.5], ["GOOG", 50.0]]

    def test_not_operator(self):
        got = run(STOCK + "from S[not (volume >= 100)] select symbol "
                          "insert into OutputStream;", ROWS)
        assert [g[0] for g in got] == ["GOOG", "WSO2"]

    def test_bool_attribute_filter(self):
        got = run(TYPED + "from S[b] select i insert into OutputStream;",
                  [[1, 1, 1.0, 1.0, "x", True],
                   [2, 2, 2.0, 2.0, "y", False],
                   [3, 3, 3.0, 3.0, "z", True]])
        assert [g[0] for g in got] == [1, 3]

    def test_bool_compare_literal(self):
        # reference: BooleanCompareTestCase
        got = run(TYPED + "from S[b == true] select i "
                          "insert into OutputStream;",
                  [[1, 1, 1.0, 1.0, "x", True],
                   [2, 2, 2.0, 2.0, "y", False]])
        assert [g[0] for g in got] == [1]
        got = run(TYPED + "from S[b != true] select i "
                          "insert into OutputStream;",
                  [[1, 1, 1.0, 1.0, "x", True],
                   [2, 2, 2.0, 2.0, "y", False]])
        assert [g[0] for g in got] == [2]


class TestStringCompares:
    """Reference: StringCompareTestCase."""

    def test_equىality(self):
        got = run(STOCK + "from S[symbol == 'IBM'] select symbol, volume "
                          "insert into OutputStream;", ROWS)
        assert got == [["IBM", 100], ["IBM", 400]]

    def test_inequality(self):
        got = run(STOCK + "from S[symbol != 'IBM'] select symbol "
                          "insert into OutputStream;", ROWS)
        assert [g[0] for g in got] == ["WSO2", "GOOG", "WSO2"]

    def test_string_vs_attribute(self):
        app = ("define stream S (a string, b string); "
               "from S[a == b] select a insert into OutputStream;")
        got = run(app, [["x", "x"], ["x", "y"], ["z", "z"]])
        assert [g[0] for g in got] == ["x", "z"]


class TestArithmetic:
    """Reference: executor/math per-type classes; java semantics for
    int division/modulo (truncation toward zero)."""

    def test_add_sub_mul(self):
        app = TYPED + ("from S select i + 2 as a, l - 1 as b, f * 2.0 as c, "
                       "d / 2.0 as e insert into OutputStream;")
        got = run(app, [[10, 100, 1.5, 9.0, "x", True]])
        assert got == [[12, 99, 3.0, 4.5]]

    def test_int_division_truncates(self):
        app = TYPED + "from S select i / 3 as q insert into OutputStream;"
        got = run(app, [[7, 0, 0.0, 0.0, "", True],
                        [-7, 0, 0.0, 0.0, "", True]])
        assert [g[0] for g in got] == [2, -2]  # java truncation, not floor

    def test_int_modulo_sign(self):
        app = TYPED + "from S select i % 3 as r insert into OutputStream;"
        got = run(app, [[7, 0, 0.0, 0.0, "", True],
                        [-7, 0, 0.0, 0.0, "", True]])
        assert [g[0] for g in got] == [1, -1]  # java: sign of dividend

    def test_promotion_int_long_float_double(self):
        app = TYPED + ("from S select i + l as il, i + f as if_, "
                       "l + d as ld insert into OutputStream;")
        got = run(app, [[1, 2, 0.5, 0.25, "", True]])
        assert got == [[3, 1.5, 2.25]]

    def test_arithmetic_in_filter(self):
        got = run(STOCK + "from S[price * 2.0 > 150.0] select symbol "
                          "insert into OutputStream;", ROWS)
        assert [g[0] for g in got] == ["IBM", "IBM"]


class TestIsNullAndNullFlow:
    """Reference: IsNullTestCase — null attribute routing."""

    def test_is_null_on_sent_none(self):
        app = ("define stream S (symbol string, price double); "
               "from S[price is null] select symbol insert into OutputStream;")
        got = run(app, [["A", 1.0], ["B", None], ["C", 2.0]])
        assert [g[0] for g in got] == ["B"]

    def test_not_null(self):
        app = ("define stream S (symbol string, price double); "
               "from S[not (price is null)] select symbol "
               "insert into OutputStream;")
        got = run(app, [["A", 1.0], ["B", None]])
        assert [g[0] for g in got] == ["A"]

    def test_null_comparison_is_false(self):
        # reference: null compares false on every operator
        app = ("define stream S (symbol string, price double); "
               "from S[price > 0.0] select symbol insert into OutputStream;")
        got = run(app, [["A", 1.0], ["B", None], ["C", -1.0]])
        assert [g[0] for g in got] == ["A"]


class TestBuiltinFunctions:
    """Reference: executor/function builtins."""

    def test_if_then_else(self):
        got = run(STOCK + "from S select symbol, "
                          "ifThenElse(volume > 150, 'hi', 'lo') as lvl "
                          "insert into OutputStream;", ROWS[:3])
        assert got == [["IBM", "lo"], ["WSO2", "hi"], ["GOOG", "lo"]]

    def test_coalesce(self):
        app = ("define stream S (a string, b string); "
               "from S select coalesce(a, b) as v insert into OutputStream;")
        got = run(app, [[None, "fallback"], ["first", "unused"]])
        assert [g[0] for g in got] == ["fallback", "first"]

    def test_cast_and_convert(self):
        app = ("define stream S (v double); "
               "from S select convert(v, 'int') as i, "
               "convert(v, 'string') as s insert into OutputStream;")
        got = run(app, [[3.7]])
        assert got[0][0] == 3 and got[0][1].startswith("3.7")

    def test_math_min_max(self):
        app = ("define stream S (a double, b double); "
               "from S select maximum(a, b) as mx, minimum(a, b) as mn "
               "insert into OutputStream;")
        got = run(app, [[3.0, 7.0], [9.0, 2.0]])
        assert got == [[7.0, 3.0], [9.0, 2.0]]

    def test_event_timestamp(self):
        app = ("define stream S (v double); "
               "from S select eventTimestamp() as ts, v "
               "insert into OutputStream;")
        got = run(app, [[1.0], [2.0]])
        assert got == [[1000, 1.0], [1100, 2.0]]

    def test_instance_of(self):
        app = ("define stream S (v double, s string); "
               "from S select instanceOfDouble(v) as a, "
               "instanceOfString(v) as b, instanceOfString(s) as c "
               "insert into OutputStream;")
        got = run(app, [[1.5, "x"]])
        assert got == [[True, False, True]]


class TestSelectorShapes:
    """Reference: PassThroughTestCase / selector basics."""

    def test_select_star_passthrough(self):
        got = run(STOCK + "from S select * insert into OutputStream;",
                  ROWS[:2])
        assert got == [["IBM", 700.0, 100], ["WSO2", 60.5, 200]]

    def test_rename_and_expression_projection(self):
        got = run(STOCK + "from S select symbol as sym, "
                          "price * volume as notional "
                          "insert into OutputStream;", ROWS[:2])
        assert got == [["IBM", 70000.0], ["WSO2", 12100.0]]

    def test_constant_projection(self):
        got = run(STOCK + "from S select symbol, 42 as k "
                          "insert into OutputStream;", ROWS[:1])
        assert got == [["IBM", 42]]


class TestOrderByLimit:
    """Reference: OrderByLimitTestCase — deterministic ordering with
    limit/offset over batch windows."""

    APP = STOCK + ("from S#window.lengthBatch(5) select symbol, volume "
                   "order by volume {} insert into OutputStream;")

    def test_order_asc_limit(self):
        got = run(self.APP.format("limit 2"), ROWS)
        assert got == [["GOOG", 30], ["WSO2", 50]]

    def test_order_desc(self):
        got = run(STOCK + "from S#window.lengthBatch(5) "
                          "select symbol, volume order by volume desc "
                          "limit 3 insert into OutputStream;", ROWS)
        assert got == [["IBM", 400], ["WSO2", 200], ["IBM", 100]]

    def test_offset(self):
        got = run(STOCK + "from S#window.lengthBatch(5) "
                          "select symbol, volume order by volume "
                          "limit 2 offset 2 insert into OutputStream;", ROWS)
        assert got == [["IBM", 100], ["WSO2", 200]]

    def test_order_by_two_keys(self):
        got = run(STOCK + "from S#window.lengthBatch(5) "
                          "select symbol, volume order by symbol, volume desc "
                          "insert into OutputStream;", ROWS)
        assert got == [["GOOG", 30], ["IBM", 400], ["IBM", 100],
                       ["WSO2", 200], ["WSO2", 50]]


class TestGroupByHaving:
    """Reference: GroupByTestCase — per-group aggregates with having."""

    def test_group_by_running_sum(self):
        got = run(STOCK + "from S select symbol, sum(volume) as total "
                          "group by symbol insert into OutputStream;", ROWS)
        assert got == [["IBM", 100], ["WSO2", 200], ["GOOG", 30],
                       ["IBM", 500], ["WSO2", 250]]

    def test_group_by_two_keys(self):
        app = ("define stream S (a string, b string, v double); "
               "from S select a, b, sum(v) as t group by a, b "
               "insert into OutputStream;")
        got = run(app, [["x", "1", 10.0], ["x", "2", 20.0],
                        ["x", "1", 5.0]])
        assert got == [["x", "1", 10.0], ["x", "2", 20.0], ["x", "1", 15.0]]

    def test_having_filters_groups(self):
        got = run(STOCK + "from S select symbol, sum(volume) as total "
                          "group by symbol having total > 150 "
                          "insert into OutputStream;", ROWS)
        assert got == [["WSO2", 200], ["IBM", 500], ["WSO2", 250]]

    def test_avg_min_max_count(self):
        got = run(STOCK + "from S select avg(price) as a, min(price) as mn, "
                          "max(price) as mx, count() as c "
                          "insert into OutputStream;", ROWS[:3])
        assert got[-1] == [pytest.approx((700.0 + 60.5 + 50.0) / 3), 50.0,
                           700.0, 3]

    def test_distinct_count(self):
        got = run(STOCK + "from S select distinctCount(symbol) as dc "
                          "insert into OutputStream;", ROWS)
        assert [g[0] for g in got] == [1, 2, 3, 3, 3]

    def test_stddev(self):
        app = "define stream S (v double); " \
              "from S select stdDev(v) as sd insert into OutputStream;"
        got = run(app, [[2.0], [4.0], [4.0], [4.0], [5.0], [5.0], [7.0],
                        [9.0]])
        assert got[-1][0] == pytest.approx(2.0)
