"""Join conformance tests.

Modeled on the reference join test corpus
(modules/siddhi-core/src/test/java/io/siddhi/core/query/join/
JoinTestCase / OuterJoinTestCase and query/table/JoinTableTestCase):
SiddhiQL in, events in, asserted joined outputs out.
"""

import pytest

from siddhi_tpu import SiddhiManager


@pytest.fixture
def manager():
    m = SiddhiManager()
    yield m
    m.shutdown()


def collect_stream(rt, stream):
    got = []
    rt.add_callback(stream, lambda events: got.extend(e.data for e in events))
    return got


def test_window_join(manager):
    app = (
        "define stream TickStream (symbol string, price double); "
        "define stream NewsStream (symbol string, headline string); "
        "@info(name='q') "
        "from TickStream#window.length(10) as t "
        "join NewsStream#window.length(10) as n "
        "on t.symbol == n.symbol "
        "select t.symbol as symbol, t.price as price, n.headline as headline "
        "insert into OutStream;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    got = collect_stream(rt, "OutStream")
    rt.get_input_handler("TickStream").send(["WSO2", 55.6])
    rt.get_input_handler("TickStream").send(["IBM", 75.6])
    assert got == []
    rt.get_input_handler("NewsStream").send(["WSO2", "up"])
    assert got == [["WSO2", 55.6, "up"]]
    # new tick joins against buffered news
    rt.get_input_handler("TickStream").send(["WSO2", 57.0])
    assert got == [["WSO2", 55.6, "up"], ["WSO2", 57.0, "up"]]


def test_join_select_star(manager):
    app = (
        "define stream A (x int); "
        "define stream B (y int); "
        "from A#window.length(5) join B#window.length(5) on A.x == B.y "
        "select * insert into OutStream;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    got = collect_stream(rt, "OutStream")
    rt.get_input_handler("A").send([7])
    rt.get_input_handler("B").send([7])
    rt.get_input_handler("B").send([8])
    assert got == [[7, 7]]


def test_left_outer_join(manager):
    app = (
        "define stream A (sym string, price double); "
        "define stream B (sym string, qty long); "
        "from A#window.length(5) as a "
        "left outer join B#window.length(5) as b "
        "on a.sym == b.sym "
        "select a.sym as sym, a.price as price, b.qty as qty "
        "insert into OutStream;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    got = collect_stream(rt, "OutStream")
    rt.get_input_handler("A").send(["X", 1.0])  # no match -> null right
    rt.get_input_handler("B").send(["X", 10])  # matches buffered A
    rt.get_input_handler("B").send(["Y", 20])  # right arrival, no emit (left outer keeps left)
    assert got == [["X", 1.0, None], ["X", 1.0, 10]]


def test_unidirectional_join(manager):
    app = (
        "define stream A (sym string); "
        "define stream B (sym string); "
        "from A#window.length(5) as a "
        "unidirectional join B#window.length(5) as b "
        "on a.sym == b.sym "
        "select a.sym as sym "
        "insert into OutStream;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    got = collect_stream(rt, "OutStream")
    rt.get_input_handler("B").send(["X"])  # buffers, must not trigger
    assert got == []
    rt.get_input_handler("A").send(["X"])  # triggers
    assert got == [["X"]]
    rt.get_input_handler("B").send(["X"])  # still must not trigger
    assert got == [["X"]]


def test_stream_table_join(manager):
    app = (
        "define stream StockStream (symbol string, price double); "
        "define stream CheckStream (symbol string); "
        "define table StockTable (symbol string, price double); "
        "from StockStream insert into StockTable; "
        "from CheckStream join StockTable "
        "on CheckStream.symbol == StockTable.symbol "
        "select CheckStream.symbol as symbol, StockTable.price as price "
        "insert into OutStream;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    got = collect_stream(rt, "OutStream")
    rt.get_input_handler("StockStream").send(["WSO2", 55.6])
    rt.get_input_handler("StockStream").send(["IBM", 75.6])
    rt.get_input_handler("CheckStream").send(["WSO2"])
    assert got == [["WSO2", 55.6]]


def test_self_join_with_aliases(manager):
    app = (
        "define stream S (sym string, v int); "
        "from S#window.length(5) as a "
        "join S#window.length(5) as b "
        "on a.v < b.v "
        "select a.sym as l, b.sym as r "
        "insert into OutStream;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    got = collect_stream(rt, "OutStream")
    rt.get_input_handler("S").send(["p", 1])
    rt.get_input_handler("S").send(["q", 2])  # pairs (p,q) exactly once
    assert got == [["p", "q"]]


def test_join_with_side_filters(manager):
    app = (
        "define stream A (sym string, v int); "
        "define stream B (sym string, w int); "
        "from A[v > 0]#window.length(5) as a "
        "join B[w > 10]#window.length(5) as b "
        "on a.sym == b.sym "
        "select a.sym as sym, a.v as v, b.w as w "
        "insert into OutStream;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    got = collect_stream(rt, "OutStream")
    rt.get_input_handler("A").send(["X", -1])  # filtered out
    rt.get_input_handler("A").send(["X", 5])
    rt.get_input_handler("B").send(["X", 3])  # filtered out
    rt.get_input_handler("B").send(["X", 30])
    assert got == [["X", 5, 30]]


def test_join_expired_events_flow(manager):
    """Length-window eviction on the left side emits EXPIRED joined rows
    (visible through a query callback's removeEvents)."""
    app = (
        "define stream A (sym string); "
        "define stream B (sym string); "
        "@info(name='q') "
        "from A#window.length(1) as a join B#window.length(5) as b "
        "on a.sym == b.sym "
        "select a.sym as sym "
        "insert all events into OutStream;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    current, expired = [], []
    def cb(ts, ins, outs):
        if ins:
            current.extend(e.data for e in ins)
        if outs:
            expired.extend(e.data for e in outs)
    rt.add_callback("q", cb)
    rt.get_input_handler("B").send(["X"])
    rt.get_input_handler("A").send(["X"])   # joins
    rt.get_input_handler("A").send(["X"])   # joins; evicts previous A -> expired join
    assert current == [["X"], ["X"]]
    assert expired == [["X"]]


def test_left_outer_join_float_null_is_none(manager):
    # ADVICE r1: unmatched-side float lanes used to surface NaN while
    # other types surfaced None; nulls must be uniform across types.
    app = (
        "define stream A (sym string, qty long); "
        "define stream B (sym string, price double, n long); "
        "from A#window.length(5) as a "
        "left outer join B#window.length(5) as b "
        "on a.sym == b.sym "
        "select a.sym as sym, b.price as price, b.n as n "
        "insert into OutStream;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    got = collect_stream(rt, "OutStream")
    rt.get_input_handler("A").send(["X", 1])
    assert got == [["X", None, None]]
    assert got[0][1] is None  # real None, not NaN


def test_left_outer_join_null_arithmetic(manager):
    """Arithmetic over a nullable outer-join column propagates null
    instead of raising (reference:
    MultiplyExpressionExecutorDouble.java:43-45 returns null on null
    operand)."""
    app = (
        "define stream A (symbol string, qty int); "
        "define stream B (symbol string, price double); "
        "@info(name='q') "
        "from A#window.length(5) as a "
        "left outer join B#window.length(5) as b "
        "on a.symbol == b.symbol "
        "select a.symbol as symbol, b.price * 2.0 as doubled "
        "insert into OutStream;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    got = collect_stream(rt, "OutStream")
    rt.get_input_handler("A").send(["WSO2", 1])   # no match -> null price
    rt.get_input_handler("B").send(["IBM", 10.0])
    rt.get_input_handler("A").send(["IBM", 2])    # match -> 20.0
    assert got == [["WSO2", None], ["IBM", 20.0]]


def test_outer_join_null_comparison_filters_false(manager):
    """Comparisons against a null outer-join column are false, not an
    error (null-comparison semantics of the reference compare
    executors)."""
    app = (
        "define stream A (symbol string, qty int); "
        "define stream B (symbol string, price double); "
        "@info(name='q') "
        "from A#window.length(5) as a "
        "left outer join B#window.length(5) as b "
        "on a.symbol == b.symbol "
        "select a.symbol as symbol, b.price as price "
        "having price > 5.0 "
        "insert into OutStream;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    got = collect_stream(rt, "OutStream")
    rt.get_input_handler("A").send(["WSO2", 1])   # null price -> filtered
    rt.get_input_handler("B").send(["IBM", 10.0])
    rt.get_input_handler("A").send(["IBM", 2])
    assert got == [["IBM", 10.0]]
