"""Partition conformance, part 2: per-key windows, group-by inside
partitions, inner-stream pipelines, shared global tables, range
partitions and purge — the behavioral families of the reference's
partition suite (modules/siddhi-core/src/test/java/io/siddhi/core/query/
partition/ — PartitionTestCase1/2, WindowPartitionTestCase,
JoinPartitionTestCase, TablePartitionTestCase,
PartitionDataPurgingTestCase).  Per-key state isolation is the contract
under test: each key must see its OWN window/aggregator state.
"""

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager

DEFS = "define stream S (k string, v long); "


def run(app, sends, out="OutputStream"):
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime("@app:playback " + app)
        got = []
        rt.add_callback(out, lambda evs: got.extend(list(e.data) for e in evs))
        rt.start()
        for stream, row, ts in sends:
            rt.get_input_handler(stream).send(row, timestamp=ts)
        rt.shutdown()
        return got
    finally:
        m.shutdown()


def s(rows, t0=1000, dt=100):
    return [("S", r, t0 + i * dt) for i, r in enumerate(rows)]


class TestPartitionedWindows:
    def test_per_key_length_window_sum(self):
        # each key's length(2) window holds ITS OWN last two events
        app = (DEFS +
               "partition with (k of S) begin "
               "@info(name='q') from S#window.length(2) "
               "select k, sum(v) as total insert into OutputStream; end;")
        got = run(app, s([["a", 1], ["b", 10], ["a", 2], ["b", 20],
                          ["a", 3], ["b", 30]]))
        assert got == [["a", 1], ["b", 10], ["a", 3], ["b", 30],
                       ["a", 5], ["b", 50]]

    def test_per_key_length_batch_flushes_independently(self):
        app = (DEFS +
               "partition with (k of S) begin "
               "@info(name='q') from S#window.lengthBatch(2) "
               "select k, sum(v) as total insert into OutputStream; end;")
        got = run(app, s([["a", 1], ["b", 10], ["b", 20], ["a", 2],
                          ["a", 3]]))
        # b's batch closes at its 2nd event, before a's does
        assert got == [["b", 30], ["a", 3]]

    def test_per_key_time_batch_watermark(self):
        app = (DEFS +
               "define stream Tick (x int); "
               "from Tick select x insert into _T; "
               "partition with (k of S) begin "
               "@info(name='q') from S#window.timeBatch(1 sec) "
               "select k, sum(v) as total insert into OutputStream; end;")
        got = run(app, [
            ("S", ["a", 1], 1000),
            ("S", ["b", 10], 1200),
            ("S", ["a", 2], 1400),
            ("Tick", [1], 2500),
        ])
        assert sorted(map(tuple, got)) == [("a", 3), ("b", 10)]

    def test_per_key_group_by_inside_partition(self):
        # group-by nested inside a partition: state per (key, group)
        defs = "define stream T (k string, g string, v long); "
        app = (defs +
               "partition with (k of T) begin "
               "@info(name='q') from T select k, g, sum(v) as total "
               "group by g insert into OutputStream; end;")
        sends = [("T", r, 1000 + i * 10) for i, r in enumerate(
            [["a", "x", 1], ["b", "x", 10], ["a", "x", 2],
             ["a", "y", 5], ["b", "x", 20]])]
        got = run(app, sends)
        assert got == [["a", "x", 1], ["b", "x", 10], ["a", "x", 3],
                       ["a", "y", 5], ["b", "x", 30]]


class TestPartitionInnerStreams:
    def test_inner_stream_pipeline_stays_per_key(self):
        # stage 1 aggregates per key into #P; stage 2 filters it —
        # the inner stream is local to each key instance
        app = (DEFS +
               "partition with (k of S) begin "
               "@info(name='q1') from S select k, sum(v) as total "
               "insert into #P; "
               "@info(name='q2') from #P[total > 10] "
               "select k, total insert into OutputStream; end;")
        got = run(app, s([["a", 6], ["b", 11], ["a", 6], ["b", 1]]))
        assert got == [["b", 11], ["a", 12], ["b", 12]]

    def test_inner_window_per_key(self):
        app = (DEFS +
               "partition with (k of S) begin "
               "@info(name='q1') from S select k, v insert into #P; "
               "@info(name='q2') from #P#window.length(2) "
               "select k, sum(v) as total insert into OutputStream; end;")
        got = run(app, s([["a", 1], ["a", 2], ["a", 3], ["b", 10]]))
        assert got == [["a", 1], ["a", 3], ["a", 5], ["b", 10]]


class TestPartitionedTables:
    def test_global_table_shared_across_keys(self):
        # a table defined OUTSIDE the partition is one shared store
        app = (DEFS +
               "define table T (k string, v long); "
               "partition with (k of S) begin "
               "@info(name='q1') from S select k, v insert into T; end;")
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime("@app:playback " + app)
            rt.start()
            h = rt.get_input_handler("S")
            h.send(["a", 1], timestamp=1000)
            h.send(["b", 2], timestamp=1100)
            h.send(["a", 3], timestamp=1200)
            rows = sorted(tuple(e.data) for e in rt.query(
                "from T select k, v;"))
            assert rows == [("a", 1), ("a", 3), ("b", 2)]
            rt.shutdown()
        finally:
            m.shutdown()

    def test_partitioned_query_joins_global_table(self):
        app = (DEFS +
               "define stream Boot (k string, lim long); "
               "define table T (k string, lim long); "
               "from Boot insert into T; "
               "partition with (k of S) begin "
               "@info(name='q') from S join T on S.k == T.k and S.v > T.lim "
               "select S.k as k, S.v as v insert into OutputStream; end;")
        got = run(app, [
            ("Boot", ["a", 5], 500),
            ("Boot", ["b", 50], 600),
            ("S", ["a", 10], 1000),   # 10 > 5: out
            ("S", ["b", 10], 1100),   # 10 < 50: no
            ("S", ["b", 60], 1200),   # 60 > 50: out
        ])
        assert got == [["a", 10], ["b", 60]]


class TestRangePartitions:
    APP = (DEFS +
           "partition with (v < 10 as 'small' or v < 100 as 'mid' or "
           "v >= 100 as 'big' of S) begin "
           "@info(name='q') from S select k, count() as n "
           "insert into OutputStream; end;")

    def test_range_buckets_have_independent_state(self):
        got = run(self.APP, s([["a", 5], ["b", 50], ["c", 500],
                               ["d", 6], ["e", 600]]))
        # per-range count() state: small 1,2; mid 1; big 1,2
        assert got == [["a", 1], ["b", 1], ["c", 1], ["d", 2], ["e", 2]]

    def test_first_matching_range_wins(self):
        # v=5 matches both 'small' and 'mid' conditions; the FIRST
        # declared range claims it (reference RangePartitionExecutor
        # evaluates in declaration order)
        got = run(self.APP, s([["a", 5], ["b", 5]]))
        assert got == [["a", 1], ["b", 2]]


class TestPartitionPurge:
    def test_purged_key_state_resets(self):
        app = (DEFS +
               "@purge(enable='true', interval='1 sec', "
               "idle.period='2 sec') "
               "partition with (k of S) begin "
               "@info(name='q') from S select k, sum(v) as total "
               "insert into OutputStream; end;")
        got = run(app, [
            ("S", ["a", 5], 1000),
            ("S", ["b", 1], 1100),
            ("S", ["b", 1], 5000),   # watermark: BOTH keys idle > 2s
            ("S", ["a", 7], 5100),   # fresh instances: sums restart
        ])
        assert got == [["a", 5], ["b", 1], ["b", 1], ["a", 7]]

    def test_active_key_survives_purge(self):
        app = (DEFS +
               "@purge(enable='true', interval='1 sec', "
               "idle.period='10 sec') "
               "partition with (k of S) begin "
               "@info(name='q') from S select k, sum(v) as total "
               "insert into OutputStream; end;")
        got = run(app, [
            ("S", ["a", 5], 1000),
            ("S", ["a", 7], 5000),   # within idle.period: state kept
        ])
        assert got == [["a", 5], ["a", 12]]


class TestPartitionedPatternsHostVsDense:
    APP_BODY = (
        "define stream Txn (card string, amount double); "
        "partition with (card of Txn) begin "
        "@info(name='q') from every a=Txn[amount > 100.0] -> "
        "b=Txn[amount > a.amount] "
        "select a.amount as base, b.amount as bv "
        "insert into Alerts; end;"
    )

    def test_interleaved_keys_differential(self):
        sends = []
        rng = np.random.default_rng(5)
        t = 1000
        for _ in range(60):
            k = f"c{int(rng.integers(0, 6))}"
            t += int(rng.integers(1, 40))
            sends.append((k, float(rng.integers(50, 400)), t))

        def drive(header):
            m = SiddhiManager()
            try:
                rt = m.create_siddhi_app_runtime(header + self.APP_BODY)
                got = []
                rt.add_callback(
                    "Alerts", lambda evs: got.extend(e.data for e in evs))
                rt.start()
                h = rt.get_input_handler("Txn")
                for k, a, ts in sends:
                    h.send([k, a], timestamp=ts)
                rt.shutdown()
                return sorted(map(tuple, got))
            finally:
                m.shutdown()

        host = drive("@app:playback ")
        dense = drive("@app:playback @app:execution('tpu', "
                      "partitions='16') ")
        assert dense == host
        assert len(host) > 0
