"""Differential fixtures for the flow-sensitive concurrency rules.

Each fixture plants a bug the flow-INsensitive pass provably misses
(or a safe pattern it provably over-reports), and asserts both sides:

- **released-then-write**: a write lexically inside ``with lock:`` but
  after an explicit ``release()`` — lexical lock-discipline calls it
  locked, lockset-race sees the empty per-statement lockset;
- **disjoint locks**: thread and main path each hold *a* lock, just
  not the same one — lexically locked, dynamically unordered;
- **AB/BA deadlock**: opposite nesting orders across two methods,
  including the interprocedural variant where the inner acquisition
  lives in a private helper (caught only via entry-lockset seeding);
- **barrier missing one queue flush**: a shutdown barrier that drains
  one owned queue and only "flushes" the other in dead code after a
  ``return`` — reachability through the CFG, not lexical presence;
- **de-duplication**: a conflict both passes can see emits once, from
  lockset-race (the wrapper stands down), and lock-discipline keeps
  its full behavior when run standalone.
"""

import textwrap
from pathlib import Path

from siddhi_tpu.analysis import Allowlist, ModuleIndex, get_rule, run_rules

THREADING = "import threading\n"


def _mod(rel, src):
    return ModuleIndex(Path(rel), rel, source=textwrap.dedent(src))


def _run(files, rule_names, allowlists=None):
    indexes = [_mod(rel, src) for rel, src in files.items()]
    rules = [get_rule(n) for n in rule_names]
    al = {n: Allowlist(n, (allowlists or {}).get(n, {}))
          for n in rule_names}
    res = run_rules(indexes, rules, al)
    return res["findings"], res["suppressed"]


# -- lockset-race ------------------------------------------------------------

RELEASED_THEN_WRITE = {
    "pkg/__init__.py": "",
    "pkg/worker.py": """
        import threading

        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0

            def start(self):
                t = threading.Thread(target=self._run, daemon=True)
                t.start()

            def _run(self):
                with self._lock:
                    self.count += 1

            def bump(self):
                with self._lock:
                    self._lock.release()
                    self.count += 1
                    self._lock.acquire()
    """,
}


def test_released_then_write_race_lexical_pass_misses_it():
    findings, _ = _run(RELEASED_THEN_WRITE, ["lock-discipline"])
    assert findings == []   # lexically both writes sit under `with`


def test_released_then_write_race_lockset_catches_it():
    findings, _ = _run(RELEASED_THEN_WRITE, ["lockset-race"])
    assert [(f.rule, f.key) for f in findings] == \
        [("lockset-race", "pkg/worker.py:Worker.count")]
    assert "empty lockset intersection" in findings[0].message


DISJOINT_LOCKS = {
    "pkg/__init__.py": "",
    "pkg/disjoint.py": """
        import threading

        class Disjoint:
            def __init__(self):
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()
                self.shared = 0

            def start(self):
                threading.Thread(target=self._run, daemon=True).start()

            def _run(self):
                with self._a_lock:
                    self.shared = 1

            def poke(self):
                with self._b_lock:
                    self.shared = 2
    """,
}


def test_disjoint_locks_race_only_the_lockset_rule_sees():
    lex, _ = _run(DISJOINT_LOCKS, ["lock-discipline"])
    assert lex == []
    flow, _ = _run(DISJOINT_LOCKS, ["lockset-race"])
    assert [f.key for f in flow] == ["pkg/disjoint.py:Disjoint.shared"]


SEEDED_SAFE = {
    "pkg/__init__.py": "",
    "pkg/seeded.py": """
        import threading

        class Seeded:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def start(self):
                threading.Thread(target=self._run, daemon=True).start()

            def _run(self):
                with self._lock:
                    self.n += 1
                    self._bump()

            def _bump(self):
                self.n += 1

            def reset(self):
                with self._lock:
                    self._bump()
    """,
}


def test_interprocedural_seeding_clears_the_lexical_false_positive():
    """``_bump`` writes with no lexical lock, but every call site holds
    ``_lock`` — the seeded entry lockset proves the discipline the
    lexical closure rule cannot."""
    lex, _ = _run(SEEDED_SAFE, ["lock-discipline"])
    assert [f.key for f in lex] == ["pkg/seeded.py:Seeded.n"]  # lexical FP
    flow, _ = _run(SEEDED_SAFE, ["lockset-race"])
    assert flow == []

def test_lockset_allowlist_keys_are_lock_discipline_compatible():
    findings, suppressed = _run(
        RELEASED_THEN_WRITE, ["lockset-race"],
        allowlists={"lockset-race": {
            "pkg/worker.py:Worker.count": "fixture: sanctioned"}})
    assert findings == []          # suppressed, and the entry not stale
    assert [f.key for f in suppressed] == ["pkg/worker.py:Worker.count"]


# -- de-duplication (lockset wins) -------------------------------------------

PLAIN_RACE = {
    "pkg/__init__.py": "",
    "pkg/plain.py": """
        import threading

        class Plain:
            def __init__(self):
                self.v = 0

            def start(self):
                threading.Thread(target=self._run, daemon=True).start()

            def _run(self):
                self.v = 1

            def poke(self):
                self.v = 2
    """,
}


def test_shared_conflict_emits_once_lockset_wins():
    findings, _ = _run(PLAIN_RACE, ["lockset-race", "lock-discipline"])
    assert [(f.rule, f.key) for f in findings] == \
        [("lockset-race", "pkg/plain.py:Plain.v")]


def test_lock_discipline_standalone_keeps_lexical_behavior():
    findings, _ = _run(PLAIN_RACE, ["lock-discipline"])
    assert [(f.rule, f.key) for f in findings] == \
        [("lock-discipline", "pkg/plain.py:Plain.v")]


# -- lock-order-deadlock -----------------------------------------------------

AB_BA = {
    "pkg/__init__.py": "",
    "pkg/pipe.py": """
        import threading

        class Pipe:
            def __init__(self):
                self._head_lock = threading.Lock()
                self._tail_lock = threading.Lock()

            def push(self):
                with self._head_lock:
                    with self._tail_lock:
                        pass

            def pull(self):
                with self._tail_lock:
                    with self._head_lock:
                        pass
    """,
}


def test_ab_ba_cycle_reported_with_both_witness_paths():
    findings, _ = _run(AB_BA, ["lock-order-deadlock"])
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "lock-order-deadlock"
    assert f.scope.startswith("cycle:")
    assert "Pipe._head_lock" in f.scope and "Pipe._tail_lock" in f.scope
    # both witness paths, with their acquisition sites
    assert "pkg.pipe.Pipe.push" in f.message
    assert "pkg.pipe.Pipe.pull" in f.message


INTERPROC_CYCLE = {
    "pkg/__init__.py": "",
    "pkg/nested.py": """
        import threading

        class Nested:
            def __init__(self):
                self._x_lock = threading.Lock()
                self._y_lock = threading.Lock()

            def a(self):
                with self._x_lock:
                    self._grab()

            def _grab(self):
                with self._y_lock:
                    pass

            def b(self):
                with self._y_lock:
                    with self._x_lock:
                        pass
    """,
}


def test_interprocedural_cycle_found_via_entry_seeding():
    """The x->y edge exists only because ``_grab`` (acquiring y) is
    always entered holding x — a fact the call-site seeding carries
    across the function boundary."""
    findings, _ = _run(INTERPROC_CYCLE, ["lock-order-deadlock"])
    assert len(findings) == 1
    assert "Nested._x_lock" in findings[0].scope
    assert "Nested._y_lock" in findings[0].scope


REACQUIRE = {
    "pkg/__init__.py": "",
    "pkg/reacq.py": """
        import threading

        class Bad:
            def __init__(self):
                self._lock = threading.Lock()

            def f(self):
                with self._lock:
                    with self._lock:
                        pass

        class Fine:
            def __init__(self):
                self._lock = threading.RLock()

            def f(self):
                with self._lock:
                    with self._lock:
                        pass
    """,
}


def test_nonreentrant_reacquire_flagged_rlock_not():
    findings, _ = _run(REACQUIRE, ["lock-order-deadlock"])
    assert len(findings) == 1
    f = findings[0]
    assert f.scope == "self-cycle:Bad._lock"
    assert "non-reentrant" in f.message


def test_acyclic_nesting_is_clean():
    findings, _ = _run({
        "pkg/__init__.py": "",
        "pkg/ok.py": """
            import threading

            class Ok:
                def __init__(self):
                    self._outer_lock = threading.Lock()
                    self._inner_lock = threading.Lock()

                def a(self):
                    with self._outer_lock:
                        with self._inner_lock:
                            pass

                def b(self):
                    with self._outer_lock:
                        with self._inner_lock:
                            pass
        """,
    }, ["lock-order-deadlock"])
    assert findings == []


# -- barrier-flush-completeness ----------------------------------------------

BARRIER_MISS = {
    "siddhi_tpu/__init__.py": "",
    "siddhi_tpu/core/__init__.py": "",
    "siddhi_tpu/core/fx_pump.py": """
        import queue
        from collections import deque

        class Pump:
            def __init__(self):
                self._in_queue = queue.Queue(maxsize=64)
                self._out_spool = deque(maxlen=16)

            def shutdown(self):
                self._drain_in()
                return
                self._flush_out()

            def _drain_in(self):
                while True:
                    try:
                        self._in_queue.get_nowait()
                    except queue.Empty:
                        break

            def _flush_out(self):
                while self._out_spool:
                    self._out_spool.popleft()
    """,
}


def test_barrier_missing_one_queue_flush_dead_code_does_not_count():
    """``shutdown`` drains ``_in_queue`` through a helper, but the
    ``_flush_out`` call sits after a ``return`` — lexically present,
    CFG-unreachable.  Exactly the spool queue is reported."""
    findings, _ = _run(BARRIER_MISS, ["barrier-flush-completeness"])
    assert [(f.rule, f.scope) for f in findings] == \
        [("barrier-flush-completeness", "Pump.shutdown:_out_spool")]


def test_barrier_flushing_every_queue_is_clean():
    files = dict(BARRIER_MISS)
    files["siddhi_tpu/core/fx_pump.py"] = files[
        "siddhi_tpu/core/fx_pump.py"].replace(
        "self._drain_in()\n                return\n",
        "self._drain_in()\n")
    findings, _ = _run(files, ["barrier-flush-completeness"])
    assert findings == []


def test_queue_with_no_barrier_at_all_is_reported():
    findings, _ = _run({
        "siddhi_tpu/__init__.py": "",
        "siddhi_tpu/core/__init__.py": "",
        "siddhi_tpu/core/fx_hoard.py": """
            from collections import deque

            class Hoard:
                def __init__(self):
                    self._buf = deque(maxlen=8)

                def add(self, x):
                    self._buf.append(x)
        """,
    }, ["barrier-flush-completeness"])
    assert len(findings) == 1
    assert findings[0].scope == "Hoard._buf"
    assert "no barrier method" in findings[0].message


def test_out_of_scope_modules_carry_no_flush_obligation():
    files = {"pkg/__init__.py": "",
             "pkg/free.py": BARRIER_MISS[
                 "siddhi_tpu/core/fx_pump.py"]}
    findings, _ = _run(files, ["barrier-flush-completeness"])
    assert findings == []
