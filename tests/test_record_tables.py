"""Record (store-backed) table + cache tests.

Reference: query/table/store/* and cache test cases — @store tables
route CRUD/find through the AbstractRecordTable SPI with condition
push-down, optionally behind a FIFO/LRU/LFU cache.
"""

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.table import (
    AbstractRecordTable,
    InMemoryRecordStore,
    TableCache,
)
from siddhi_tpu.table.record import (
    StoreCompare,
    StoreParam,
    StoreTrue,
    evaluate_store_condition,
)


@pytest.fixture
def manager():
    m = SiddhiManager()
    yield m
    m.shutdown()


APP = (
    "define stream StockStream (symbol string, price float, volume long); "
    "define stream CheckStream (symbol string); "
    "@store(type='memory') @PrimaryKey('symbol') "
    "define table StockTable (symbol string, price float, volume long); "
    "from StockStream insert into StockTable; "
)


def start(manager, app):
    rt = manager.create_siddhi_app_runtime(app)
    got = []
    rt.add_callback("OutStream", lambda evs: got.extend(evs))
    rt.start()
    return rt, got


class TestRecordTable:
    def test_insert_and_join(self, manager):
        rt, got = start(manager, APP + (
            "from CheckStream join StockTable on CheckStream.symbol == StockTable.symbol "
            "select CheckStream.symbol as symbol, StockTable.price as price "
            "insert into OutStream;"
        ))
        rt.get_input_handler("StockStream").send(["IBM", 75.5, 100])
        rt.get_input_handler("StockStream").send(["WSO2", 57.5, 10])
        rt.get_input_handler("CheckStream").send(["IBM"])
        rt.shutdown()
        assert [e.data for e in got] == [["IBM", 75.5]]

    def test_update(self, manager):
        rt, got = start(manager, APP + (
            "define stream UpdateStream (symbol string, price float); "
            "from UpdateStream update StockTable set StockTable.price = price "
            "on StockTable.symbol == symbol; "
            "from CheckStream join StockTable on CheckStream.symbol == StockTable.symbol "
            "select StockTable.price as price insert into OutStream;"
        ))
        rt.get_input_handler("StockStream").send(["IBM", 75.5, 100])
        rt.get_input_handler("UpdateStream").send(["IBM", 100.0])
        rt.get_input_handler("CheckStream").send(["IBM"])
        rt.shutdown()
        assert [e.data for e in got] == [[100.0]]

    def test_delete(self, manager):
        rt, got = start(manager, APP + (
            "define stream DeleteStream (symbol string); "
            "from DeleteStream delete StockTable on StockTable.symbol == symbol; "
            "from CheckStream join StockTable on CheckStream.symbol == StockTable.symbol "
            "select StockTable.price as price insert into OutStream;"
        ))
        rt.get_input_handler("StockStream").send(["IBM", 75.5, 100])
        rt.get_input_handler("DeleteStream").send(["IBM"])
        rt.get_input_handler("CheckStream").send(["IBM"])
        rt.shutdown()
        assert got == []

    def test_in_table_membership(self, manager):
        rt, got = start(manager, APP + (
            "from CheckStream[CheckStream.symbol in StockTable] "
            "select symbol insert into OutStream;"
        ))
        rt.get_input_handler("StockStream").send(["IBM", 75.5, 100])
        rt.get_input_handler("CheckStream").send(["IBM"])
        rt.get_input_handler("CheckStream").send(["MSFT"])
        rt.shutdown()
        assert [e.data for e in got] == [["IBM"]]

    def test_on_demand_query(self, manager):
        rt = manager.create_siddhi_app_runtime(APP)
        rt.start()
        rt.get_input_handler("StockStream").send(["IBM", 75.5, 100])
        rt.get_input_handler("StockStream").send(["WSO2", 57.5, 10])
        events = rt.query("from StockTable select symbol, price")
        rt.shutdown()
        assert sorted(e.data[0] for e in events) == ["IBM", "WSO2"]

    def test_custom_store_spi(self, manager):
        calls = []

        class SpyStore(InMemoryRecordStore):
            def find(self, condition, params):
                calls.append(("find", condition, dict(params)))
                return super().find(condition, params)

        manager.set_extension("spy", SpyStore, kind="store")
        app = APP.replace("type='memory'", "type='spy'")
        rt = manager.create_siddhi_app_runtime(app)
        rt.start()
        rt.get_input_handler("StockStream").send(["IBM", 75.5, 100])
        rt.get_input_handler("StockStream").send(["WSO2", 57.5, 10])
        events = rt.query("from StockTable on symbol == 'IBM' select symbol, volume")
        rt.shutdown()
        assert [e.data for e in events] == [["IBM", 100]]
        # the pk-equality condition was pushed down, not a full scan
        assert any(isinstance(c[1], StoreCompare) for c in calls), calls


class TestStoreConditionIR:
    def test_evaluate(self):
        ir = StoreCompare("price", ">", StoreParam("p0"))
        assert evaluate_store_condition(ir, {"price": 10}, {"p0": 5})
        assert not evaluate_store_condition(ir, {"price": 10}, {"p0": 50})
        assert evaluate_store_condition(StoreTrue(), {"x": 1}, {})


class TestTableCache:
    def test_fifo_eviction(self):
        c = TableCache(2, "FIFO")
        c.put("a", 1)
        c.put("b", 2)
        c.get("a")
        c.put("c", 3)  # evicts 'a' (insertion order, hits irrelevant)
        assert c.get("a") is None and c.get("b") == 2 and c.get("c") == 3

    def test_lru_eviction(self):
        c = TableCache(2, "LRU")
        c.put("a", 1)
        c.put("b", 2)
        c.get("a")      # 'a' recently used
        c.put("c", 3)   # evicts 'b'
        assert c.get("b") is None and c.get("a") == 1 and c.get("c") == 3

    def test_lfu_eviction(self):
        c = TableCache(2, "LFU")
        c.put("a", 1)
        c.put("b", 2)
        c.get("a")
        c.get("a")
        c.get("b")
        c.put("c", 3)   # evicts 'b' (freq 2 < a's 3)
        assert c.get("b") is None and c.get("a") == 1 and c.get("c") == 3

    def test_cached_pk_lookup_hits(self, manager):
        app = APP.replace("@store(type='memory')",
                          "@store(type='memory', @cache(size='10', cache.policy='LRU'))")
        rt = manager.create_siddhi_app_runtime(app)
        rt.start()
        rt.get_input_handler("StockStream").send(["IBM", 75.5, 100])
        table = rt.tables["StockTable"]
        for _ in range(3):
            events = rt.query("from StockTable on symbol == 'IBM' select price")
            assert [e.data for e in events] == [[75.5]]
        rt.shutdown()
        assert table.cache.hits >= 2  # first pk probe misses, rest hit

    def test_zero_cache_size_rejected_at_creation(self, manager):
        # ADVICE r1: max_size=0 used to crash with KeyError on the first
        # put at runtime; must fail app creation with a typed error.
        from siddhi_tpu.core.exceptions import SiddhiAppCreationError

        app = APP.replace("@store(type='memory')",
                          "@store(type='memory', @cache(size='0'))")
        with pytest.raises(SiddhiAppCreationError):
            manager.create_siddhi_app_runtime(app)

    def test_shared_store_uses_shared_lock(self):
        # ADVICE r1: two store instances sharing rows must share the
        # guarding lock, else concurrent mutation from two runtimes races.
        from siddhi_tpu.query_api import AttrType
        from siddhi_tpu.query_api.attribute import Attribute
        from siddhi_tpu.query_api.definition import TableDefinition

        d = TableDefinition("SharedLockT", [Attribute("v", AttrType.LONG)])
        s1, s2 = InMemoryRecordStore(), InMemoryRecordStore()
        s1.init(d, {"shared": "true"})
        s2.init(d, {"shared": "true"})
        try:
            assert s1._rows is s2._rows
            assert s1._lock is s2._lock
        finally:
            InMemoryRecordStore._shared.pop("SharedLockT", None)
            InMemoryRecordStore._shared_locks.pop("SharedLockT", None)


class TestCacheRetention:
    """@cache(retention.period=...) — entries expire by wall time
    (reference: table/cache/CacheExpireTestCase.java; expiry is lazy on
    access + swept on insert)."""

    def test_entries_expire(self):
        from siddhi_tpu.table.record import TableCache

        clock = [1000]
        c = TableCache(10, "FIFO", retention_ms=500,
                       now_fn=lambda: clock[0])
        c.put("a", [1])
        assert c.get("a") == [1]
        clock[0] += 499
        assert c.get("a") == [1]  # just inside retention
        clock[0] += 1
        assert c.get("a") is None  # expired at exactly retention
        assert len(c) == 0

    def test_put_sweeps_expired(self):
        from siddhi_tpu.table.record import TableCache

        clock = [0]
        c = TableCache(10, "LRU", retention_ms=100,
                       now_fn=lambda: clock[0])
        c.put("a", [1])
        c.put("b", [2])
        clock[0] = 150
        c.put("c", [3])  # sweep drops a and b
        assert len(c) == 1 and c.get("c") == [3]

    def test_product_cache_expiry_misses_fall_to_store(self, manager):
        """Expired cache entries must re-fetch from the store (and the
        row is still there — retention expires the CACHE, not the
        table)."""
        import time

        app = ("@primaryKey('symbol') "
               "@store(type='memory', @cache(size='10', "
               "cache.policy='FIFO', retention.period='50 ms')) "
               "define table T (symbol string, price double); "
               "define stream S (symbol string, price double); "
               "define stream C (symbol string); "
               "from S insert into T; "
               "from C join T on T.symbol == C.symbol "
               "select T.symbol as s, T.price as p insert into Out;")
        rt = manager.create_siddhi_app_runtime(app)
        got = []
        rt.add_callback("Out", lambda evs: got.extend(e.data for e in evs))
        rt.start()
        rt.get_input_handler("S").send(["IBM", 7.0])
        rt.get_input_handler("C").send(["IBM"])
        cache = rt.tables["T"].cache
        assert len(cache) >= 1
        time.sleep(0.08)  # past retention
        rt.get_input_handler("C").send(["IBM"])  # cache miss -> store hit
        rt.shutdown()
        assert got == [["IBM", 7.0], ["IBM", 7.0]]
