"""Order-by / limit / offset conformance, ported from the reference
`query/OrderByLimitTestCase.java` (37 cases): per-chunk ordering over
single/multiple keys asc/desc, with batch windows, group-by, and
limit/offset slicing — on the host engine AND under
@app:execution('tpu') (round 5 lowers these via the host-side
passthrough selector).
"""

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager

DEFS = ("define stream StockStream (symbol string, price double, "
        "volume long); ")

ROWS = [
    ["IBM", 75.6, 100], ["WSO2", 55.6, 200], ["IBM", 75.6, 300],
    ["GOOG", 50.0, 50], ["WSO2", 57.6, 400], ["GOOG", 50.0, 150],
]


def run(app, mode="", rows=ROWS, batch=True):
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime("@app:playback " + mode + DEFS + app)
        got = []
        rt.add_callback("Out", lambda evs: got.extend(
            list(e.data) for e in evs))
        rt.start()
        h = rt.get_input_handler("StockStream")
        if batch:
            from siddhi_tpu.core.event import Event

            h.send([Event(1000 + i, list(r)) for i, r in enumerate(rows)])
        else:
            for i, r in enumerate(rows):
                h.send(list(r), timestamp=1000 + i)
        rt.shutdown()
        return got
    finally:
        m.shutdown()


MODES = ["", "@app:execution('tpu') "]


class TestOrderBy:
    @pytest.mark.parametrize("mode", MODES)
    def test_single_key_ascending(self, mode):
        # one chunk: the whole batch orders together (reference
        # per-chunk semantics)
        got = run("from StockStream select symbol, volume order by volume "
                  "insert into Out;", mode)
        assert [g[1] for g in got] == [50, 100, 150, 200, 300, 400]

    @pytest.mark.parametrize("mode", MODES)
    def test_single_key_descending(self, mode):
        got = run("from StockStream select symbol, volume "
                  "order by volume desc insert into Out;", mode)
        assert [g[1] for g in got] == [400, 300, 200, 150, 100, 50]

    @pytest.mark.parametrize("mode", MODES)
    def test_multi_key_mixed_directions(self, mode):
        got = run("from StockStream select symbol, price, volume "
                  "order by price asc, volume desc insert into Out;", mode)
        assert [(g[0], g[2]) for g in got] == [
            ("GOOG", 150), ("GOOG", 50), ("WSO2", 200),
            ("WSO2", 400), ("IBM", 300), ("IBM", 100)]

    @pytest.mark.parametrize("mode", MODES)
    def test_string_key(self, mode):
        got = run("from StockStream select symbol, volume "
                  "order by symbol insert into Out;", mode)
        assert [g[0] for g in got] == sorted(r[0] for r in ROWS)

    def test_per_event_sends_order_within_chunk_only(self):
        # per-event sends = one-row chunks: ordering is a no-op
        # (reference: ordering applies within each output chunk)
        got = run("from StockStream select symbol, volume "
                  "order by volume insert into Out;", batch=False)
        assert [g[1] for g in got] == [r[2] for r in ROWS]


class TestLimitOffset:
    @pytest.mark.parametrize("mode", MODES)
    def test_limit(self, mode):
        got = run("from StockStream select symbol, volume "
                  "order by volume desc limit 3 insert into Out;", mode)
        assert [g[1] for g in got] == [400, 300, 200]

    @pytest.mark.parametrize("mode", MODES)
    def test_limit_offset(self, mode):
        got = run("from StockStream select symbol, volume "
                  "order by volume desc limit 2 offset 2 "
                  "insert into Out;", mode)
        assert [g[1] for g in got] == [200, 150]

    @pytest.mark.parametrize("mode", MODES)
    def test_offset_beyond_rows_empty(self, mode):
        got = run("from StockStream select symbol, volume "
                  "order by volume limit 5 offset 50 insert into Out;",
                  mode)
        assert got == []

    @pytest.mark.parametrize("mode", MODES)
    def test_limit_without_order_by(self, mode):
        got = run("from StockStream select symbol, volume limit 2 "
                  "insert into Out;", mode)
        assert [g[1] for g in got] == [100, 200]


class TestWithWindowsAndGroups:
    @pytest.mark.parametrize("mode", MODES)
    def test_length_batch_group_by_order(self, mode):
        got = run(
            "from StockStream#window.lengthBatch(6) "
            "select symbol, sum(volume) as t group by symbol "
            "order by t desc insert into Out;", mode)
        assert got == [["WSO2", 600], ["IBM", 400], ["GOOG", 200]]

    @pytest.mark.parametrize("mode", MODES)
    def test_length_batch_group_by_limit(self, mode):
        got = run(
            "from StockStream#window.lengthBatch(6) "
            "select symbol, sum(volume) as t group by symbol "
            "order by t desc, symbol asc limit 1 insert into Out;", mode)
        assert got == [["WSO2", 600]]

    def test_unknown_order_attribute_rejected(self):
        from siddhi_tpu.core.exceptions import SiddhiAppCreationError

        m = SiddhiManager()
        try:
            with pytest.raises(SiddhiAppCreationError):
                m.create_siddhi_app_runtime(
                    DEFS + "from StockStream select symbol "
                    "order by nope insert into Out;")
        finally:
            m.shutdown()
