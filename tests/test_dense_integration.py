"""Dense (jitted) pattern path integrated in the product engine.

`@app:execution('tpu')` routes eligible pattern queries created through
the public SiddhiManager API onto the dense NFA (ops/dense_nfa.py) —
asserted via the runtime's step-invocation counter — with host-engine
fallback for queries outside the dense subset.  Reference analog: the
planner wiring the pattern hot path
(util/parser/StateInputStreamParser.java:76-146).
"""

import contextlib

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.dense_pattern import DensePatternRuntime

TPU = "@app:execution('tpu') "


@pytest.fixture
def manager():
    m = SiddhiManager()
    yield m
    m.shutdown()


def run_app(manager, app, sends, out="Alerts", stream="Txn",
            transfer_guard=False):
    rt = manager.create_siddhi_app_runtime(app)
    got = []
    rt.add_callback(out, lambda evs: got.extend(e.data for e in evs))
    rt.start()
    h = rt.get_input_handler(stream)
    # transfer_guard: device↔host crossings in the event loop must be
    # explicit (staged_put in, device_get on the drain) — the dynamic
    # twin of the host-sync-hazard analysis rule.  No-op on the CPU
    # backend; bites on real accelerator runs.
    guard = contextlib.nullcontext()
    if transfer_guard:
        import jax

        guard = jax.transfer_guard("disallow")
    with guard:
        for row, ts in sends:
            h.send(row, timestamp=ts)
    rt.shutdown()
    return rt, got


PATTERN_APP = (
    "define stream Txn (card long, amount double); "
    "@info(name='q') "
    "from every a=Txn[amount > 100.0] -> b=Txn[amount > a.amount] "
    "within 10 min "
    "select a.amount as base, b.amount as bv insert into Alerts;"
)

SENDS = [
    ([1, 150.0], 1000),
    ([1, 90.0], 1500),    # matches neither filter
    ([1, 200.0], 2000),   # completes a->b
    ([1, 300.0], 3000),   # next every cycle: 200 armed? (host semantics)
]


class TestDensePath:
    def test_dense_path_executes_jitted_step(self, manager):
        rt, got = run_app(manager, TPU + PATTERN_APP, SENDS)
        qr = rt.query_runtimes["q"]
        assert isinstance(qr.pattern_processor, DensePatternRuntime)
        assert qr.pattern_processor.step_invocations == len(SENDS)
        assert got  # matches flowed through selector/output to callback

    def test_dense_matches_host_output(self, manager):
        # non-`every` pattern: dense and host semantics coincide exactly
        # (overlapping-`every` instances are the multi-instance work —
        # see test_dense_nfa for the dense-subset contract)
        app = PATTERN_APP.replace("from every a=", "from a=")
        _rt, dense = run_app(manager, TPU + app, SENDS)
        m2 = SiddhiManager()
        _rt2, host = run_app(m2, app, SENDS)
        m2.shutdown()
        assert dense == host == [[150.0, 200.0]]

    def test_dense_every_rearm_matches_host(self, manager):
        # `every`: a match must consume only the matched instance — the
        # completing event re-arms the start in the SAME step, so the
        # next event completes again (reset-on-emit would lose it)
        _rt, dense = run_app(manager, TPU + PATTERN_APP, SENDS,
                             transfer_guard=True)
        m2 = SiddhiManager()
        _rt2, host = run_app(m2, PATTERN_APP, SENDS)
        m2.shutdown()
        assert dense == host == [[150.0, 200.0], [200.0, 300.0]]

    def test_fallback_on_long_filter_operand(self, manager):
        # LONG filter comparisons ride the bit-exact hi/lo int32 pair
        # bank — values one apart above 2^24 (where float32 would
        # collide) still distinguish, ON the dense path
        app = TPU + (
            "define stream Txn (card long, amount double); "
            "@info(name='q') "
            "from a=Txn[card == 16777217] -> b=Txn[amount > a.amount] "
            "select a.amount as base, b.amount as bv insert into Alerts;"
        )
        rt, got = run_app(manager, app, [
            ([16777216, 150.0], 1000),   # NOT the filtered card value
            ([16777217, 140.0], 1500),
            ([16777217, 200.0], 2000),
        ])
        assert isinstance(
            rt.query_runtimes["q"].pattern_processor, DensePatternRuntime)
        assert got == [[140.0, 200.0]]  # exact dense comparison

    def test_host_mode_untouched(self, manager):
        rt, _ = run_app(manager, PATTERN_APP, SENDS)
        assert not isinstance(
            rt.query_runtimes["q"].pattern_processor, DensePatternRuntime)

    def test_trailing_absent_lowers_dense(self, manager):
        # round 4: `not X for t` rides deadline registers + the jitted
        # timer step (see tests/test_dense_absent.py for the semantics
        # corpus); only leading/sequence absent still falls back
        app = TPU + (
            "define stream A (v double); define stream B (v double); "
            "@info(name='q') from A -> not B for 1 sec "
            "select a.v as av insert into Alerts;"
        ).replace("from A ->", "from a=A ->")
        rt = manager.create_siddhi_app_runtime(app)
        proc = rt.query_runtimes["q"].pattern_processor
        assert isinstance(proc, DensePatternRuntime)
        assert proc.engine.has_deadlines

    def test_fallback_on_string_capture(self, manager):
        app = TPU + (
            "define stream Txn (card string, amount double); "
            "@info(name='q') "
            "from every a=Txn[amount > 100.0] -> b=Txn[card == a.card] "
            "select a.amount as base insert into Alerts;"
        )
        rt = manager.create_siddhi_app_runtime(app)
        assert not isinstance(
            rt.query_runtimes["q"].pattern_processor, DensePatternRuntime)

    def test_aggregating_selector_lowers_dense_with_host_selector(self, manager):
        """Group-by/aggregating pattern selectors lower densely: the
        engine emits raw capture columns and the host QuerySelector
        aggregates the (sparse) match rows — output matches host mode."""
        app = (
            "define stream Txn (card long, amount double); "
            "@info(name='q') "
            "from every a=Txn[amount > 100.0] -> b=Txn[amount > a.amount] "
            "within 10 min "
            "select a.amount as base, sum(b.amount) as total "
            "group by a.amount insert into Alerts;"
        )
        sends = [([1, 150.0], 1000), ([1, 200.0], 2000),
                 ([1, 300.0], 3000), ([1, 120.0], 3500),
                 ([1, 400.0], 4000)]
        rt, dense = run_app(manager, TPU + app, sends)
        assert isinstance(
            rt.query_runtimes["q"].pattern_processor, DensePatternRuntime)
        m2 = SiddhiManager()
        _rt2, host = run_app(m2, app, sends)
        m2.shutdown()
        assert dense == host and len(host) > 0

    def test_partitioned_aggregating_selector_per_key_sums(self, manager):
        """Round-4: the partitioned aggregating form runs dense with ONE
        shared selector keyed by the partition-key side channel — sums
        must stay per key, never pooled (host parity)."""
        app = (
            "define stream Txn (card string, amount double); "
            "partition with (card of Txn) begin "
            "@info(name='q') from every a=Txn[amount > 100.0] "
            "-> b=Txn[amount > a.amount] within 10 min "
            "select sum(b.amount) as t insert into Alerts; end;")
        sends = [(["c1", 150.0], 1000), (["c2", 500.0], 1100),
                 (["c1", 200.0], 2000), (["c2", 600.0], 2100)]
        _rt, dense_mode = run_app(
            manager, "@app:execution('tpu', partitions='64') " + app, sends)
        m2 = SiddhiManager()
        _rt2, host = run_app(m2, app, sends)
        m2.shutdown()
        # per-key sums: c1 gets 200, c2 gets 600 — never pooled
        assert dense_mode == host == [[200.0], [600.0]]

    def test_dense_persist_restore(self, manager):
        rt = manager.create_siddhi_app_runtime(TPU + PATTERN_APP)
        got = []
        rt.add_callback("Alerts", lambda evs: got.extend(e.data for e in evs))
        rt.start()
        h = rt.get_input_handler("Txn")
        h.send([1, 150.0], timestamp=1000)      # arms a=150
        snap = rt.snapshot()
        h.send([1, 200.0], timestamp=2000)      # completes
        assert got == [[150.0, 200.0]]
        rt.restore(snap)                         # back to armed-only
        h.send([1, 180.0], timestamp=3000)
        assert got == [[150.0, 200.0], [150.0, 180.0]]
        rt.shutdown()


PARTITIONED_APP = (
    "@app:execution('tpu', partitions='64') "
    "define stream Txn (card string, amount double); "
    "partition with (card of Txn) begin "
    "@info(name='q') "
    "from every a=Txn[amount > 100.0] -> b=Txn[amount > a.amount] "
    "within 10 min "
    "select a.amount as base, b.amount as bv insert into Alerts; "
    "end;"
)


class TestDensePartition:
    def test_partition_lowered_to_one_engine(self, manager):
        rt, got = run_app(manager, PARTITIONED_APP, [
            (["c1", 150.0], 1000),
            (["c2", 500.0], 1100),
            (["c1", 200.0], 2000),   # completes c1
            (["c2", 400.0], 2100),   # not b for 500; arms its own 'every'
            (["c2", 600.0], 2200),   # completes BOTH c2 arms (500 and 400)
        ])
        pr = rt.partitions["partition_0"]
        assert pr.is_dense
        # host-exact since the instance axis: overlapping every arms both
        # match (arming-age order), where the old engine dropped [400, 600]
        assert got == [[150.0, 200.0], [500.0, 600.0], [400.0, 600.0]]
        runtime = next(iter(pr.dense_query_runtimes.values())).pattern_processor
        assert runtime.step_invocations == 5
        assert len(runtime._key_rows) == 2

    def test_partition_matches_host_instances(self, manager):
        # per-key isolation with non-`every` patterns: each key matches
        # once independently, identical to per-key host instances
        sends = [
            (["c1", 150.0], 1000),
            (["c2", 500.0], 1100),
            (["c1", 90.0], 1500),    # c1: matches neither filter
            (["c1", 200.0], 2000),   # completes c1
            (["c2", 600.0], 2200),   # completes c2
            (["c3", 90.0], 2300),    # never arms
        ]
        app = PARTITIONED_APP.replace("from every a=", "from a=")
        _rt, dense = run_app(manager, app, sends)
        m2 = SiddhiManager()
        host_app = app.replace("@app:execution('tpu', partitions='64') ", "")
        _rt2, host = run_app(m2, host_app, sends)
        m2.shutdown()
        assert sorted(map(tuple, dense)) == sorted(map(tuple, host))
        assert len(dense) == 2

    def test_partition_key_capacity_enforced(self, manager):
        app = PARTITIONED_APP.replace("partitions='64'", "partitions='2'")
        rt = manager.create_siddhi_app_runtime(app)
        rt.start()
        h = rt.get_input_handler("Txn")
        h.send(["c1", 150.0], timestamp=1000)
        h.send(["c2", 150.0], timestamp=1001)
        errors = []
        rt.app_context.exception_listeners.append(
            lambda e: errors.append(e))
        h.send(["c3", 150.0], timestamp=1002)  # third key exceeds cap 2
        rt.shutdown()
        assert errors  # routed to the app's exception listeners

    def test_partition_general_query_lowers_to_device(self, manager):
        # round 5: general (non-pattern) partition bodies lower to the
        # device query engine with the key composed into the group axis
        # (previously they fell back to per-key instances)
        app = (
            "@app:execution('tpu') "
            "define stream S (k string, v double); "
            "partition with (k of S) begin "
            "@info(name='q') from S select k, sum(v) as total "
            "insert into Out; end;"
        )
        rt, got = run_app(manager, app, [
            (["a", 1.0], 10), (["a", 2.0], 20), (["b", 5.0], 30),
        ], out="Out", stream="S")
        pr = rt.partitions["partition_0"]
        assert pr.is_dense
        assert pr.query_lowering() == {"q": "device"}
        assert got == [["a", 1.0], ["a", 3.0], ["b", 5.0]]

    def test_partition_dense_persist_restore(self, manager):
        rt = manager.create_siddhi_app_runtime(PARTITIONED_APP)
        got = []
        rt.add_callback("Alerts", lambda evs: got.extend(e.data for e in evs))
        rt.start()
        h = rt.get_input_handler("Txn")
        h.send(["c1", 150.0], timestamp=1000)
        snap = rt.snapshot()
        h.send(["c1", 200.0], timestamp=2000)
        assert got == [[150.0, 200.0]]
        rt.restore(snap)
        h.send(["c1", 180.0], timestamp=3000)
        assert got == [[150.0, 200.0], [150.0, 180.0]]
        rt.shutdown()


class TestReviewRegressions:
    def test_long_capture_lowers_dense_and_exact(self, manager):
        """LONG captures/selects ride the hi/lo int32 pair bank: the
        card-number query lowers densely and round-trips bit-exact far
        above 2^24 (round-3 verdict item 6's done-criterion)."""
        app = TPU + (
            "define stream Txn (card long, amount double); "
            "@info(name='q') "
            "from a=Txn[amount > 100.0] -> b=Txn[amount > a.amount] "
            "select a.card as card, b.amount as bv insert into Alerts;"
        )
        rt, got = run_app(manager, app, [
            ([4111111111111111, 150.0], 1000),
            ([4111111111111111, 200.0], 2000),
        ])
        assert isinstance(
            rt.query_runtimes["q"].pattern_processor, DensePatternRuntime)
        assert got == [[4111111111111111, 200.0]]  # exact on the dense path

    def test_partitions_element_validated(self, manager):
        import pytest as _pytest

        from siddhi_tpu.core.exceptions import SiddhiAppCreationError

        for bad in ("0", "-5", "abc"):
            with _pytest.raises(SiddhiAppCreationError):
                manager.create_siddhi_app_runtime(
                    f"@app:execution('tpu', partitions='{bad}') "
                    "define stream S (v double); "
                    "@info(name='q') from a=S -> b=S "
                    "select a.v as av insert into Out;")

    def test_purge_reclaims_idle_key_rows(self, manager):
        """@purge on a dense partition recycles idle key rows, so key
        churn beyond capacity keeps working (host analog: idle
        PartitionInstance purge)."""
        app = (
            "@app:playback "
            "@app:execution('tpu', partitions='4') "
            "define stream Txn (card string, amount double); "
            "@purge(enable='true', interval='1 sec', idle.period='2 sec') "
            "partition with (card of Txn) begin "
            "@info(name='q') "
            "from a=Txn[amount > 100.0] -> b=Txn[amount > a.amount] "
            "select a.amount as base, b.amount as bv insert into Alerts; "
            "end;"
        )
        rt = manager.create_siddhi_app_runtime(app)
        got = []
        rt.add_callback("Alerts", lambda evs: got.extend(e.data for e in evs))
        rt.start()
        h = rt.get_input_handler("Txn")
        # 4 distinct keys fill capacity
        for i, k in enumerate(["a", "b", "c", "d"]):
            h.send([k, 150.0], timestamp=1000 + i)
        pr = rt.partitions["partition_0"]
        runtime = next(iter(pr.dense_query_runtimes.values())).pattern_processor
        assert len(runtime._key_rows) == 4
        # playback time advances far past idle.period; purge fires on the
        # watermark advance
        h.send(["a", 90.0], timestamp=20_000)  # keeps 'a' alive, no arm
        rt.scheduler.advance(20_001)
        assert len(runtime._key_rows) < 4
        # a fresh key now fits again and completes a match
        h.send(["e", 150.0], timestamp=21_000)
        h.send(["e", 250.0], timestamp=21_500)
        assert [150.0, 250.0] in got
        rt.shutdown()


class TestPartitionedAggregatingSelector:
    """Round-4: partitioned aggregating pattern selectors run dense with
    ONE shared QuerySelector keeping per-(key, group) state via the
    partition-key side channel (host analog: per-key selector
    instances)."""

    APP_BODY = (
        "define stream Txn (card string, amount double); "
        "partition with (card of Txn) begin "
        "@info(name='q') from every a=Txn[amount > 100.0] -> "
        "b=Txn[amount > a.amount] "
        "select count() as n, sum(b.amount) as total "
        "having n >= 1 insert into Alerts; "
        "end;"
    )

    def _drive(self, header, sends):
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(header + self.APP_BODY)
            got = []
            rt.add_callback(
                "Alerts", lambda evs: got.extend(list(e.data) for e in evs))
            rt.start()
            h = rt.get_input_handler("Txn")
            for row, ts in sends:
                h.send(row, timestamp=ts)
            pr = rt.partitions.get("partition_0")
            runtime = (next(iter(pr.dense_query_runtimes.values()))
                       .pattern_processor
                       if pr is not None and getattr(pr, "is_dense", False)
                       else None)
            rt.shutdown()
            return got, runtime
        finally:
            m.shutdown()

    def test_per_key_aggregation_matches_host(self):
        rng = np.random.default_rng(23)
        sends = []
        t = 1000
        for _ in range(50):
            k = f"c{int(rng.integers(0, 5))}"
            t += int(rng.integers(1, 30))
            sends.append(([k, float(rng.integers(50, 400))], t))
        host, hproc = self._drive("@app:playback ", sends)
        dense, dproc = self._drive(
            "@app:playback @app:execution('tpu', partitions='16') ", sends)
        assert hproc is None
        assert isinstance(dproc, DensePatternRuntime)
        assert dproc.step_invocations > 0
        # equality against the host's PER-KEY selector instances proves
        # the shared selector isolates state per partition key (pooled
        # counts/sums would diverge immediately)
        assert dense == host
        assert len(host) > 0
        assert max(n for n, _t in dense) > 1  # some key aggregated twice


class TestPartitionedAggregatingPurge:
    def test_purged_key_selector_state_resets(self):
        # idle purge must reset a key's AGGREGATION state too: after the
        # purge, count() restarts at 1 exactly like the host per-key
        # instance form (review finding r4)
        app = (
            "@app:playback "
            "define stream Txn (card string, amount double); "
            "@purge(enable='true', interval='1 sec', idle.period='2 sec') "
            "partition with (card of Txn) begin "
            "@info(name='q') from every a=Txn[amount > 100.0] -> "
            "b=Txn[amount > a.amount] "
            "select count() as n insert into Alerts; "
            "end;"
        )
        sends = [
            (["c1", 150.0], 1000), (["c1", 200.0], 1100),   # match: n=1
            (["c1", 150.0], 6000),                          # purged; re-arm
            (["c1", 200.0], 6100),                          # match: n=1 again
        ]

        def drive(header):
            m = SiddhiManager()
            try:
                rt = m.create_siddhi_app_runtime(header + app)
                got = []
                rt.add_callback(
                    "Alerts", lambda evs: got.extend(list(e.data) for e in evs))
                rt.start()
                h = rt.get_input_handler("Txn")
                for row, ts in sends:
                    h.send(row, timestamp=ts)
                rt.shutdown()
                return got
            finally:
                m.shutdown()

        host = drive("")
        dense = drive("@app:execution('tpu', partitions='16') ")
        assert dense == host == [[1], [1]]

    def test_partitioned_rate_limit_falls_back(self, manager):
        # per-key limiters cannot share one dense limiter — host used
        app = (
            "@app:execution('tpu', partitions='16') "
            "define stream Txn (card string, amount double); "
            "partition with (card of Txn) begin "
            "@info(name='q') from every a=Txn[amount > 100.0] -> "
            "b=Txn[amount > a.amount] "
            "select a.amount as av output every 2 events "
            "insert into Alerts; end;")
        rt = manager.create_siddhi_app_runtime(app)
        pr = rt.partitions.get("partition_0")
        assert pr is not None and not getattr(pr, "is_dense", False)


class TestGroupEveryDense:
    def test_whole_chain_group_every_lowers(self, manager):
        # `every (e1 -> e2)`: one arm at a time, re-armed at completion
        # and after within-expiry (WithinPatternTestCase.testQuery4/6)
        app = TPU + (
            "define stream T (v double, w long); "
            "@info(name='q') from every (a=T[v > 1.0] -> "
            "b=T[w == a.w]) within 5 sec "
            "select a.v as av, b.v as bv insert into Alerts;")
        rt, got = run_app(manager, app, [
            ([5.0, 7], 1000),
            ([6.0, 7], 7000),    # first arm expired; fresh arm
            ([7.0, 7], 7500),    # completes (6, 7)
            ([8.0, 7], 7510),    # new arm
        ], stream="T")
        proc = rt.query_runtimes["q"].pattern_processor
        assert isinstance(proc, DensePatternRuntime)
        assert proc.engine.group_every and proc.engine.I == 1
        assert got == [[6.0, 7.0]]

    def test_partial_chain_group_every_falls_back(self, manager):
        app = TPU + (
            "define stream T (v double, w long); "
            "@info(name='q') from every (a=T[v > 1.0] -> b=T[v > a.v]) "
            "-> c=T[v > b.v] "
            "select a.v as av, c.v as cv insert into Alerts;")
        rt = manager.create_siddhi_app_runtime(app)
        assert not isinstance(
            rt.query_runtimes["q"].pattern_processor, DensePatternRuntime)


class TestOverflowSignal:
    def test_dropped_instances_reach_exception_listeners(self, manager):
        """Instance-lane overflow (real matches possibly lost) must be a
        USER-VISIBLE signal — a WARNING log plus the app's exception
        listeners — not just an internal counter (the overflow policy
        is documented at ops/dense_nfa.py:39-47)."""
        import logging

        app = (
            "@app:playback @app:execution('tpu', instances='1') "
            "define stream S (k string, v double); "
            "@info(name='q') from every a=S[v > 0.0] -> b=S[v > 100.0] "
            "within 10 min select a.v as av, b.v as bv insert into Out;"
        )
        rt = manager.create_siddhi_app_runtime(app)
        seen = []
        rt.add_exception_listener(seen.append)
        rt.start()
        h = rt.get_input_handler("S")
        logger = logging.getLogger("siddhi_tpu")
        records = []
        handler = logging.Handler()
        handler.emit = lambda r: records.append(r)
        logger.addHandler(handler)
        try:
            # 'every' arms a new pending instance per event; with a
            # single lane, the second arm drops a pending instance
            for i in range(400):
                h.send(["u", 1.0 + i], timestamp=1000 + i)
            rt.shutdown()  # close() runs the final overflow check
        finally:
            logger.removeHandler(handler)
        qr = rt.query_runtimes["q"]
        stats = qr.pattern_processor.stats()
        assert stats["dropped_instances"] > 0  # overflow really happened
        assert seen, "exception listeners must observe dropped matches"
        assert "dropped" in str(seen[0])
        assert any("dropped" in r.getMessage() for r in records)
