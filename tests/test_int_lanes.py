"""Integer (INT/LONG) lanes in the dense NFA: bit-exact at any magnitude.

Round-3 verdict item 6: INT/LONG attributes forced host fallback because
the register bank was float32.  They now ride hi/lo int32 pairs —
captures, selects, and plain comparisons (==, !=, <, <=, >, >=) are
bit-exact far above 2^24 and 2^53, matching the reference's per-type
executors (executor/math/, condition/compare/); integer arithmetic
still falls back.  Every case here runs host vs @app:execution('tpu')
through the public API and requires identical output.
"""

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.dense_pattern import DensePatternRuntime

TPU = "@app:playback @app:execution('tpu') "


def run(app, sends, mode_tpu, stream="S", out="Alerts"):
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime(
            (TPU if mode_tpu else "@app:playback ") + app)
        got = []
        rt.add_callback(out, lambda evs: got.extend(e.data for e in evs))
        rt.start()
        h = rt.get_input_handler(stream)
        for row, ts in sends:
            h.send(row, timestamp=ts)
        qr = next(iter(rt.query_runtimes.values()))
        runtime = getattr(qr, "pattern_processor", None)
        rt.shutdown()
        return got, runtime
    finally:
        m.shutdown()


def differential(app, sends, **kw):
    host, _ = run(app, sends, mode_tpu=False, **kw)
    dense, runtime = run(app, sends, mode_tpu=True, **kw)
    assert isinstance(runtime, DensePatternRuntime), "did not lower densely"
    assert dense == host, f"dense {dense} != host {host}"
    return host


BIG = 4_111_111_111_111_111          # 16-digit card number, > 2^32
HUGE = 9_220_000_000_000_000_123     # near int64 max, > 2^53
NEG = -9_220_000_000_000_000_123


class TestIntCaptureSelect:
    def test_long_capture_roundtrip_above_2p53(self):
        app = ("define stream S (card long, v double); "
               "@info(name='q') from a=S[v > 1.0] -> b=S[v > a.v] "
               "select a.card as c, b.v as bv insert into Alerts;")
        host = differential(app, [
            ([HUGE, 2.0], 1000),
            ([HUGE - 1, 3.0], 1100),
        ])
        assert host == [[HUGE, 3.0]]

    def test_negative_long_roundtrip(self):
        app = ("define stream S (card long, v double); "
               "@info(name='q') from a=S[v > 1.0] -> b=S[v > a.v] "
               "select a.card as c insert into Alerts;")
        host = differential(app, [([NEG, 2.0], 1000), ([0, 3.0], 1100)])
        assert host == [[NEG]]

    def test_int_candidate_select_from_last_node(self):
        app = ("define stream S (n int, v double); "
               "@info(name='q') from a=S[v > 1.0] -> b=S[v > a.v] "
               "select a.n as an, b.n as bn insert into Alerts;")
        host = differential(app, [
            ([2_000_000_001, 2.0], 1000),
            ([2_000_000_002, 3.0], 1100),
        ])
        assert host == [[2_000_000_001, 2_000_000_002]]


class TestIntCompares:
    def test_equality_join_on_long_id(self):
        # the canonical CEP id-join: b[card == a.card]
        app = ("define stream S (card long, v double); "
               "@info(name='q') from every a=S[v > 100.0] "
               "-> b=S[card == a.card] within 10 min "
               "select a.v as av, b.v as bv insert into Alerts;")
        host = differential(app, [
            ([BIG, 150.0], 1000),
            ([BIG + 1, 50.0], 1100),   # adjacent id must NOT join
            ([BIG, 60.0], 1200),       # joins
        ])
        assert host == [[150.0, 60.0]]

    def test_ordering_compares_cross_word_boundary(self):
        # hi words equal, lo words differ across the 2^31 bias point —
        # the (hi, lo-biased) lexicographic order must hold
        base = (7 << 32)
        lo_small = base + 5
        lo_big = base + 0x8000_0005  # low word crosses the sign bit
        app = ("define stream S (seq long, v double); "
               "@info(name='q') from every a=S[v > 0.0] "
               "-> b=S[seq > a.seq] within 10 min "
               "select a.seq as sa, b.seq as sb insert into Alerts;")
        host = differential(app, [
            ([lo_big, 1.0], 1000),
            ([lo_small, 1.0], 1100),  # smaller: not b for first arm
            ([lo_big + 1, 1.0], 1200),
        ])
        assert [r[:2] for r in host] == [
            [lo_big, lo_big + 1], [lo_small, lo_big + 1]]

    def test_long_constant_compare(self):
        app = ("define stream S (card long, v double); "
               f"@info(name='q') from a=S[card == {BIG}] -> b=S[v > a.v] "
               "select a.card as c, b.v as bv insert into Alerts;")
        host = differential(app, [
            ([BIG + 1, 1.0], 1000),   # adjacent id: must not arm
            ([BIG, 1.0], 1100),
            ([0, 2.0], 1200),
        ])
        assert host == [[BIG, 2.0]]

    def test_negative_vs_positive_ordering(self):
        app = ("define stream S (x long, v double); "
               "@info(name='q') from every a=S[v > 0.0] -> b=S[x < a.x] "
               "within 10 min select a.x as ax, b.x as bx "
               "insert into Alerts;")
        host = differential(app, [
            ([5, 1.0], 1000),
            ([-3, 1.0], 1100),       # -3 < 5: completes first arm
        ])
        assert host[0] == [5, -3]


class TestIntFallbacks:
    def test_int_literal_on_float_lane_stays_dense(self):
        """An unsuffixed integer literal against a double attribute —
        [v > 100] — is the commonest filter shape; it must stay ON the
        dense path (review regression)."""
        app = ("define stream S (v double); "
               "@info(name='q') from every a=S[v > 100] -> b=S[v > a.v] "
               "within 10 min select a.v as av, b.v as bv "
               "insert into Alerts;")
        host = differential(app, [([150.0], 1000), ([200.0], 1100)])
        assert host == [[150.0, 200.0]]

    def test_string_select_falls_back_not_zero(self):
        """A STRING select item has no device lane: the query must fall
        back to the host engine, not emit 0.0 (review regression)."""
        app = ("define stream S (name string, v double); "
               "@info(name='q') from every a=S[v > 1.0] -> b=S[v > a.v] "
               "within 10 min select a.name as nm, b.v as bv "
               "insert into Alerts;")
        got, runtime = run(app, [(["alice", 2.0], 1000),
                                 (["bob", 3.0], 1100)], mode_tpu=True)
        assert not isinstance(runtime, DensePatternRuntime)
        assert got == [["alice", 3.0]]

    def test_integer_arithmetic_falls_back(self):
        app = ("define stream S (n long, v double); "
               "@info(name='q') from every a=S[v > 0.0] "
               "-> b=S[n == a.n + 1] within 10 min "
               "select a.v as av insert into Alerts;")
        _got, runtime = run(app, [([1, 1.0], 1000)], mode_tpu=True)
        assert not isinstance(runtime, DensePatternRuntime)

    def test_int_float_mixed_compare_falls_back(self):
        app = ("define stream S (n long, v double); "
               "@info(name='q') from every a=S[v > 0.0] -> b=S[v > a.n] "
               "within 10 min select a.v as av insert into Alerts;")
        _got, runtime = run(app, [([1, 1.0], 1000)], mode_tpu=True)
        assert not isinstance(runtime, DensePatternRuntime)


class TestIntPartitionedSharded:
    def test_long_id_join_partitioned_and_sharded(self):
        app = (
            "define stream S (user string, sess long, v double); "
            "partition with (user of S) begin "
            "@info(name='q') from every a=S[v > 10.0] "
            "-> b=S[sess == a.sess] within 10 min "
            "select a.sess as sa, b.v as bv insert into Alerts; end;")
        sends = [(["u1", HUGE, 20.0], 1000),
                 (["u2", BIG, 30.0], 1100),
                 (["u1", HUGE, 5.0], 1200),     # joins u1's arm
                 (["u2", BIG + 1, 5.0], 1300),  # wrong session: no join
                 (["u2", BIG, 6.0], 1400)]      # joins u2's arm

        def run_p(header):
            m = SiddhiManager()
            try:
                rt = m.create_siddhi_app_runtime(header + app)
                got = []
                rt.add_callback("Alerts",
                                lambda evs: got.extend(e.data for e in evs))
                rt.start()
                h = rt.get_input_handler("S")
                for row, ts in sends:
                    h.send(row, timestamp=ts)
                rt.shutdown()
                return got
            finally:
                m.shutdown()

        host = run_p("@app:playback ")
        dense = run_p("@app:playback @app:execution('tpu', partitions='64') ")
        sharded = run_p("@app:playback @app:execution('tpu', "
                        "partitions='64', devices='8') ")
        assert dense == host == [[HUGE, 5.0], [BIG, 6.0]]
        assert sharded == host
