"""Pallas kernel layer (siddhi_tpu/kernels/): bit-identity + gating.

Every kernel is pinned bit-identical to the XLA formulation it
replaces (on CPU the kernels run under ``interpret=True`` — semantics
-exact, which is what makes these differentials meaningful without a
TPU).  The planner gates are exercised both ways: eligible queries
must actually lower to the kernel (asserted via ``lowered_to``), and
every ineligible/unavailable case must fall back gracefully with a
counted ``kernelFallbackReason`` — never an error, never silently.
"""

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.exceptions import SiddhiAppCreationError
from siddhi_tpu.query_api import AttrType

DEFINE = "define stream S (k long, u double, v double); "

# capture-free chain: the class the packed-plane NFA kernel covers
ELIGIBLE = ("@info(name='q') from every a=S[v > 8.0] -> b=S[v > 12.0] "
            "within 3 sec select b.v as bv insert into Alerts;")

# b's filter captures a.v -> needs the register file -> NFA fallback
CAPTURING = ("@info(name='q') from every a=S[v > 8.0] -> b=S[v > a.v] "
             "within 3 sec select a.v as av, b.v as bv "
             "insert into Alerts;")


def gen_stream(seed, n=60):
    rng = np.random.default_rng(seed)
    ts = 1000 + np.cumsum(rng.integers(1, 400, size=n))
    ks = rng.integers(0, 3, size=n)
    us = rng.uniform(0.0, 20.0, size=n).round(1)
    vs = rng.uniform(0.0, 20.0, size=n).round(1)
    return [([int(k), float(u), float(v)], int(t))
            for k, u, v, t in zip(ks, us, vs, ts)]


def run_app(header, app, sends):
    """-> (rows, lowered_to, statistics_manager)."""
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime(header + DEFINE + app)
        got = []
        rt.add_callback("Alerts", lambda evs: got.extend(e.data for e in evs))
        rt.start()
        h = rt.get_input_handler("S")
        for row, ts in sends:
            h.send(row, timestamp=ts)
        qr = next(iter(rt.query_runtimes.values()), None)
        lowered = getattr(qr, "lowered_to", None)
        sm = rt.app_context.statistics_manager
        rt.shutdown()
        return got, lowered, sm
    finally:
        m.shutdown()


TPU = "@app:playback @app:execution('tpu', instances='16') "


class TestProbeAndPlanePack:
    def test_probe_reports_available_on_cpu(self):
        from siddhi_tpu.kernels import probe

        ok, reason = probe.kernels_available()
        assert ok, reason
        assert probe.interpret_mode()  # tests are CPU-only by contract

    def test_host_pack_roundtrip_non_multiple_of_32(self):
        from siddhi_tpu.kernels import plane_pack

        rng = np.random.default_rng(3)
        active = rng.random((53, 4, 5)) < 0.4  # P=53: pad bits in play
        planes = plane_pack.pack_active_host(active)
        assert planes.shape == (2, 4, 5) and planes.dtype == np.int32
        back = plane_pack.unpack_active_host(planes, 53)
        assert np.array_equal(back, active)

    def test_state_dict_roundtrip_bit_exact(self):
        from siddhi_tpu.kernels import plane_pack

        rng = np.random.default_rng(5)
        state = {
            "active": rng.random((40, 3, 2)) < 0.5,
            "first_ts": rng.integers(0, 1 << 30, (40, 3, 2)).astype(
                np.int32),
            "overflow": rng.integers(0, 9, 40).astype(np.int32),
        }
        packed = plane_pack.pack_state(state)
        assert "active" not in packed and "active_planes" in packed
        back = plane_pack.unpack_state(plane_pack.pack_state(state))
        assert set(back) == set(state)
        for k in state:
            assert np.array_equal(back[k], state[k]), k

    def test_traced_pack_matches_host_bit_order(self):
        import jax
        import jax.numpy as jnp

        from siddhi_tpu.kernels import plane_pack

        rng = np.random.default_rng(7)
        bits = rng.random(64) < 0.5
        # host flavour packs axis 0 of [64,1,1]; traced packs the last
        # axis of [1,1,64] — same bit order means identical words
        host_words = plane_pack.pack_active_host(
            bits.reshape(64, 1, 1)).reshape(2)
        traced = np.asarray(plane_pack.pack_bits(
            jax, jnp, jnp.asarray(bits.reshape(1, 1, 64)))).reshape(2)
        assert np.array_equal(host_words, traced)
        back = np.asarray(plane_pack.unpack_bits(
            jax, jnp, jnp.asarray(traced.reshape(1, 1, 2)))).reshape(64)
        assert np.array_equal(back, bits)


class TestBankSegmentedReduce:
    @pytest.mark.parametrize("op", ["sum", "min", "max"])
    def test_matches_numpy_reference_int32(self, op):
        from siddhi_tpu.kernels import bank_scatter

        rng = np.random.default_rng(11)
        n, r = 512, 256
        rows = rng.integers(0, 40, n).astype(np.int32)
        vals = rng.integers(-1000, 1000, n).astype(np.int32)
        ident = {"sum": 0, "min": np.iinfo(np.int32).max,
                 "max": np.iinfo(np.int32).min}[op]
        got = np.asarray(bank_scatter.segmented_reduce(
            rows, vals, r, op, ident, interpret=True))
        want = np.full(r, ident, dtype=np.int32)
        getattr(np, {"sum": "add", "min": "minimum", "max": "maximum"}[op]
                ).at(want, rows, vals)
        assert np.array_equal(got, want)

    def test_collision_stress_all_events_one_key(self):
        """The scatter's worst case — every event on ONE row — must
        reduce to the same row values through the kernel and the XLA
        scatter banks (integer-valued f32 sums stay order-free)."""
        from siddhi_tpu.aggregation.runtime import BaseField
        from siddhi_tpu.aggregation.device_bank import DeviceBucketBank

        fields = [
            BaseField("_SUM0", "sum", None, AttrType.LONG),
            BaseField("_MIN1", "min", None, AttrType.LONG),
            BaseField("_MAX2", "max", None, AttrType.LONG),
            BaseField("_SUM3", "sum", None, AttrType.DOUBLE),
        ]
        rng = np.random.default_rng(13)
        n = 2048
        fvals = {
            # sums ride the 16-bit hi/lo split: keep 2048 summands small
            # enough that the int32 hi lane cannot overflow
            "_SUM0": rng.integers(-(2**20), 2**20, n),
            "_MIN1": rng.integers(-(2**60), 2**60, n),
            "_MAX2": rng.integers(-(2**60), 2**60, n),
            # integer-valued floats: f32 sum reassociation cannot bite
            "_SUM3": rng.integers(0, 100, n).astype(np.float64),
        }
        out = {}
        for use_kernel in (False, True):
            bank = DeviceBucketBank(fields, cap=8, use_kernel=use_kernel)
            assert bank.assign([(0, ())])
            # ALL n events collide on the single assigned row
            rows = np.full(n, bank.rows[(0, ())], dtype=np.int32)
            bank.scatter(rows, fvals)
            out[use_kernel] = bank.flush()[(0, ())]
        assert out[False] == out[True], out
        assert out[True]["_SUM0"] == int(fvals["_SUM0"].sum())
        assert out[True]["_MIN1"] == int(fvals["_MIN1"].min())
        assert out[True]["_MAX2"] == int(fvals["_MAX2"].max())
        assert out[True]["_SUM3"] == float(fvals["_SUM3"].sum())


class TestLongExtremaDeviceBank:
    """LONG min/max ride the bank as lexicographic hi/lo int32 pairs —
    the signed 64-bit compare must be exact at full width, kernel and
    XLA scatter alike."""

    @pytest.mark.parametrize("use_kernel", [False, True])
    def test_unit_differential_negative_heavy(self, use_kernel):
        from siddhi_tpu.aggregation.runtime import BaseField
        from siddhi_tpu.aggregation.device_bank import DeviceBucketBank

        fields = [BaseField("_MIN0", "min", None, AttrType.LONG),
                  BaseField("_MAX1", "max", None, AttrType.LONG)]
        bank = DeviceBucketBank(fields, cap=16, use_kernel=use_kernel)
        rng = np.random.default_rng(17)
        keys = [(0, ("a",)), (0, ("b",)), (1, ("a",))]
        assert bank.assign(keys)
        ref = {k: [None, None] for k in keys}
        for _batch in range(3):
            n = 200
            ks = rng.integers(0, len(keys), n)
            # negative-heavy incl. values whose hi word ties but lo
            # differs (the lexicographic second pass must decide)
            v = rng.integers(-(2**62), 2**20, n)
            v[::7] = -(2**62) + rng.integers(0, 3, len(v[::7]))
            rows = np.asarray([bank.rows[keys[k]] for k in ks],
                              dtype=np.int32)
            bank.scatter(rows, {"_MIN0": v, "_MAX1": v.copy()})
            for k, x in zip(ks, v):
                cur = ref[keys[k]]
                cur[0] = int(x) if cur[0] is None else min(cur[0], int(x))
                cur[1] = int(x) if cur[1] is None else max(cur[1], int(x))
        got = bank.flush()
        for k in keys:
            assert got[k]["_MIN0"] == ref[k][0], (k, got[k], ref[k])
            assert got[k]["_MAX1"] == ref[k][1], (k, got[k], ref[k])

    AGG_APP = (
        "{mode}@app:playback "
        "define stream S (sym string, v long, ts long); "
        "define aggregation A from S select sym, min(v) as lo, "
        "max(v) as hi group by sym aggregate by ts every sec...min;"
    )
    BASE = 1_600_000_000_000

    def _run_agg(self, mode, vals, probe_bank=False):
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(self.AGG_APP.format(mode=mode))
            rt.start()
            agg = rt.aggregations["A"]
            rng = np.random.default_rng(11)
            n = len(vals)
            ts = np.sort(self.BASE + rng.integers(0, 5_000, n)).astype(
                np.int64)
            h = rt.get_input_handler("S")
            for j in range(n):
                h.send([f"s{int(rng.integers(0, 6))}", int(vals[j]),
                        int(ts[j])])
            if probe_bank:
                assert agg._bank is not None, "LONG extrema did not bank"
                assert agg._bank.scatters > 0
                # extrema pairs are excluded from the sum-overflow guard
                assert not agg._bank.long_names
            out = rt.query(
                f"from A within {self.BASE - 1000}, "
                f"{self.BASE + 100_000} per 'seconds' select sym, lo, hi;")
            rt.shutdown()
            return sorted([list(e.data) for e in out], key=lambda r: r[0])
        finally:
            m.shutdown()

    def test_app_level_exact_vs_host(self):
        rng = np.random.default_rng(3)
        vals = rng.integers(-(2**40), 2**40, 300)
        host = self._run_agg("", vals)
        dev = self._run_agg("@app:execution('tpu') ", vals,
                            probe_bank=True)
        assert len(host) == len(dev) > 0
        assert host == dev, (host[:3], dev[:3])

    @pytest.mark.slow
    def test_app_level_kernel_bank_negative_heavy(self):
        rng = np.random.default_rng(5)
        vals = rng.integers(-(2**62), -1, 300)
        host = self._run_agg("", vals)
        kern = self._run_agg("@app:execution('tpu') @app:kernels('bank') ",
                             vals, probe_bank=True)
        assert len(host) == len(kern) > 0
        assert host == kern, (host[:3], kern[:3])


class TestDenseKernelApp:
    def test_eligible_query_lowers_and_matches_xla(self):
        sends = gen_stream(seed=1, n=40)
        plain, lp, _ = run_app(TPU, ELIGIBLE, sends)
        kern, lk, sm = run_app(TPU + "@app:kernels ", ELIGIBLE, sends)
        assert lp == "dense" and lk == "kernel"
        assert kern == plain  # bit-identical, not approximately
        assert not sm.kernel_fallbacks

    def test_capturing_query_falls_back_counted(self):
        sends = gen_stream(seed=2, n=30)
        plain, lp, _ = run_app(TPU, CAPTURING, sends)
        kern, lk, sm = run_app(
            TPU + "@app:kernels @app:statistics('basic') ",
            CAPTURING, sends)
        assert lk == "dense"  # graceful: query still runs on XLA
        assert kern == plain
        assert sm.kernel_fallbacks.get("q") == 1
        assert "register file" in sm.kernel_fallback_reasons["q"]
        stats = sm.stats()
        assert any(k.endswith("q.kernelFallbacks") for k in stats)

    def test_no_annotation_means_no_kernel_machinery(self):
        sends = gen_stream(seed=3, n=30)
        _rows, lowered, sm = run_app(TPU, ELIGIBLE, sends)
        assert lowered == "dense"
        assert not sm.kernel_fallbacks


@pytest.mark.slow
class TestScanKernelApp:
    def test_hotkey_scan_kernel_bit_identity(self):
        """Skewed keys promoting mid-run: the fused scan-chain kernel
        must emit exactly what the two-pass associative scan emits."""
        app = ("partition with (k of S) begin "
               "@info(name='q') from every a=S[v > 8.0] -> b=S[v > 12.0] "
               "select b.v as bv insert into Alerts; "
               "end;")
        rng = np.random.default_rng(51)
        sends, t = [], 1000
        for i in range(360):
            t += int(rng.integers(1, 60))
            phase = (3 * i) // 360
            hot = phase != 1 and rng.random() < 0.85
            k = 7 if hot else int(rng.integers(0, 30))
            sends.append(([int(k), float(round(rng.uniform(0, 20), 1)),
                           float(round(rng.uniform(0, 20), 1))], int(t)))

        def run(kern):
            m = SiddhiManager()
            try:
                rt = m.create_siddhi_app_runtime(
                    TPU + "@app:hotkeys(k='4', promote='0.3', demote='0.1') "
                    + ("@app:kernels('scan') " if kern else "")
                    + DEFINE + app)
                got = []
                rt.add_callback(
                    "Alerts", lambda evs: got.extend(e.data for e in evs))
                rt.start()
                h = rt.get_input_handler("S")
                for row, ts in sends:
                    h.send(row, timestamp=ts)
                lowered, hot_m = None, {}
                for pr in rt.partitions.values():
                    for qr in pr.dense_query_runtimes.values():
                        lowered = qr.lowered_to
                        hot_m = qr.pattern_processor.hot_metrics()
                rt.shutdown()
                return got, lowered, hot_m
            finally:
                m.shutdown()

        kern, lk, hot = run(True)
        plain, lp, _ = run(False)
        assert lp == "hotkey" and lk == "hotkey+kernel"
        assert hot["hotkeyPromotions"] >= 1, hot  # the scan actually ran
        assert kern == plain


class TestKernelsAnnotation:
    def test_requires_tpu_mode(self):
        m = SiddhiManager()
        try:
            with pytest.raises(SiddhiAppCreationError,
                               match="needs @app:execution"):
                m.create_siddhi_app_runtime(
                    "@app:kernels " + DEFINE + ELIGIBLE)
        finally:
            m.shutdown()

    def test_unknown_kind_rejected(self):
        m = SiddhiManager()
        try:
            with pytest.raises(SiddhiAppCreationError,
                               match="unknown kernel kind"):
                m.create_siddhi_app_runtime(
                    "@app:execution('tpu') @app:kernels('nfa,warp') "
                    + DEFINE + ELIGIBLE)
        finally:
            m.shutdown()

    def test_false_keeps_kernels_off(self):
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(
                TPU + "@app:kernels('false') " + DEFINE + ELIGIBLE)
            rt.start()
            assert rt.app_context.kernels is False
            qr = next(iter(rt.query_runtimes.values()))
            assert qr.lowered_to == "dense"
            rt.shutdown()
        finally:
            m.shutdown()

    def test_kind_subset_skips_other_kinds_silently(self):
        # bank-only request: the pattern query is NOT a fallback — nfa
        # was never asked for
        sends = gen_stream(seed=4, n=20)
        _rows, lowered, sm = run_app(
            TPU + "@app:kernels('bank') ", ELIGIBLE, sends)
        assert lowered == "dense"
        assert not sm.kernel_fallbacks
