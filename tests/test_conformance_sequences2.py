"""Sequence conformance, part 2: ported from the reference's
SequenceTestCase.java (modules/siddhi-core/src/test/java/io/siddhi/core/
query/sequence/SequenceTestCase.java) — the cases beyond the basics
already pinned by tests/test_patterns.py: Kleene-star/plus capture
edges, logical sequences, strict-continuity kills, and the peak/trough
detection family using e2[last]/e2[last-1] back-references.  Expected
rows are the reference's literal assertions.
"""

import numpy as np

from siddhi_tpu import SiddhiManager

S12 = (
    "define stream Stream1 (symbol string, price float, volume int); "
    "define stream Stream2 (symbol string, price float, volume int); "
)
S123 = S12 + "define stream Stream3 (symbol string, price float, volume int); "


def f32(x):
    return np.float32(x).item()


def run(app, sends, out="OutputStream"):
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime("@app:playback " + app)
        got = []
        rt.add_callback(out, lambda evs: got.extend(list(e.data) for e in evs))
        rt.start()
        for stream, row, ts in sends:
            rt.get_input_handler(stream).send(row, timestamp=ts)
        rt.shutdown()
        return got
    finally:
        m.shutdown()


def both(app, sends, expected, out="OutputStream"):
    host = run(app, sends, out)
    assert host == expected, f"host {host} != expected {expected}"
    tpu = run("@app:execution('tpu') " + app, sends, out)
    assert tpu == host, f"tpu {tpu} != host {host}"
    return host


def seq(rows, base=1000, gap=100):
    return [(s, r, base + i * gap) for i, (s, r) in enumerate(rows)]


class TestKleeneSequences2:
    def test_star_collects_with_smaller_second(self):
        # SequenceTestCase.testQuery5
        q = ("@info(name='q') from every e1=Stream2[price>20]*, "
             "e2=Stream1[price>e1[0].price] "
             "select e1[0].price as price1, e1[1].price as price2, "
             "e2.price as price3 insert into OutputStream;")
        both(S12 + q, seq([
            ("Stream1", ["WSO2", 59.6, 100]),
            ("Stream2", ["WSO2", 55.6, 100]),
            ("Stream2", ["IBM", 55.0, 100]),
            ("Stream1", ["WSO2", 57.6, 100]),
        ]), [[f32(55.6), f32(55.0), f32(57.6)]])

    def test_or_sequence_last_arm_wins(self):
        # SequenceTestCase.testQuery9: the IBM branch completes the
        # SECOND pending arm after the first completed via price
        q = ("@info(name='q') from every e1=Stream2[price>20], "
             "e2=Stream2[price>e1.price] or e3=Stream2[symbol=='IBM'] "
             "select e1.price as price1, e2.price as price2, "
             "e3.price as price3 insert into OutputStream;")
        both(S12 + q, seq([
            ("Stream2", ["WSO2", 59.6, 100]),
            ("Stream2", ["WSO2", 55.6, 100]),
            ("Stream2", ["WSO2", 57.6, 100]),
            ("Stream2", ["IBM", 55.7, 100]),
        ]), [
            [f32(55.6), f32(57.6), None],
            [f32(57.6), None, f32(55.7)],
        ])

    def test_two_stream_every_sequence(self):
        # SequenceTestCase.testQuery12: strict continuity across streams
        st = ("define stream StockStream (symbol string, price float, "
              "volume int); "
              "define stream TwitterStream (symbol string, count int); ")
        q = ("@info(name='q') from every e1=StockStream[price >= 50 and "
             "volume > 100], e2=TwitterStream[count > 10] "
             "select e1.price as price, e1.symbol as symbol, "
             "e2.count as count insert into OutputStream;")
        both(st + q, seq([
            ("StockStream", ["IBM", 75.6, 105]),
            ("StockStream", ["GOOG", 51.0, 101]),
            ("StockStream", ["IBM", 76.6, 111]),
            ("TwitterStream", ["IBM", 20]),
            ("StockStream", ["WSO2", 45.6, 100]),
            ("TwitterStream", ["GOOG", 20]),
        ]), [[f32(76.6), "IBM", 20]])

    def test_star_mid_chain(self):
        # SequenceTestCase.testQuery13
        st = ("define stream StockStream (symbol string, price float, "
              "volume int); "
              "define stream TwitterStream (symbol string, count int); ")
        q = ("@info(name='q') from every e1=StockStream[price >= 50 and "
             "volume > 100], e2=StockStream[price <= 40]*, "
             "e3=StockStream[volume <= 70] "
             "select e1.symbol as symbol1, e2[0].symbol as symbol2, "
             "e3.symbol as symbol3 insert into OutputStream;")
        both(st + q, seq([
            ("StockStream", ["IBM", 75.6, 105]),
            ("StockStream", ["GOOG", 21.0, 81]),
            ("StockStream", ["WSO2", 176.6, 65]),
        ]), [["IBM", "GOOG", "WSO2"]])

    def test_star_two_streams_multi_match(self):
        # SequenceTestCase.testQuery14
        st = ("define stream StockStream1 (symbol string, price float, "
              "volume int); "
              "define stream StockStream2 (symbol string, price float, "
              "volume int); ")
        q = ("@info(name='q') from every e1=StockStream1[price >= 50 and "
             "volume > 100], e2=StockStream2[price <= 40]*, "
             "e3=StockStream2[volume <= 70] "
             "select e3.symbol as symbol1, e2[0].symbol as symbol2, "
             "e3.volume as volume insert into OutputStream;")
        both(st + q, seq([
            ("StockStream1", ["IBM", 75.6, 105]),
            ("StockStream2", ["GOOG", 21.0, 81]),
            ("StockStream2", ["WSO2", 176.6, 65]),
            ("StockStream1", ["BIRT", 21.0, 81]),
            ("StockStream1", ["AMBA", 126.6, 165]),
            ("StockStream2", ["DDD", 23.0, 181]),
            ("StockStream2", ["BIRT", 21.0, 86]),
            ("StockStream2", ["BIRT", 21.0, 82]),
            ("StockStream2", ["WSO2", 176.6, 60]),
            ("StockStream1", ["AMBA", 126.6, 165]),
            ("StockStream2", ["DOX", 16.2, 25]),
        ]), [
            ["WSO2", "GOOG", 65],
            ["WSO2", "DDD", 60],
            ["DOX", None, 25],
        ])

    def test_star_cross_ref_filter(self):
        # SequenceTestCase.testQuery15
        st = ("define stream StockStream1 (symbol string, price float, "
              "volume int); "
              "define stream StockStream2 (symbol string, price float, "
              "volume int); ")
        q = ("@info(name='q') from every e1=StockStream1[price >= 50 and "
             "volume > 100], e2=StockStream2[e1.symbol != 'AMBA']*, "
             "e3=StockStream2[volume <= 70] "
             "select e3.symbol as symbol1, e2[0].symbol as symbol2, "
             "e3.volume as volume insert into OutputStream;")
        both(st + q, seq([
            ("StockStream1", ["IBM", 75.6, 105]),
            ("StockStream2", ["GOOG", 21.0, 81]),
            ("StockStream2", ["WSO2", 176.6, 65]),
            ("StockStream1", ["BIRT", 21.0, 81]),
            ("StockStream1", ["AMBA", 126.6, 165]),
            ("StockStream2", ["DDD", 23.0, 181]),
            ("StockStream2", ["BIRT", 21.0, 86]),
            ("StockStream2", ["BIRT", 21.0, 82]),
            ("StockStream2", ["WSO2", 176.6, 60]),
            ("StockStream1", ["AMBA", 126.6, 165]),
            ("StockStream2", ["DOX", 16.2, 25]),
        ]), [
            ["WSO2", "GOOG", 65],
            ["DOX", None, 25],
        ])

    def test_star_unfiltered_start(self):
        # SequenceTestCase.testQuery16
        st = ("define stream StockStream1 (symbol string, price float, "
              "volume int); "
              "define stream StockStream2 (symbol string, price float, "
              "volume int); ")
        q = ("@info(name='q') from every e1=StockStream1, "
             "e2=StockStream2[e1.symbol != 'AMBA']*, "
             "e3=StockStream2[volume <= 70] "
             "select e3.symbol as symbol1, e2[0].symbol as symbol2, "
             "e3.volume as volume insert into OutputStream;")
        both(st + q, seq([
            ("StockStream1", ["IBM", 75.6, 105]),
            ("StockStream2", ["GOOG", 21.0, 81]),
            ("StockStream2", ["WSO2", 176.6, 65]),
            ("StockStream1", ["BIRT", 21.0, 81]),
            ("StockStream1", ["AMBA", 126.6, 165]),
            ("StockStream2", ["DDD", 23.0, 181]),
            ("StockStream2", ["BIRT", 21.0, 86]),
            ("StockStream2", ["BIRT", 21.0, 82]),
            ("StockStream2", ["WSO2", 176.6, 60]),
            ("StockStream1", ["AMBA", 126.6, 165]),
            ("StockStream2", ["DOX", 16.2, 25]),
        ]), [
            ["WSO2", "GOOG", 65],
            ["DOX", None, 25],
        ])


PEAK_Q = ("@info(name='q') from every e1=Stream1[price>20], "
          "e2=Stream1[((e2[last].price is null) and price>=e1.price) or "
          "((not (e2[last].price is null)) and price>=e2[last].price)]+, "
          "e3=Stream1[price<e2[last].price] "
          "select e1.price as price1, e2[0].price as price2, "
          "e2[1].price as price3, e3.price as price4 "
          "insert into OutputStream;")


class TestPeakDetection2:
    def test_peak_restarts_on_dip(self):
        # SequenceTestCase.testQuery18
        both(S12 + PEAK_Q, seq([
            ("Stream1", ["WSO2", 29.6, 100]),
            ("Stream1", ["WSO2", 25.0, 100]),
            ("Stream1", ["WSO2", 35.6, 100]),
            ("Stream1", ["WSO2", 57.6, 100]),
            ("Stream1", ["IBM", 47.6, 100]),
        ]), [[f32(25.0), f32(35.6), f32(57.6), f32(47.6)]])

    def test_peak_single_rise(self):
        # SequenceTestCase.testQuery19
        both(S12 + PEAK_Q, seq([
            ("Stream1", ["WSO2", 25.0, 100]),
            ("Stream1", ["WSO2", 40.0, 100]),
            ("Stream1", ["WSO2", 35.0, 100]),
        ]), [[f32(25.0), f32(40.0), None, f32(35.0)]])

    def test_peak_three_matches(self):
        # SequenceTestCase.testQuery20
        both(S12 + PEAK_Q, seq([
            ("Stream1", ["WSO2", 29.6, 100]),
            ("Stream1", ["WSO2", 25.0, 100]),
            ("Stream1", ["WSO2", 35.6, 100]),
            ("Stream1", ["WSO2", 25.5, 100]),
            ("Stream1", ["WSO2", 57.6, 100]),
            ("Stream1", ["WSO2", 58.6, 100]),
            ("Stream1", ["IBM", 47.6, 100]),
            ("Stream1", ["IBM", 27.6, 100]),
            ("Stream1", ["IBM", 49.6, 100]),
            ("Stream1", ["IBM", 45.6, 100]),
        ]), [
            [f32(25.0), f32(35.6), None, f32(25.5)],
            [f32(25.5), f32(57.6), f32(58.6), f32(47.6)],
            [f32(27.6), f32(49.6), None, f32(45.6)],
        ])

    def test_peak_ifthenelse_form(self):
        # SequenceTestCase.testQuery20_2: same peaks via ifThenElse
        q = ("@info(name='q') from every e1=Stream1, "
             "e2=Stream1[ifThenElse(e2[last].price is null, "
             "e1.price <= price, e2[last].price <= price)]+, "
             "e3=Stream1[e2[last].price > price] "
             "select e1.price as initialPrice, e2[last].price as peekPrice, "
             "e3.price as firstDropPrice insert into OutputStream;")
        got = run(S12 + q, seq([
            ("Stream1", ["WSO2", 29.6, 100]),
            ("Stream1", ["WSO2", 25.0, 100]),
            ("Stream1", ["WSO2", 15.6, 100]),
            ("Stream1", ["WSO2", 25.5, 100]),
            ("Stream1", ["WSO2", 57.6, 100]),
            ("Stream1", ["WSO2", 58.6, 100]),
            ("Stream1", ["IBM", 47.6, 100]),
            ("Stream1", ["IBM", 27.6, 100]),
            ("Stream1", ["IBM", 49.6, 100]),
            ("Stream1", ["IBM", 45.6, 100]),
            ("Stream1", ["IBM", 37.7, 100]),
            ("Stream1", ["IBM", 33.7, 100]),
            ("Stream1", ["IBM", 27.7, 100]),
            ("Stream1", ["IBM", 49.7, 100]),
            ("Stream1", ["IBM", 45.7, 100]),
        ]))
        assert len(got) == 3  # reference asserts the count

    def test_peak_last_minus_n_refs(self):
        # SequenceTestCase.testQuery23: e2[last-1]/e2[last-2] select refs
        q = ("@info(name='q') from every e1=Stream1[price>20], "
             "e2=Stream1[price>=e2[last].price or price>=e1.price]+, "
             "e3=Stream1[price<e2[last].price] "
             "select e1.price as price1, e2[0].price as price2, "
             "e2[last-2].price as price3, e2[last-1].price as price4, "
             "e2[last].price as price5, e3.price as price6 "
             "insert into OutputStream;")
        both(S12 + q, seq([
            ("Stream1", ["WSO2", 29.6, 100]),
            ("Stream1", ["WSO2", 25.0, 100]),
            ("Stream1", ["WSO2", 35.6, 100]),
            ("Stream1", ["WSO2", 29.5, 100]),
            ("Stream1", ["WSO2", 57.6, 100]),
            ("Stream1", ["WSO2", 58.6, 100]),
            ("Stream1", ["IBM", 57.7, 100]),
            ("Stream1", ["IBM", 45.6, 100]),
        ]), [
            [f32(25.0), f32(35.6), None, None, f32(35.6), f32(29.5)],
            [f32(29.5), f32(57.6), None, f32(57.6), f32(58.6), f32(57.7)],
        ])

    def test_peak_last_minus_n_filters(self):
        # SequenceTestCase.testQuery24: e2[last-1] back-ref in FILTER
        q = ("@info(name='q') from every e1=Stream1[price>20], "
             "e2=Stream1[(price>=e2[last].price and "
             "(not (e2[last-1].price is null)) and "
             "price>=e2[last-1].price+5) or "
             "((e2[last-1].price is null) and price>=e1.price+5)]+, "
             "e3=Stream1[price<e2[last].price] "
             "select e1.price as price1, e2[0].price as price2, "
             "e2[last-2].price as price3, e2[last-1].price as price4, "
             "e2[last].price as price5, e3.price as price6 "
             "insert into OutputStream;")
        both(S12 + q, seq([
            ("Stream1", ["WSO2", 29.6, 100]),
            ("Stream1", ["WSO2", 25.0, 100]),
            ("Stream1", ["WSO2", 35.6, 100]),
            ("Stream1", ["WSO2", 41.5, 100]),
            ("Stream1", ["WSO2", 42.6, 100]),
            ("Stream1", ["WSO2", 43.6, 100]),
            ("Stream1", ["IBM", 57.7, 100]),
            ("Stream1", ["IBM", 58.7, 100]),
            ("Stream1", ["IBM", 45.6, 100]),
        ]), [
            [f32(43.6), f32(57.7), None, f32(57.7), f32(58.7), f32(45.6)],
        ])


class TestLogicalSequences:
    AQ = ("@info(name='q') from e1=Stream1[price >20], "
          "e2=Stream2['IBM' == symbol] and e3=Stream3['WSO2' == symbol] "
          "select e1.price as price1, e2.price as price2, "
          "e3.price as price3 insert into OutputStream;")

    def test_and_sequence(self):
        # SequenceTestCase.testQuery25/26
        both(S123 + self.AQ, seq([
            ("Stream1", ["IBM", 25.5, 100]),
            ("Stream2", ["IBM", 45.5, 100]),
            ("Stream3", ["WSO2", 46.56, 100]),
        ]), [[f32(25.5), f32(45.5), f32(46.56)]])

    def test_or_sequence_immediate(self):
        # SequenceTestCase.testQuery27
        q = ("@info(name='q') from e1=Stream1[price >20], "
             "e2=Stream2['IBM' == symbol] or e3=Stream3['WSO2' == symbol] "
             "select e1.price as price1, e2.price as price2, "
             "e3.price as price3 insert into OutputStream;")
        both(S123 + q, seq([
            ("Stream1", ["IBM", 59.65, 100]),
            ("Stream2", ["IBM", 45.5, 100]),
        ]), [[f32(59.65), f32(45.5), None]])

    def test_and_sequence_single_match(self):
        # SequenceTestCase.testQuery28: non-every — one match only
        both(S123 + self.AQ, seq([
            ("Stream1", ["IBM", 59.65, 100]),
            ("Stream2", ["IBM", 45.5, 100]),
            ("Stream3", ["WSO2", 46.56, 100]),
        ]), [[f32(59.65), f32(45.5), f32(46.56)]])

    def test_and_start_sequence(self):
        # SequenceTestCase.testQuery32: logical node FIRST in sequence
        q = ("@info(name='q') from e1=Stream1[price >20] and "
             "e2=Stream2['IBM' == symbol], e3=Stream3['WSO2' == symbol] "
             "select e1.price as price1, e2.price as price2, "
             "e3.price as price3 insert into OutputStream;")
        both(S123 + q, seq([
            ("Stream1", ["IBM", 25.5, 100]),
            ("Stream2", ["IBM", 45.5, 100]),
            ("Stream3", ["WSO2", 46.56, 100]),
        ]), [[f32(25.5), f32(45.5), f32(46.56)]])


class TestStrictContinuity2:
    def test_non_every_interrupted_never_matches(self):
        # SequenceTestCase.testQuery31: GOOG breaks continuity; without
        # `every` the engine never recovers for the later pair
        q = ("@info(name='q') from e1=Stream1[price>20], "
             "e2=Stream2[price>e1.price] "
             "select e1.symbol as symbol1, e2.symbol as symbol2 "
             "insert into OutputStream;")
        both(S12 + q, seq([
            ("Stream1", ["WSO2", 55.6, 100]),
            ("Stream1", ["GOOG", 57.6, 100]),
            ("Stream2", ["IBM", 65.7, 100]),
        ]), [])

    def test_non_every_single_match_then_stop(self):
        # SequenceTestCase.testQuery29
        q = ("@info(name='q') from e1=Stream1[price>20], "
             "e2=Stream2[price>e1.price] "
             "select e1.symbol as symbol1, e2.symbol as symbol2 "
             "insert into OutputStream;")
        both(S12 + q, seq([
            ("Stream1", ["WSO2", 55.6, 100]),
            ("Stream2", ["IBM", 55.7, 100]),
            ("Stream1", ["ORACLE", 55.6, 100]),
            ("Stream2", ["GOOGLE", 55.7, 100]),
        ]), [["WSO2", "IBM"]])

    def test_every_interrupted_then_recovers(self):
        # SequenceTestCase.testQuery30
        q = ("@info(name='q') from every e1=Stream1[price>20], "
             "e2=Stream2[price>e1.price] "
             "select e1.symbol as symbol1, e2.symbol as symbol2 "
             "insert into OutputStream;")
        both(S12 + q, seq([
            ("Stream1", ["WSO2", 55.6, 100]),
            ("Stream2", ["IBM", 55.7, 100]),
            ("Stream1", ["ORACLE", 55.6, 100]),
            ("Stream1", ["MICROSOFT", 55.8, 100]),
            ("Stream2", ["GOOGLE", 55.9, 100]),
        ]), [["WSO2", "IBM"], ["MICROSOFT", "GOOGLE"]])
