"""Async emit pipeline: count-gated, queued device→host transfers.

Every device engine now emits through ``core/emit_queue.py``: the jitted
step returns a scalar match count (zero-match batches transfer NOTHING),
matched batches stay device-resident in a bounded pending-emit queue
(``@app:execution('tpu', emit.depth='N')``), and explicit drain barriers
keep callback content/order bit-identical to the synchronous path.

These tests pin the exactness contract differentially — the same app and
event series at ``emit.depth='1'`` (sync timing) vs a deeper queue must
produce identical callbacks across every flush trigger (queue-full,
timer fire, snapshot mid-stream, pull query, shutdown) on the
device-single, partitioned, dense, and sharded paths — and assert the
transfer counters: zero-match batches perform no column transfer.
"""

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.dense_pattern import DensePatternRuntime
from siddhi_tpu.core.device_single import DeviceQueryRuntime

DEFINE = "define stream S (k long, v double); "


def series(n, seed, n_keys=4, t0=1000, dt_max=400):
    rng = np.random.default_rng(seed)
    ts = t0 + np.cumsum(rng.integers(1, dt_max, size=n))
    keys = rng.integers(0, n_keys, size=n)
    vals = rng.integers(1, 100, size=n).astype(float)
    return [([int(k), float(v)], int(t)) for k, v, t in zip(keys, vals, ts)]


def run_app(app, sends, out="OutputStream", exec_opts=None,
            want_runtime=False):
    """Playback run -> list of data tuples.  ``exec_opts`` is the option
    tail of @app:execution('tpu'...), e.g. ", emit.depth='4'"; None runs
    the host engine."""
    header = "@app:playback "
    if exec_opts is not None:
        header += f"@app:execution('tpu'{exec_opts}) "
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime(header + app)
        got = []
        rt.add_callback(out, lambda evs: got.extend(tuple(e.data)
                                                    for e in evs))
        rt.start()
        h = rt.get_input_handler("S")
        for row, ts in sends:
            h.send(row, timestamp=ts)
        qr = next(iter(rt.query_runtimes.values()))
        runtime = (getattr(qr, "device_runtime", None)
                   or getattr(qr, "pattern_processor", None))
        rt.shutdown()
        if want_runtime:
            return got, runtime
        return got
    finally:
        m.shutdown()


def depth_differential(app, sends, deep=4, ordered=True, out="OutputStream",
                       extra=""):
    """host == depth-1 == depth-N rows; asserts the deep run deferred."""
    host = run_app(app, sends, out=out)
    d1, rt1 = run_app(app, sends, out=out, exec_opts=extra, want_runtime=True)
    dN, rtN = run_app(app, sends, out=out,
                      exec_opts=f"{extra}, emit.depth='{deep}'",
                      want_runtime=True)
    assert rt1 is not None, "query did not lower to a device engine"
    assert rt1.step_invocations > 0
    assert rtN.emit_queue.depth == deep
    if not ordered:
        host, d1, dN = sorted(host), sorted(d1), sorted(dN)
    assert d1 == host, "depth-1 device path diverged from host"
    assert dN == host, "deferred emits changed callback content/order"
    return rtN


class TestDeviceSingleDifferential:
    def test_filter_projection_deferred(self):
        app = DEFINE + ("from S[v > 20.0] select k, v, v * 2.0 as dbl "
                        "insert into OutputStream;")
        rt = depth_differential(app, series(120, seed=1))
        assert isinstance(rt, DeviceQueryRuntime)
        # most batches match -> the deep queue actually deferred and
        # coalesced: strictly fewer transfers than matching batches
        assert rt.emit_stats.deferred_batches > 0
        assert rt.emit_stats.max_pending_depth == 4
        matched = rt.emit_stats.emit_transfers + rt.emit_stats.deferred_batches
        assert rt.emit_stats.emit_transfers < matched

    def test_grouped_window_deferred(self):
        app = DEFINE + ("from S#window.length(8) select k, sum(v) as s, "
                        "max(v) as hi group by k insert into OutputStream;")
        rt = depth_differential(app, series(150, seed=2, n_keys=5))
        assert rt.emit_stats.deferred_batches > 0

    def test_timer_fire_tumbling_pane(self):
        # timeBatch emits happen on pane close (timer fire) — the drain
        # barrier in fire() must keep deferred content exact
        app = DEFINE + ("from S#window.timeBatch(1 sec) select k, "
                        "sum(v) as s group by k insert into OutputStream;")
        depth_differential(app, series(150, seed=3), ordered=False)

    def test_rate_limiter_decision_barrier(self):
        # time-based output rate: the limiter's on_time decision must see
        # every deferred row first (fire() drains device_runtime)
        app = DEFINE + ("from S select k, sum(v) as s group by k "
                        "output last every 1 sec insert into OutputStream;")
        depth_differential(app, series(200, seed=4), deep=8)

    def test_string_group_keys_survive_deferred_drain(self):
        # gvals are captured at enqueue time — a deep queue must not
        # alias or reorder the key side channel
        app = ("define stream S (sym string, v double); "
               "from S select sym, sum(v) as s group by sym "
               "insert into OutputStream;")
        sends = [(["IBM", 10.0], 1000), (["MSFT", 20.0], 1100),
                 (["IBM", 5.0], 1200), (["MSFT", 1.0], 1300),
                 (["ORCL", 2.0], 1400)]
        dN, rt = run_app(app, sends, exec_opts=", emit.depth='8'",
                         want_runtime=True)
        assert isinstance(rt, DeviceQueryRuntime)
        assert rt.emit_stats.deferred_batches > 0
        assert [r[0] for r in dN] == ["IBM", "MSFT", "IBM", "MSFT", "ORCL"]
        assert dN == run_app(app, sends)


class TestFlushTriggers:
    APP = DEFINE + "from S[v > 0.0] select k, v insert into OutputStream;"
    HDR = "@app:playback @app:execution('tpu', emit.depth='{d}') "

    def _start(self, depth, app=None):
        m = SiddhiManager()
        rt = m.create_siddhi_app_runtime(
            self.HDR.format(d=depth) + (app or self.APP))
        got = []
        rt.add_callback("OutputStream",
                        lambda evs: got.extend(tuple(e.data) for e in evs))
        rt.start()
        return m, rt, got

    def test_queue_full_drains_mid_stream(self):
        m, rt, got = self._start(2)
        try:
            h = rt.get_input_handler("S")
            h.send([1, 10.0], timestamp=1000)
            assert got == []  # first match deferred
            h.send([2, 20.0], timestamp=1100)
            assert len(got) == 2  # queue reached depth -> drained
            h.send([3, 30.0], timestamp=1200)
            assert len(got) == 2  # third pending again
            rt.shutdown()
            assert got == [(1, 10.0), (2, 20.0), (3, 30.0)]
        finally:
            m.shutdown()

    def test_shutdown_flushes_pending(self):
        m, rt, got = self._start(16)
        try:
            h = rt.get_input_handler("S")
            for i in range(5):
                h.send([i, float(i + 1)], timestamp=1000 + i)
            assert got == []  # all five below depth
            rt.shutdown()
            assert got == [(i, float(i + 1)) for i in range(5)]
        finally:
            m.shutdown()

    def test_snapshot_mid_stream_flushes_pending(self):
        m, rt, got = self._start(16)
        try:
            h = rt.get_input_handler("S")
            for i in range(4):
                h.send([i, 1.0], timestamp=1000 + i)
            assert got == []
            blob = rt.snapshot()
            assert len(got) == 4  # snapshot barrier drained first
            # and the blob restores into a runtime that continues exactly
            m2 = SiddhiManager()
            try:
                rt2 = m2.create_siddhi_app_runtime(
                    self.HDR.format(d=16) + self.APP)
                got2 = []
                rt2.add_callback(
                    "OutputStream",
                    lambda evs: got2.extend(tuple(e.data) for e in evs))
                rt2.start()
                rt2.restore(blob)
                rt2.get_input_handler("S").send([9, 9.0], timestamp=2000)
                rt2.shutdown()
                assert got2 == [(9, 9.0)]
            finally:
                m2.shutdown()
            rt.shutdown()
        finally:
            m.shutdown()

    def test_persist_flushes_pending(self):
        from siddhi_tpu.util.persistence import InMemoryPersistenceStore

        m = SiddhiManager()
        try:
            m.set_persistence_store(InMemoryPersistenceStore())
            rt = m.create_siddhi_app_runtime(
                self.HDR.format(d=16) + self.APP)
            got = []
            rt.add_callback(
                "OutputStream",
                lambda evs: got.extend(tuple(e.data) for e in evs))
            rt.start()
            h = rt.get_input_handler("S")
            for i in range(3):
                h.send([i, 1.0], timestamp=1000 + i)
            assert got == []
            rt.persist()
            assert len(got) == 3  # persist barrier drained first
            rt.shutdown()
        finally:
            m.shutdown()

    def test_pull_query_flushes_pending(self):
        app = (DEFINE + "define table T (k long, v double); "
               "from S[v > 0.0] select k, v insert into OutputStream; "
               "from S select k, v insert into T;")
        m, rt, got = self._start(16, app=app)
        try:
            h = rt.get_input_handler("S")
            for i in range(3):
                h.send([i, 2.0], timestamp=1000 + i)
            assert got == []
            rows = rt.query("from T select k, v;")
            assert len(got) == 3  # pull-query barrier drained first
            assert len(rows) == 3
            rt.shutdown()
        finally:
            m.shutdown()

    def test_debugger_forces_depth_one(self):
        m, rt, got = self._start(8)
        try:
            qr = next(iter(rt.query_runtimes.values()))
            assert qr.device_runtime.emit_queue.depth == 8
            rt.debug()
            assert qr.device_runtime.emit_queue.depth == 1
            h = rt.get_input_handler("S")
            h.send([1, 1.0], timestamp=1000)
            assert len(got) == 1  # no deferral under the debugger
            rt.shutdown()
        finally:
            m.shutdown()


class TestZeroMatchGating:
    def test_no_transfer_on_zero_match_batches(self):
        app = DEFINE + ("from S[v > 1000000.0] select k, v "
                        "insert into OutputStream;")
        sends = series(40, seed=5)  # vals < 100: nothing ever matches
        got, rt = run_app(app, sends, exec_opts="", want_runtime=True)
        assert got == []
        assert isinstance(rt, DeviceQueryRuntime)
        assert rt.step_invocations == 40  # the jitted step DID run
        assert rt.emit_stats.zero_match_skips == 40
        assert rt.emit_stats.emit_transfers == 0  # no column fetched
        assert rt.emit_stats.max_pending_depth == 0

    def test_zero_match_dense_pattern(self):
        app = DEFINE + ("from every e1=S[v > 1000000.0] -> "
                        "e2=S[v > e1.v] within 10 sec "
                        "select e1.v as a, e2.v as b "
                        "insert into OutputStream;")
        got, rt = run_app(app, series(40, seed=6), exec_opts="",
                          want_runtime=True)
        assert got == []
        assert isinstance(rt, DensePatternRuntime)
        assert rt.step_invocations == 40
        assert rt.emit_stats.zero_match_skips == 40
        assert rt.emit_stats.emit_transfers == 0

    def test_counters_ride_statistics_feed(self):
        app = ("@app:name('emitApp') @app:statistics('true') "
               "@app:playback @app:execution('tpu', emit.depth='2') "
               + DEFINE +
               "@info(name='q') from S[v > 50.0] select k, v "
               "insert into OutputStream;")
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(app)
            rt.start()
            h = rt.get_input_handler("S")
            for i, v in enumerate([60.0, 70.0, 10.0, 80.0]):
                h.send([i, v], timestamp=1000 + i)
            stats = rt.statistics()
            pre = "io.siddhi.SiddhiApps.emitApp.Siddhi.Queries.q."
            assert stats[pre + "zeroMatchSkips"] == 1  # the 10.0 batch
            assert stats[pre + "emitTransfers"] >= 1
            assert stats[pre + "deferredBatches"] >= 1
            assert stats[pre + "maxPendingDepth"] == 2
            rt.shutdown()
        finally:
            m.shutdown()


PATTERN_APP = DEFINE + (
    "from every e1=S[v > 50.0] -> e2=S[v > e1.v] within 10 sec "
    "select e1.v as a, e2.v as b insert into OutputStream;")

PART_APP = (
    "define stream S (card string, v double); "
    "partition with (card of S) begin "
    "@info(name='q') "
    "from every a=S[v > 100.0] -> b=S[v > a.v] within 10 min "
    "select a.v as base, b.v as bv insert into Alerts; "
    "end;")


def part_sends(n_keys=12, rounds=6, seed=7):
    rng = np.random.default_rng(seed)
    sends, t = [], 1000
    for _ in range(rounds):
        for k in range(n_keys):
            t += int(rng.integers(1, 50))
            sends.append(([f"c{k}", float(rng.integers(50, 400))], t))
    return sends


def run_part(header, sends, out="Alerts"):
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime(header + PART_APP)
        got = []
        rt.add_callback(out, lambda evs: got.extend(tuple(e.data)
                                                    for e in evs))
        rt.start()
        h = rt.get_input_handler("S")
        for row, ts in sends:
            h.send(row, timestamp=ts)
        pr = rt.partitions.get("partition_0")
        runtime = (next(iter(pr.dense_query_runtimes.values()))
                   .pattern_processor
                   if pr is not None and pr.is_dense else None)
        rt.shutdown()
        return got, runtime
    finally:
        m.shutdown()


class TestDenseAndShardedDifferential:
    def test_dense_pattern_deferred(self):
        # instances='32': `every` on a dense 120-event series overflows
        # the default 4 pending lanes, which drops matches vs host —
        # orthogonal to emit deferral
        rt = depth_differential(PATTERN_APP, series(120, seed=8),
                                extra=", instances='32'")
        assert isinstance(rt, DensePatternRuntime)
        assert rt.emit_stats.deferred_batches > 0

    def test_partitioned_dense_deferred(self):
        sends = part_sends()
        host, _ = run_part("@app:playback ", sends)
        d1, rt1 = run_part(
            "@app:playback @app:execution('tpu', partitions='64') ", sends)
        dN, rtN = run_part(
            "@app:playback @app:execution('tpu', partitions='64', "
            "emit.depth='4') ", sends)
        assert isinstance(rt1, DensePatternRuntime)
        assert rtN.emit_queue.depth == 4
        assert rtN.emit_stats.deferred_batches > 0
        assert d1 == host
        assert dN == host

    def test_sharded_dense_deferred(self):
        sends = part_sends(n_keys=16)
        host, _ = run_part("@app:playback ", sends)
        dN, rtN = run_part(
            "@app:playback @app:execution('tpu', partitions='64', "
            "devices='8', emit.depth='4') ", sends)
        assert isinstance(rtN, DensePatternRuntime)
        assert rtN._sharded is not None and rtN.n_shards == 8
        assert rtN.emit_stats.deferred_batches > 0
        assert dN == host


class TestShardedBigBatchRegression:
    def test_group_keys_aligned_past_2048_rows_deferred(self):
        """>MAX_DEVICE_BATCH sharded batches chunk internally; the
        group-key side channel must stay row-aligned across chunks AND
        survive a deferred (depth>1) drain — per-group FIRST rate
        limiting collapses to one global row if keys alias."""
        from siddhi_tpu.core.event import EventBatch

        for depth in ("1", "4"):
            m = SiddhiManager()
            try:
                rt = m.create_siddhi_app_runtime(
                    "@app:playback "
                    f"@app:execution('tpu', partitions='16', devices='8', "
                    f"emit.depth='{depth}') "
                    "define stream S (sym string, v double, k int); "
                    "@info(name='gq') from S select k, sum(v) as s "
                    "group by k output first every 5000 events "
                    "insert into Out;")
                got = []
                rt.add_callback("Out", lambda evs: got.extend(
                    tuple(e.data) for e in evs))
                rt.start()
                n = 3000
                rng = np.random.default_rng(0)
                ks = rng.integers(0, 4, n).astype(np.int32)
                rt.get_input_handler("S").send_batch(EventBatch(
                    "S", ["sym", "v", "k"],
                    {"sym": np.asarray(["x"] * n, dtype=object),
                     "v": np.ones(n), "k": ks},
                    1000 + np.arange(n, dtype=np.int64)))
                rt.shutdown()
                assert len(got) == 4, (depth, got)
                assert sorted(g[0] for g in got) == [0, 1, 2, 3]
            finally:
                m.shutdown()


class TestEmitDepthKnob:
    def test_depth_parses_onto_runtime(self):
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(
                "@app:execution('tpu', emit.depth='3') " + DEFINE +
                "from S[v > 0.0] select k insert into Out;")
            assert rt.app_context.tpu_emit_depth == 3
            qr = next(iter(rt.query_runtimes.values()))
            assert qr.device_runtime.emit_queue.depth == 3
        finally:
            m.shutdown()

    @pytest.mark.parametrize("bad", ["0", "-2", "abc", "1.5"])
    def test_invalid_depth_rejected(self, bad):
        from siddhi_tpu.core.exceptions import SiddhiAppCreationError

        m = SiddhiManager()
        try:
            with pytest.raises(SiddhiAppCreationError):
                m.create_siddhi_app_runtime(
                    f"@app:execution('tpu', emit.depth='{bad}') " + DEFINE +
                    "from S[v > 0.0] select k insert into Out;")
        finally:
            m.shutdown()
