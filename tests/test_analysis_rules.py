"""Fixture tests for every ``siddhi_tpu.analysis`` rule.

Each rule gets a BAD snippet it must fire on and a GOOD snippet it must
stay quiet on — the rules' false-positive/false-negative contract, pinned
so heuristic refinements can't silently weaken a guard.  Allowlist
mechanics (mandatory justifications, suppression, expiry) and baseline
round-tripping are covered at the end.
"""

import textwrap
from pathlib import Path

import pytest

from siddhi_tpu.analysis import (Allowlist, ModuleIndex, get_rule,
                                 run_rules)
from siddhi_tpu.analysis import reporting


def _check(rule_name, rel, src):
    """Raw findings from one rule over one fixture module (no
    allowlist, no cross-module finish hooks)."""
    rule = get_rule(rule_name)
    rule.begin()
    idx = ModuleIndex(Path("fixture.py"), rel, source=textwrap.dedent(src))
    return list(rule.check(idx))


# -- host-sync-hazard -------------------------------------------------------

DEVICE_RT = "siddhi_tpu/ops/device_query.py"  # a scanned device module


def test_host_sync_fires_on_materializer_in_device_module():
    hits = _check("host-sync-hazard", DEVICE_RT, """
        import numpy as np
        class E:
            def process(self, out):
                return np.asarray(out)   # implicit sync fetch
    """)
    assert [(f.line, f.scope) for f in hits] == [(5, "E.process")]
    assert hits[0].key == f"{DEVICE_RT}:E.process"  # line-number-free


def test_host_sync_sees_through_self_receivers():
    hits = _check("host-sync-hazard", DEVICE_RT, """
        class E:
            def process(self, out):
                return self.jax.device_get(out)
    """)
    assert len(hits) == 1


def test_host_sync_quiet_outside_device_modules_and_on_clean_code():
    clean = """
        import numpy as np
        class E:
            def process(self, q, out):
                q.push(out)  # device ref stays on device
    """
    assert _check("host-sync-hazard", DEVICE_RT, clean) == []
    # host-side modules are free to use numpy
    hot = "import numpy as np\ndef f(x):\n    return np.asarray(x)\n"
    assert _check("host-sync-hazard", "siddhi_tpu/core/event.py", hot) == []


# -- ingest-put-bypass ------------------------------------------------------

def test_ingest_put_fires_anywhere_in_the_package():
    hits = _check("ingest-put-bypass", "siddhi_tpu/core/anything.py", """
        import jax
        def ingest(cols):
            return jax.device_put(cols)
    """)
    assert [(f.line, f.scope) for f in hits] == [(4, "ingest")]


def test_ingest_put_quiet_on_staged_put():
    hits = _check("ingest-put-bypass", "siddhi_tpu/core/anything.py", """
        from siddhi_tpu.core.ingest_stage import staged_put
        def ingest(self, cols):
            return staged_put(self.stage, cols)
    """)
    assert hits == []


# -- broad-except-swallow ---------------------------------------------------

def test_broad_except_fires_on_silent_swallow_in_core():
    hits = _check("broad-except-swallow", "siddhi_tpu/core/x.py", """
        def f():
            try:
                g()
            except Exception:
                pass
    """)
    assert len(hits) == 1 and hits[0].scope == "f"


def test_broad_except_quiet_on_narrow_or_logged_handlers():
    narrow = """
        import queue
        def f(q):
            try:
                return q.get_nowait()
            except queue.Empty:
                pass
    """
    logged = """
        def f(log):
            try:
                g()
            except Exception as e:
                log.warning("probe failed: %s", e)
    """
    assert _check("broad-except-swallow", "siddhi_tpu/core/x.py", narrow) == []
    assert _check("broad-except-swallow", "siddhi_tpu/core/x.py", logged) == []
    # layers outside core/ and transport/ are not scanned
    bad = "try:\n    g()\nexcept Exception:\n    pass\n"
    assert _check("broad-except-swallow", "siddhi_tpu/util/x.py", bad) == []


# -- lock-discipline --------------------------------------------------------

def test_lock_discipline_fires_on_unlocked_cross_thread_write():
    hits = _check("lock-discipline", "siddhi_tpu/core/x.py", """
        import threading
        class Worker:
            def start(self):
                self._t = threading.Thread(target=self._loop, daemon=True)
                self._t.start()
            def _loop(self):
                self.count += 1          # thread side, unlocked
            def reset(self):
                self.count = 0           # main side, unlocked
    """)
    assert [f.scope for f in hits] == ["Worker.count"]
    assert hits[0].key == "siddhi_tpu/core/x.py:Worker.count"


def test_lock_discipline_quiet_when_writes_are_locked():
    hits = _check("lock-discipline", "siddhi_tpu/core/x.py", """
        import threading
        class Worker:
            def start(self):
                self._t = threading.Thread(target=self._loop, daemon=True)
                self._t.start()
            def _loop(self):
                with self._lock:
                    self.count += 1
            def reset(self):
                with self._lock:
                    self.count = 0
    """)
    assert hits == []


def test_lock_discipline_excludes_constructors_and_follows_timers():
    # __init__ writes happen-before thread start: not a conflict; but a
    # Timer chain (transport retry style) IS a thread entry.
    hits = _check("lock-discipline", "siddhi_tpu/core/x.py", """
        import threading
        class Retry:
            def __init__(self):
                self.failed = False      # constructor: excluded
            def arm(self):
                t = threading.Timer(1.0, self._fire)
                t.start()
            def _fire(self):
                self.failed = True       # thread side
            def reset(self):
                self.failed = False      # main side -> conflict
    """)
    assert [f.scope for f in hits] == ["Retry.failed"]


def test_lock_discipline_locked_call_site_does_not_extend_closure():
    # Scheduler pattern: the thread loop calls advance() under the
    # process lock, so advance()'s writes are lock-protected.
    hits = _check("lock-discipline", "siddhi_tpu/core/x.py", """
        import threading
        class Sched:
            def start(self):
                self._t = threading.Thread(target=self._loop, daemon=True)
                self._t.start()
            def _loop(self):
                while True:
                    with self.ctx.process_lock:
                        self.advance()
            def advance(self):
                self.head = 1
            def submit(self):
                self.head = 0
    """)
    assert hits == []


# -- jit-purity -------------------------------------------------------------

def test_jit_purity_fires_on_host_effects_in_jitted_step():
    hits = _check("jit-purity", "siddhi_tpu/ops/x.py", """
        import time
        import jax
        class E:
            def build(self, fi, log):
                def step(state, cols):
                    fi.check("device.step")        # fault hook
                    log.debug("stepping")          # logging
                    t0 = time.time()               # host clock
                    self.stats.batches += 1        # stats counter
                    n = int(state.sum())           # tracer materialization
                    return state, n
                self._step = jax.jit(step)
    """)
    whats = sorted(f.message.split(" inside")[0] for f in hits)
    assert len(hits) == 5, whats
    assert all(f.scope == "E.build.step" for f in hits)


def test_jit_purity_resolves_lambdas_and_self_jax_receivers():
    hits = _check("jit-purity", "siddhi_tpu/ops/x.py", """
        class E:
            def build(self):
                self._f = self.jax.jit(lambda x: float(x.sum()))
    """)
    assert len(hits) == 1


def test_jit_purity_quiet_on_pure_step_and_host_side_effects():
    hits = _check("jit-purity", "siddhi_tpu/ops/x.py", """
        import jax
        import jax.numpy as jnp
        class E:
            def build(self):
                def step(state, cols):
                    return state + jnp.sum(cols), jnp.max(cols)
                self._step = jax.jit(step)
            def process(self, state, cols):
                state, peak = self._step(state, cols)
                self.stats.batches += 1   # host side: fine
                return state
    """)
    assert hits == []


# -- retrace-hazard ---------------------------------------------------------

def test_retrace_fires_on_per_batch_wrap():
    hits = _check("retrace-hazard", "siddhi_tpu/ops/x.py", """
        import jax
        class E:
            def process_batch(self, cols):
                f = jax.jit(lambda c: c * 2)   # fresh trace cache per call
                return f(cols)
    """)
    assert [f.scope for f in hits] == ["E.process_batch"]


def test_retrace_quiet_when_memoized_or_off_hot_path():
    memoized = """
        import jax
        class E:
            def process_batch(self, cols):
                if self._f is None:
                    self._f = jax.jit(lambda c: c * 2)
                return self._f(cols)
    """
    cached_local = """
        import jax
        class E:
            def _kernel(self, B):
                k = jax.jit(lambda c: c * 2)
                self._kernels[B] = k
                return k
    """
    builder = """
        import jax
        class E:
            def _build(self):
                return jax.jit(lambda c: c * 2)
    """
    for src in (memoized, cached_local, builder):
        assert _check("retrace-hazard", "siddhi_tpu/ops/x.py", src) == []


# -- fallback-discipline ----------------------------------------------------

def test_fallback_discipline_fires_when_not_counted():
    hits = _check("fallback-discipline", "siddhi_tpu/planner/x.py", """
        from siddhi_tpu.core.exceptions import SiddhiAppCreationError
        def plan(log, name):
            try:
                lower(name)
            except SiddhiAppCreationError as e:
                log.warning("query '%s': fallback (%s)", name, e)
    """)
    assert [f.scope for f in hits] == ["plan"]
    assert "no record_*_fallback" in hits[0].message


def test_fallback_discipline_fires_when_not_logged():
    hits = _check("fallback-discipline", "siddhi_tpu/planner/x.py", """
        from siddhi_tpu.core.exceptions import SiddhiAppCreationError
        def plan(sm, name):
            try:
                lower(name)
            except SiddhiAppCreationError as e:
                sm.record_kernel_fallback(name, str(e))
    """)
    assert [f.scope for f in hits] == ["plan"]
    assert "no log.warning" in hits[0].message


def test_fallback_discipline_quiet_when_counted_and_logged_or_reraised():
    good = """
        from siddhi_tpu.core.exceptions import SiddhiAppCreationError
        def plan(log, sm, name):
            try:
                lower(name)
            except SiddhiAppCreationError as e:
                log.warning("query '%s': fallback (%s)", name, e)
                sm.record_kernel_fallback(name, str(e))
    """
    reraise = """
        from siddhi_tpu.core.exceptions import SiddhiAppCreationError
        def plan(name):
            try:
                lower(name)
            except SiddhiAppCreationError:
                raise
    """
    assert _check("fallback-discipline", "siddhi_tpu/planner/x.py",
                  good) == []
    assert _check("fallback-discipline", "siddhi_tpu/planner/x.py",
                  reraise) == []


def test_fallback_discipline_follows_delegation_in_project_mode():
    """Handler delegates to self._fallback two methods away — the call
    graph proves both obligations are met."""
    rule = get_rule("fallback-discipline")
    src = """
        import logging
        from siddhi_tpu.core.exceptions import SiddhiAppCreationError
        log = logging.getLogger("x")
        class Planner:
            def _fallback(self, name, reason):
                log.warning("query '%s': %s", name, reason)
                self.sm.record_multiplex_fallback(name, reason)
            def plan(self, name):
                try:
                    lower(name)
                except SiddhiAppCreationError as e:
                    return self._fallback(name, str(e))
    """
    idx = ModuleIndex(Path("fixture.py"), "siddhi_tpu/planner/x.py",
                      source=textwrap.dedent(src))
    # lexical mode cannot see into _fallback: it reports the gate
    rule.begin()
    assert [f.scope for f in rule.check(idx)] == ["Planner.plan"]
    # project mode follows the edge and stays quiet
    res = run_rules([idx], [rule], {"fallback-discipline":
                                    Allowlist("fallback-discipline", {})})
    assert res["findings"] == []


# -- thread-lifecycle -------------------------------------------------------

def test_thread_lifecycle_fires_on_unmanaged_thread():
    hits = _check("thread-lifecycle", "siddhi_tpu/core/x.py", """
        import threading
        class W:
            def start(self):
                self._t = threading.Thread(target=self._loop)
                self._t.start()
    """)
    assert [f.scope for f in hits] == ["W.start"]


def test_thread_lifecycle_quiet_on_daemon_or_joined():
    daemon_kw = """
        import threading
        class W:
            def start(self):
                self._t = threading.Thread(target=self._loop, daemon=True)
                self._t.start()
    """
    daemon_attr = """
        import threading
        class W:
            def arm(self):
                t = threading.Timer(1.0, self._fire)
                t.daemon = True
                t.start()
    """
    joined = """
        import threading
        class W:
            def start(self):
                self._t = threading.Thread(target=self._loop)
                self._t.start()
            def stop(self):
                self._t.join()
    """
    cancelled = """
        import threading
        class W:
            def arm(self):
                self._timer = threading.Timer(1.0, self._fire)
                self._timer.start()
            def shutdown(self):
                self._timer.cancel()
    """
    local_joined = """
        import threading
        def run_pool(fns):
            ts = []
            for fn in fns:
                t = threading.Thread(target=fn)
                t.start()
                t.join()
    """
    for src in (daemon_kw, daemon_attr, joined, cancelled, local_joined):
        assert _check("thread-lifecycle", "siddhi_tpu/core/x.py",
                      src) == [], src


def test_thread_lifecycle_join_in_subclass_resolves_in_project_mode():
    """The mixin arms the Timer, the subclass's shutdown cancels it —
    only the MRO-merged view connects the two."""
    rule = get_rule("thread-lifecycle")
    files = {
        "pkg/__init__.py": "",
        "pkg/mix.py": """
            import threading
            class Mix:
                def arm(self):
                    self._timer = threading.Timer(1.0, self._fire)
                    self._timer.start()
        """,
        "pkg/sub.py": """
            from pkg.mix import Mix
            class Sub(Mix):
                def shutdown(self):
                    self._timer.cancel()
        """,
    }
    indexes = [ModuleIndex(Path(rel), rel, source=textwrap.dedent(src))
               for rel, src in files.items()]
    mix_idx = next(i for i in indexes if i.rel == "pkg/mix.py")
    # lexically the mixin's Timer looks unmanaged...
    rule.begin()
    assert [f.scope for f in rule.check(mix_idx)] == ["Mix.arm"]
    # ...project mode finds the subclass shutdown path
    res = run_rules(indexes, [rule], {"thread-lifecycle":
                                      Allowlist("thread-lifecycle", {})})
    assert res["findings"] == []


# -- allowlist mechanics ----------------------------------------------------

BAD_EXCEPT = """
    def f():
        try:
            g()
        except Exception:
            pass
"""


def _run_one(rule_name, rel, src, entries):
    rule = get_rule(rule_name)
    idx = ModuleIndex(Path("fixture.py"), rel,
                      source=textwrap.dedent(src))
    return run_rules([idx], [rule],
                     {rule_name: Allowlist(rule_name, entries)})


def test_allowlist_requires_justification():
    with pytest.raises(ValueError, match="justification"):
        Allowlist("broad-except-swallow", {"siddhi_tpu/core/x.py:f": ""})


def test_allowlist_suppresses_with_justification():
    res = _run_one("broad-except-swallow", "siddhi_tpu/core/x.py",
                   BAD_EXCEPT,
                   {"siddhi_tpu/core/x.py:f": "probe failure is benign"})
    assert res["findings"] == []
    assert [f.scope for f in res["suppressed"]] == ["f"]


def test_allowlist_entries_expire():
    """An entry that no longer trips the rule FAILS the run — lists
    only shrink (the old guards' test_allowlist_not_stale, generalized)."""
    res = _run_one("broad-except-swallow", "siddhi_tpu/core/x.py",
                   "def f():\n    g()\n",   # nothing to suppress anymore
                   {"siddhi_tpu/core/x.py:f": "obsolete"})
    assert [f.rule for f in res["findings"]] == ["stale-allowlist"]
    assert res["findings"][0].key == \
        "broad-except-swallow:siddhi_tpu/core/x.py:f"


def test_resolved_lock_entry_fails_as_stale_allowlist():
    """The cross-module-upgrade hygiene loop: once a sanctioned
    conflict is actually FIXED (the write is locked), its allowlist
    entry fails the run until pruned."""
    fixed = """
        import threading
        class Worker:
            def start(self):
                self._t = threading.Thread(target=self._loop, daemon=True)
                self._t.start()
            def _loop(self):
                with self._lock:
                    self.count += 1
            def reset(self):
                with self._lock:
                    self.count = 0
    """
    res = _run_one("lock-discipline", "siddhi_tpu/core/x.py", fixed,
                   {"siddhi_tpu/core/x.py:Worker.count":
                    "was unlocked before the fix"})
    assert [f.rule for f in res["findings"]] == ["stale-allowlist"]
    assert res["findings"][0].key == \
        "lock-discipline:siddhi_tpu/core/x.py:Worker.count"


# -- SARIF round-trip -------------------------------------------------------

def test_sarif_round_trip_minimal_schema():
    """Findings render to SARIF 2.1.0 with the minimal required shape:
    schema/version, driver rule catalog, one result per finding with a
    physical location and a stable fingerprint."""
    import json

    from siddhi_tpu.analysis import all_rules

    res = _run_one("broad-except-swallow", "siddhi_tpu/core/x.py",
                   BAD_EXCEPT, {})
    rules = all_rules()
    doc = json.loads(reporting.render_sarif(res["findings"], rules))
    assert doc["version"] == "2.1.0"
    assert doc["$schema"].endswith("sarif-schema-2.1.0.json")
    assert len(doc["runs"]) == 1
    run = doc["runs"][0]
    driver = run["tool"]["driver"]
    assert driver["name"] == "siddhi-tpu-analysis"
    ids = [r["id"] for r in driver["rules"]]
    assert ids == [r.name for r in rules]
    assert all(r["shortDescription"]["text"] for r in driver["rules"])
    (result,) = run["results"]
    assert result["ruleId"] == "broad-except-swallow"
    assert ids[result["ruleIndex"]] == "broad-except-swallow"
    assert result["level"] == "error"
    assert result["message"]["text"]
    loc = result["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == "siddhi_tpu/core/x.py"
    assert loc["region"]["startLine"] >= 1
    # the fingerprint is the line-number-free allowlist identity
    assert result["partialFingerprints"]["analysisKey/v1"] == \
        "broad-except-swallow:siddhi_tpu/core/x.py:f"


# -- baseline round-trip ----------------------------------------------------

def test_baseline_round_trip(tmp_path):
    res = _run_one("broad-except-swallow", "siddhi_tpu/core/x.py",
                   BAD_EXCEPT, {})
    assert len(res["findings"]) == 1
    path = tmp_path / "analysis_baseline.json"
    reporting.write_baseline(path, res["findings"])
    baseline = reporting.load_baseline(path)
    kept, baselined, stale = reporting.apply_baseline(
        res["findings"], baseline)
    assert kept == [] and len(baselined) == 1 and stale == []
    # a baselined identity that disappears is reported as stale, not fatal
    kept, baselined, stale = reporting.apply_baseline([], baseline)
    assert kept == [] and baselined == [] and \
        stale == ["broad-except-swallow:siddhi_tpu/core/x.py:f"]
