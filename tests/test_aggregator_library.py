"""Aggregator-library conformance (reference:
query/aggregator/*TestCase.java — sum/avg/count/distinctCount/min/max/
minForever/maxForever/stdDev/and/or/unionSet incremental executors,
including windowed subtract paths)."""

import math

import pytest

from siddhi_tpu import SiddhiManager


@pytest.fixture
def manager():
    m = SiddhiManager()
    yield m
    m.shutdown()


def run(manager, select, rows, window=""):
    app = (
        "define stream S (sym string, v long, d double, b bool); "
        f"from S{window} select {select} insert into O;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    got = []
    rt.add_callback("O", lambda evs: got.extend(evs))
    rt.start()
    h = rt.get_input_handler("S")
    for r in rows:
        h.send(r)
    rt.shutdown()
    return [e.data for e in got]


ROWS = [
    ["A", 10, 1.5, True],
    ["B", 20, 2.5, True],
    ["A", 30, 3.5, False],
]


class TestRunningAggregators:
    def test_distinct_count(self, manager):
        out = run(manager, "distinctCount(sym) as dc", ROWS)
        assert [r[0] for r in out] == [1, 2, 2]

    def test_min_forever_max_forever(self, manager):
        out = run(manager, "minForever(v) as mn, maxForever(v) as mx", ROWS)
        assert out == [[10, 10], [10, 20], [10, 30]]

    def test_stddev(self, manager):
        out = run(manager, "stdDev(v) as sd", ROWS)
        # population stddev (reference semantics): 0, 5, 8.1649...
        assert out[0][0] == 0.0
        assert abs(out[1][0] - 5.0) < 1e-9
        assert abs(out[2][0] - math.sqrt(200 / 3)) < 1e-9

    def test_bool_and_or(self, manager):
        out = run(manager, "and(b) as allb, or(b) as anyb", ROWS)
        assert out == [[True, True], [True, True], [False, True]]

    def test_union_set(self, manager):
        out = run(manager, "unionSet(sym) as s", ROWS)
        assert [sorted(r[0]) for r in out] == [["A"], ["A", "B"], ["A", "B"]]

    def test_double_sum_precision(self, manager):
        out = run(manager, "sum(d) as t", ROWS)
        assert [r[0] for r in out] == [1.5, 4.0, 7.5]


class TestWindowedAggregators:
    """Expiry (subtract) paths over a sliding length window."""

    def test_windowed_distinct_count_subtracts(self, manager):
        out = run(manager, "distinctCount(sym) as dc", ROWS + [["B", 40, 4.5, True]],
                  window="#window.length(2)")
        # windows: [A], [A,B], [B,A], [A,B]
        assert [r[0] for r in out] == [1, 2, 2, 2]

    def test_windowed_min_max_heap(self, manager):
        out = run(manager, "min(v) as mn, max(v) as mx",
                  ROWS + [["C", 5, 0.0, True]], window="#window.length(2)")
        assert out == [[10, 10], [10, 20], [20, 30], [5, 30]]

    def test_windowed_stddev(self, manager):
        out = run(manager, "stdDev(v) as sd", ROWS, window="#window.length(2)")
        assert abs(out[2][0] - 5.0) < 1e-9  # window [20, 30]

    def test_windowed_bool_and(self, manager):
        out = run(manager, "and(b) as allb", ROWS + [["C", 1, 0.0, True]],
                  window="#window.length(2)")
        # windows: [T], [T,T], [T,F], [F,T]
        assert [r[0] for r in out] == [True, True, False, False]


class TestOuterJoins:
    APP = (
        "define stream L (k string, lv long); "
        "define stream R (k string, rv long); "
    )

    def collect(self, manager, app, sends):
        rt = manager.create_siddhi_app_runtime(app)
        got = []
        rt.add_callback("O", lambda evs: got.extend(evs))
        rt.start()
        for stream, row in sends:
            rt.get_input_handler(stream).send(row)
        rt.shutdown()
        return [e.data for e in got]

    def test_right_outer_join(self, manager):
        app = self.APP + (
            "from L#window.length(10) right outer join R#window.length(10) "
            "on L.k == R.k select R.k as k, L.lv as lv, R.rv as rv insert into O;"
        )
        out = self.collect(manager, app, [
            ("R", ["x", 1]),          # no left match -> emitted with null lv
            ("L", ["x", 7]),          # match emits joined row
        ])
        assert out[0][0] == "x" and out[0][1] is None and out[0][2] == 1
        assert ["x", 7, 1] in out

    def test_full_outer_join(self, manager):
        app = self.APP + (
            "from L#window.length(10) full outer join R#window.length(10) "
            "on L.k == R.k select L.lv as lv, R.rv as rv insert into O;"
        )
        out = self.collect(manager, app, [
            ("L", ["a", 1]),   # unmatched left -> [1, None]
            ("R", ["b", 2]),   # unmatched right -> [None, 2]
        ])
        assert [1, None] in out and [None, 2] in out
