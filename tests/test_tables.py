"""Table conformance tests.

Modeled on the reference table test corpus
(modules/siddhi-core/src/test/java/io/siddhi/core/query/table/
InsertIntoTableTestCase / DeleteFromTableTestCase / UpdateFromTableTestCase
/ UpdateOrInsertTableTestCase / IndexedTableTestCase): SiddhiQL string in,
events in, asserted table contents / query outputs out.
"""

import pytest

from siddhi_tpu import SiddhiManager


@pytest.fixture
def manager():
    m = SiddhiManager()
    yield m
    m.shutdown()


def table_rows(runtime, name):
    t = runtime.tables[name]
    b = t.rows_batch()
    return sorted(
        tuple(b.columns[nm][i] for nm in b.attribute_names) for i in range(len(b))
    )


def test_insert_into_table(manager):
    app = (
        "define stream StockStream (symbol string, price float, volume long); "
        "define table StockTable (symbol string, price float, volume long); "
        "from StockStream insert into StockTable;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    h = rt.get_input_handler("StockStream")
    h.send(["WSO2", 55.6, 100])
    h.send(["IBM", 75.6, 10])
    assert table_rows(rt, "StockTable") == [("IBM", 75.6, 10), ("WSO2", 55.6, 100)]


def test_insert_with_projection(manager):
    app = (
        "define stream S (symbol string, price float, volume long); "
        "define table T (symbol string, volume long); "
        "from S select symbol, volume insert into T;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    rt.get_input_handler("S").send(["WSO2", 55.6, 100])
    assert table_rows(rt, "T") == [("WSO2", 100)]


def test_delete_on_condition(manager):
    app = (
        "define stream StockStream (symbol string, price float, volume long); "
        "define stream DeleteStockStream (symbol string); "
        "define table StockTable (symbol string, price float, volume long); "
        "from StockStream insert into StockTable; "
        "from DeleteStockStream delete StockTable on StockTable.symbol == symbol;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    rt.get_input_handler("StockStream").send(["WSO2", 55.6, 100])
    rt.get_input_handler("StockStream").send(["IBM", 75.6, 10])
    rt.get_input_handler("DeleteStockStream").send(["IBM"])
    assert table_rows(rt, "StockTable") == [("WSO2", 55.6, 100)]


def test_update_on_condition(manager):
    app = (
        "define stream StockStream (symbol string, price float, volume long); "
        "define stream UpdateStream (symbol string, price float); "
        "define table StockTable (symbol string, price float, volume long); "
        "from StockStream insert into StockTable; "
        "from UpdateStream update StockTable set StockTable.price = price "
        "on StockTable.symbol == symbol;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    rt.get_input_handler("StockStream").send(["WSO2", 55.6, 100])
    rt.get_input_handler("StockStream").send(["IBM", 75.6, 10])
    rt.get_input_handler("UpdateStream").send(["IBM", 99.0])
    assert table_rows(rt, "StockTable") == [("IBM", 99.0, 10), ("WSO2", 55.6, 100)]


def test_update_without_set_copies_matching_attrs(manager):
    app = (
        "define stream StockStream (symbol string, price float, volume long); "
        "define stream UpdateStream (symbol string, price float, volume long); "
        "define table StockTable (symbol string, price float, volume long); "
        "from StockStream insert into StockTable; "
        "from UpdateStream update StockTable on StockTable.symbol == symbol;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    rt.get_input_handler("StockStream").send(["WSO2", 55.6, 100])
    rt.get_input_handler("UpdateStream").send(["WSO2", 77.7, 200])
    assert table_rows(rt, "StockTable") == [("WSO2", 77.7, 200)]


def test_update_or_insert(manager):
    app = (
        "define stream UpsertStream (symbol string, price float, volume long); "
        "define table StockTable (symbol string, price float, volume long); "
        "from UpsertStream update or insert into StockTable "
        "set StockTable.price = price, StockTable.volume = volume "
        "on StockTable.symbol == symbol;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    h = rt.get_input_handler("UpsertStream")
    h.send(["WSO2", 55.6, 100])
    h.send(["IBM", 75.6, 10])
    h.send(["WSO2", 57.6, 300])
    assert table_rows(rt, "StockTable") == [("IBM", 75.6, 10), ("WSO2", 57.6, 300)]


def test_in_table_condition(manager):
    app = (
        "define stream StockStream (symbol string, price float); "
        "define stream CheckStream (symbol string); "
        "@PrimaryKey('symbol') "
        "define table StockTable (symbol string, price float); "
        "from StockStream insert into StockTable; "
        "@info(name='q') "
        "from CheckStream[symbol in StockTable] insert into OutStream;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    got = []
    rt.add_callback("OutStream", lambda events: got.extend(e.data for e in events))
    rt.get_input_handler("StockStream").send(["WSO2", 55.6])
    rt.get_input_handler("CheckStream").send(["WSO2"])
    rt.get_input_handler("CheckStream").send(["IBM"])
    assert got == [["WSO2"]]


def test_primary_key_upsert_semantics(manager):
    """Insert with an existing primary key replaces the row."""
    app = (
        "define stream S (symbol string, price float); "
        "@PrimaryKey('symbol') "
        "define table T (symbol string, price float); "
        "from S insert into T;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    h = rt.get_input_handler("S")
    h.send(["WSO2", 1.0])
    h.send(["WSO2", 2.0])
    h.send(["IBM", 3.0])
    assert table_rows(rt, "T") == [("IBM", 3.0), ("WSO2", 2.0)]


def test_indexed_delete_uses_index(manager):
    app = (
        "define stream S (symbol string, price float); "
        "define stream D (symbol string); "
        "@Index('symbol') "
        "define table T (symbol string, price float); "
        "from S insert into T; "
        "from D delete T on T.symbol == symbol;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    for sym, p in [("A", 1.0), ("B", 2.0), ("A", 3.0), ("C", 4.0)]:
        rt.get_input_handler("S").send([sym, p])
    rt.get_input_handler("D").send(["A"])
    assert table_rows(rt, "T") == [("B", 2.0), ("C", 4.0)]
    # index maintained after delete
    t = rt.tables["T"]
    assert set(t.indexes["symbol"].keys()) == {"B", "C"}


def test_multi_attr_primary_key_probe(manager):
    app = (
        "define stream S (a string, b int, v double); "
        "define stream D (a string, b int); "
        "@PrimaryKey('a','b') "
        "define table T (a string, b int, v double); "
        "from S insert into T; "
        "from D delete T on T.a == a and T.b == b;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    for row in [["x", 1, 1.0], ["x", 2, 2.0], ["y", 1, 3.0]]:
        rt.get_input_handler("S").send(row)
    rt.get_input_handler("D").send(["x", 2])
    assert table_rows(rt, "T") == [("x", 1, 1.0), ("y", 1, 3.0)]


def test_delete_with_compound_condition_scan(manager):
    app = (
        "define stream D (threshold double); "
        "define stream S (symbol string, price double); "
        "define table T (symbol string, price double); "
        "from S insert into T; "
        "from D delete T on T.price < threshold;"
    )
    rt = manager.create_siddhi_app_runtime(app)
    rt.start()
    for row in [["A", 10.0], ["B", 20.0], ["C", 30.0]]:
        rt.get_input_handler("S").send(row)
    rt.get_input_handler("D").send([25.0])
    assert table_rows(rt, "T") == [("C", 30.0)]
