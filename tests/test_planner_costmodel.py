"""Cost-based unified lowering (planner/costmodel.py) suite.

``@app:plan(auto='true')`` replaces the per-annotation opt-ins with one
cost-model pass: every query's eligible lowerings — including the
fuse+shard composition the annotation gates never offered — are scored
with static shape/arity costs and the cheapest feasible candidate wins.
Explicit annotations keep working as pins.

The contract under test:

- auto mode reaches the SAME lowering as the hand-annotated equivalent
  on each existing differential shape (fuse chain, multiplex tumbling
  window, mesh-sharded partition, hot-key partition);
- the fuse+shard composition runs bit-identical to the dedicated
  single-device fused engine;
- cost-gate rejections are counted (plannerFallbacks) and pinned
  annotation conflicts are counted (plannerConflicts) — never silent;
- ``PlanMonitor.decide()`` re-scores with observed batch widths and
  respects the hysteresis margin.
"""

import types

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.exceptions import SiddhiAppCreationError
from siddhi_tpu.planner import costmodel as cm
from siddhi_tpu.planner.monitor import MIN_BATCHES, PlanMonitor


def _collector(res):
    return lambda events: res.extend(
        (e.timestamp, tuple(e.data)) for e in events)


def _lowering(app):
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime(app)
        rt.start()
        out = dict(rt.lowering())
        rt.shutdown()
        return out
    finally:
        m.shutdown()


FUSE_APP = """
@app:name('cf{tag}') @app:playback @app:execution('tpu') {ann}
define stream SIn (sym int, price float, vol int);
@info(name='q1') from SIn[price > 10.0]
select sym, price, vol insert into Mid;
@info(name='q2') from Mid[vol > 50] select sym, price insert into Out;
"""

MUX_APP = """
@app:name('cm{tag}') @app:execution('tpu') @app:playback {ann}
define stream S (k long, v double);
@info(name='qw') from S#window.lengthBatch(4)
select k, sum(v) as s, count() as c group by k insert into OutW;
"""

SHARD_APP = """
@app:playback @app:execution('tpu', partitions='64', devices='8') {ann}
define stream Txn (card string, amount double);
partition with (card of Txn) begin
@info(name='q') from every a=Txn[amount > 100.0] -> b=Txn[amount > a.amount]
within 10 min select a.amount as base, b.amount as bv insert into Alerts;
end;
"""

HK_APP = """
@app:playback @app:execution('tpu', instances='16') {ann}
define stream S (k long, u double, v double);
partition with (k of S) begin
@info(name='q') from every a=S[v > 8.0] -> b=S[v > 12.0]
select b.v as bv insert into Alerts;
end;
"""

AUTO = "@app:plan(auto='true')"


class TestAutoVsAnnotatedParity:
    """Un-annotated + @app:plan(auto) lands on the same lowering the
    hand-annotated app pins, on every existing differential shape."""

    def test_fuse_shape(self):
        ann = _lowering(FUSE_APP.format(tag="a", ann="@app:fuse"))
        auto = _lowering(FUSE_APP.format(tag="b", ann=AUTO))
        assert ann == {"q1": "fused", "q2": "fused"}
        assert auto == ann

    def test_multiplex_shape(self):
        ann = _lowering(MUX_APP.format(
            tag="a", ann="@app:multiplex(slots='8')"))
        auto = _lowering(MUX_APP.format(tag="b", ann=AUTO))
        assert ann == {"qw": "multiplex"}
        assert auto == ann

    def test_shard_shape(self):
        def run(ann):
            m = SiddhiManager()
            try:
                rt = m.create_siddhi_app_runtime(SHARD_APP.format(ann=ann))
                rt.start()
                low = dict(rt.lowering())
                pr = rt.partitions.get("partition_0")
                runtime = next(
                    iter(pr.dense_query_runtimes.values())).pattern_processor
                sharded = runtime._sharded is not None
                rt.shutdown()
                return low, sharded
            finally:
                m.shutdown()

        ann_low, ann_sharded = run("")
        auto_low, auto_sharded = run(AUTO)
        assert ann_low == auto_low == {"q": "dense"}
        # a declared mesh IS the shard pin: auto mode keeps the 8-way
        # sharded dense engine the legacy planner builds
        assert ann_sharded and auto_sharded

    def test_hotkey_shape(self):
        ann = _lowering(HK_APP.format(
            ann="@app:hotkeys(k='4', promote='0.3', demote='0.1')"))
        auto = _lowering(HK_APP.format(ann=AUTO))
        assert ann == {"q": "hotkey"}
        assert auto == ann


class TestFuseShardComposition:
    """The composition the annotation gates forbade: an all-filter
    fused chain with its batch axis sharded over the mesh, bit-identical
    to the dedicated single-device fused engine."""

    def _run(self, dev, ann, sends):
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(
                FUSE_APP.format(tag="s" if dev else "r", ann=ann)
                .replace("@app:execution('tpu')",
                         f"@app:execution('tpu'{dev})"))
            got = []
            rt.add_callback("Out", _collector(got))
            rt.start()
            h = rt.get_input_handler("SIn")
            for row, ts in sends:
                h.send(list(row), timestamp=ts)
            low = dict(rt.lowering())
            rt.shutdown()
            return got, low
        finally:
            m.shutdown()

    def test_fuse_shard_bit_identical_to_fused_reference(self):
        rng = np.random.default_rng(7)
        sends, ts = [], 1000
        for _ in range(300):
            sends.append(([int(rng.integers(0, 5)),
                           float(np.float32(rng.uniform(0, 30))),
                           int(rng.integers(1, 100))], ts))
            ts += 3
        ref, low_ref = self._run("", "@app:fuse", sends)
        got, low = self._run(", devices='8'", AUTO, sends)
        assert low_ref == {"q1": "fused", "q2": "fused"}
        assert low == {"q1": "fuse+shard", "q2": "fuse+shard"}
        assert len(ref) > 0
        assert got == ref


class TestCostModelUnits:
    def _traits(self, kind="single", **kw):
        t = cm.QueryTraits(kind)
        for k, v in kw.items():
            setattr(t, k, v)
        return t

    def _ctx(self, devices=0, slots=8):
        return types.SimpleNamespace(tpu_devices=devices,
                                     multiplex_slots=slots)

    def test_host_cost_grows_with_batch_device_amortizes(self):
        t, ctx = self._traits(), self._ctx()
        assert cm.score_path("host", t, ctx, 64) \
            < cm.score_path("host", t, ctx, 4096)
        # at the planning batch hint the device path beats host
        assert cm.score_path("device", t, ctx, cm.BATCH_HINT) \
            < cm.score_path("host", t, ctx, cm.BATCH_HINT)
        # at tiny batches the dispatch+H2D overhead flips the order
        assert cm.score_path("host", t, ctx, 4) \
            < cm.score_path("device", t, ctx, 4)

    def test_multiplex_amortizes_dispatch_and_fusion_kills_hops(self):
        t, ctx = self._traits(tumbling_batch=True), self._ctx()
        assert cm.score_path("multiplex", t, ctx, cm.BATCH_HINT) \
            < cm.score_path("device", t, ctx, cm.BATCH_HINT)
        chain = self._traits(n_stages=3)
        # a 3-stage fused program vs 3 dispatches + 2 junction hops
        three_dedicated = 3 * cm.score_path(
            "device", self._traits(), ctx, cm.BATCH_HINT) \
            + 2 * cm.JUNCTION_HOP
        assert cm.score_path("fuse", chain, ctx, cm.BATCH_HINT) \
            < three_dedicated

    def test_uncomposable_paths_raise_with_reason(self):
        t = self._traits("state")
        ctx = self._ctx(devices=8)
        for path, frag in [
            ("multiplex+hotkey", "not composable"),
            ("dense+hotkey+shard", "not composable"),
            ("multiplex+shard", "does not multiplex"),
        ]:
            with pytest.raises(SiddhiAppCreationError, match=frag):
                cm._check_composable(path, t, ctx)
        with pytest.raises(SiddhiAppCreationError, match="no device mesh"):
            cm._check_composable("device+shard", t, self._ctx(devices=0))

    def test_auto_mode_counts_rejected_candidates(self):
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime("""
@app:name('cj') @app:playback @app:execution('tpu') @app:plan(auto='true')
define stream S (sym int, price float);
@info(name='q1') from S[price > 10.0] select sym, price insert into Out;
""")
            rt.start()
            st = rt.statistics()
            # a sliding filter cannot seat in a multiplex group: the
            # enumerated candidate is rejected, logged AND counted
            key = "io.siddhi.SiddhiApps.cj.Siddhi.Queries.q1"
            assert st[f"{key}.plannerFallbacks"] >= 1
            assert "multiplex" in st[f"{key}.plannerFallbackReason"]
            rt.shutdown()
        finally:
            m.shutdown()

    def test_pinned_annotation_conflict_is_counted(self):
        # @app:multiplex + a declared mesh: precedence says shard wins
        # (mesh-sharded state does not multiplex) and the losing pin is
        # counted, never silent
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(MUX_APP.format(
                tag="c", ann="@app:multiplex(slots='8')").replace(
                "@app:execution('tpu')",
                "@app:execution('tpu', devices='8')"))
            rt.start()
            st = rt.statistics()
            conf = {k: v for k, v in st.items() if "plannerConflict" in k}
            assert any(v for k, v in conf.items()
                       if k.endswith("plannerConflicts")), st
            rt.shutdown()
        finally:
            m.shutdown()


class TestPlanMonitorDecide:
    """decide() is side-effect free: feed it observed widths, read the
    pins it would switch."""

    def _auto_rt(self, m):
        rt = m.create_siddhi_app_runtime("""
@app:name('mon') @app:playback @app:execution('tpu') @app:plan(auto='true')
define stream S (sym int, price float);
@info(name='q1') from S[price > 10.0] select sym insert into Out;
""")
        rt.start()
        return rt

    def _feed(self, rt, events, batches):
        sm = rt.app_context.statistics_manager
        sm.latency["q1"] = types.SimpleNamespace(
            name="q1", events=events, batches=batches)

    def test_small_observed_batches_switch_to_host(self):
        m = SiddhiManager()
        try:
            rt = self._auto_rt(m)
            assert rt.lowering() == {"q1": "device"}
            mon = PlanMonitor(rt)
            # device was chosen at the 4096-event planning hint; the
            # app actually sees 4-event batches where host dispatch wins
            self._feed(rt, events=40, batches=10)
            assert mon.decide() == {"q1": "host"}
            rt.shutdown()
        finally:
            m.shutdown()

    def test_hysteresis_margin_blocks_marginal_wins(self):
        m = SiddhiManager()
        try:
            rt = self._auto_rt(m)
            mon = PlanMonitor(rt)
            # at ~47 events/batch host is cheaper than device but NOT
            # by the 30% hysteresis margin — no flip-flop
            self._feed(rt, events=470, batches=10)
            assert mon.decide() == {}
            # a wider margin setting blocks even the clear win
            strict = PlanMonitor(rt, hysteresis=9.0)
            self._feed(rt, events=40, batches=10)
            assert strict.decide() == {}
            rt.shutdown()
        finally:
            m.shutdown()

    def test_too_few_batches_is_not_evidence(self):
        m = SiddhiManager()
        try:
            rt = self._auto_rt(m)
            mon = PlanMonitor(rt)
            self._feed(rt, events=4, batches=MIN_BATCHES - 1)
            assert mon.decide() == {}
            rt.shutdown()
        finally:
            m.shutdown()

    def test_pinned_records_never_auto_switch(self):
        m = SiddhiManager()
        try:
            rt = self._auto_rt(m)
            sm = rt.app_context.statistics_manager
            sm.plans["q1"].mode = "pinned"
            mon = PlanMonitor(rt)
            self._feed(rt, events=40, batches=10)
            assert mon.decide() == {}
            rt.shutdown()
        finally:
            m.shutdown()
