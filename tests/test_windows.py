"""Window conformance tests (reference: query/window/*TestCase.java).

Time-driven windows run in playback mode (@app:playback) so event
timestamps drive the clock deterministically.
"""

import time

import pytest

from siddhi_tpu import SiddhiManager


@pytest.fixture
def manager():
    m = SiddhiManager()
    yield m
    m.shutdown()


def run_pb(manager, app, sends, out="OutputStream"):
    """Playback-mode run; sends = [(row, ts)]."""
    rt = manager.create_siddhi_app_runtime("@app:playback " + app)
    got = []
    rt.add_callback(out, lambda evs: got.extend(evs))
    rt.start()
    h = rt.get_input_handler("S")
    for row, ts in sends:
        h.send(row, timestamp=ts)
    rt.shutdown()
    return got


class TestTimeWindow:
    APP = (
        "define stream S (symbol string, v long); "
        "from S#window.time(1 sec) select symbol, sum(v) as total "
        "insert all events into OutputStream;"
    )

    def test_sliding_time_sum(self, manager):
        got = run_pb(manager, self.APP, [
            (["A", 10], 1000),
            (["B", 20], 1500),
            (["C", 30], 2100),  # A (ts 1000) expired at 2000 <= 2100
        ])
        # outputs: A(10), B(30), expired-A(20), C(50)
        totals = [e.data[1] for e in got]
        assert totals == [10, 30, 20, 50]

    def test_wall_clock_expiry(self, manager):
        app = (
            "define stream S (v long); "
            "@info(name='q') from S#window.time(100 millisec) select v "
            "insert expired events into OutputStream;"
        )
        rt = manager.create_siddhi_app_runtime(app)
        got = []
        rt.add_callback("OutputStream", lambda evs: got.extend(evs))
        rt.start()
        rt.get_input_handler("S").send([7])
        time.sleep(0.4)  # background scheduler tick fires expiry
        rt.shutdown()
        assert [e.data for e in got] == [[7]]


class TestTimeBatchWindow:
    def test_tumbling_flush(self, manager):
        app = (
            "define stream S (v long); "
            "from S#window.timeBatch(1 sec) select sum(v) as total "
            "insert into OutputStream;"
        )
        got = run_pb(manager, app, [
            ([1], 1000),
            ([2], 1400),
            ([3], 2000),  # flush [1,2] at 2000, start new window
            ([4], 2500),
            ([5], 3100),  # flush [3,4]
        ])
        assert [e.data[0] for e in got] == [3, 7]

    def test_group_by_batch_mode(self, manager):
        app = (
            "define stream S (sym string, v long); "
            "from S#window.timeBatch(1 sec) select sym, sum(v) as t group by sym "
            "insert into OutputStream;"
        )
        got = run_pb(manager, app, [
            (["A", 1], 1000),
            (["B", 10], 1200),
            (["A", 2], 1400),
            (["X", 0], 2100),  # triggers flush of window 1
            (["Y", 0], 3200),  # flush window 2 (X)
        ])
        first = {tuple(e.data) for e in got[:2]}
        assert first == {("A", 3), ("B", 10)}


class TestExternalTime:
    def test_external_time_sliding(self, manager):
        app = (
            "define stream S (ts long, v long); "
            "from S#window.externalTime(ts, 1 sec) select sum(v) as total "
            "insert all events into OutputStream;"
        )
        got = run_pb(manager, app, [
            ([1000, 10], 1),
            ([1500, 20], 2),
            ([2100, 30], 3),  # expires ts=1000 row
        ])
        totals = [e.data[0] for e in got]
        assert totals == [10, 30, 20, 50]

    def test_external_time_batch(self, manager):
        app = (
            "define stream S (ts long, v long); "
            "from S#window.externalTimeBatch(ts, 1 sec) select sum(v) as total "
            "insert into OutputStream;"
        )
        got = run_pb(manager, app, [
            ([1000, 1], 1),
            ([1400, 2], 2),
            ([2000, 3], 3),
            ([2500, 4], 4),
            ([3100, 5], 5),
        ])
        assert [e.data[0] for e in got] == [3, 7]


class TestSortWindow:
    def test_sort_keeps_smallest(self, manager):
        app = (
            "define stream S (v long); "
            "from S#window.sort(2, v) select v insert expired events into OutputStream;"
        )
        got = run_pb(manager, app, [
            ([50], 1000),
            ([20], 1100),
            ([40], 1200),  # evicts 50 (largest)
            ([10], 1300),  # evicts 40
        ])
        assert [e.data[0] for e in got] == [50, 40]

    def test_sort_desc(self, manager):
        app = (
            "define stream S (v long); "
            "from S#window.sort(2, v, 'desc') select v insert expired events into OutputStream;"
        )
        got = run_pb(manager, app, [
            ([50], 1000),
            ([20], 1100),
            ([40], 1200),  # desc keeps largest: evicts 20
        ])
        assert [e.data[0] for e in got] == [20]


class TestDelayWindow:
    def test_delay_releases_later(self, manager):
        app = (
            "define stream S (v long); "
            "from S#window.delay(1 sec) select v insert into OutputStream;"
        )
        got = run_pb(manager, app, [
            ([1], 1000),
            ([2], 1500),
            ([3], 2100),  # releases v=1 (due at 2000)
            ([4], 2600),  # releases v=2
        ])
        assert [e.data[0] for e in got] == [1, 2]


class TestTimeLengthWindow:
    def test_length_bound(self, manager):
        app = (
            "define stream S (v long); "
            "from S#window.timeLength(10 sec, 2) select v "
            "insert expired events into OutputStream;"
        )
        got = run_pb(manager, app, [
            ([1], 1000),
            ([2], 1100),
            ([3], 1200),  # length 2 exceeded -> expire v=1
        ])
        assert [e.data[0] for e in got] == [1]

    def test_time_bound(self, manager):
        app = (
            "define stream S (v long); "
            "from S#window.timeLength(1 sec, 10) select v "
            "insert expired events into OutputStream;"
        )
        got = run_pb(manager, app, [
            ([1], 1000),
            ([2], 2100),  # v=1 expired by time
        ])
        assert [e.data[0] for e in got] == [1]


class TestFrequentWindows:
    def test_frequent(self, manager):
        app = (
            "define stream S (sym string, v long); "
            "from S#window.frequent(1, sym) select sym, v insert into OutputStream;"
        )
        got = run_pb(manager, app, [
            (["A", 1], 1000),
            (["A", 2], 1100),
            (["B", 3], 1200),  # decrements A, no emit for B
            (["A", 4], 1300),
        ])
        assert [e.data[0] for e in got] == ["A", "A", "A"]

    def test_batch_window(self, manager):
        app = (
            "define stream S (v long); "
            "from S#window.batch() select sum(v) as t insert into OutputStream;"
        )
        rt = manager.create_siddhi_app_runtime("@app:playback " + app)
        got = []
        rt.add_callback("OutputStream", lambda evs: got.extend(evs))
        rt.start()
        from siddhi_tpu.core.event import Event

        h = rt.get_input_handler("S")
        h.send([Event(1000, [1]), Event(1000, [2])])  # one chunk
        h.send([Event(1100, [5]), Event(1100, [6])])  # next chunk expires prev
        rt.shutdown()
        # batch mode: only the final aggregate per chunk
        assert [e.data[0] for e in got] == [3, 11]


class TestSessionWindow:
    def test_session_close_by_gap(self, manager):
        app = (
            "define stream S (user string, v long); "
            "from S#window.session(1 sec, user) select user, v "
            "insert expired events into OutputStream;"
        )
        got = run_pb(manager, app, [
            (["u1", 1], 1000),
            (["u1", 2], 1500),
            (["u2", 9], 1800),
            (["u1", 3], 3000),  # u1 session (last 1500) closed at 2500; u2 (1800) closed at 2800
        ])
        datas = [tuple(e.data) for e in got]
        assert ("u1", 1) in datas and ("u1", 2) in datas and ("u2", 9) in datas


class TestOutputRateLimiting:
    def test_first_every_n_events(self, manager):
        app = (
            "define stream S (v long); "
            "from S select v output first every 3 events insert into OutputStream;"
        )
        rt = manager.create_siddhi_app_runtime(app)
        got = []
        rt.add_callback("OutputStream", lambda evs: got.extend(evs))
        rt.start()
        h = rt.get_input_handler("S")
        for i in range(7):
            h.send([i])
        rt.shutdown()
        assert [e.data[0] for e in got] == [0, 3, 6]

    def test_last_every_n_events(self, manager):
        app = (
            "define stream S (v long); "
            "from S select v output last every 3 events insert into OutputStream;"
        )
        rt = manager.create_siddhi_app_runtime(app)
        got = []
        rt.add_callback("OutputStream", lambda evs: got.extend(evs))
        rt.start()
        h = rt.get_input_handler("S")
        for i in range(6):
            h.send([i])
        rt.shutdown()
        assert [e.data[0] for e in got] == [2, 5]

    def test_all_every_n_events(self, manager):
        app = (
            "define stream S (v long); "
            "from S select v output all every 2 events insert into OutputStream;"
        )
        rt = manager.create_siddhi_app_runtime(app)
        chunks = []
        rt.add_callback("OutputStream", lambda evs: chunks.append([e.data[0] for e in evs]))
        rt.start()
        h = rt.get_input_handler("S")
        for i in range(5):
            h.send([i])
        rt.shutdown()
        assert chunks == [[0, 1], [2, 3]]

    def test_time_rate_last(self, manager):
        app = (
            "define stream S (v long); "
            "from S select v output last every 1 sec insert into OutputStream;"
        )
        got = run_pb(manager, app, [
            ([1], 1000),
            ([2], 1400),
            ([3], 2100),  # period [1000,2000) flushes last=2
        ])
        assert [e.data[0] for e in got] == [2]

    def test_snapshot_rate(self, manager):
        app = (
            "define stream S (sym string, v long); "
            "from S select sym, sum(v) as t group by sym "
            "output snapshot every 1 sec insert into OutputStream;"
        )
        got = run_pb(manager, app, [
            (["A", 1], 1000),
            (["B", 5], 1200),
            (["A", 2], 1500),
            (["X", 0], 2100),  # snapshot of latest per group
        ])
        datas = {tuple(e.data) for e in got}
        assert ("A", 3) in datas and ("B", 5) in datas


class TestCronWindow:
    def test_cron_batch_flush(self, manager):
        # fire every second; events held until the fire, then batched out
        app = (
            "define stream S (symbol string, v long); "
            "from S#window.cron('* * * * * ?') select symbol, sum(v) as total "
            "insert into OutputStream;"
        )
        got = run_pb(manager, app, [
            (["A", 10], 1000),
            (["B", 20], 1400),
            (["C", 30], 2500),  # past the 2000ms cron fire -> flush A,B first
        ])
        # at the 2000ms fire: batch A+B flushed as one batch -> sum 30
        assert [e.data[1] for e in got] == [30]


class TestExpressionWindow:
    def test_count_retention(self, manager):
        app = (
            "define stream S (symbol string, v long); "
            "from S#window.expression('count() <= 2') select symbol, sum(v) as total "
            "insert all events into OutputStream;"
        )
        got = run_pb(manager, app, [
            (["A", 10], 1000),
            (["B", 20], 1100),
            (["C", 30], 1200),  # A evicted: count()<=2
        ])
        totals = [e.data[1] for e in got]
        # A(10), B(30), expired-A(20), C(50)
        assert totals == [10, 30, 20, 50]

    def test_sum_retention(self, manager):
        app = (
            "define stream S (symbol string, v long); "
            "from S#window.expression('sum(v) < 100') select symbol, sum(v) as total "
            "insert into OutputStream;"
        )
        got = run_pb(manager, app, [
            (["A", 60], 1000),
            (["B", 50], 1100),   # 110 >= 100 -> evict A
            (["C", 40], 1200),   # 90 ok
        ])
        totals = [e.data[1] for e in got]
        assert totals == [60, 50, 90]

    def test_first_last_timestamp_span(self, manager):
        app = (
            "define stream S (v long); "
            "from S#window.expression('eventTimestamp(last) - eventTimestamp(first) < 1000') "
            "select sum(v) as total insert into OutputStream;"
        )
        got = run_pb(manager, app, [
            ([1], 1000),
            ([2], 1500),
            ([4], 2200),  # first=1000 span 1200 -> evict; then span 700 ok
        ])
        totals = [e.data[0] for e in got]
        assert totals == [1, 3, 6]


class TestExpressionBatchWindow:
    def test_count_batch(self, manager):
        app = (
            "define stream S (symbol string, v long); "
            "from S#window.expressionBatch('count() <= 2') "
            "select symbol, sum(v) as total insert into OutputStream;"
        )
        got = run_pb(manager, app, [
            (["A", 10], 1000),
            (["B", 20], 1100),
            (["C", 30], 1200),  # count 3 > 2 -> flush [A,B], C starts new batch
            (["D", 40], 1300),
            (["E", 50], 1400),  # flush [C,D]
        ])
        # batch [A,B] flushed (sum 30), then batch [C,D] (sum 70)
        assert [e.data[1] for e in got] == [30, 70]

    def test_attribute_trigger_include(self, manager):
        app = (
            "define stream S (v long, flush bool); "
            "from S#window.expressionBatch('not flush', true) "
            "select sum(v) as total insert into OutputStream;"
        )
        got = run_pb(manager, app, [
            ([1, False], 1000),
            ([2, False], 1100),
            ([4, True], 1200),   # flush fires; triggering event included
            ([8, False], 1300),
        ])
        # batch [1,2,4] flushed including the trigger -> single output sum 7
        assert [e.data[0] for e in got] == [7]


class TestHoppingWindow:
    """Reference: HopingWindowProcessor.java (abstract HOP-mode base; the
    concrete semantics here generalize timeBatch with an overlap)."""

    def test_overlapping_panes(self, manager):
        app = (
            "define stream S (v long); "
            "from S#window.hopping(2 sec, 1 sec) select sum(v) as total "
            "insert into OutputStream;"
        )
        got = run_pb(manager, app, [
            ([1], 1000),
            ([2], 1600),
            ([3], 2400),
            ([4], 3050),  # flush pane [1000,3000): 1+2+3
            ([0], 4100),  # flush pane [2000,4000): 3+4 — 3 re-emitted
        ])
        assert [e.data[0] for e in got] == [6, 7]

    def test_hop_equals_window_is_time_batch(self, manager):
        app = (
            "define stream S (v long); "
            "from S#window.hopping(1 sec, 1 sec) select sum(v) as total "
            "insert into OutputStream;"
        )
        got = run_pb(manager, app, [
            ([1], 1000),
            ([2], 1400),
            ([3], 2000),  # flush [1,2]
            ([4], 2500),
            ([5], 3100),  # flush [3,4]
        ])
        assert [e.data[0] for e in got] == [3, 7]

    def test_previous_pane_expires(self, manager):
        app = (
            "define stream S (v long); "
            "from S#window.hopping(2 sec, 1 sec) select v "
            "insert all events into OutputStream;"
        )
        got = run_pb(manager, app, [
            ([1], 1000),
            ([2], 2400),
            ([0], 3100),  # pane [1000,3000) = [1, 2] CURRENT
            ([0], 4100),  # pane [2000,4000): [1, 2] expire, [2, 0] current
        ])
        # insert-into converts EXPIRED to CURRENT on the next stream
        # (reference: InsertIntoStreamCallback), so identify the expired
        # re-emission of pane 1 by its boundary timestamp (4000)
        assert [e.data[0] for e in got] == [1, 2, 1, 2, 2, 0]
        assert [e.timestamp for e in got] == [1000, 2400, 4000, 4000, 2400, 3100]

    def test_bad_args_rejected(self, manager):
        from siddhi_tpu.core.exceptions import SiddhiAppCreationError

        for bad in ("hopping(1 sec)", "hopping(0 sec, 1 sec)"):
            with pytest.raises(SiddhiAppCreationError):
                manager.create_siddhi_app_runtime(
                    "define stream S (v long); "
                    f"from S#window.{bad} select v insert into OutputStream;"
                )
