"""Output rate-limiting conformance matrix.

Ported behavior families from the reference's ratelimit suite
(modules/siddhi-core/src/test/java/io/siddhi/core/query/ratelimit/ —
output first/last/all every N events / every T time / snapshot every T),
driven on event-time playback so time-based limits fire
deterministically.
"""

import pytest

from siddhi_tpu import SiddhiManager

DEFINE = "define stream S (symbol string, price double, volume long); "
TICK = "define stream Tick (x int); from Tick select x insert into _T; "


def run(query, sends, out="OutputStream"):
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime(
            "@app:playback " + DEFINE + TICK + query)
        got = []
        rt.add_callback(out, lambda evs: got.extend(e.data for e in evs))
        rt.start()
        for stream, row, ts in sends:
            rt.get_input_handler(stream).send(row, timestamp=ts)
        rt.shutdown()
        return got
    finally:
        m.shutdown()


def s_rows(rows, t0=1000, dt=100):
    return [("S", r, t0 + i * dt) for i, r in enumerate(rows)]


ROWS = [["A", 1.0, 10], ["B", 2.0, 20], ["C", 3.0, 30],
        ["D", 4.0, 40], ["E", 5.0, 50], ["F", 6.0, 60]]


class TestEventRateLimits:
    """output first/last/all every N events."""

    def test_first_every_3_events(self):
        got = run("from S select symbol output first every 3 events "
                  "insert into OutputStream;", s_rows(ROWS))
        assert [g[0] for g in got] == ["A", "D"]

    def test_last_every_3_events(self):
        got = run("from S select symbol output last every 3 events "
                  "insert into OutputStream;", s_rows(ROWS))
        assert [g[0] for g in got] == ["C", "F"]

    def test_all_every_3_events_batches(self):
        got = run("from S select symbol output every 3 events "
                  "insert into OutputStream;", s_rows(ROWS))
        assert [g[0] for g in got] == ["A", "B", "C", "D", "E", "F"]

    def test_partial_batch_not_emitted(self):
        got = run("from S select symbol output last every 4 events "
                  "insert into OutputStream;", s_rows(ROWS))
        # only one full window of 4 completes; E/F stay buffered
        assert [g[0] for g in got] == ["D"]


class TestTimeRateLimits:
    """output first/last/all every T — fired by the event-time
    scheduler."""

    def test_first_every_second(self):
        sends = s_rows(ROWS, t0=1000, dt=300)  # spans 1000..2500
        sends.append(("Tick", [1], 4000))      # closes the last period
        got = run("from S select symbol output first every 1 sec "
                  "insert into OutputStream;", sends)
        # events at 1000..2500 step 300; periods [1000,2000): first A;
        # [2000,3000): first E (2200)
        assert [g[0] for g in got] == ["A", "E"]

    def test_last_every_second(self):
        sends = s_rows(ROWS, t0=1000, dt=300)
        sends.append(("Tick", [1], 4000))
        got = run("from S select symbol output last every 1 sec "
                  "insert into OutputStream;", sends)
        # last of [1000,2000) is D (1900); last of [2000,3000) is F (2500)
        assert [g[0] for g in got] == ["D", "F"]

    def test_all_every_second_flushes_period(self):
        sends = s_rows(ROWS, t0=1000, dt=300)
        sends.append(("Tick", [1], 4000))
        got = run("from S select symbol output every 1 sec "
                  "insert into OutputStream;", sends)
        assert [g[0] for g in got] == ["A", "B", "C", "D", "E", "F"]

    def test_empty_period_emits_nothing(self):
        sends = [("S", ROWS[0], 1000), ("Tick", [1], 5000)]
        got = run("from S select symbol output last every 1 sec "
                  "insert into OutputStream;", sends)
        assert [g[0] for g in got] == ["A"]


class TestSnapshotRate:
    """output snapshot every T — periodic full-state emission of the
    aggregation (reference: snapshot/ WrappedSnapshotOutputRateLimiter)."""

    def test_snapshot_running_sum(self):
        q = ("from S select symbol, sum(volume) as total group by symbol "
             "output snapshot every 1 sec insert into OutputStream;")
        sends = [("S", ["A", 1.0, 10], 1000),
                 ("S", ["B", 1.0, 5], 1200),
                 ("S", ["A", 1.0, 7], 1300),
                 ("Tick", [1], 2100)]
        got = run(q, sends)
        # snapshot at 2000: current per-group totals
        assert sorted(map(tuple, got)) == [("A", 17), ("B", 5)]

    def test_snapshot_updates_between_periods(self):
        q = ("from S select symbol, sum(volume) as total group by symbol "
             "output snapshot every 1 sec insert into OutputStream;")
        sends = [("S", ["A", 1.0, 10], 1000),
                 ("Tick", [1], 2100),          # snapshot 1: A=10
                 ("S", ["A", 1.0, 5], 2500),
                 ("Tick", [1], 3100)]          # snapshot 2: A=15
        got = run(q, sends)
        assert [tuple(g) for g in got] == [("A", 10), ("A", 15)]


class TestRateLimitWithGroupBy:
    def test_last_per_group_every_events(self):
        q = ("from S select symbol, sum(volume) as t group by symbol "
             "output last every 4 events insert into OutputStream;")
        sends = s_rows([["A", 1.0, 10], ["B", 1.0, 20],
                        ["A", 1.0, 30], ["B", 1.0, 40]])
        got = run(q, sends)
        # per-group LAST within the 4-event window
        assert sorted(map(tuple, got)) == [("A", 40), ("B", 60)]

    def test_first_per_group_every_events(self):
        q = ("from S select symbol, sum(volume) as t group by symbol "
             "output first every 4 events insert into OutputStream;")
        sends = s_rows([["A", 1.0, 10], ["B", 1.0, 20],
                        ["A", 1.0, 30], ["B", 1.0, 40]])
        got = run(q, sends)
        assert sorted(map(tuple, got)) == [("A", 10), ("B", 20)]


class TestGroupedTimeRateLimits:
    """output first/last every T with group by — per-group emission
    (reference: *GroupByOutputRateLimiter variants)."""

    def test_last_per_group_every_second(self):
        q = ("from S select symbol, sum(volume) as total group by symbol "
             "output last every 1 sec insert into OutputStream;")
        got = run(q, [
            ("S", ["A", 1.0, 10], 1000),
            ("S", ["B", 1.0, 5], 1100),
            ("S", ["A", 1.0, 20], 1400),
            ("Tick", [1], 2100),          # period ends: last per group
            ("S", ["A", 1.0, 1], 2200),
            ("Tick", [2], 3300),
        ])
        assert sorted(map(tuple, got)) == [("A", 30), ("A", 31), ("B", 5)]

    def test_first_per_group_every_second(self):
        q = ("from S select symbol, sum(volume) as total group by symbol "
             "output first every 1 sec insert into OutputStream;")
        got = run(q, [
            ("S", ["A", 1.0, 10], 1000),   # first A of period 1
            ("S", ["B", 1.0, 5], 1100),    # first B of period 1
            ("S", ["A", 1.0, 20], 1400),   # suppressed
            ("S", ["A", 1.0, 1], 2200),    # first A of period 2
        ])
        assert sorted(map(tuple, got)) == [("A", 10), ("A", 31), ("B", 5)]


class TestRateLimitWithWindows:
    def test_all_every_events_passes_expired_too(self):
        # a sliding window's CURRENT+EXPIRED pairs ride the batch
        q = ("from S#window.length(2) select symbol "
             "output every 3 events insert into OutputStream;")
        got = run(q, s_rows(ROWS[:4]))
        # 4 current + 2 expired events flow; batches of 3 outputs flush
        assert [g[0] for g in got[:3]] == ["A", "B", "C"]

    def test_snapshot_over_group_by(self):
        # snapshot limiter emits the FULL group state each period
        q = ("from S select symbol, sum(volume) as total group by symbol "
             "output snapshot every 1 sec insert into OutputStream;")
        got = run(q, [
            ("S", ["A", 1.0, 10], 1000),
            ("S", ["B", 1.0, 5], 1200),
            ("Tick", [1], 2100),
            ("S", ["B", 1.0, 7], 2200),
            ("Tick", [2], 3300),
        ])
        assert sorted(map(tuple, got)) == [
            ("A", 10), ("A", 10), ("B", 5), ("B", 12)]

    def test_last_every_events_on_pattern_output(self):
        # rate limiter downstream of a pattern query
        q = ("from every e1=S[volume > 10] -> e2=S[volume > e1.volume] "
             "select e1.symbol as s1, e2.symbol as s2 "
             "output last every 2 events insert into OutputStream;")
        got = run(q, s_rows([
            ["A", 1.0, 20], ["B", 1.0, 30],   # match (A,B)
            ["C", 1.0, 40],                    # matches (A,C),(B,C)
        ]))
        # 3 matches total: limiter emits the 2nd, holds the 3rd
        assert got == [["A", "C"]] or got == [["B", "C"]]


class TestTimeRateLimitEdges:
    def test_all_every_time_multiple_periods_one_gap(self):
        # one watermark jump across several empty periods flushes once
        q = ("from S select symbol output every 1 sec "
             "insert into OutputStream;")
        got = run(q, [
            ("S", ["A", 1.0, 10], 1000),
            ("Tick", [1], 5000),
            ("S", ["B", 1.0, 10], 5100),
            ("Tick", [2], 6200),
        ])
        assert [g[0] for g in got] == ["A", "B"]

    def test_first_every_time_new_period_reopens(self):
        q = ("from S select symbol output first every 1 sec "
             "insert into OutputStream;")
        got = run(q, [
            ("S", ["A", 1.0, 10], 1000),   # emitted (first of period)
            ("S", ["B", 1.0, 10], 1500),   # suppressed
            ("S", ["C", 1.0, 10], 2500),   # new period: emitted
            ("S", ["D", 1.0, 10], 2600),   # suppressed
        ])
        assert [g[0] for g in got] == ["A", "C"]


class TestGroupedLimiterEmptyBatches:
    def test_having_filtered_empty_output_does_not_crash(self):
        # a having clause that rejects every row hands the limiter an
        # EMPTY batch with no group-key side channel — must be a no-op
        q = ("from S select symbol, price group by symbol "
             "having price > 100.0 output first every 1 sec "
             "insert into OutputStream;")
        got = run(q, [
            ("S", ["A", 1.0, 10], 1000),    # filtered by having
            ("S", ["B", 200.0, 5], 1100),   # passes
            ("S", ["C", 2.0, 5], 1200),     # filtered
        ])
        assert got == [["B", 200.0]]
