"""Table condition-operator matrices, ported from the reference
`query/table/PrimaryKeyTableTestCase.java` (76 cases) /
`IndexTableTestCase.java` (63) / `LogicalTableTestCase.java` /
`DeleteFromTableTestCase.java` / `UpdateFromTableTestCase.java`.

The reference's per-case assertions mostly pin that INDEXED lookups
(compiled CollectionExecutor probes) return the same rows an exhaustive
scan would.  That contract is tested here directly: every (operator x
condition-shape x operation) cell runs on a PLAIN table, a @primaryKey
table, and an @index table, and all three must agree — plus absolute
assertions on representative cells.
"""

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager

DEFS = (
    "define stream StockStream (symbol string, price double, volume long); "
    "define stream Check (symbol string, price double, volume long); "
    "define stream Del (symbol string, price double, volume long); "
    "define stream Upd (symbol string, price double, volume long); "
)

ROWS = [
    ["A", 10.0, 100], ["B", 20.0, 200], ["C", 30.0, 300],
    ["D", 40.0, 400], ["E", 50.0, 500],
]


def run(table_ann, body, sends):
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime(
            "@app:playback " + DEFS + table_ann +
            "define table T (symbol string, price double, volume long); "
            "from StockStream insert into T; " + body)
        got = []
        if "insert into Out" in body:
            rt.add_callback("Out", lambda evs: got.extend(
                tuple(e.data) for e in evs))
        rt.start()
        ts = 1000
        for row in ROWS:
            rt.get_input_handler("StockStream").send(list(row), timestamp=ts)
            ts += 1
        for sid, row in sends:
            rt.get_input_handler(sid).send(list(row), timestamp=ts)
            ts += 1
        batch = rt.tables["T"].rows_batch()
        if batch is None or len(batch) == 0:
            table_rows = []
        else:
            cols = [np.asarray(batch.columns[c]).tolist()
                    for c in ("symbol", "price", "volume")]
            table_rows = sorted(tuple(r) for r in zip(*cols))
        rt.shutdown()
        return got, table_rows
    finally:
        m.shutdown()


ANNS = ["", "@primaryKey('symbol') ", "@index('volume') "]


def agree(body, sends):
    """Run under all three table layouts; results must be identical."""
    results = [run(a, body, sends) for a in ANNS]
    base_got, base_rows = results[0]
    for (g, r), a in zip(results[1:], ANNS[1:]):
        assert g == base_got, (a, base_got, g)
        assert r == base_rows, (a, base_rows, r)
    return base_got, base_rows


class TestJoinProbeOperators:
    """reference: PrimaryKeyTableTestCase / IndexTableTestCase — every
    compare operator against the key/indexed column, probe == scan."""

    @pytest.mark.parametrize("op,expect_syms", [
        ("==", ["C"]),
        ("!=", ["A", "B", "D", "E"]),
        ("<", ["A", "B"]),
        ("<=", ["A", "B", "C"]),
        (">", ["D", "E"]),
        (">=", ["C", "D", "E"]),
    ])
    def test_volume_operator(self, op, expect_syms):
        body = (f"from Check join T on T.volume {op} 300 "
                "select T.symbol as s insert into Out;")
        got, _ = agree(body, [("Check", ["x", 0.0, 0])])
        assert sorted(g[0] for g in got) == expect_syms

    @pytest.mark.parametrize("op,expect_syms", [
        ("==", ["B"]), ("<", ["A"]), (">=", ["B", "C", "D", "E"]),
    ])
    def test_symbol_pk_operator(self, op, expect_syms):
        body = (f"from Check join T on T.symbol {op} 'B' "
                "select T.symbol as s insert into Out;")
        got, _ = agree(body, [("Check", ["x", 0.0, 0])])
        assert sorted(g[0] for g in got) == expect_syms

    def test_dynamic_probe_value_from_stream(self):
        body = ("from Check join T on T.symbol == Check.symbol "
                "select T.symbol as s, T.volume as v insert into Out;")
        got, _ = agree(body, [("Check", ["D", 0.0, 0]),
                              ("Check", ["Z", 0.0, 0])])
        assert got == [("D", 400)]


class TestLogicalConditions:
    """reference: LogicalTableTestCase — and/or/not combinations must
    plan identically across layouts."""

    @pytest.mark.parametrize("cond,expect", [
        ("T.symbol == 'B' and T.volume == 200", ["B"]),
        ("T.symbol == 'B' and T.volume == 999", []),
        ("T.symbol == 'B' or T.volume == 400", ["B", "D"]),
        ("not (T.volume > 200)", ["A", "B"]),
        ("T.volume > 100 and T.volume < 400", ["B", "C"]),
        ("T.symbol == 'A' or T.symbol == 'E' or T.volume == 300",
         ["A", "C", "E"]),
    ])
    def test_compound(self, cond, expect):
        body = (f"from Check join T on {cond} "
                "select T.symbol as s insert into Out;")
        got, _ = agree(body, [("Check", ["x", 0.0, 0])])
        assert sorted(g[0] for g in got) == expect


class TestDeleteOperators:
    """reference: DeleteFromTableTestCase — delete conditions over each
    layout leave identical table contents."""

    @pytest.mark.parametrize("cond,left", [
        ("T.symbol == Del.symbol", ["A", "C", "D", "E"]),
        ("T.volume < 300", ["C", "D", "E"]),
        ("T.volume >= Del.volume", ["A"]),
        ("T.symbol != 'C'", ["C"]),
    ])
    def test_delete(self, cond, left):
        body = f"from Del delete T on {cond};"
        _got, rows = agree(body, [("Del", ["B", 20.0, 200])])
        assert [r[0] for r in rows] == left


class TestUpdateOperators:
    """reference: UpdateFromTableTestCase / UpdateOrInsertTableTestCase."""

    def test_update_set_with_expression(self):
        body = ("from Upd update T set T.price = T.price + Upd.price "
                "on T.symbol == Upd.symbol;")
        _got, rows = agree(body, [("Upd", ["B", 5.0, 0])])
        assert [r for r in rows if r[0] == "B"][0][1] == 25.0

    def test_update_condition_on_non_key(self):
        body = ("from Upd update T set T.price = 0.0 on T.volume > 300;")
        _got, rows = agree(body, [("Upd", ["x", 0.0, 0])])
        assert sorted(r[0] for r in rows if r[1] == 0.0) == ["D", "E"]

    def test_update_or_insert_both_paths(self):
        body = ("from Upd update or insert into T "
                "set T.price = Upd.price on T.symbol == Upd.symbol;")
        _got, rows = agree(body, [("Upd", ["B", 99.0, 0]),
                                  ("Upd", ["Z", 7.0, 700])])
        assert [r for r in rows if r[0] == "B"][0][1] == 99.0
        assert [r for r in rows if r[0] == "Z"][0] == ("Z", 7.0, 700)


class TestInOperatorLayouts:
    """reference: the `in T` membership probe across layouts."""

    def test_value_membership(self):
        body = ("from Check[Check.symbol in T] select symbol "
                "insert into Out;")
        # value-membership needs a single-attr primary key; plain/index
        # layouts use the condition form instead, so compare pk against
        # the explicit-condition equivalents
        got_pk, _ = run("@primaryKey('symbol') ", body,
                        [("Check", ["C", 0.0, 0]), ("Check", ["Z", 0.0, 0])])
        body2 = ("from Check[(Check.symbol == T.symbol) in T] "
                 "select symbol insert into Out;")
        for ann in ANNS:
            got, _ = run(ann, body2, [("Check", ["C", 0.0, 0]),
                                      ("Check", ["Z", 0.0, 0])])
            assert got == got_pk == [("C",)]


class TestDefineTableEdges:
    """reference: DefineTableTestCase — definition-level contracts."""

    def test_duplicate_table_definition_rejected(self):
        from siddhi_tpu.core.exceptions import SiddhiAppCreationError

        m = SiddhiManager()
        try:
            with pytest.raises(SiddhiAppCreationError):
                m.create_siddhi_app_runtime(
                    "define table T (a string); define table T (b long);")
        finally:
            m.shutdown()

    def test_table_and_stream_name_collision_rejected(self):
        from siddhi_tpu.core.exceptions import SiddhiAppCreationError

        m = SiddhiManager()
        try:
            with pytest.raises(SiddhiAppCreationError):
                m.create_siddhi_app_runtime(
                    "define stream T (a string); define table T (a string);")
        finally:
            m.shutdown()

    def test_unknown_pk_attribute_rejected(self):
        from siddhi_tpu.core.exceptions import SiddhiAppCreationError

        m = SiddhiManager()
        try:
            with pytest.raises(SiddhiAppCreationError):
                m.create_siddhi_app_runtime(
                    "@primaryKey('nope') define table T (a string);")
        finally:
            m.shutdown()
