"""Playback / state / start-stop / sandbox conformance, ported from the
reference `managment/` suites (PlaybackTestCase.java,
StateTestCase.java, StartStopTestCase.java, SandboxTestCase.java):
event-time windows under @app:playback, heartbeat idle-time flushes,
out-of-order arrivals, stateful restarts.
"""

import time

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.exceptions import SiddhiAppCreationError


@pytest.fixture
def manager():
    m = SiddhiManager()
    yield m
    m.shutdown()


def query_counts(rt, qname):
    counts = {"in": 0, "out": 0, "first_remove_before_in": False}

    def cb(ts, in_events, out_events):
        if counts["in"] == 0 and out_events:
            counts["first_remove_before_in"] = True
        counts["in"] += len(in_events or [])
        counts["out"] += len(out_events or [])

    rt.add_callback(qname, cb)
    return counts


class TestPlaybackWindows:
    def test_time_batch_window_event_time(self, manager):
        """reference: playbackTest1:48 — a timeBatch window under
        playback flushes on EVENT time; remove events only appear from
        the second pane on."""
        rt = manager.create_siddhi_app_runtime(
            "@app:playback "
            "define stream cseEventStream (symbol string, price float, "
            "volume int); "
            "@info(name='query1') from cseEventStream#window.timeBatch(1 sec) "
            "select * insert all events into outputStream;")
        counts = query_counts(rt, "query1")
        rt.start()
        h = rt.get_input_handler("cseEventStream")
        ts = 1_600_000_000_000
        h.send(["IBM", 700.0, 0], timestamp=ts)
        h.send(["WSO2", 60.5, 1], timestamp=ts + 500)
        h.send(["GOOGLE", 85.0, 1], timestamp=ts + 1000)   # closes pane 1
        h.send(["ORACLE", 90.5, 1], timestamp=ts + 2000)   # closes pane 2
        rt.shutdown()
        assert counts["in"] == 3
        assert counts["out"] == 2
        assert not counts["first_remove_before_in"]

    def test_time_window_all_events(self, manager):
        """reference: playbackTest3-ish — sliding time window expiry on
        event time with `insert all events`."""
        rt = manager.create_siddhi_app_runtime(
            "@app:playback "
            "define stream S (symbol string, price float); "
            "@info(name='q') from S#window.time(1 sec) select * "
            "insert all events into Out;")
        counts = query_counts(rt, "q")
        rt.start()
        h = rt.get_input_handler("S")
        ts = 1_600_000_000_000
        h.send(["A", 1.0], timestamp=ts)
        h.send(["B", 2.0], timestamp=ts + 500)
        h.send(["C", 3.0], timestamp=ts + 1100)  # A expired by now
        rt.shutdown()
        assert counts["in"] == 3
        assert counts["out"] >= 1  # A (and possibly B) expired

    def test_heartbeat_idle_time_flushes(self, manager):
        """reference: playbackTest7/8 — @app:playback(idle.time,
        increment): when no events arrive, the playback clock
        auto-increments and closes panes."""
        rt = manager.create_siddhi_app_runtime(
            "@app:playback(idle.time='50 millisecond', increment='1 sec') "
            "define stream S (symbol string, price float); "
            "@info(name='q') from S#window.timeBatch(1 sec) select * "
            "insert into Out;")
        got = []
        rt.add_callback("Out", lambda evs: got.extend(e.data for e in evs))
        rt.start()
        h = rt.get_input_handler("S")
        h.send(["A", 1.0], timestamp=1_600_000_000_000)
        deadline = time.time() + 3
        while not got and time.time() < deadline:
            time.sleep(0.02)
        rt.shutdown()
        # the idle heartbeat advanced event time past the pane boundary
        assert got and got[0][0] == "A"

    def test_out_of_order_event_below_watermark(self, manager):
        """reference: playbackTest11 — an event older than the playback
        clock still processes (watermark does not reject it)."""
        rt = manager.create_siddhi_app_runtime(
            "@app:playback "
            "define stream S (symbol string, price float); "
            "@info(name='q') from S select symbol insert into Out;")
        got = []
        rt.add_callback("Out", lambda evs: got.extend(e.data for e in evs))
        rt.start()
        h = rt.get_input_handler("S")
        ts = 1_600_000_000_000
        h.send(["A", 1.0], timestamp=ts)
        h.send(["B", 2.0], timestamp=ts - 5000)  # older than watermark
        rt.shutdown()
        assert [g[0] for g in got] == ["A", "B"]

    def test_invalid_increment_constant_rejected(self, manager):
        """reference: playbackTest9 — a non-time increment constant is
        a parse/creation error (the reference throws
        SiddhiParserException)."""
        from siddhi_tpu.compiler.parser import SiddhiParserError

        with pytest.raises((SiddhiAppCreationError, SiddhiParserError)):
            manager.create_siddhi_app_runtime(
                "@app:playback(idle.time='100 millisecond', increment='x') "
                "define stream S (v long); "
                "from S#window.time(2 sec) select v insert into Out;")

    def test_length_batch_under_playback(self, manager):
        """reference: playbackTest13-ish — count-based windows are
        unaffected by the playback clock."""
        rt = manager.create_siddhi_app_runtime(
            "@app:playback "
            "define stream S (v long); "
            "@info(name='q') from S#window.lengthBatch(2) "
            "select sum(v) as t insert into Out;")
        got = []
        rt.add_callback("Out", lambda evs: got.extend(e.data for e in evs))
        rt.start()
        h = rt.get_input_handler("S")
        for i, ts in enumerate([10, 5, 30, 2]):  # wildly non-monotone
            h.send([i + 1], timestamp=1_000_000 + ts)
        rt.shutdown()
        assert got == [[3], [7]]


class TestStateAcrossRestart:
    """reference: StateTestCase.java — stateful elements resume after
    persist + fresh-runtime restore."""

    def test_count_window_sum_resumes(self, manager):
        from siddhi_tpu.util.persistence import InMemoryPersistenceStore

        manager.set_persistence_store(InMemoryPersistenceStore())
        app = ("@app:name('stateApp') @app:playback "
               "define stream S (symbol string, price float); "
               "@info(name='q') from S#window.length(4) "
               "select symbol, sum(price) as total insert into Out;")
        rt = manager.create_siddhi_app_runtime(app)
        rt.start()
        h = rt.get_input_handler("S")
        h.send(["IBM", 100.0], timestamp=1000)
        h.send(["IBM", 200.0], timestamp=1001)
        rev = rt.persist()
        rt.shutdown()

        rt2 = manager.create_siddhi_app_runtime(app)
        got = []
        rt2.add_callback("Out", lambda evs: got.extend(e.data for e in evs))
        rt2.start()
        rt2.restore_revision(rev)
        rt2.get_input_handler("S").send(["IBM", 50.0], timestamp=1002)
        rt2.shutdown()
        assert got == [["IBM", 350.0]]

    def test_pattern_half_match_resumes(self, manager):
        from siddhi_tpu.util.persistence import InMemoryPersistenceStore

        manager.set_persistence_store(InMemoryPersistenceStore())
        app = ("@app:name('patState') @app:playback "
               "define stream S (k string, v double); "
               "@info(name='q') from every a=S[v > 10.0] -> b=S[v > a.v] "
               "within 1 min select a.v as av, b.v as bv insert into Out;")
        rt = manager.create_siddhi_app_runtime(app)
        rt.start()
        rt.get_input_handler("S").send(["x", 20.0], timestamp=1000)  # arms
        rev = rt.persist()
        rt.shutdown()

        rt2 = manager.create_siddhi_app_runtime(app)
        got = []
        rt2.add_callback("Out", lambda evs: got.extend(e.data for e in evs))
        rt2.start()
        rt2.restore_revision(rev)
        rt2.get_input_handler("S").send(["x", 25.0], timestamp=2000)
        rt2.shutdown()
        assert got == [[20.0, 25.0]]


class TestStartStop:
    def test_events_before_start_and_after_shutdown_ignored(self, manager):
        """reference: StartStopTestCase — sends before start() do not
        crash or emit."""
        from siddhi_tpu.core.exceptions import SiddhiAppRuntimeError

        rt = manager.create_siddhi_app_runtime(
            "@app:playback define stream S (v long); "
            "@info(name='q') from S[v > 0] select v insert into Out;")
        got = []
        rt.add_callback("Out", lambda evs: got.extend(e.data for e in evs))
        h = rt.get_input_handler("S")
        with pytest.raises(SiddhiAppRuntimeError):
            h.send([1], timestamp=1000)  # before start: app not running
        rt.start()
        h.send([2], timestamp=1001)
        rt.shutdown()
        with pytest.raises(SiddhiAppRuntimeError):
            h.send([3], timestamp=1002)  # after shutdown
        assert got == [[2]]

    def test_restartable(self, manager):
        rt = manager.create_siddhi_app_runtime(
            "@app:playback define stream S (v long); "
            "@info(name='q') from S select v insert into Out;")
        got = []
        rt.add_callback("Out", lambda evs: got.extend(e.data for e in evs))
        rt.start()
        rt.get_input_handler("S").send([1], timestamp=1000)
        rt.shutdown()
        rt.start()
        rt.get_input_handler("S").send([2], timestamp=2000)
        rt.shutdown()
        assert got == [[1], [2]]


class TestSandbox:
    def test_sandbox_strips_non_inmemory_transports(self, manager):
        """reference: SandboxTestCase.java:56 +
        SiddhiManager.removeSourceSinkAndStoreAnnotations:121 —
        non-inMemory @source/@sink are removed (the stream stays
        drivable via its input handler); inMemory transports SURVIVE
        sandboxing."""
        from siddhi_tpu.transport.source import Source

        class ExternalSource(Source):
            def connect(self):
                raise AssertionError("sandbox must not connect this")

        manager.set_extension("externalThing", ExternalSource, kind="source")
        app = (
            "define stream S (v long); "
            "@source(type='externalThing', topic='x', "
            "@map(type='passThrough')) "
            "define stream T (v long); "
            "@sink(type='log') "
            "@sink(type='inMemory', topic='sandbox-out', "
            "@map(type='passThrough')) "
            "define stream Out (v long); "
            "from S select v insert into Out; "
            "from T select v insert into Out;")
        rt = manager.create_sandbox_siddhi_app_runtime(app)
        got = []
        rt.add_callback("Out", lambda evs: got.extend(e.data for e in evs))
        rt.start()
        assert rt.sources == []  # externalThing stripped
        assert len(rt.sinks) == 1  # log stripped, inMemory kept
        rt.get_input_handler("S").send([7])
        # T lost its source but is still drivable via its input handler
        rt.get_input_handler("T").send([8])
        rt.shutdown()
        assert got == [[7], [8]]
