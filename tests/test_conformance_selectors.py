"""Selector conformance: order-by / limit / offset matrices, isNull,
string & boolean comparison operators, multi-key group-by, and having
edges — the behavioral families of the reference's
OrderByLimitTestCase.java, IsNullTestCase.java, StringCompareTestCase
.java, BooleanCompareTestCase.java and GroupByTestCase.java
(modules/siddhi-core/src/test/java/io/siddhi/core/query/).  Expectations
are computed from the documented semantics: order-by sorts each output
chunk, limit/offset slice it, group-by keys aggregates per distinct key
tuple.
"""

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager

DEFS = "define stream S (symbol string, price float, volume long); "
F = lambda x: np.float32(x).item()

ROWS4 = [
    ["IBM", 20.0, 100], ["WSO2", 40.0, 200],
    ["IBM", 30.0, 300], ["APPL", 10.0, 400],
]


def run(query, rows, defs=DEFS, out="OutputStream", stream="S"):
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime("@app:playback " + defs + query)
        got = []
        rt.add_callback(out, lambda evs: got.extend(list(e.data) for e in evs))
        rt.start()
        h = rt.get_input_handler(stream)
        for i, r in enumerate(rows):
            h.send(r, timestamp=1000 + i * 100)
        rt.shutdown()
        return got
    finally:
        m.shutdown()


class TestOrderByLimit:
    def test_limit_without_order(self):
        # OrderByLimitTestCase.limitTest1: first 2 of each 4-batch
        got = run("from S#window.lengthBatch(4) select symbol, price "
                  "limit 2 insert into OutputStream;", ROWS4)
        assert got == [["IBM", F(20.0)], ["WSO2", F(40.0)]]

    def test_order_by_symbol_limit(self):
        # limitTest2
        got = run("from S#window.lengthBatch(4) select symbol, price "
                  "order by symbol limit 3 insert into OutputStream;", ROWS4)
        assert got == [["APPL", F(10.0)], ["IBM", F(20.0)], ["IBM", F(30.0)]]

    def test_order_by_price_desc(self):
        got = run("from S#window.lengthBatch(4) select symbol, price "
                  "order by price desc insert into OutputStream;", ROWS4)
        assert got == [["WSO2", F(40.0)], ["IBM", F(30.0)],
                       ["IBM", F(20.0)], ["APPL", F(10.0)]]

    def test_order_by_aggregated_value(self):
        # limitTest6-style: group-by sum ordered by the aggregate
        got = run("from S#window.lengthBatch(4) "
                  "select symbol, sum(price) as totalPrice group by symbol "
                  "order by totalPrice limit 2 insert into OutputStream;",
                  ROWS4)
        assert got == [["APPL", 10.0], ["WSO2", 40.0]]

    def test_order_by_aggregate_desc_offset(self):
        # limitTest12: desc order, skip the top entry
        got = run("from S#window.lengthBatch(4) "
                  "select symbol, sum(price) as totalPrice group by symbol "
                  "order by totalPrice desc offset 1 "
                  "insert into OutputStream;", ROWS4)
        assert got == [["WSO2", 40.0], ["APPL", 10.0]]

    def test_multi_key_order(self):
        # limitTest5-style: secondary sort key breaks ties
        rows = [["B", 10.0, 2], ["A", 10.0, 1], ["C", 5.0, 3], ["D", 7.0, 4]]
        got = run("from S#window.lengthBatch(4) select symbol, price, volume "
                  "order by price, volume insert into OutputStream;", rows)
        assert got == [["C", F(5.0), 3], ["D", F(7.0), 4],
                       ["A", F(10.0), 1], ["B", F(10.0), 2]]

    def test_limit_zero(self):
        got = run("from S#window.lengthBatch(4) select symbol "
                  "limit 0 insert into OutputStream;", ROWS4)
        assert got == []

    def test_offset_beyond_chunk(self):
        got = run("from S#window.lengthBatch(4) select symbol "
                  "offset 10 insert into OutputStream;", ROWS4)
        assert got == []

    def test_order_limit_per_chunk_not_global(self):
        # each lengthBatch flush is ordered/limited independently
        rows = ROWS4 + [["ZZZ", 1.0, 1], ["AAA", 2.0, 2],
                        ["MMM", 3.0, 3], ["BBB", 4.0, 4]]
        got = run("from S#window.lengthBatch(4) select symbol "
                  "order by symbol limit 1 insert into OutputStream;", rows)
        assert got == [["APPL"], ["AAA"]]


class TestIsNull:
    def test_is_null_filter_on_stream(self):
        # IsNullTestCase: null attribute values pass `is null`
        got = run("from S[symbol is null] select price "
                  "insert into OutputStream;",
                  [["IBM", 20.0, 100], [None, 30.0, 200]])
        assert got == [[F(30.0)]]

    def test_not_is_null_filter(self):
        got = run("from S[not (symbol is null)] select symbol "
                  "insert into OutputStream;",
                  [["IBM", 20.0, 100], [None, 30.0, 200]])
        assert got == [["IBM"]]

    def test_null_propagates_through_projection(self):
        got = run("from S select symbol, price insert into OutputStream;",
                  [[None, 20.0, 100]])
        assert got == [[None, F(20.0)]]

    def test_null_comparison_never_matches(self):
        # null compared with anything is no-match (not an error)
        got = run("from S[symbol == 'IBM'] select price "
                  "insert into OutputStream;",
                  [[None, 20.0, 100], ["IBM", 30.0, 200]])
        assert got == [[F(30.0)]]


class TestStringBoolCompare:
    def test_string_operators(self):
        # StringCompareTestCase: ==, !=, >, < over strings
        rows = [["AAA", 1.0, 1], ["BBB", 2.0, 2], ["CCC", 3.0, 3]]
        assert run("from S[symbol == 'BBB'] select symbol "
                   "insert into OutputStream;", rows) == [["BBB"]]
        assert run("from S[symbol != 'BBB'] select symbol "
                   "insert into OutputStream;", rows) == [["AAA"], ["CCC"]]
        assert run("from S[symbol > 'AAA'] select symbol "
                   "insert into OutputStream;", rows) == [["BBB"], ["CCC"]]
        assert run("from S[symbol <= 'BBB'] select symbol "
                   "insert into OutputStream;", rows) == [["AAA"], ["BBB"]]

    def test_bool_attribute_compare(self):
        defs = "define stream B (name string, ok bool); "
        rows = [["a", True], ["b", False], ["c", True]]
        assert run("from B[ok == true] select name "
                   "insert into OutputStream;", rows, defs=defs,
                   stream="B") == [["a"], ["c"]]
        assert run("from B[ok != true] select name "
                   "insert into OutputStream;", rows, defs=defs,
                   stream="B") == [["b"]]
        assert run("from B[not ok] select name "
                   "insert into OutputStream;", rows, defs=defs,
                   stream="B") == [["b"]]


class TestGroupByEdges:
    def test_multi_key_group_by(self):
        # GroupByTestCase: two grouping keys form a composite key
        defs = "define stream T (a string, b string, v long); "
        rows = [["x", "1", 10], ["x", "2", 20], ["x", "1", 30],
                ["y", "1", 40]]
        got = run("from T select a, b, sum(v) as total group by a, b "
                  "insert into OutputStream;", rows, defs=defs, stream="T")
        assert got == [["x", "1", 10], ["x", "2", 20], ["x", "1", 40],
                       ["y", "1", 40]]

    def test_group_by_with_having_on_aggregate(self):
        defs = "define stream T (a string, v long); "
        rows = [["x", 10], ["y", 5], ["x", 10], ["y", 5]]
        got = run("from T select a, sum(v) as total group by a "
                  "having total > 10 insert into OutputStream;",
                  rows, defs=defs, stream="T")
        assert got == [["x", 20]]

    def test_group_by_sliding_window_subtracts(self):
        # per-group sums fall when events expire from a length window
        defs = "define stream T (a string, v long); "
        rows = [["x", 10], ["x", 20], ["x", 30]]
        got = run("from T#window.length(2) select a, sum(v) as total "
                  "group by a insert into OutputStream;",
                  rows, defs=defs, stream="T")
        assert got == [["x", 10], ["x", 30], ["x", 50]]

    def test_having_references_select_alias_and_raw_attr(self):
        defs = "define stream T (a string, v long); "
        rows = [["x", 10], ["y", 50]]
        got = run("from T select a, v, sum(v) as total "
                  "having v >= 50 and total >= 60 "
                  "insert into OutputStream;", rows, defs=defs, stream="T")
        assert got == [["y", 50, 60]]


class TestMathAndFunctions:
    def test_integer_division_truncates(self):
        # java semantics: long/long is integer division
        defs = "define stream T (a long, b long); "
        got = run("from T select a / b as q, a % b as r "
                  "insert into OutputStream;", [[7, 2]], defs=defs,
                  stream="T")
        assert got == [[3, 1]]

    def test_float_division(self):
        defs = "define stream T (a double, b long); "
        got = run("from T select a / b as q insert into OutputStream;",
                  [[7.0, 2]], defs=defs, stream="T")
        assert got == [[3.5]]

    def test_coalesce_and_ifthenelse(self):
        got = run("from S select coalesce(symbol, 'none') as s, "
                  "ifThenElse(price > 25.0, 'hi', 'lo') as lvl "
                  "insert into OutputStream;",
                  [[None, 20.0, 1], ["A", 30.0, 2]])
        assert got == [["none", "lo"], ["A", "hi"]]

    def test_cast_and_convert(self):
        # convert float->long TRUNCATES (reference
        # ConvertFunctionExecutor uses Float.longValue())
        got = run("from S select cast(volume, 'string') as vs, "
                  "convert(price, 'long') as pl "
                  "insert into OutputStream;", [["A", 20.6, 42]])
        assert got == [["42", 20]]

    def test_instance_of_checks(self):
        got = run("from S select instanceOfString(symbol) as a, "
                  "instanceOfFloat(symbol) as b, "
                  "instanceOfFloat(price) as c "
                  "insert into OutputStream;", [["A", 20.0, 1]])
        assert got == [[True, False, True]]


class TestConvertMatrix:
    """convert(value, 'type') across every (from, to) pair (reference:
    ConvertFunctionTestCase / ConvertFunctionExecutor's per-type
    switch): numeric conversions truncate like Java casts, strings
    parse, bools map via string semantics."""

    DEFS6 = ("define stream C (i int, l long, f float, d double, "
             "s string, b bool); ")
    ROW = [7, 5_000_000_000, 2.5, 3.9, "11", True]

    def _convert(self, src, target):
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(
                self.DEFS6 + f"@info(name='q') from C select "
                f"convert({src}, '{target}') as c insert into O;")
            got = []
            rt.add_callback("O", lambda evs: got.extend(e.data[0] for e in evs))
            rt.start()
            rt.get_input_handler("C").send(list(self.ROW))
            rt.shutdown()
            return got[0]
        finally:
            m.shutdown()

    def test_numeric_to_numeric_truncates(self):
        assert self._convert("d", "int") == 3       # 3.9 -> 3
        assert self._convert("d", "long") == 3
        assert self._convert("f", "int") == 2       # 2.5 -> 2
        assert self._convert("i", "double") == 7.0
        assert self._convert("l", "double") == 5_000_000_000.0
        assert self._convert("i", "long") == 7

    def test_string_parses_to_numbers(self):
        assert self._convert("s", "int") == 11
        assert self._convert("s", "long") == 11
        assert self._convert("s", "double") == 11.0

    def test_to_string(self):
        assert self._convert("i", "string") == "7"
        assert self._convert("b", "string").lower() == "true"

    def test_bool_conversions(self):
        assert self._convert("b", "bool") is True or \
            self._convert("b", "bool") == True  # noqa: E712
