"""REST service tests (reference: siddhi-service deploy/undeploy API)."""

import json
import time
import urllib.error
import urllib.request

import pytest

from siddhi_tpu.service import SiddhiService
from siddhi_tpu.transport.broker import InMemoryBroker, Subscriber


APP = (
    "@app:name('restApp') "
    "@source(type='inMemory', topic='rest-in', @map(type='passThrough')) "
    "define stream S (v long); "
    "@sink(type='inMemory', topic='rest-out', @map(type='passThrough')) "
    "define stream Out (v long); "
    "from S[v > 10] select v insert into Out;"
)


@pytest.fixture
def service():
    svc = SiddhiService()
    svc.start()
    yield svc
    svc.stop()


def post(url, body: str):
    req = urllib.request.Request(url, data=body.encode(), method="POST")
    with urllib.request.urlopen(req) as r:
        return r.status, json.loads(r.read())


def get(url):
    try:
        with urllib.request.urlopen(url) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_deploy_process_undeploy(service):
    base = f"http://127.0.0.1:{service.port}"
    status, payload = post(f"{base}/siddhi-artifact-deploy", APP)
    assert status == 200 and payload["status"] == "OK"
    assert payload["name"] == "restApp"

    got = []

    class Sub(Subscriber):
        def on_message(self, msg):
            got.append(msg)

        def get_topic(self):
            return "rest-out"

    sub = Sub()
    InMemoryBroker.subscribe(sub)
    InMemoryBroker.publish("rest-in", [50])
    InMemoryBroker.publish("rest-in", [5])
    time.sleep(0.2)
    InMemoryBroker.unsubscribe(sub)
    assert [e.data for e in got] == [[50]]

    status, payload = get(f"{base}/siddhi-apps")
    assert payload["apps"] == ["restApp"]

    status, payload = get(f"{base}/siddhi-artifact-undeploy/restApp")
    assert status == 200 and payload["status"] == "OK"
    assert service.app_names() == []


def test_duplicate_deploy_conflicts(service):
    base = f"http://127.0.0.1:{service.port}"
    assert post(f"{base}/siddhi-artifact-deploy", APP)[0] == 200
    try:
        status, payload = post(f"{base}/siddhi-artifact-deploy", APP)
    except urllib.error.HTTPError as e:
        status, payload = e.code, json.loads(e.read())
    assert status == 409 and payload["status"] == "ERROR"


def test_bad_app_rejected(service):
    base = f"http://127.0.0.1:{service.port}"
    try:
        status, payload = post(f"{base}/siddhi-artifact-deploy", "define nonsense;;;")
    except urllib.error.HTTPError as e:
        status, payload = e.code, json.loads(e.read())
    assert status == 400 and payload["status"] == "ERROR"


def test_undeploy_missing_404(service):
    base = f"http://127.0.0.1:{service.port}"
    status, payload = get(f"{base}/siddhi-artifact-undeploy/nope")
    assert status == 404 and payload["status"] == "ERROR"


def test_deploy_conflicts_with_manager_registered_app(service):
    # ADVICE r1: deploying an app whose name matches a runtime created
    # directly on the shared manager must 409, not silently replace the
    # manager registration while the old runtime keeps running.
    rt = service.manager.create_siddhi_app_runtime(APP)
    try:
        status, body = service.deploy(APP)
        assert status == 409
        assert service.manager.get_siddhi_app_runtime("restApp") is rt
    finally:
        rt.shutdown()
