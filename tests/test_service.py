"""REST service tests (reference: siddhi-service deploy/undeploy API)."""

import json
import time
import urllib.error
import urllib.request

import pytest

from siddhi_tpu.service import SiddhiService
from siddhi_tpu.transport.broker import InMemoryBroker, Subscriber


APP = (
    "@app:name('restApp') "
    "@source(type='inMemory', topic='rest-in', @map(type='passThrough')) "
    "define stream S (v long); "
    "@sink(type='inMemory', topic='rest-out', @map(type='passThrough')) "
    "define stream Out (v long); "
    "from S[v > 10] select v insert into Out;"
)


@pytest.fixture
def service():
    svc = SiddhiService()
    svc.start()
    yield svc
    svc.stop()


def post(url, body: str):
    req = urllib.request.Request(url, data=body.encode(), method="POST")
    with urllib.request.urlopen(req) as r:
        return r.status, json.loads(r.read())


def get(url):
    try:
        with urllib.request.urlopen(url) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_deploy_process_undeploy(service):
    base = f"http://127.0.0.1:{service.port}"
    status, payload = post(f"{base}/siddhi-artifact-deploy", APP)
    assert status == 200 and payload["status"] == "OK"
    assert payload["name"] == "restApp"

    got = []

    class Sub(Subscriber):
        def on_message(self, msg):
            got.append(msg)

        def get_topic(self):
            return "rest-out"

    sub = Sub()
    InMemoryBroker.subscribe(sub)
    InMemoryBroker.publish("rest-in", [50])
    InMemoryBroker.publish("rest-in", [5])
    time.sleep(0.2)
    InMemoryBroker.unsubscribe(sub)
    assert [e.data for e in got] == [[50]]

    status, payload = get(f"{base}/siddhi-apps")
    assert payload["apps"] == ["restApp"]

    status, payload = get(f"{base}/siddhi-artifact-undeploy/restApp")
    assert status == 200 and payload["status"] == "OK"
    assert service.app_names() == []


def test_duplicate_deploy_conflicts(service):
    base = f"http://127.0.0.1:{service.port}"
    assert post(f"{base}/siddhi-artifact-deploy", APP)[0] == 200
    try:
        status, payload = post(f"{base}/siddhi-artifact-deploy", APP)
    except urllib.error.HTTPError as e:
        status, payload = e.code, json.loads(e.read())
    assert status == 409 and payload["status"] == "ERROR"


def test_bad_app_rejected(service):
    base = f"http://127.0.0.1:{service.port}"
    try:
        status, payload = post(f"{base}/siddhi-artifact-deploy", "define nonsense;;;")
    except urllib.error.HTTPError as e:
        status, payload = e.code, json.loads(e.read())
    assert status == 400 and payload["status"] == "ERROR"


def test_undeploy_missing_404(service):
    base = f"http://127.0.0.1:{service.port}"
    status, payload = get(f"{base}/siddhi-artifact-undeploy/nope")
    assert status == 404 and payload["status"] == "ERROR"


def test_deploy_conflicts_with_manager_registered_app(service):
    # ADVICE r1: deploying an app whose name matches a runtime created
    # directly on the shared manager must 409, not silently replace the
    # manager registration while the old runtime keeps running.
    rt = service.manager.create_siddhi_app_runtime(APP)
    try:
        status, body = service.deploy(APP)
        assert status == 409
        assert service.manager.get_siddhi_app_runtime("restApp") is rt
    finally:
        rt.shutdown()


def test_query_lowering_endpoint(service):
    base = f"http://127.0.0.1:{service.port}"
    app = (
        "@app:name('lowApp') @app:playback "
        "@app:execution('tpu', partitions='16') "
        "define stream S (user string, v double); "
        "@info(name='dev') from S select user, sum(v) as t insert into A; "
        "@info(name='hostq') from S#window.length(2) select user, v "
        "insert expired events into B; "
        "partition with (user of S) begin "
        "@info(name='pq') from S[v > 1.0] select user, v insert into C; "
        "end;"
    )
    status, payload = post(f"{base}/siddhi-artifact-deploy", app)
    assert status == 200, payload
    status, payload = get(f"{base}/siddhi-query-lowering/lowApp")
    assert status == 200
    q = payload["queries"]
    assert q["dev"] == "device"       # eligible single-stream query
    assert q["hostq"] == "host"       # order-by keeps the host selector
    assert q["pq"] == "device"        # partitioned filter on the device
    status, payload = get(f"{base}/siddhi-query-lowering/ghost")
    assert status == 404


def test_lowering_in_statistics(service):
    from siddhi_tpu import SiddhiManager

    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime(
            "@app:name('statsLow') @app:playback @app:statistics "
            "@app:execution('tpu', partitions='8') "
            "define stream S (user string, v double); "
            "@info(name='dq') from S select user, count() as c "
            "insert into Out;")
        sm = rt.app_context.statistics_manager
        stats = sm.stats()
        key = "io.siddhi.SiddhiApps.statsLow.Siddhi.Queries.dq.loweredTo"
        assert stats[key] == "device"
        assert rt.lowering() == {"dq": "device"}
    finally:
        m.shutdown()


def test_fallback_warns(caplog):
    import logging

    from siddhi_tpu import SiddhiManager

    m = SiddhiManager()
    try:
        with caplog.at_level(logging.WARNING, logger="siddhi_tpu"):
            rt = m.create_siddhi_app_runtime(
                "@app:playback @app:execution('tpu') "
                "define stream S (user string, v double); "
                "@info(name='hq') from S#window.length(2) select user, v "
                "insert expired events into Out;")
        assert rt.lowering() == {"hq": "host"}
        assert any("device query path unavailable" in r.getMessage()
                   for r in caplog.records), caplog.records
    finally:
        m.shutdown()
