"""Tier-1: the whole package passes the static-analysis pass.

``python -m siddhi_tpu.analysis`` must exit 0 — zero unbaselined
findings across ALL registered rules (device-contract, ingest staging,
fault visibility, lock discipline, jit purity, retrace hazards) and no
stale allowlist entries.  This is the single guard new code answers to:
a violation either gets fixed or gets an allowlist entry with a written
justification, never a silent merge.
"""

from pathlib import Path

from siddhi_tpu.analysis import all_rules, index_package, run_rules
from siddhi_tpu.analysis.__main__ import main

REPO = Path(__file__).resolve().parent.parent


def test_rule_catalog_is_complete():
    rules = all_rules()
    names = {r.name for r in rules}
    assert len(rules) >= 6, names
    assert {"host-sync-hazard", "ingest-put-bypass", "broad-except-swallow",
            "lock-discipline", "jit-purity", "retrace-hazard"} <= names
    for r in rules:
        assert r.description, f"rule {r.name} has no description"


def test_whole_package_has_no_unbaselined_findings():
    indexes = index_package(REPO / "siddhi_tpu", REPO)
    assert len(indexes) > 50  # the walk actually covered the package
    res = run_rules(indexes)
    assert not res["findings"], (
        "static-analysis violations (fix them, or allowlist in "
        "siddhi_tpu/analysis/allowlists.py WITH a justification):\n  "
        + "\n  ".join(f.render() for f in res["findings"]))
    # the curated allowlists really are doing work, not vacuously empty
    assert len(res["suppressed"]) > 50


def test_cli_exits_zero_on_clean_package(capsys):
    rc = main(["--root", str(REPO / "siddhi_tpu")])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "0 finding(s)" in out


def test_cli_lists_rules(capsys):
    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    assert "jit-purity" in out and "lock-discipline" in out
