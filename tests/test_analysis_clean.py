"""Tier-1: the whole package passes the static-analysis pass.

``python -m siddhi_tpu.analysis`` must exit 0 — zero unbaselined
findings across ALL registered rules (device-contract, ingest staging,
fault visibility, lock discipline, jit purity, retrace hazards,
fallback discipline, thread lifecycle) and no stale allowlist
entries.  This is the single guard new code answers to:
a violation either gets fixed or gets an allowlist entry with a written
justification, never a silent merge.
"""

import json
from pathlib import Path

from siddhi_tpu.analysis import all_rules, index_package, run_rules
from siddhi_tpu.analysis.__main__ import main
from siddhi_tpu.analysis.index import ModuleIndex

REPO = Path(__file__).resolve().parent.parent


def test_rule_catalog_is_complete():
    rules = all_rules()
    names = {r.name for r in rules}
    assert len(rules) >= 9, names
    assert {"host-sync-hazard", "ingest-put-bypass", "broad-except-swallow",
            "lock-discipline", "jit-purity", "retrace-hazard",
            "fallback-discipline", "thread-lifecycle",
            "bounded-queue-discipline"} <= names
    for r in rules:
        assert r.description, f"rule {r.name} has no description"


def test_whole_package_has_no_unbaselined_findings():
    indexes = index_package(REPO / "siddhi_tpu", REPO)
    assert len(indexes) > 50  # the walk actually covered the package
    res = run_rules(indexes)
    assert not res["findings"], (
        "static-analysis violations (fix them, or allowlist in "
        "siddhi_tpu/analysis/allowlists.py WITH a justification):\n  "
        + "\n  ".join(f.render() for f in res["findings"]))
    # the curated allowlists really are doing work, not vacuously empty
    assert len(res["suppressed"]) > 50


def test_new_planner_modules_are_in_the_scan_set():
    """The cost-model pass (planner/costmodel.py), the plan monitor
    (planner/monitor.py) and the fuse+shard engine
    (parallel/fused_shard.py) answer to the same whole-package scan —
    in particular the fallback-discipline rule walks their
    ``except SiddhiAppCreationError`` gates (monitor.decide's candidate
    skip is allowlisted WITH a justification, not invisible)."""
    indexes = index_package(REPO / "siddhi_tpu", REPO)
    rels = {i.rel for i in indexes}
    assert {"siddhi_tpu/planner/costmodel.py",
            "siddhi_tpu/planner/monitor.py",
            "siddhi_tpu/parallel/fused_shard.py"} <= rels
    res = run_rules(indexes)
    suppressed = {(f.rule, f.key) for f in res["suppressed"]}
    assert ("fallback-discipline",
            "siddhi_tpu/planner/monitor.py:PlanMonitor.decide") \
        in suppressed


def test_cli_exits_zero_on_clean_package(capsys):
    rc = main(["--root", str(REPO / "siddhi_tpu")])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "0 finding(s)" in out


def test_cli_lists_rules(capsys):
    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    assert "jit-purity" in out and "lock-discipline" in out


def test_cli_sarif_smoke(capsys):
    """Fast-fail CI entry point: SARIF output, exit 0, >= 8 rules."""
    rc = main(["--root", str(REPO / "siddhi_tpu"), "--format", "sarif"])
    out = capsys.readouterr().out
    assert rc == 0, out
    doc = json.loads(out)
    assert doc["version"] == "2.1.0"
    assert doc["$schema"].endswith("sarif-schema-2.1.0.json")
    run = doc["runs"][0]
    assert len(run["tool"]["driver"]["rules"]) >= 8
    assert run["results"] == []  # clean package


def test_cli_sarif_round_trips_flow_findings(tmp_path, capsys):
    """SARIF with actual results: a fixture package planted with a
    race, an AB/BA cycle, and an incomplete barrier round-trips through
    ``--format sarif`` — every result's ruleIndex points at the right
    driver rule and the partialFingerprints carry the allowlist key."""
    pkg = tmp_path / "siddhi_tpu"   # name puts core/ in barrier scope
    (pkg / "core").mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "core" / "__init__.py").write_text("")
    (pkg / "worker.py").write_text(
        "import threading\n\n\n"
        "class Worker:\n"
        "    def __init__(self):\n"
        "        self._a_lock = threading.Lock()\n"
        "        self._b_lock = threading.Lock()\n"
        "        self.count = 0\n\n"
        "    def start(self):\n"
        "        threading.Thread(target=self._run, daemon=True).start()\n\n"
        "    def _run(self):\n"
        "        self.count += 1\n\n"
        "    def ab(self):\n"
        "        with self._a_lock:\n"
        "            with self._b_lock:\n"
        "                self.count += 1\n\n"
        "    def ba(self):\n"
        "        with self._b_lock:\n"
        "            with self._a_lock:\n"
        "                pass\n")
    (pkg / "core" / "pump.py").write_text(
        "from collections import deque\n\n\n"
        "class Pump:\n"
        "    def __init__(self):\n"
        "        self._spool = deque(maxlen=8)\n\n"
        "    def shutdown(self):\n"
        "        pass\n")
    rc = main(["--root", str(pkg), "--format", "sarif", "--rules",
               "lockset-race,lock-order-deadlock,"
               "barrier-flush-completeness"])
    out = capsys.readouterr().out
    assert rc == 1, out
    doc = json.loads(out)
    run = doc["runs"][0]
    rules = run["tool"]["driver"]["rules"]
    assert [r["id"] for r in rules] == \
        ["lockset-race", "lock-order-deadlock",
         "barrier-flush-completeness"]
    # the real package's allowlist entries are all stale against this
    # fixture tree; those synthesized findings carry no ruleIndex
    results = [r for r in run["results"]
               if r["ruleId"] != "stale-allowlist"]
    by_rule = {rules[r["ruleIndex"]]["id"]: r for r in results}
    assert by_rule.keys() == {"lockset-race", "lock-order-deadlock",
                              "barrier-flush-completeness"}
    for r in results:
        assert r["ruleId"] == rules[r["ruleIndex"]]["id"]
    assert by_rule["lockset-race"]["partialFingerprints"] == \
        {"analysisKey/v1": "lockset-race:siddhi_tpu/worker.py:Worker.count"}
    loc = by_rule["lockset-race"]["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == "siddhi_tpu/worker.py"
    assert "Worker._a_lock" in \
        by_rule["lock-order-deadlock"]["partialFingerprints"][
            "analysisKey/v1"]
    assert by_rule["barrier-flush-completeness"]["partialFingerprints"][
        "analysisKey/v1"].endswith("core/pump.py:Pump._spool")


def test_json_report_stamps_rule_and_finding_counts(capsys):
    rc = main(["--root", str(REPO / "siddhi_tpu"), "--format", "json"])
    out = capsys.readouterr().out
    assert rc == 0, out
    doc = json.loads(out)
    assert doc["rule_count"] >= 8
    assert doc["finding_count"] == 0
    assert doc["rule_count"] == len(doc["rules"])


def test_parse_cache_one_parse_per_file():
    """The 8 rules (and repeated runs in one process) share one parse
    per file, keyed (path, mtime, size)."""
    root = REPO / "siddhi_tpu"
    first = index_package(root, REPO)
    count = ModuleIndex.parse_count
    again = index_package(root, REPO)
    assert ModuleIndex.parse_count == count  # no re-parse
    assert [i.rel for i in again] == [i.rel for i in first]
    assert all(a is b for a, b in zip(first, again))
    # cache=False forces fresh parses (fixture isolation escape hatch)
    index_package(root, REPO, cache=False)
    assert ModuleIndex.parse_count == count + len(first)
