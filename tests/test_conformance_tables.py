"""Table conformance matrix: CRUD, keys/indexes, IN-op, caches.

Ported behavior families from the reference's table suites
(modules/siddhi-core/src/test/java/io/siddhi/core/query/table/ —
InsertIntoTableTestCase, DeleteFromTableTestCase, UpdateFromTableTestCase,
UpdateOrInsertTableTestCase, InOperatorTestCase, cache/store corpora).
"""

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.extension.registry import extension
from siddhi_tpu.table.record import InMemoryRecordStore


# the reference test double (test/.../TestStoreContainingInMemoryTable)
# is test-scoped there too; registered unconditionally so the cache
# test below can never silently skip
@extension("store", "testStoreContainingInMemoryTable")
class _TestStoreContainingInMemoryTable(InMemoryRecordStore):
    pass

BASE = (
    "define stream StockStream (symbol string, price double, volume long); "
    "define stream Ops (symbol string, price double, volume long); "
    "define stream Check (symbol string); "
)


def run(app, sends, out="OutputStream"):
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime("@app:playback " + BASE + app)
        got = []
        if out in rt.junctions:
            rt.add_callback(out, lambda evs: got.extend(e.data for e in evs))
        rt.start()
        t = 1000
        for stream, row in sends:
            rt.get_input_handler(stream).send(row, timestamp=t)
            t += 100
        tables = rt.tables
        rt.shutdown()
        return got, tables
    finally:
        m.shutdown()


def table_rows(tables, name="T"):
    tb = tables[name]
    batch = tb.rows_batch()
    if batch is None or len(batch) == 0:
        return []
    return [list(r) for r in zip(*[batch.columns[c]
                                   for c in batch.attribute_names])]


class TestInsertDelete:
    def test_insert_and_contents(self):
        app = ("define table T (symbol string, price double, volume long); "
               "from StockStream insert into T;")
        _got, tables = run(app, [("StockStream", ["IBM", 700.0, 100]),
                                 ("StockStream", ["WSO2", 60.0, 200])])
        assert table_rows(tables) == [["IBM", 700.0, 100],
                                      ["WSO2", 60.0, 200]]

    def test_delete_on_condition(self):
        app = ("define table T (symbol string, price double, volume long); "
               "from StockStream insert into T; "
               "from Ops delete T on T.symbol == symbol;")
        _got, tables = run(app, [
            ("StockStream", ["IBM", 700.0, 100]),
            ("StockStream", ["WSO2", 60.0, 200]),
            ("Ops", ["IBM", 0.0, 0]),
        ])
        assert table_rows(tables) == [["WSO2", 60.0, 200]]

    def test_delete_compound_condition(self):
        app = ("define table T (symbol string, price double, volume long); "
               "from StockStream insert into T; "
               "from Ops delete T on T.symbol == symbol and T.volume < volume;")
        _got, tables = run(app, [
            ("StockStream", ["IBM", 700.0, 100]),
            ("StockStream", ["IBM", 700.0, 500]),
            ("Ops", ["IBM", 0.0, 300]),   # deletes only the 100-row
        ])
        assert table_rows(tables) == [["IBM", 700.0, 500]]


class TestUpdate:
    def test_update_set_clause(self):
        app = ("define table T (symbol string, price double, volume long); "
               "from StockStream insert into T; "
               "from Ops update T set T.price = price "
               "on T.symbol == symbol;")
        _got, tables = run(app, [
            ("StockStream", ["IBM", 700.0, 100]),
            ("Ops", ["IBM", 710.5, 0]),
        ])
        assert table_rows(tables) == [["IBM", 710.5, 100]]

    def test_update_expression_set(self):
        app = ("define table T (symbol string, price double, volume long); "
               "from StockStream insert into T; "
               "from Ops update T set T.volume = T.volume + volume "
               "on T.symbol == symbol;")
        _got, tables = run(app, [
            ("StockStream", ["IBM", 700.0, 100]),
            ("Ops", ["IBM", 0.0, 50]),
            ("Ops", ["IBM", 0.0, 25]),
        ])
        assert table_rows(tables) == [["IBM", 700.0, 175]]

    def test_update_or_insert(self):
        app = ("define table T (symbol string, price double, volume long); "
               "from Ops update or insert into T set T.price = price "
               "on T.symbol == symbol;")
        _got, tables = run(app, [
            ("Ops", ["IBM", 700.0, 100]),   # inserts
            ("Ops", ["IBM", 710.0, 999]),   # updates price only
            ("Ops", ["WSO2", 60.0, 200]),   # inserts
        ])
        assert table_rows(tables) == [["IBM", 710.0, 100],
                                      ["WSO2", 60.0, 200]]


class TestInOperator:
    def test_membership_filter(self):
        # IN probes the table's single-attribute primary key
        app = ("@primaryKey('symbol') "
               "define table T (symbol string, price double, volume long); "
               "from StockStream insert into T; "
               "from Check[Check.symbol in T] select symbol "
               "insert into OutputStream;")
        got, _ = run(app, [
            ("StockStream", ["IBM", 700.0, 100]),
            ("Check", ["IBM"]),
            ("Check", ["GOOG"]),
        ])
        assert [g[0] for g in got] == ["IBM"]


class TestPrimaryKeyAndIndex:
    def test_primary_key_upsert_semantics(self):
        app = ("@primaryKey('symbol') "
               "define table T (symbol string, price double, volume long); "
               "from StockStream insert into T; "
               "from Ops update T set T.price = price on T.symbol == symbol;")
        _got, tables = run(app, [
            ("StockStream", ["IBM", 700.0, 100]),
            ("Ops", ["IBM", 705.0, 0]),
        ])
        assert table_rows(tables) == [["IBM", 705.0, 100]]

    def test_indexed_lookup_join(self):
        app = ("@index('symbol') "
               "define table T (symbol string, price double, volume long); "
               "from StockStream insert into T; "
               "from Check join T on Check.symbol == T.symbol "
               "select T.symbol as symbol, T.price as price "
               "insert into OutputStream;")
        got, _ = run(app, [
            ("StockStream", ["IBM", 700.0, 100]),
            ("StockStream", ["WSO2", 60.0, 200]),
            ("Check", ["WSO2"]),
        ])
        assert got == [["WSO2", 60.0]]


class TestCacheTable:
    def test_fifo_cache_bounded(self):
        # @store in-memory record table fronted by a FIFO cache
        app = ("@store(type='testStoreContainingInMemoryTable', "
               "@cache(size='2', cache.policy='FIFO')) "
               "define table T (symbol string, price double, volume long); "
               "from StockStream insert into T; "
               "from Check join T on Check.symbol == T.symbol "
               "select T.symbol as symbol insert into OutputStream;")
        got, _ = run(app, [
            ("StockStream", ["A", 1.0, 1]),
            ("StockStream", ["B", 2.0, 2]),
            ("StockStream", ["C", 3.0, 3]),
            ("Check", ["C"]),
        ])
        assert [g[0] for g in got] == ["C"]


class TestOnDemandQueries:
    """Pull queries against tables (reference: OnDemandQueryTableTestCase)."""

    def _runtime(self, app):
        m = SiddhiManager()
        rt = m.create_siddhi_app_runtime("@app:playback " + BASE + app)
        rt.start()
        return m, rt

    def test_select_from_table(self):
        m, rt = self._runtime(
            "define table T (symbol string, price double, volume long); "
            "from StockStream insert into T;")
        try:
            h = rt.get_input_handler("StockStream")
            h.send(["IBM", 700.0, 100], timestamp=1000)
            h.send(["WSO2", 60.0, 200], timestamp=1100)
            rows = rt.query("from T select symbol, price")
            assert sorted(e.data for e in rows) == [["IBM", 700.0],
                                                    ["WSO2", 60.0]]
            rows = rt.query("from T on volume > 150 select symbol")
            assert [e.data for e in rows] == [["WSO2"]]
        finally:
            rt.shutdown()
            m.shutdown()

    def test_aggregate_on_demand(self):
        m, rt = self._runtime(
            "define table T (symbol string, price double, volume long); "
            "from StockStream insert into T;")
        try:
            h = rt.get_input_handler("StockStream")
            for row in [["IBM", 10.0, 1], ["IBM", 20.0, 2], ["WSO2", 5.0, 3]]:
                h.send(row, timestamp=1000)
            rows = rt.query(
                "from T select symbol, sum(price) as total group by symbol")
            assert sorted(e.data for e in rows) == [["IBM", 30.0],
                                                    ["WSO2", 5.0]]
        finally:
            rt.shutdown()
            m.shutdown()
