"""Sequence conformance: strict continuity, counts, every, within.

Ported behavior families from the reference's sequence suites
(modules/siddhi-core/src/test/java/io/siddhi/core/query/sequence/
SequenceTestCase.java, absent/...).  A sequence (`,`) requires
CONSECUTIVE matching events — any non-matching event kills pending
chains; the start state stays armed.
"""

import pytest

from siddhi_tpu import SiddhiManager

STREAMS = (
    "define stream S (symbol string, price float, volume int); "
    "define stream S2 (symbol string, price float, volume int); "
)


def run(query, sends, out="OutputStream"):
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime("@app:playback " + STREAMS + query)
        got = []
        rt.add_callback(out, lambda evs: got.extend(e.data for e in evs))
        rt.start()
        t = 1000
        for item in sends:
            if len(item) == 2:
                stream, row = item
                ts = t
                t += 100
            else:
                stream, row, ts = item
            rt.get_input_handler(stream).send(row, timestamp=ts)
        rt.shutdown()
        return got
    finally:
        m.shutdown()


class TestStrictContinuity:
    Q = ("@info(name='q') from e1=S[price > 100], e2=S[price > e1.price] "
         "select e1.price as p1, e2.price as p2 insert into OutputStream;")

    def test_consecutive_matches(self):
        got = run(self.Q, [("S", ["A", 110.0, 1]), ("S", ["B", 120.0, 1])])
        assert got == [[110.0, 120.0]]

    def test_interloper_kills_chain(self):
        # the middle event matches neither e1-continuation nor e2
        got = run(self.Q, [("S", ["A", 110.0, 1]),
                           ("S", ["X", 50.0, 1]),     # kills the pending e2
                           ("S", ["B", 120.0, 1])])
        # B then arms a NEW e1 (120) — no match emitted for (110, 120)
        assert got == []

    def test_non_every_dead_after_kill(self):
        # a non-every sequence arms ONCE; after the interloper kills the
        # pending arm nothing re-arms (reference
        # SequenceTestCase.testQuery31 expects zero matches)
        got = run(self.Q, [("S", ["A", 110.0, 1]),
                           ("S", ["X", 50.0, 1]),
                           ("S", ["B", 120.0, 1]),
                           ("S", ["C", 130.0, 1])])
        assert got == []

    def test_non_every_matches_once(self):
        got = run(self.Q, [("S", ["A", 110.0, 1]), ("S", ["B", 120.0, 1]),
                           ("S", ["C", 130.0, 1]), ("S", ["D", 140.0, 1])])
        assert got == [[110.0, 120.0]]


class TestEverySequence:
    Q = ("@info(name='q') from every e1=S[price > 100], "
         "e2=S[price > e1.price] "
         "select e1.price as p1, e2.price as p2 insert into OutputStream;")

    def test_every_overlapping_consecutive_pairs(self):
        got = run(self.Q, [("S", ["A", 110.0, 1]), ("S", ["B", 120.0, 1]),
                           ("S", ["C", 130.0, 1]), ("S", ["D", 140.0, 1])])
        # `every` rearms the start on EVERY event, so each ascending
        # consecutive pair matches (overlapping) — the reference's
        # every-sequence contract
        assert got == [[110.0, 120.0], [120.0, 130.0], [130.0, 140.0]]

    def test_every_with_kill_between(self):
        got = run(self.Q, [("S", ["A", 110.0, 1]), ("S", ["B", 120.0, 1]),
                           ("S", ["X", 10.0, 1]),
                           ("S", ["C", 130.0, 1]), ("S", ["D", 140.0, 1])])
        # the X interloper kills the (120, ...) arm; pairs on both sides
        # of it survive
        assert got == [[110.0, 120.0], [130.0, 140.0]]


class TestSequenceCounts:
    def test_plus_collects_consecutive(self):
        q = ("@info(name='q') from e1=S[price > 100]+, e2=S[price < 50] "
             "select e1[0].price as first, e1[last].price as last_, "
             "e2.price as stop insert into OutputStream;")
        got = run(q, [("S", ["A", 110.0, 1]), ("S", ["B", 120.0, 1]),
                      ("S", ["C", 130.0, 1]), ("S", ["D", 10.0, 1])])
        assert got == [[110.0, 130.0, 10.0]]

    def test_star_zero_occurrences(self):
        q = ("@info(name='q') from e1=S[price > 200]*, e2=S[price < 50] "
             "select e2.price as stop insert into OutputStream;")
        got = run(q, [("S", ["D", 10.0, 1])])
        assert got == [[10.0]]

    def test_bounded_count_exact(self):
        q = ("@info(name='q') from e1=S[price > 100]<2>, e2=S[price < 50] "
             "select e1[0].price as a, e1[last].price as b, e2.price as c "
             "insert into OutputStream;")
        got = run(q, [("S", ["A", 110.0, 1]), ("S", ["B", 120.0, 1]),
                      ("S", ["D", 10.0, 1])])
        assert got == [[110.0, 120.0, 10.0]]


class TestSequenceTwoStreams:
    def test_cross_stream_strictness(self):
        q = ("@info(name='q') from e1=S[price > 100], e2=S2[price > 100] "
             "select e1.symbol as a, e2.symbol as b "
             "insert into OutputStream;")
        got = run(q, [("S", ["A", 110.0, 1]), ("S2", ["B", 120.0, 1])])
        assert got == [["A", "B"]]
        # an S event between them kills the chain (strict continuity is
        # across ALL source streams of the sequence)
        got = run(q, [("S", ["A", 110.0, 1]), ("S", ["X", 10.0, 1]),
                      ("S2", ["B", 120.0, 1])])
        assert got == []

    def test_within_prunes_sequence(self):
        q = ("@info(name='q') from every e1=S[price > 100], "
             "e2=S[price > e1.price] within 1 sec "
             "select e1.price as p1, e2.price as p2 "
             "insert into OutputStream;")
        got = run(q, [("S", ["A", 110.0, 1], 1000),
                      ("S", ["B", 120.0, 1], 2500)])  # too late
        assert got == []
        got = run(q, [("S", ["A", 110.0, 1], 1000),
                      ("S", ["B", 120.0, 1], 1500)])
        assert got == [[110.0, 120.0]]
