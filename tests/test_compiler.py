"""Parser conformance tests.

Query corpus mirrors the shapes exercised by the reference TestNG suite
(modules/siddhi-core/src/test/java/io/siddhi/core/query/*) — SiddhiQL
string in, AST asserted out.
"""

import pytest

from siddhi_tpu.compiler import SiddhiCompiler, SiddhiParserError
from siddhi_tpu.query_api import (
    AttrType,
    Constant,
    TimeConstant,
    Variable,
    FunctionCall,
    CompareOp,
    AndOp,
    ArithmeticOp,
    SingleInputStream,
    JoinInputStream,
    StateInputStream,
    Filter,
    WindowHandler,
    StreamStateElement,
    AbsentStreamStateElement,
    CountStateElement,
    LogicalStateElement,
    NextStateElement,
    EveryStateElement,
    InsertIntoStream,
    ReturnStream,
    EventOutputRate,
    TimeOutputRate,
    SnapshotOutputRate,
    ValuePartitionType,
    RangePartitionType,
)


def parse(s):
    return SiddhiCompiler.parse(s)


class TestDefinitions:
    def test_stream_definition(self):
        app = parse("define stream StockStream (symbol string, price float, volume long);")
        d = app.stream_definitions["StockStream"]
        assert d.attribute_names == ["symbol", "price", "volume"]
        assert d.attribute_type("price") == AttrType.FLOAT
        assert d.attribute_type("volume") == AttrType.LONG

    def test_all_attribute_types(self):
        app = parse(
            "define stream S (a string, b int, c long, d float, e double, f bool, g object);"
        )
        d = app.stream_definitions["S"]
        assert [a.type for a in d.attributes] == [
            AttrType.STRING, AttrType.INT, AttrType.LONG,
            AttrType.FLOAT, AttrType.DOUBLE, AttrType.BOOL, AttrType.OBJECT,
        ]

    def test_table_definition_with_annotations(self):
        app = parse(
            "@primaryKey('symbol') @index('volume') "
            "define table StockTable (symbol string, price float, volume long);"
        )
        d = app.table_definitions["StockTable"]
        assert d.annotations[0].name == "primaryKey"
        assert d.annotations[0].element() == "symbol"
        assert d.annotations[1].element() == "volume"

    def test_window_definition(self):
        app = parse("define window CheckW (symbol string) length(5) output all events;")
        d = app.window_definitions["CheckW"]
        assert d.window_function.name == "length"
        assert d.window_function.args[0] == Constant(5, AttrType.INT)
        assert d.output_event_type == "all"

    def test_window_definition_time(self):
        app = parse("define window W2 (a int) time(2 sec);")
        d = app.window_definitions["W2"]
        assert d.window_function.args[0] == TimeConstant(2000)
        # reference default: ALL events (WindowDefinition.java:40)
        assert d.output_event_type == "all"

    def test_trigger_definitions(self):
        app = parse(
            "define trigger T5 at every 5 sec; "
            "define trigger TStart at 'start'; "
            "define trigger TCron at '*/5 * * * * ?';"
        )
        assert app.trigger_definitions["T5"].at_every_ms == 5000
        assert app.trigger_definitions["TStart"].at_start
        assert app.trigger_definitions["TCron"].at_cron == "*/5 * * * * ?"

    def test_function_definition(self):
        app = parse(
            "define function concatFn[javascript] return string { var res = ''; return res; };"
        )
        f = app.function_definitions["concatFn"]
        assert f.language == "javascript"
        assert f.return_type == AttrType.STRING
        assert "var res" in f.body

    def test_aggregation_definition_range(self):
        app = parse(
            "define stream TradeStream (symbol string, price double, volume long, timestamp long); "
            "define aggregation TradeAggregation "
            "from TradeStream "
            "select symbol, avg(price) as avgPrice, sum(volume) as total "
            "group by symbol "
            "aggregate by timestamp every sec ... year;"
        )
        agg = app.aggregation_definitions["TradeAggregation"]
        assert agg.durations == ["seconds", "minutes", "hours", "days", "weeks", "months", "years"]
        assert agg.aggregate_by == "timestamp"
        assert agg.selector.group_by[0].attribute == "symbol"

    def test_aggregation_definition_list(self):
        app = parse(
            "define stream S (a string, ts long); "
            "define aggregation A from S select a, count() as c "
            "aggregate by ts every min, hour;"
        )
        assert app.aggregation_definitions["A"].durations == ["minutes", "hours"]

    def test_app_annotation(self):
        app = parse(
            "@app:name('Test-App') @app:statistics(reporter = 'console', interval = '5') "
            "define stream S (a int);"
        )
        assert app.annotations[0].name == "app:name"
        assert app.annotations[0].element() == "Test-App"
        assert app.annotations[1].element("reporter") == "console"

    def test_duplicate_definition_rejected(self):
        with pytest.raises(Exception):
            parse("define stream S (a int); define table S (a int);")


class TestFilterQueries:
    def test_simple_filter(self):
        app = parse(
            "define stream cseEventStream (symbol string, price float, volume long); "
            "@info(name = 'query1') "
            "from cseEventStream[volume < 150] select symbol, price insert into outputStream;"
        )
        q = app.queries[0]
        assert q.annotations[0].element("name") == "query1"
        s = q.input_stream
        assert isinstance(s, SingleInputStream)
        assert s.stream_id == "cseEventStream"
        f = s.handlers[0]
        assert isinstance(f, Filter)
        assert f.expression == CompareOp("<", Variable("volume"), Constant(150, AttrType.INT))
        assert [a.name for a in q.selector.selection] == ["symbol", "price"]
        assert isinstance(q.output_stream, InsertIntoStream)
        assert q.output_stream.target == "outputStream"

    def test_filter_compound_condition(self):
        app = parse(
            "define stream S (symbol string, price float, volume long); "
            "from S[volume < 150 and price > 50.0] select * insert into O;"
        )
        f = app.queries[0].input_stream.handlers[0]
        assert isinstance(f.expression, AndOp)

    def test_math_precedence(self):
        app = parse(
            "define stream S (a int, b int, c int); "
            "from S select a + b * c as x insert into O;"
        )
        expr = app.queries[0].selector.selection[0].expression
        assert isinstance(expr, ArithmeticOp) and expr.op == "+"
        assert isinstance(expr.right, ArithmeticOp) and expr.right.op == "*"

    def test_select_star_implicit(self):
        app = parse("define stream S (a int); from S insert into O;")
        assert app.queries[0].selector.is_select_all

    def test_function_call_namespaced(self):
        app = parse(
            "define stream S (a string); "
            "from S select str:concat(a, '!') as x insert into O;"
        )
        e = app.queries[0].selector.selection[0].expression
        assert isinstance(e, FunctionCall)
        assert e.namespace == "str" and e.name == "concat"

    def test_stream_qualified_attr(self):
        app = parse(
            "define stream S (a int); from S[S.a > 5] select S.a as a insert into O;"
        )
        f = app.queries[0].input_stream.handlers[0]
        assert f.expression.left == Variable("a", stream_id="S")

    def test_insert_event_types(self):
        app = parse(
            "define stream S (a int); "
            "from S#window.length(5) select a insert expired events into O;"
        )
        assert app.queries[0].output_stream.event_type == "expired"

    def test_fault_stream_output(self):
        app = parse("define stream S (a int); from !S select a insert into O;")
        assert app.queries[0].input_stream.is_fault


class TestWindowQueries:
    def test_length_window(self):
        app = parse(
            "define stream S (symbol string, price float); "
            "from S#window.length(50) select symbol, avg(price) as p "
            "group by symbol having p > 10 insert into O;"
        )
        q = app.queries[0]
        w = q.input_stream.window
        assert isinstance(w, WindowHandler) and w.name == "length"
        assert q.selector.having is not None

    def test_time_window_with_group_order_limit(self):
        app = parse(
            "define stream S (symbol string, price float, volume long); "
            "from S#window.time(1 min) "
            "select symbol, sum(volume) as v group by symbol "
            "order by v desc limit 5 offset 1 insert into O;"
        )
        sel = app.queries[0].selector
        assert sel.order_by[0].ascending is False
        assert sel.limit == Constant(5, AttrType.INT)
        assert sel.offset == Constant(1, AttrType.INT)

    def test_filter_then_window_then_filter(self):
        app = parse(
            "define stream S (a int); "
            "from S[a > 1]#window.lengthBatch(4)[a < 10] select a insert into O;"
        )
        handlers = app.queries[0].input_stream.handlers
        assert isinstance(handlers[0], Filter)
        assert isinstance(handlers[1], WindowHandler)
        assert isinstance(handlers[2], Filter)

    def test_time_value_compound(self):
        app = parse(
            "define stream S (a int); "
            "from S#window.time(1 hour 30 min) select a insert into O;"
        )
        w = app.queries[0].input_stream.window
        assert w.args[0] == TimeConstant(90 * 60 * 1000)

    def test_external_time_window(self):
        app = parse(
            "define stream S (ts long, a int); "
            "from S#window.externalTime(ts, 5 sec) select a insert into O;"
        )
        w = app.queries[0].input_stream.window
        assert w.name == "externalTime"
        assert w.args[0] == Variable("ts")


class TestJoinQueries:
    def test_simple_join(self):
        app = parse(
            "define stream A (symbol string, price float); "
            "define stream B (symbol string, volume long); "
            "from A#window.length(10) join B#window.length(20) "
            "on A.symbol == B.symbol "
            "select A.symbol as s, price, volume insert into O;"
        )
        j = app.queries[0].input_stream
        assert isinstance(j, JoinInputStream)
        assert j.join_type == JoinInputStream.JOIN
        assert j.left.stream_id == "A" and j.right.stream_id == "B"
        assert isinstance(j.on_condition, CompareOp)

    def test_left_outer_join_with_alias_unidirectional(self):
        app = parse(
            "define stream A (s string); define stream B (s string); "
            "from A#window.time(1 min) as l unidirectional "
            "left outer join B#window.time(1 min) as r "
            "on l.s == r.s select l.s as s insert into O;"
        )
        j = app.queries[0].input_stream
        assert j.join_type == JoinInputStream.LEFT_OUTER
        assert j.trigger == "left"
        assert j.left.alias == "l" and j.right.alias == "r"

    def test_join_table(self):
        app = parse(
            "define stream S (symbol string); define table T (symbol string, price float); "
            "from S join T on S.symbol == T.symbol select S.symbol as s, T.price as p insert into O;"
        )
        j = app.queries[0].input_stream
        assert isinstance(j, JoinInputStream)


class TestPatternQueries:
    def test_simple_pattern(self):
        app = parse(
            "define stream S1 (price float); define stream S2 (price float); "
            "from e1=S1[price > 20] -> e2=S2[price > e1.price] "
            "select e1.price as p1, e2.price as p2 insert into O;"
        )
        st = app.queries[0].input_stream
        assert isinstance(st, StateInputStream)
        assert st.type == StateInputStream.PATTERN
        nxt = st.state
        assert isinstance(nxt, NextStateElement)
        assert isinstance(nxt.element, StreamStateElement)
        assert nxt.element.event_ref == "e1"
        assert isinstance(nxt.next, StreamStateElement)
        # cross-state reference parsed as stream-qualified variable
        f = nxt.next.stream.handlers[0]
        assert f.expression.right == Variable("price", stream_id="e1")

    def test_every_pattern_within(self):
        app = parse(
            "define stream S (a int); define stream R (a int); "
            "from every e1=S[a > 1] -> e2=R[a > e1.a] within 10 min "
            "select e1.a as a1, e2.a as a2 insert into O;"
        )
        st = app.queries[0].input_stream
        assert st.within_ms == 600000
        assert isinstance(st.state.element, EveryStateElement)

    def test_every_group_pattern(self):
        app = parse(
            "define stream S (a int); "
            "from every (e1=S -> e2=S) -> e3=S select e1.a as x insert into O;"
        )
        st = app.queries[0].input_stream.state
        assert isinstance(st, NextStateElement)
        assert isinstance(st.element, EveryStateElement)
        assert isinstance(st.element.element, NextStateElement)

    def test_count_pattern(self):
        app = parse(
            "define stream TempStream (temp double); "
            "from e1=TempStream[temp > 39]<1:5> -> e2=TempStream[temp < 35] "
            "select e1[0].temp as t0, e1[last].temp as tl insert into O;"
        )
        st = app.queries[0].input_stream.state
        c = st.element
        assert isinstance(c, CountStateElement)
        assert c.min_count == 1 and c.max_count == 5
        sel = app.queries[0].selector.selection
        assert sel[0].expression.stream_index == 0
        assert sel[1].expression.stream_index == -1

    def test_logical_and_pattern(self):
        app = parse(
            "define stream A (a int); define stream B (b int); "
            "from e1=A and e2=B select e1.a as a, e2.b as b insert into O;"
        )
        st = app.queries[0].input_stream.state
        assert isinstance(st, LogicalStateElement)
        assert st.operator == "and"

    def test_absent_pattern(self):
        app = parse(
            "define stream A (a int); define stream B (b int); "
            "from e1=A -> not B for 5 sec select e1.a as a insert into O;"
        )
        st = app.queries[0].input_stream.state
        assert isinstance(st.next, AbsentStreamStateElement)
        assert st.next.waiting_time_ms == 5000

    def test_logical_absent_pattern(self):
        app = parse(
            "define stream A (a int); define stream B (b int); "
            "from not A[a > 1] and e2=B select e2.b as b insert into O;"
        )
        st = app.queries[0].input_stream.state
        assert isinstance(st, LogicalStateElement)
        assert isinstance(st.element1, AbsentStreamStateElement)


class TestSequenceQueries:
    def test_simple_sequence(self):
        app = parse(
            "define stream S (price float); "
            "from e1=S, e2=S[price > e1.price] "
            "select e1.price as p1, e2.price as p2 insert into O;"
        )
        st = app.queries[0].input_stream
        assert st.type == StateInputStream.SEQUENCE
        assert isinstance(st.state, NextStateElement)

    def test_kleene_sequence(self):
        app = parse(
            "define stream S (a int); "
            "from every e1=S[a == 1], e2=S[a > 1]+, e3=S[a < 0] "
            "select e1.a as x, e2[0].a as y insert into O;"
        )
        st = app.queries[0].input_stream.state
        assert isinstance(st.element, EveryStateElement)
        plus = st.next.element
        assert isinstance(plus, CountStateElement)
        assert plus.min_count == 1 and plus.max_count == CountStateElement.ANY

    def test_zero_or_more_and_optional(self):
        app = parse(
            "define stream S (a int); "
            "from e1=S, e2=S*, e3=S? , e4=S select e1.a as x insert into O;"
        )
        st = app.queries[0].input_stream.state
        e2 = st.next.element
        assert e2.min_count == 0 and e2.max_count == CountStateElement.ANY
        e3 = st.next.next.element
        assert e3.min_count == 0 and e3.max_count == 1


class TestOutputRateAndPartition:
    def test_event_rate(self):
        app = parse(
            "define stream S (a int); "
            "from S select a output first every 5 events insert into O;"
        )
        r = app.queries[0].output_rate
        assert isinstance(r, EventOutputRate)
        assert r.type == "first" and r.events == 5

    def test_time_rate_and_snapshot(self):
        app = parse(
            "define stream S (a int); "
            "from S select a output last every 2 sec insert into O; "
            "from S select a output snapshot every 1 sec insert into O2;"
        )
        r0 = app.queries[0].output_rate
        assert isinstance(r0, TimeOutputRate) and r0.value_ms == 2000 and r0.type == "last"
        r1 = app.queries[1].output_rate
        assert isinstance(r1, SnapshotOutputRate) and r1.value_ms == 1000

    def test_value_partition(self):
        app = parse(
            "define stream S (symbol string, price float); "
            "partition with (symbol of S) begin "
            "@info(name='q') from S select symbol, sum(price) as t insert into O; "
            "end;"
        )
        p = app.execution_elements[0]
        assert isinstance(p.partition_types[0], ValuePartitionType)
        assert len(p.queries) == 1

    def test_range_partition(self):
        app = parse(
            "define stream S (temp double); "
            "partition with (temp < 10 as 'low' or temp >= 10 as 'high' of S) begin "
            "from S select temp insert into #Inner; "
            "from #Inner select temp insert into O; "
            "end;"
        )
        p = app.execution_elements[0]
        rt = p.partition_types[0]
        assert isinstance(rt, RangePartitionType)
        assert [lbl for _, lbl in rt.ranges] == ["low", "high"]
        assert p.queries[0].output_stream.is_inner

    def test_return_output(self):
        q = SiddhiCompiler.parse_query("from S select a return;")
        assert isinstance(q.output_stream, ReturnStream)


class TestOnDemandQueries:
    def test_find(self):
        q = SiddhiCompiler.parse_on_demand_query(
            "from StockTable on price > 40 select symbol, price order by price limit 2"
        )
        assert q.type == "find"
        assert q.input_store == "StockTable"
        assert q.on_condition is not None

    def test_update(self):
        q = SiddhiCompiler.parse_on_demand_query(
            "select 100f as price update StockTable set StockTable.price = price on StockTable.symbol == 'X'"
        )
        assert q.type == "update"

    def test_insert(self):
        q = SiddhiCompiler.parse_on_demand_query(
            "select 'WSO2' as symbol, 100f as price insert into StockTable"
        )
        assert q.type == "insert"


class TestMisc:
    def test_comments_and_variables(self):
        src = (
            "-- comment line\n"
            "/* block\ncomment */\n"
            "define stream S (a int);\n"
            "from S select a insert into O;"
        )
        app = parse(src)
        assert len(app.queries) == 1

    def test_update_variables(self):
        out = SiddhiCompiler.update_variables(
            "define stream S (a ${T});", env={"T": "int"}
        )
        assert out == "define stream S (a int);"

    def test_parse_error_has_location(self):
        with pytest.raises(SiddhiParserError):
            parse("define stream S (a int) from")

    def test_is_null(self):
        app = parse("define stream S (a int); from S[a is null] select a insert into O;")
        from siddhi_tpu.query_api import IsNull

        f = app.queries[0].input_stream.handlers[0]
        assert isinstance(f.expression, IsNull)

    def test_in_table(self):
        app = parse(
            "define stream S (a int); define table T (a int); "
            "from S[a in T] select a insert into O;"
        )
        from siddhi_tpu.query_api import InOp

        f = app.queries[0].input_stream.handlers[0]
        assert isinstance(f.expression, InOp)
        assert f.expression.source_id == "T"

    def test_not_precedence(self):
        app = parse(
            "define stream S (a bool, b bool); from S[not a and b] select a insert into O;"
        )
        from siddhi_tpu.query_api import NotOp

        f = app.queries[0].input_stream.handlers[0]
        assert isinstance(f.expression, AndOp)
        assert isinstance(f.expression.left, NotOp)

    def test_triple_quoted_string(self):
        app = parse('define stream S (a string); from S[a == """x "y" z"""] select a insert into O;')
        f = app.queries[0].input_stream.handlers[0]
        assert f.expression.right.value == 'x "y" z'


class TestScriptFunctions:
    def test_parse_function_definition(self):
        from siddhi_tpu.compiler import SiddhiCompiler

        app = SiddhiCompiler.parse(
            "define function double[python] return long { data[0] * 2 }; "
            "define stream S (v long); from S select double(v) as d insert into O;"
        )
        fd = app.function_definitions["double"]
        assert fd.language == "python"
        assert "data[0] * 2" in fd.body


class TestFluentBuilder:
    def test_build_and_run(self):
        from siddhi_tpu import SiddhiManager
        from siddhi_tpu.query_api import AttrType
        from siddhi_tpu.query_api import builder as b

        app = (
            b.siddhi_app("fluent")
            .define_stream(
                b.stream("S").attribute("sym", AttrType.STRING).attribute("v", AttrType.LONG)
            )
            .add_query(
                b.query("q1")
                .from_stream("S", where=b.compare(b.var("v"), ">", b.value(10)))
                .select("sym", ("doubled", b.multiply(b.var("v"), b.value(2))))
                .insert_into("Out")
            )
            .build()
        )
        m = SiddhiManager()
        rt = m.create_siddhi_app_runtime(app)
        assert rt.name == "fluent"
        got = []
        rt.add_callback("Out", lambda evs: got.extend(evs))
        rt.start()
        rt.get_input_handler("S").send(["A", 5])
        rt.get_input_handler("S").send(["B", 50])
        rt.shutdown()
        m.shutdown()
        assert [e.data[0] for e in got] == ["B"]

    def test_window_group_by_having(self):
        from siddhi_tpu import SiddhiManager
        from siddhi_tpu.query_api import AttrType
        from siddhi_tpu.query_api import builder as b

        app = (
            b.siddhi_app()
            .define_stream(
                b.stream("S").attribute("sym", AttrType.STRING).attribute("v", AttrType.LONG)
            )
            .add_query(
                b.query()
                .from_stream("S", window=("length", [b.value(10)]))
                .select("sym", ("total", b.function("sum", b.var("v"))))
                .group_by("sym")
                .having(b.compare(b.var("total"), ">", b.value(15)))
                .insert_into("Out")
            )
            .build()
        )
        m = SiddhiManager()
        rt = m.create_siddhi_app_runtime(app)
        got = []
        rt.add_callback("Out", lambda evs: got.extend(evs))
        rt.start()
        h = rt.get_input_handler("S")
        h.send(["A", 10])    # total 10, filtered by having
        h.send(["A", 10])    # total 20 -> emitted
        rt.shutdown()
        m.shutdown()
        assert [e.data for e in got] == [["A", 20]]
