"""Associative-scan NFA (ops/nfa_scan.py) — the single-hot-key
sequence-parallel engine — differentially against the host pattern
engine: for capture-free linear chains the set of COMPLETING events
(detections) must match exactly, including `within` pruning.
"""

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.exceptions import SiddhiAppCreationError
from siddhi_tpu.ops.nfa_scan import compile_scan_pattern

DEFS = "define stream S (v double, n int); "


def host_detections(app, cols, ts):
    """Timestamps of events where the host engine emitted >= 1 match."""
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime("@app:playback " + app)
        seen = []

        def cb(cts, in_events, out_events):
            if in_events:
                seen.append(cts)

        rt.add_callback("q", cb)
        rt.start()
        h = rt.get_input_handler("S")
        for i in range(len(ts)):
            h.send([float(cols["v"][i]), int(cols["n"][i])],
                   timestamp=int(ts[i]))
        rt.shutdown()
        return sorted(set(seen))
    finally:
        m.shutdown()


def scan_detections(app, cols, ts, chunks=1):
    eng = compile_scan_pattern(app, "q")
    st = eng.init_state()
    out = []
    for part in np.array_split(np.arange(len(ts)), chunks):
        if len(part) == 0:
            continue
        st, idx, _starts = eng.process(
            st, {k: v[part] for k, v in cols.items()}, ts[part])
        out.extend(int(ts[part[0] + i]) for i in idx)
    return sorted(set(out))


def mk(n=60, seed=0, t_step=300):
    rng = np.random.default_rng(seed)
    cols = {
        "v": rng.uniform(0, 50, n).round(1),
        "n": rng.integers(0, 5, n),
    }
    ts = 1_000 + np.cumsum(rng.integers(1, t_step, n)).astype(np.int64)
    return cols, ts


class TestScanVsHost:
    def test_three_node_chain(self):
        app = (DEFS + "@info(name='q') from every a=S[v > 10.0] -> "
               "b=S[v > 20.0] -> c=S[v > 30.0] "
               "select a.v as av insert into Out;")
        cols, ts = mk()
        assert scan_detections(app, cols, ts) == host_detections(
            app, cols, ts)

    def test_within_pruning(self):
        app = (DEFS + "@info(name='q') from every a=S[v > 10.0] -> "
               "b=S[v > 20.0] -> c=S[v > 30.0] within 1 sec "
               "select a.v as av insert into Out;")
        cols, ts = mk(80, seed=1, t_step=700)  # many chains expire
        host = host_detections(app, cols, ts)
        assert scan_detections(app, cols, ts) == host
        # the window must actually prune something for this to pin within
        app_nw = app.replace(" within 1 sec", "")
        assert host_detections(app_nw, cols, ts) != host

    def test_two_node_chain(self):
        app = (DEFS + "@info(name='q') from every a=S[v > 25.0] -> "
               "b=S[v < 5.0] select a.v as av insert into Out;")
        cols, ts = mk(50, seed=2)
        assert scan_detections(app, cols, ts) == host_detections(
            app, cols, ts)

    def test_chunked_state_carry(self):
        # chunk boundaries must be invisible (state carries across)
        app = (DEFS + "@info(name='q') from every a=S[v > 10.0] -> "
               "b=S[v > 20.0] -> c=S[v > 30.0] within 5 sec "
               "select a.v as av insert into Out;")
        cols, ts = mk(90, seed=3)
        whole = scan_detections(app, cols, ts, chunks=1)
        assert scan_detections(app, cols, ts, chunks=7) == whole
        assert whole == host_detections(app, cols, ts)

    def test_compound_filters(self):
        app = (DEFS + "@info(name='q') from every a=S[v > 10.0 and n != 2] "
               "-> b=S[v > 20.0 or n == 4] -> c=S[v > 30.0] "
               "select a.v as av insert into Out;")
        cols, ts = mk(70, seed=4)
        assert scan_detections(app, cols, ts) == host_detections(
            app, cols, ts)

    @pytest.mark.parametrize("seed", range(4))
    def test_fuzz(self, seed):
        rng = np.random.default_rng(500 + seed)
        s = int(rng.integers(2, 6))
        thr = sorted(rng.uniform(5, 45, s).round(1))
        chain = " -> ".join(
            f"e{i}=S[v > {thr[i]}]" for i in range(s))
        within = (f" within {int(rng.integers(1, 4))} sec"
                  if rng.integers(2) else "")
        app = (DEFS + f"@info(name='q') from every {chain}{within} "
               "select e0.v as x insert into Out;")
        cols, ts = mk(int(rng.integers(30, 100)), seed=900 + seed,
                      t_step=int(rng.integers(100, 900)))
        assert scan_detections(app, cols, ts, chunks=int(
            rng.integers(1, 4))) == host_detections(app, cols, ts)


class TestScanEligibility:
    def test_capture_reference_rejected(self):
        app = (DEFS + "@info(name='q') from every a=S[v > 10.0] -> "
               "b=S[v > a.v] select a.v as av insert into Out;")
        with pytest.raises(SiddhiAppCreationError):
            compile_scan_pattern(app, "q")

    def test_count_node_rejected(self):
        app = (DEFS + "@info(name='q') from every a=S[v > 10.0] -> "
               "b=S[v > 20.0]<2:3> select a.v as av insert into Out;")
        with pytest.raises(SiddhiAppCreationError):
            compile_scan_pattern(app, "q")

    def test_non_every_head_rejected(self):
        app = (DEFS + "@info(name='q') from a=S[v > 10.0] -> "
               "b=S[v > 20.0] select a.v as av insert into Out;")
        with pytest.raises(SiddhiAppCreationError):
            compile_scan_pattern(app, "q")

    def test_logical_rejected(self):
        app = ("define stream A (v double); define stream B (v double); "
               "@info(name='q') from every (a=A and b=B) "
               "select a.v as av insert into Out;")
        with pytest.raises(SiddhiAppCreationError):
            compile_scan_pattern(app, "q")


class TestScanRebase:
    def test_long_stream_time_rebases_exactly(self):
        """Batches spanning days of stream time: per-batch rebasing
        keeps within math millisecond-exact where a fixed float32 base
        would round (2^24 ms ~ 4.7 h)."""
        app = (DEFS + "@info(name='q') from every a=S[v > 10.0] -> "
               "b=S[v > 30.0] within 1 sec "
               "select a.v as av insert into Out;")
        eng_det = []
        eng = compile_scan_pattern(app, "q")
        st = eng.init_state()
        day = 86_400_000
        for batch_i in range(4):  # 4 batches, one per day
            t0 = 1_600_000_000_000 + batch_i * day
            ts = np.array([t0 + 1, t0 + 500, t0 + 2_000, t0 + 2_300],
                          dtype=np.int64)
            cols = {"v": np.array([20.0, 40.0, 20.0, 40.0]),
                    "n": np.zeros(4, np.int32)}
            st, idx, starts = eng.process(st, cols, ts)
            eng_det.extend(int(ts[i]) for i in idx)
            # within 1 sec: (t0+1 -> t0+500) matches; the t0+2000 arm
            # completes at t0+2300 — both inside the window
            assert list(idx) == [1, 3], (batch_i, idx)
            # starts exact to the millisecond despite days of offset
            assert list(starts) == [t0 + 1, t0 + 2_000], (batch_i, starts)
        assert len(eng_det) == 8
