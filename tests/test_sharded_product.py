"""Sharded execution of the PRODUCT dense pattern path.

The round-3 verdict's missing item 2: ShardedPatternEngine worked but no
SiddhiManager-created app could shard.  @app:execution('tpu',
devices='N') now routes a partitioned pattern app's dense runtime
through the sharded engine over an N-device mesh (8 virtual CPU devices
under tests, exactly as the driver's dryrun).  BASELINE config 5's
shape: key-partitioned pattern, sharded partition axis, global emit.
"""

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.dense_pattern import DensePatternRuntime

APP = (
    "define stream Txn (card string, amount double); "
    "partition with (card of Txn) begin "
    "@info(name='q') "
    "from every a=Txn[amount > 100.0] -> b=Txn[amount > a.amount] "
    "within 10 min "
    "select a.amount as base, b.amount as bv insert into Alerts; "
    "end;"
)

HDR_SHARDED = "@app:playback @app:execution('tpu', partitions='64', devices='8') "
HDR_HOST = "@app:playback "


def run(header, sends, restore_blob=None, snapshot_at=None):
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime(header + APP)
        got = []
        rt.add_callback("Alerts", lambda evs: got.extend(e.data for e in evs))
        rt.start()
        if restore_blob is not None:
            rt.restore(restore_blob)
        h = rt.get_input_handler("Txn")
        blob = None
        for i, (row, ts) in enumerate(sends):
            h.send(row, timestamp=ts)
            if snapshot_at is not None and i == snapshot_at:
                blob = rt.snapshot()
        pr = rt.partitions.get("partition_0")
        runtime = (next(iter(pr.dense_query_runtimes.values()))
                   .pattern_processor if pr is not None and pr.is_dense
                   else None)
        state = runtime.state if runtime is not None else None
        rt.shutdown()
        return got, runtime, state, blob
    finally:
        m.shutdown()


def sends_over_keys(n_keys=20, seed=3):
    rng = np.random.default_rng(seed)
    sends = []
    t = 1000
    for r in range(6):
        for k in range(n_keys):
            t += int(rng.integers(1, 50))
            sends.append(([f"c{k}", float(rng.integers(50, 400))], t))
    return sends


class TestShardedProduct:
    def test_sharded_app_matches_host(self):
        sends = sends_over_keys()
        host, _, _, _ = run(HDR_HOST, sends)
        dense, runtime, state, _ = run(HDR_SHARDED, sends)
        assert isinstance(runtime, DensePatternRuntime)
        assert runtime._sharded is not None and runtime.n_shards == 8
        assert runtime.step_invocations > 0
        assert dense == host

    def test_state_actually_sharded_over_8_devices(self):
        sends = sends_over_keys(n_keys=16)
        _got, runtime, state, _ = run(HDR_SHARDED, sends)
        devices = {d for arr in state.values() for d in arr.devices()}
        assert len(devices) == 8, f"state spans {len(devices)} devices"
        # keys dealt round-robin: 16 keys over 8 shards = 2 rows/shard
        rows = np.fromiter(runtime._key_rows.values(), dtype=np.int64)
        shard_of = rows // runtime.parts_per_shard
        assert np.bincount(shard_of, minlength=8).tolist() == [2] * 8

    def test_snapshot_restore_roundtrip_sharded(self):
        sends = sends_over_keys(n_keys=12, seed=7)
        mid = len(sends) // 2
        full, _, _, _ = run(HDR_SHARDED, sends)
        # snapshot mid-stream, then restore into a FRESH app and replay
        # only the tail
        got_head, _, _, blob = run(HDR_SHARDED, sends[:mid],
                                   snapshot_at=mid - 1)
        assert blob is not None
        got_tail, runtime2, state2, _ = run(HDR_SHARDED, sends[mid:],
                                            restore_blob=blob)
        assert runtime2._sharded is not None
        assert got_head + got_tail == full
        devices = {d for arr in state2.values() for d in arr.devices()}
        assert len(devices) == 8  # restore keeps the mesh sharding

    def test_dryrun_layout_matches(self):
        # partitions not divisible by devices fails loudly at parse time
        from siddhi_tpu.core.exceptions import SiddhiAppCreationError

        m = SiddhiManager()
        try:
            with pytest.raises(SiddhiAppCreationError):
                m.create_siddhi_app_runtime(
                    "@app:execution('tpu', partitions='63', devices='8') "
                    + APP)
        finally:
            m.shutdown()


ABSENT_APP = (
    "define stream Txn (card string, amount double); "
    "define stream Confirm (card string, amount double); "
    "define stream Tick (x int); "
    "from Tick select x insert into _T; "
    "partition with (card of Txn, card of Confirm) begin "
    "@info(name='q') "
    "from e1=Txn[amount > 1000.0] -> "
    "not Confirm[amount == e1.amount] for 2 sec "
    "select e1.amount as amt insert into Alerts; "
    "end;"
)


class TestShardedProductExtended:
    def test_within_expiry_fuzz_matches_host(self):
        # `within` close to the per-key event gap so arms expire often; 40 keys over 8
        # shards, randomized amounts — sharded output must equal host
        app = APP.replace("within 10 min", "within 2 sec")
        rng = np.random.default_rng(17)
        sends = []
        t = 1000
        for _r in range(8):
            for k in range(40):
                t += int(rng.integers(5, 60))
                sends.append(([f"k{k}", float(rng.integers(50, 400))], t))

        def drive(header):
            m = SiddhiManager()
            try:
                rt = m.create_siddhi_app_runtime(header + app)
                got = []
                rt.add_callback(
                    "Alerts", lambda evs: got.extend(e.data for e in evs))
                rt.start()
                h = rt.get_input_handler("Txn")
                for row, ts in sends:
                    h.send(row, timestamp=ts)
                rt.shutdown()
                return sorted(map(tuple, got))
            finally:
                m.shutdown()

        host = drive(HDR_HOST)
        shard = drive(HDR_SHARDED)
        assert shard == host
        assert len(host) > 0  # the scenario actually produces matches

    def test_sharded_absent_deadlines_fire(self):
        # the jitted timer step must run shard-local over the sharded
        # state (XLA propagates the row sharding; no collectives)
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(
                "@app:playback "
                "@app:execution('tpu', partitions='64', devices='8') "
                + ABSENT_APP)
            got = []
            rt.add_callback(
                "Alerts",
                lambda evs: got.extend((list(e.data), e.timestamp)
                                       for e in evs))
            rt.start()
            t = rt.get_input_handler("Txn")
            c = rt.get_input_handler("Confirm")
            # 12 keys arm deadlines across shards; 4 get confirmed
            for k in range(12):
                t.send([f"c{k}", 2000.0 + k], timestamp=1000 + k)
            for k in range(4):
                c.send([f"c{k}", 2000.0 + k], timestamp=1500 + k)
            rt.get_input_handler("Tick").send([1], timestamp=5000)
            pr = rt.partitions.get("partition_0")
            runtime = next(iter(pr.dense_query_runtimes.values())
                           ).pattern_processor
            assert isinstance(runtime, DensePatternRuntime)
            assert runtime._sharded is not None
            assert runtime.engine.has_deadlines
            rt.shutdown()
            amts = sorted(row[0] for row, _ts in got)
            assert amts == [2000.0 + k for k in range(4, 12)]
            # timer emissions carry the per-arm deadline timestamps
            ts_by_amt = {row[0]: ts for row, ts in got}
            for k in range(4, 12):
                assert ts_by_amt[2000.0 + k] == 3000 + k
        finally:
            m.shutdown()

    def test_purge_recycles_rows_sharded(self):
        app = (
            "define stream Txn (card string, amount double); "
            "@purge(enable='true', interval='1 sec', idle.period='2 sec') "
            "partition with (card of Txn) begin "
            "@info(name='q') "
            "from every a=Txn[amount > 100.0] -> b=Txn[amount > a.amount] "
            "select a.amount as base, b.amount as bv insert into Alerts; "
            "end;"
        )
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(
                "@app:playback "
                "@app:execution('tpu', partitions='16', devices='8') " + app)
            rt.start()
            h = rt.get_input_handler("Txn")
            # first wave: 16 keys fill capacity
            for k in range(16):
                h.send([f"a{k}", 150.0], timestamp=1000 + k)
            # idle them out, then a second wave of NEW keys must fit
            h.send(["a0", 150.0], timestamp=8000)
            for k in range(15):
                h.send([f"b{k}", 150.0], timestamp=8100 + k)
            pr = rt.partitions.get("partition_0")
            runtime = next(iter(pr.dense_query_runtimes.values())
                           ).pattern_processor
            assert len(runtime._key_rows) <= 16
            rt.shutdown()
        finally:
            m.shutdown()
