"""Pattern conformance, part 2: Complex/Count/Every/Logical/Within
matrices ported from the reference TestNG corpus
(modules/siddhi-core/src/test/java/io/siddhi/core/query/pattern/
ComplexPatternTestCase.java, CountPatternTestCase.java,
EveryPatternTestCase.java, LogicalPatternTestCase.java,
WithinPatternTestCase.java).  Each case asserts the reference's concrete
output rows (Thread.sleep gaps become playback timestamp gaps).  Where a
query is dense-eligible, `both()` also runs it under
@app:execution('tpu') and asserts the dense output matches host
bit-for-bit.
"""

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager

S12 = (
    "define stream Stream1 (symbol string, price float, volume int); "
    "define stream Stream2 (symbol string, price float, volume int); "
)
S123 = S12 + "define stream Stream3 (symbol string, price float, volume int); "


def f32(x):
    return np.float32(x).item()


def run(app, sends, out="OutputStream"):
    """Playback-mode run; sends = (stream, row, ts)."""
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime("@app:playback " + app)
        got = []
        rt.add_callback(out, lambda evs: got.extend(list(e.data) for e in evs))
        rt.start()
        for stream, row, ts in sends:
            rt.get_input_handler(stream).send(row, timestamp=ts)
        rt.shutdown()
        return got
    finally:
        m.shutdown()


def both(app, sends, expected, out="OutputStream"):
    """Host run asserts the reference rows; TPU run (dense where
    eligible, host fallback otherwise) must agree exactly."""
    host = run(app, sends, out)
    assert host == expected, f"host {host} != expected {expected}"
    tpu = run("@app:execution('tpu') " + app, sends, out)
    assert tpu == host, f"tpu {tpu} != host {host}"
    return host


def ts_seq(streams_rows, base=1000, gap=100):
    """[(stream, row), ...] -> evenly spaced playback sends."""
    return [(s, r, base + i * gap) for i, (s, r) in enumerate(streams_rows)]


class TestComplexPatterns:
    def test_every_group_or_then_next(self):
        # ComplexPatternTestCase.testQuery1
        q = ("@info(name='q') from every (e1=Stream1[price > 20] -> "
             "e2=Stream2[price > e1.price] or e3=Stream2['IBM' == symbol]) "
             "-> e4=Stream2[price > e1.price] "
             "select e1.price as price1, e2.price as price2, "
             "e3.price as price3, e4.price as price4 insert into OutputStream;")
        both(S12 + q, ts_seq([
            ("Stream1", ["WSO2", 55.6, 100]),
            ("Stream2", ["WSO2", 55.7, 100]),
            ("Stream2", ["GOOG", 55.0, 100]),
            ("Stream1", ["GOOG", 54.0, 100]),
            ("Stream2", ["IBM", 57.7, 100]),
            ("Stream2", ["IBM", 59.7, 100]),
        ]), [
            [f32(55.6), f32(55.7), None, f32(57.7)],
            [f32(54.0), f32(57.7), None, f32(59.7)],
        ])

    def test_every_group_count_then_cross_filter(self):
        # ComplexPatternTestCase.testQuery2
        q = ("@info(name='q') from every (e1=Stream1[price > 20] -> "
             "e2=Stream1[price > 20]<1:2>) -> e3=Stream1[price > e1.price] "
             "select e1.price as price1, e2[0].price as price2_0, "
             "e2[1].price as price2_1, e3.price as price3 "
             "insert into OutputStream;")
        both(S12 + q, ts_seq([
            ("Stream1", ["WSO2", 55.6, 100]),
            ("Stream1", ["GOOG", 54.0, 100]),
            ("Stream1", ["WSO2", 53.6, 100]),
            ("Stream1", ["GOOG", 57.0, 100]),
        ]), [[f32(55.6), f32(54.0), f32(53.6), f32(57.0)]])

    def test_every_open_count_single_stream(self):
        # ComplexPatternTestCase.testQuery3: three interleaved matches
        q = ("@info(name='q') from every e1=Stream1[price >= 50 and "
             "volume > 100] -> e2=Stream1[price <= 40]<2:> -> "
             "e3=Stream1[volume <= 70] "
             "select e1.symbol as symbol1, e2[last].symbol as symbol2, "
             "e3.symbol as symbol3 insert into OutputStream;")
        both(S12 + q, ts_seq([
            ("Stream1", ["IBM", 75.6, 105]),
            ("Stream1", ["GOOG", 39.8, 91]),
            ("Stream1", ["FB", 35.0, 81]),
            ("Stream1", ["WSO2", 21.0, 61]),
            ("Stream1", ["ADP", 50.0, 101]),
            ("Stream1", ["GOOG", 41.2, 90]),
            ("Stream1", ["FB", 40.0, 100]),
            ("Stream1", ["WSO2", 33.6, 85]),
            ("Stream1", ["AMZN", 23.5, 55]),
            ("Stream1", ["WSO2", 51.7, 180]),
            ("Stream1", ["TXN", 34.0, 61]),
            ("Stream1", ["QQQ", 24.6, 45]),
            ("Stream1", ["CSCO", 181.6, 40]),
            ("Stream1", ["WSO2", 53.7, 200]),
        ]), [
            ["IBM", "FB", "WSO2"],
            ["ADP", "WSO2", "AMZN"],
            ["WSO2", "QQQ", "CSCO"],
        ])

    def test_every_open_count_two_streams(self):
        # ComplexPatternTestCase.testQuery4
        q = ("@info(name='q') from every e1=Stream1[price >= 50 and "
             "volume > 100] -> e2=Stream2[price <= 40]<1:> -> "
             "e3=Stream2[volume <= 70] "
             "select e3.symbol as symbol1, e2[0].symbol as symbol2, "
             "e3.volume as symbol3 insert into OutputStream;")
        both(S12 + q, ts_seq([
            ("Stream1", ["IBM", 75.6, 105]),
            ("Stream2", ["GOOG", 21.0, 81]),
            ("Stream2", ["WSO2", 176.6, 65]),
            ("Stream1", ["BIRT", 21.0, 81]),
            ("Stream1", ["AMBA", 126.6, 165]),
            ("Stream2", ["DDD", 23.0, 181]),
            ("Stream2", ["BIRT", 21.0, 86]),
            ("Stream2", ["BIRT", 21.0, 82]),
            ("Stream2", ["WSO2", 176.6, 60]),
            ("Stream1", ["AMBA", 126.6, 165]),
            ("Stream2", ["DOX", 16.2, 25]),
        ]), [["WSO2", "GOOG", 65], ["WSO2", "DDD", 60]])

    def test_cross_ref_filter_in_second_state(self):
        # ComplexPatternTestCase.testQuery5 (non-every)
        q = ("@info(name='q') from e1=Stream1[price >= 50 and volume > 100] "
             "-> e2=Stream2[e1.symbol != 'AMBA'] -> "
             "e3=Stream2[volume <= 70] "
             "select e3.symbol as symbol1, e2[0].symbol as symbol2, "
             "e3.volume as volume3 insert into OutputStream;")
        both(S12 + q, ts_seq([
            ("Stream1", ["IBM", 75.6, 105]),
            ("Stream2", ["GOOG", 21.0, 81]),
            ("Stream2", ["WSO2", 176.6, 65]),
            ("Stream1", ["BIRT", 21.0, 81]),
            ("Stream1", ["AMBA", 126.6, 165]),
            ("Stream2", ["DDD", 23.0, 181]),
            ("Stream2", ["BIRT", 21.0, 86]),
            ("Stream2", ["BIRT", 21.0, 82]),
            ("Stream2", ["WSO2", 176.6, 60]),
            ("Stream1", ["AMBA", 126.6, 165]),
            ("Stream2", ["DOX", 16.2, 25]),
        ]), [["WSO2", "GOOG", 65]])

    def test_every_unfiltered_start_open_count(self):
        # ComplexPatternTestCase.testQuery6
        q = ("@info(name='q') from every e1=Stream1 -> "
             "e2=Stream2[e1.symbol != 'AMBA']<2:> -> "
             "e3=Stream2[volume <= 70] "
             "select e3.symbol as symbol1, e2[0].symbol as symbol2, "
             "e3.volume as volume3 insert into OutputStream;")
        both(S12 + q, ts_seq([
            ("Stream1", ["IBM", 75.6, 105]),
            ("Stream2", ["GOOG", 21.0, 51]),
            ("Stream2", ["FBX", 21.0, 81]),
            ("Stream2", ["WSO2", 176.6, 65]),
            ("Stream1", ["BIRT", 21.0, 81]),
            ("Stream1", ["AMBA", 126.6, 165]),
            ("Stream2", ["DDD", 23.0, 181]),
            ("Stream2", ["BIRT", 21.0, 86]),
            ("Stream2", ["IBN", 21.0, 70]),
            ("Stream2", ["WSO2", 176.6, 90]),
            ("Stream1", ["AMBA", 126.6, 165]),
            ("Stream2", ["DOX", 16.2, 25]),
        ]), [["WSO2", "GOOG", 65], ["IBN", "DDD", 70]])


class TestCountPatterns2:
    CQ = ("@info(name='q') from e1=Stream1[price>20] <0:5> -> "
          "e2=Stream2[price>20] "
          "select e1[0].price as price1_0, e1[1].price as price1_1, "
          "e2.price as price2 insert into OutputStream;")

    def test_zero_min_skipped_entirely(self):
        # CountPatternTestCase.testQuery7: <0:5> satisfied with no events
        both(S12 + self.CQ, ts_seq([
            ("Stream2", ["IBM", 45.7, 100]),
        ]), [[None, None, f32(45.7)]])

    def test_zero_min_cross_ref_filter(self):
        # CountPatternTestCase.testQuery8: failing capture not stored
        q = ("@info(name='q') from e1=Stream1[price>20] <0:5> -> "
             "e2=Stream2[price>e1[0].price] "
             "select e1[0].price as price1_0, e1[1].price as price1_1, "
             "e2.price as price2 insert into OutputStream;")
        both(S12 + q, ts_seq([
            ("Stream1", ["WSO2", 25.6, 100]),
            ("Stream1", ["GOOG", 7.6, 100]),
            ("Stream2", ["IBM", 45.7, 100]),
        ]), [[f32(25.6), None, f32(45.7)]])

    def test_zero_min_mid_chain(self):
        # CountPatternTestCase.testQuery9
        q = ("@info(name='q') from e1=Stream1[price >= 50 and volume > 100] "
             "-> e2=Stream1[price <= 40]<0:5> -> e3=Stream1[volume <= 70] "
             "select e1.symbol as symbol1, e2[0].symbol as symbol2, "
             "e3.symbol as symbol3 insert into OutputStream;")
        both(S12 + q, ts_seq([
            ("Stream1", ["IBM", 75.6, 105]),
            ("Stream1", ["GOOG", 21.0, 81]),
            ("Stream1", ["WSO2", 176.6, 65]),
        ]), [["IBM", "GOOG", "WSO2"]])

    def test_upper_only_count_zero_captures(self):
        # CountPatternTestCase.testQuery10: <:5> with first-ref select
        q = ("@info(name='q') from e1=Stream1[price >= 50 and volume > 100] "
             "-> e2=Stream1[price <= 40]<:5> -> e3=Stream1[volume <= 70] "
             "select e1.symbol as symbol1, e2[0].symbol as symbol2, "
             "e3.symbol as symbol3 insert into OutputStream;")
        both(S12 + q, ts_seq([
            ("Stream1", ["IBM", 75.6, 105]),
            ("Stream1", ["GOOG", 21.0, 61]),
            ("Stream1", ["WSO2", 21.0, 61]),
        ]), [["IBM", None, "GOOG"]])

    def test_upper_only_count_last_ref(self):
        # CountPatternTestCase.testQuery11: e2[last] null when e2 empty
        q = ("@info(name='q') from e1=Stream1[price >= 50 and volume > 100] "
             "-> e2=Stream1[price <= 40]<:5> -> e3=Stream1[volume <= 70] "
             "select e1.symbol as symbol1, e2[last].symbol as symbol2, "
             "e3.symbol as symbol3 insert into OutputStream;")
        both(S12 + q, ts_seq([
            ("Stream1", ["IBM", 75.6, 105]),
            ("Stream1", ["GOOG", 21.0, 61]),
            ("Stream1", ["WSO2", 21.0, 61]),
        ]), [["IBM", None, "GOOG"]])

    def test_upper_only_count_last_ref_filled(self):
        # CountPatternTestCase.testQuery12
        q = ("@info(name='q') from e1=Stream1[price >= 50 and volume > 100] "
             "-> e2=Stream1[price <= 40]<:5> -> e3=Stream1[volume <= 70] "
             "select e1.symbol as symbol1, e2[last].symbol as symbol2, "
             "e3.symbol as symbol3 insert into OutputStream;")
        both(S12 + q, ts_seq([
            ("Stream1", ["IBM", 75.6, 105]),
            ("Stream1", ["GOOG", 21.0, 91]),
            ("Stream1", ["FB", 21.0, 81]),
            ("Stream1", ["WSO2", 21.0, 61]),
        ]), [["IBM", "FB", "WSO2"]])

    def test_every_sliding_count_window(self):
        # CountPatternTestCase.testQuery13: every + <4:6> same-symbol runs
        q = ("@info(name='q') from every e1=Stream1 -> "
             "e2=Stream1[e1.symbol==e2.symbol]<4:6> "
             "select e1.volume as volume1, e2[0].volume as volume2, "
             "e2[1].volume as volume3, e2[2].volume as volume4, "
             "e2[3].volume as volume5, e2[4].volume as volume6, "
             "e2[5].volume as volume7 insert into OutputStream;")
        both(S12 + q, ts_seq([
            ("Stream1", ["IBM", 75.6, 100]),
            ("Stream1", ["IBM", 75.6, 200]),
            ("Stream1", ["IBM", 75.6, 300]),
            ("Stream1", ["GOOG", 21.0, 91]),
            ("Stream1", ["IBM", 75.6, 400]),
            ("Stream1", ["IBM", 75.6, 500]),
            ("Stream1", ["GOOG", 21.0, 91]),
            ("Stream1", ["IBM", 75.6, 600]),
            ("Stream1", ["IBM", 75.6, 700]),
            ("Stream1", ["IBM", 75.6, 800]),
            ("Stream1", ["GOOG", 21.0, 91]),
            ("Stream1", ["IBM", 75.6, 900]),
        ]), [
            [100, 200, 300, 400, 500, None, None],
            [200, 300, 400, 500, 600, None, None],
            [300, 400, 500, 600, 700, None, None],
            [400, 500, 600, 700, 800, None, None],
            [500, 600, 700, 800, 900, None, None],
        ])

    def test_instanceof_having_on_count_refs(self):
        # CountPatternTestCase.testQuery14
        q = ("@info(name='q') from e1=Stream1[price>20] <0:5> -> "
             "e2=Stream2[price>e1[0].price] "
             "select e1[0].price as price1_0, e1[1].price as price1_1, "
             "e1[2].price as price1_2, e2.price as price2 "
             "having instanceOfFloat(e1[1].price) and "
             "not instanceOfFloat(e1[2].price) and "
             "instanceOfFloat(price1_1) and not instanceOfFloat(price1_2) "
             "insert into OutputStream;")
        both(S12 + q, ts_seq([
            ("Stream1", ["WSO2", 25.6, 100]),
            ("Stream1", ["WSO2", 23.6, 100]),
            ("Stream1", ["GOOG", 7.6, 100]),
            ("Stream2", ["IBM", 45.7, 100]),
        ]), [[f32(25.6), f32(23.6), None, f32(45.7)]])

    def test_exact_count_then_not_and(self):
        # CountPatternTestCase.testQuery15: <2> then (not S1 and e3=S2)
        q = ("@info(name='q') from every e1=Stream1[price>20] -> "
             "e2=Stream1[price>20]<2> -> "
             "not Stream1[price>20] and e3=Stream2 "
             "select e1.price as price1_0, e2[0].price as price2_0, "
             "e2[1].price as price2_1, e2[2].price as price2_2, "
             "e3.price as price3_0 insert into OutputStream;")
        both(S12 + q, ts_seq([
            ("Stream1", ["WSO2", 25.6, 100]),
            ("Stream1", ["WSO2", 23.6, 100]),
            ("Stream1", ["WSO2", 23.6, 100]),
            ("Stream1", ["GOOG", 27.6, 100]),
            ("Stream1", ["GOOG", 28.6, 100]),
            ("Stream2", ["IBM", 45.7, 100]),
        ]), [[f32(23.6), f32(27.6), f32(28.6), None, f32(45.7)]])


class TestEveryPatterns2:
    def test_reused_event_ref(self):
        # EveryPatternTestCase.testQuery9: the same ref name on two
        # states — the select resolves to the FIRST captured event
        q = ("@info(name='q') from every e1=Stream1[symbol == 'MSFT'] -> "
             "e1=Stream1[symbol == 'WSO2'] "
             "select e1.price as price1 insert into OutputStream;")
        both(S12 + q, ts_seq([
            ("Stream1", ["MSFT", 55.6, 100]),
            ("Stream1", ["MSFT", 77.6, 100]),
            ("Stream1", ["WSO2", 57.6, 100]),
        ]), [[f32(55.6)], [f32(77.6)]])


class TestLogicalPatterns2:
    OQ = ("@info(name='q') from e1=Stream1[price > 20] -> "
          "e2=Stream2[price > e1.price] or e3=Stream2['IBM' == symbol] "
          "select e1.symbol as symbol1, e2.symbol as symbol2 "
          "insert into OutputStream;")

    def test_or_first_branch(self):
        # LogicalPatternTestCase.testQuery1
        both(S12 + self.OQ, ts_seq([
            ("Stream1", ["WSO2", 55.6, 100]),
            ("Stream2", ["GOOG", 59.6, 100]),
        ]), [["WSO2", "GOOG"]])

    def test_or_second_branch_null_side(self):
        # LogicalPatternTestCase.testQuery2
        both(S12 + self.OQ, ts_seq([
            ("Stream1", ["WSO2", 55.6, 100]),
            ("Stream2", ["IBM", 10.7, 100]),
        ]), [["WSO2", None]])

    def test_or_both_sides_could_match_first_wins(self):
        # LogicalPatternTestCase.testQuery3
        q = ("@info(name='q') from e1=Stream1[price > 20] -> "
             "e2=Stream2[price > e1.price] or e3=Stream2['IBM' == symbol] "
             "select e1.symbol as symbol1, e2.price as price2, "
             "e3.price as price3 insert into OutputStream;")
        both(S12 + q, ts_seq([
            ("Stream1", ["WSO2", 55.6, 100]),
            ("Stream2", ["IBM", 72.7, 100]),
            ("Stream2", ["IBM", 75.7, 100]),
        ]), [["WSO2", f32(72.7), None]])

    def test_and_same_stream_two_events(self):
        # LogicalPatternTestCase.testQuery5: one event per side
        q = ("@info(name='q') from e1=Stream1[price > 20] -> "
             "e2=Stream2[price > e1.price] and e3=Stream2['IBM' == symbol] "
             "select e1.symbol as symbol1, e2.price as price2, "
             "e3.price as price3 insert into OutputStream;")
        both(S12 + q, ts_seq([
            ("Stream1", ["WSO2", 55.6, 100]),
            ("Stream2", ["IBM", 72.7, 100]),
            ("Stream2", ["IBM", 75.7, 100]),
        ]), [["WSO2", f32(72.7), f32(72.7)]])

    def test_and_cross_stream_sides(self):
        # LogicalPatternTestCase.testQuery6
        q = ("@info(name='q') from e1=Stream1[price > 20] -> "
             "e2=Stream2[price > e1.price] and e3=Stream1['IBM' == symbol] "
             "select e1.symbol as symbol1, e2.price as price2, "
             "e3.price as price3 insert into OutputStream;")
        both(S12 + q, ts_seq([
            ("Stream1", ["WSO2", 55.6, 100]),
            ("Stream2", ["IBM", 72.7, 100]),
            ("Stream1", ["IBM", 75.7, 100]),
        ]), [["WSO2", f32(72.7), f32(75.7)]])

    def test_and_start_then_next(self):
        # LogicalPatternTestCase.testQuery7
        q = ("@info(name='q') from e1=Stream1[price > 20] and "
             "e2=Stream2[price > 30] -> e3=Stream2['IBM' == symbol] "
             "select e1.symbol as symbol1, e2.price as price2, "
             "e3.price as price3 insert into OutputStream;")
        both(S12 + q, ts_seq([
            ("Stream1", ["WSO2", 55.6, 100]),
            ("Stream2", ["GOOG", 72.7, 100]),
            ("Stream2", ["IBM", 4.7, 100]),
        ]), [["WSO2", f32(72.7), f32(4.7)]])

    def test_or_start_then_next(self):
        # LogicalPatternTestCase.testQuery8
        q = ("@info(name='q') from e1=Stream1[price > 20] or "
             "e2=Stream2[price > 30] -> e3=Stream2['IBM' == symbol] "
             "select e1.symbol as symbol1, e2.price as price2, "
             "e3.price as price3 insert into OutputStream;")
        both(S12 + q, ts_seq([
            ("Stream1", ["WSO2", 55.6, 100]),
            ("Stream2", ["GOOG", 72.7, 100]),
            ("Stream2", ["IBM", 4.7, 100]),
        ]), [["WSO2", None, f32(4.7)]])

    def test_or_start_second_side(self):
        # LogicalPatternTestCase.testQuery9
        q = ("@info(name='q') from e1=Stream1[price > 20] or "
             "e2=Stream2[price > 30] -> e3=Stream2['IBM' == symbol] "
             "select e1.symbol as symbol1, e2.price as price2, "
             "e3.price as price3 insert into OutputStream;")
        both(S12 + q, ts_seq([
            ("Stream2", ["GOOG", 72.7, 100]),
            ("Stream2", ["IBM", 4.7, 100]),
        ]), [[None, f32(72.7), f32(4.7)]])

    def test_or_start_one_event_each(self):
        # LogicalPatternTestCase.testQuery10
        q = ("@info(name='q') from e1=Stream1[price > 20] or "
             "e2=Stream2[price > 30] -> e3=Stream2['IBM' == symbol] "
             "select e1.symbol as symbol1, e2.price as price2, "
             "e3.price as price3 insert into OutputStream;")
        both(S12 + q, ts_seq([
            ("Stream1", ["WSO2", 55.6, 100]),
            ("Stream2", ["IBM", 4.7, 100]),
        ]), [["WSO2", None, f32(4.7)]])

    def test_every_then_and_fanout(self):
        # LogicalPatternTestCase.testQuery11: two every-arms share the
        # later and-completion
        q = ("@info(name='q') from every e1=Stream1[price > 20] -> "
             "e2=Stream2['IBM' == symbol] and e3=Stream3['WSO2' == symbol] "
             "select e1.price as price1, e2.price as price2, "
             "e3.price as price3 insert into OutputStream;")
        both(S123 + q, ts_seq([
            ("Stream1", ["IBM", 25.5, 100]),
            ("Stream1", ["IBM", 59.65, 100]),
            ("Stream2", ["IBM", 45.5, 100]),
            ("Stream3", ["WSO2", 46.56, 100]),
        ]), [
            [f32(25.5), f32(45.5), f32(46.56)],
            [f32(59.65), f32(45.5), f32(46.56)],
        ])

    def test_every_then_or_fanout(self):
        # LogicalPatternTestCase.testQuery12
        q = ("@info(name='q') from every e1=Stream1[price > 20] -> "
             "e2=Stream2['IBM' == symbol] or e3=Stream3['WSO2' == symbol] "
             "select e1.price as price1, e2.price as price2, "
             "e3.price as price3 insert into OutputStream;")
        both(S123 + q, ts_seq([
            ("Stream1", ["IBM", 25.5, 100]),
            ("Stream1", ["IBM", 59.65, 100]),
            ("Stream2", ["IBM", 45.5, 100]),
        ]), [
            [f32(25.5), f32(45.5), None],
            [f32(59.65), f32(45.5), None],
        ])

    def test_whole_query_and(self):
        # LogicalPatternTestCase.testQuery13 (non-every: one match)
        q = ("@info(name='q') from e1=Stream1[price > 20] and "
             "e2=Stream2[price > 30] "
             "select e1.symbol as symbol1, e2.price as price2 "
             "insert into OutputStream;")
        both(S12 + q, ts_seq([
            ("Stream1", ["WSO2", 25.0, 100]),
            ("Stream2", ["IBM", 35.0, 100]),
            ("Stream1", ["GOOGLE", 45.0, 100]),
            ("Stream2", ["ORACLE", 55.0, 100]),
        ]), [["WSO2", f32(35.0)]])

    def test_whole_query_or(self):
        # LogicalPatternTestCase.testQuery14
        q = ("@info(name='q') from e1=Stream1[price > 20] or "
             "e2=Stream2[price > 30] "
             "select e1.symbol as symbol1, e2.price as price2 "
             "insert into OutputStream;")
        both(S12 + q, ts_seq([
            ("Stream1", ["WSO2", 25.0, 100]),
            ("Stream2", ["IBM", 35.0, 100]),
            ("Stream2", ["ORACLE", 45.0, 100]),
        ]), [["WSO2", None]])

    def test_every_and(self):
        # LogicalPatternTestCase.testQuery15
        q = ("@info(name='q') from every (e1=Stream1[price > 20] and "
             "e2=Stream2[price > 30]) "
             "select e1.symbol as symbol1, e2.price as price2 "
             "insert into OutputStream;")
        both(S12 + q, ts_seq([
            ("Stream1", ["WSO2", 25.0, 100]),
            ("Stream2", ["IBM", 35.0, 100]),
            ("Stream1", ["GOOGLE", 45.0, 100]),
            ("Stream2", ["ORACLE", 55.0, 100]),
        ]), [["WSO2", f32(35.0)], ["GOOGLE", f32(55.0)]])

    def test_every_or(self):
        # LogicalPatternTestCase.testQuery16: each event completes alone
        q = ("@info(name='q') from every (e1=Stream1[price > 20] or "
             "e2=Stream2[price > 30]) "
             "select e1.symbol as symbol1, e2.price as price2 "
             "insert into OutputStream;")
        both(S12 + q, ts_seq([
            ("Stream1", ["WSO2", 25.0, 100]),
            ("Stream2", ["IBM", 35.0, 100]),
            ("Stream2", ["ORACLE", 45.0, 100]),
        ]), [["WSO2", None], [None, f32(35.0)], [None, f32(45.0)]])

    def test_or_within_expired(self):
        # LogicalPatternTestCase.testQuery17: 1.1s gap kills the chain
        q = ("@info(name='q') from e1=Stream1[price > 20] -> "
             "e2=Stream2[price > e1.price] or e3=Stream2['IBM' == symbol] "
             "within 1 sec "
             "select e1.symbol as symbol1, e2.symbol as symbol2 "
             "insert into OutputStream;")
        both(S12 + q, [
            ("Stream1", ["WSO2", 55.6, 100], 1000),
            ("Stream2", ["GOOG", 59.6, 100], 2100),
        ], [])

    def test_and_within_expired_half_match(self):
        # LogicalPatternTestCase.testQuery18: one side matched, window
        # passes before the other side completes
        q = ("@info(name='q') from e1=Stream1[price > 20] -> "
             "e2=Stream2[price > e1.price] and e3=Stream2['IBM' == symbol] "
             "within 1 sec "
             "select e1.symbol as symbol1, e2.price as price2, "
             "e3.price as price3 insert into OutputStream;")
        both(S12 + q, [
            ("Stream1", ["WSO2", 55.6, 100], 1000),
            ("Stream2", ["GOOG", 72.7, 100], 1100),
            ("Stream2", ["IBM", 4.7, 100], 2200),
        ], [])

    def test_every_and_group_then_next(self):
        # LogicalPatternTestCase.testQuery19
        q = ("@info(name='q') from every (e1=Stream1[price>10] and "
             "e2=Stream2[price>20]) -> e3=Stream3[price>30] "
             "select e1.symbol as symbol1, e2.symbol as symbol2, "
             "e3.symbol as symbol3 insert into OutputStream;")
        both(S123 + q, ts_seq([
            ("Stream1", ["ORACLE", 15.0, 100]),
            ("Stream2", ["MICROSOFT", 45.0, 100]),
            ("Stream1", ["IBM", 55.0, 100]),
            ("Stream2", ["WSO2", 65.0, 100]),
            ("Stream3", ["GOOGLE", 75.0, 100]),
        ]), [
            ["ORACLE", "MICROSOFT", "GOOGLE"],
            ["IBM", "WSO2", "GOOGLE"],
        ])


class TestWithinPatterns:
    def test_within_survivor_matches(self):
        # WithinPatternTestCase.testQuery1: first arm expires, the
        # re-armed one (GOOG) survives the 1s window
        q = ("@info(name='q') from every e1=Stream1[price>20] -> "
             "e2=Stream2[price>e1.price] within 1 sec "
             "select e1.symbol as symbol1, e2.symbol as symbol2 "
             "insert into OutputStream;")
        both(S12 + q, [
            ("Stream1", ["WSO2", 55.6, 100], 1000),
            ("Stream1", ["GOOG", 54.0, 100], 2500),
            ("Stream2", ["IBM", 55.7, 100], 3000),
        ], [["GOOG", "IBM"]])

    def test_within_parenthesized_whole(self):
        # WithinPatternTestCase.testQuery2
        q = ("@info(name='q') from (every e1=Stream1[price>20] -> "
             "e2=Stream2[price>e1.price]) within 1 sec "
             "select e1.symbol as symbol1, e2.symbol as symbol2 "
             "insert into OutputStream;")
        both(S12 + q, [
            ("Stream1", ["WSO2", 55.6, 100], 1000),
            ("Stream1", ["GOOG", 54.0, 100], 2500),
            ("Stream2", ["IBM", 55.7, 100], 3000),
        ], [["GOOG", "IBM"]])

    def test_within_every_group_pairs(self):
        # WithinPatternTestCase.testQuery3: only the second (unexpired)
        # pair completes inside 2s
        q = ("@info(name='q') from (every (e1=Stream1[price>20] -> "
             "e3=Stream1[price>20]) -> e2=Stream2[price>e1.price]) "
             "within 2 sec "
             "select e1.price as price1, e3.price as price3, "
             "e2.price as price2 insert into OutputStream;")
        both(S12 + q, [
            ("Stream1", ["WSO2", 55.6, 100], 1000),
            ("Stream1", ["GOOG", 54.0, 100], 1600),
            ("Stream1", ["WSO2", 53.6, 100], 2200),
            ("Stream1", ["GOOG", 53.0, 100], 3100),
            ("Stream2", ["IBM", 57.7, 100], 3700),
        ], [[f32(53.6), f32(53.0), f32(57.7)]])

    def test_within_rearm_after_expiry(self):
        # WithinPatternTestCase.testQuery4: 6s gap expires the first
        # arm; the next pair inside 5s matches once
        q = ("@info(name='q') from every (e1=Stream1 -> "
             "e2=Stream1[symbol == e1.symbol]) within 5 sec "
             "select e1.symbol as symbol1, e1.volume as volume1, "
             "e2.symbol as symbol2, e2.volume as volume2 "
             "insert into OutputStream;")
        both(S12 + q, [
            ("Stream1", ["WSO2", 55.6, 100], 1000),
            ("Stream1", ["WSO2", 55.7, 150], 7000),
            ("Stream1", ["WSO2", 58.7, 200], 7500),
            ("Stream1", ["WSO2", 58.7, 250], 7510),
        ], [["WSO2", 150, "WSO2", 200]])

    def test_within_three_chain_expiry(self):
        # WithinPatternTestCase.testQuery5
        q = ("@info(name='q') from every (e1=Stream1 -> "
             "e2=Stream1[symbol == e1.symbol] -> "
             "e3=Stream1[symbol == e2.symbol]) within 5 sec "
             "select e1.symbol as symbol1, e1.volume as volume1, "
             "e2.symbol as symbol2, e2.volume as volume2, "
             "e3.symbol as symbol3, e3.volume as volume3 "
             "insert into OutputStream;")
        both(S12 + q, [
            ("Stream1", ["WSO2", 55.6, 100], 1000),
            ("Stream1", ["WSO2", 56.6, 150], 1100),
            ("Stream1", ["WSO2", 57.7, 200], 7100),
            ("Stream1", ["WSO2", 58.7, 250], 7600),
            ("Stream1", ["WSO2", 57.7, 300], 7610),
            ("Stream1", ["WSO2", 59.7, 350], 7620),
        ], [["WSO2", 200, "WSO2", 250, "WSO2", 300]])

    def test_within_three_chain_two_matches(self):
        # WithinPatternTestCase.testQuery6: everything inside the window
        q = ("@info(name='q') from every (e1=Stream1 -> "
             "e2=Stream1[symbol == e1.symbol] -> "
             "e3=Stream1[symbol == e2.symbol]) within 5 sec "
             "select e1.symbol as symbol1, e1.volume as volume1, "
             "e2.symbol as symbol2, e2.volume as volume2, "
             "e3.symbol as symbol3, e3.volume as volume3 "
             "insert into OutputStream;")
        both(S12 + q, [
            ("Stream1", ["WSO2", 55.6, 100], 1000),
            ("Stream1", ["WSO2", 55.7, 150], 1010),
            ("Stream1", ["WSO2", 58.7, 200], 1020),
            ("Stream1", ["WSO2", 58.7, 210], 1030),
            ("Stream1", ["WSO2", 58.7, 250], 1540),
            ("Stream1", ["WSO2", 58.7, 260], 1550),
            ("Stream1", ["WSO2", 58.7, 270], 1560),
        ], [
            ["WSO2", 100, "WSO2", 150, "WSO2", 200],
            ["WSO2", 210, "WSO2", 250, "WSO2", 260],
        ])

    def test_within_first_pair_expired(self):
        # WithinPatternTestCase.testQuery7
        q = ("@info(name='q') from every (e1=Stream1 -> "
             "e2=Stream1[symbol == e1.symbol] -> "
             "e3=Stream1[symbol == e2.symbol]) within 5 sec "
             "select e1.symbol as symbol1, e1.volume as volume1, "
             "e2.symbol as symbol2, e2.volume as volume2, "
             "e3.symbol as symbol3, e3.volume as volume3 "
             "insert into OutputStream;")
        both(S12 + q, [
            ("Stream1", ["WSO2", 55.6, 100], 1000),
            ("Stream1", ["WSO2", 56.6, 150], 7000),
            ("Stream1", ["WSO2", 57.7, 200], 7010),
            ("Stream1", ["WSO2", 58.7, 250], 7520),
            ("Stream1", ["WSO2", 57.7, 300], 7530),
            ("Stream1", ["WSO2", 59.7, 350], 7540),
        ], [["WSO2", 150, "WSO2", 200, "WSO2", 250]])
