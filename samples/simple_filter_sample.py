"""Quick-start: filter query (reference: quickstart-samples
SimpleFilterSample.java).

Run: python samples/simple_filter_sample.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from siddhi_tpu import SiddhiManager


def main():
    manager = SiddhiManager()
    runtime = manager.create_siddhi_app_runtime(
        "define stream StockStream (symbol string, price float, volume long); "
        "@info(name='query1') "
        "from StockStream[volume < 150] select symbol, price insert into OutputStream;"
    )
    runtime.add_callback(
        "OutputStream", lambda events: [print(e) for e in events]
    )
    runtime.start()
    h = runtime.get_input_handler("StockStream")
    h.send(["IBM", 700.0, 100])
    h.send(["WSO2", 60.5, 200])
    h.send(["GOOG", 50.0, 30])
    runtime.shutdown()
    manager.shutdown()


if __name__ == "__main__":
    main()
