"""Hot-key throughput: associative-scan NFA vs sequential stepping.

The dense engine parallelizes over partitions, so ONE key's events are
sequential (collision rounds — one jitted step per event).  The scan
engine (ops/nfa_scan.py) advances the same chain in O(log n) depth.
This measures both on a single-key stream (the skewed-key tail of the
north-star workload).

Run: python samples/performance/hotkey_scan.py [seconds] [batch_pow2]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import numpy as np

APP = ("define stream S (v double, n int); "
       "@info(name='q') from every a=S[v > 10.0] -> b=S[v > 20.0] -> "
       "c=S[v > 30.0] -> d=S[v > 40.0] within 10 sec "
       "select a.v as av insert into Out;")


def bench_scan(seconds, batch):
    from siddhi_tpu.ops.nfa_scan import compile_scan_pattern

    eng = compile_scan_pattern(APP, "q")
    st = eng.init_state()
    rng = np.random.default_rng(0)
    cols = {"v": rng.uniform(0, 50, batch), "n": np.zeros(batch, np.int32)}
    ts = 1000 + np.arange(batch, dtype=np.int64) * 3
    st, idx, _ = eng.process(st, cols, ts)  # compile + warm
    sent = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < seconds:
        st, idx, _ = eng.process(st, cols, ts)
        sent += batch
    return sent / (time.perf_counter() - t0), len(idx)


def bench_sequential(seconds, batch):
    """The dense engine on the same single-key stream: every event is a
    collision round, so the jitted step runs once per event."""
    from siddhi_tpu.ops.dense_nfa import compile_pattern

    eng = compile_pattern(APP, "q", n_partitions=1)
    state = eng.init_state()
    step = eng.make_step("S", jit=True)
    jnp = eng.jnp
    rng = np.random.default_rng(0)
    part = jnp.zeros(1, dtype=jnp.int32)
    valid = jnp.ones(1, dtype=bool)
    vs = rng.uniform(0, 50, batch).astype(np.float32)
    # warm
    state, emit, _, _ = step(state, part, {
        "v": jnp.asarray(vs[:1]), "n": jnp.zeros(1, jnp.int32)},
        jnp.asarray(np.array([1000], np.int32)), valid)
    sent = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < seconds:
        for i in range(min(batch, 4096)):  # bounded inner loop
            state, emit, _, _ = step(state, part, {
                "v": jnp.asarray(vs[i:i + 1]),
                "n": jnp.zeros(1, jnp.int32)},
                jnp.asarray(np.array([1000 + 3 * i], np.int32)), valid)
            sent += 1
    emit.block_until_ready()
    return sent / (time.perf_counter() - t0)


def main(seconds=3.0, pow2=17):
    batch = 1 << pow2
    scan_rate, n_matches = bench_scan(seconds, batch)
    seq_rate = bench_sequential(seconds, batch)
    import json

    print(json.dumps({
        "workload": "hotkey_single_partition",
        "scan_events_per_sec": round(scan_rate, 1),
        "sequential_events_per_sec": round(seq_rate, 1),
        "speedup": round(scan_rate / seq_rate, 1),
        "batch": batch,
        "matches_per_batch": int(n_matches),
    }))


if __name__ == "__main__":
    main(float(sys.argv[1]) if len(sys.argv) > 1 else 3.0,
         int(sys.argv[2]) if len(sys.argv) > 2 else 17)
