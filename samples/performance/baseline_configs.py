"""The five BASELINE.json benchmark configs on the dense engine.

Prints one JSON line per config: events/sec (median window) on the
available accelerator.  Configs (BASELINE.md):
  1. 3-state sequence `e1, e2, e3 within 1 sec` (single stream)
  2. credit-card fraud `every a -> b[amount>a.amount]<3:5> within 10 min`,
     100K card partitions
  3. brute-force login `fail<3:> -> success`, 1M user partitions
  4. multi-stream `stockTick AND newsEvent within 5 sec` (logical NFA)
  5. IoT anomaly, 32-state escalation pattern, 1M device partitions
     (the 10M-partition variant needs the sharded multi-chip path)

Run: python samples/performance/baseline_configs.py [seconds-per-config]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

import numpy as np


def _measure(eng, stream_key, n_partitions, batch, seconds, cols_of):
    import jax

    state = eng.init_state()
    step = eng.make_step(stream_key, jit=True)
    jnp = eng.jnp
    rng = np.random.default_rng(3)
    part = jnp.asarray(
        ((np.arange(batch, dtype=np.int64) * 524287) % n_partitions).astype(np.int32))
    cols = {k: jnp.asarray(v) for k, v in cols_of(rng, batch).items()}
    ts = jnp.asarray(np.full(batch, 1_000, dtype=np.int32))
    valid = jnp.ones(batch, dtype=bool)
    state, emit, _ = step(state, part, cols, ts, valid)  # compile
    jax.block_until_ready(emit)
    rates = []
    for _ in range(3):
        t0 = time.perf_counter()
        n = 0
        while time.perf_counter() - t0 < seconds / 3:
            state, emit, _ = step(state, part, cols, ts, valid)
            n += batch
        jax.block_until_ready(emit)
        rates.append(n / (time.perf_counter() - t0))
    return float(np.median(rates))


def main(seconds: float = 3.0):
    from siddhi_tpu.ops.dense_nfa import compile_pattern

    B = 1 << 15

    def report(name, rate, extra=""):
        print(json.dumps({"config": name, "events_per_sec": round(rate, 1),
                          "note": extra}))

    # 1. 3-state sequence
    eng = compile_pattern(
        "define stream T (key long, p double); @info(name='q') "
        "from every e1=T[p > 10.0], e2=T[p > e1.p], e3=T[p > e2.p] within 1 sec "
        "select e1.p as p1, e3.p as p3 insert into O;",
        "q", n_partitions=100_000)
    rate = _measure(eng, "T", 100_000, B, seconds,
                    lambda r, n: {"p": r.uniform(5, 30, n).astype(np.float32),
                                  "key": np.zeros(n, dtype=np.float32)})
    report("1_sequence_3state", rate)

    # 2. credit-card fraud, 100K partitions
    eng = compile_pattern(
        "define stream Txn (card long, amount double); @info(name='q') "
        "from every a=Txn[amount > 100.0] -> b=Txn[amount > a.amount]<3:5> "
        "within 10 min select a.amount as base, b[0].amount as b0 insert into O;",
        "q", n_partitions=100_000)
    rate = _measure(eng, "Txn", 100_000, B, seconds,
                    lambda r, n: {"amount": r.uniform(50, 500, n).astype(np.float32),
                                  "card": np.zeros(n, dtype=np.float32)})
    report("2_fraud_count_100k", rate)

    # 3. brute-force login, 1M partitions (Kleene count then success)
    eng = compile_pattern(
        "define stream Login (user long, ok bool); @info(name='q') "
        "from every f=Login[ok == false]<3:100> -> s=Login[ok == true] "
        "within 5 min select f[0].ok as f0 insert into O;",
        "q", n_partitions=1_000_000)
    rate = _measure(eng, "Login", 1_000_000, B, seconds,
                    lambda r, n: {"ok": (r.uniform(0, 1, n) > 0.7).astype(np.float32),
                                  "user": np.zeros(n, dtype=np.float32)})
    report("3_bruteforce_kleene_1m", rate)

    # 4. two-stream logical AND
    eng = compile_pattern(
        "define stream StockTick (sym long, p double); "
        "define stream NewsEvent (sym long, sentiment double); @info(name='q') "
        "from every (t=StockTick[p > 0.0] and n=NewsEvent[sentiment < 0.0]) "
        "within 5 sec select t.p as p, n.sentiment as s insert into O;",
        "q", n_partitions=100_000)
    rate = _measure(eng, "StockTick", 100_000, B, seconds,
                    lambda r, n: {"p": r.uniform(1, 10, n).astype(np.float32),
                                  "sym": np.zeros(n, dtype=np.float32)})
    report("4_two_stream_and", rate, "stockTick side; newsEvent side symmetrical")

    # 5. 32-state escalation, 1M partitions
    states = ["every e1=D[v > 0.0]"]
    for i in range(2, 33):
        states.append(f"e{i}=D[v > {float(i - 1)} and v > e1.v]")
    eng = compile_pattern(
        "define stream D (dev long, v double); @info(name='q') "
        "from " + " -> ".join(states) + " within 10 min "
        "select e1.v as v1, e32.v as v32 insert into O;",
        "q", n_partitions=1_000_000)
    rate = _measure(eng, "D", 1_000_000, B, seconds,
                    lambda r, n: {"v": r.uniform(0, 40, n).astype(np.float32),
                                  "dev": np.zeros(n, dtype=np.float32)})
    report("5_iot_32state_1m", rate,
           "10M-partition variant runs sharded via siddhi_tpu.parallel")


if __name__ == "__main__":
    main(float(sys.argv[1]) if len(sys.argv) > 1 else 3.0)
