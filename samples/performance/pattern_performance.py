"""Pattern-matching throughput on the dense NFA — the north-star path
(reference: the JVM equivalent runs StreamPreStateProcessor chains with
per-event locking; see BASELINE.md).

Run: python samples/performance/pattern_performance.py [seconds]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

import numpy as np


def main(seconds: float = 5.0):
    from siddhi_tpu.ops.dense_nfa import compile_pattern

    app = (
        "define stream Txn (key long, v double); "
        "@info(name='fraud') "
        "from every a=Txn[v > 100.0] -> b=Txn[v > a.v]<3:5> within 10 min "
        "select a.v as base, b[0].v as b0 insert into Alerts;"
    )
    N_PART, B = 1 << 17, 1 << 15
    eng = compile_pattern(app, "fraud", n_partitions=N_PART)
    state = eng.init_state()
    step = eng.make_step("Txn", jit=True)
    jnp = eng.jnp
    rng = np.random.default_rng(7)
    part = jnp.asarray(rng.integers(0, N_PART, B).astype(np.int32))
    cols = {
        "v": jnp.asarray(rng.uniform(50, 500, B).astype(np.float32)),
        "key": jnp.asarray(np.zeros(B, dtype=np.float32)),
    }
    ts = jnp.asarray(np.full(B, 1_000, dtype=np.int32))
    valid = jnp.ones(B, dtype=bool)

    # warmup/compile
    state, emit, out_vals = step(state, part, cols, ts, valid)
    import jax

    jax.block_until_ready(emit)
    sent = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < seconds:
        state, emit, out_vals = step(state, part, cols, ts, valid)
        sent += B
    jax.block_until_ready(emit)
    dt = time.perf_counter() - t0
    print(f"events processed : {sent}")
    print(f"throughput       : {sent / dt:,.0f} events/sec "
          f"({N_PART} partitions, batch {B})")


if __name__ == "__main__":
    main(float(sys.argv[1]) if len(sys.argv) > 1 else 5.0)
