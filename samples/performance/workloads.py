"""The reference performance-sample workloads, host vs device.

Ports every workload of the reference harness
(`/root/reference/modules/siddhi-samples/performance-samples/src/main/
java/io/siddhi/performance/`) onto this engine, runs each on the host
path AND — where the query is device-eligible — under
``@app:execution('tpu')``, and prints one JSON array of
``{workload, host_events_per_sec, device_events_per_sec, speedup,
lowered}`` rows (BASELINE.md's "workloads to re-measure").

| workload                  | reference file                                   |
|---------------------------|--------------------------------------------------|
| simple_filter             | SimpleFilterSingleQueryPerformance.java:51       |
| filter_multi_4q           | SimpleFilterMultipleQueryPerformance.java:57     |
| filter_async              | SimpleFilterSyncPerformance.java:73 (@async)     |
| sliding_window            | SimpleWindowSingleQueryPerformance.java:35       |
| groupby_length_batch      | GroupByWindowSingleQueryPerformance.java:35      |
| partitioned_filter        | SimplePartitionedFilterQueryPerformance.java:39  |
| partitioned_double_filter | SimplePartitionedDoubleFilterQueryPerformance.java:61 |
| partition_scaling_<N>     | PartitionPerformance.java (N symbol keys)        |
| table_noindex             | NoIndexingTablePerformance.java:80               |

Run: python samples/performance/workloads.py [seconds-per-run]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import numpy as np

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.event import EventBatch

CSE_DEF = ("define stream cseEventStream (symbol string, price float, "
           "volume int, timestamp long); ")
B = 8192


def cse_batch(n_symbols: int, seed: int = 7) -> EventBatch:
    rng = np.random.default_rng(seed)
    return EventBatch(
        "cseEventStream",
        ["symbol", "price", "volume", "timestamp"],
        {
            "symbol": np.asarray(
                [f"S{int(i)}" for i in rng.integers(0, n_symbols, B)],
                dtype=object),
            "price": rng.uniform(100.0, 1000.0, B).astype(np.float32),
            "volume": rng.integers(0, 300, B).astype(np.int32),
            "timestamp": np.zeros(B, dtype=np.int64),
        },
        np.zeros(B, dtype=np.int64),
    )


def measure(app: str, batch: EventBatch, seconds: float,
            out_streams=("outputStream",), expect_lowered=None):
    """Pump `batch` repeatedly for `seconds`; returns (events/sec,
    lowering-map)."""
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime(app)
        n_out = [0]
        from siddhi_tpu.core.stream import StreamCallback

        class Counter(StreamCallback):
            def receive_batch(self, b):
                n_out[0] += len(b)

        for out in out_streams:
            rt.add_callback(out, Counter())
        rt.start()
        lowering = rt.lowering()
        if expect_lowered is not None:
            for q, where in expect_lowered.items():
                assert lowering.get(q) == where, (q, lowering)
        h = rt.get_input_handler(batch.stream_id)
        for _ in range(3):  # warmup (jit compiles on the device path)
            h.send_batch(batch)
        sent = 0
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < seconds:
            h.send_batch(batch)
            sent += len(batch)
        dt = time.perf_counter() - t0
        rt.shutdown()
        return sent / dt, lowering
    finally:
        m.shutdown()


def workloads(seconds: float):
    tpu = "@app:execution('tpu', partitions='65536') "
    out = []

    def row(name, host_app, dev_app, batch, out_streams=("outputStream",),
            dev_expect=None):
        host_rate, _ = measure(host_app, batch, seconds, out_streams)
        dev_rate = None
        lowered = None
        if dev_app is not None:
            dev_rate, lowering = measure(dev_app, batch, seconds,
                                         out_streams, dev_expect)
            lowered = sorted(set(lowering.values()))
        out.append({
            "workload": name,
            "host_events_per_sec": round(host_rate, 1),
            "device_events_per_sec": (round(dev_rate, 1)
                                      if dev_rate is not None else None),
            "speedup": (round(dev_rate / host_rate, 3)
                        if dev_rate is not None else None),
            "lowered": lowered,
        })
        print(json.dumps(out[-1]), file=sys.stderr)

    b = cse_batch(50)

    # SimpleFilterSingleQueryPerformance.java:51
    q = (CSE_DEF + "@info(name='q0') from cseEventStream[volume < 150] "
         "select symbol, price insert into outputStream;")
    row("simple_filter", q, tpu + q, b, dev_expect={"q0": "device"})

    # SimpleFilterMultipleQueryPerformance.java:57 — 4-query fan-out
    q = CSE_DEF + " ".join(
        f"@info(name='q{i}') from cseEventStream[volume > 90] select * "
        "insert into outputStream;" for i in range(4))
    row("filter_multi_4q", q, tpu + q, b,
        dev_expect={f"q{i}": "device" for i in range(4)})

    # SimpleFilterSyncPerformance.java:73 — @async junction
    q = ("@async(buffer.size='1024', batch.size.max='4096') " + CSE_DEF +
         "@info(name='q0') from cseEventStream[volume < 150] "
         "select symbol, price insert into outputStream;")
    row("filter_async", q, tpu + q, b)

    # SimpleWindowSingleQueryPerformance.java:35
    q = (CSE_DEF + "@info(name='q0') from cseEventStream#window.length(10) "
         "select symbol, sum(price) as total, avg(volume) as avgVolume, "
         "timestamp insert into outputStream;")
    row("sliding_window", q, tpu + q, b, dev_expect={"q0": "device"})

    # GroupByWindowSingleQueryPerformance.java:35 (faithful shape: the
    # bare `timestamp` select item needs per-group last-row registers,
    # so the tumbling device path declines — host engine, by design)
    q = (CSE_DEF + "@info(name='q0') from cseEventStream"
         "#window.lengthBatch(10) select symbol, sum(price) as total, "
         "avg(volume) as avgVolume, timestamp group by symbol "
         "insert into outputStream;")
    row("groupby_length_batch", q, tpu + q, b)

    # device-eligible variant: group keys + aggregates only
    q = (CSE_DEF + "@info(name='q0') from cseEventStream"
         "#window.lengthBatch(10) select symbol, sum(price) as total, "
         "avg(volume) as avgVolume group by symbol "
         "insert into outputStream;")
    row("groupby_length_batch_agg_only", q, tpu + q, b,
        dev_expect={"q0": "device"})

    # SimplePartitionedFilterQueryPerformance.java:39
    q = (CSE_DEF + "partition with (symbol of cseEventStream) begin "
         "@info(name='q0') from cseEventStream[700 > price] select * "
         "insert into outputStream; end;")
    row("partitioned_filter", q, tpu + q, b, dev_expect={"q0": "device"})

    # SimplePartitionedDoubleFilterQueryPerformance.java:61
    q = (CSE_DEF + "partition with (symbol of cseEventStream) begin "
         "@info(name='q0') from cseEventStream[700 > price] select * "
         "insert into outputStream; "
         "@info(name='q1') from cseEventStream[price >= 700] select * "
         "insert into outputStream; end;")
    row("partitioned_double_filter", q, tpu + q, b,
        dev_expect={"q0": "device", "q1": "device"})

    # PartitionPerformance.java — partition-count scaling
    for n_keys in (10, 1_000, 50_000):
        q = (CSE_DEF + "partition with (symbol of cseEventStream) begin "
             "@info(name='q0') from cseEventStream[700 > price] "
             "select symbol, count() as c insert into outputStream; end;")
        row(f"partition_scaling_{n_keys}", q, tpu + q, cse_batch(n_keys),
            dev_expect={"q0": "device"})

    # NoIndexingTablePerformance.java:80 — un-indexed table insert+join
    # (joins run host-side; no device variant yet)
    q = ("define stream StockInputStream (symbol string, company string, "
         "price float, volume long); "
         "define stream StockCheckStream (symbol string, company string, "
         "timestamp long); "
         "define table StockTable (symbol string, company string, "
         "price float, volume long); "
         "from StockInputStream select symbol, company, price, volume "
         "insert into StockTable; "
         "from StockCheckStream join StockTable "
         "on StockCheckStream.symbol == StockTable.symbol "
         "select StockCheckStream.timestamp, StockCheckStream.symbol, "
         "StockCheckStream.company as company, StockTable.price as price "
         "insert into OutputStream;")
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime(q)
        n_out = [0]
        rt.add_callback("OutputStream",
                        lambda evs: n_out.__setitem__(0, n_out[0] + len(evs)))
        rt.start()
        hi = rt.get_input_handler("StockInputStream")
        hc = rt.get_input_handler("StockCheckStream")
        rng = np.random.default_rng(3)
        n_rows = 1_000
        syms = np.asarray([f"S{i}" for i in range(n_rows)], dtype=object)
        hi.send_batch(EventBatch(
            "StockInputStream",
            ["symbol", "company", "price", "volume"],
            {"symbol": syms, "company": syms,
             "price": rng.uniform(1, 100, n_rows).astype(np.float32),
             "volume": rng.integers(1, 100, n_rows).astype(np.int64)},
            np.zeros(n_rows, dtype=np.int64)))
        bc = EventBatch(
            "StockCheckStream", ["symbol", "company", "timestamp"],
            {"symbol": np.asarray(
                [f"S{int(i)}" for i in rng.integers(0, n_rows, 512)],
                dtype=object),
             "company": np.asarray(["c"] * 512, dtype=object),
             "timestamp": np.zeros(512, dtype=np.int64)},
            np.zeros(512, dtype=np.int64))
        sent = 0
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < seconds:
            hc.send_batch(bc)
            sent += len(bc)
        dt = time.perf_counter() - t0
        rt.shutdown()
        out.append({
            "workload": "table_noindex",
            "host_events_per_sec": round(sent / dt, 1),
            "device_events_per_sec": None,
            "speedup": None,
            "lowered": None,
        })
        print(json.dumps(out[-1]), file=sys.stderr)
    finally:
        m.shutdown()
    return out


def main(seconds: float = 2.0):
    print(json.dumps(workloads(seconds)))


if __name__ == "__main__":
    main(float(sys.argv[1]) if len(sys.argv) > 1 else 2.0)
