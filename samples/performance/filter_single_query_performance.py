"""Single filter query throughput (reference: performance-samples
SimpleFilterSingleQueryPerformance.java:51 — prints steady-state
events/sec and average in-pipeline latency every batch window).

Run: python samples/performance/filter_single_query_performance.py [seconds]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

import numpy as np

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.event import EventBatch


def main(seconds: float = 5.0, columnar: bool = False):
    manager = SiddhiManager()
    runtime = manager.create_siddhi_app_runtime(
        "define stream StockStream (symbol string, price float, volume long); "
        "@info(name='filter') from StockStream[volume < 150] "
        "select symbol, price insert into OutputStream;"
    )
    n_out = [0]
    if columnar:
        # columnar subscriber: skips per-event materialization entirely
        from siddhi_tpu.core.stream import StreamCallback

        class Counter(StreamCallback):
            def receive_batch(self, batch):
                n_out[0] += len(batch)

        runtime.add_callback("OutputStream", Counter())
    else:
        runtime.add_callback(
            "OutputStream", lambda evs: n_out.__setitem__(0, n_out[0] + len(evs)))
    runtime.start()
    h = runtime.get_input_handler("StockStream")

    B = 8192
    batch = EventBatch(
        "StockStream",
        ["symbol", "price", "volume"],
        {
            "symbol": np.asarray(["WSO2"] * B, dtype=object),
            "price": np.full(B, 55.6, dtype=np.float32),
            "volume": (np.arange(B) % 300).astype(np.int64),
        },
        np.zeros(B, dtype=np.int64),
    )
    # warmup
    for _ in range(5):
        h.send_batch(batch)
    sent = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < seconds:
        h.send_batch(batch)
        sent += B
    dt = time.perf_counter() - t0
    print(f"callback mode    : {'columnar batch' if columnar else 'per-event'}")
    print(f"events sent      : {sent}")
    print(f"events matched   : {n_out[0]}")
    print(f"throughput       : {sent / dt:,.0f} events/sec")
    print(f"avg latency      : {dt / (sent / B) * 1e3:.3f} ms/batch ({B} events)")
    runtime.shutdown()
    manager.shutdown()


if __name__ == "__main__":
    secs = float(sys.argv[1]) if len(sys.argv) > 1 else 5.0
    main(secs)
    main(secs, columnar=True)
