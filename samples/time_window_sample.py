"""Quick-start: sliding time window aggregation (reference:
quickstart-samples TimeWindowSample.java)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import time

from siddhi_tpu import SiddhiManager


def main():
    manager = SiddhiManager()
    runtime = manager.create_siddhi_app_runtime(
        "define stream StockStream (symbol string, price float); "
        "@info(name='query1') "
        "from StockStream#window.time(500 millisec) "
        "select symbol, avg(price) as avgPrice group by symbol "
        "insert into OutputStream;"
    )
    runtime.add_callback("OutputStream", lambda events: [print(e) for e in events])
    runtime.start()
    h = runtime.get_input_handler("StockStream")
    h.send(["IBM", 100.0])
    h.send(["IBM", 200.0])
    time.sleep(0.6)   # window slides; IBM events expire
    h.send(["IBM", 300.0])
    runtime.shutdown()
    manager.shutdown()


if __name__ == "__main__":
    main()
