"""Quick-start: custom function extension + script UDF (reference:
quickstart-samples ExtensionSample.java)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from siddhi_tpu import SiddhiManager
from siddhi_tpu.extension.function import FunctionExecutor
from siddhi_tpu.query_api import AttrType


class StringConcatFunction(FunctionExecutor):
    """custom:plus(a, b) — concatenates its arguments."""

    return_type = AttrType.STRING

    def execute(self, *values):
        return "".join(str(v) for v in values)


def main():
    manager = SiddhiManager()
    manager.set_extension("custom:plus", StringConcatFunction, kind="function")
    runtime = manager.create_siddhi_app_runtime(
        "define function tax[python] return double { data[0] * 1.2 }; "
        "define stream Orders (item string, price double); "
        "from Orders select custom:plus('item-', item) as label, tax(price) as gross "
        "insert into Priced;"
    )
    runtime.add_callback("Priced", lambda events: [print(e) for e in events])
    runtime.start()
    runtime.get_input_handler("Orders").send(["book", 10.0])
    runtime.shutdown()
    manager.shutdown()


if __name__ == "__main__":
    main()
