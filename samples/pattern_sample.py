"""Quick-start: pattern detection (the engine's north-star path):
every price-rise pair within 5 seconds."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from siddhi_tpu import SiddhiManager


def main():
    manager = SiddhiManager()
    runtime = manager.create_siddhi_app_runtime(
        "define stream Ticks (symbol string, price double); "
        "@info(name='rise') "
        "from every e1=Ticks -> e2=Ticks[price > e1.price] within 5 sec "
        "select e1.price as low, e2.price as high insert into Rises;"
    )
    runtime.add_callback("Rises", lambda events: [print(e) for e in events])
    runtime.start()
    h = runtime.get_input_handler("Ticks")
    h.send(["ACME", 10.0])
    h.send(["ACME", 12.5])
    h.send(["ACME", 11.0])
    h.send(["ACME", 14.0])
    runtime.shutdown()
    manager.shutdown()


if __name__ == "__main__":
    main()
