"""Quick-start: per-key partitioned query (reference:
quickstart-samples PartitionSample.java)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from siddhi_tpu import SiddhiManager


def main():
    manager = SiddhiManager()
    runtime = manager.create_siddhi_app_runtime(
        "define stream LoginStream (user string, latency long); "
        "partition with (user of LoginStream) begin "
        "  @info(name='perUser') "
        "  from LoginStream select user, sum(latency) as total insert into UserTotals; "
        "end;"
    )
    runtime.add_callback("UserTotals", lambda events: [print(e) for e in events])
    runtime.start()
    h = runtime.get_input_handler("LoginStream")
    h.send(["alice", 10])
    h.send(["bob", 5])
    h.send(["alice", 7])   # alice's running sum is isolated from bob's
    runtime.shutdown()
    manager.shutdown()


if __name__ == "__main__":
    main()
