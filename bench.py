"""Benchmark: pattern-match events/sec on the dense TPU NFA.

North-star config (BASELINE.json): 16-state fraud-style pattern over 1M
key partitions.  The dense engine advances per-partition NFA state
(bitmasks + capture registers in HBM) with one jitted step per event
micro-batch; measured throughput is end-of-steady-state events/sec on
the available accelerator (single chip under axon; CPU fallback).

Baseline: the reference publishes no numbers (BASELINE.md).  The JVM
pattern path (StreamPreStateProcessor chain with per-event locking) is
estimated at 2M events/sec/core from the reference's own perf-harness
methodology (SimpleFilterSingleQueryPerformance prints ~1-5M ev/s for a
plain filter; the 16-state pattern path does strictly more work per
event).  vs_baseline = measured / 2e6, so the >= 50x north-star target
corresponds to vs_baseline >= 50.

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import sys
import time

import numpy as np

N_PARTITIONS = 1_000_000
BATCH = 1 << 17  # 131072 events per step
STEPS = 20
WARMUP = 3
N_STATES = 16
JVM_BASELINE_EVENTS_PER_SEC = 2_000_000.0


def build_app() -> str:
    """16-state escalation pattern: every e1=[v>θ1] -> e2=[v>θ2 and v>e1.v] -> ... within 10 min."""
    defs = "define stream Txn (key long, v double); "
    states = ["every e1=Txn[v > 0.0]"]
    for i in range(2, N_STATES + 1):
        states.append(f"e{i}=Txn[v > {float(i - 1)} and v > e1.v]")
    pattern = " -> ".join(states)
    select = "select e1.v as v1, e16.v as v16"
    return (
        defs
        + f"@info(name='bench') from {pattern} within 10 min {select} insert into Alerts;"
    )


def main():
    import jax

    from siddhi_tpu.ops.dense_nfa import compile_pattern

    dev = jax.devices()[0]
    eng = compile_pattern(build_app(), "bench", n_partitions=N_PARTITIONS)
    state = eng.init_state()
    step = eng.make_step("Txn")

    rng = np.random.default_rng(7)
    jnp = eng.jnp

    def make_batch(i):
        # unique partitions within a batch (stride walk) -> no collision
        # rounds; values escalate so the chain actually advances
        part = ((np.arange(BATCH, dtype=np.int64) * 524287 + i * BATCH) % N_PARTITIONS).astype(np.int32)
        v = rng.uniform(0.0, float(N_STATES + 4), BATCH).astype(np.float32)
        ts = np.full(BATCH, 1_000 + i * 10, dtype=np.int32)
        return (
            jnp.asarray(part),
            {"v": jnp.asarray(v), "key": jnp.asarray(part.astype(np.float32))},
            jnp.asarray(ts),
            jnp.ones(BATCH, dtype=bool),
        )

    batches = [make_batch(i) for i in range(STEPS + WARMUP)]

    # warmup / compile
    for i in range(WARMUP):
        pi, cols, ts, valid = batches[i]
        state, emit, _ = step(state, pi, cols, ts, valid)
    emit.block_until_ready()

    # throughput: several async-dispatched windows (sync once per window
    # so XLA pipelines steps); the median window resists transient
    # contention on a shared/tunneled chip
    N_WINDOWS = 5
    window_rates = []
    for w in range(N_WINDOWS):
        t_w = time.perf_counter()
        for i in range(WARMUP, WARMUP + STEPS):
            pi, cols, ts, valid = batches[i]
            state, emit, _ = step(state, pi, cols, ts, valid)
        emit.block_until_ready()
        window_rates.append(BATCH * STEPS / (time.perf_counter() - t_w))
    events_per_sec = float(np.median(window_rates))

    # detection latency: separate synced pass (per-batch wall time incl.
    # host round trip — the north-star's p99 axis)
    per_step = []
    for i in range(WARMUP, WARMUP + STEPS):
        pi, cols, ts, valid = batches[i]
        t0 = time.perf_counter()
        state, emit, _ = step(state, pi, cols, ts, valid)
        emit.block_until_ready()
        per_step.append(time.perf_counter() - t0)
    p99_batch_ms = float(np.percentile(np.asarray(per_step), 99) * 1e3)
    print(
        json.dumps(
            {
                "metric": "pattern_match_events_per_sec_per_chip",
                "value": round(events_per_sec, 1),
                "unit": "events/s",
                "vs_baseline": round(events_per_sec / JVM_BASELINE_EVENTS_PER_SEC, 2),
                "p99_batch_latency_ms": round(p99_batch_ms, 3),
                "batch": BATCH,
                "n_partitions": N_PARTITIONS,
                "n_states": N_STATES,
            }
        )
    )


if __name__ == "__main__":
    main()
